"""Correlated-market consensus propagation — a damped sweep over a
market-dependency graph, as dense gather arithmetic.

Markets are not independent: a constituent market's consensus carries
information about the composites that depend on it ("Graphical
Representations of Consensus Belief", PAPERS.md). This module is the
device half of that coupling: a FIXED-ITERATION damped relaxation

    c'_i = (1 − λ)·c_i + λ · (Σ_j w_ij·c_j) / (Σ_j w_ij)

iterated ``steps`` times over a dense per-row neighbour block — the
market-graph analogue of one synchronous belief-propagation sweep per
iteration, with damping λ in place of message normalisation. No
sampler, no sparse scatter: the CSR edge structure is padded host-side
(analytics/graph.py) to a static ``(markets, max_degree)`` neighbour
index/weight block, so each iteration is one gather + two masked
reductions — embarrassingly parallel over the markets axis except for
one ``all_gather`` of the tiny per-market vector when that axis is
sharded.

Semantics at the edges of the domain:

* ``neighbor_idx < 0`` lanes are padding (rows with fewer than
  ``max_degree`` dependencies) — they contribute nothing.
* A NaN neighbour (a market that had no signalling slot this batch, or
  a padding row of the sharded axis) is EXCLUDED from the neighbourhood
  mean rather than poisoning it; a row with no finite neighbour (or no
  edges) keeps its own value untouched, NaN included.
* The sweep is an ADDITIVE analytics output: the settle's point
  consensus and the reliability state are never written back from here
  (the byte-parity contract of the analytics tier).

Determinism: ``steps``, λ, and ``max_degree`` are static; every
reduction is a fixed-width row-local sum, and the gathered vector is
the same on every device — so the sweep is a pure bit-stable function
of (values, neighbor_idx, neighbor_w) on any mesh factorisation
(pinned by tests/test_analytics.py). Layer 1 (ops): no obs, no clock,
explicit dtypes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array

#: Recorded default damping: hold 50% of a market's own consensus per
#: sweep step. A plain float (no backend touch at import, LY302).
DEFAULT_DAMPING = 0.5

#: Recorded default sweep depth: two synchronous iterations carry a
#: neighbour-of-neighbour influence without letting long cycles ring.
DEFAULT_SWEEP_STEPS = 2


def damped_sweep_math(
    values: Array,        # f32[M_loc] this shard's per-market values
    neighbor_idx: Array,  # i32[M_loc, D] GLOBAL market positions; -1 pad
    neighbor_w: Array,    # f32[M_loc, D] edge weights
    *,
    damping: float = DEFAULT_DAMPING,
    steps: int = DEFAULT_SWEEP_STEPS,
    axis_name: "str | None" = None,
) -> Array:
    """Run *steps* damped propagation sweeps; returns the relaxed values.

    Inside ``shard_map`` the markets axis may be sharded over
    *axis_name*: each iteration all-gathers the per-market vector
    (tiled, so positions stay global) and gathers neighbours from the
    full copy — ``neighbor_idx`` entries index the GLOBAL padded
    markets axis. ``axis_name=None`` is the single-shard form (values
    already global).
    """
    f32 = jnp.float32
    values = values.astype(f32)
    weights = jnp.where(
        neighbor_idx >= 0, neighbor_w.astype(f32), f32(0.0)
    )
    lam = f32(damping)
    keep = f32(1.0) - lam

    def body(_, v):
        full = (
            jax.lax.all_gather(v, axis_name, tiled=True)
            if axis_name is not None
            else v
        )
        nb = full[jnp.clip(neighbor_idx, 0)]
        ok = (neighbor_idx >= 0) & jnp.isfinite(nb)
        w = jnp.where(ok, weights, f32(0.0))
        wsum = jnp.sum(w, axis=-1)
        wval = jnp.sum(w * jnp.where(ok, nb, f32(0.0)), axis=-1)
        mixes = (wsum > 0) & jnp.isfinite(v)
        blended = keep * v + lam * (
            wval / jnp.where(wsum > 0, wsum, f32(1.0))
        )
        return jnp.where(mixes, blended, v)

    if steps <= 0:
        return values
    return jax.lax.fori_loop(0, steps, body, values)
