"""Pure JAX kernels: batched consensus, decay, and outcome update.

Dtype-polymorphic (float32 for throughput, float64 under x64 for parity
gates); no host state, no string ids — everything indexed int32.
"""

from bayesian_consensus_engine_tpu.ops.consensus import (
    pair_mean_from_flat,
    weighted_sums_from_pairs,
)
from bayesian_consensus_engine_tpu.ops.decay import (
    decay_factor,
    decayed_reliability,
    decayed_reliability_at,
)
from bayesian_consensus_engine_tpu.ops.tiebreak import (
    BatchTieBreakResult,
    batched_tiebreak,
    build_batched_tiebreak,
)
from bayesian_consensus_engine_tpu.ops.update import (
    masked_outcome_update,
    outcome_update,
)

__all__ = [
    "pair_mean_from_flat",
    "weighted_sums_from_pairs",
    "decay_factor",
    "decayed_reliability",
    "decayed_reliability_at",
    "masked_outcome_update",
    "outcome_update",
    "BatchTieBreakResult",
    "batched_tiebreak",
    "build_batched_tiebreak",
]
