"""Per-market uncertainty bands — reliability-weighted dispersion as an
array program.

The engine's consensus is a reliability-weighted mean; this module turns
the SPREAD of the same weighted signal population into a credible
interval per market, batched over the whole (markets × slots) block in
one pass — the MRF-inference-with-UQ prescription (PAPERS.md): make the
uncertainty an array program over the state you already hold, not a
sampler loop beside it. Per market row:

  Σw, Σw·p, Σw·p², Σw²  →  mean, weighted population variance,
  Kish effective sample size n_eff = (Σw)²/Σw², standard error
  sqrt(var / n_eff), and the z-scaled band [mean − z·se, mean + z·se]
  clipped to [0, 1].

The weights are the DECAYED read reliabilities — exactly the weights the
consensus reduction gives the same slots, read at the same ``now`` — so
the band is a statement about the consensus the settle reports, not a
parallel model.

**The accumulation-order contract.** The four weighted sums are computed
with a FIXED balanced binary tree over the slots axis, padded to the
next power of two with exact zeros (masked and padded lanes contribute
``+0.0``, and ``x + 0.0 ≡ x`` in IEEE-754, so padding never moves a
bit). This buys two bit-level invariances that a bare ``jnp.sum``
cannot:

* **Chunk invariance, by construction** — ``chunk_slots`` (the
  ``chunk_agents``-style memory knob: per-step temps drop from
  O(slots × markets) to O(chunk × markets)) is clamped to a power of two
  dividing the padded width, so every chunk's tree is an internal
  subtree of the one global tree and the cross-chunk fold (a balanced
  tree over the chunk roots, accumulated in fixed chunk order 0..n−1)
  reproduces the remaining upper levels exactly. Any chunk setting,
  same bits — not an empirical observation, a structural identity.
* **Mesh invariance when shards stay power-of-two** — each shard
  reduces its local slots with the same tree and the per-shard roots
  are all-gathered and folded in fixed device order with the same
  balanced tree; when the per-shard slot width is a power of two (the
  bucketed plan default), shard boundaries land on subtree boundaries
  and every mesh factorisation reproduces the identical global tree.

Deterministic by contract either way (DT-series rules); the bit matrix
is pinned by tests/test_analytics.py. Layer 1 (ops): no obs, no clock,
explicit dtypes — the sharded fused program (parallel/sharded.py) and
the analytics surfaces (analytics/bands.py) both import from here.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

Array = jax.Array

#: Two-sided 95% normal quantile — the default band width. A plain float
#: (module import must not touch the JAX backend, lint rule LY302).
Z_95 = 1.959964

#: Recorded default chunk width for the memory-dieted band accumulation
#: (the bands twin of ops.tiebreak.DEFAULT_CHUNK_AGENTS): wide enough
#: that per-chunk overhead vanishes, narrow enough that per-step temps
#: stay tens of MB at the 2048×10k stress shape. Always rounded DOWN to
#: a power of two at resolve time — the tree-alignment contract above.
DEFAULT_CHUNK_SLOTS = 1024


class UncertaintyBands(NamedTuple):
    """Per-market credible-interval outputs, one entry per market row.

    ``mean`` is the band's own reliability-weighted mean, computed with
    the tree accumulation order — equal to the settle's consensus to
    float tolerance (the settle's reduction keeps its original
    expression; the point consensus is NEVER replaced by this value).
    Rows with zero total weight (no signalling slot) report NaN
    mean/lo/hi and zeroed dispersion, mirroring the consensus NaN
    convention for empty markets.
    """

    mean: Array      # f32[M] tree-ordered weighted mean
    lo: Array        # f32[M] mean − z·stderr, clipped to [0, 1]
    hi: Array        # f32[M] mean + z·stderr, clipped to [0, 1]
    stderr: Array    # f32[M] sqrt(variance / n_eff)
    n_eff: Array     # f32[M] Kish effective sample size (Σw)²/Σw²
    count: Array     # i32[M] signalling slots (global across shards)


def _pow2_at_most(n: int) -> int:
    """Largest power of two ≤ *n* (n ≥ 1)."""
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _pow2_at_least(n: int) -> int:
    """Smallest power of two ≥ *n* (min 1)."""
    p = 1
    while p < n:
        p *= 2
    return p


def resolve_chunk_slots(chunk_slots: "int | None", width: int) -> int:
    """Clamp the chunk knob to a power of two dividing the padded width.

    ``None`` (or anything ≥ the padded width) resolves to one full-width
    chunk — the unchunked reference. Every resolution divides
    ``_pow2_at_least(width)`` exactly, which is what keeps chunk trees
    subtree-aligned (see module docstring).
    """
    padded = _pow2_at_least(max(width, 1))
    if chunk_slots is None:
        return padded
    # chunk_slots is a static Python knob (config resolution happens at
    # trace time when a kernel calls this); the int() never sees a tracer.
    return min(_pow2_at_most(max(int(chunk_slots), 1)), padded)  # noqa: JX110  # static knob


def _tree_sum(x: Array, axis: int) -> Array:
    """Balanced-binary-tree sum over a POWER-OF-TWO axis width.

    The one reduction expression every band sum goes through: add
    ADJACENT pairs (leaf 2i with 2i+1), log2(width) times. Adjacent
    pairing — not fold-in-half — is load-bearing: it makes every
    aligned power-of-two leaf range reduce to exactly one internal node
    of the global tree, so a chunk's root (and a power-of-two shard's
    root) slots into the upper levels unchanged. The pairing depends
    only on the width, never on the values.
    """
    width = x.shape[axis]
    while width > 1:
        even = jax.lax.slice_in_dim(x, 0, width, stride=2, axis=axis)
        odd = jax.lax.slice_in_dim(x, 1, width, stride=2, axis=axis)
        x = even + odd
        width //= 2
    return jnp.squeeze(x, axis=axis)


def _pad_pow2(x: Array, axis: int, fill) -> Array:
    """Zero-pad *axis* up to the next power of two (no-op when aligned)."""
    width = x.shape[axis]
    padded = _pow2_at_least(max(width, 1))
    if padded == width:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis % x.ndim] = (0, padded - width)
    return jnp.pad(x, widths, constant_values=fill)


def band_sums(
    probs: Array,
    mask: Array,
    read_rel: Array,
    *,
    axis_name: "str | None",
    axis_size: int,
    chunk_slots: "int | None" = None,
    agents_last: bool = True,
) -> tuple:
    """The four tree-accumulated band moments of one device shard.

    Returns ``(sums, count)`` where ``sums`` is the (4, M) stack
    Σw / Σw·p / Σw·p² / Σw² reduced with the fixed balanced tree (the
    whole accumulation-order contract lives here) and ``count`` the i32
    per-market signalling-slot count. Split out of :func:`band_math` in
    round 14 so the one-pass Pallas settlement kernel can emit the RAW
    moments from inside its VMEM sweep and leave the epilogue
    (:func:`band_epilogue` — divisions, the variance square, the z
    scaling) to plain XLA outside the kernel, where the epilogue's
    optimization barriers are preserved and every program rounds lo/hi
    identically (Pallas interpret mode strips ``optimization_barrier``
    from kernel bodies, so an in-kernel epilogue could FMA-contract
    differently from the fused XLA program's).
    """
    f32 = jnp.float32
    slots_axis = (probs.ndim - 1) if agents_last else 0
    local_width = probs.shape[slots_axis]

    probs = _pad_pow2(probs.astype(f32), slots_axis, 0.0)
    read_rel = _pad_pow2(read_rel.astype(f32), slots_axis, 0.0)
    mask = _pad_pow2(mask, slots_axis, False)
    padded_width = probs.shape[slots_axis]
    chunk = resolve_chunk_slots(chunk_slots, local_width)
    n_chunks = padded_width // chunk

    def chunk_roots(offset):
        """The four tree-reduced sums of one slot chunk → (4, M)."""
        p = jax.lax.dynamic_slice_in_dim(probs, offset, chunk, slots_axis)
        r = jax.lax.dynamic_slice_in_dim(read_rel, offset, chunk, slots_axis)
        m = jax.lax.dynamic_slice_in_dim(mask, offset, chunk, slots_axis)
        w = jnp.where(m, r, f32(0.0))
        wp = w * p
        contributions = jnp.stack([w, wp, wp * p, w * w])
        return _tree_sum(contributions, slots_axis + 1)

    if n_chunks == 1:
        sums = chunk_roots(0)
    else:
        markets = probs.shape[0] if agents_last else probs.shape[1]
        buf = jnp.zeros((n_chunks, 4, markets), dtype=f32)

        def body(i, acc):
            return jax.lax.dynamic_update_slice_in_dim(
                acc, chunk_roots(i * chunk)[None], i, axis=0
            )

        buf = jax.lax.fori_loop(0, n_chunks, body, buf)
        # The cross-chunk fold IS the upper levels of the global tree:
        # chunk i's root sits at leaf i in chunk order, and n_chunks is
        # a power of two by construction.
        sums = _tree_sum(buf, 0)

    count = jnp.sum(mask, axis=slots_axis).astype(jnp.int32)
    return band_merge(sums, count, axis_name=axis_name, axis_size=axis_size)


def band_merge(
    sums: Array,
    count: Array,
    *,
    axis_name: "str | None",
    axis_size: int,
) -> tuple:
    """Fold per-shard band-moment roots across the mesh axis.

    The cross-device tail of :func:`band_sums`, split out in round 20 so
    the sources-sharded one-pass kernel can emit its LOCAL (4, M) roots
    from inside the VMEM sweep and have the identical merge — all-gather,
    zero-pad to a power-of-two device count, the same balanced tree in
    fixed device order, psum of the i32 count — traced OUTSIDE the kernel
    body by the shard_map wrapper. A no-op on an unsharded axis.
    """
    if axis_name is not None and axis_size > 1:
        # Per-shard roots folded in fixed device order with the same
        # balanced tree (padded to a power-of-two device count with
        # exact zeros) — shard boundaries land on subtree boundaries
        # whenever the local width is a power of two.
        gathered = jax.lax.all_gather(sums, axis_name)  # (n_dev, 4, M)
        gathered = _pad_pow2(gathered, 0, 0.0)
        sums = _tree_sum(gathered, 0)
        count = jax.lax.psum(count, axis_name)
    return sums, count


def band_epilogue(sums: Array, count: Array, z: float = Z_95) -> UncertaintyBands:
    """Moments → credible intervals (the division/normalisation half).

    Pure elementwise (M,)-vector work on :func:`band_sums` outputs; runs
    in plain XLA in EVERY path (the fused XLA program and the one-pass
    kernel's wrapper alike) so its optimization barriers pin the
    roundings — see :func:`band_sums`.
    """
    f32 = jnp.float32
    sw, swp, swp2, sw2 = sums[0], sums[1], sums[2], sums[3]
    has_weight = sw != 0
    safe_w = jnp.where(has_weight, sw, f32(1.0))
    mean = jnp.where(has_weight, swp / safe_w, f32(jnp.nan))
    # One-pass weighted population variance E[p²] − E[p]²; clamped at 0
    # against cancellation (the same form as the tie-break's confidence
    # variance, reference: tiebreak.py:107-110).
    ex2 = jnp.where(has_weight, swp2 / safe_w, f32(0.0))
    # Optimization barriers pin the two mul→add/sub sites an XLA backend
    # may otherwise FMA-contract DIFFERENTLY in different surrounding
    # programs (the fused XLA program's shard_map body vs the one-pass
    # kernel wrapper's straight-line epilogue) — μ·μ feeding the
    # variance subtraction and z·stderr feeding the lo/hi add/sub. The
    # epilogue deliberately runs in plain XLA in every path (barriers
    # are stripped inside Pallas kernel bodies — see band_sums), so the
    # pins hold and lo/hi agree bit-for-bit across programs
    # (tests/test_pallas_settle.py). Cost: fusion breaks on (M,) vectors.
    mean_sq = jax.lax.optimization_barrier(
        jnp.where(has_weight, mean, f32(0.0)) ** 2
    )
    centered = ex2 - mean_sq
    variance = jnp.maximum(centered, f32(0.0))
    n_eff = jnp.where(sw2 > 0, (sw * sw) / jnp.where(sw2 > 0, sw2, f32(1.0)),
                      f32(0.0))
    stderr = jnp.where(
        n_eff > 0,
        jnp.sqrt(variance / jnp.maximum(n_eff, f32(1e-30))),
        f32(0.0),
    )
    stderr = jax.lax.optimization_barrier(stderr)
    half = jax.lax.optimization_barrier(f32(z) * stderr)
    lo = jnp.clip(mean - half, f32(0.0), f32(1.0))
    hi = jnp.clip(mean + half, f32(0.0), f32(1.0))
    return UncertaintyBands(
        mean=mean, lo=lo, hi=hi, stderr=stderr, n_eff=n_eff, count=count
    )


def band_math(
    probs: Array,
    mask: Array,
    read_rel: Array,
    *,
    axis_name: "str | None",
    axis_size: int,
    z: float = Z_95,
    chunk_slots: "int | None" = None,
    agents_last: bool = True,
) -> UncertaintyBands:
    """Credible intervals for one device shard (shard_map body).

    Blocks are ``(M, K)`` with ``agents_last=True`` or slot-major
    ``(K, M)`` with ``agents_last=False`` (the fused resident program's
    layout, where the slots axis is sharded over *axis_name* across
    *axis_size* devices). ``read_rel`` must be the decayed READ
    reliability — the same per-slot weight the consensus reduction uses
    at the same ``now`` (``parallel.sharded.read_phase``).

    ``chunk_slots`` bounds the local working set: the shard's slots are
    consumed in power-of-two-width chunks, each chunk's four weighted
    sums tree-reduced and parked in a per-market roots buffer that the
    same tree folds at the end — outputs bit-identical at every setting
    (see module docstring). ``None`` is one full-width chunk.
    :func:`band_sums` + :func:`band_epilogue` composed; the one-pass
    kernel calls the halves separately (sums in-kernel, epilogue out).
    """
    sums, count = band_sums(
        probs, mask, read_rel,
        axis_name=axis_name,
        axis_size=axis_size,
        chunk_slots=chunk_slots,
        agents_last=agents_last,
    )
    return band_epilogue(sums, count, z)
