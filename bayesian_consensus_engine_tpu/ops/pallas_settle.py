"""One-pass settlement: consensus + tie-break + band moments in a single
HBM sweep per tile (Pallas TPU kernel, round 14).

The fused XLA program (``parallel.sharded.build_cycle_analytics_loop``)
co-residences N cycles + the chunked ring tie-break + the uncertainty
bands as ONE program per chip, but they remain 2–3 separate reduce
passes over the same resident (slots × markets) state: read-decay for
the analytics view, the tie-break's group fold, the band tree, and the
cycle's own read each stream the block from HBM again. At the 1M-market
regime the block is read-bandwidth-bound, and a hand-fused multi-output
reduction is exactly the shape XLA's fusion handles badly (the
documented reason the plain-cycle kernel in ``ops/pallas_cycle.py`` was
retired: the *plain* cycle is a shape XLA fuses optimally — this one is
not).

This kernel grids over slot-major (K, TILE_M) market tiles (markets on
the 128-wide lane dimension, the retired scaffold's measured layout) and
computes, in ONE VMEM sweep per tile:

  (a) the decay-on-read weighted consensus + capped N-step state update
      of ``ops.cycle_math`` (``input_output_aliases`` keeps the state
      in-place in HBM — read once, written once);
  (b) the top-2 tie-break fold of ``ops.tiebreak.ring_tiebreak_math``
      (the ``_merge_top2`` carry over fixed-width chunks, same total
      order, called with ``axis_name=None``);
  (c) the balanced-tree band moments of ``ops.uncertainty.band_math``
      (Σw / Σw·p / Σw·p² / Σw² with the fixed adjacent-pair tree).

**Bit parity is structural, not empirical**: the kernel body calls the
SAME layer-1 functions the XLA fused program traces under ``shard_map``
— ``read_phase``, ``ring_tiebreak_math``, ``band_math``, and the
``make_loop_math`` N-step scaffold — so per (K, TILE_M) tile the jaxpr
is the XLA program's jaxpr with the size-1 sources psums dropped
(bit-identity) and the markets axis tiled (every reduction runs over
the K axis only, so tiling cannot move a bit). The acceptance oracle is
interpret mode on the tier-1 CPU backend: store bytes, tie-break, bands
— at every ``chunk_agents``/``chunk_slots`` setting
(tests/test_pallas_settle.py).

Scope: :func:`build_onepass_settle` serves meshes whose SOURCES axis is
unsharded (markets sharded, K source slots local) and finishes all three
result families in-kernel. Round 20 extends the route to 2-D meshes:
:func:`build_onepass_partials` runs the same sweep over each shard's
LOCAL (K_local, TILE_M) block and emits *partials* — the raw shard-local
consensus sums, the band-moment tree roots, the per-slot decayed read
views, and the per-shard loop-carried state — for a small deterministic
cross-device merge OUTSIDE the kernel body (psum + epilogue for the
exactly-associative sums, ``band_merge`` for the moment roots, the full
axis-gated ``ring_tiebreak_math`` over the emitted read views for the
tie-break). ``parallel.sharded.build_cycle_analytics_loop`` owns the
routing between the two builders; ``kernel="pallas"`` still raises only
for genuinely unsupported combinations (a disabled analytics stage,
``tiebreak_kind="sorted"``, or ``steps=0`` on a sources-sharded mesh —
zero raw sums cannot reproduce the XLA program's zero consensus). XLA
stays the production default; the kernel ships per-shape only when the
honesty-guarded A/B says it wins (``ShapeTuner`` knob ``settle_kernel``,
``kernel="auto"``). ``bench.py --leg e2e_onepass`` is the standing
re-adjudication.

Masks ride as float32 0/1 (uniform (8, 128) tiling, the retired
scaffold's discipline) and are converted to bool at the kernel boundary;
``resolved_by``/``num_groups``/``count`` come back as real int32 blocks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from functools import partial

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bayesian_consensus_engine_tpu.ops.cycle_math import (
    MarketBlockState,
    _cycle_math,
    _fast_cycle_math,
    _sums_cycle_math,
    _sums_fast_cycle_math,
    make_loop_math,
)
from bayesian_consensus_engine_tpu.ops.tiebreak import (
    RingTieBreakResult,
    ring_tiebreak_math,
)
from bayesian_consensus_engine_tpu.ops.uncertainty import (
    Z_95,
    band_epilogue,
    band_sums,
)

#: VMEM-side working set per (K, TILE_M) f32 block held by the launch:
#: 7 input blocks + 4 output state blocks, double-buffered by the
#: pipelined grid. Aliased state outputs share their input's HBM buffer
#: but are counted as SEPARATE VMEM windows here — this resolver is
#: deliberately the conservative side of the budget asymmetry (PL501's
#: static check counts an aliased pair once and flags only unambiguous
#: overshoot; a tile this model admits should never fail the Mosaic
#: scoped-VMEM check, and the autotuner records any residual failure as
#: ineligible).
_BLOCKS_PER_TILE = 11
#: The partials variant trades the in-kernel tie-break for two extra
#: full (K, TILE_M) output blocks (the decayed read views the outside
#: ring merge consumes): 7 inputs + 4 state outputs + 2 view outputs.
_PARTIALS_BLOCKS_PER_TILE = 13
_VMEM_BUDGET_BYTES = 16 * 1024 * 1024
_TILE_CANDIDATES = (2048, 1024, 512, 256, 128)


def resolve_tile_markets(
    num_markets: int, num_slots: int, blocks_per_tile: int = _BLOCKS_PER_TILE
) -> int:
    """The largest standard tile dividing *num_markets* that keeps the
    double-buffered block set inside the 16 MB scoped-VMEM budget.

    Falls back to ``num_markets`` itself (one tile) when no standard
    tile divides it — the ragged case never reaches the kernel grid
    (the divisibility guard in :func:`build_onepass_settle` is the PL501
    contract), and a one-tile launch over the VMEM budget fails at TPU
    compile time, which the autotuned A/B records as "ineligible" rather
    than shipping. ``blocks_per_tile`` is the launch's (K, TILE_M) block
    count — the partials variant carries two more than the fused kernel.
    """
    for tile in _TILE_CANDIDATES:
        if num_markets % tile:
            continue
        bytes_ = num_slots * tile * 4 * blocks_per_tile * 2
        if bytes_ <= _VMEM_BUDGET_BYTES:
            return tile
    return num_markets


def _onepass_kernel(
    now_ref,        # SMEM (1, 1)
    probs_ref,      # VMEM (K, TM) f32
    mask_ref,       # VMEM (K, TM) f32 0/1
    outcome_ref,    # VMEM (1, TM) f32 0/1
    rel_ref,        # VMEM (K, TM) f32
    conf_ref,       # VMEM (K, TM) f32
    upd_ref,        # VMEM (K, TM) f32
    *refs,          # [ex_ref] + output refs (see build_onepass_settle)
    steps: int,
    has_exists: bool,
    precision: int,
    chunk_agents,
    chunk_slots,
):
    if has_exists:
        ex_ref, refs = refs[0], refs[1:]
        exists = ex_ref[...] > 0.0
        state_out_refs, refs = refs[:4], refs[4:]
    else:
        exists = None
        state_out_refs, refs = refs[:3], refs[3:]
    (consensus_ref,
     tb_pred_ref, tb_wd_ref, tb_mr_ref, tb_rb_ref, tb_ng_ref, tb_cv_ref,
     b_sw_ref, b_swp_ref, b_swp2_ref, b_sw2_ref, b_count_ref) = refs

    now = now_ref[0, 0]
    probs = probs_ref[...]
    mask = mask_ref[...] > 0.0
    outcome = outcome_ref[...][0] > 0.0          # (TM,)
    state = MarketBlockState(
        reliability=rel_ref[...],
        confidence=conf_ref[...],
        updated_days=upd_ref[...],
        exists=exists,
    )

    # -- the ONE decayed read both analytics stages share (the XLA fused
    # program's exact structure: read_phase happens inside the stages'
    # shared preamble, the cycle re-reads through its own sanitised view).
    from bayesian_consensus_engine_tpu.ops.cycle_math import read_phase

    read_rel, read_conf = read_phase(state, now)

    with jax.named_scope("bce.ring_tiebreak"):
        tb = ring_tiebreak_math(
            probs, read_rel, read_conf, read_rel, mask,
            axis_name=None,
            axis_size=1,
            precision=precision,
            chunk_agents=chunk_agents,
            agents_last=False,       # slot-major: agents on axis 0
        )
    with jax.named_scope("bce.uncertainty_bands"):
        # The RAW moments only: the division/z epilogue runs OUTSIDE the
        # kernel (band_epilogue in the wrapper) because its optimization
        # barriers — the cross-program rounding pins — are stripped
        # inside Pallas kernel bodies (ops/uncertainty.py).
        sums, count = band_sums(
            probs, mask, read_rel,
            axis_name=None,
            axis_size=1,
            chunk_slots=chunk_slots,
            agents_last=False,
        )
    loop_math = make_loop_math(
        partial(_cycle_math, axis_name=None, slots_axis=0),
        steps,
        fast_cycle_fn=partial(_fast_cycle_math, axis_name=None, slots_axis=0),
    )
    new_state, consensus = loop_math(probs, mask, outcome, state, now)

    f32 = jnp.float32
    state_out_refs[0][...] = new_state.reliability
    state_out_refs[1][...] = new_state.confidence
    state_out_refs[2][...] = new_state.updated_days
    if has_exists:
        state_out_refs[3][...] = new_state.exists.astype(f32)
    consensus_ref[...] = consensus[None, :]
    tb_pred_ref[...] = tb.prediction[None, :]
    tb_wd_ref[...] = tb.weight_density[None, :]
    tb_mr_ref[...] = tb.max_reliability[None, :]
    tb_rb_ref[...] = tb.resolved_by[None, :]
    tb_ng_ref[...] = tb.num_groups[None, :]
    tb_cv_ref[...] = tb.confidence_variance[None, :]
    b_sw_ref[...] = sums[0][None, :]
    b_swp_ref[...] = sums[1][None, :]
    b_swp2_ref[...] = sums[2][None, :]
    b_sw2_ref[...] = sums[3][None, :]
    b_count_ref[...] = count[None, :]


def build_onepass_settle(
    num_markets: int,
    num_slots: int,
    steps: int,
    *,
    has_exists: bool = True,
    tile_markets: "int | None" = None,
    precision: int = 6,
    chunk_agents: "int | None" = None,
    chunk_slots: "int | None" = None,
    z: float = Z_95,
    interpret: bool = False,
):
    """The one-pass settlement launch for fixed (K=num_slots, M=num_markets).

    Returns ``onepass(probs, mask, outcome, state, now) ->
    (MarketBlockState, consensus, RingTieBreakResult, UncertaintyBands)``
    over slot-major float32 (K, M) blocks: ``mask``/``outcome`` are bool,
    ``state`` a float32 :class:`~.ops.cycle_math.MarketBlockState` (bool
    ``exists``, or ``None`` with ``has_exists=False``); per-market
    outputs are (M,). All three result families read the PRE-update
    decayed state at ``now``; the returned state is the N-step loop's —
    bit-identical to the XLA fused program's at every chunk setting.

    The callable is meant to be traced inside a surrounding jit /
    ``shard_map`` body (``parallel.sharded`` builds it at trace time per
    local shard shape); it is not jitted here. ``num_markets`` must be a
    multiple of the resolved ``tile_markets`` (``None`` →
    :func:`resolve_tile_markets`).
    """
    tile = (
        resolve_tile_markets(num_markets, num_slots)
        if tile_markets is None
        else int(tile_markets)
    )
    if num_markets % tile:
        raise ValueError(
            f"num_markets={num_markets} not a multiple of "
            f"tile_markets={tile} — pad the markets axis (pad_markets) "
            "before the kernel; a ragged tail tile would be dropped"
        )
    grid = (num_markets // tile,)

    block = pl.BlockSpec(
        (num_slots, tile), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    row = pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    scalar = pl.BlockSpec(memory_space=pltpu.SMEM)

    f32 = jnp.float32
    km = jax.ShapeDtypeStruct((num_slots, num_markets), f32)
    m1 = jax.ShapeDtypeStruct((1, num_markets), f32)
    m1_i32 = jax.ShapeDtypeStruct((1, num_markets), jnp.int32)

    n_state = 4 if has_exists else 3
    in_specs = [scalar, block, block, row] + [block] * n_state
    out_specs = [block] * n_state + [row] * 12
    out_shape = (
        [km] * n_state
        + [m1]                          # consensus
        + [m1, m1, m1, m1_i32, m1_i32, m1]   # tie-break
        + [m1, m1, m1, m1, m1_i32]           # band moments + count
    )
    # State tensors update in place: state inputs alias the state outputs
    # (input 4+j -> output j), so the resident block is read from HBM
    # once and written once — the single-sweep contract.
    aliases = {4 + j: j for j in range(n_state)}

    call = pl.pallas_call(
        partial(
            _onepass_kernel,
            steps=steps,
            has_exists=has_exists,
            precision=precision,
            chunk_agents=chunk_agents,
            chunk_slots=chunk_slots,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )

    def onepass(probs, mask, outcome, state: MarketBlockState, now):
        if state.reliability.dtype != f32:
            raise ValueError(
                "the one-pass kernel serves float32 state blocks only "
                f"(got {state.reliability.dtype}); keep kernel='xla' for "
                "other compute dtypes"
            )
        now_arr = jnp.reshape(jnp.asarray(now, f32), (1, 1))
        args = [
            now_arr,
            probs.astype(f32),
            mask.astype(f32),
            outcome.astype(f32)[None, :],
            state.reliability,
            state.confidence,
            state.updated_days,
        ]
        if has_exists:
            args.append(state.exists.astype(f32))
        out = call(*args)
        state_out, rest = out[:n_state], out[n_state:]
        new_state = MarketBlockState(
            reliability=state_out[0],
            confidence=state_out[1],
            updated_days=state_out[2],
            exists=state_out[3] > 0.0 if has_exists else None,
        )
        consensus = rest[0][0]
        tb = RingTieBreakResult(*(x[0] for x in rest[1:7]))
        # Moments → intervals in plain XLA: the epilogue's optimization
        # barriers survive here, so lo/hi round exactly as the fused XLA
        # program's band_math does.
        sums = jnp.stack([x[0] for x in rest[7:11]])
        bands = band_epilogue(sums, rest[11][0], z)
        return new_state, consensus, tb, bands

    return onepass


def _onepass_partials_kernel(
    now_ref,        # SMEM (1, 1)
    probs_ref,      # VMEM (K_local, TM) f32
    mask_ref,       # VMEM (K_local, TM) f32 0/1
    outcome_ref,    # VMEM (1, TM) f32 0/1
    rel_ref,        # VMEM (K_local, TM) f32
    conf_ref,       # VMEM (K_local, TM) f32
    upd_ref,        # VMEM (K_local, TM) f32
    *refs,          # [ex_ref] + output refs (see build_onepass_partials)
    steps: int,
    has_exists: bool,
    chunk_slots,
):
    if has_exists:
        ex_ref, refs = refs[0], refs[1:]
        exists = ex_ref[...] > 0.0
        state_out_refs, refs = refs[:4], refs[4:]
    else:
        exists = None
        state_out_refs, refs = refs[:3], refs[3:]
    (csum_ref, bsum_ref, b_count_ref, rrel_ref, rconf_ref) = refs

    now = now_ref[0, 0]
    probs = probs_ref[...]
    mask = mask_ref[...] > 0.0
    outcome = outcome_ref[...][0] > 0.0          # (TM,)
    state = MarketBlockState(
        reliability=rel_ref[...],
        confidence=conf_ref[...],
        updated_days=upd_ref[...],
        exists=exists,
    )

    # The ONE decayed read of this shard's slots. Unlike the fused
    # kernel, the views themselves are OUTPUTS: the tie-break needs
    # bit-identical GLOBAL stats per quantised-key group (a group can
    # span shards), so the axis-gated ring merge consumes these blocks
    # outside the kernel body instead of a per-shard fold inside it.
    from bayesian_consensus_engine_tpu.ops.cycle_math import read_phase

    read_rel, read_conf = read_phase(state, now)

    with jax.named_scope("bce.uncertainty_bands"):
        # RAW shard-local moment roots only; band_merge + band_epilogue
        # run outside (same reasoning as the fused kernel: barriers are
        # stripped in kernel bodies, and the cross-shard fold needs the
        # mesh axis).
        sums, count = band_sums(
            probs, mask, read_rel,
            axis_name=None,
            axis_size=1,
            chunk_slots=chunk_slots,
            agents_last=False,
        )
    loop_math = make_loop_math(
        partial(_sums_cycle_math, slots_axis=0),
        steps,
        fast_cycle_fn=partial(_sums_fast_cycle_math, slots_axis=0),
    )
    new_state, csums = loop_math(probs, mask, outcome, state, now)

    f32 = jnp.float32
    state_out_refs[0][...] = new_state.reliability
    state_out_refs[1][...] = new_state.confidence
    state_out_refs[2][...] = new_state.updated_days
    if has_exists:
        state_out_refs[3][...] = new_state.exists.astype(f32)
    csum_ref[...] = csums                 # (3, TM) Σw / Σw·p / Σw·conf
    bsum_ref[...] = sums                  # (4, TM) band tree roots
    b_count_ref[...] = count[None, :]
    rrel_ref[...] = read_rel
    rconf_ref[...] = read_conf


def build_onepass_partials(
    num_markets: int,
    num_slots: int,
    steps: int,
    *,
    has_exists: bool = True,
    tile_markets: "int | None" = None,
    chunk_slots: "int | None" = None,
    interpret: bool = False,
):
    """The sources-sharded one-pass launch: per-shard kernel PARTIALS.

    Returns ``partials(probs, mask, outcome, state, now) ->
    (MarketBlockState, consensus_sums, band_sums, band_count,
    read_rel, read_conf)`` over one shard's slot-major float32
    (K_local, M_local) block:

    * ``consensus_sums`` — (3, M) LAST-step raw local sums
      (Σw, Σw·p, Σw·conf) for the three cross-device psums +
      :func:`~.ops.cycle_math.consensus_epilogue` outside;
    * ``band_sums``/``band_count`` — (4, M) shard-local tree roots +
      i32 (M,) count for :func:`~.ops.uncertainty.band_merge` +
      :func:`~.ops.uncertainty.band_epilogue` outside;
    * ``read_rel``/``read_conf`` — the (K_local, M) decayed read views
      for the full axis-gated ``ring_tiebreak_math`` outside (a
      quantised-key tie-break group can span shards, so no per-shard
      fold is exact);
    * the returned state is the N-step loop's, exact per shard with NO
      collectives (``update_phase`` never consumes the consensus — the
      state evolution is embarrassingly parallel over sources).

    The caller merges the partials INSIDE its shard_map body, tracing the
    identical ``ops/cycle_math.py`` / ``ops/uncertainty.py`` phases the
    fused XLA program traces — parity stays structural.
    ``steps`` must be ≥ 1: zero raw sums normalise to NaN, not the XLA
    program's zero-step zero consensus.
    """
    if steps < 1:
        raise ValueError(
            "build_onepass_partials needs steps >= 1: the kernel emits "
            "RAW last-step consensus sums for the cross-device merge, and "
            "a zero-step program's zero consensus is not representable as "
            "sums (the epilogue of all-zero sums is NaN); route steps=0 "
            "through kernel='xla'"
        )
    tile = (
        resolve_tile_markets(
            num_markets, num_slots, blocks_per_tile=_PARTIALS_BLOCKS_PER_TILE
        )
        if tile_markets is None
        else int(tile_markets)
    )
    if num_markets % tile:
        raise ValueError(
            f"num_markets={num_markets} not a multiple of "
            f"tile_markets={tile} — pad the markets axis (pad_markets) "
            "before the kernel; a ragged tail tile would be dropped"
        )
    grid = (num_markets // tile,)

    block = pl.BlockSpec(
        (num_slots, tile), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    row = pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    row3 = pl.BlockSpec((3, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    row4 = pl.BlockSpec((4, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    scalar = pl.BlockSpec(memory_space=pltpu.SMEM)

    f32 = jnp.float32
    km = jax.ShapeDtypeStruct((num_slots, num_markets), f32)
    m3 = jax.ShapeDtypeStruct((3, num_markets), f32)
    m4 = jax.ShapeDtypeStruct((4, num_markets), f32)
    m1_i32 = jax.ShapeDtypeStruct((1, num_markets), jnp.int32)

    n_state = 4 if has_exists else 3
    in_specs = [scalar, block, block, row] + [block] * n_state
    out_specs = [block] * n_state + [row3, row4, row, block, block]
    out_shape = (
        [km] * n_state
        + [m3]          # consensus raw sums
        + [m4, m1_i32]  # band moment roots + count
        + [km, km]      # decayed read views for the outside ring merge
    )
    # State tensors update in place: state inputs alias the state outputs
    # (input 4+j -> output j) exactly as in build_onepass_settle; the
    # view/partial outputs are fresh buffers.
    aliases = {4 + j: j for j in range(n_state)}

    call = pl.pallas_call(
        partial(
            _onepass_partials_kernel,
            steps=steps,
            has_exists=has_exists,
            chunk_slots=chunk_slots,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )

    def partials(probs, mask, outcome, state: MarketBlockState, now):
        if state.reliability.dtype != f32:
            raise ValueError(
                "the one-pass kernel serves float32 state blocks only "
                f"(got {state.reliability.dtype}); keep kernel='xla' for "
                "other compute dtypes"
            )
        now_arr = jnp.reshape(jnp.asarray(now, f32), (1, 1))
        args = [
            now_arr,
            probs.astype(f32),
            mask.astype(f32),
            outcome.astype(f32)[None, :],
            state.reliability,
            state.confidence,
            state.updated_days,
        ]
        if has_exists:
            args.append(state.exists.astype(f32))
        out = call(*args)
        state_out, rest = out[:n_state], out[n_state:]
        new_state = MarketBlockState(
            reliability=state_out[0],
            confidence=state_out[1],
            updated_days=state_out[2],
            exists=state_out[3] > 0.0 if has_exists else None,
        )
        csums = rest[0]          # (3, M)
        bsums = rest[1]          # (4, M)
        b_count = rest[2][0]     # (M,) i32
        read_rel = rest[3]
        read_conf = rest[4]
        return new_state, csums, bsums, b_count, read_rel, read_conf

    return partials
