"""cluster/ — multi-host membership, degraded-mesh views, and recovery.

The round-13 subsystem (ROADMAP item 1): make the resident settlement
session a multi-host citizen and make host loss a recoverable event
rather than a process death. Three coordinated pieces:

* :mod:`~.cluster.membership` — deterministic, epoch-tagged
  :class:`MeshView` layouts over a host set: every host computes the
  SAME canonical mesh factorisation and band assignment from the same
  membership epoch, with no coordinator (agreement is by construction —
  the view is a pure function of the sorted host set), plus the
  *degraded* view derived from any surviving subset.
* :mod:`~.cluster.recover` — journal-driven recovery: merge the
  surviving per-band durability journals (:mod:`~.state.journal`) into
  one store deterministically, adopt a dead band's journal into a LIVE
  surviving stream, and pin the degraded-mesh byte contract (a merge of
  one journal is bit-equal to ``replay_journal`` of it).
* the session side lives where the session lives: round 13 extended
  ``ShardedSettlementSession.adopt`` (pipeline.py) past the PR-5
  teardown+rebuild fallback, so band-mode and cluster deployments keep
  their reliability block resident across topology drift — the
  ``stream.resident_fallbacks`` counter measures the retirement.

Failure is steady state here (PAPERS.md: SIGMA's early-life-hardware
stack; the TPU v2→Ironwood goodput framing): the reported health metric
of a kill is **recovered ``goodput_within_slo``** (obs/slo.py — refused
and crash-eaten traffic counting against), measured end to end by
``scripts/kill_soak.py`` and the ``e2e_kill_soak`` bench leg.
"""

from bayesian_consensus_engine_tpu.cluster.membership import (
    MeshView,
    runtime_view,
)
from bayesian_consensus_engine_tpu.cluster.recover import (
    ClusterModeUnsupported,
    ClusterReplay,
    adopt_journal,
    replay_cluster_journals,
    store_digest,
)

__all__ = [
    "ClusterModeUnsupported",
    "ClusterReplay",
    "MeshView",
    "adopt_journal",
    "replay_cluster_journals",
    "runtime_view",
    "store_digest",
]
