"""Deterministic epoch-tagged mesh views over the market-sharded cluster.

The cluster's one invariant is *agreement without a coordinator*: every
host must lay the global markets axis out identically — which host owns
which band, at which mesh factorisation — or the per-band plans, journals
and stores stop composing. Rather than electing anything, the layout is a
**pure function of the membership epoch's host set**: :class:`MeshView`
derives the canonical factorisation from the sorted host ids alone, so N
hosts that agree on "epoch 7 = hosts {0, 2, 3}" agree on everything else
by construction. A membership change (host loss, host return) is an epoch
bump producing a new view — the *degraded* view is just
:meth:`MeshView.degraded` over the surviving subset, computed identically
by every survivor.

Two deployment shapes read the same view:

* **hybrid multi-controller** — one JAX runtime over all hosts
  (:func:`~.parallel.distributed.make_hybrid_mesh`, DCN-outer markets):
  :meth:`MeshView.build_mesh` reproduces exactly that mesh, and
  :meth:`MeshView.band` reproduces :func:`~.parallel.distributed.
  process_market_rows`'s contiguous per-host band, because both order
  granules by sorted host id.
* **shared-nothing banded** — each host runs its OWN local mesh over its
  own devices and settles only its band's markets (the view is the
  agreement on *which* markets those are). The cycle needs zero
  cross-market communication (markets are pure data parallelism — the
  reason the hybrid mesh puts them DCN-outer in the first place), so the
  two shapes compute the same numbers; shared-nothing is the posture the
  kill soak (scripts/kill_soak.py) proves recovery under, and the only
  one a backend without multi-process collectives can run.

Epochs are small integers, strictly increasing across membership changes
within one service lifetime; journals and ledger records carry
:attr:`MeshView.fingerprint` so a replayed artifact names the membership
it was written under.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple


@dataclass(frozen=True)
class MeshView:
    """One membership epoch's canonical layout over *hosts*.

    ``hosts`` is the set of member host ids (any hashable ints — process
    indices on a pod, worker ranks in the shared-nothing shape), stored
    sorted so equal sets compare equal. ``devices_per_host`` and
    ``ici_shape`` describe the per-host (per-granule) device layout;
    ``ici_shape=None`` defaults to all in-host devices on the markets
    axis (mesh.py's default policy — reductions stay device-local).

    The view is immutable and hashable: two hosts holding equal views
    ARE in agreement, and a view is safe to use as a cache/compile key.
    """

    epoch: int
    hosts: Tuple[int, ...]
    devices_per_host: int = 1
    ici_shape: Optional[Tuple[int, int]] = None

    def __post_init__(self) -> None:
        if self.epoch < 0:
            raise ValueError(f"epoch must be >= 0; got {self.epoch}")
        hosts = tuple(sorted(int(h) for h in self.hosts))
        if not hosts:
            raise ValueError("a membership view needs at least one host")
        if len(set(hosts)) != len(hosts):
            raise ValueError(f"duplicate host ids in {self.hosts!r}")
        object.__setattr__(self, "hosts", hosts)
        if self.devices_per_host < 1:
            raise ValueError("devices_per_host must be >= 1")
        ici = self.ici_shape
        if ici is None:
            ici = (self.devices_per_host, 1)
        else:
            ici = (int(ici[0]), int(ici[1]))
        if ici[0] * ici[1] != self.devices_per_host:
            raise ValueError(
                f"ici_shape {ici} needs {ici[0] * ici[1]} devices per "
                f"host, declared {self.devices_per_host}"
            )
        object.__setattr__(self, "ici_shape", ici)

    # -- derived layout ------------------------------------------------------

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    @property
    def markets_extent(self) -> int:
        """Global markets-axis device extent (granules × ICI markets)."""
        return self.num_hosts * self.ici_shape[0]

    @property
    def sources_extent(self) -> int:
        return self.ici_shape[1]

    @property
    def shape(self) -> Tuple[int, int]:
        """The (markets, sources) mesh shape this view factorises to."""
        return (self.markets_extent, self.sources_extent)

    def rank_of(self, host: int) -> int:
        """This host's position in the sorted member list (= its granule
        index in the hybrid mesh's granule-major device order)."""
        try:
            return self.hosts.index(int(host))
        except ValueError:
            raise ValueError(
                f"host {host} is not a member of epoch {self.epoch} "
                f"(hosts {self.hosts})"
            ) from None

    def padded_markets(self, global_markets: int) -> int:
        """*global_markets* padded up to the markets-axis extent."""
        extent = self.markets_extent
        return -(-max(int(global_markets), 1) // extent) * extent

    def band(self, host: int, global_markets: int) -> Tuple[int, int]:
        """*host*'s ``(lo, global_markets)`` band of the markets axis.

        The contiguous padded-row band the host feeds and absorbs —
        the exact tuple :func:`~.pipeline.settle_stream`'s ``band=``
        takes, and (on the hybrid mesh) the same rows
        :func:`~.parallel.distributed.process_market_rows` assigns,
        because both lay granules out in sorted-host order.
        """
        padded = self.padded_markets(global_markets)
        width = padded // self.num_hosts
        return (self.rank_of(host) * width, int(global_markets))

    def owned_markets(self, host: int, global_markets: int) -> range:
        """The LIVE (unpadded) market rows *host* owns — its ingest shard."""
        lo, _ = self.band(host, global_markets)
        padded = self.padded_markets(global_markets)
        hi = lo + padded // self.num_hosts
        return range(min(lo, int(global_markets)), min(hi, int(global_markets)))

    # -- membership changes --------------------------------------------------

    def degraded(self, surviving: Sequence[int]) -> "MeshView":
        """The next epoch's view over a surviving subset of this one.

        Every survivor computes the same degraded view from the same
        (epoch, survivor-set) observation — the coordinator-free
        agreement this module exists for. The failed hosts' market rows
        re-band across the survivors; their state re-enters through
        journal replay (:mod:`~.cluster.recover`), never through the
        dead hosts.
        """
        survivors = tuple(sorted(int(h) for h in surviving))
        if not survivors:
            raise ValueError("cannot degrade to an empty host set")
        missing = set(survivors) - set(self.hosts)
        if missing:
            raise ValueError(
                f"surviving hosts {sorted(missing)} are not members of "
                f"epoch {self.epoch} (hosts {self.hosts})"
            )
        return MeshView(
            epoch=self.epoch + 1,
            hosts=survivors,
            devices_per_host=self.devices_per_host,
            ici_shape=self.ici_shape,
        )

    @property
    def fingerprint(self) -> bytes:
        """Order-sensitive digest of the view — the membership identity a
        journal, ledger record, or soak log carries. Length-delimited
        (the topology_fingerprint discipline): distinct views can never
        collide by concatenation."""
        h = hashlib.blake2b(digest_size=16)
        for value in (
            self.epoch, self.num_hosts, self.devices_per_host,
            *self.ici_shape, *self.hosts,
        ):
            raw = str(int(value)).encode()
            h.update(len(raw).to_bytes(4, "little"))
            h.update(raw)
        return h.digest()

    # -- mesh construction ---------------------------------------------------

    def build_mesh(self, devices=None):
        """The JAX mesh this view factorises to, for THIS process.

        Multi-host members on a multi-controller runtime get the hybrid
        DCN-outer mesh (:func:`~.parallel.distributed.make_hybrid_mesh`
        with one granule per member, sorted-host order — the order
        :meth:`band` assumes). A single-host view (including every
        shared-nothing worker, whose cluster identity lives in the view
        rather than in a shared runtime) gets the plain local mesh over
        its own devices.
        """
        if self.num_hosts == 1:
            import jax

            from bayesian_consensus_engine_tpu.parallel.mesh import (
                make_mesh,
            )

            if devices is None:
                devices = jax.local_devices()[: self.devices_per_host]
            return make_mesh(self.ici_shape, devices=devices)
        from bayesian_consensus_engine_tpu.parallel.distributed import (
            make_hybrid_mesh,
        )

        return make_hybrid_mesh(
            ici_shape=self.ici_shape,
            num_granules=self.num_hosts,
            devices=devices,
        )


def runtime_view(epoch: int = 0) -> MeshView:
    """The view of the CURRENT multi-controller runtime, epoch-tagged.

    Reads ``jax.process_count()``/``jax.local_devices()`` (initialising
    the backend — call after :func:`~.parallel.distributed.
    init_distributed`, never at import time) and returns the view every
    process in the runtime derives identically: hosts = the process
    indices, one granule each.
    """
    import jax

    return MeshView(
        epoch=epoch,
        hosts=tuple(range(jax.process_count())),
        devices_per_host=len(jax.local_devices()),
    )
