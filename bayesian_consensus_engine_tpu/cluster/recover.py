"""Journal-driven recovery onto a degraded membership view.

Host loss must cost one membership epoch and a journal replay — never
the service (ROADMAP item 1; PAPERS.md's failure-as-steady-state
framing). The durable truth of a band is its append-only journal
(:mod:`~.state.journal`): each host's journal replays to exactly its
band's store, independent of which mesh factorisation wrote it (replay
is pure host-side row arithmetic — the degraded-mesh byte contract the
crash-resume suite pins). Recovery is therefore composition:

* :func:`replay_cluster_journals` — merge N band journals into ONE
  fresh store, deterministically: journals in the given order, each
  journal's pairs interned in its own replay order. A single-journal
  merge is **bit-equal** to :func:`~.state.journal.replay_journal` by
  construction (same frame walk, same intern order, same value writes),
  which is the contract that makes "replay onto the surviving-host
  mesh" and "single-host replay" the same bytes.
* :func:`adopt_journal` — replay a dead band's journal INTO a live
  surviving store mid-stream: the survivor interns the orphan pairs
  (disjoint from its own by the band partition — enforced), absorbs the
  replayed values, and its resident session picks the rows up on the
  next batch's adopt (the round-13 relayout path: orphan rows enter the
  device block as host-exact uploads, the surviving rows never leave
  HBM). The survivor's own NEXT journal epoch then carries the adopted
  band — the dead journal is read once and never needed again.

Split-brain is refused, not merged: a (source, market) pair appearing in
two journals means two hosts both claimed its band — recovery raises
rather than guess which history wins.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from pathlib import Path
from time import perf_counter
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from bayesian_consensus_engine_tpu.obs.trace import active_tracer
from bayesian_consensus_engine_tpu.state.journal import (
    MAGIC,
    TornTraceError,
    TraceBatch,
    _iter_frames,
    _read_exact,
    extract_trace,
)

#: Trace scope for live-recovery spans (obs/trace.py). Recovery runs
#: between batches on a surviving host — if a dispatch failure lands
#: WHILE an adoption is in flight, the crash postmortem must say so,
#: which is why :func:`adopt_journal` records its start before the
#: replay walk begins (the flight ring then holds an adopt_start with
#: no adopt_done — recovery-in-progress, captured; pinned by
#: tests/test_cluster.py).
RECOVERY_SCOPE = "recovery"


class ClusterModeUnsupported(RuntimeError):
    """A cluster/band-mode deployment asked for a route that is not
    served on this topology yet. The message names the supported route
    (the shared-nothing band membership of
    :class:`~.cluster.membership.MeshView`) and what remains open."""


@dataclass
class ClusterReplay:
    """The result of merging band journals onto one store.

    ``tags[i]`` is journal *i*'s last complete epoch watermark (``None``
    for a journal with no complete epoch) — with ``settle_stream``'s
    ``journal=`` mode, the last durably-covered settled batch index of
    that band; the band resumes from ``tags[i] + 1``. ``rows[i]`` is how
    many interned rows journal *i* contributed.
    """

    store: object
    paths: Tuple[str, ...]
    tags: Tuple[Optional[int], ...]
    rows: Tuple[int, ...]

    def resume_index(self, which: int) -> int:
        """First un-durable batch index of band *which* (0 when the
        journal held no complete epoch)."""
        tag = self.tags[which]
        return 0 if tag is None else tag + 1


def _replay_into(store, path: str) -> Tuple[Optional[int], int]:
    """Replay *path*'s epochs into *store*, remapping journal-local rows
    onto the store's interner. Returns ``(last_tag, rows_contributed)``.

    The journal's pairs intern in replay order — for a fresh store and a
    single journal that reproduces :func:`~.state.journal.replay_journal`
    row for row; for a merge, each journal's block appends after the
    previous journals' rows, and any pair already interned (an
    overlapping band) is a split-brain refusal.
    """
    row_map: List[int] = []
    last_tag: Optional[int] = None
    with open(path, "rb") as f:
        if _read_exact(f, len(MAGIC)) != MAGIC:
            raise ValueError(f"{path}: not a BCE journal (bad magic)")
        for fields, decoded, _off in _iter_frames(f):
            tag = fields[6]
            pairs, idx, rel, conf, days, exists, iso_values = decoded
            if pairs:
                before = len(store)
                rows = store.rows_for_pairs(pairs, allocate=True)
                if list(rows) != list(range(before, before + len(pairs))):
                    raise ValueError(
                        f"{path}: journal pairs overlap rows already "
                        "replayed from another journal — two hosts "
                        "claimed the same band (split-brain); refusing "
                        "to merge"
                    )
                row_map.extend(int(r) for r in rows)
            mapped = np.asarray(row_map, dtype=np.int64)[
                idx.astype(np.int64)
            ]
            store.absorb_replayed_rows(
                mapped, rel, conf, days, exists.astype(bool), iso_values
            )
            last_tag = int(tag)
    return last_tag, len(row_map)


def replay_cluster_journals(
    paths: Sequence[Union[str, Path]]
) -> ClusterReplay:
    """Rebuild ONE store from N band journals, deterministically.

    Journals replay in the order given (callers pass a sorted list —
    every surviving host must pass the SAME order to agree on row
    assignment; sorting by path is the convention the kill soak and the
    recovery example use). Each journal's contribution is exactly what
    :func:`~.state.journal.replay_journal` would rebuild from it alone —
    a one-journal call is bit-equal to ``replay_journal`` (store arrays,
    pair order, ISO sidecars), pinned by tests/test_cluster.py.
    """
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    if not paths:
        raise ValueError("no journals to replay")
    store = TensorReliabilityStore()
    tags: List[Optional[int]] = []
    rows: List[int] = []
    for path in paths:
        tag, contributed = _replay_into(store, str(path))
        tags.append(tag)
        rows.append(contributed)
    return ClusterReplay(
        store=store,
        paths=tuple(str(p) for p in paths),
        tags=tuple(tags),
        rows=tuple(rows),
    )


def adopt_journal(store, path: Union[str, Path]) -> Tuple[Optional[int], int]:
    """Replay a dead band's journal INTO a live surviving *store*.

    The survivor-side half of degraded-mesh recovery: called between
    batches (the stream's batch generator is the natural site — see
    scripts/kill_soak.py), it appends the orphan band's pairs to the
    live interner and absorbs its replayed values, so the very next
    batch that covers the orphan markets finds host-exact state and the
    resident session's adopt carries it onto the device block as
    entering rows. Returns ``(last_tag, rows_adopted)`` — the orphan
    band's workload resumes from ``last_tag + 1``.

    The adopted rows are fresh by the band partition (an overlap raises
    — split-brain), hence disjoint from every pending device recipe of
    the live stream: the adoption never stalls on, nor perturbs, the
    survivor's own deferred settlements.

    When a tracer is active the adoption records a ``recovery``-scope
    span chain (``adopt_start`` before the replay walk, ``adopt_done``
    with the rows/tag after) on its own flight-recorder component — a
    dispatch failure mid-adoption leaves a postmortem that SHOWS the
    recovery in flight.
    """
    tracer = active_tracer()
    if tracer.enabled:
        tracer.span_event(
            RECOVERY_SCOPE, 0, "adopt_start",
            args={"journal": str(path)}, component="recovery",
        )
    start = perf_counter()
    tag, rows_adopted = _replay_into(store, str(path))
    if tracer.enabled:
        tracer.span_event(
            RECOVERY_SCOPE, 0, "adopt_done",
            dur_s=perf_counter() - start,
            args={
                "journal": str(path),
                "rows_adopted": rows_adopted,
                "tag": tag,
            },
            component="recovery",
        )
    return tag, rows_adopted


def extract_cluster_trace(
    paths: Sequence[Union[str, Path]],
    strict: bool = False,
) -> Tuple[List[TraceBatch], Tuple[Optional[int], ...]]:
    """Merge N band journals' trace sidecars into ONE replayable workload.

    The fleet-journal half of the counterfactual replay lab
    (``replay/``): each band's trace (``<journal>.trace``) is bounded by
    its own journal's durable tag exactly as
    :func:`~.state.journal.extract_trace` bounds a single host's, then
    batch ``i`` of the merged workload concatenates every band's batch
    ``i`` in the order *paths* are given — the same order convention as
    :func:`replay_cluster_journals`, so the merged batch covers the same
    markets the fleet settled that cadence. The merged length is the
    SHORTEST band's covered prefix; ``strict=True`` refuses
    (:class:`~.state.journal.TornTraceError`) when bands disagree on it
    (a band lost trace or journal tail) instead of silently shortening.
    Bands must agree on each batch's settlement day and step count — a
    fleet driven on wall clock records per-host days and cannot be
    merged; record with an explicit ``now`` schedule.

    Returns ``(batches, tags)`` with ``tags[i]`` journal *i*'s watermark.
    """
    if not paths:
        raise ValueError("no journals to extract a trace from")
    per_band: List[List[TraceBatch]] = []
    tags: List[Optional[int]] = []
    for path in paths:
        covered, tag = extract_trace(str(path), strict=strict)
        per_band.append(covered)
        tags.append(tag)
    length = min(len(covered) for covered in per_band)
    if strict and any(len(covered) != length for covered in per_band):
        raise TornTraceError(
            "bands disagree on the covered batch count "
            f"({[len(c) for c in per_band]}); strict replay refuses a "
            "workload some band never made durable"
        )
    merged: List[TraceBatch] = []
    for i in range(length):
        bands = [covered[i] for covered in per_band]
        first = bands[0]
        for band_batch, path in zip(bands, paths):
            if band_batch.now_days != first.now_days or (
                band_batch.steps != first.steps
            ):
                raise ValueError(
                    f"{path}: batch {i} settlement day/steps "
                    f"({band_batch.now_days}, {band_batch.steps}) disagree "
                    f"with {paths[0]} ({first.now_days}, {first.steps}); "
                    "merged replay needs an explicit now schedule"
                )
        offsets = [0]
        for band_batch in bands:
            for width in np.diff(band_batch.offsets):
                offsets.append(offsets[-1] + int(width))
        merged.append(
            TraceBatch(
                index=i,
                market_keys=tuple(
                    k for b in bands for k in b.market_keys
                ),
                source_ids=tuple(
                    s for b in bands for s in b.source_ids
                ),
                probabilities=np.concatenate(
                    [b.probabilities for b in bands]
                ),
                offsets=np.asarray(offsets, dtype=np.int64),
                outcomes=np.concatenate([b.outcomes for b in bands]),
                now_days=first.now_days,
                steps=first.steps,
            )
        )
    return merged, tuple(tags)


def store_digest(store) -> str:
    """Order-sensitive content digest of a store's replayable state.

    Hashes the interned pair list (in row order), the four value
    columns, and the ISO sidecars — everything journal replay
    reproduces — length-delimited. Two stores with equal digests hold
    bit-identical state in identical row order; the kill soak and the
    recovery example use it as the byte-exactness coda's witness.
    Deferred device settlements are synced first (the digest is of the
    durable truth, not a racing snapshot).
    """
    store.sync()
    used = len(store)
    h = hashlib.blake2b(digest_size=16)

    def put(raw: bytes) -> None:
        h.update(len(raw).to_bytes(8, "little"))
        h.update(raw)

    for source_id, market_id in store._pairs.ids():
        put(source_id.encode())
        put(market_id.encode())
    put(np.ascontiguousarray(store._rel[:used]).tobytes())
    put(np.ascontiguousarray(store._conf[:used]).tobytes())
    put(np.ascontiguousarray(store._days[:used]).tobytes())
    put(np.ascontiguousarray(store._exists[:used]).tobytes())
    for value in store._iso[:used]:
        put(value.encode())
    return h.hexdigest()
