"""`MarketGraph` — the host-side market-dependency graph behind the
correlated-consensus sweep.

An edge ``(market, depends_on, weight)`` declares that *market*'s
consensus should be pulled toward *depends_on*'s, with relative
strength *weight* — the dependency structure of composite/constituent
markets ("Graphical Representations of Consensus Belief", PAPERS.md).
The graph is built ONCE from an edge list, interned and CSR-compacted
with the same machinery the signal topology uses (``core.batch``:
first-seen id interning, market-major offsets/indices arrays), and then
ALIGNED per plan: :meth:`align` maps node ids onto a plan's market rows
and pads the CSR rows to a dense static ``(markets, max_degree)``
neighbour block — the shape :func:`~.ops.propagate.damped_sweep_math`
gathers from on device.

**Fingerprints.** :attr:`fingerprint` is the order-sensitive digest of
the graph (node table, raw edge order, weights, damping, sweep depth) —
the graph-side extension of :func:`~.core.batch.topology_fingerprint`:
:meth:`extended_fingerprint` folds a plan's topology digest together
with the graph's, so any cache keyed on it (the session's aligned
neighbour blocks, a fused-program registry) reuses across
probability-only refreshes and misses the moment either the signal
topology OR the graph changes. Edge reordering changes the digest by
contract, mirroring the float-summation-order rule of the topology
fingerprint (the neighbour accumulation order follows edge order).
"""

from __future__ import annotations

import hashlib
from typing import Iterable, Sequence, Tuple

import numpy as np

from bayesian_consensus_engine_tpu.core.batch import encode_source_ids
from bayesian_consensus_engine_tpu.ops.propagate import (
    DEFAULT_DAMPING,
    DEFAULT_SWEEP_STEPS,
)

Edge = Tuple[str, str, float]


class MarketGraph:
    """Immutable CSR market-dependency graph + sweep configuration.

    Build with :meth:`from_edges`. ``damping`` (λ) and ``steps`` are
    part of the graph object — they parameterise the compiled sweep, so
    carrying them here keeps "one graph, one program" true.
    """

    __slots__ = (
        "node_ids", "offsets", "indices", "weights",
        "damping", "steps", "fingerprint",
    )

    def __init__(
        self,
        node_ids: Sequence[str],
        offsets: np.ndarray,
        indices: np.ndarray,
        weights: np.ndarray,
        damping: float,
        steps: int,
        fingerprint: bytes,
    ) -> None:
        self.node_ids = list(node_ids)
        self.offsets = offsets
        self.indices = indices
        self.weights = weights
        self.damping = float(damping)
        self.steps = int(steps)
        self.fingerprint = fingerprint
        for array in (self.offsets, self.indices, self.weights):
            array.setflags(write=False)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_edges(
        cls,
        edges: Iterable[Edge],
        damping: float = DEFAULT_DAMPING,
        steps: int = DEFAULT_SWEEP_STEPS,
    ) -> "MarketGraph":
        """Build from ``(market_id, depends_on_id, weight)`` triples.

        Node ids intern first-seen (the same discipline as source-id
        interning — ``core.batch.encode_source_ids`` does the pass);
        edges group market-major in a stable sort, so each market's
        neighbour order is its submission order. Self-edges and
        non-positive weights are rejected: a zero-weight edge is an
        absent edge, and a self-edge would double-count the damping
        term.
        """
        edges = list(edges)
        if not 0.0 <= damping <= 1.0:
            raise ValueError(f"damping must be in [0, 1], got {damping}")
        if steps < 0:
            raise ValueError(f"steps must be >= 0, got {steps}")
        flat_ids: list[str] = []
        weights = np.empty(len(edges), dtype=np.float64)
        for i, (src, dst, weight) in enumerate(edges):
            if src == dst:
                raise ValueError(f"self-edge on {src!r}")
            if not weight > 0.0:
                raise ValueError(
                    f"edge ({src!r}, {dst!r}) weight must be > 0, "
                    f"got {weight}"
                )
            flat_ids.append(src)
            flat_ids.append(dst)
            weights[i] = weight
        codes = encode_source_ids(flat_ids)
        src_codes = codes.codes[0::2]
        dst_codes = codes.codes[1::2]
        num_nodes = len(codes.table)
        # Market-major CSR in first-seen node order; a STABLE sort keeps
        # each node's neighbours in edge-submission order (the
        # order-sensitivity contract).
        order = np.argsort(src_codes, kind="stable")
        indices = np.ascontiguousarray(dst_codes[order], dtype=np.int32)
        csr_weights = np.ascontiguousarray(weights[order])
        offsets = np.searchsorted(
            src_codes[order], np.arange(num_nodes + 1)
        ).astype(np.int64)

        digest = hashlib.blake2b(digest_size=16)
        digest.update(
            np.asarray(
                [num_nodes, len(edges), steps], np.int64
            ).tobytes()
        )
        digest.update(np.float64(damping).tobytes())
        digest.update(
            np.fromiter(
                map(len, codes.table), np.int64, len(codes.table)
            ).tobytes()
        )
        digest.update("".join(codes.table).encode("utf-8"))
        # Raw (pre-sort) edge order: reordering MUST miss.
        digest.update(np.ascontiguousarray(src_codes).tobytes())
        digest.update(np.ascontiguousarray(dst_codes).tobytes())
        digest.update(weights.tobytes())
        return cls(
            codes.table, offsets, indices, csr_weights,
            damping, steps, digest.digest(),
        )

    # -- introspection -------------------------------------------------------

    @property
    def num_nodes(self) -> int:
        return len(self.node_ids)

    @property
    def num_edges(self) -> int:
        return int(self.indices.shape[0])

    def extended_fingerprint(self, topology_digest: "bytes | None") -> bytes:
        """Fold a plan's topology digest with the graph's — the combined
        reuse key for anything cached per (signal topology, graph)."""
        digest = hashlib.blake2b(digest_size=16)
        digest.update(topology_digest or b"\x00")
        digest.update(self.fingerprint)
        return digest.digest()

    # -- per-plan alignment --------------------------------------------------

    def align(
        self,
        market_keys: Sequence[str],
        padded_total: "int | None" = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Dense neighbour blocks for one batch's market universe.

        Maps graph nodes onto *market_keys* positions (row order — the
        global padded markets axis of the sharded layout) and pads each
        present market's CSR row to the batch's max degree:
        ``(neighbor_idx i32[T, D], neighbor_w f32[T, D])`` with
        ``T = padded_total or len(market_keys)`` and ``-1`` in unused
        lanes. Edges touching a market absent from this batch are
        dropped — the sweep couples only markets that exist in the
        block it runs against.
        """
        total = len(market_keys) if padded_total is None else padded_total
        if total < len(market_keys):
            raise ValueError(
                f"padded_total {total} < {len(market_keys)} markets"
            )
        row_of = {key: i for i, key in enumerate(market_keys)}
        rows: list[tuple[int, np.ndarray, np.ndarray]] = []
        max_degree = 1
        for code, node in enumerate(self.node_ids):
            row = row_of.get(node)
            if row is None:
                continue
            lo, hi = int(self.offsets[code]), int(self.offsets[code + 1])
            nb_rows = []
            nb_weights = []
            for dst, weight in zip(
                self.indices[lo:hi], self.weights[lo:hi]
            ):
                dst_row = row_of.get(self.node_ids[int(dst)])
                if dst_row is None:
                    continue
                nb_rows.append(dst_row)
                nb_weights.append(weight)
            if nb_rows:
                rows.append((
                    row,
                    np.asarray(nb_rows, dtype=np.int32),
                    np.asarray(nb_weights, dtype=np.float32),
                ))
                max_degree = max(max_degree, len(nb_rows))
        neighbor_idx = np.full((total, max_degree), -1, dtype=np.int32)
        neighbor_w = np.zeros((total, max_degree), dtype=np.float32)
        for row, nb_rows, nb_weights in rows:
            neighbor_idx[row, : len(nb_rows)] = nb_rows
            neighbor_w[row, : len(nb_rows)] = nb_weights
        return neighbor_idx, neighbor_w
