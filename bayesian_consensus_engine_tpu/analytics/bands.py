"""Sharded surfaces for the uncertainty-band kernel.

Two things live here, one level above the kernel
(``ops/uncertainty.py``) and below the orchestration tier:

* :func:`build_band_program` — the STANDALONE mesh program: bands from a
  slot-major (K, M) probability/mask block and a resident
  ``MarketBlockState``, reading the same decayed reliabilities the
  consensus weighs with (``parallel.sharded.read_phase`` at the given
  ``now``). This is the two-program shape the fused resident path
  (``ShardedSettlementSession.settle_with_analytics``) exists to beat:
  dispatching it after a settle re-sends the whole block argument list a
  second time — the ``e2e_analytics`` leg's co-residency A/B measures
  exactly that arg-bytes double-pay.
* :class:`AnalyticsOptions` — the one bag of analytics knobs the session
  entry, the serve driver, and ``ConsensusService(analytics=...)`` all
  accept, so the opt-in surface is a single object rather than five
  keyword arguments threaded through three layers.

``chunk_slots="auto"`` resolves through the honesty-guarded process
:class:`~.utils.autotune.ShapeTuner` (knob ``band_chunk_slots``), racing
the power-of-two candidate ladder against the recorded
:data:`~.ops.uncertainty.DEFAULT_CHUNK_SLOTS` on the same clock —
disabled (the default) it resolves straight to the recorded default.
Every resolution is rounded down to a power of two, so the tuner can
never buy speed at the price of the tree-alignment bit contract.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from bayesian_consensus_engine_tpu.analytics.graph import MarketGraph
from bayesian_consensus_engine_tpu.ops.uncertainty import (
    DEFAULT_CHUNK_SLOTS,
    UncertaintyBands,
    Z_95,
    band_math,
)
from bayesian_consensus_engine_tpu.parallel._jax_compat import shard_map
from bayesian_consensus_engine_tpu.parallel.mesh import (
    MARKETS_AXIS,
    SOURCES_AXIS,
)
from bayesian_consensus_engine_tpu.parallel.sharded import (
    MarketBlockState,
    read_phase,
)

#: Candidate chunk widths the shape tuner races (clamped to the shard
#: width at resolve time). Module constant so tests can monkeypatch the
#: ladder down to toy shapes — the same idiom as parallel/ring.py.
_CHUNK_CANDIDATES = (128, 256, 512, 1024, 2048)


@dataclass(frozen=True)
class AnalyticsOptions:
    """Per-call analytics configuration (session, driver, and service).

    ``chunk_slots``/``chunk_agents`` take an int, ``None`` (unchunked),
    or ``"default"`` (the recorded defaults — the memory diet is ON by
    default; co-residency is the point). ``graph=None`` skips the
    propagation sweep entirely (no neighbour arguments enter the fused
    program); ``tiebreak=False`` likewise drops the ring tie-break
    stage from the compiled program — a service that only wants bands
    pays for neither the ring pass nor its temps — and
    ``tiebreak="sorted"`` swaps the ring fold for the sort-based
    grouping kernel (:func:`~.ops.tiebreak.batched_tiebreak` — the
    CPU-heavy-deployment shape; needs the sources axis unsharded).
    ``z`` scales the credible interval (default two-sided 95%).

    ``kernel`` picks the fused program's execution route (round 14):
    ``"xla"`` (default) is the multi-pass XLA program, ``"pallas"`` the
    one-pass settlement kernel (``ops/pallas_settle.py`` — cycles +
    tie-break + bands in a single HBM sweep per tile, bit-identical
    outputs, sources axis unsharded), ``"auto"`` the honesty-guarded
    shape tuner (knob ``settle_kernel``; the kernel ships per shape
    only when it strictly beat XLA on the same clock).

    **Round 18 (infer/).** ``inference`` takes an
    :class:`~.infer.bp.InferenceOptions`: the graph sweep upgrades to
    moment-pair belief propagation (precision-weighted mixing seeded
    from the band stderr; propagated output becomes a
    :class:`~.ops.propagate.PropagatedBeliefs`), optionally with the
    deterministic residual early-exit. ``blocks`` takes a
    :class:`~.infer.blocks.MarketBlocks`: constraint-typed
    combinatorial declarations compiled to graph edges when no
    ``graph=`` is given, plus the deterministic post-sweep projection
    applied to the propagated means. Both are typed loosely here —
    analytics (layer 6) must not import infer (layer 7); the pipeline
    (layer 8) imports both and validates.

    **Round 19.** ``sweep_kernel`` picks the graph sweep's execution
    route, orthogonal to ``kernel``: ``"xla"`` (default) is the
    ``while_loop`` sweep, ``"pallas"`` the VMEM-resident
    belief-propagation kernel (``ops/pallas_bp.py`` — the (mean,
    variance) state pinned in VMEM across all iterations, bit-identical
    outputs including the early-exit audit pair), ``"auto"`` the
    honesty-guarded shape tuner (knob ``sweep_kernel``). Needs a graph
    (or blocks) — there is no sweep to offload otherwise.
    """

    z: float = Z_95
    chunk_slots: "int | str | None" = "default"
    chunk_agents: "int | str | None" = "default"
    graph: Optional[MarketGraph] = None
    precision: int = 6
    tiebreak: "bool | str" = True
    kernel: str = "xla"
    sweep_kernel: str = "xla"
    inference: Optional[object] = None
    blocks: Optional[object] = None


def _tuned_chunk_slots(mesh: Mesh, z: float, shape: tuple) -> "int | None":
    """Resolve ``chunk_slots="auto"`` for one slot-major (K, M) shape.

    Measured once per (shape, mesh, device-kind) through the process
    tuner; the honesty guard races every candidate against the recorded
    default on the same clock and ships the default unless something
    strictly beat it.
    """
    import numpy as np

    from bayesian_consensus_engine_tpu.utils.autotune import (
        default_tuner,
        time_best_of,
    )

    slots, markets = int(shape[0]), int(shape[1])
    k_loc = max(1, slots // mesh.shape[SOURCES_AXIS])
    default = min(DEFAULT_CHUNK_SLOTS, k_loc)
    candidates = [c for c in _CHUNK_CANDIDATES if c < k_loc]
    candidates.append(k_loc)  # the unchunked reference rides the race
    candidates = [c for c in candidates if c != default]
    if not candidates:
        return default

    def measure(chunk: int) -> float:
        import jax.numpy as jnp

        fn = _compile_band_program(mesh, z, chunk, has_exists=True)
        rng = np.random.default_rng(23)
        probs = jnp.asarray(rng.random((slots, markets)), jnp.float32)
        mask = jnp.asarray(rng.random((slots, markets)) < 0.9)
        state = MarketBlockState(
            reliability=jnp.asarray(
                rng.uniform(0.1, 1.0, (slots, markets)), jnp.float32
            ),
            confidence=jnp.asarray(
                rng.uniform(0.0, 1.0, (slots, markets)), jnp.float32
            ),
            updated_days=jnp.zeros((slots, markets), jnp.float32),
            exists=jnp.asarray(rng.random((slots, markets)) < 0.7),
        )
        now = jnp.asarray(400.0, jnp.float32)

        def run() -> None:
            out = fn(probs, mask, state, now)
            np.asarray(out.mean)  # fence: force the result to host

        return time_best_of(run, repeats=2, warmup=1)

    return default_tuner().tune(
        "band_chunk_slots",
        (slots, markets, *(int(s) for s in mesh.devices.shape)),
        candidates,
        measure,
        default,
    )


def _compile_band_program(
    mesh: Mesh, z: float, chunk_slots: "int | None", has_exists: bool
):
    """One jitted slot-major standalone band program for *mesh*."""
    block = P(SOURCES_AXIS, MARKETS_AXIS)
    market = P(MARKETS_AXIS)
    n_sources = mesh.shape[SOURCES_AXIS]

    def math(probs, mask, state, now):
        read_rel, _ = read_phase(state, now)
        return band_math(
            probs, mask, read_rel,
            axis_name=SOURCES_AXIS,
            axis_size=n_sources,
            z=z,
            chunk_slots=chunk_slots,
            agents_last=False,  # slot-major: slots on axis 0
        )

    state_spec = MarketBlockState(
        block, block, block, block if has_exists else None
    )
    fn = shard_map(
        math,
        mesh=mesh,
        in_specs=(block, block, state_spec, P()),
        out_specs=UncertaintyBands(*([market] * 6)),
        check_vma=False,  # the tree fold defeats the vma checker
    )
    # Never donates: the standalone program reads the SAME resident
    # block a subsequent settle will donate — the whole point of the
    # fused alternative is that this program must not own anything.
    return jax.jit(fn)


def build_band_program(
    mesh: Mesh,
    z: float = Z_95,
    chunk_slots: "int | str | None" = None,
):
    """Standalone sharded band program over a resident state block.

    ``bands(probs, mask, state, now) -> UncertaintyBands`` on slot-major
    (K, M) blocks sharded ``P(sources, markets)`` — the session layout —
    with per-market outputs ``P(markets)``. ``now`` is the scalar read
    day: weights are the decayed reliabilities the consensus reduction
    would use at the same instant (cold slots read the cold-start
    prior). ``chunk_slots``: ``None`` unchunked, an int (power-of-two
    clamped), or ``"auto"`` via the shape tuner. Outputs bit-identical
    at every setting (tests/test_analytics.py). Exposes ``.lower`` for
    AOT ``memory_analysis()`` captures.
    """
    compiled: dict = {}

    def resolve(shape) -> "int | None":
        if chunk_slots == "auto":
            return _tuned_chunk_slots(mesh, z, shape)
        if isinstance(chunk_slots, str):
            raise ValueError(
                f"chunk_slots={chunk_slots!r}: the only supported string "
                "is 'auto'"
            )
        return chunk_slots

    def program(shape, has_exists):
        key = (resolve(shape), has_exists)
        fn = compiled.get(key)
        if fn is None:
            fn = compiled[key] = _compile_band_program(
                mesh, z, key[0], has_exists
            )
        return fn

    def bands(probs, mask, state, now):
        return program(probs.shape, state.exists is not None)(
            probs, mask, state, now
        )

    def lower(probs, mask, state, now):
        return program(probs.shape, state.exists is not None).lower(
            probs, mask, state, now
        )

    bands.lower = lower
    return bands
