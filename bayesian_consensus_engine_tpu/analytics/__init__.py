"""analytics/ — device-resident uncertainty bands and correlated-market
consensus (round 12).

The engine's reference surface emits POINT consensus; this subsystem is
the additive analytics tier above it — per-market credible intervals
(reliability-weighted dispersion, ``analytics/bands.py`` over
``ops/uncertainty.py``) and graph-structured cross-market coupling
(``analytics/graph.py`` over ``ops/propagate.py``) — designed to ride
the SAME resident reliability block the settlement loop already holds:
``ShardedSettlementSession.settle_with_analytics`` runs cycles +
tie-break + bands (+ an optional graph sweep) as ONE compiled program
per chip, and the serving path (``serve/``) exposes it per request via
``ConsensusService(analytics=...)``.

The whole tier is PURE-ADDITIVE by contract: golden fixtures, settle
results, store state, journal epoch payloads, and SQLite bytes are
byte-identical with analytics on or off (the obs on/off contract,
applied to analytics; pinned by tests/test_analytics.py). Layer map:
above ops/parallel (it builds on their kernels and mesh machinery),
below pipeline/serve (which orchestrate it).
"""

from bayesian_consensus_engine_tpu.analytics.bands import (
    AnalyticsOptions,
    build_band_program,
)
from bayesian_consensus_engine_tpu.analytics.graph import MarketGraph
from bayesian_consensus_engine_tpu.ops.propagate import (
    DEFAULT_DAMPING,
    DEFAULT_SWEEP_STEPS,
)
from bayesian_consensus_engine_tpu.ops.uncertainty import (
    DEFAULT_CHUNK_SLOTS,
    UncertaintyBands,
    Z_95,
)

__all__ = [
    "AnalyticsOptions",
    "DEFAULT_CHUNK_SLOTS",
    "DEFAULT_DAMPING",
    "DEFAULT_SWEEP_STEPS",
    "MarketGraph",
    "UncertaintyBands",
    "Z_95",
    "build_band_program",
]
