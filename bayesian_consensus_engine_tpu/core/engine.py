"""Reliability-weighted consensus — scalar reference-semantics path.

Observable behaviour matches the reference kernel
(reference: src/bayesian_engine/core.py:63-180) exactly, including float
summation order, so the golden fixture
(reference: tests/fixtures/golden_regression.json — consensus
0.6966666666666667) reproduces byte-for-byte:

  * duplicate signals from one source are averaged in signal order
  * sources are processed in sorted-id order
  * consensus = Σ(p̄·w)/Σw and confidence = Σ(c·w)/Σw accumulate
    left-to-right over the sorted sources

Unlike the reference's O(unique_sources × signals) re-scan loop
(core.py:108-128), this is a single pass over the signals plus one pass over
the sorted unique sources — same floats, linear time.  The batched TPU path
lives in :mod:`..ops` / :mod:`.batch` and is selected with ``backend=`` —
``"python"`` stays the default so the CLI/golden contract is bit-exact by
construction.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

from bayesian_consensus_engine_tpu.utils.config import (
    DEFAULT_CONFIDENCE,
    DEFAULT_RELIABILITY,
    SCHEMA_VERSION,
)

# Re-exported for API parity with the reference module surface
# (reference: core.py:7-15 exposes SCHEMA_VERSION and ValidationError).
from bayesian_consensus_engine_tpu.core.validate import (  # noqa: F401
    ValidationError,
    validate_input_payload,
)

def compute_consensus(
    signals: Sequence[Mapping[str, Any]],
    source_reliability: Mapping[str, Mapping[str, float]] | None = None,
    backend: str = "python",
) -> dict[str, Any]:
    """Weighted-average consensus over per-source probability signals.

    Args:
        signals: sequence of ``{"sourceId": str, "probability": float}``.
        source_reliability: optional per-source ``{"reliability", "confidence"}``
            mapping; absent sources use cold-start defaults and are listed in
            ``diagnostics.coldStartSources``.
        backend: ``"python"`` (default, bit-exact scalar path) or
            ``"jax"``/``"tpu"`` (batched array path, see ``core.batch``).

    Returns the full v1.0.0 output document (consensus, confidence,
    sourceWeights, normalization, diagnostics).
    """
    if backend not in ("python", "jax", "tpu"):
        raise ValueError(f"unknown backend: {backend!r}")
    if backend != "python" and signals:
        try:
            from bayesian_consensus_engine_tpu.core.batch import compute_consensus_jax
        except ImportError as exc:
            raise NotImplementedError(
                f"backend {backend!r} requires the batched array path "
                "(core.batch), which is not available in this build"
            ) from exc
        return compute_consensus_jax(signals, source_reliability)

    if not signals:
        # Fresh document per call — callers mutate results (CLI dryRun stamp).
        return {
            "schemaVersion": SCHEMA_VERSION,
            "consensus": None,
            "confidence": 0.0,
            "sourceWeights": [],
            "normalization": {"totalWeight": 0.0, "sourceCount": 0},
            "diagnostics": {"status": "no_signals", "sources": 0},
        }

    reliability_map: Mapping[str, Mapping[str, float]] = source_reliability or {}

    # Single pass: bucket probabilities per source in signal order (preserves
    # the reference's duplicate-averaging float order, core.py:115-116).
    by_source: dict[str, list[float]] = {}
    for signal in signals:
        by_source.setdefault(signal["sourceId"], []).append(signal["probability"])

    ordered_ids = sorted(by_source)

    # Float-semantics note: builtin sum() (Neumaier-compensated for floats on
    # CPython ≥3.12) is used exactly where the reference uses it — per-source
    # means (core.py:116) and the two weighted reductions (core.py:135-144) —
    # while totalWeight accumulates naively like the reference's `+=` loop
    # (core.py:120). Mixing these up costs 1 ulp on random inputs.
    per_source: list[tuple[str, float, float, float]] = []
    total_weight = 0.0
    for sid in ordered_ids:
        entry = reliability_map.get(sid, {})
        reliability = entry.get("reliability", DEFAULT_RELIABILITY)
        confidence = entry.get("confidence", DEFAULT_CONFIDENCE)
        probs = by_source[sid]
        mean_prob = sum(probs) / len(probs)
        total_weight += reliability
        per_source.append((sid, mean_prob, reliability, confidence))

    if total_weight == 0:
        consensus: float | None = None
        overall_confidence = 0.0
    else:
        weighted_prob = sum(
            mean_prob * weight for _sid, mean_prob, weight, _conf in per_source
        )
        weighted_conf = sum(
            confidence * weight for _sid, _mean_prob, weight, confidence in per_source
        )
        consensus = weighted_prob / total_weight
        overall_confidence = weighted_conf / total_weight

    source_weights = [
        {
            "sourceId": sid,
            "weight": weight,
            "normalizedWeight": weight / total_weight if total_weight > 0 else 0.0,
        }
        for sid, _mean_prob, weight, _confidence in per_source
    ]

    return {
        "schemaVersion": SCHEMA_VERSION,
        "consensus": consensus,
        "confidence": overall_confidence,
        "sourceWeights": source_weights,
        "normalization": {
            "totalWeight": total_weight,
            "sourceCount": len(ordered_ids),
        },
        "diagnostics": {
            "status": "computed",
            "sources": len(signals),
            "uniqueSources": len(ordered_ids),
            "coldStartSources": [
                sid
                for sid, _mean_prob, _weight, _confidence in per_source
                if sid not in reliability_map
            ],
        },
    }
