"""Input-contract validation for the v1.0.0 payload schema.

Behavioural parity with the reference validator
(reference: src/bayesian_engine/core.py:24-60): same checks, same order,
same error strings — downstream tests assert on the exact messages
(reference: tests/test_core.py:32,43,54).

Deliberately NOT enforced, matching the reference: ``additionalProperties``
(schema-input-v1.0.0.json declares false), ``weightHint``, source-id length
limits and MAX_SIGNALS_PER_REQUEST (declared in config, unenforced).
"""

from __future__ import annotations

from typing import Any, Mapping

from bayesian_consensus_engine_tpu.utils.config import SCHEMA_VERSION


class ValidationError(ValueError):
    """Input payload violates the v1.0.0 contract."""


def _field(payload: Mapping[str, Any], key: str) -> Any:
    try:
        return payload[key]
    except KeyError:
        raise ValidationError(f"{key} is required") from None


def validate_input_payload(payload: Mapping[str, Any]) -> None:
    """Reject payloads that violate the strict v1.0.0 input contract.

    Checks, in order:
      1. ``schemaVersion`` present and exactly "1.0.0"
      2. ``marketId`` present, a non-empty (non-whitespace) string
      3. ``signals`` present and a list
      4. each signal is an object with a non-empty ``sourceId`` string and a
         numeric ``probability`` in [0, 1]
    """
    version = _field(payload, "schemaVersion")
    if version != SCHEMA_VERSION:
        raise ValidationError(
            f"schemaVersion must be '{SCHEMA_VERSION}' (got '{version}')"
        )

    market_id = _field(payload, "marketId")
    if not isinstance(market_id, str) or not market_id.strip():
        raise ValidationError("marketId must be a non-empty string")

    signals = _field(payload, "signals")
    if not isinstance(signals, list):
        raise ValidationError("signals must be an array")

    for idx, signal in enumerate(signals):
        if not isinstance(signal, dict):
            raise ValidationError(f"signals[{idx}] must be an object")

        source_id = _field(signal, "sourceId")
        if not isinstance(source_id, str) or not source_id.strip():
            raise ValidationError(f"signals[{idx}].sourceId must be a non-empty string")

        probability = _field(signal, "probability")
        if not isinstance(probability, (int, float)):
            raise ValidationError(f"signals[{idx}].probability must be a number")
        if probability < 0 or probability > 1:
            raise ValidationError(f"signals[{idx}].probability must be between 0 and 1")
