"""Core consensus layer: validation + scalar engine + batched array engine."""

from bayesian_consensus_engine_tpu.utils.config import SCHEMA_VERSION
from bayesian_consensus_engine_tpu.core.validate import (
    ValidationError,
    validate_input_payload,
)
from bayesian_consensus_engine_tpu.core.engine import compute_consensus

__all__ = [
    "SCHEMA_VERSION",
    "ValidationError",
    "validate_input_payload",
    "compute_consensus",
]
