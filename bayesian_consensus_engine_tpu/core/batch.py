"""Host packing layer + batched array consensus engine.

This is the seam where ragged, string-keyed payloads become dense int32/float
tensors and back (the "ragged → dense" hard part of the TPU design):
(market, source) pairs are assigned rows in **sorted-source order within each
market** so device reductions add floats in the same order the scalar engine
does, and output documents are rehydrated host-side with the exact reference
shape. (Long-lived row assignment for persistent state uses
``utils.interning.IdInterner`` in the tensor store; the per-batch slotting
here is positional by construction.)

Device work is two kernels from ``ops.consensus``:
  1. per-pair duplicate-signal mean          (segment-mean over raw signals)
  2. per-market weighted triple reduction    (Σw, Σp̄w, Σcw → consensus)

The whole batch — any number of markets — is one jit call, replacing the
reference's per-market Python loop + per-(source, market) SQLite query
(reference: market.py:200-221, core.py:108-128).

Dtype: float64 when ``jax.config.x64_enabled`` (parity gate), else float32.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Mapping, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.ops.consensus import (
    pair_mean_from_flat,
    weighted_sums_from_pairs,
)
from bayesian_consensus_engine_tpu.utils.config import (
    DEFAULT_CONFIDENCE,
    DEFAULT_RELIABILITY,
    SCHEMA_VERSION,
)

from bayesian_consensus_engine_tpu.utils.dtypes import default_float_dtype

#: host lookup: (source_id, market_id) → (reliability, confidence, known)
ReliabilityLookup = Callable[[str, str], tuple[float, float, bool]]


def cold_start_lookup(_sid: str, _mid: str) -> tuple[float, float, bool]:
    return DEFAULT_RELIABILITY, DEFAULT_CONFIDENCE, False


def mapping_lookup(
    table: Mapping[str, Mapping[str, float]] | None,
) -> ReliabilityLookup:
    """Adapt the reference's per-source dict (one market) to a lookup.

    Presence semantics match the scalar engine: a key that exists — even with
    missing fields — is NOT cold-start (reference: core.py:110-112,167-170).
    """
    if table is None:
        return cold_start_lookup

    def lookup(sid: str, _mid: str) -> tuple[float, float, bool]:
        entry = table.get(sid)
        if entry is None:
            return DEFAULT_RELIABILITY, DEFAULT_CONFIDENCE, False
        return (
            entry.get("reliability", DEFAULT_RELIABILITY),
            entry.get("confidence", DEFAULT_CONFIDENCE),
            True,
        )

    return lookup


@dataclass(frozen=True)
class PackedBatch:
    """Dense packing of M markets' signals, ready for device kernels.

    Pairs are ordered (market row asc, source id asc) — the float-summation
    order of the scalar engine. ``pair_slice(m)`` gives market *m*'s pairs.
    """

    market_keys: list[str]            # row → market id
    pair_market: np.ndarray           # i32[P]
    pair_source_ids: list[str]        # row → source id (sorted within market)
    pair_reliability: np.ndarray      # f[P]
    pair_confidence: np.ndarray       # f[P]
    pair_known: np.ndarray            # bool[P] — False ⇒ coldStartSources
    flat_probs: np.ndarray            # f[N] raw signal probabilities
    flat_pair: np.ndarray             # i32[N]
    signals_per_market: np.ndarray    # i32[M] raw signal counts (diagnostics)
    pair_offsets: np.ndarray          # i32[M+1] pair range per market

    @property
    def num_markets(self) -> int:
        return len(self.market_keys)

    @property
    def num_pairs(self) -> int:
        return len(self.pair_source_ids)

    def pair_slice(self, market_row: int) -> slice:
        return slice(
            int(self.pair_offsets[market_row]), int(self.pair_offsets[market_row + 1])
        )


class SourceCodes:
    """Zero-copy per-signal source column: int32 codes + code → id table.

    The buffer-protocol intake for callers that never hold one Python
    string per signal — a feed that already tables its source ids (the
    streamed service's steady state) passes ``SourceCodes(codes, table)``
    anywhere a per-signal ``source_ids`` sequence is accepted
    (:func:`topology_fingerprint`, :func:`group_columns`,
    :func:`~.pipeline.build_settlement_plan_columnar`) and the whole
    ingest path runs without materialising a per-signal object: codes
    flow straight into the native grouping pass, and the fingerprint's
    joined-id bytes come from one C concat over the table.

    ``codes[i]`` indexes ``table``; the table must be unique (two codes
    mapping to one id would alias a pair) — validated here, once, O(U).
    Equivalent string and coded columns produce byte-identical plans and
    fingerprints (pinned by tests/test_fastpack.py).
    """

    __slots__ = ("codes", "table")

    def __init__(self, codes, table: Sequence[str]) -> None:
        self.codes = np.ascontiguousarray(codes, dtype=np.int32)
        self.table = list(table)
        if len(set(self.table)) != len(self.table):
            raise ValueError("SourceCodes table entries must be unique")
        if len(self.codes) and len(self.table) == 0:
            raise ValueError("SourceCodes has signals but an empty table")
        if len(self.codes) and (
            int(self.codes.min()) < 0
            or int(self.codes.max()) >= len(self.table)
        ):
            # Checked HERE, not just at plan build: the fingerprint path
            # indexes the table with these codes, and a negative code
            # would WRAP (Python/numpy negative indexing) into a silently
            # aliased digest — a wrong-topology plan-reuse hit.
            raise ValueError("SourceCodes codes out of table range")

    def __len__(self) -> int:
        return len(self.codes)


def encode_source_ids(source_ids: Sequence[str]) -> SourceCodes:
    """Strings → :class:`SourceCodes` (one interning pass, C when built).

    The bridge for callers that want to pay the per-signal string walk
    ONCE and then re-submit coded columns every batch (ids in a steady
    feed repeat heavily; re-encoding only new ids is the caller's
    amortisation to claim).
    """
    codes, table = _intern_source_codes(source_ids)
    return SourceCodes(codes, table)


def _intern_source_codes(source_ids):
    """Strings → first-seen int32 codes + unique table, C pass when built."""
    from bayesian_consensus_engine_tpu.utils.interning import (
        IdInterner,
        _load_internmap,
    )

    module = _load_internmap()
    if module is not None:
        table = module.InternMap()
        # The C pass accepts any sequence — don't copy 4M refs when the
        # caller already holds a list/tuple.
        if not isinstance(source_ids, (list, tuple)):
            source_ids = list(source_ids)
        codes = np.frombuffer(
            table.intern_batch(source_ids), dtype=np.int32
        )
        return codes, table.ids()
    interner = IdInterner()
    codes = np.asarray(interner.intern_all(source_ids), dtype=np.int32)
    return codes, interner.ids()


def topology_fingerprint(
    market_keys: Sequence[str],
    source_ids: "Sequence[str] | SourceCodes",
    offsets,
) -> bytes:
    """Order-sensitive fingerprint of a batch's SIGNAL TOPOLOGY.

    The topology is everything about a batch except the probabilities and
    outcomes: the market ids (in payload order), the raw per-signal source
    ids (markets back to back, original signal order), and the CSR
    *offsets* delimiting each market's signals. Two batches with equal
    fingerprints produce byte-identical settlement plans up to the
    probability columns — the invariant the plan-reuse fast path rests on
    (:meth:`~.pipeline.SettlementPlan.refresh`). Any reordering of
    markets, sources, or duplicate signals changes the digest: per-market
    source ordering and duplicate-averaging order are both
    float-summation-order contracts, so a reordered batch MUST rebuild.

    The encoding is injective (ids are length-delimited, counts are part
    of the digest), so distinct topologies collide only with blake2b
    itself (~2^-64 at any realistic stream length). One join + one hash
    pass over the columns: ~10ms per million signals, paid on the
    prefetch thread.

    *source_ids* may be a :class:`SourceCodes` column: the digest is
    IDENTICAL to the one the decoded string column would produce (the
    per-signal code-point lengths come from a table-lengths take, the
    joined UTF-8 from one C concat over codes), so string and coded
    feeds interoperate in one plan-reuse chain.
    """
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    digest = hashlib.blake2b(digest_size=16)
    digest.update(
        np.asarray([len(market_keys), len(source_ids)], np.int64).tobytes()
    )
    digest.update(
        np.fromiter(map(len, market_keys), np.int64, len(market_keys))
        .tobytes()
    )
    digest.update("".join(market_keys).encode("utf-8"))
    if isinstance(source_ids, SourceCodes):
        table_lens = np.fromiter(
            map(len, source_ids.table), np.int64, len(source_ids.table)
        )
        if len(source_ids):
            digest.update(table_lens[source_ids.codes].tobytes())
        if (
            _fastpack is not None
            and hasattr(_fastpack, "join_codes")
            and not native_disabled()
        ):
            digest.update(
                _fastpack.join_codes(source_ids.codes, source_ids.table)
            )
        else:
            table = source_ids.table
            digest.update(
                "".join(
                    [table[c] for c in source_ids.codes.tolist()]
                ).encode("utf-8")
            )
    else:
        digest.update(
            np.fromiter(map(len, source_ids), np.int64, len(source_ids))
            .tobytes()
        )
        digest.update("".join(source_ids).encode("utf-8"))
    digest.update(offsets.tobytes())
    return digest.digest()


def pair_fingerprint(
    market_keys: Sequence[str],
    sid_of_rank: Sequence[str],
    pair_market,
    pair_rank,
    pair_offsets,
) -> bytes:
    """Order-sensitive fingerprint of a batch's RESOLVED PAIR SET.

    The second link of the plan-reuse fingerprint chain (round 15): where
    :func:`topology_fingerprint` digests the raw signal columns
    (duplicates and all), this digests exactly what pair interning
    consumes — the market table in payload order, the code-point-sorted
    source table, and the grouped (market, source-rank) pair list with
    its CSR offsets. Equal digests ⇒ the identical ordered pair list ⇒
    the previous epoch's resolved rows apply verbatim (the
    epoch-persistent pair table's O(1) tier in
    :class:`~.state.tensor_store.TensorReliabilityStore`). A batch whose
    signals changed but whose pair set did not — reordered duplicates,
    different per-pair signal counts — misses the topology digest yet
    hits here, paying zero interning.

    Same injectivity posture as the topology digest: ids are
    length-delimited, table sizes are part of the digest, and the pair
    arrays are hashed as raw little-endian bytes, so distinct pair sets
    collide only with blake2b itself.
    """
    pair_market = np.ascontiguousarray(pair_market, dtype=np.int32)
    pair_rank = np.ascontiguousarray(pair_rank, dtype=np.int32)
    pair_offsets = np.ascontiguousarray(pair_offsets, dtype=np.int64)
    digest = hashlib.blake2b(digest_size=16)
    digest.update(
        np.asarray(
            [len(market_keys), len(sid_of_rank), len(pair_market)], np.int64
        ).tobytes()
    )
    digest.update(
        np.fromiter(map(len, market_keys), np.int64, len(market_keys))
        .tobytes()
    )
    digest.update("".join(market_keys).encode("utf-8"))
    digest.update(
        np.fromiter(map(len, sid_of_rank), np.int64, len(sid_of_rank))
        .tobytes()
    )
    digest.update("".join(sid_of_rank).encode("utf-8"))
    digest.update(pair_market.tobytes())
    digest.update(pair_rank.tobytes())
    digest.update(pair_offsets.tobytes())
    return digest.digest()


def columns_from_payloads(payloads, native: "bool | None" = None):
    """Flatten dict payloads to ``(market_keys, source_ids, probs, offsets)``.

    The light single pass the delta-ingest path runs INSTEAD of packing:
    no grouping, no sorting, no interning — just the raw columns in
    original signal order, i.e. exactly the columnar form
    :func:`~.pipeline.build_settlement_plan_columnar` consumes and
    :func:`topology_fingerprint` hashes. Runs as one C pass when the
    native extension is built (``native=None`` auto-detects; the pure-
    Python twin below produces identical values either way), so the
    prefetch thread's dict → columns conversion never walks per-signal
    Python bytecode.
    """
    use_native = _columnar_native_available() if native is None else native
    if use_native:
        if _fastpack is None or not hasattr(
            _fastpack, "columns_from_payloads"
        ):
            raise RuntimeError(
                "native packer requested but not built; "
                "run python native/build.py"
            )
        if not isinstance(payloads, (list, tuple)):
            payloads = list(payloads)
        keys, source_ids, probs_buf, offs_buf = (
            _fastpack.columns_from_payloads(payloads)
        )
        return (
            keys,
            source_ids,
            np.frombuffer(probs_buf, dtype=np.float64),
            np.frombuffer(offs_buf, dtype=np.int64),
        )
    market_keys: list[str] = []
    source_ids: list[str] = []
    probs: list[float] = []
    offsets: list[int] = [0]
    for market_id, signals in payloads:
        market_keys.append(market_id)
        for signal in signals:
            source_ids.append(signal["sourceId"])
            probs.append(signal["probability"])
        offsets.append(len(source_ids))
    return (
        market_keys,
        source_ids,
        np.asarray(probs, dtype=np.float64),
        np.asarray(offsets, dtype=np.int64),
    )


from bayesian_consensus_engine_tpu.utils.interning import native_disabled

try:  # native ingest packer (see native/fastpack.c; build with native/build.py)
    # ``BCE_NO_NATIVE=1`` forces every native ingest fast path (fastpack
    # AND the internmap interner) down to its pure-Python twin — the CI
    # lane that keeps the twins from rotting (tests/test_fastpack.py
    # runs a parity matrix under it in a subprocess). Gated at import
    # AND re-consulted by every auto-detection below, so a runtime env
    # change flips the whole stack, never a half-native hybrid.
    if native_disabled():
        raise ImportError("BCE_NO_NATIVE forces the pure-Python packer")
    from bayesian_consensus_engine_tpu._native import fastpack as _fastpack
except ImportError:  # pure-Python fallback below — identical outputs
    _fastpack = None


def _object_native_available() -> bool:
    """Auto-detection for the object packer: extension built AND the
    forced-fallback knob unset (explicit ``native=True`` bypasses this)."""
    return _fastpack is not None and not native_disabled()


def _columnar_native_available() -> bool:
    """True when the built extension carries the columnar fast path (an
    older ``fastpack.so`` predating it degrades to the numpy twins
    instead of erroring) and the forced-fallback knob is unset."""
    return (
        _fastpack is not None
        and hasattr(_fastpack, "group_columns")
        and not native_disabled()
    )


def group_columns(
    codes: np.ndarray,
    rank_of_code: np.ndarray,
    offsets: np.ndarray,
    probabilities: np.ndarray,
    native: "bool | None" = None,
):
    """The columnar grouping pass: coded signals → ordered pair arrays.

    Given per-signal int32 source *codes*, the code → code-point-rank
    permutation, int64 CSR *offsets* (market ``m``'s signals are
    ``[offsets[m], offsets[m+1])``) and float64 *probabilities*, returns

    ``(signal_pairs i64[N], pair_market i32[P], pair_rank i32[P],
    pair_offsets i64[M+1], pair_sums f64[P], pair_counts i64[P])``

    with pairs ordered market-major, rank ascending within each market
    (the scalar engine's float-summation order) and per-pair sums
    accumulated in original signal order (the duplicate-averaging
    contract). ``native=None`` auto-detects the C pass
    (``fastpack.group_columns``, emitting into preallocated buffers);
    ``False`` forces the numpy twin; both produce identical arrays
    bit-for-bit (pinned by tests/test_fastpack.py).
    """
    use_native = _columnar_native_available() if native is None else native
    num_markets = len(offsets) - 1
    if use_native:
        if not _columnar_native_available():
            raise RuntimeError(
                "native packer requested but not built; "
                "run python native/build.py"
            )
        n = len(codes)
        signal_pairs = np.empty(n, dtype=np.int64)
        pair_market = np.empty(n, dtype=np.int32)
        pair_rank = np.empty(n, dtype=np.int32)
        pair_offsets = np.empty(num_markets + 1, dtype=np.int64)
        sums = np.empty(n, dtype=np.float64)
        counts = np.empty(n, dtype=np.int64)
        num_pairs = _fastpack.group_columns(
            np.ascontiguousarray(codes, dtype=np.int32),
            np.ascontiguousarray(rank_of_code, dtype=np.int32),
            np.ascontiguousarray(offsets, dtype=np.int64),
            np.ascontiguousarray(probabilities, dtype=np.float64),
            signal_pairs, pair_market, pair_rank, pair_offsets, sums, counts,
        )
        return (
            signal_pairs,
            pair_market[:num_pairs],
            pair_rank[:num_pairs],
            pair_offsets,
            sums[:num_pairs],
            counts[:num_pairs],
        )

    # Numpy twin. Composite (market, source-rank) key: its sorted-unique
    # sequence IS the pair list in the scalar engine's order.
    codes = np.asarray(codes)
    if len(codes) and int(codes.min()) < 0:
        # Negative-index wrapping would return a plausible-but-wrong
        # grouping where the C pass raises; the twins must error alike.
        raise IndexError("source codes must be non-negative")
    num_uniq = len(rank_of_code)
    market_of_signal = np.repeat(
        np.arange(num_markets, dtype=np.int64), np.diff(offsets)
    )
    stride = max(num_uniq, 1)
    key = market_of_signal * stride + np.asarray(
        rank_of_code, dtype=np.int64
    )[codes]
    uniq_keys, signal_pairs = np.unique(key, return_inverse=True)
    pair_market = (uniq_keys // stride).astype(np.int32)
    pair_rank = (uniq_keys % stride).astype(np.int32)
    pair_offsets = np.searchsorted(
        pair_market, np.arange(num_markets + 1)
    ).astype(np.int64)
    num_pairs = len(uniq_keys)
    # np.add.at accumulates in signal order — the scalar path's
    # left-to-right duplicate sum per pair.
    sums = np.zeros(num_pairs, dtype=np.float64)
    np.add.at(sums, signal_pairs, probabilities)
    counts = np.bincount(signal_pairs, minlength=num_pairs)
    return (
        signal_pairs, pair_market, pair_rank, pair_offsets, sums, counts,
    )


def pair_accumulate(
    pair_idx: np.ndarray,
    probabilities: np.ndarray,
    num_pairs: int,
    native: "bool | None" = None,
) -> np.ndarray:
    """Ordered per-pair probability sums — the refresh twin's inner pass.

    ``sums[pair_idx[i]] += probabilities[i]`` in signal order (the float
    contract of the scalar engine's left-to-right duplicate sum). The C
    pass and the ``np.add.at`` twin are bit-identical; ``native=None``
    auto-detects.
    """
    use_native = (
        _fastpack is not None
        and hasattr(_fastpack, "pair_accumulate")
        and not native_disabled()
        if native is None
        else native
    )
    sums = np.zeros(num_pairs, dtype=np.float64)
    if use_native:
        if _fastpack is None or not hasattr(_fastpack, "pair_accumulate"):
            raise RuntimeError(
                "native packer requested but not built; "
                "run python native/build.py"
            )
        _fastpack.pair_accumulate(
            np.ascontiguousarray(pair_idx),
            np.ascontiguousarray(probabilities, dtype=np.float64),
            sums,
        )
    else:
        pair_idx = np.asarray(pair_idx)
        if len(pair_idx) and int(pair_idx.min()) < 0:
            # np.add.at wraps negative indices; the C pass raises — the
            # twins must error alike.
            raise IndexError("pair indices must be non-negative")
        np.add.at(sums, pair_idx, probabilities)
    return sums


def _pack_grouping_python(markets):
    """Pure-Python twin of native/fastpack.c's grouping pass."""
    pair_market: list[int] = []
    pair_source_ids: list[str] = []
    flat_probs: list[float] = []
    flat_pair: list[int] = []
    signals_per_market: list[int] = []
    pair_offsets: list[int] = [0]

    for market_row, (_market_id, signals) in enumerate(markets):
        signals_per_market.append(len(signals))
        seen: dict[str, None] = {}
        for signal in signals:
            seen[signal["sourceId"]] = None

        base = len(pair_source_ids)
        ordered = sorted(seen)
        slot_of = {sid: base + i for i, sid in enumerate(ordered)}
        pair_market.extend([market_row] * len(ordered))
        pair_source_ids.extend(ordered)

        # Raw signals in original order → duplicate averaging keeps the
        # scalar path's left-to-right accumulation order per pair.
        for signal in signals:
            flat_probs.append(signal["probability"])
            flat_pair.append(slot_of[signal["sourceId"]])

        pair_offsets.append(len(pair_source_ids))

    return (
        pair_market, pair_source_ids, flat_probs, flat_pair,
        signals_per_market, pair_offsets,
    )


def pack_markets(
    markets: Sequence[tuple[str, Sequence[Mapping[str, Any]]]],
    lookup: ReliabilityLookup = cold_start_lookup,
    native: bool | None = None,
) -> PackedBatch:
    """Intern, sort, and flatten raw (market_id, signals) payloads.

    The grouping/flattening pass runs in the C extension when built
    (``native=None`` auto-detects; True forces it, False forces the Python
    twin — both produce identical outputs). The reliability ``lookup`` is a
    user callable and always runs in Python, once per unique pair.
    """
    use_native = _object_native_available() if native is None else native
    if use_native and _fastpack is None:
        raise RuntimeError(
            "native packer requested but not built; run python native/build.py"
        )

    markets = list(markets)  # consumed twice (grouping pass + key/lookup pass)
    grouping = (_fastpack.pack if use_native else _pack_grouping_python)(markets)
    (pair_market, pair_source_ids, flat_probs, flat_pair,
     signals_per_market, pair_offsets) = grouping

    market_keys = [market_id for market_id, _signals in markets]
    num_pairs = len(pair_source_ids)
    if lookup is cold_start_lookup:
        # Constant output — skip the per-pair Python call loop (the
        # settlement pipeline packs hundreds of thousands of pairs and reads
        # state from device rows instead, never from these arrays).
        pair_rel = np.full(num_pairs, DEFAULT_RELIABILITY)
        pair_conf = np.full(num_pairs, DEFAULT_CONFIDENCE)
        pair_known = [False] * num_pairs
    else:
        pair_rel = []
        pair_conf = []
        pair_known = []
        for sid, market_row in zip(pair_source_ids, pair_market):
            reliability, confidence, known = lookup(sid, market_keys[market_row])
            pair_rel.append(reliability)
            pair_conf.append(confidence)
            pair_known.append(known)

    dtype = np.float64  # host packing always f64; cast on device transfer
    return PackedBatch(
        market_keys=market_keys,
        pair_market=np.asarray(pair_market, dtype=np.int32),
        pair_source_ids=list(pair_source_ids),
        pair_reliability=np.asarray(pair_rel, dtype=dtype),
        pair_confidence=np.asarray(pair_conf, dtype=dtype),
        pair_known=np.asarray(pair_known, dtype=bool),
        flat_probs=np.asarray(flat_probs, dtype=dtype),
        flat_pair=np.asarray(flat_pair, dtype=np.int32),
        signals_per_market=np.asarray(signals_per_market, dtype=np.int32),
        pair_offsets=np.asarray(pair_offsets, dtype=np.int32),
    )


@partial(jax.jit, static_argnames=("num_pairs", "num_markets"))
def _batch_kernel(
    flat_probs: jax.Array,
    flat_pair: jax.Array,
    pair_reliability: jax.Array,
    pair_confidence: jax.Array,
    pair_market: jax.Array,
    num_pairs: int,
    num_markets: int,
):
    """One fused device pass: dedupe-mean → weighted per-market reductions.

    Returns the three per-market sums; the two normalization divides happen
    host-side in the formatter so golden byte-parity survives XLA's
    divide→reciprocal-multiply rewrite.
    """
    pair_mean = pair_mean_from_flat(flat_probs, flat_pair, num_pairs)
    total_weight, weighted_prob, weighted_conf = weighted_sums_from_pairs(
        pair_mean, pair_reliability, pair_confidence, pair_market, num_markets
    )
    return pair_mean, total_weight, weighted_prob, weighted_conf


def _next_bucket(n: int) -> int:
    """Round up to a power of two (min 8) so jit compilations are reused
    across batches instead of recompiling per exact shape."""
    size = 8
    while size < n:
        size *= 2
    return size


def run_packed(batch: PackedBatch):
    """Execute the batch kernel; returns host numpy results.

    Inputs are padded to power-of-two buckets: padding signals/pairs are
    routed to a dummy trailing market row with zero reliability, so they
    cannot perturb real outputs, and results are sliced back to true sizes.
    """
    dtype = default_float_dtype()
    num_markets = batch.num_markets
    num_pairs = batch.num_pairs
    markets_pad = _next_bucket(num_markets + 1)   # +1 dummy sink market
    pairs_pad = _next_bucket(num_pairs + 1)       # +1 dummy sink pair
    flat_pad = _next_bucket(len(batch.flat_probs))

    def pad(array: np.ndarray, size: int, fill) -> np.ndarray:
        out = np.full(size, fill, dtype=array.dtype)
        out[: len(array)] = array
        return out

    pair_mean, total_weight, weighted_prob, weighted_conf = _batch_kernel(
        jnp.asarray(pad(batch.flat_probs, flat_pad, 0.0), dtype=dtype),
        jnp.asarray(pad(batch.flat_pair, flat_pad, pairs_pad - 1)),
        jnp.asarray(pad(batch.pair_reliability, pairs_pad, 0.0), dtype=dtype),
        jnp.asarray(pad(batch.pair_confidence, pairs_pad, 0.0), dtype=dtype),
        jnp.asarray(pad(batch.pair_market, pairs_pad, markets_pad - 1)),
        num_pairs=pairs_pad,
        num_markets=markets_pad,
    )
    return (
        np.asarray(pair_mean)[:num_pairs],
        np.asarray(total_weight)[:num_markets],
        np.asarray(weighted_prob)[:num_markets],
        np.asarray(weighted_conf)[:num_markets],
    )


def _format_document(
    batch: PackedBatch,
    market_row: int,
    total_weight: np.ndarray,
    weighted_prob: np.ndarray,
    weighted_conf: np.ndarray,
) -> dict[str, Any]:
    """Rehydrate one market's reference-shaped output document.

    The two normalization divides happen here, on host Python floats, so the
    document matches the scalar engine bit-for-bit when the device sums do.
    """
    pairs = batch.pair_slice(market_row)
    tw = float(total_weight[market_row])
    has_weight = tw != 0  # scalar parity: reference tests == 0 (core.py:131)

    source_weights = []
    cold_sources = []
    for p in range(pairs.start, pairs.stop):
        weight = float(batch.pair_reliability[p])
        source_weights.append(
            {
                "sourceId": batch.pair_source_ids[p],
                "weight": weight,
                "normalizedWeight": weight / tw if has_weight else 0.0,
            }
        )
        if not batch.pair_known[p]:
            cold_sources.append(batch.pair_source_ids[p])

    return {
        "schemaVersion": SCHEMA_VERSION,
        "consensus": float(weighted_prob[market_row]) / tw if has_weight else None,
        "confidence": float(weighted_conf[market_row]) / tw if has_weight else 0.0,
        "sourceWeights": source_weights,
        "normalization": {
            "totalWeight": tw,
            "sourceCount": pairs.stop - pairs.start,
        },
        "diagnostics": {
            "status": "computed",
            "sources": int(batch.signals_per_market[market_row]),
            "uniqueSources": pairs.stop - pairs.start,
            "coldStartSources": cold_sources,
        },
    }


def compute_batch_consensus(
    markets: Sequence[tuple[str, Sequence[Mapping[str, Any]]]],
    lookup: ReliabilityLookup = cold_start_lookup,
) -> dict[str, dict[str, Any]]:
    """Batched consensus over many markets in one device pass.

    Returns ``{market_id: output_document}`` with each document in the
    reference shape (stamped with ``marketId``, like
    ``Market.compute_consensus`` — reference: market.py:112-128). Markets
    with no signals get the reduced 4-key document (reference quirk #8).
    """
    live = [(mid, sigs) for mid, sigs in markets if sigs]
    results: dict[str, dict[str, Any]] = {}

    if live:
        batch = pack_markets(live, lookup)
        _pair_mean, total_weight, weighted_prob, weighted_conf = run_packed(batch)
        for row, market_id in enumerate(batch.market_keys):
            document = _format_document(
                batch, row, total_weight, weighted_prob, weighted_conf
            )
            document["marketId"] = market_id
            results[market_id] = document

    for market_id, signals in markets:
        if not signals:
            results[market_id] = {
                "schemaVersion": SCHEMA_VERSION,
                "consensus": None,
                "confidence": 0.0,
                "marketId": market_id,
            }
    return results


def store_lookup(store) -> ReliabilityLookup:
    """Adapt a ``ReliabilityStore`` to a packing lookup (decay-on-read).

    ``known`` is always True, matching the scalar market sweep: the reference
    builds a reliability dict entry for every signalling source, so its sweep
    never reports coldStartSources (reference: market.py:208-219).
    """

    def lookup(sid: str, mid: str) -> tuple[float, float, bool]:
        record = store.get_reliability(sid, mid, apply_decay=True)
        return record.reliability, record.confidence, True

    return lookup


def compute_all_consensus_batched(
    market_store,
    reliability_store=None,
) -> dict[str, dict[str, Any]]:
    """Batched twin of ``MarketStore.compute_all_consensus``.

    One device pass over every OPEN market instead of the reference's
    market-by-market Python loop (reference: market.py:200-221). Results are
    cached on each ``Market`` (``consensus_result``) exactly like the scalar
    sweep.
    """
    # `MarketStatus` is a str-Enum, so the wire value "open" IS the status
    # contract — comparing against it keeps core/ below models/ in the
    # layer map (lint rule LY301) without an upward import.
    open_markets = [
        m for m in market_store.list_markets() if m.status == "open"
    ]
    payload = [(str(m.id), m.signals) for m in open_markets]
    lookup = (
        store_lookup(reliability_store)
        if reliability_store is not None
        else cold_start_lookup
    )
    results = compute_batch_consensus(payload, lookup)
    for market in open_markets:
        if market.signals:
            market.consensus_result = results[str(market.id)]
    return results


def compute_consensus_jax(
    signals: Sequence[Mapping[str, Any]],
    source_reliability: Mapping[str, Mapping[str, float]] | None = None,
) -> dict[str, Any]:
    """Single-market array-path twin of ``engine.compute_consensus``.

    Same output shape and key order; float values come from the device
    kernels (float64 under x64, float32 otherwise). The scalar path remains
    the byte-exact contract; this path is property-tested against it.
    """
    batch = pack_markets([("_", signals)], mapping_lookup(source_reliability))
    _pair_mean, total_weight, weighted_prob, weighted_conf = run_packed(batch)
    return _format_document(batch, 0, total_weight, weighted_prob, weighted_conf)
