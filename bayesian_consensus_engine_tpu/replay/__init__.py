"""Counterfactual replay lab: batched journal replay at device speed.

See :mod:`~.replay.lab` for the full story. Surface:

* :func:`replay_sweep` — K altered configs through one vmapped
  settlement program per recorded batch; lane 0 is the recorded config,
  re-driven authoritatively (byte contract witness).
* :func:`replay_single` — one config, full staging paid per call (the
  sequential baseline the sweep's ≥6× acceptance measures against).
* :func:`load_trace` / :func:`load_cluster_trace` /
  :func:`trace_from_batches` — workload sources: a journal's trace
  sidecar, a fleet's merged band sidecars, a serving front end's
  ``record_batches`` log.
* :class:`ReplayConfig` / :data:`RECORDED_CONFIG`, :class:`LaneReport`,
  :class:`SweepResult`.
"""

from bayesian_consensus_engine_tpu.replay.lab import (
    RECORDED_CONFIG,
    LaneReport,
    ReplayConfig,
    SweepResult,
    load_cluster_trace,
    load_trace,
    replay_single,
    replay_sweep,
    trace_from_batches,
)

__all__ = [
    "RECORDED_CONFIG",
    "LaneReport",
    "ReplayConfig",
    "SweepResult",
    "load_cluster_trace",
    "load_trace",
    "replay_single",
    "replay_sweep",
    "trace_from_batches",
]
