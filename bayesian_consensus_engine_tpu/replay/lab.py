"""The counterfactual replay lab — the journal as a time machine.

A recorded run leaves two artefacts: the journal (OUTPUT deltas — the
durability tier) and its trace sidecar (INPUTS — the columnar payload
columns, outcomes, settlement days, step count per admitted batch;
:class:`~.state.journal.TraceWriter`, recorded by ``settle_stream``'s
``trace=`` or rebuilt from a serving front end's ``record_batches``
batch log). This module re-drives that workload under K altered
parameter configs at device speed:

* **Lane 0 is authoritative.** The recorded config re-drives through
  the REAL settle machinery (:func:`~.serve.driver.drive_trace`'s loop
  body: :class:`~.serve.driver.PlanCache` stage/bind in admission order,
  one :class:`~.serve.driver.SessionDriver`, flat or sharded-resident),
  so "lane 0 reproduces the live run byte-for-byte" (store digest +
  SQLite bytes) is structural — the live loop over the live inputs —
  not a parallel implementation kept honest.
* **Lanes ride one program.** All K configs advance through ONE vmapped
  settlement step per batch
  (:func:`~.parallel.sharded.build_replay_sweep_step`): the flat
  gather → N-cycle loop → scatter program with the plan arrays
  broadcast and the cycle's tunable scalars per-lane. Plan build,
  interning, and the plan's host→device upload are paid ONCE for all K
  lanes (and shared with lane 0's authoritative dispatch via the plan's
  device-array cache) — the ≥6×-over-sequential contract the
  ``e2e_replay_sweep`` bench leg pins.

Determinism: a sweep's :class:`SweepResult` carries no timing and is a
pure function of (trace, config set) — run twice, byte-identical
(``result_digest``). ``confidence_growth`` is deliberately NOT a sweep
dimension: the settled-confidence trajectory is data-independent and
host-replayed in exact arithmetic (:func:`~.pipeline._replay_confidences`),
so sweeping it device-side would diverge from what any live run could
produce.

Layer 7 (lint LY301, alongside ``serve``): may import pipeline/serve
and everything below, never ``cli``.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from bayesian_consensus_engine_tpu.obs.metrics import metrics_registry
from bayesian_consensus_engine_tpu.obs.timeline import active_timeline
from bayesian_consensus_engine_tpu.ops.propagate import DEFAULT_DAMPING
from bayesian_consensus_engine_tpu.ops.uncertainty import Z_95
from bayesian_consensus_engine_tpu.state.journal import TraceBatch
from bayesian_consensus_engine_tpu.utils.config import (
    BASE_LEARNING_RATE,
    DECAY_HALF_LIFE_DAYS,
    DECAY_MINIMUM,
    DEFAULT_CONFIDENCE,
    DEFAULT_RELIABILITY,
    MAX_UPDATE_STEP,
)


class ReplayConfig(NamedTuple):
    """One counterfactual parameter point — a lane of the sweep.

    Defaults are the RECORDED constants, so ``ReplayConfig()`` is the
    live run's config (:data:`RECORDED_CONFIG`). ``graph_steps=0``
    disables the correlated-market relaxation for that lane (its
    ``graph_brier`` then equals its plain ``brier``); any lane with
    ``graph_steps > 0`` makes the sweep require a
    :class:`~.analytics.graph.MarketGraph`. ``graph_tol > 0`` arms the
    round-18 adaptive early-exit for that lane: iterations freeze once
    the relaxation's ``max |Δvalue|`` residual drops to the tolerance,
    inside the same static ``graph_steps`` bound — so ``bce-tpu
    replay`` can counterfactually tune the inference depth/tolerance
    trade-off over a recorded trace.
    """

    half_life_days: float = DECAY_HALF_LIFE_DAYS
    decay_floor: float = DECAY_MINIMUM
    base_learning_rate: float = BASE_LEARNING_RATE
    max_update_step: float = MAX_UPDATE_STEP
    band_z: float = Z_95
    graph_damping: float = DEFAULT_DAMPING
    graph_steps: int = 0
    graph_tol: float = 0.0


#: The live run's parameter point — always lane 0 of a sweep.
RECORDED_CONFIG = ReplayConfig()


class LaneReport(NamedTuple):
    """One lane's accumulated sweep metrics (sums over all batches).

    ``markets_settled`` counts market-settlements with nonzero consensus
    weight (a market re-settling in later batches counts each time);
    ``brier_sum`` is Σ(consensus − outcome)² over those, ``band_width_sum``
    the Σ of two-sided ``2·z·stderr`` credible widths over the pre-update
    read (the same weights the live analytics band), and
    ``graph_brier_sum`` the Brier after the lane's damped graph
    relaxation (equal to ``brier_sum`` for ``graph_steps=0`` lanes).
    Means divide by ``markets_settled`` (``nan`` when nothing settled).
    """

    config: ReplayConfig
    markets_settled: int
    brier_sum: float
    band_width_sum: float
    graph_brier_sum: float

    @property
    def brier_mean(self) -> float:
        return (
            self.brier_sum / self.markets_settled
            if self.markets_settled else float("nan")
        )

    @property
    def band_width_mean(self) -> float:
        return (
            self.band_width_sum / self.markets_settled
            if self.markets_settled else float("nan")
        )

    @property
    def graph_brier_mean(self) -> float:
        return (
            self.graph_brier_sum / self.markets_settled
            if self.markets_settled else float("nan")
        )


@dataclass(frozen=True)
class SweepResult:
    """A finished sweep: per-lane reports + the authoritative rebuild.

    ``lanes[0]`` is always :data:`RECORDED_CONFIG`. ``store`` and
    ``digest`` are the lane-0 authoritative rebuild (the byte contract's
    witness) — ``None`` when the sweep ran with ``rebuild=False``.
    ``lane_state`` holds the final stacked flat columns
    ``(reliability, confidence, updated_days, exists)``, each
    ``(K, rows)`` — lane k's row r is what the store's row r would hold
    had the run used config k (stamps relative to ``epoch0``).
    ``result_digest`` hashes (configs, raw metric bytes, lane-0 digest):
    equal digests ⇔ the same sweep happened, which is the run-twice
    determinism pin.
    """

    lanes: Tuple[LaneReport, ...]
    batches: int
    epoch0: float
    lane_state: Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]
    result_digest: str
    store: object = None
    digest: Optional[str] = None

    def by_config(self) -> "dict[ReplayConfig, LaneReport]":
        return {lane.config: lane for lane in self.lanes}


def load_trace(journal_path, strict: bool = False) -> List[TraceBatch]:
    """The replayable workload of one recorded journal.

    :func:`~.state.journal.extract_trace` bounded by the journal's
    durable tag: a journal cut mid-frame replays to its last joined
    epoch; ``strict=True`` refuses (:class:`~.state.journal.
    TornTraceError`) instead of silently shortening.
    """
    from bayesian_consensus_engine_tpu.state.journal import extract_trace

    batches, _tag = extract_trace(str(journal_path), strict=strict)
    return batches


def load_cluster_trace(paths, strict: bool = False) -> List[TraceBatch]:
    """The merged replayable workload of N fleet band journals
    (:func:`~.cluster.recover.extract_cluster_trace`)."""
    from bayesian_consensus_engine_tpu.cluster.recover import (
        extract_cluster_trace,
    )

    batches, _tags = extract_cluster_trace(paths, strict=strict)
    return batches


def trace_from_batches(
    batch_log: Sequence,
    now: float,
    steps: int = 1,
) -> List[TraceBatch]:
    """A serving front end's ``record_batches`` log as a trace.

    *batch_log* entries are ``((market_keys, source_ids, probabilities,
    offsets), outcomes)`` — :attr:`~.serve.coalesce.ConsensusService.
    batch_log`'s shape, which is also ``settle_stream``'s columnar batch
    shape. *now* is the first batch's settlement day, advancing one day
    per batch (the stream's ``now=float`` cadence — services recording
    for replay should drive an explicit day schedule).
    """
    batches: List[TraceBatch] = []
    for index, (payload, outcomes) in enumerate(batch_log):
        market_keys, source_ids, probabilities, offsets = payload
        batches.append(TraceBatch(
            index=index,
            market_keys=tuple(market_keys),
            source_ids=tuple(source_ids),
            probabilities=np.ascontiguousarray(
                probabilities, dtype=np.float64
            ),
            offsets=np.ascontiguousarray(offsets, dtype=np.int64),
            outcomes=np.asarray(outcomes, dtype=bool),
            now_days=float(now) + index,
            steps=int(steps),
        ))
    return batches


def _uniform_steps(batches: Sequence[TraceBatch]) -> int:
    steps_seen = {int(batch.steps) for batch in batches}
    if len(steps_seen) != 1:
        raise ValueError(
            f"trace mixes step counts {sorted(steps_seen)}; one sweep "
            "runs one compiled step shape — split the trace"
        )
    return steps_seen.pop()


def _plan_device_arrays(plan, cdtype):
    """The plan's device copies, cached on the plan — the SAME cache (and
    tuple format, including the refresh-donor fast path) the flat
    :func:`~.pipeline.settle` keeps, so a sweep running beside the
    lane-0 authoritative dispatch re-uses its uploads instead of paying
    the host→device topology transfer twice."""
    import jax.numpy as jnp

    touched = getattr(plan, "_touched_rows", None)
    if touched is None:
        touched = plan.slot_rows[plan.mask]
        touched.setflags(write=False)
        object.__setattr__(plan, "_touched_rows", touched)
    device_plan = getattr(plan, "_device_arrays", None)
    if device_plan is None or device_plan[0] != str(cdtype):
        parent = getattr(plan, "_refreshed_from", None)
        donor = (
            getattr(parent, "_device_arrays", None)
            if parent is not None else None
        )
        if donor is not None and donor[0] == str(cdtype):
            device_plan = (
                donor[0],
                donor[1],
                jnp.asarray(plan.probs, dtype=cdtype),
                donor[3],
                donor[4],
            )
            object.__setattr__(parent, "_device_arrays", None)
        else:
            device_plan = (
                str(cdtype),
                jnp.asarray(plan.slot_rows),
                jnp.asarray(plan.probs, dtype=cdtype),
                jnp.asarray(plan.mask),
                jnp.asarray(touched),
            )
        object.__setattr__(plan, "_device_arrays", device_plan)
        if parent is not None:
            object.__setattr__(plan, "_refreshed_from", None)
    return device_plan


def _result_digest(
    lanes: Sequence[ReplayConfig],
    metrics: np.ndarray,
    lane0_digest: Optional[str],
) -> str:
    h = hashlib.blake2b(digest_size=16)

    def put(raw: bytes) -> None:
        h.update(len(raw).to_bytes(8, "little"))
        h.update(raw)

    for config in lanes:
        put(np.asarray(config, dtype=np.float64).tobytes())
    put(np.ascontiguousarray(metrics, dtype=np.float32).tobytes())
    put((lane0_digest or "").encode())
    return h.hexdigest()


def replay_sweep(
    trace: Sequence[TraceBatch],
    configs: Sequence[ReplayConfig] = (),
    *,
    graph=None,
    dtype=None,
    rebuild: bool = True,
    journal=None,
    db_path=None,
    checkpoint_every: int = 1,
    num_slots: "int | str | None" = "bucket",
    intern_mode: str = "auto",
) -> SweepResult:
    """Re-drive *trace* under the recorded config + every *configs* lane.

    One pass over the trace: each batch's plan stages and binds ONCE (in
    recorded admission order, against the lane-0 store — row assignment
    reproduces the live interner), lane 0 dispatches through the real
    :class:`~.serve.driver.SessionDriver` (``rebuild=True``; *journal* /
    *db_path* / *checkpoint_every* run the recorded durability cadence
    against fresh files, and ``SweepResult.digest`` witnesses the byte
    contract), and ALL lanes advance through one vmapped device step.
    ``rebuild=False`` skips the authoritative lane — the pure sweep
    (what the bench times), same lane metrics, no store/digest.

    *graph*, a :class:`~.analytics.graph.MarketGraph`, is required when
    any lane sets ``graph_steps > 0``; each batch's neighbour blocks
    align once and serve every lane (λ and depth are per-lane traced
    scalars inside the program).

    Device work per batch is ONE jit dispatch regardless of lane count;
    programs cache per (steps, max graph depth) and compile per distinct
    batch shape (``num_slots="bucket"`` keeps wobbling widths shared,
    exactly as live).
    """
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel.sharded import (
        CycleParams,
        build_replay_sweep_step,
    )
    from bayesian_consensus_engine_tpu.serve.driver import (
        PlanCache,
        SessionDriver,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )
    from bayesian_consensus_engine_tpu.utils.dtypes import (
        default_float_dtype,
    )

    batches = list(trace)
    if not batches:
        raise ValueError("empty trace: nothing to replay")
    steps = _uniform_steps(batches)
    lanes: Tuple[ReplayConfig, ...] = tuple(configs)
    if not lanes or lanes[0] != RECORDED_CONFIG:
        lanes = (RECORDED_CONFIG,) + lanes
    num_lanes = len(lanes)
    max_graph_steps = max(int(config.graph_steps) for config in lanes)
    if max_graph_steps > 0 and graph is None:
        raise ValueError(
            "a lane sets graph_steps > 0 but no graph= was given — the "
            "relaxation sweep needs the MarketGraph the lanes vary over"
        )

    timeline = active_timeline()
    registry = metrics_registry()
    batch_counter = registry.counter("replay.sweep_batches")
    registry.gauge("replay.sweep_lanes").set(float(num_lanes))

    # Plan build (stage + bind, admission order) — paid once for every
    # lane. The store doubles as lane 0's authoritative store.
    store = TensorReliabilityStore()
    plans = PlanCache(store, num_slots=num_slots, intern_mode=intern_mode)
    with timeline.span("replay"):
        plan_list = [
            plans.plan_for(
                list(batch.market_keys),
                list(batch.source_ids),
                batch.probabilities,
                batch.offsets,
            )
            for batch in batches
        ]
    rows = len(store)

    cdtype = default_float_dtype() if dtype is None else jnp.dtype(dtype)
    # Stamps are relative; one epoch strictly before the first batch's
    # day keeps every stamp positive ("> 0" means "ever updated").
    epoch0 = float(batches[0].now_days) - 1.0

    def stacked(fill, dt):
        return jnp.full((num_lanes, rows), fill, dtype=dt)

    state = (
        stacked(DEFAULT_RELIABILITY, cdtype),
        stacked(DEFAULT_CONFIDENCE, cdtype),
        stacked(0.0, cdtype),
        stacked(False, bool),
    )
    metrics = jnp.zeros((num_lanes, 4), jnp.float32)
    lane_f32 = lambda field: jnp.asarray(  # noqa: E731 - five-field stamp
        [float(getattr(config, field)) for config in lanes], jnp.float32
    )
    params = CycleParams(
        half_life_days=lane_f32("half_life_days"),
        decay_floor=lane_f32("decay_floor"),
        base_learning_rate=lane_f32("base_learning_rate"),
        max_update_step=lane_f32("max_update_step"),
        confidence_growth=jnp.full(
            num_lanes, np.float32(CycleParams().confidence_growth),
            jnp.float32,
        ),
    )
    band_z = lane_f32("band_z")
    graph_lanes = (
        (
            lane_f32("graph_damping"),
            jnp.asarray(
                [int(config.graph_steps) for config in lanes], jnp.int32
            ),
            lane_f32("graph_tol"),
        )
        if max_graph_steps > 0 else ()
    )
    step = build_replay_sweep_step(steps, max_graph_steps)

    driver = None
    if rebuild:
        owns_journal = False
        if journal is not None and not hasattr(journal, "append_epoch"):
            from bayesian_consensus_engine_tpu.state.journal import (
                JournalWriter,
            )

            journal = JournalWriter(journal)
            owns_journal = True
        driver = SessionDriver(
            store,
            steps=steps,
            journal=journal,
            owns_journal=owns_journal,
            db_path=db_path,
            checkpoint_every=checkpoint_every,
        )
    try:
        for index, (batch, plan) in enumerate(zip(batches, plan_list)):
            if driver is not None:
                driver.dispatch(
                    plan, batch.outcomes, now=float(batch.now_days)
                )
                driver.checkpoint(index)
            with timeline.span("replay"):
                _, slot_rows_d, probs_d, mask_d, _ = _plan_device_arrays(
                    plan, cdtype
                )
                neighbors = ()
                if max_graph_steps > 0:
                    neighbor_idx, neighbor_w = graph.align(
                        list(batch.market_keys)
                    )
                    neighbors = (
                        jnp.asarray(neighbor_idx), jnp.asarray(neighbor_w)
                    )
                state, metrics = step(
                    state, metrics, params, band_z, graph_lanes,
                    slot_rows_d, probs_d, mask_d,
                    jnp.asarray(np.asarray(batch.outcomes, dtype=bool)),
                    jnp.asarray(float(batch.now_days) - epoch0, cdtype),
                    neighbors,
                )
            batch_counter.inc()
    finally:
        if driver is not None:
            driver.finalize()

    metrics_np = np.asarray(metrics)
    lane_state = tuple(np.asarray(column) for column in state)
    digest = None
    if rebuild:
        from bayesian_consensus_engine_tpu.cluster.recover import (
            store_digest,
        )

        digest = store_digest(store)
    reports = tuple(
        LaneReport(
            config=config,
            markets_settled=int(metrics_np[lane, 0]),
            brier_sum=float(metrics_np[lane, 1]),
            band_width_sum=float(metrics_np[lane, 2]),
            graph_brier_sum=float(metrics_np[lane, 3]),
        )
        for lane, config in enumerate(lanes)
    )
    return SweepResult(
        lanes=reports,
        batches=len(batches),
        epoch0=epoch0,
        lane_state=lane_state,
        result_digest=_result_digest(lanes, metrics_np, digest),
        store=store if rebuild else None,
        digest=digest,
    )


def replay_single(
    trace: Sequence[TraceBatch],
    config: ReplayConfig = RECORDED_CONFIG,
    *,
    graph=None,
    dtype=None,
) -> LaneReport:
    """One config's replay, paying the full staging cost itself.

    The sequential baseline the sweep amortises: each call builds its
    own fresh store, re-stages and re-interns every plan, and runs a
    1-wide device program (plus the recorded lane when *config* differs
    — the sweep always carries lane 0). K calls ≈ K× the host cost ONE
    :func:`replay_sweep` pays once; the ``e2e_replay_sweep`` leg records
    the ratio (acceptance ≥6× at 16 configs).
    """
    result = replay_sweep(
        trace,
        () if config == RECORDED_CONFIG else (config,),
        graph=graph,
        dtype=dtype,
        rebuild=False,
    )
    return result.by_config()[config]
