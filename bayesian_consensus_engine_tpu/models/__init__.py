"""Domain models: markets, cross-market aggregation, tie-breaking."""

from bayesian_consensus_engine_tpu.models.market import (
    CrossMarketAggregator,
    Market,
    MarketId,
    MarketStatus,
    MarketStore,
    SourcePerformance,
)
from bayesian_consensus_engine_tpu.models.tiebreak import (
    AgentSignal,
    DeterministicTieBreaker,
    TieBreakDiagnostics,
)

__all__ = [
    "CrossMarketAggregator",
    "Market",
    "MarketId",
    "MarketStatus",
    "MarketStore",
    "SourcePerformance",
    "AgentSignal",
    "DeterministicTieBreaker",
    "TieBreakDiagnostics",
]
