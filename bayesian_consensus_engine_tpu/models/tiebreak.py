"""Deterministic resolution of conflicting predictions.

Host-side (diagnostics-sized) implementation with observable parity to the
reference tie-breaker (reference: src/bayesian_engine/tiebreak.py):

resolution hierarchy over prediction groups (rounded to ``precision``):
  1. weight density  = total_weight / count      (higher wins)
  2. max reliability within the group            (higher wins)
  3. smallest prediction value                   (deterministic tertiary)

Preserved reference quirks: the tertiary rule is smallest-prediction (the
reference's own docs claim lexicographic-source-id; code wins — quirk #5),
and ``tie_resolved_by`` reports "weight_density" even when the decision fell
to max_reliability (quirk #6).

This module stays stdlib-only; tie-breaking is diagnostics-sized, not the
hot loop (SURVEY §7: grouping-by-rounded-prediction is a sort/unique problem
that can stay host-side).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class TieBreakDiagnostics:
    """How a conflict was resolved, with per-group metrics."""

    method: str
    groups: Dict[float, Dict]
    selected_group: float
    tie_resolved_by: str
    confidence_variance: float


@dataclass
class AgentSignal:
    """One agent's prediction plus resolution metadata."""

    agent_id: str
    prediction: float
    confidence: float
    weight: float = 1.0
    reliability_score: float = 0.5

    def __post_init__(self) -> None:
        if not 0 <= self.confidence <= 1:
            raise ValueError(f"confidence must be in [0,1], got {self.confidence}")
        if not 0 <= self.reliability_score <= 1:
            raise ValueError(
                f"reliability_score must be in [0,1], got {self.reliability_score}"
            )


@dataclass
class _Group:
    members: List[AgentSignal] = field(default_factory=list)

    @property
    def count(self) -> int:
        return len(self.members)

    @property
    def total_weight(self) -> float:
        return sum(a.weight for a in self.members)

    @property
    def weight_density(self) -> float:
        return self.total_weight / self.count

    @property
    def avg_confidence(self) -> float:
        return sum(a.confidence for a in self.members) / self.count

    @property
    def max_reliability(self) -> float:
        return max(a.reliability_score for a in self.members)


class DeterministicTieBreaker:
    """Resolve conflicting agent predictions with a fixed hierarchy."""

    def __init__(self, precision: int = 6):
        self.precision = precision

    def resolve(self, agents: List[AgentSignal]) -> Tuple[float, TieBreakDiagnostics]:
        """Pick the winning prediction; raise ``ValueError`` on empty input."""
        if not agents:
            raise ValueError("Cannot resolve tie with empty agent list")

        if len(agents) == 1:
            only = agents[0]
            return only.prediction, TieBreakDiagnostics(
                method="single_agent",
                groups={only.prediction: {"count": 1}},
                selected_group=only.prediction,
                tie_resolved_by="unanimous",
                confidence_variance=0.0,
            )

        groups: Dict[float, _Group] = {}
        for agent in agents:
            groups.setdefault(round(agent.prediction, self.precision), _Group()).members.append(
                agent
            )

        mean_conf = sum(a.confidence for a in agents) / len(agents)
        variance = sum((a.confidence - mean_conf) ** 2 for a in agents) / len(agents)

        # Hierarchy as one sort key; -prediction so that, descending, the
        # SMALLEST prediction wins the tertiary tie.
        ranked = sorted(
            groups.items(),
            key=lambda item: (item[1].weight_density, item[1].max_reliability, -item[0]),
            reverse=True,
        )
        winning_pred, winning = ranked[0]

        if len(ranked) == 1:
            resolved_by = "unanimous"
        else:
            runner_up = ranked[1][1]
            density_tied = winning.weight_density == runner_up.weight_density
            reliability_tied = winning.max_reliability == runner_up.max_reliability
            if density_tied and reliability_tied:
                resolved_by = "prediction_value_smallest"
            else:
                # Reference labels any non-full tie "weight_density", even when
                # the decision actually fell to max_reliability (quirk #6).
                resolved_by = "weight_density"

        diagnostics = TieBreakDiagnostics(
            method="prioritized_weight_density",
            groups={
                pred: {
                    "count": g.count,
                    "weight_density": round(g.weight_density, 4),
                    "avg_confidence": round(g.avg_confidence, 4),
                    "max_reliability": round(g.max_reliability, 4),
                }
                for pred, g in groups.items()
            },
            selected_group=winning_pred,
            tie_resolved_by=resolved_by,
            confidence_variance=round(variance, 6),
        )
        return winning_pred, diagnostics
