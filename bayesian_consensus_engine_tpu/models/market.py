"""Multi-market orchestration and cross-market analytics.

Behavioural parity with the reference market layer
(reference: src/bayesian_engine/market.py:41-408), with one structural
difference: consensus can be computed through any
:class:`~..state.sqlite_store.ReliabilityStore` implementation — including
the HBM tensor store — and ``compute_all_consensus`` has a batched sibling
in ``core.batch`` that replaces the per-market Python loop with one vmapped
kernel (the reference's M×S scaling wall, market.py:200-221).

Preserved reference quirks: empty-market consensus returns a reduced 4-key
document (quirk #8); nothing ever transitions a market to CLOSED (quirk #14);
a source is "correct" iff (probability >= 0.5) == outcome.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

from bayesian_consensus_engine_tpu.core.engine import compute_consensus
from bayesian_consensus_engine_tpu.state.sqlite_store import ReliabilityStore
from bayesian_consensus_engine_tpu.state.update_math import utc_now_iso
from bayesian_consensus_engine_tpu.utils.config import SCHEMA_VERSION


@dataclass(frozen=True)
class MarketId:
    """Identifier for a market/question; supports ``cat:subcat:...`` structure."""

    value: str

    def __post_init__(self) -> None:
        if not self.value or not self.value.strip():
            raise ValueError("Market ID cannot be empty")

    def __str__(self) -> str:
        return self.value

    def __repr__(self) -> str:
        return f"MarketId({self.value!r})"

    @property
    def category(self) -> Optional[str]:
        """Leading segment of a ``cat:...`` id, else None."""
        return self.value.split(":")[0] if ":" in self.value else None

    @property
    def parts(self) -> List[str]:
        return self.value.split(":")

    def matches(self, pattern: str) -> bool:
        """Glob match (``crypto:*``, ``*:price``, exact ids)."""
        return fnmatch.fnmatch(self.value, pattern)


class MarketStatus(str, Enum):
    OPEN = "open"          # accepting signals
    CLOSED = "closed"      # no more signals, outcome pending
    RESOLVED = "resolved"  # outcome known


@dataclass
class Market:
    """One market: status, collected signals, optional resolved outcome."""

    id: MarketId
    status: MarketStatus = MarketStatus.OPEN
    signals: List[Dict[str, Any]] = field(default_factory=list)
    consensus_result: Optional[Dict[str, Any]] = None
    outcome: Optional[bool] = None
    created_at: str = field(default_factory=utc_now_iso)
    resolved_at: Optional[str] = None
    metadata: Dict[str, Any] = field(default_factory=dict)

    def add_signal(self, signal: Dict[str, Any]) -> None:
        if self.status != MarketStatus.OPEN:
            raise ValueError(f"Cannot add signal to {self.status} market")
        self.signals.append(signal)

    def compute_consensus(
        self,
        source_reliability: Optional[Dict[str, Dict[str, float]]] = None,
        backend: str = "python",
    ) -> Dict[str, Any]:
        """Consensus for this market, stamped with ``marketId``.

        Empty market → reduced 4-key document (no sourceWeights/normalization/
        diagnostics), unlike core's empty-signals shape (reference quirk #8).
        """
        if not self.signals:
            return {
                "schemaVersion": SCHEMA_VERSION,
                "consensus": None,
                "confidence": 0.0,
                "marketId": str(self.id),
            }
        result = compute_consensus(self.signals, source_reliability, backend=backend)
        result["marketId"] = str(self.id)
        self.consensus_result = result
        return result

    def resolve(self, outcome: bool) -> None:
        self.outcome = outcome
        self.status = MarketStatus.RESOLVED
        self.resolved_at = utc_now_iso()


class MarketStore:
    """In-memory registry of markets keyed by id string."""

    def __init__(self) -> None:
        self._markets: Dict[str, Market] = {}

    def create_market(
        self,
        market_id: MarketId,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> Market:
        key = str(market_id)
        if key in self._markets:
            raise ValueError(f"Market {market_id} already exists")
        market = Market(id=market_id, metadata=metadata or {})
        self._markets[key] = market
        return market

    def get_market(self, market_id: MarketId) -> Optional[Market]:
        return self._markets.get(str(market_id))

    def get_or_create(self, market_id: MarketId) -> Market:
        return self.get_market(market_id) or self.create_market(market_id)

    def add_signal(self, market_id: MarketId, signal: Dict[str, Any]) -> Market:
        market = self.get_or_create(market_id)
        market.add_signal(signal)
        return market

    def list_markets(
        self,
        status: Optional[MarketStatus] = None,
        pattern: Optional[str] = None,
    ) -> List[Market]:
        markets = list(self._markets.values())
        if status is not None:
            markets = [m for m in markets if m.status == status]
        if pattern is not None:
            markets = [m for m in markets if m.id.matches(pattern)]
        return markets

    def compute_all_consensus(
        self,
        reliability_store: Optional[ReliabilityStore] = None,
        backend: str = "python",
    ) -> Dict[str, Dict[str, Any]]:
        """Consensus for every OPEN market (decayed reliability per source).

        This is the loop the TPU path replaces wholesale — see
        ``core.batch.compute_batch_consensus`` for the vmapped (M×S) kernel
        over a packed signal tensor.
        """
        results: Dict[str, Dict[str, Any]] = {}
        for market in self.list_markets(status=MarketStatus.OPEN):
            source_rel: Optional[Dict[str, Dict[str, float]]] = None
            if reliability_store is not None:
                source_rel = {}
                for signal in market.signals:
                    sid = signal["sourceId"]
                    if sid not in source_rel:
                        record = reliability_store.get_reliability(
                            sid, str(market.id), apply_decay=True
                        )
                        source_rel[sid] = {
                            "reliability": record.reliability,
                            "confidence": record.confidence,
                        }
            results[str(market.id)] = market.compute_consensus(source_rel, backend=backend)
        return results


@dataclass
class SourcePerformance:
    """A source's aggregate track record across resolved markets."""

    source_id: str
    total_markets: int
    correct_predictions: int
    wrong_predictions: int
    reliability: float
    markets: List[str] = field(default_factory=list)

    @property
    def accuracy(self) -> float:
        total = self.correct_predictions + self.wrong_predictions
        return self.correct_predictions / total if total else 0.0


class CrossMarketAggregator:
    """Analytics across markets: source scorecards, category summaries,
    cross-market consensus aggregation."""

    def __init__(self, market_store: MarketStore):
        self._store = market_store

    def summarize_sources(
        self,
        patterns: Optional[List[str]] = None,
    ) -> Dict[str, SourcePerformance]:
        """Per-source accuracy over RESOLVED markets (optionally filtered)."""
        markets = self._store.list_markets(status=MarketStatus.RESOLVED)
        if patterns:
            markets = [
                m for m in markets if any(m.id.matches(p) for p in patterns)
            ]

        tallies: Dict[str, Dict[str, Any]] = {}
        for market in markets:
            if market.outcome is None:
                continue
            for signal in market.signals:
                sid = signal["sourceId"]
                stats = tallies.setdefault(
                    sid, {"total": 0, "correct": 0, "wrong": 0, "markets": []}
                )
                stats["total"] += 1
                stats["markets"].append(str(market.id))
                # Binary correctness: predicted-true iff probability >= 0.5.
                predicted_true = signal.get("probability", 0.5) >= 0.5
                if predicted_true == market.outcome:
                    stats["correct"] += 1
                else:
                    stats["wrong"] += 1

        summary: Dict[str, SourcePerformance] = {}
        for sid, stats in tallies.items():
            judged = stats["correct"] + stats["wrong"]
            summary[sid] = SourcePerformance(
                source_id=sid,
                total_markets=stats["total"],
                correct_predictions=stats["correct"],
                wrong_predictions=stats["wrong"],
                reliability=stats["correct"] / judged if judged else 0.5,
                markets=stats["markets"],
            )
        return summary

    def summarize_category(self, category: str) -> Dict[str, Any]:
        markets = self._store.list_markets(pattern=f"{category}:*")
        resolved = [m for m in markets if m.status == MarketStatus.RESOLVED]
        open_markets = [m for m in markets if m.status == MarketStatus.OPEN]
        return {
            "category": category,
            "total_markets": len(markets),
            "resolved": len(resolved),
            "open": len(open_markets),
            "markets": [str(m.id) for m in markets],
        }

    def aggregate_consensus(
        self,
        patterns: List[str],
        method: str = "weighted_average",
    ) -> Dict[str, Any]:
        """Combine cached per-market consensus across matching markets.

        Methods: confidence-weighted average, upper median, binary majority.
        """
        markets: List[Market] = []
        for pattern in patterns:
            markets.extend(self._store.list_markets(pattern=pattern))

        if not markets:
            return {
                "schemaVersion": SCHEMA_VERSION,
                "consensus": None,
                "confidence": 0.0,
                "marketsIncluded": 0,
            }

        entries = [
            {
                "marketId": str(m.id),
                "consensus": m.consensus_result["consensus"],
                "confidence": m.consensus_result.get("confidence", 0.5),
            }
            for m in markets
            if m.consensus_result and m.consensus_result.get("consensus") is not None
        ]

        if not entries:
            return {
                "schemaVersion": SCHEMA_VERSION,
                "consensus": None,
                "confidence": 0.0,
                "marketsIncluded": len(markets),
            }

        if method == "weighted_average":
            total_conf = sum(e["confidence"] for e in entries)
            if total_conf == 0:
                aggregated = sum(e["consensus"] for e in entries) / len(entries)
            else:
                aggregated = (
                    sum(e["consensus"] * e["confidence"] for e in entries) / total_conf
                )
        elif method == "median":
            ordered = sorted(e["consensus"] for e in entries)
            aggregated = ordered[len(ordered) // 2]  # upper median, like reference
        elif method == "majority":
            votes = [1 if e["consensus"] >= 0.5 else 0 for e in entries]
            aggregated = sum(votes) / len(votes)
        else:
            raise ValueError(f"Unknown aggregation method: {method}")

        return {
            "schemaVersion": SCHEMA_VERSION,
            "consensus": aggregated,
            "confidence": sum(e["confidence"] for e in entries) / len(entries),
            "marketsIncluded": len(entries),
            "method": method,
        }
