"""Multi-market orchestration and cross-market analytics.

Behavioural parity with the reference market layer
(reference: src/bayesian_engine/market.py:41-408), with one structural
difference: consensus can be computed through any
:class:`~..state.sqlite_store.ReliabilityStore` implementation — including
the HBM tensor store — and ``compute_all_consensus`` has a batched sibling
in ``core.batch`` that replaces the per-market Python loop with one vmapped
kernel (the reference's M×S scaling wall, market.py:200-221).

Preserved reference quirks: empty-market consensus returns a reduced 4-key
document (quirk #8); nothing ever transitions a market to CLOSED (quirk #14);
a source is "correct" iff (probability >= 0.5) == outcome.
"""

from __future__ import annotations

import fnmatch
from collections import Counter
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

import numpy as np

from bayesian_consensus_engine_tpu.core.engine import compute_consensus
from bayesian_consensus_engine_tpu.utils.interning import IdInterner
from bayesian_consensus_engine_tpu.state.sqlite_store import ReliabilityStore
from bayesian_consensus_engine_tpu.utils.timeconv import utc_now_iso
from bayesian_consensus_engine_tpu.utils.config import SCHEMA_VERSION


@dataclass(frozen=True)
class MarketId:
    """Identifier for a market/question; supports ``cat:subcat:...`` structure."""

    value: str

    def __post_init__(self) -> None:
        if not self.value or not self.value.strip():
            raise ValueError("Market ID cannot be empty")

    def __str__(self) -> str:
        return self.value

    def __repr__(self) -> str:
        return f"MarketId({self.value!r})"

    @property
    def category(self) -> Optional[str]:
        """Leading segment of a ``cat:...`` id, else None."""
        return self.value.split(":")[0] if ":" in self.value else None

    @property
    def parts(self) -> List[str]:
        return self.value.split(":")

    def matches(self, pattern: str) -> bool:
        """Glob match (``crypto:*``, ``*:price``, exact ids)."""
        return fnmatch.fnmatch(self.value, pattern)


class MarketStatus(str, Enum):
    OPEN = "open"          # accepting signals
    CLOSED = "closed"      # no more signals, outcome pending
    RESOLVED = "resolved"  # outcome known


@dataclass
class Market:
    """One market: status, collected signals, optional resolved outcome."""

    id: MarketId
    status: MarketStatus = MarketStatus.OPEN
    signals: List[Dict[str, Any]] = field(default_factory=list)
    consensus_result: Optional[Dict[str, Any]] = None
    outcome: Optional[bool] = None
    created_at: str = field(default_factory=utc_now_iso)
    resolved_at: Optional[str] = None
    metadata: Dict[str, Any] = field(default_factory=dict)

    def add_signal(self, signal: Dict[str, Any]) -> None:
        if self.status != MarketStatus.OPEN:
            raise ValueError(f"Cannot add signal to {self.status} market")
        self.signals.append(signal)

    def compute_consensus(
        self,
        source_reliability: Optional[Dict[str, Dict[str, float]]] = None,
        backend: str = "python",
    ) -> Dict[str, Any]:
        """Consensus for this market, stamped with ``marketId``.

        Empty market → reduced 4-key document (no sourceWeights/normalization/
        diagnostics), unlike core's empty-signals shape (reference quirk #8).
        """
        if not self.signals:
            return {
                "schemaVersion": SCHEMA_VERSION,
                "consensus": None,
                "confidence": 0.0,
                "marketId": str(self.id),
            }
        result = compute_consensus(self.signals, source_reliability, backend=backend)
        result["marketId"] = str(self.id)
        self.consensus_result = result
        return result

    def resolve(self, outcome: bool) -> None:
        self.outcome = outcome
        self.status = MarketStatus.RESOLVED
        self.resolved_at = utc_now_iso()


class MarketStore:
    """In-memory registry of markets keyed by id string."""

    def __init__(self) -> None:
        self._markets: Dict[str, Market] = {}

    def create_market(
        self,
        market_id: MarketId,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> Market:
        key = str(market_id)
        if key in self._markets:
            raise ValueError(f"Market {market_id} already exists")
        market = Market(id=market_id, metadata=metadata or {})
        self._markets[key] = market
        return market

    def get_market(self, market_id: MarketId) -> Optional[Market]:
        return self._markets.get(str(market_id))

    def get_or_create(self, market_id: MarketId) -> Market:
        return self.get_market(market_id) or self.create_market(market_id)

    def add_signal(self, market_id: MarketId, signal: Dict[str, Any]) -> Market:
        market = self.get_or_create(market_id)
        market.add_signal(signal)
        return market

    def list_markets(
        self,
        status: Optional[MarketStatus] = None,
        pattern: Optional[str] = None,
    ) -> List[Market]:
        markets = list(self._markets.values())
        if status is not None:
            markets = [m for m in markets if m.status == status]
        if pattern is not None:
            markets = [m for m in markets if m.id.matches(pattern)]
        return markets

    def compute_all_consensus(
        self,
        reliability_store: Optional[ReliabilityStore] = None,
        backend: str = "python",
    ) -> Dict[str, Dict[str, Any]]:
        """Consensus for every OPEN market (decayed reliability per source).

        Any non-scalar backend routes the whole sweep through the batched
        engine — one device pass over a packed signal tensor — instead of
        market-by-market scalar calls; results land in the same
        ``{market_id: document}`` mapping and are cached on each market
        either way.
        """
        if backend not in ("python", "jax", "tpu"):
            raise ValueError(f"unknown backend: {backend!r}")
        if backend != "python":
            from bayesian_consensus_engine_tpu.core.batch import (
                compute_all_consensus_batched,
            )

            return compute_all_consensus_batched(self, reliability_store)

        results: Dict[str, Dict[str, Any]] = {}
        for market in self.list_markets(status=MarketStatus.OPEN):
            table = (
                None
                if reliability_store is None
                else _decayed_reliability_table(
                    reliability_store, market.signals, str(market.id)
                )
            )
            results[str(market.id)] = market.compute_consensus(table)
        return results


def _decayed_reliability_table(
    store: ReliabilityStore, signals: List[Dict[str, Any]], market_id: str
) -> Dict[str, Dict[str, float]]:
    """Per-source decayed reliability for one market's signalling sources."""
    table: Dict[str, Dict[str, float]] = {}
    for signal in signals:
        sid = signal["sourceId"]
        if sid not in table:
            record = store.get_reliability(sid, market_id, apply_decay=True)
            table[sid] = {
                "reliability": record.reliability,
                "confidence": record.confidence,
            }
    return table


@dataclass
class SourcePerformance:
    """A source's aggregate track record across resolved markets."""

    source_id: str
    total_markets: int
    correct_predictions: int
    wrong_predictions: int
    reliability: float
    markets: List[str] = field(default_factory=list)

    @property
    def accuracy(self) -> float:
        total = self.correct_predictions + self.wrong_predictions
        return self.correct_predictions / total if total else 0.0


class CrossMarketAggregator:
    """Analytics across markets: source scorecards, category summaries,
    cross-market consensus aggregation."""

    def __init__(self, market_store: MarketStore):
        self._store = market_store

    def summarize_sources(
        self,
        patterns: Optional[List[str]] = None,
    ) -> Dict[str, SourcePerformance]:
        """Per-source accuracy over RESOLVED markets (optionally filtered).

        Columnar: all signals across all resolved markets flatten into
        (source row, probability, outcome) arrays once, correctness is one
        vectorised compare (predicted-true iff p ≥ 0.5, reference:
        market.py:296-303), and per-source tallies are bincounts — no
        per-signal dict churn. Scorecards come out in first-seen source
        order, exactly like the sequential walk.
        """
        markets = [
            m
            for m in self._store.list_markets(status=MarketStatus.RESOLVED)
            if m.outcome is not None
            and (not patterns or any(m.id.matches(p) for p in patterns))
        ]
        market_ids = [str(m.id) for m in markets]

        sources = IdInterner()
        columns = [
            (sources.intern(sig["sourceId"]), sig.get("probability", 0.5), mi)
            for mi, market in enumerate(markets)
            for sig in market.signals
        ]
        if not columns:
            return {}
        src, prob, market_of = (np.asarray(c) for c in zip(*columns))
        outcome_of = np.asarray([m.outcome for m in markets], dtype=bool)
        correct = (prob.astype(np.float64) >= 0.5) == outcome_of[market_of]

        n = len(sources)
        total = np.bincount(src, minlength=n)
        n_correct = np.bincount(src, weights=correct, minlength=n).astype(np.int64)
        # Group signal rows by source, preserving original signal order
        # within each group (stable sort), for the per-source market lists.
        grouped = np.argsort(src, kind="stable")
        group_end = np.cumsum(total)

        summary: Dict[str, SourcePerformance] = {}
        for row in range(n):
            rows = grouped[group_end[row] - total[row]: group_end[row]]
            summary[sources.id_of(row)] = SourcePerformance(
                source_id=sources.id_of(row),
                total_markets=int(total[row]),
                correct_predictions=int(n_correct[row]),
                wrong_predictions=int(total[row] - n_correct[row]),
                reliability=(
                    float(n_correct[row] / total[row]) if total[row] else 0.5
                ),
                markets=[market_ids[mi] for mi in market_of[rows]],
            )
        return summary

    def summarize_category(self, category: str) -> Dict[str, Any]:
        """Status census of one ``category:*`` id prefix."""
        markets = self._store.list_markets(pattern=f"{category}:*")
        by_status = Counter(m.status for m in markets)
        return {
            "category": category,
            "total_markets": len(markets),
            "resolved": by_status[MarketStatus.RESOLVED],
            "open": by_status[MarketStatus.OPEN],
            "markets": [str(m.id) for m in markets],
        }

    def consensus_columns(
        self, patterns: List[str]
    ) -> tuple[int, np.ndarray, np.ndarray]:
        """Cached consensus documents for matching markets, as columns.

        Returns ``(matched, values, confidences)`` where *matched* counts
        every pattern match (a market matching two patterns counts twice,
        as the sequential walk did) and the arrays hold the non-null cached
        consensus/confidence pairs — the columnar feed for any aggregation,
        and exactly the arrays a ``compute_all_consensus`` sweep (batched or
        scalar) just cached onto the markets.
        """
        docs = [
            m.consensus_result
            for pattern in patterns
            for m in self._store.list_markets(pattern=pattern)
        ]
        live = [
            d for d in docs if d and d.get("consensus") is not None
        ]
        values = np.asarray([d["consensus"] for d in live], dtype=np.float64)
        confs = np.asarray(
            [d.get("confidence", 0.5) for d in live], dtype=np.float64
        )
        return len(docs), values, confs

    def aggregate_consensus(
        self,
        patterns: List[str],
        method: str = "weighted_average",
    ) -> Dict[str, Any]:
        """Combine cached per-market consensus across matching markets.

        Methods: confidence-weighted average, upper median, binary majority
        — each one vectorised over the consensus columns.
        """
        matched, values, confs = self.consensus_columns(patterns)
        if values.size == 0:
            return {
                "schemaVersion": SCHEMA_VERSION,
                "consensus": None,
                "confidence": 0.0,
                "marketsIncluded": matched,
            }

        # Reductions run over Python floats via builtin sum(): CPython ≥3.12
        # compensates float sums, and the scalar reference inherits exactly
        # that — numpy pairwise/BLAS accumulation differs in the last ulp,
        # which would break parity with reference outputs. The elementwise
        # product is IEEE-exact either way, so only the sums need care.
        conf_list = confs.tolist()
        if method == "weighted_average":
            total_conf = sum(conf_list)
            aggregated = (
                sum((values * confs).tolist()) / total_conf
                if total_conf
                else sum(values.tolist()) / values.size
            )
        elif method == "median":
            # Upper median: the reference indexes the sorted list at n//2.
            aggregated = float(np.sort(values)[values.size // 2])
        elif method == "majority":
            aggregated = float((values >= 0.5).mean())
        else:
            raise ValueError(f"Unknown aggregation method: {method}")

        return {
            "schemaVersion": SCHEMA_VERSION,
            "consensus": aggregated,
            "confidence": sum(conf_list) / len(conf_list),
            "marketsIncluded": int(values.size),
            "method": method,
        }
