"""The network front door: N asyncio acceptor tasks feeding the ONE
deterministic coalescer.

:class:`ConsensusServer` puts a real multi-client transport in front of
:class:`~.serve.coalesce.ConsensusService` without adding a second
batching discipline: every connection's requests are submitted — on the
event-loop thread, in wire arrival order — through the same
``service.submit`` an in-process caller uses, so the batch sequence
stays a deterministic function of the ADMITTED-REQUEST TRACE exactly as
before. The server owns sockets and frames; the service owns windows,
admission, QoS, and settlement. The headline contract rides on that
split: the same admitted-request trace served over the wire and
submitted in-process yields identical results, journal epochs (sans
wall_ts), and SQLite bytes (pinned by tests/test_net.py).

**Acceptor pool.** ``acceptors`` tasks loop on ``sock_accept`` over one
listening socket — the stdlib-only analogue of a multi-acceptor front
end (the kernel load-balances the accept queue across them). Each
accepted connection gets its own reader task.

**Pipelining.** A connection may send request frames back to back
without waiting; each settled future writes its response frame (under a
per-connection write lock) when it resolves, carrying the request's
``id`` so a pipelining client can match responses arriving in
completion order. This is load-bearing, not a luxury: a deterministic
trace is SUBMISSION-ordered, and a client that had to await each
settlement before the next submit could never fill a coalescing window.

**Failure containment.** Admission refusals (``Overloaded``/
``ShedError``/``ServiceClosed``) are ERROR frames on a healthy
connection — backpressure is an answer. Framing violations (bad magic,
version mismatch, oversized or checksum-failed frames, torn
mid-frame writes) kill ONLY the offending connection — a best-effort
error frame, then close — and are counted (``net.wire_errors``); the
coalescer, every other connection, and the journal bytes are untouched
(the wire-robustness tests pin this).
"""

from __future__ import annotations

import asyncio
import socket
from typing import Optional

from bayesian_consensus_engine_tpu.net import wire
from bayesian_consensus_engine_tpu.obs.metrics import metrics_registry
from bayesian_consensus_engine_tpu.serve.admission import (
    Overloaded,
    ServeError,
    ServiceClosed,
    ShedError,
)


def _error_frame_for(exc: BaseException, request_id=None) -> bytes:
    """Map a serve-layer exception onto its explicit error frame."""
    if isinstance(exc, Overloaded):
        return wire.encode_error(
            "overloaded", str(exc), request_id=request_id,
            retry_after_s=exc.retry_after_s, pending=exc.pending,
        )
    if isinstance(exc, ShedError):
        return wire.encode_error("shed", str(exc), request_id=request_id)
    if isinstance(exc, ServiceClosed):
        return wire.encode_error("closed", str(exc), request_id=request_id)
    if isinstance(exc, (ValueError, KeyError, TypeError)):
        return wire.encode_error(
            "bad_request", repr(exc), request_id=request_id
        )
    return wire.encode_error("failed", repr(exc), request_id=request_id)


class ConsensusServer:
    """Length-prefixed socket front door over one coalescing service.

    Start from inside the event loop that owns *service* (its coalescer
    is loop-owned state): ``server = await ConsensusServer(service).
    start()``; ``port`` reads the bound port back (``port=0`` binds
    ephemeral). ``close()`` stops the acceptors and closes every open
    connection; the service is NOT closed — the caller that composed
    them owns both lifecycles (``bce-tpu serve`` closes the service
    after the server).
    """

    def __init__(
        self,
        service,
        host: str = "127.0.0.1",
        port: int = 0,
        acceptors: int = 4,
        max_frame_bytes: int = wire.MAX_FRAME_BYTES,
    ) -> None:
        if acceptors < 1:
            raise ValueError("acceptors must be >= 1")
        self._service = service
        self._host = host
        self._requested_port = int(port)
        self._acceptors = int(acceptors)
        self._max_frame_bytes = int(max_frame_bytes)
        self._sock: Optional[socket.socket] = None
        self._tasks: set = set()
        self._closed = False
        registry = metrics_registry()
        self._connections = registry.counter("net.connections")
        self._open_gauge = registry.gauge("net.open_connections")
        self._requests = registry.counter("net.requests")
        self._responses = registry.counter("net.responses")
        self._errors = registry.counter("net.error_frames")
        self._wire_errors = registry.counter("net.wire_errors")
        self._open = 0

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> "ConsensusServer":
        if self._sock is not None:
            return self
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self._host, self._requested_port))
        sock.listen(128)
        sock.setblocking(False)
        self._sock = sock
        loop = asyncio.get_running_loop()
        for i in range(self._acceptors):
            self._track(loop.create_task(self._accept_loop(i)))
        return self

    def _track(self, task) -> None:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    @property
    def port(self) -> int:
        if self._sock is None:
            raise RuntimeError("server not started")
        return self._sock.getsockname()[1]

    @property
    def address(self) -> str:
        return f"{self._host}:{self.port}"

    async def close(self) -> None:
        """Stop accepting, close every connection; idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._sock is not None:
            self._sock.close()
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)

    async def __aenter__(self) -> "ConsensusServer":
        return await self.start()

    async def __aexit__(self, *exc_info: object) -> None:
        await self.close()

    # -- accepting -----------------------------------------------------------

    async def _accept_loop(self, index: int) -> None:
        loop = asyncio.get_running_loop()
        while not self._closed:
            try:
                conn, _addr = await loop.sock_accept(self._sock)
            except asyncio.CancelledError:
                raise
            except OSError:
                return  # listening socket closed under us: shutdown
            self._connections.inc()
            self._track(loop.create_task(self._serve_connection(conn)))

    # -- per-connection ------------------------------------------------------

    async def _serve_connection(self, conn: socket.socket) -> None:
        loop = asyncio.get_running_loop()
        reader = asyncio.StreamReader(loop=loop)
        protocol = asyncio.StreamReaderProtocol(reader, loop=loop)
        transport, _ = await loop.connect_accepted_socket(
            lambda: protocol, conn
        )
        writer = asyncio.StreamWriter(transport, protocol, reader, loop)
        write_lock = asyncio.Lock()
        self._open += 1
        self._open_gauge.set(float(self._open))
        try:
            await self._read_frames(reader, writer, write_lock, loop)
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError):
            pass  # peer went away; nothing to salvage
        finally:
            self._open -= 1
            self._open_gauge.set(float(self._open))
            try:
                writer.close()
            except (ConnectionError, OSError):
                pass

    async def _read_frames(self, reader, writer, write_lock, loop) -> None:
        while True:
            try:
                header = await reader.readexactly(wire.HEADER.size)
            except asyncio.IncompleteReadError as exc:
                if exc.partial:
                    # Torn mid-header: the peer died mid-send. Count it;
                    # there is no framing left to answer into.
                    self._wire_errors.inc()
                return  # clean EOF at a frame boundary
            try:
                kind, length, crc = wire.decode_header(
                    header, self._max_frame_bytes
                )
                payload = wire.decode_payload(
                    await reader.readexactly(length), crc
                )
            except asyncio.IncompleteReadError:
                # Torn mid-payload (a slow client that died mid-frame):
                # the stream is desynced — close, never resynchronise by
                # guesswork.
                self._wire_errors.inc()
                return
            except wire.WireError as exc:
                # A framing violation gets its explicit refusal, then
                # the connection dies: after a desync every subsequent
                # byte is noise. best-effort — the peer may be gone.
                self._wire_errors.inc()
                code = (
                    "version_mismatch"
                    if isinstance(exc, wire.VersionMismatch)
                    else "oversized"
                    if isinstance(exc, wire.FrameTooLarge)
                    else "bad_frame"
                )
                await self._send(
                    writer, write_lock, wire.encode_error(code, str(exc))
                )
                return
            if kind != wire.KIND_REQUEST:
                self._wire_errors.inc()
                await self._send(
                    writer, write_lock,
                    wire.encode_error(
                        "bad_frame",
                        f"clients send request frames; got kind {kind}",
                    ),
                )
                return
            await self._handle_request(payload, writer, write_lock, loop)

    async def _handle_request(self, payload, writer, write_lock, loop):
        self._requests.inc()
        # The id is echoed through int() on every reply path
        # (encode_response / encode_error), so a non-integer id must be
        # refused HERE as bad_request — discovering it at respond time
        # would kill the reply task after the request already settled,
        # and the client would never get a frame.
        raw_id = payload.get("id", 0)
        try:
            request_id = int(raw_id)
        except (TypeError, ValueError):
            await self._send(
                writer, write_lock,
                wire.encode_error(
                    "bad_request",
                    f"request id must be an integer; got {raw_id!r}",
                ),
            )
            return
        try:
            market = payload["market"]
            signals = [
                (sid, prob) for sid, prob in payload["signals"]
            ]
            outcome = bool(payload["outcome"])
            qos_class = payload.get("class")
            future = self._service.submit(
                market, signals, outcome, qos_class=qos_class
            )
        except ServeError as exc:
            # Admission said no: an answer on a healthy connection.
            await self._send(
                writer, write_lock, _error_frame_for(exc, request_id)
            )
            return
        except (KeyError, TypeError, ValueError) as exc:
            await self._send(
                writer, write_lock,
                wire.encode_error(
                    "bad_request", repr(exc), request_id=request_id
                ),
            )
            return
        # Pipelining: the reader moves on; this task replies when the
        # settlement resolves. Submission order (= wire arrival order)
        # already fixed the request's place in the batch trace.
        self._track(
            loop.create_task(
                self._respond(future, request_id, writer, write_lock)
            )
        )

    async def _respond(self, future, request_id, writer, write_lock):
        try:
            result = await future
        except asyncio.CancelledError:
            raise
        except BaseException as exc:  # noqa: BLE001 — refusals ride frames
            await self._send(
                writer, write_lock, _error_frame_for(exc, request_id)
            )
            return
        self._responses.inc()
        await self._send(
            writer, write_lock, wire.encode_response(request_id, result)
        )

    async def _send(self, writer, write_lock, frame: bytes) -> None:
        try:
            async with write_lock:
                writer.write(frame)
                await writer.drain()
        except (ConnectionError, OSError):
            pass  # peer gone mid-reply; its future already resolved
        else:
            # Header byte 5 is the kind field (magic 4s, version B, kind B).
            if frame[5] == wire.KIND_ERROR:
                self._errors.inc()
