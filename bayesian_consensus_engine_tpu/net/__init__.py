"""net/ — the stdlib network front door (round 17).

The serving tier's missing transport: until this package, "millions of
clients" meant one Python process calling
:meth:`~.serve.coalesce.ConsensusService.submit` on its own event loop.
``net`` puts a real multi-client socket protocol in front of the SAME
service without forking its batching discipline:

* :mod:`~.net.wire` — the sans-IO framed codec: length-prefixed frames
  with a versioned header, payload CRC, canonical-JSON payloads, and
  explicit error frames (``overloaded``/``shed``/``closed``/``failed``
  plus the transport tier: ``bad_frame``/``version_mismatch``/
  ``oversized``).
* :mod:`~.net.server` — :class:`ConsensusServer`: N asyncio acceptor
  tasks over one listening socket, each connection's requests submitted
  in wire arrival order into the ONE existing coalescer, responses
  pipelined back in completion order by request id. Framing violations
  kill only the offending connection.
* :mod:`~.net.client` — :class:`ConsensusClient`: a blocking client for
  tests, bench load generators, and the CLI; raises the same
  serve-layer exceptions as in-process ``submit``.

The byte contract is the headline: the same admitted-request trace
served over the wire and submitted in-process yields identical results,
journal epoch payloads (wall_ts masked), and SQLite bytes — flat AND
sharded-resident (tests/test_net.py). Layer tier of ``serve`` in the
lint map; engine tiers never import ``net``.
"""

from bayesian_consensus_engine_tpu.net.client import ConsensusClient
from bayesian_consensus_engine_tpu.net.server import ConsensusServer
from bayesian_consensus_engine_tpu.net.wire import (
    MAX_FRAME_BYTES,
    WIRE_VERSION,
    WireError,
)

__all__ = [
    "ConsensusClient",
    "ConsensusServer",
    "MAX_FRAME_BYTES",
    "WIRE_VERSION",
    "WireError",
]
