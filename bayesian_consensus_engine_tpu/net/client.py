"""Blocking client for the network front door — the test/bench/CLI half.

:class:`ConsensusClient` speaks the :mod:`~.net.wire` protocol over one
TCP connection with plain blocking sockets (no asyncio on the client
side: load generators are threads, tests are synchronous, and the CLI
is a script). Two calling shapes:

* :meth:`submit` — one request, wait for its frame: the closed-loop
  client. Raises exactly what in-process ``submit`` would have raised
  (:class:`~.serve.admission.Overloaded` with its retry hint,
  :class:`~.serve.admission.ShedError`,
  :class:`~.serve.admission.ServiceClosed`) — the wire adds transport,
  not semantics.
* :meth:`submit_pipelined` — send a whole request list back to back,
  THEN collect responses: the trace driver. Responses arrive in
  completion order and are matched by request id; the return list is in
  REQUEST order with each slot a :class:`~.serve.coalesce.ServeResult`
  or the mapped exception instance (refusals are data when you offered
  a burst on purpose). This is how a deterministic submission trace is
  offered over the wire — a closed loop per request could never fill a
  coalescing window.

Framing violations from the server arrive as explicit error frames and
raise :class:`~.net.wire.WireError`; a connection that dies mid-frame
raises :class:`~.net.wire.TruncatedFrame`.
"""

from __future__ import annotations

import socket
from typing import Dict, List, Optional, Sequence, Union

from bayesian_consensus_engine_tpu.net import wire
from bayesian_consensus_engine_tpu.serve.coalesce import ServeResult


def _result_from_payload(payload) -> ServeResult:
    return ServeResult(
        market_id=payload["market"],
        consensus=float(payload["consensus"]),
        batch_index=int(payload["batch"]),
        band_lo=payload.get("band_lo"),
        band_hi=payload.get("band_hi"),
        band_stderr=payload.get("band_stderr"),
        propagated=payload.get("propagated"),
    )


class ConsensusClient:
    """One blocking connection to a :class:`~.net.server.ConsensusServer`.

    ``timeout`` bounds every socket operation (None blocks forever —
    fine for tests, unkind in production loops). Use as a context
    manager or call :meth:`close`.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        timeout: Optional[float] = 30.0,
        max_frame_bytes: int = wire.MAX_FRAME_BYTES,
    ) -> None:
        self._max_frame_bytes = int(max_frame_bytes)
        self._next_id = 0
        self._sock = socket.create_connection((host, port), timeout=timeout)
        # One request is tiny; batching happens server-side in the
        # coalescer, so trade throughput for latency on the socket.
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    # -- raw frame IO (also used by the robustness tests) --------------------

    def _recv_exactly(self, n: int) -> bytes:
        chunks = []
        remaining = n
        while remaining:
            chunk = self._sock.recv(remaining)
            if not chunk:
                raise wire.TruncatedFrame(
                    f"connection closed {remaining} bytes short of a frame"
                )
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)

    def send_frame(self, frame: bytes) -> None:
        self._sock.sendall(frame)

    def read_frame(self):
        """One ``(kind, payload)`` off the wire; raises on violations."""
        kind, length, crc = wire.decode_header(
            self._recv_exactly(wire.HEADER.size), self._max_frame_bytes
        )
        return kind, wire.decode_payload(self._recv_exactly(length), crc)

    # -- request/response ----------------------------------------------------

    def submit(
        self,
        market_id: str,
        signals: Sequence,
        outcome: bool,
        qos_class: Optional[str] = None,
    ) -> ServeResult:
        """One request, one settled result — or the mapped refusal."""
        (result,) = self.submit_pipelined(
            [(market_id, signals, outcome)], qos_class=qos_class,
            return_exceptions=False,
        )
        return result

    def submit_pipelined(
        self,
        requests: Sequence,
        qos_class: Optional[str] = None,
        return_exceptions: bool = True,
    ) -> List[Union[ServeResult, BaseException]]:
        """Send every ``(market_id, signals, outcome)`` (or 4-tuple with
        a per-request class overriding *qos_class*) back to back, then
        collect all responses. Returns request-ordered results; with
        ``return_exceptions=False`` the first refusal raises instead."""
        ids: List[int] = []
        for request in requests:
            if len(request) == 4:
                market_id, signals, outcome, cls = request
            else:
                market_id, signals, outcome = request
                cls = qos_class
            request_id = self._next_id
            self._next_id += 1
            ids.append(request_id)
            self.send_frame(
                wire.encode_request(
                    market_id, signals, outcome,
                    qos_class=cls, request_id=request_id,
                )
            )
        by_id: Dict[int, Union[ServeResult, BaseException]] = {}
        want = set(ids)
        while want:
            kind, payload = self.read_frame()
            request_id = payload.get("id")
            if kind == wire.KIND_RESPONSE:
                outcome_value: Union[ServeResult, BaseException] = (
                    _result_from_payload(payload)
                )
            elif kind == wire.KIND_ERROR:
                try:
                    wire.raise_error_payload(payload)
                except BaseException as exc:  # noqa: BLE001 — data here
                    outcome_value = exc
                if request_id is None:
                    # A connection-scoped error frame (framing violation):
                    # nothing request-shaped is coming after it.
                    raise outcome_value
            else:
                raise wire.WireError(
                    f"unexpected frame kind {kind} from server"
                )
            if request_id in want:
                want.discard(request_id)
                by_id[request_id] = outcome_value
        ordered = [by_id[i] for i in ids]
        if not return_exceptions:
            for value in ordered:
                if isinstance(value, BaseException):
                    raise value
        return ordered

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ConsensusClient":
        return self

    def __exit__(self, *exc_info: object) -> None:
        self.close()
