"""Length-prefixed wire codec for the network front door: framed
request/response with a versioned header, CRC, and explicit error frames.

The serving byte contract ("same admitted-request trace, same settled
bytes") only survives a network hop if the transport can NEVER corrupt a
request silently and can never take the service down with a malformed
one. This module is the sans-IO half of that promise — pure
bytes-in/bytes-out, no sockets — shared verbatim by the asyncio server
(:mod:`~.net.server`) and the blocking client (:mod:`~.net.client`):

* **Frame layout** (little-endian): a fixed :data:`HEADER` —
  ``magic(4s) version(B) kind(B) reserved(H) payload_len(I) crc32(I)`` —
  followed by ``payload_len`` bytes of payload. The CRC covers the
  payload only (the header is validated field by field); a frame whose
  CRC disagrees raises :class:`ChecksumMismatch` and the connection
  dies — the request is never guessed at.
* **Versioned**: the header carries :data:`WIRE_VERSION`. A peer
  speaking a different version gets an explicit ``version_mismatch``
  ERROR frame (encoded at THEIR lowest common denominator: the error
  frame layout is the part of the protocol that must outlive version
  bumps) and a clean close, never a silent misparse.
* **Bounded**: ``payload_len`` above ``max_frame_bytes`` raises
  :class:`FrameTooLarge` BEFORE any allocation — a hostile length
  prefix cannot balloon server memory.
* **Payloads are canonical JSON** (sorted keys, fixed separators — the
  DT203 discipline on the wire): two encoders given the same request
  produce identical frame bytes, which is what lets tests pin recorded
  wire traffic.
* **Errors are frames, not disconnects**: admission refusals
  (``overloaded`` with its retry-after hint, ``shed``), service
  shutdown (``closed``), dispatch failures (``failed``), and transport
  violations (``bad_frame``/``version_mismatch``/``oversized``) all
  travel as kind-:data:`KIND_ERROR` frames so a client can distinguish
  backpressure from breakage. The mapping back to the serve-layer
  exceptions lives in :func:`raise_error_payload`.

Stdlib-only (``struct`` + ``zlib.crc32`` + ``json``), layer tier of
``serve`` in the lint map: the engine tiers never import ``net``.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Mapping, Optional, Tuple

#: Bump on any header or payload-schema change; the handshake is the
#: header itself (every frame carries the version).
WIRE_VERSION = 1

MAGIC = b"BCEW"

#: magic(4s) version(B) kind(B) reserved(H) payload_len(I) crc32(I)
HEADER = struct.Struct("<4sBBHII")

#: Frame kinds. Requests flow client → server, responses and errors
#: server → client. The vocabulary is deliberately tiny: everything
#: request-shaped rides in the JSON payload, so new fields never need a
#: new kind.
KIND_REQUEST = 1
KIND_RESPONSE = 2
KIND_ERROR = 3

_KINDS = (KIND_REQUEST, KIND_RESPONSE, KIND_ERROR)

#: Default refusal bound for one frame's payload. A request is one
#: market's signal list — far below this; the bound exists so a hostile
#: (or corrupted) length prefix is refused before allocation.
MAX_FRAME_BYTES = 1 << 20

#: Error payload ``code`` vocabulary (the wire analogue of the SLO
#: outcome vocabulary — every refusal names its policy).
ERROR_CODES = (
    "overloaded",   # admission bound under reject policy; retry_after_s rides
    "shed",         # this request was admitted then shed under overload
    "closed",       # service is draining/closed
    "failed",       # dispatch/journal failure ate the batch
    "bad_request",  # request payload did not validate
    "bad_frame",    # magic/CRC/framing violation — connection closes
    "version_mismatch",  # peer speaks a different WIRE_VERSION
    "oversized",    # payload_len exceeded the server's bound
)


class WireError(ValueError):
    """Base class for framing violations (the connection-fatal tier)."""


class BadMagic(WireError):
    """The stream does not start with a frame (desync or not our peer)."""


class VersionMismatch(WireError):
    def __init__(self, got: int, expected: int = WIRE_VERSION) -> None:
        super().__init__(
            f"wire version mismatch: peer speaks v{got}, this end v{expected}"
        )
        self.got = got
        self.expected = expected


class FrameTooLarge(WireError):
    def __init__(self, length: int, bound: int) -> None:
        super().__init__(
            f"frame payload of {length} bytes exceeds the {bound}-byte bound"
        )
        self.length = length
        self.bound = bound


class ChecksumMismatch(WireError):
    """Payload bytes disagree with the header CRC — torn or corrupted."""


class TruncatedFrame(WireError):
    """The stream ended mid-frame (torn write / peer died mid-send)."""


def encode_frame(kind: int, payload: Mapping[str, object]) -> bytes:
    """One complete frame: header + canonical-JSON payload bytes."""
    if kind not in _KINDS:
        raise ValueError(f"unknown frame kind {kind}")
    body = json.dumps(
        payload, sort_keys=True, separators=(",", ":")
    ).encode("utf-8")
    return HEADER.pack(
        MAGIC, WIRE_VERSION, kind, 0, len(body), zlib.crc32(body)
    ) + body


def decode_header(
    header: bytes, max_frame_bytes: int = MAX_FRAME_BYTES
) -> Tuple[int, int, int]:
    """Validate one header; returns ``(kind, payload_len, crc32)``.

    Raises the specific :class:`WireError` subclass the server turns
    into its explicit error frame: :class:`BadMagic`,
    :class:`VersionMismatch`, :class:`FrameTooLarge`, or a generic
    :class:`WireError` for an unknown kind. Version is checked BEFORE
    the kind — a future version may well add kinds, and the peer
    deserves the precise refusal.
    """
    if len(header) != HEADER.size:
        raise TruncatedFrame(
            f"header is {len(header)} bytes; need {HEADER.size}"
        )
    magic, version, kind, _reserved, length, crc = HEADER.unpack(header)
    if magic != MAGIC:
        raise BadMagic(f"bad frame magic {magic!r}")
    if version != WIRE_VERSION:
        raise VersionMismatch(version)
    if kind not in _KINDS:
        raise WireError(f"unknown frame kind {kind}")
    if length > max_frame_bytes:
        raise FrameTooLarge(length, max_frame_bytes)
    return kind, length, crc


def decode_payload(payload: bytes, crc: int) -> dict:
    """CRC-check and parse one frame's payload bytes."""
    if zlib.crc32(payload) != crc:
        raise ChecksumMismatch(
            "frame payload fails its CRC — torn or corrupted"
        )
    try:
        decoded = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise WireError(f"frame payload is not JSON: {exc}") from exc
    if not isinstance(decoded, dict):
        raise WireError("frame payload must be a JSON object")
    return decoded


# -- request / response / error payload shapes --------------------------------


def encode_request(
    market_id: str,
    signals,
    outcome: bool,
    qos_class: Optional[str] = None,
    request_id: int = 0,
) -> bytes:
    """One submit as a frame. ``signals`` is the serve layer's shape —
    ``(source_id, probability)`` pairs or reference payload dicts —
    normalised here to pairs so the frame bytes are canonical."""
    pairs = []
    for signal in signals:
        if isinstance(signal, Mapping):
            pairs.append(
                [str(signal["sourceId"]), float(signal["probability"])]
            )
        else:
            sid, prob = signal
            pairs.append([str(sid), float(prob)])
    payload = {
        "id": int(request_id),
        "market": str(market_id),
        "signals": pairs,
        "outcome": bool(outcome),
    }
    if qos_class is not None:
        payload["class"] = str(qos_class)
    return encode_frame(KIND_REQUEST, payload)


def encode_response(request_id: int, result) -> bytes:
    """A settled :class:`~.serve.coalesce.ServeResult` as a frame."""
    return encode_frame(
        KIND_RESPONSE,
        {
            "id": int(request_id),
            "market": result.market_id,
            "consensus": result.consensus,
            "batch": result.batch_index,
            "band_lo": result.band_lo,
            "band_hi": result.band_hi,
            "band_stderr": result.band_stderr,
            "propagated": result.propagated,
        },
    )


def encode_error(
    code: str,
    message: str,
    request_id: Optional[int] = None,
    retry_after_s: Optional[float] = None,
    pending: Optional[int] = None,
) -> bytes:
    """An explicit refusal/failure frame (``code`` ∈ :data:`ERROR_CODES`)."""
    if code not in ERROR_CODES:
        raise ValueError(f"error code must be one of {ERROR_CODES}; got {code!r}")
    payload: dict = {"code": code, "message": str(message)}
    if request_id is not None:
        payload["id"] = int(request_id)
    if retry_after_s is not None:
        payload["retry_after_s"] = float(retry_after_s)
    if pending is not None:
        payload["pending"] = int(pending)
    return encode_frame(KIND_ERROR, payload)


def raise_error_payload(payload: Mapping[str, object]) -> None:
    """Lift an ERROR frame back into the serve-layer exception the
    in-process ``submit`` would have raised — the client-side half of
    "the wire adds transport, not semantics". Transport-tier codes
    (``bad_frame``/``version_mismatch``/``oversized``) raise
    :class:`WireError`; unknown codes raise the base
    :class:`~.serve.admission.ServeError` so a newer server never
    crashes an older client with a KeyError.
    """
    from bayesian_consensus_engine_tpu.serve.admission import (
        Overloaded,
        ServeError,
        ServiceClosed,
        ShedError,
    )

    code = payload.get("code")
    message = str(payload.get("message", ""))
    if code == "overloaded":
        raise Overloaded(
            float(payload.get("retry_after_s") or 0.0),
            int(payload.get("pending") or 0),
        )
    if code == "shed":
        raise ShedError(message or "request shed under overload")
    if code == "closed":
        raise ServiceClosed(message or "service closed")
    if code in ("bad_frame", "version_mismatch", "oversized"):
        raise WireError(f"{code}: {message}")
    raise ServeError(f"{code}: {message}")
