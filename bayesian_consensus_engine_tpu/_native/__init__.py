"""Native (C) host-path accelerators. Built on demand by native/build.py;
everything here has a pure-Python fallback in the importing module."""
