"""bayesian_consensus_engine_tpu — TPU-native reliability-weighted consensus.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
consensus-nexus/bayesian-consensus-engine: reliability-weighted consensus over
probability signals for prediction markets, with persistent per-(source,
market) reliability that updates after outcomes and decays over time.

Layers (bottom → top), mirroring the reference's layer map but TPU-first:

  utils/     constants (public contract) + id interning + time conversion
  ops/       pure JAX kernels: batched consensus, decay, update, tiebreak,
             Pallas fused fast path
  core/      validation + scalar bit-exact engine + batched array engine
  state/     reliability stores: SQLite (durable/compat), device-tensor (HBM)
  models/    market orchestration, cross-market aggregation, tie-breaking
  parallel/  device mesh + shard_map sharded consensus/update step
  analytics/ additive device-resident analytics: uncertainty bands +
             correlated-market consensus (graph-propagated)
  cluster/   multi-host membership views (epoch-tagged, coordinator-free)
             + journal-driven degraded-mesh recovery
  pipeline   payloads → plan → device settle → store → SQLite, end to end
             (sessions, the streamed service loop, mesh/band sharding)
  serve/     online micro-batch coalescing front end over the session
             + multi-tenant per-class QoS (variance-aware shedding)
  net/       stdlib socket front door: framed wire codec, N-acceptor
             server feeding the one coalescer, blocking client
  replay/    counterfactual replay lab: journal trace sidecars re-driven
             under K altered configs via one vmapped settlement program
  cli        command-line surface (byte-compatible with the reference CLI)

The scalar path imports no JAX; array paths import it lazily.
"""

__version__ = "0.1.0"
