"""Command-line surface — byte-compatible with the reference CLI.

Same contract as the reference (reference: src/bayesian_engine/cli.py):

    bce-tpu [--db PATH] [--dry-run] [--input FILE] [COMMAND ...]

Subcommands: ``consensus``, ``report-outcome``, ``list-sources``; invoking
with no subcommand runs legacy consensus on ``--input``/stdin without a DB.
Output is pretty-printed JSON on stdout; validation errors go to stderr with
exit code 1. Preserved quirks: ``--input`` exists both top-level and on the
consensus subcommand (quirk #13); DB subcommand failures print ``Error: ...``
and exit 1.

Extensions (additive, do not change reference-shaped outputs): ``--backend
{python,jax,tpu}`` selects the consensus engine implementation;
``journal-export JRNL`` replays a ``settle_stream`` durability journal
(state/journal.py) and exports the reference-compatible SQLite file to
``--db`` — the crash-recovery path without writing Python; ``serve``
runs the round-17 network front door — the net/ socket server over the
coalescing ``ConsensusService``, with repeated ``--qos`` specs
declaring multi-tenant classes (per-class SLO/budget/policy) — printing
a banner JSON line on bind and a per-class goodput summary on exit;
``replay`` re-drives a recorded journal's trace sidecar under K
altered parameter configs through one vmapped settlement program
(replay/ — lane 0 reproduces the recorded run byte-for-byte and can
export its SQLite file to ``--db``); ``lint`` runs
graftlint, the repo's JAX/determinism/layering static analysis
(docs/static-analysis.md); ``stats`` renders an obs run ledger
(obs/ledger.py JSONL — the min-of-N bench discipline) as per-leg bands
and, with ``--live URL``, a running telemetry exporter's scraped
snapshot + health verdict (obs/export.py) beside them; ``trace``
converts a request-tracing span log (obs/trace.py JSONL) to
Chrome/Perfetto trace-event JSON; ``bank`` round-trips the versioned
autotune bank (utils/autotune.py — shippable kernel-knob verdicts keyed
by device generation and shape, loadable via ``BCE_AUTOTUNE_BANK``).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
from typing import Any

from bayesian_consensus_engine_tpu.core import (
    ValidationError,
    compute_consensus,
    validate_input_payload,
)
from bayesian_consensus_engine_tpu.state import SQLiteReliabilityStore


def _read_payload(input_path: str | None) -> dict[str, Any]:
    """Load the JSON payload from a file or stdin (reference: cli.py:14-22)."""
    if input_path:
        with open(input_path, "r", encoding="utf-8") as f:
            return json.load(f)
    if sys.stdin.isatty():
        raise ValidationError("Input required: provide --input <file> or JSON via stdin")
    return json.load(sys.stdin)


def _emit(document: dict[str, Any]) -> None:
    print(json.dumps(document, indent=2))


def _run_consensus(args: argparse.Namespace) -> None:
    try:
        payload = _read_payload(args.input)
        validate_input_payload(payload)

        source_reliability = None
        if args.db:
            with SQLiteReliabilityStore(args.db) as store:
                source_reliability = {}
                for signal in payload.get("signals", []):
                    sid = signal.get("sourceId")
                    if sid:
                        record = store.get_reliability(
                            sid, payload["marketId"], apply_decay=True
                        )
                        source_reliability[sid] = {
                            "reliability": record.reliability,
                            "confidence": record.confidence,
                        }

        result = compute_consensus(
            payload["signals"], source_reliability, backend=args.backend
        )
        if args.dry_run:
            result["diagnostics"]["dryRun"] = True
        _emit(result)
    except (json.JSONDecodeError, ValidationError) as exc:
        print(f"Validation error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc
    except NotImplementedError as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc


def _run_legacy_consensus(args: argparse.Namespace) -> None:
    """No-subcommand mode: consensus from --input/stdin, no DB lookups."""
    try:
        payload = _read_payload(args.input)
        validate_input_payload(payload)
        result = compute_consensus(payload["signals"], backend=args.backend)
        if args.dry_run:
            result["diagnostics"]["dryRun"] = True
        _emit(result)
    except (json.JSONDecodeError, ValidationError) as exc:
        print(f"Validation error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc
    except NotImplementedError as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc


def _run_report_outcome(args: argparse.Namespace) -> None:
    if not args.db:
        print("Error: --db is required for report-outcome", file=sys.stderr)
        raise SystemExit(1)
    try:
        with SQLiteReliabilityStore(args.db) as store:
            record = store.update_reliability(
                source_id=args.source_id,
                market_id=args.market_id,
                outcome_correct=args.correct,
                dry_run=args.dry_run,
            )
        _emit(
            {
                "sourceId": record.source_id,
                "marketId": record.market_id,
                "reliability": record.reliability,
                "confidence": record.confidence,
                "updatedAt": record.updated_at,
                "dryRun": args.dry_run,
            }
        )
    except Exception as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc


def _run_journal_export(args: argparse.Namespace) -> None:
    """Replay a durability journal and export the SQLite interchange file.

    Additive maintenance command (no reference counterpart): the
    crash-recovery path for a service that streamed with
    ``settle_stream(journal=...)`` — replay the fsynced epochs (torn
    tails dropped), report the durable watermark, and write the
    reference-compatible SQLite file. Honors the global ``--dry-run``
    (replay + report, never write) and ``--db`` (the export target).
    """
    if not args.db and not args.dry_run:
        print(
            "Error: --db is required for journal-export (or use --dry-run)",
            file=sys.stderr,
        )
        raise SystemExit(1)
    if (
        not args.dry_run
        and os.path.exists(args.db)
        and os.path.getsize(args.db) > 0
    ):
        # An export must EQUAL the recovered journal state; flushing into
        # an existing file would UPSERT-merge and leave stale rows the
        # journal never held.
        print(
            f"Error: export target {args.db} already exists — "
            "journal-export writes a fresh interchange file",
            file=sys.stderr,
        )
        raise SystemExit(1)
    try:
        from bayesian_consensus_engine_tpu.state.journal import replay_journal

        store, tag = replay_journal(args.journal)
        rows = store.live_row_count()
        exported = None
        if not args.dry_run:
            store.flush_to_sqlite(args.db)
            exported = args.db
        _emit(
            {
                "journal": args.journal,
                "epochTag": tag,
                "rows": rows,
                "exportedTo": exported,
                "dryRun": args.dry_run,
            }
        )
    except Exception as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc


def _run_list_sources(args: argparse.Namespace) -> None:
    if not args.db:
        print("Error: --db is required for list-sources", file=sys.stderr)
        raise SystemExit(1)
    try:
        with SQLiteReliabilityStore(args.db) as store:
            records = store.list_sources(market_id=args.market_id)
        _emit(
            {
                "sources": [
                    {
                        "sourceId": r.source_id,
                        "marketId": r.market_id,
                        "reliability": r.reliability,
                        "confidence": r.confidence,
                        "updatedAt": r.updated_at,
                    }
                    for r in records
                ],
                "count": len(records),
            }
        )
    except Exception as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc


def _parse_replay_config(spec: str):
    """``field=value[,field=value...]`` → :class:`~.replay.ReplayConfig`.

    Fields are the sweep's knobs (``half_life_days``, ``decay_floor``,
    ``base_learning_rate``, ``max_update_step``, ``band_z``,
    ``graph_damping``, ``graph_steps``, ``graph_tol``); unnamed
    fields keep the
    recorded constants, so ``--configs half_life_days=20`` is "the live
    run, but with a 20-day decay half-life".
    """
    from bayesian_consensus_engine_tpu.replay import ReplayConfig

    kwargs: dict[str, Any] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, eq, value = part.partition("=")
        name = name.strip()
        if not eq or name not in ReplayConfig._fields:
            raise ValueError(
                f"--configs takes field=value pairs over "
                f"{', '.join(ReplayConfig._fields)}; got {part!r}"
            )
        kwargs[name] = (
            int(value) if name == "graph_steps" else float(value)
        )
    return ReplayConfig(**kwargs)


def _run_replay(args: argparse.Namespace) -> None:
    """Counterfactual replay: re-drive a journal's trace under K configs.

    Loads the trace sidecar of one recorded journal (or the merged
    trace of several fleet band journals), then runs one
    :func:`~.replay.replay_sweep` — every ``--configs`` spec is a lane
    beside the always-present recorded lane 0, all lanes advancing
    through one vmapped settlement program per batch. Prints a per-lane
    sweep table (markets settled, Brier mean, credible band width) with
    each lane's Brier diffed against the recorded lane, ``--against``-
    style (``brier recorded->lane``); ``--json`` emits the machine
    document instead. The global ``--db`` exports lane 0's rebuilt
    state as the SQLite interchange file (refused if the target exists,
    like ``journal-export``; ``--dry-run`` skips the write), and the
    printed lane-0 ``digest`` is the byte-contract witness — equal to
    the live run's store digest by construction.

    ``--strict`` refuses a torn trace tail
    (:class:`~.state.journal.TornTraceError`) instead of replaying to
    the last joined epoch. Lanes with ``graph_steps > 0`` need the
    market graph the relaxation runs over — that sweep is Python-API
    only (:func:`~.replay.replay_sweep` with ``graph=``).
    """
    try:
        configs = [_parse_replay_config(s) for s in (args.configs or [])]
    except ValueError as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc
    if any(config.graph_steps > 0 for config in configs):
        print(
            "Error: graph_steps > 0 needs a MarketGraph — use the "
            "Python API (replay.replay_sweep(..., graph=...))",
            file=sys.stderr,
        )
        raise SystemExit(1)
    export_db = args.db if not args.dry_run else None
    if (
        export_db
        and os.path.exists(export_db)
        and os.path.getsize(export_db) > 0
    ):
        print(
            f"Error: export target {export_db} already exists — "
            "replay writes a fresh interchange file",
            file=sys.stderr,
        )
        raise SystemExit(1)
    try:
        from bayesian_consensus_engine_tpu.replay import (
            RECORDED_CONFIG,
            load_cluster_trace,
            load_trace,
            replay_sweep,
        )

        if len(args.journals) == 1:
            trace = load_trace(args.journals[0], strict=args.strict)
        else:
            trace = load_cluster_trace(args.journals, strict=args.strict)
        result = replay_sweep(trace, configs, db_path=export_db)
    except Exception as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc

    recorded = result.lanes[0]

    def delta_fields(config) -> dict[str, Any]:
        return {
            field: getattr(config, field)
            for field in RECORDED_CONFIG._fields
            if getattr(config, field) != getattr(RECORDED_CONFIG, field)
        }

    if args.json:
        _emit(
            {
                "journals": list(args.journals),
                "batches": result.batches,
                "digest": result.digest,
                "resultDigest": result.result_digest,
                "exportedTo": export_db,
                "dryRun": args.dry_run,
                "lanes": [
                    {
                        "config": dict(lane.config._asdict()),
                        "delta": delta_fields(lane.config),
                        "marketsSettled": lane.markets_settled,
                        "brierMean": lane.brier_mean,
                        "bandWidthMean": lane.band_width_mean,
                        "graphBrierMean": lane.graph_brier_mean,
                    }
                    for lane in result.lanes
                ],
            }
        )
        return

    def num(x: float) -> str:
        return f"{x:.4g}" if x == x else "-"  # NaN when nothing settled

    print(
        f"{', '.join(args.journals)}: {result.batches} batches, "
        f"{len(result.lanes)} lanes, lane-0 digest {result.digest}"
    )
    print(
        f"{'lane':>4} {'settled':>8} {'brier':>9} {'band_w':>9} "
        f"{'g_brier':>9}  config"
    )
    for index, lane in enumerate(result.lanes):
        delta = delta_fields(lane.config)
        label = (
            "recorded"
            if not delta
            else ",".join(f"{k}={v:g}" for k, v in sorted(delta.items()))
        )
        trailer = (
            f"  brier {num(recorded.brier_mean)}->{num(lane.brier_mean)}"
            if index else ""
        )
        print(
            f"{index:>4} {lane.markets_settled:>8} "
            f"{num(lane.brier_mean):>9} {num(lane.band_width_mean):>9} "
            f"{num(lane.graph_brier_mean):>9}  {label}{trailer}"
        )
    if export_db:
        print(f"exported lane-0 state to {export_db}")


def _run_stats(args: argparse.Namespace) -> None:
    """Render an obs run ledger (bench/soak JSONL) as per-leg bands.

    The reading half of the min-of-N discipline: every bench/soak capture
    appends ledger records (``bench.py --ledger``, obs/ledger.py); this
    subcommand folds one file into per-leg min/max bands with the host
    load that attributes the spread. Legs whose records carry per-request
    latency distributions (``extras.latency_hist`` — the serving bench)
    additionally render ``p50``/``p99`` columns, merged across repeats
    through the shared log-bucket quantile rule; legs that sample the
    device allocator's high-water mark into ``extras.hbm_peak_bytes``
    (the ring-memory leg, on devices exposing allocator stats) render
    the ``peak_mem`` column (min across repeats), so a memory regression
    shows up in the same table as a wall-time one; legs carrying the
    per-settle bytes-read capture (``extras.hbm_read_bytes`` — the
    round-14 one-pass legs: args + temps of the AOT settle executable
    that ran) render the ``hbm_read`` column the same way; legs carrying
    pair-interning seconds (``extras.intern_s`` — the round-15 ingest/
    stream/serve legs) render the ``intern`` column beside ``ingest_w``
    (the delta-interning signal: the slice of ingest that cannot
    overlap, driven toward zero for drifting topologies); legs carrying
    recovery accounting (``extras.recovery_s`` + ``extras.slo`` — the
    kill-soak leg) render the ``recovery`` column beside ``goodput``,
    the failure story in one row; SLO-carrying legs (the serve and
    kill-soak legs) additionally render the ``slo`` column — the
    absolute offered-but-not-met count beside the goodput fraction,
    diffed by ``--against`` like ``hbm_read``. ``--json`` emits the
    machine-shaped summary instead of the table.

    ``--against OLD.jsonl`` switches to cross-round diffing: each leg's
    band is compared against the old ledger's and flagged when the bands
    stopped overlapping (``shifted_up``/``shifted_down`` — the
    regression signal the VERDICT previously extracted by hand; which
    direction is the regression depends on the leg's unit).

    ``--live URL`` scrapes a running telemetry exporter
    (obs/export.py — the ``/snapshot`` and ``/healthz`` endpoints a
    ``ConsensusService.start_telemetry`` or kill-soak worker serves) and
    renders the live counters/gauges/latency histograms, phase sums, and
    burn-rate health verdict — next to the ledger bands when a ledger is
    also given, alone otherwise (the ledger argument becomes optional).
    """
    from bayesian_consensus_engine_tpu.obs.ledger import (
        diff_bands,
        read_ledger,
        render,
        render_diff,
        summarize,
    )

    if args.ledger is None and not args.live:
        print("Error: give a ledger path and/or --live URL", file=sys.stderr)
        raise SystemExit(1)
    if args.against and args.ledger is None:
        # Diffing needs a NEW ledger to stand on — a live scrape is not
        # band-shaped data, and diffing OLD against nothing would render
        # every leg as removed.
        print(
            "Error: --against diffs two ledgers — give the new ledger "
            "path too",
            file=sys.stderr,
        )
        raise SystemExit(1)

    live_snapshot = live_health = None
    if args.live:
        from bayesian_consensus_engine_tpu.obs.export import scrape_endpoint

        base = args.live.rstrip("/")
        try:
            _status, live_snapshot = scrape_endpoint(base + "/snapshot")
            # A 503 /healthz (burning/degraded) still carries the
            # verdict in its body — scrape_endpoint parses it either way.
            _status, live_health = scrape_endpoint(base + "/healthz")
        except (OSError, ValueError) as exc:
            print(f"Error: scrape of {base} failed: {exc}", file=sys.stderr)
            raise SystemExit(1) from exc

    try:
        records = read_ledger(args.ledger) if args.ledger else []
        old_records = (
            read_ledger(args.against) if args.against else None
        )
    except (OSError, ValueError) as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc
    if args.leg:
        records = [r for r in records if r.get("leg") == args.leg]
        if old_records is not None:
            old_records = [
                r for r in old_records if r.get("leg") == args.leg
            ]
    def _print_live() -> None:
        if live_snapshot is None:
            return
        from bayesian_consensus_engine_tpu.obs.export import (
            render_live_snapshot,
        )

        print(f"{args.live} (live)")
        print(render_live_snapshot(live_snapshot, live_health))

    live_payload = (
        {"url": args.live, "snapshot": live_snapshot,
         "healthz": live_health}
        if live_snapshot is not None else None
    )
    if old_records is not None:
        diff = diff_bands(old_records, records)
        if args.json:
            document = {"ledger": args.ledger, "against": args.against,
                        "legs": diff}
            if live_payload is not None:
                document["live"] = live_payload
            _emit(document)
        else:
            print(f"{args.ledger} vs {args.against}")
            print(render_diff(diff))
            _print_live()
        return
    if args.json:
        document = {"ledger": args.ledger, "records": len(records),
                    "legs": summarize(records)}
        if live_payload is not None:
            document["live"] = live_payload
        _emit(document)
    else:
        if args.ledger is not None:
            print(f"{args.ledger}: {len(records)} records")
            print(render(records))
        _print_live()


def _parse_qos_spec(spec: str):
    """``name:slo_s:max_pending[:policy[:burning]]`` → QosClass.

    The CLI shape of :class:`~.serve.admission.QosClass`:
    ``premium:0.05:512`` declares class *premium* with a 50 ms SLO and
    a 512-request budget (reject policy); a fourth field picks the
    overload policy (``reject``/``shed_oldest``) and a literal fifth
    field ``burning`` opts the class into shedding on its own burn-rate
    verdict (``shed_when_burning``).
    """
    from bayesian_consensus_engine_tpu.serve import QosClass

    parts = spec.split(":")
    if len(parts) < 3 or len(parts) > 5:
        raise ValueError(
            f"--qos takes name:slo_s:max_pending[:policy[:burning]]; "
            f"got {spec!r}"
        )
    if len(parts) == 5 and parts[4] != "burning":
        raise ValueError(
            f"--qos fifth field must be the literal 'burning'; got "
            f"{parts[4]!r}"
        )
    return QosClass(
        name=parts[0],
        slo_s=float(parts[1]),
        max_pending=int(parts[2]),
        policy=parts[3] if len(parts) > 3 else "reject",
        shed_when_burning=len(parts) == 5,
    )


def _run_serve(args: argparse.Namespace) -> None:
    """Run the network front door: socket server → coalescer → session.

    Composes the round-17 serving stack over one fresh in-memory store:
    a :class:`~.serve.coalesce.ConsensusService` (journal durability
    via ``--journal``, rolling SQLite via the global ``--db``, QoS
    classes via repeated ``--qos`` specs, a global SLO via
    ``--slo-ms``) behind a :class:`~.net.server.ConsensusServer`
    (``--port 0`` binds ephemeral), optionally exposing the live
    telemetry plane (``--telemetry-port``). Prints ONE banner JSON line
    (address, port, classes, telemetry URL) when the socket is bound —
    the line a launcher script parses — then serves for ``--duration``
    seconds (0 = until interrupted). On exit the service drains and
    closes (journal ends on a joined epoch) and a summary JSON document
    (requests, batches, per-class goodput) lands on stdout.
    """
    import asyncio

    try:
        qos = [_parse_qos_spec(spec) for spec in (args.qos or [])]
    except ValueError as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc

    from bayesian_consensus_engine_tpu import obs
    from bayesian_consensus_engine_tpu.net import ConsensusServer
    from bayesian_consensus_engine_tpu.obs.metrics import metrics_registry
    from bayesian_consensus_engine_tpu.serve import ConsensusService
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    # A live registry BEFORE the service binds its counters: without
    # one, every metric is the shared no-op (obs disabled by default),
    # the exit summary would report zeros, and --telemetry-port would
    # export an empty plane — the one process where obs is the product.
    obs.set_metrics_registry(obs.MetricsRegistry())
    store = TensorReliabilityStore()

    async def main() -> dict[str, Any]:
        service = ConsensusService(
            store,
            steps=args.steps,
            journal=args.journal,
            db_path=args.db,
            max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1e3,
            qos=qos or None,
            slo=(args.slo_ms / 1e3) if args.slo_ms else None,
        )
        telemetry_url = None
        if args.telemetry_port is not None:
            telemetry = service.start_telemetry(port=args.telemetry_port)
            telemetry_url = telemetry.url
        server = await ConsensusServer(
            service, host=args.host, port=args.port,
            acceptors=args.acceptors,
        ).start()
        banner = {
            "address": server.address,
            "port": server.port,
            "classes": [cls.name for cls in qos],
            "telemetry": telemetry_url,
        }
        print(json.dumps(banner, sort_keys=True), flush=True)
        # Ctrl-C must still land the exit summary: route SIGINT through
        # a stop event so the interrupted path drains and returns like
        # the --duration path, instead of cancelling main() before the
        # summary is built (the outer KeyboardInterrupt catch is only
        # the fallback for loops without signal-handler support).
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGINT, stop.set)
            sigint_routed = True
        except (NotImplementedError, RuntimeError, ValueError):
            sigint_routed = False
        try:
            if args.duration > 0:
                try:
                    await asyncio.wait_for(stop.wait(), args.duration)
                except asyncio.TimeoutError:
                    pass
            else:
                await stop.wait()  # until interrupted
        finally:
            if sigint_routed:
                loop.remove_signal_handler(signal.SIGINT)
            await server.close()
            await service.close()
        counters = metrics_registry().export().get("counters", {})
        return {
            "served": {
                "connections": counters.get("net.connections", 0),
                "requests": counters.get("net.requests", 0),
                "responses": counters.get("net.responses", 0),
                "wireErrors": counters.get("net.wire_errors", 0),
                "batches": counters.get("serve.batches", 0),
            },
            "goodputWithinSlo": (service.goodput() or {}).get(
                "goodput_within_slo"
            ),
            "qos": service.qos_snapshot(),
        }

    try:
        summary = asyncio.run(main())
    except KeyboardInterrupt:
        return
    except Exception as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc
    _emit(summary)


def _run_trace(args: argparse.Namespace) -> None:
    """Convert a tracer span log (JSONL) to Chrome trace-event JSON.

    The reading half of request-scoped tracing (obs/trace.py): a service
    dumps its span log with ``Tracer.write_jsonl``; this subcommand
    converts it to the Chrome trace-event format, which loads at
    https://ui.perfetto.dev (or ``chrome://tracing``) — host request/
    batch/journal spans on named lanes, viewable alongside a device
    profile captured with ``utils.profiling.trace``. Output keys are
    sorted (deterministic bytes for a deterministic span log, the DT203
    contract).
    """
    from bayesian_consensus_engine_tpu.obs.trace import (
        load_trace_jsonl,
        to_chrome_trace,
    )

    try:
        events = load_trace_jsonl(args.trace)
        document = to_chrome_trace(events)
        out = args.out or (args.trace + ".chrome.json")
        with open(out, "w", encoding="utf-8") as f:
            json.dump(document, f, sort_keys=True)
    except (OSError, ValueError) as exc:
        print(f"Error: {exc}", file=sys.stderr)
        raise SystemExit(1) from exc
    _emit(
        {
            "trace": args.trace,
            "events": len(events),
            "traceEvents": len(document["traceEvents"]),
            "out": out,
        }
    )


def _run_lint(args: argparse.Namespace) -> None:
    # Lazy import: the lint engine is tool code and the hot CLI paths
    # (consensus on stdin) should not pay for loading it.
    from bayesian_consensus_engine_tpu.lint import main as lint_main

    argv: list[str] = list(args.paths)
    if args.list_rules:
        argv.append("--list-rules")
    if args.format != "text":
        argv += ["--format", args.format]
    if args.select:
        argv += ["--select", args.select]
    if args.cache:
        argv += ["--cache", args.cache]
    raise SystemExit(lint_main(argv))


def _run_bank(args: argparse.Namespace) -> None:
    """Round-trip the versioned autotune bank (utils/autotune.py).

    ``export`` folds a host's honesty-guarded tuner cache into a
    shippable bank payload (verdicts keyed by knob/shape/device
    generation, evidence embedded); ``merge`` combines banks and REFUSES
    on a verdict flip (same identity, different adjudication — a human
    re-races, the tool never picks a side); ``show`` schema-validates a
    bank and renders its verdicts. A deployment loads a bank with
    ``BCE_AUTOTUNE_BANK=/path`` (or ``ShapeTuner(bank=...)``) and starts
    from the recorded decisions without re-racing.
    """
    # Lazy import: tool code, off the hot consensus path.
    from bayesian_consensus_engine_tpu.utils.autotune import (
        export_bank,
        load_bank,
        merge_banks,
        validate_bank,
    )

    def write_or_print(payload: dict) -> None:
        text = json.dumps(payload, indent=2, sort_keys=True)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as fh:
                fh.write(text + "\n")
            print(
                f"wrote {len(payload['entries'])} verdicts to {args.out}"
            )
        else:
            print(text)

    if args.bank_verb == "export":
        payload = export_bank(args.cache, device_kind=args.device_kind)
        if not payload["entries"]:
            print(
                "Error: no adjudicated verdicts to export (race some "
                "shapes with BCE_AUTOTUNE=1 first)",
                file=sys.stderr,
            )
            raise SystemExit(1)
        write_or_print(payload)
    elif args.bank_verb == "merge":
        payloads = []
        for path in args.banks:
            payload = load_bank(path)
            if payload is None:
                print(f"Error: {path} is not a valid bank", file=sys.stderr)
                raise SystemExit(1)
            payloads.append(payload)
        try:
            merged = merge_banks(*payloads)
        except ValueError as exc:
            print(f"Error: {exc}", file=sys.stderr)
            raise SystemExit(1) from exc
        write_or_print(merged)
    else:  # show
        try:
            with open(args.bank, encoding="utf-8") as fh:
                payload = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"Error: {exc}", file=sys.stderr)
            raise SystemExit(1) from exc
        errors = validate_bank(payload)
        if errors:
            for err in errors:
                print(f"Error: {err}", file=sys.stderr)
            raise SystemExit(1)
        print(f"{args.bank}: schema {payload['schema']}, "
              f"{len(payload['entries'])} verdicts")
        for entry in payload["entries"]:
            verdict = (
                "beat default" if entry["beat_default"] else "default held"
            )
            shape = ",".join(str(s) for s in entry["shape_key"])
            print(
                f"  {entry['generation']}  {entry['knob']}[{shape}] -> "
                f"{entry['choice']} ({verdict}; default "
                f"{entry['default']})"
            )


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="bce-tpu",
        description=(
            "Reliability-weighted consensus over probability signals, "
            "with persistent per-(source, market) track records — "
            "TPU-native engine, reference-compatible surface"
        ),
    )
    parser.add_argument(
        "--db",
        type=str,
        help="SQLite file holding reliability state (omit for an ephemeral run)",
    )
    parser.add_argument(
        "--dry-run",
        action="store_true",
        help="show what would change while guaranteeing the DB is never written",
    )
    parser.add_argument(
        "--input",
        type=str,
        help="read the consensus payload from this JSON file instead of stdin",
    )
    parser.add_argument(
        "--backend",
        choices=("python", "jax", "tpu"),
        default="python",
        help="which consensus engine runs the math (default: python, bit-exact)",
    )

    sub = parser.add_subparsers(dest="command", help="subcommands")

    consensus = sub.add_parser(
        "consensus",
        help="weigh a payload's signals into a consensus document",
    )
    consensus.add_argument(
        "--input", help="JSON payload file (stdin when omitted)"
    )
    consensus.set_defaults(handler=_run_consensus)

    outcome = sub.add_parser(
        "report-outcome",
        help="settle one source's prediction and adjust its reliability",
    )
    outcome.add_argument(
        "--source-id", required=True, help="which source to settle"
    )
    outcome.add_argument(
        "--market-id", required=True, help="the market the prediction was for"
    )
    outcome.add_argument(
        "--correct",
        action="store_true",
        help="the source called it right (omit for a wrong call)",
    )
    outcome.set_defaults(handler=_run_report_outcome)

    listing = sub.add_parser(
        "list-sources",
        help="dump every stored reliability record as JSON",
    )
    listing.add_argument(
        "--market-id", help="restrict the listing to one market"
    )
    listing.set_defaults(handler=_run_list_sources)

    journal = sub.add_parser(
        "journal-export",
        help=(
            "replay a settle_stream durability journal and export the "
            "SQLite interchange file to --db"
        ),
    )
    journal.add_argument(
        "journal", help="path to the journal written by settle_stream"
    )
    journal.set_defaults(handler=_run_journal_export)

    serve = sub.add_parser(
        "serve",
        help=(
            "run the network front door: a length-prefixed socket "
            "server (net/) over the coalescing consensus service, with "
            "optional multi-tenant QoS classes"
        ),
    )
    serve.add_argument(
        "--host", default="127.0.0.1", help="bind address"
    )
    serve.add_argument(
        "--port", type=int, default=0,
        help="bind port (0 = ephemeral; read the banner JSON back)",
    )
    serve.add_argument(
        "--acceptors", type=int, default=4,
        help="asyncio acceptor tasks over the listening socket",
    )
    serve.add_argument(
        "--qos", action="append", metavar="NAME:SLO_S:MAX_PENDING[:POLICY[:burning]]",
        help=(
            "declare one QoS class (repeatable; first = default class); "
            "e.g. premium:0.05:512 besteffort:1.0:64:shed_oldest:burning"
        ),
    )
    serve.add_argument(
        "--steps", type=int, default=1, help="settlement steps per batch"
    )
    serve.add_argument(
        "--max-batch", type=int, default=256,
        help="coalescing window size bound",
    )
    serve.add_argument(
        "--max-delay-ms", type=float, default=5.0,
        help="coalescing window max delay",
    )
    serve.add_argument(
        "--slo-ms", type=float, default=None,
        help="service-wide latency objective (per-class SLOs ride --qos)",
    )
    serve.add_argument(
        "--journal", default=None,
        help="durability journal path (epochs per checkpoint cadence)",
    )
    serve.add_argument(
        "--telemetry-port", type=int, default=None,
        help="also expose the live telemetry plane on this port (0 = ephemeral)",
    )
    serve.add_argument(
        "--duration", type=float, default=0.0,
        help="serve this many seconds then drain (0 = until interrupted)",
    )
    serve.set_defaults(handler=_run_serve)

    replay = sub.add_parser(
        "replay",
        help=(
            "counterfactual replay: re-drive a recorded journal's trace "
            "sidecar under K altered configs through one vmapped "
            "settlement program (lane 0 = the recorded run, "
            "byte-exact)"
        ),
    )
    replay.add_argument(
        "journals", nargs="+",
        help=(
            "journal(s) with trace sidecars (settle_stream trace=); "
            "several paths merge as one fleet trace"
        ),
    )
    replay.add_argument(
        "--configs", action="append",
        metavar="FIELD=VALUE[,FIELD=VALUE...]",
        help=(
            "one counterfactual lane (repeatable); fields: "
            "half_life_days, decay_floor, base_learning_rate, "
            "max_update_step, band_z, graph_damping, graph_steps, "
            "graph_tol — "
            "e.g. --configs half_life_days=20,max_update_step=0.05"
        ),
    )
    replay.add_argument(
        "--strict", action="store_true",
        help=(
            "refuse a torn trace tail instead of replaying to the last "
            "joined epoch"
        ),
    )
    replay.add_argument(
        "--json", action="store_true",
        help="machine-readable sweep document instead of the table",
    )
    replay.set_defaults(handler=_run_replay)

    stats = sub.add_parser(
        "stats",
        help=(
            "render an obs run ledger (bench/soak JSONL) as per-leg "
            "min/max bands with host-load attribution"
        ),
    )
    stats.add_argument(
        "ledger", nargs="?", default=None,
        help=(
            "path to a JSONL run ledger (bench.py --ledger); optional "
            "when --live is given"
        ),
    )
    stats.add_argument("--leg", help="restrict to one leg name")
    stats.add_argument(
        "--against", metavar="OLD_LEDGER",
        help=(
            "cross-round diff: compare each leg's band against this "
            "older ledger and flag bands that stopped overlapping"
        ),
    )
    stats.add_argument(
        "--live", metavar="URL",
        help=(
            "scrape a running telemetry exporter (obs/export.py) and "
            "render its /snapshot + /healthz next to the ledger bands"
        ),
    )
    stats.add_argument(
        "--json", action="store_true",
        help="machine-readable summary instead of the table",
    )
    stats.set_defaults(handler=_run_stats)

    trace = sub.add_parser(
        "trace",
        help=(
            "convert a tracer span log (obs.Tracer.write_jsonl JSONL) "
            "to Chrome/Perfetto trace-event JSON"
        ),
    )
    trace.add_argument(
        "trace", help="path to a span-log JSONL written by obs.Tracer"
    )
    trace.add_argument(
        "--out",
        help="output path (default: <trace>.chrome.json)",
    )
    trace.set_defaults(handler=_run_trace)

    lint = sub.add_parser(
        "lint",
        help=(
            "run graftlint — JAX/determinism/layering static analysis "
            "(exit 1 on any error-severity finding)"
        ),
    )
    lint.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to check (default: the repo gate set)",
    )
    lint.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (default: text)",
    )
    lint.add_argument(
        "--select",
        help="comma-separated rule IDs to run (default: all)",
    )
    lint.add_argument(
        "--cache",
        metavar="PATH",
        help=(
            "JSON sidecar for per-file result caching — warm runs of an "
            "unchanged tree skip re-parsing entirely"
        ),
    )
    lint.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    lint.set_defaults(handler=_run_lint)

    bank = sub.add_parser(
        "bank",
        help=(
            "export/merge/show the versioned autotune bank — shippable "
            "kernel-knob verdicts keyed by (device generation, shape)"
        ),
    )
    bank_sub = bank.add_subparsers(dest="bank_verb", required=True)
    bank_export = bank_sub.add_parser(
        "export",
        help="fold a host's tuner cache into a shippable bank payload",
    )
    bank_export.add_argument(
        "--cache",
        help=(
            "tuner cache to export (default: the live BCE_AUTOTUNE_CACHE "
            "resolution)"
        ),
    )
    bank_export.add_argument(
        "--device-kind",
        help="export only this accelerator's verdicts (default: all)",
    )
    bank_export.add_argument(
        "-o", "--out", help="write the bank here (default: stdout)"
    )
    bank_export.set_defaults(handler=_run_bank)
    bank_merge = bank_sub.add_parser(
        "merge",
        help=(
            "merge bank files; refuses on a verdict flip (same "
            "knob/shape/generation, different adjudication)"
        ),
    )
    bank_merge.add_argument(
        "banks", nargs="+", help="bank files to merge"
    )
    bank_merge.add_argument(
        "-o", "--out", help="write the merged bank here (default: stdout)"
    )
    bank_merge.set_defaults(handler=_run_bank)
    bank_show = bank_sub.add_parser(
        "show",
        help="schema-validate a bank file and render its verdicts",
    )
    bank_show.add_argument("bank", help="bank file to inspect")
    bank_show.set_defaults(handler=_run_bank)

    return parser


def main() -> None:
    args = build_parser().parse_args()
    if args.command is None:
        _run_legacy_consensus(args)
    else:
        args.handler(args)


if __name__ == "__main__":
    main()
