"""End-to-end settlement pipeline: payloads → device cycle → SQLite.

The one flow the subsystems exist for, as a single tested API:

    raw (market_id, signals) payloads
      → native ingest packer                    (core.batch.pack_markets)
      → interned (source, market) rows          (TensorReliabilityStore)
      → gather flat rows into an (K, M) block   (this module)
      → N consensus+update cycles in one jit    (parallel.sharded loop)
      → scatter the block back into flat rows   (this module)
      → host-authoritative absorb               (TensorReliabilityStore.absorb)
      → reference-format SQLite checkpoint      (flush_to_sqlite)

The reference's contract is per-(source_id, market_id) state updated after
outcomes (reference: reliability.py:36-45, market.py:200-221); here that
state lives in the store's flat HBM tensors and the cycle runs on a dense
slot-major block, so the bridge is one gather at entry and one scatter at
exit — both inside the same jit dispatch as the cycle loop itself.

Performance note for million-market callers: ingest is allocation-heavy,
and CPython's generational GC re-scans every long-lived container the
caller holds on each full collection — a process holding 1M dict payloads
pays ~3x on every host-side pass here until it calls ``gc.freeze()`` on
that long-lived state (the standard service pattern). Callers with columnar
signals should prefer :func:`build_settlement_plan_columnar`, which never
materialises per-signal Python objects in the first place.

Two-phase API so the (host-side) packing/interning cost is paid once per
signal topology, then any number of settlement cycles run device-only:

    plan = build_settlement_plan(store, payloads)
    result = settle(store, plan, outcomes, steps=5)
    store.flush_to_sqlite("checkpoint.db")

`settle_payloads` wraps the three steps for the one-shot case.
"""

from __future__ import annotations

import collections as _collections
import queue as _queue
import threading
import weakref as _weakref
from dataclasses import dataclass
from functools import partial
from typing import Any, Mapping, Optional, Sequence

import numpy as np

from bayesian_consensus_engine_tpu.core.batch import (
    SourceCodes,
    _intern_source_codes,
    columns_from_payloads,
    group_columns,
    pack_markets,
    pair_accumulate,
    pair_fingerprint,
    topology_fingerprint,
)
from bayesian_consensus_engine_tpu.obs.metrics import metrics_registry
from bayesian_consensus_engine_tpu.ops.propagate import PropagatedBeliefs
from bayesian_consensus_engine_tpu.obs.timeline import (
    PhaseTimeline,
    active_timeline,
)
from bayesian_consensus_engine_tpu.obs.trace import active_tracer
from bayesian_consensus_engine_tpu.utils.config import (
    CONFIDENCE_GROWTH_RATE,
    DEFAULT_CONFIDENCE,
    DEFAULT_RELIABILITY,
)
from bayesian_consensus_engine_tpu.utils.timeconv import now_days as _now_days

Payload = Sequence[tuple[str, Sequence[Mapping[str, Any]]]]


@dataclass(frozen=True)
class SettlementPlan:
    """Static device layout for one signal topology (reusable across cycles).

    Block arrays are slot-major (K, M): markets ride the 128-wide lane
    dimension (the measured-fastest layout — see parallel.sharded). Padding
    slots carry ``row = -1`` and ``mask = False``; at kernel time row −1
    resolves to a sink row appended past the store's flat state, so the plan
    stays valid even if the store interns more pairs after it was built.

    The plan is **immutable after build** — ``build_settlement_plan`` marks
    every array read-only, because ``settle`` caches device copies of
    ``slot_rows``/``probs``/``mask``/``touched_rows`` on the plan (keyed by
    dtype) and
    ``settle_sharded`` caches its padded band + sharded device arrays
    (keyed by mesh, dtype, and band) to skip the host→device re-upload on repeat
    settlements; a mutated host array would silently diverge from its
    cached device twin.
    """

    market_keys: list[str]        # row → market id (payload order)
    slot_rows: np.ndarray         # i32[K, M] flat store row per slot (−1 pad)
    probs: np.ndarray             # f64[K, M] per-pair mean probability
    mask: np.ndarray              # bool[K, M] slot carries a signal
    signals_per_market: np.ndarray  # i32[M] raw signal counts (diagnostics)
    binding: tuple[tuple[int, str, str], ...]  # (row, source, market) probes
    #: order-sensitive topology digest (core.batch.topology_fingerprint),
    #: or None when the builder was not asked for one. Equal fingerprints
    #: mean "identical plan up to the probability columns" — the reuse key.
    fingerprint: "bytes | None" = None

    @property
    def num_markets(self) -> int:
        return len(self.market_keys)

    @property
    def num_slots(self) -> int:
        return int(self.slot_rows.shape[0])

    def refresh(self, probabilities) -> "SettlementPlan":
        """Probability-only twin of this plan: same topology, new signals.

        The delta-ingest fast path for the steady-state service, where
        consecutive batches re-settle the same (source, market) universe
        (the reference's daily re-settlement, market.py:200-221) and only
        probabilities/outcomes change. *probabilities* is the flat
        per-signal column (original signal order, markets back to back —
        the same order packing consumes). The interned row mapping, slot
        layout, padding, binding probes, and fingerprint are all SHARED
        with this plan (same array objects); only the probability block
        is recomputed — bit-identical to a full rebuild on the same input
        (the duplicate-averaging pass is the builders' own).

        The caller must guarantee the topology is unchanged — compare
        :func:`~.core.batch.topology_fingerprint` digests, as
        :class:`PlanPrefetcher` does with ``reuse_plans=True``. The
        returned plan remembers its parent, so ``settle`` /
        ``ShardedSettlementSession`` upload only the new probability
        block and adopt the parent's device-resident topology arrays.
        """
        meta = getattr(self, "_refresh_meta", None)
        if meta is None:
            raise ValueError(
                "plan carries no refresh metadata (built before the "
                "delta-ingest path existed?)"
            )
        signal_pairs, counts, slot_of_pair, market_of_pair = meta
        probabilities = np.ascontiguousarray(probabilities, dtype=np.float64)
        if probabilities.shape != (len(signal_pairs),):
            raise ValueError(
                f"{len(probabilities)} probabilities for a topology of "
                f"{len(signal_pairs)} signals"
            )
        # Same ordered accumulate as the builders (signal order = the
        # scalar engine's left-to-right duplicate sum; one C pass when
        # the native packer is built, np.add.at otherwise — bit-equal).
        sums = pair_accumulate(signal_pairs, probabilities, len(counts))
        pair_mean = sums / np.maximum(counts, 1)
        probs = np.zeros_like(self.probs)
        probs[slot_of_pair, market_of_pair] = pair_mean
        probs.setflags(write=False)
        plan = SettlementPlan(
            market_keys=self.market_keys,
            slot_rows=self.slot_rows,
            probs=probs,
            mask=self.mask,
            signals_per_market=self.signals_per_market,
            binding=self.binding,
            fingerprint=self.fingerprint,
        )
        object.__setattr__(plan, "_refresh_meta", meta)
        object.__setattr__(plan, "_refreshed_from", self)
        touched = getattr(self, "_touched_rows", None)
        if touched is not None:
            object.__setattr__(plan, "_touched_rows", touched)
        return plan


def build_settlement_plan(
    store,
    payloads: Payload,
    native: Optional[bool] = None,
    num_slots: "int | str | None" = None,
    fingerprint: "bool | bytes" = False,
) -> SettlementPlan:
    """Pack, intern, and lay out payloads as a dense settlement block.

    One native packing pass groups/sorts/flattens the raw signals
    (duplicate signals from one source collapse to their mean, in scalar
    accumulation order), one native interning pass maps every
    (source, market) pair to its flat store row, and the ragged per-market
    pair lists become a dense slot-major block.

    Market ids must be unique within one plan: two slots mapping to the
    same flat row would race in the scatter.

    ``num_slots`` pins the block's slot height K instead of deriving it
    from the data's max pairs-per-market (error if the data needs more):
    plans built across batches/processes then share one compiled shape —
    and per-process band plans (see :class:`ShardedSettlementSession`)
    MUST pass the globally-agreed K, since no process can see the others'
    maxima. ``num_slots="bucket"`` pads the natural K to the next sublane
    multiple (8) — streamed batches with wobbling K then share one
    compiled settle program per bucket without any globally-agreed
    constant. Note a different K compiles a different slot-reduction
    tree, so consensus values can move ≤1 ulp vs the natural-K plan
    (state updates are quantised ±0.1 steps and typically identical).

    ``fingerprint=True`` stamps the plan with its topology digest
    (:func:`~.core.batch.topology_fingerprint`) so the plan-reuse path
    can recognise same-topology successors; a ``bytes`` value attaches a
    digest the caller already computed.
    """
    payloads = list(payloads)
    keys = [market_id for market_id, _ in payloads]
    if len(set(keys)) != len(keys):
        raise ValueError("duplicate market ids in one settlement plan")

    packed = pack_markets(payloads, native=native)
    from bayesian_consensus_engine_tpu.core import batch as _batch_mod

    _count_pack(
        _batch_mod._object_native_available()
        if native is None
        else bool(native)
    )
    market_of_pair = packed.pair_market
    pair_markets = [keys[row] for row in market_of_pair.tolist()]
    rows = store.rows_for_arrays(
        packed.pair_source_ids, pair_markets, allocate=True
    )
    if fingerprint is True:
        # Reconstruct the raw per-signal source column (flat_pair encodes
        # each signal's pair slot; the pair table holds the id).
        raw_sids = [
            packed.pair_source_ids[p] for p in packed.flat_pair.tolist()
        ]
        signal_offsets = np.concatenate(
            [[0], np.cumsum(packed.signals_per_market, dtype=np.int64)]
        )
        fingerprint = topology_fingerprint(keys, raw_sids, signal_offsets)
    return _assemble_plan(
        keys,
        rows,
        market_of_pair,
        packed.pair_offsets,
        _pair_means(packed),
        packed.pair_source_ids.__getitem__,
        pair_markets.__getitem__,
        packed.signals_per_market,
        num_slots=num_slots,
        signal_pairs=packed.flat_pair,
        fingerprint=fingerprint or None,
    )


@dataclass
class StagedColumnarPlan:
    """The store-free half of a columnar plan build.

    Everything :func:`build_settlement_plan_columnar` computes WITHOUT
    touching the store: validation, source-code resolution, the grouping
    pass (native C when built), duplicate averaging, and the topology
    fingerprint. :meth:`bind` completes it — one pair-interning pass plus
    the dense block fill — and is the only part that mutates shared state.

    The split is what lets the serving front end overlap packing with
    device compute WITHOUT perturbing durability bytes: staging runs
    ahead on a pack thread while the previous batch settles, and the
    interning (whose order decides row assignment — and therefore which
    journal epoch a new pair's table row lands in) stays on the single
    dispatch thread, in batch order, exactly where it always ran.
    ``stage(...).bind(store)`` ≡ the one-shot builder, bit-for-bit.
    """

    market_keys: list
    sid_of_rank: list
    pair_market: np.ndarray
    pair_rank: np.ndarray
    pair_offsets: np.ndarray
    pair_mean: np.ndarray
    signals_per_market: np.ndarray
    signal_pairs: np.ndarray
    num_slots: "int | str | None"
    fingerprint: "bytes | None"
    used_native: bool
    #: ``"full" | "delta" | "auto"`` — how :meth:`bind` interns (round
    #: 15). ``full`` is the legacy one-pass walk over every pair;
    #: ``delta``/``auto`` (no behavioural difference today — ``auto`` is
    #: the forward-compatible spelling callers should use) consult the
    #: store's epoch-persistent pair table so only the batch's pair-delta
    #: walks the interner. All modes produce byte-identical plans, row
    #: assignment, and durability bytes — the mode only moves time.
    intern_mode: str = "auto"
    #: pair-set digest (:func:`~.core.batch.pair_fingerprint`) — the
    #: epoch table's O(1) reuse key; ``None`` skips that tier only.
    pair_fingerprint: "bytes | None" = None

    def bind(self, store) -> SettlementPlan:
        """Intern this stage's pairs into *store* and assemble the plan.

        The interning pass routes by ``intern_mode``: the delta path asks
        the store to resolve against its epoch-persistent pair table
        (fingerprint hit → O(1); per-market match → memcmp; remainder →
        the ordered intern walk), the full path walks every pair. The
        bound plan carries ``plan.intern_stats`` — ``{"intern_s",
        "mode", "pairs", "matched_pairs", "interned_pairs",
        "fingerprint_hit"}`` — which the stream/serve layers fold into
        the ``intern.delta_pairs``/``intern.full_pairs`` counters and the
        ``stream.intern_wait_s`` gauge.
        """
        import time as _time

        intern_start = _time.perf_counter()
        use_delta = self.intern_mode != "full" and hasattr(
            store, "rows_for_pairs_delta"
        )
        if use_delta:
            rows, resolve_stats = store.rows_for_pairs_delta(
                self.sid_of_rank, self.pair_rank,
                self.market_keys, self.pair_market,
                self.pair_offsets, self.pair_fingerprint,
            )
        else:
            rows = store.rows_for_indexed(
                self.sid_of_rank, self.pair_rank,
                self.market_keys, self.pair_market,
            )
            resolve_stats = {
                "pairs": len(self.pair_market),
                "matched_pairs": 0,
                "interned_pairs": len(self.pair_market),
                "fingerprint_hit": False,
            }
        intern_stats = {
            "intern_s": _time.perf_counter() - intern_start,
            "mode": "delta" if use_delta else "full",
            **resolve_stats,
        }
        _count_intern(intern_stats)
        _count_pack(self.used_native)
        sid_of_rank, pair_rank = self.sid_of_rank, self.pair_rank
        market_keys, pair_market = self.market_keys, self.pair_market
        plan = _assemble_plan(
            market_keys,
            rows,
            pair_market,
            self.pair_offsets,
            self.pair_mean,
            lambda i: sid_of_rank[pair_rank[i]],
            lambda i: market_keys[pair_market[i]],
            self.signals_per_market,
            num_slots=self.num_slots,
            signal_pairs=self.signal_pairs,
            fingerprint=self.fingerprint,
        )
        object.__setattr__(plan, "intern_stats", intern_stats)
        return plan


def _count_pack(used_native: bool) -> None:
    """ingest.native_packs / ingest.python_packs — which packer built the
    plan (no-ops unless obs enabled a registry)."""
    registry = metrics_registry()
    name = "ingest.native_packs" if used_native else "ingest.python_packs"
    registry.counter(name).inc()


def _count_intern(stats: dict) -> None:
    """intern.delta_pairs / intern.full_pairs — how many pairs this bind
    actually walked through the interner, split by route (the delta
    path's count IS the pair-delta; matched pairs never touch the
    interner). Counted here per LY303 — the store produces the stats,
    pipeline/serve own the instrumentation. No-ops unless obs enabled a
    registry."""
    registry = metrics_registry()
    name = (
        "intern.delta_pairs" if stats["mode"] == "delta"
        else "intern.full_pairs"
    )
    registry.counter(name).inc(int(stats["interned_pairs"]))


def stage_settlement_plan_columnar(
    market_keys: Sequence[str],
    source_ids: "Sequence[str] | SourceCodes",
    probabilities,
    offsets,
    num_slots: "int | str | None" = None,
    fingerprint: "bool | bytes" = False,
    native: Optional[bool] = None,
    intern_mode: str = "auto",
) -> StagedColumnarPlan:
    """Validate + group one columnar batch without touching any store.

    *source_ids* is either one string per signal (markets back to back)
    or a :class:`~.core.batch.SourceCodes` column — the zero-copy intake
    that skips per-signal Python objects entirely (codes flow straight
    into the native grouping pass). ``native`` forces the C grouping
    (True), the numpy twin (False), or auto-detects (None); outputs are
    bit-identical either way.

    ``intern_mode`` routes :meth:`StagedColumnarPlan.bind`'s interning:
    ``"auto"``/``"delta"`` consult the store's epoch-persistent pair
    table so a drifted batch interns only its pair-delta (the pair-set
    digest is computed HERE, on the staging thread, so the bind pays
    only the resolve); ``"full"`` is the legacy every-pair walk. All
    modes are byte-identical downstream.
    """
    if intern_mode not in ("full", "delta", "auto"):
        raise ValueError(
            f'intern_mode={intern_mode!r}: expected "full", "delta" or '
            '"auto"'
        )
    market_keys = list(market_keys)
    if len(set(market_keys)) != len(market_keys):
        raise ValueError("duplicate market ids in one settlement plan")
    num_markets = len(market_keys)
    probabilities = np.ascontiguousarray(probabilities, dtype=np.float64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    if offsets.shape != (num_markets + 1,):
        raise ValueError(
            f"offsets must have shape ({num_markets + 1},); got {offsets.shape}"
        )
    if num_markets and (offsets[0] != 0 or np.any(np.diff(offsets) < 0)):
        raise ValueError("offsets must start at 0 and be non-decreasing")
    num_signals = int(offsets[-1]) if num_markets else 0
    if len(source_ids) != num_signals or len(probabilities) != num_signals:
        raise ValueError(
            f"offsets cover {num_signals} signals but got "
            f"{len(source_ids)} source ids / {len(probabilities)} probabilities"
        )

    signals_per_market = np.diff(offsets).astype(np.int32)

    # Source column → dense codes: zero-copy when the caller tabled its
    # ids (SourceCodes), one C interning pass for strings. Then code →
    # rank in code-point order by sorting the unique table (small: one
    # entry per distinct source id, not per signal).
    if isinstance(source_ids, SourceCodes):
        codes, uniq = source_ids.codes, source_ids.table
        if len(codes) and (
            int(codes.min()) < 0 or int(codes.max()) >= len(uniq)
        ):
            raise ValueError("SourceCodes codes out of table range")
    else:
        codes, uniq = _intern_source_codes(source_ids)
    order = sorted(range(len(uniq)), key=uniq.__getitem__)
    rank_of_code = np.empty(max(len(uniq), 1), dtype=np.int64)
    rank_of_code[np.asarray(order, dtype=np.int64)] = np.arange(len(order))
    sid_of_rank = [uniq[code] for code in order]

    from bayesian_consensus_engine_tpu.core.batch import (
        _columnar_native_available,
    )

    used_native = (
        _columnar_native_available() if native is None else bool(native)
    )
    (signal_pairs, pair_market, pair_rank, pair_offsets,
     sums, counts) = group_columns(
        codes, rank_of_code, offsets, probabilities, native=native
    )
    pair_mean = sums / np.maximum(counts, 1)

    if fingerprint is True:
        fingerprint = topology_fingerprint(market_keys, source_ids, offsets)
    pair_fp = (
        pair_fingerprint(
            market_keys, sid_of_rank, pair_market, pair_rank, pair_offsets
        )
        if intern_mode != "full"
        else None
    )
    return StagedColumnarPlan(
        market_keys=market_keys,
        sid_of_rank=sid_of_rank,
        pair_market=pair_market,
        pair_rank=pair_rank,
        pair_offsets=pair_offsets,
        pair_mean=pair_mean,
        signals_per_market=signals_per_market,
        signal_pairs=signal_pairs,
        num_slots=num_slots,
        fingerprint=fingerprint or None,
        used_native=used_native,
        intern_mode=intern_mode,
        pair_fingerprint=pair_fp,
    )


def build_settlement_plan_columnar(
    store,
    market_keys: Sequence[str],
    source_ids: "Sequence[str] | SourceCodes",
    probabilities,
    offsets,
    num_slots: "int | str | None" = None,
    fingerprint: "bool | bytes" = False,
    native: Optional[bool] = None,
    intern_mode: str = "auto",
) -> SettlementPlan:
    """Vectorised twin of :func:`build_settlement_plan` for columnar input.

    Callers that already hold their signals as flat columns — *source_ids*
    (one string per signal, markets back to back, or a
    :class:`~.core.batch.SourceCodes` zero-copy coded column),
    *probabilities* (float64[N]) and CSR *offsets* (int64[M+1]; market
    ``m``'s signals are ``[offsets[m], offsets[m+1])``) — skip the
    per-signal Python dict walk entirely: grouping, per-market source-id
    ordering and duplicate averaging run as ONE native C pass over the
    coded columns (``fastpack.group_columns``; numpy twin without the
    extension — bit-identical), with one C interning pass each for the
    source-id strings and the (source, market) pairs. Produces a plan
    identical (bit-for-bit, including binding probes and row assignment
    order) to the dict-payload path on equivalent input.

    Semantics notes pinned to the reference engine:

    * pairs within a market are ordered by source id (code-point order, the
      scalar engine's float-summation order, reference: core.py:103);
    * duplicate signals from one (source, market) average in original
      signal order (reference: core.py:115-116).

    ``num_slots`` pins the block's slot height K and ``fingerprint``
    stamps the topology digest (see :func:`build_settlement_plan`);
    ``native`` forces/forbids the C grouping pass; ``intern_mode``
    routes the pair-interning pass through the store's epoch-persistent
    pair table (``"auto"``/``"delta"``) or the legacy every-pair walk
    (``"full"``) — byte-identical either way, see
    :func:`stage_settlement_plan_columnar`. The build is the
    composition ``stage_settlement_plan_columnar(...).bind(store)`` —
    callers that need the store-free half on its own schedule (the
    serving front end's pack thread) use the two halves directly.
    """
    return stage_settlement_plan_columnar(
        market_keys, source_ids, probabilities, offsets,
        num_slots=num_slots, fingerprint=fingerprint, native=native,
        intern_mode=intern_mode,
    ).bind(store)


def _assemble_plan(
    keys,
    rows,
    market_of_pair,
    pair_offsets,
    pair_mean,
    source_of,
    market_of,
    signals_per_market,
    num_slots: "int | str | None" = None,
    signal_pairs=None,
    fingerprint: "bytes | None" = None,
) -> SettlementPlan:
    """Shared plan tail: dense block fill + binding probes + freeze.

    ``signal_pairs`` (signal → pair slot, original signal order) is kept
    as refresh metadata so :meth:`SettlementPlan.refresh` can recompute
    the probability block for a same-topology batch without re-packing.
    """
    counts = np.diff(pair_offsets)
    num_markets = len(keys)
    needed_slots = int(counts.max()) if num_markets else 0
    if num_slots == "bucket":
        # Slot height padded to the next sublane multiple: streamed batches
        # whose natural K wobbles (e.g. Poisson signal counts) then share
        # one compiled settle program per 8-wide bucket instead of one per
        # distinct K. Same ≤1-ulp note as any pinned num_slots (docstring).
        num_slots = max(8, -(-needed_slots // 8) * 8)
    elif isinstance(num_slots, str):
        raise ValueError(
            f"num_slots={num_slots!r}: the only supported string is 'bucket'"
        )
    if num_slots is None:
        num_slots = needed_slots
    elif needed_slots > num_slots:
        raise ValueError(
            f"num_slots={num_slots} but a market carries {needed_slots} "
            "distinct sources"
        )

    # Ragged pair lists → dense slot-major (K, M), written in place: slot k
    # of market m is its k-th pair (source-id-sorted within the market, the
    # scalar engine's float order). Allocating (K, M) directly avoids a
    # strided transpose copy of every block (~1 s per 4M pairs).
    slot_rows = np.full((num_slots, num_markets), -1, dtype=np.int32)
    probs = np.zeros((num_slots, num_markets), dtype=np.float64)
    mask = np.zeros((num_slots, num_markets), dtype=bool)
    slot_of_pair = (
        np.arange(len(rows), dtype=np.int64)
        - pair_offsets[:-1][market_of_pair]
    )
    slot_rows[slot_of_pair, market_of_pair] = rows
    probs[slot_of_pair, market_of_pair] = pair_mean
    mask[slot_of_pair, market_of_pair] = True

    # Binding probes: a spread of (row, pair) samples (always including the
    # highest row) lets settle() verify the plan still matches the store's
    # interner — a checkpoint-restored store with identical row assignment
    # passes; an unrelated store of coincidentally sufficient size does not.
    if len(rows):
        probe_idx = {0, len(rows) - 1, int(np.argmax(rows))}
        probe_idx.update(range(0, len(rows), max(1, len(rows) // 8)))
        binding = tuple(
            (int(rows[i]), source_of(i), market_of(i))
            for i in sorted(probe_idx)
        )
    else:
        binding = ()

    plan = SettlementPlan(
        market_keys=keys,
        slot_rows=slot_rows,
        probs=probs,
        mask=mask,
        signals_per_market=np.asarray(signals_per_market, dtype=np.int32),
        binding=binding,
        fingerprint=fingerprint,
    )
    # Freeze the arrays: settle() caches device copies keyed by the plan
    # object (see SettlementPlan docstring), so host-side mutation after
    # build must fail loudly rather than desync host and device views.
    for array in (plan.slot_rows, plan.probs, plan.mask, plan.signals_per_market):
        array.setflags(write=False)
    if signal_pairs is not None:
        signal_pairs = np.ascontiguousarray(signal_pairs)
        counts = np.bincount(signal_pairs, minlength=len(rows))
        object.__setattr__(
            plan, "_refresh_meta",
            (signal_pairs, counts, slot_of_pair, market_of_pair),
        )
    return plan


def _pair_means(packed) -> np.ndarray:
    """Per-pair duplicate-signal means, host-side in scalar float order.

    Duplicate averaging must match the scalar engine bit-for-bit
    (left-to-right sum per pair, reference: core.py:115-116); the flat
    signal list is already in original order, so a stable per-pair
    accumulation reproduces it exactly.
    """
    num_pairs = len(packed.pair_source_ids)
    # Ordered sequential accumulate — scalar-sum order (C pass when the
    # native packer is built; np.add.at twin is bit-identical).
    sums = pair_accumulate(packed.flat_pair, packed.flat_probs, num_pairs)
    counts = np.bincount(packed.flat_pair, minlength=num_pairs)
    return sums / np.maximum(counts, 1)


class SettlementResult:
    """Per-market outputs of the final cycle, payload order.

    ``consensus`` (f[M]; NaN means zero weight) materialises LAZILY: the
    settle path hands the device array straight through, and the
    device→host fetch happens on first access. A caller that only
    settles-and-checkpoints never pays the transfer (through a tunneled
    remote device the full-vector fetch can dwarf the kernel itself);
    :meth:`fence` waits for completion with a scalar-sized fetch when a
    timing boundary is needed without the vector.
    """

    __slots__ = ("market_keys", "_consensus_raw", "_consensus_np")

    def __init__(self, market_keys: list[str], consensus) -> None:
        self.market_keys = market_keys
        self._consensus_raw = consensus
        self._consensus_np: Optional[np.ndarray] = None

    @property
    def consensus(self) -> np.ndarray:
        if self._consensus_np is None:
            self._consensus_np = np.asarray(self._consensus_raw)
            self._consensus_raw = None  # free the device buffer
        return self._consensus_np

    def fence(self) -> None:
        """Block until the settlement's outputs exist on device, fetching
        only one scalar (remote tunnels do not reliably force execution on
        ``block_until_ready``; a value fetch does)."""
        if self._consensus_np is None and getattr(
            self._consensus_raw, "size", 0
        ):
            float(self._consensus_raw[0])

    def by_market(self) -> dict[str, float]:
        return {
            key: float(value)
            for key, value in zip(self.market_keys, self.consensus)
        }


def _settle_math(
    flat_rel, flat_conf, flat_days, flat_exists,
    slot_rows, probs, mask, outcome, now0, touched_rows, steps: int,
):
    """gather → N-cycle loop → scatter, traced as one jit dispatch.

    Flat state buffers are donated (the caller re-materialises from host
    state via ``device_state()`` afterwards — ``settle`` absorbs + drops the
    cache immediately). Padding slots carry row −1, which indexes the sink
    row appended at the end; sink writes are sliced off before returning.

    ``touched_rows`` (the flat rows the scatter writes, precomputed on the
    host) drives an extra gather of the settled reliabilities: the store's
    deferred sync fetches only that vector — stamps and existence are
    closed-form host-side — so the device→host merge cost scales with
    touched rows, not store size.
    """
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel.sharded import (
        MarketBlockState,
        _cycle_math,
        _fast_cycle_math,
        make_loop_math,
    )

    def ext(x, fill):
        return jnp.concatenate([x, jnp.full((1,), fill, x.dtype)])

    rel = ext(flat_rel, DEFAULT_RELIABILITY)
    conf = ext(flat_conf, DEFAULT_CONFIDENCE)
    days = ext(flat_days, 0.0)
    exists = ext(flat_exists, False)

    block = MarketBlockState(
        reliability=rel[slot_rows],
        confidence=conf[slot_rows],
        updated_days=days[slot_rows],
        exists=exists[slot_rows],
    )
    cycle_fn = partial(_cycle_math, axis_name=None, slots_axis=0)
    fast_fn = partial(_fast_cycle_math, axis_name=None, slots_axis=0)
    loop_math = make_loop_math(cycle_fn, steps, fast_cycle_fn=fast_fn)
    new_block, consensus = loop_math(probs, mask, outcome, block, now0)

    # Every real (mask=True) slot maps to a distinct flat row, so the
    # scatter is a permutation write; pad slots all land on the sink row.
    new_rel = rel.at[slot_rows].set(new_block.reliability)[:-1]
    new_conf = conf.at[slot_rows].set(new_block.confidence)[:-1]
    new_days = days.at[slot_rows].set(new_block.updated_days)[:-1]
    new_exists = exists.at[slot_rows].set(new_block.exists)[:-1]
    rel_touched = new_rel[touched_rows]
    return new_rel, new_conf, new_days, new_exists, consensus, rel_touched


def _check_plan(store, plan: SettlementPlan, outcomes: Sequence[bool]) -> None:
    """Shared settle-entry validation: outcome count + plan↔store binding."""
    if len(outcomes) != plan.num_markets:
        raise ValueError(
            f"{len(outcomes)} outcomes for {plan.num_markets} planned markets"
        )
    if plan.mask.any() and int(plan.slot_rows.max()) >= len(store):
        # A plan built against a different (or rebuilt) store: the gather
        # would clamp onto the sink row and silently corrupt results.
        raise ValueError(
            f"plan references row {int(plan.slot_rows.max())} but the store "
            f"holds {len(store)} pairs — was the plan built for this store?"
        )
    if plan.binding:
        probe_rows = store.rows_for_pairs(
            [(source_id, market_id) for _, source_id, market_id in plan.binding],
            allocate=False,
        )
        for (row, source_id, market_id), got in zip(plan.binding, probe_rows):
            if int(got) != row:
                raise ValueError(
                    f"plan is bound to a different store: ({source_id!r}, "
                    f"{market_id!r}) does not intern to row {row} here"
                )


def _rebase_epoch(flat, epoch0: float, now_abs: float):
    """Ensure the settlement time lands strictly after the stamp epoch.

    Stamps are stored relative to *epoch0* with "> 0" meaning "ever
    updated", so a settlement at ``now_abs <= epoch0`` would write
    non-positive stamps that absorb reads back as NEVER — silently losing
    timestamps. Backdated settlements are legitimate (the reference stamps
    whatever ``now`` the caller supplies, reliability.py:175), so instead
    of rejecting, shift the epoch below ``now_abs`` and re-express the
    live stamps (never-updated rows keep exactly 0). Rare path: one cheap
    elementwise op, only when time runs backwards.
    """
    if now_abs - epoch0 > 0:
        return flat, epoch0
    import jax.numpy as jnp

    new_epoch0 = now_abs - 1.0
    delta = jnp.asarray(epoch0 - new_epoch0, flat.updated_days.dtype)
    shifted = jnp.where(
        flat.updated_days > 0, flat.updated_days + delta, flat.updated_days
    )
    return flat._replace(updated_days=shifted), new_epoch0


def _replay_confidences(store, touched_rows, conf_exact, steps: int) -> None:
    """Overwrite settled confidences with the exact host-replayed trajectory.

    XLA fuses the confidence growth's multiply-add into an FMA (one rounding
    where the scalar contract has two); the trajectory is data-independent —
    one growth step per settled cycle — so the host reproduces it bit-exactly
    regardless of device precision and overwrites.
    """
    for _ in range(steps):
        conf_exact = np.minimum(
            1.0, conf_exact + (1.0 - conf_exact) * CONFIDENCE_GROWTH_RATE
        )
    store.overwrite_confidences(touched_rows, conf_exact)


_settle_kernel = None


def _get_settle_kernel():
    global _settle_kernel
    if _settle_kernel is None:
        import jax

        _settle_kernel = jax.jit(
            _settle_math, static_argnames=("steps",), donate_argnums=(0, 1, 2, 3)
        )
    return _settle_kernel


def settle(
    store,
    plan: SettlementPlan,
    outcomes: Sequence[bool],
    steps: int = 1,
    now: Optional[float] = None,
    dtype=None,
) -> SettlementResult:
    """Run *steps* settlement cycles for the planned markets on device.

    Each cycle: decay-on-read → weighted consensus → outcome correctness at
    p ≥ 0.5 → capped update of the undecayed state (day ``now + i``).
    The mutated state is absorbed back into *store* before returning, so
    a follow-up ``flush_to_sqlite`` checkpoints exactly what settled.

    ``now`` is absolute epoch-days (defaults to the current time); pass it
    explicitly for reproducible parity runs.
    """
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.state.tensor_store import (
        DeviceReliabilityState,
    )

    _check_plan(store, plan, outcomes)

    # Capture pre-settle confidences: the post-settle values are replayed
    # host-side in exact scalar arithmetic (see overwrite_confidences — XLA
    # fuses the growth multiply-add into an FMA, one rounding short of the
    # scalar contract; the trajectory is data-independent, so the host can
    # reproduce it bit-exactly no matter what precision the device ran at).
    # Cached on the plan: the same array object chains through defer_absorb
    # recipes (same-plan links replace rather than accumulate).
    touched_rows = getattr(plan, "_touched_rows", None)
    if touched_rows is None:
        touched_rows = plan.slot_rows[plan.mask]
        touched_rows.setflags(write=False)
        object.__setattr__(plan, "_touched_rows", touched_rows)
    conf_exact = store.host_confidences(touched_rows)

    # take_device_state hands forward a pending (unsynced) predecessor
    # settlement if one exists — the chained-settle fast path: no host→
    # device re-upload and no per-settle absorb; this call's settled state
    # subsumes the predecessor's changes and replaces it as pending below.
    # Phase spans (obs/timeline.py) are no-ops unless this thread is
    # recording; they time the host-side call windows only (the dispatch
    # is deliberately unfenced — see settle_stream's stats contract).
    timeline = active_timeline()
    with timeline.span("upload"):
        (flat, epoch0) = store.take_device_state(dtype)
    now_abs = _now_days() if now is None else now
    flat, epoch0 = _rebase_epoch(flat, epoch0, now_abs)
    cdtype = flat.reliability.dtype

    # The plan is static across settle calls; keep its device copies so a
    # repeat settlement pays no host→device re-upload (measured ~1 s at
    # 100k markets through the axon tunnel). Cached on the plan itself —
    # frozen dataclass, hence object.__setattr__ — keyed by dtype.
    device_plan = getattr(plan, "_device_arrays", None)
    if device_plan is None or device_plan[0] != str(cdtype):
        parent = getattr(plan, "_refreshed_from", None)
        donor = (
            getattr(parent, "_device_arrays", None)
            if parent is not None else None
        )
        with timeline.span("upload"):
            if donor is not None and donor[0] == str(cdtype):
                # Delta-ingest fast path: a probability-only refresh shares
                # its topology with the settled parent plan, so only the new
                # probs block crosses host→device; the parent's device rows/
                # mask/touched copies transfer over, and its stale probs
                # block is dropped (donated) rather than pinned twice in HBM.
                device_plan = (
                    donor[0],
                    donor[1],
                    jnp.asarray(plan.probs, dtype=cdtype),
                    donor[3],
                    donor[4],
                )
                object.__setattr__(parent, "_device_arrays", None)
            else:
                device_plan = (
                    str(cdtype),
                    jnp.asarray(plan.slot_rows),
                    jnp.asarray(plan.probs, dtype=cdtype),
                    jnp.asarray(plan.mask),
                    jnp.asarray(touched_rows),
                )
        object.__setattr__(plan, "_device_arrays", device_plan)
        # The back-reference has served its purpose; dropping it keeps a
        # long reuse chain from pinning every predecessor plan in memory.
        if parent is not None:
            object.__setattr__(plan, "_refreshed_from", None)
    _, slot_rows_d, probs_d, mask_d, touched_d = device_plan

    with timeline.span("settle_dispatch"):
        rel, conf, days, exists, consensus, rel_touched = _get_settle_kernel()(
            flat.reliability,
            flat.confidence,
            flat.updated_days,
            flat.exists,
            slot_rows_d,
            probs_d,
            mask_d,
            jnp.asarray(np.asarray(outcomes, dtype=bool)),
            jnp.asarray(now_abs - epoch0, dtype=cdtype),
            touched_d,
            steps,
        )
    # Deferred absorb: the settled state becomes the store's pending device
    # truth (merged into the host lazily, on the first host read that needs
    # it); the exact confidence trajectory is maintained host-side NOW so
    # host confidences stay authoritative throughout. The sync recipe lets
    # that merge fetch only the touched reliabilities: the final stamp is
    # the closed form the loop itself uses (now0 + steps − 1, in device
    # precision — make_loop_math's exit reconstruction), and existence is
    # monotone. steps == 0 settles nothing: an empty recipe keeps the sync
    # a no-op rather than inventing stamps.
    np_dtype = np.dtype(cdtype).type
    stamp_rel = np_dtype(np_dtype(now_abs - epoch0) + np_dtype(steps - 1))
    recipe = (
        (touched_rows, rel_touched, stamp_rel)
        if steps > 0
        # Empty on BOTH sides: rel_touched[:0] frees the full-size gather
        # and keeps the eventual sync from fetching bytes it will discard.
        else (touched_rows[:0], rel_touched[:0], stamp_rel)
    )
    store.defer_absorb(
        DeviceReliabilityState(rel, conf, days, exists), epoch0,
        sync_recipe=recipe,
    )
    _replay_confidences(store, touched_rows, conf_exact, steps)
    return SettlementResult(
        market_keys=plan.market_keys,
        consensus=consensus,  # device array; fetched lazily (see class doc)
    )


def _sharded_plan_cache(plan: SettlementPlan, mesh, cdtype, band=None):
    """Pad + band + upload of the static plan arrays, cached on the plan.

    Deterministic per (mesh, dtype, band) — repeat settlements re-upload
    only the outcomes vector. Returns ``(padded_total, lo, hi, band_rows,
    band_mask, probs_g, mask_g)`` for THIS process's band.

    ``band=None``: the plan is GLOBAL (all markets on every process); this
    process's columns are sliced out of it. ``band=(lo, global_markets)``:
    the plan covers ONLY this process's markets (rows ``[lo, lo+M_plan)``
    of a ``global_markets``-wide axis) — the multi-host ingest shape where
    no process ever packs another's payloads; it must tile the process
    band exactly, and the plan must be built with the globally-agreed
    ``num_slots``.

    A plan produced by :meth:`SettlementPlan.refresh` whose parent holds
    this cache pays only the probability leg: ``band_rows``/``band_mask``
    and the device-resident ``mask_g`` carry over unchanged, one new
    ``probs_g`` block is uploaded, and the parent's stale block is
    dropped (donated) — the sharded delta-ingest fast path.
    """
    from bayesian_consensus_engine_tpu.parallel.distributed import (
        global_slot_block,
        process_market_rows,
    )
    from bayesian_consensus_engine_tpu.parallel.mesh import (
        MARKETS_AXIS,
        SOURCES_AXIS,
    )

    cache = getattr(plan, "_sharded_cache", None)
    cache_key = (mesh, str(cdtype), band)
    if cache is None or cache[0] != cache_key:
        num_markets_global = plan.num_markets if band is None else band[1]
        markets_extent = mesh.shape[MARKETS_AXIS]
        sources_extent = mesh.shape[SOURCES_AXIS]
        padded_total = (
            -(-max(num_markets_global, 1) // markets_extent) * markets_extent
        )
        num_slots = plan.num_slots
        pad_k = (
            -(-max(num_slots, 1) // sources_extent) * sources_extent
            - num_slots
        )
        lo, hi = process_market_rows(padded_total, mesh)

        if band is None:
            # Global plan: pad columns to the full axis, slice this
            # process's band of market columns — its shard of the work AND
            # of the store's touched rows.
            col_pad = padded_total - plan.num_markets
            cols = slice(lo, hi)
        else:
            lo_plan = band[0]
            live = max(0, min(hi, num_markets_global) - lo)
            if lo_plan != lo or plan.num_markets != live:
                raise ValueError(
                    f"band plan covers rows [{lo_plan}, "
                    f"{lo_plan + plan.num_markets}) but this process owns "
                    f"[{lo}, {min(hi, num_markets_global)}) of the "
                    f"{num_markets_global}-market axis"
                )
            # Band plan: the columns ARE the band; pad to its full width.
            col_pad = (hi - lo) - plan.num_markets
            cols = slice(None)

        def pad_cols(array, fill):
            padded = np.pad(
                array, ((0, pad_k), (0, col_pad)), constant_values=fill
            )
            return padded[:, cols]

        parent = getattr(plan, "_refreshed_from", None)
        donor = (
            getattr(parent, "_sharded_cache", None)
            if parent is not None else None
        )
        if donor is not None and donor[0] == cache_key:
            # Probability-only refresh: the topology legs (band_rows,
            # band_mask, device mask_g) are the parent's — identical by
            # construction — so only the probs block is padded, banded,
            # and uploaded. Clearing the parent's cache donates its stale
            # probs_g back to the device allocator.
            band_rows, band_mask, mask_g = donor[4], donor[5], donor[7]
            object.__setattr__(parent, "_sharded_cache", None)
        else:
            band_rows = pad_cols(plan.slot_rows, -1)
            band_mask = pad_cols(plan.mask, False)
            mask_g = global_slot_block(band_mask, mesh, padded_total)
        band_probs = pad_cols(plan.probs, 0.0)

        probs_g = global_slot_block(
            band_probs.astype(cdtype), mesh, padded_total
        )
        cache = (
            cache_key, padded_total, lo, hi,
            band_rows, band_mask, probs_g, mask_g,
        )
        object.__setattr__(plan, "_sharded_cache", cache)
        if parent is not None:
            # Drop the back-reference so long reuse chains don't pin
            # every predecessor plan (and its host blocks) in memory.
            object.__setattr__(plan, "_refreshed_from", None)
    return cache[1:]


class _BandGather:
    """Lazy ``np.asarray``-able view of a sharded block's masked band.

    Resolves to ``local_view(block)[band_mask]`` — this process's touched
    values, in ``band_rows[band_mask]`` order — only when the store's sync
    actually needs the bytes. Keeping the block on device until then is
    what makes chained sharded settles free of per-settle transfers.

    ``held_nbytes`` is the FULL block the deferral pins in HBM (not the
    touched subset): the store's recipe chain uses it to apply old links
    early before deep disjoint-batch chains of big blocks exhaust device
    memory (a north-star-scale band block is ~0.6 GB; eight would pin
    ~5 GB of a 16 GB chip). A gather whose block is the live state of a
    STANDING resident session reports 0: the session pins that block
    whether or not the recipe exists, so applying the link early would
    free nothing — the store's byte budget must not count it. The
    session is held by WEAK reference and consulted live, so the bytes
    count again the moment the block stops being session-pinned: the
    session adopts a new plan (``_standing_gather`` moves on), closes,
    or is simply dropped (the per-batch stream's abandoned sessions die
    at the next statement, so their gathers never evade the budget).
    """

    __slots__ = ("_block", "_mask", "_session")

    def __init__(self, block, mask: np.ndarray, session=None) -> None:
        self._block = block
        self._mask = mask
        self._session = (
            _weakref.ref(session) if session is not None else None
        )

    def __len__(self) -> int:
        return int(self._mask.sum())

    @property
    def held_nbytes(self) -> int:
        if self._session is not None:
            session = self._session()
            if session is not None and session._standing_gather is self:
                return 0
        return int(getattr(self._block, "nbytes", 0))

    def __array__(self, dtype=None, copy=None):
        from bayesian_consensus_engine_tpu.parallel.distributed import (
            local_view,
        )

        values = local_view(self._block)[self._mask]
        return values.astype(dtype) if dtype is not None else values


class _BandView:
    """Lazy band slice of a markets-sharded per-market vector.

    ``__array__`` resolves to this process's band rows (via ``local_view``);
    scalar ``[i]`` indexes the GLOBAL vector at ``lo + i`` — an address this
    process owns — so ``SettlementResult.fence`` stays a one-scalar fetch.
    """

    __slots__ = ("_vector", "_lo", "_live")

    def __init__(self, vector, lo: int, live: int) -> None:
        self._vector = vector
        self._lo = lo
        self._live = live

    @property
    def size(self) -> int:
        return self._live

    def __getitem__(self, index: int):
        return self._vector[self._lo + index]

    def __array__(self, dtype=None, copy=None):
        from bayesian_consensus_engine_tpu.parallel.distributed import (
            local_view,
        )

        values = np.asarray(local_view(self._vector))[: self._live]
        return values.astype(dtype) if dtype is not None else values


def _process_count() -> int:
    """The runtime's controller count, as a patchable indirection.

    The session's adopt path branches on it (single-controller adopts
    relayout in HBM; cluster adopts stage through process-local host
    buffers), and tests force the cluster branch on a single-process
    backend by patching this function — the only seam that does not
    require a real multi-controller runtime.
    """
    import jax

    return jax.process_count()


_cycle_loop_cache: dict = {}


def _cached_cycle_loop(mesh):
    """One slot-major donating cycle loop per mesh (sessions share it)."""
    loop = _cycle_loop_cache.get(mesh)
    if loop is None:
        from bayesian_consensus_engine_tpu.parallel.sharded import (
            build_cycle_loop,
        )

        loop = build_cycle_loop(mesh, slot_major=True, donate=True)
        _cycle_loop_cache[mesh] = loop
    return loop


_fused_tiebreak_loop_cache: dict = {}


def _cached_fused_tiebreak_loop(mesh, chunk_agents, precision):
    """One fused cycle+tie-break loop per (mesh, chunk, precision) —
    shared across sessions for the same reason as :func:`_cached_cycle_loop`
    (the jit tracing cache lives on the wrapper instance)."""
    key = (mesh, chunk_agents, precision)
    loop = _fused_tiebreak_loop_cache.get(key)
    if loop is None:
        from bayesian_consensus_engine_tpu.parallel.sharded import (
            build_cycle_tiebreak_loop,
        )

        loop = build_cycle_tiebreak_loop(
            mesh, chunk_agents=chunk_agents, donate=True,
            precision=precision,
        )
        _fused_tiebreak_loop_cache[key] = loop
    return loop


_analytics_loop_cache: dict = {}


def _cached_analytics_loop(mesh, chunk_agents, chunk_slots, precision,
                           z, damping, sweep_steps, with_tiebreak,
                           tiebreak_kind="ring", kernel="xla",
                           sweep_mode="point", sweep_tol=None,
                           sweep_kernel="xla"):
    """One fused cycle(+tiebreak)+bands(+sweep) loop per configuration —
    shared across sessions like :func:`_cached_cycle_loop` (the jit
    tracing cache lives on the wrapper instance)."""
    key = (mesh, chunk_agents, chunk_slots, precision, z, damping,
           sweep_steps, with_tiebreak, tiebreak_kind, kernel,
           sweep_mode, sweep_tol, sweep_kernel)
    loop = _analytics_loop_cache.get(key)
    if loop is None:
        from bayesian_consensus_engine_tpu.parallel.sharded import (
            build_cycle_analytics_loop,
        )

        loop = build_cycle_analytics_loop(
            mesh, chunk_agents=chunk_agents, chunk_slots=chunk_slots,
            donate=True, precision=precision, z=z, damping=damping,
            sweep_steps=sweep_steps, sweep_mode=sweep_mode,
            sweep_tol=sweep_tol, with_tiebreak=with_tiebreak,
            tiebreak_kind=tiebreak_kind, kernel=kernel,
            sweep_kernel=sweep_kernel,
        )
        _analytics_loop_cache[key] = loop
    return loop


class ShardedSettlementSession:
    """Chained, device-resident sharded settlements for one plan — or, via
    :meth:`refresh`/:meth:`adopt`, a long-lived SUCCESSION of plans.

    The mesh twin of :func:`settle`'s deferred chain: the sharded block
    state is built (per process band) ONCE, every :meth:`settle` runs the
    production loop on the retained state — the only per-settle
    host→device traffic is the outcomes vector — and the store merge is a
    registered sync recipe (closed-form stamps/existence + a lazy band
    gather of reliabilities) that any host read resolves transparently.
    Host confidences stay exact throughout via the eager replay.
    :meth:`refresh` swaps in a probability-only twin (topology hit: probs
    upload only); :meth:`adopt` swaps in ANY same-store plan, carrying the
    resident block across the topology change with host traffic scaling
    with the row-set delta — the two moves ``settle_stream(mesh=...)``
    makes every batch of the resident streamed service.

    Contract: one live session per store for any given set of rows — a
    flat :func:`settle` or direct host write to rows this session covers,
    while it is open, is not observed by the retained block state (the
    store's single-writer contract, made explicit). With ``band=None``,
    ``plan``/``outcomes`` are indexed globally on every process; with
    ``band=(lo, global_markets)`` the plan and outcomes cover ONLY this
    process's markets (the multi-host ingest shape: each process packs
    its own payload shard, with a globally-agreed plan ``num_slots``).
    Results cover this process's band either way. Use as a context
    manager, or call :meth:`close`.
    """

    def __init__(
        self, store, plan: SettlementPlan, mesh, dtype=None, band=None
    ):
        from bayesian_consensus_engine_tpu.utils.dtypes import (
            default_float_dtype,
        )

        self._store = store
        self._plan = plan
        self._mesh = mesh
        self._band = band
        self._cdtype = dtype or default_float_dtype()
        with active_timeline().span("upload"):
            (self._padded_total, self._lo, self._hi,
             self._band_rows, self._band_mask, self._probs_g,
             self._mask_g) = _sharded_plan_cache(
                plan, mesh, self._cdtype, band
            )
        self._touched = self._band_rows[self._band_mask]
        self._state = None  # built lazily: epoch depends on the first now
        self._epoch0 = None
        self._loop = None
        # The last settle's registered band gather: while the session is
        # live its block is session-pinned (the recipe chain must not
        # count its bytes); adopt/teardown flips it back to counted.
        self._standing_gather = None

    # -- state lifecycle -----------------------------------------------------

    def _build_state(self, epoch0: float):
        from bayesian_consensus_engine_tpu.parallel.distributed import (
            global_slot_block,
        )
        from bayesian_consensus_engine_tpu.parallel.sharded import (
            MarketBlockState,
        )
        from bayesian_consensus_engine_tpu.utils.config import (
            DEFAULT_CONFIDENCE as _CONF0,
            DEFAULT_RELIABILITY as _REL0,
        )
        from bayesian_consensus_engine_tpu.utils.timeconv import NEVER

        store, mesh, cdtype = self._store, self._mesh, self._cdtype
        band_mask = self._band_mask
        safe = np.where(self._band_rows >= 0, self._band_rows, 0)
        # sync=False: settle() already resolved any deferral that touches
        # this session's rows; a standing one is disjoint (stale only at
        # rows band_mask never selects — padding reads row 0 but is masked).
        host_rel, host_conf, host_days, host_exists = store.host_rows(
            safe, sync=False
        )
        self._state = MarketBlockState(
            reliability=global_slot_block(
                np.where(band_mask, host_rel, _REL0).astype(cdtype),
                mesh, self._padded_total,
            ),
            confidence=global_slot_block(
                np.where(band_mask, host_conf, _CONF0).astype(cdtype),
                mesh, self._padded_total,
            ),
            updated_days=global_slot_block(
                np.where(
                    band_mask & (host_days > NEVER), host_days - epoch0, 0.0
                ).astype(cdtype),
                mesh, self._padded_total,
            ),
            exists=global_slot_block(
                band_mask & host_exists, mesh, self._padded_total
            ),
        )
        self._epoch0 = epoch0
        if self._loop is None:
            # Shared per mesh, not per session: the jit tracing cache lives
            # on the wrapper instance, so a fresh build_cycle_loop() here
            # would retrace (and re-compile) every per-batch session of a
            # sharded settle_stream even at identical shapes.
            self._loop = _cached_cycle_loop(mesh)

    def _settle_preamble(
        self, outcomes: Sequence[bool], now: Optional[float]
    ) -> tuple:
        """The shared pre-dispatch path of :meth:`settle` and
        :meth:`settle_with_tiebreak`: plan/outcome validation, (re)build
        of the resident state, exact host confidences, and the band-local
        outcome columns. Returns ``(now_abs, conf_exact, outcome_band)``.
        """
        store, plan = self._store, self._plan
        timeline = active_timeline()
        _check_plan(store, plan, outcomes)
        now_abs = _now_days() if now is None else now
        if self._state is None or now_abs <= self._epoch0:
            # First settle, or time ran backwards past the epoch (stamps
            # would go non-positive): (re)build from host at an epoch below
            # now. The rebuild path keeps the rare backdated case bit-equal
            # to the one-shot settle_sharded (no stamp re-expression drift).
            # Deferred settlements merge ONLY if one touches this plan's
            # rows: a streamed service whose batches bring fresh markets
            # (disjoint rows) keeps its predecessors' band gathers deferred
            # — the device→host fetch overlaps later work instead of
            # stalling this build (chain bounded at 8 by the store).
            if store.pending_overlaps(self._touched):
                store.sync()
            with timeline.span("upload"):
                self._build_state(
                    min(store.epoch_origin(sync=False), now_abs - 1.0)
                )

        conf_exact = store.host_confidences(self._touched)
        # Band-local outcome columns, padded to the band width (band mode:
        # outcomes ARE band-local; global mode: pad globally then slice).
        band_width = self._hi - self._lo
        outcome_arr = np.asarray(outcomes, dtype=bool)
        if self._band is None:
            outcome_band = np.pad(
                outcome_arr,
                (0, self._padded_total - len(outcome_arr)),
                constant_values=False,
            )[self._lo:self._hi]
        else:
            outcome_band = np.pad(
                outcome_arr,
                (0, band_width - len(outcome_arr)),
                constant_values=False,
            )
        return now_abs, conf_exact, outcome_band

    def _settle_commit(
        self, new_state, steps: int, now_abs: float, conf_exact
    ) -> None:
        """The shared post-dispatch path: retain the new block, register
        the merge recipe (closed-form stamps/existence; reliabilities stay
        on device behind a lazy band gather until a host read needs them),
        and replay the exact host confidences."""
        self._state = new_state
        np_dtype = np.dtype(self._cdtype).type
        stamp_rel = np_dtype(
            np_dtype(now_abs - self._epoch0) + np_dtype(steps - 1)
        )
        gather = _BandGather(
            new_state.reliability, self._band_mask, session=self
        )
        self._store.defer_settle_recipe(
            self._touched, gather, self._epoch0, stamp_rel,
        )
        self._standing_gather = gather
        _replay_confidences(self._store, self._touched, conf_exact, steps)

    def _band_live(self) -> tuple:
        """(live, keys) for this process's band of the current plan.

        A band can lie entirely in padding (more band capacity than
        markets): clamp so keys and per-market views stay aligned
        (maybe empty)."""
        plan = self._plan
        if self._band is None:
            band_stop = min(self._hi, plan.num_markets)
            return max(0, band_stop - self._lo), plan.market_keys[
                self._lo:band_stop
            ]
        return plan.num_markets, plan.market_keys  # the plan IS the band

    def settle(
        self,
        outcomes: Sequence[bool],
        steps: int = 1,
        now: Optional[float] = None,
    ) -> SettlementResult:
        """Run *steps* cycles on the retained sharded state."""
        import jax.numpy as jnp

        from bayesian_consensus_engine_tpu.parallel.distributed import (
            global_market,
        )

        now_abs, conf_exact, outcome_band = self._settle_preamble(
            outcomes, now
        )
        with active_timeline().span("settle_dispatch"):
            outcome_g = global_market(
                outcome_band, self._mesh, self._padded_total
            )
            new_state, consensus = self._loop(
                self._probs_g, self._mask_g, outcome_g, self._state,
                jnp.asarray(now_abs - self._epoch0, dtype=self._cdtype),
                steps,
            )
        self._settle_commit(new_state, steps, now_abs, conf_exact)
        live, keys = self._band_live()
        return SettlementResult(
            market_keys=keys,
            consensus=_BandView(consensus, self._lo, live),
        )

    def settle_with_tiebreak(
        self,
        outcomes: Sequence[bool],
        steps: int = 1,
        now: Optional[float] = None,
        chunk_agents: "int | str | None" = "default",
        precision: int = 6,
    ) -> tuple:
        """Settle AND tie-break the batch in ONE compiled program per chip.

        The co-resident entry point the ring memory diet exists for
        (ROADMAP item 4): the chunked tie-break
        (:func:`~.parallel.sharded.build_cycle_tiebreak_loop`) runs inside
        the same dispatch as the consensus/update loop, against the same
        resident reliability block — no second program competing for HBM,
        no re-upload of the block it already holds. Returns
        ``(SettlementResult, RingTieBreakResult)`` where the tie-break
        fields are per-market band views over this process's markets: each
        signalling slot enters as one agent with its probability as the
        prediction and its decayed READ reliability (the pre-update view
        this settle's consensus weighs with, at *now*) as both weight and
        reliability score.

        Settlement semantics — state merge recipe, confidence replay,
        journal/export bytes — are exactly :meth:`settle`'s (the shared
        commit path); the consensus itself comes from this entry's own
        compiled program, equal to :meth:`settle`'s to float tolerance
        (fusion may associate differently), so a stream that mixes both
        entries should not expect bitwise-stable consensus across the mix.
        ``chunk_agents="default"`` takes the recorded
        :data:`~.ops.tiebreak.DEFAULT_CHUNK_AGENTS` (the diet is ON here
        by default — co-residency is the point); ``None`` opts back into
        the unchunked accumulation.
        """
        import jax.numpy as jnp

        from bayesian_consensus_engine_tpu.ops.tiebreak import (
            DEFAULT_CHUNK_AGENTS,
            RingTieBreakResult,
        )
        from bayesian_consensus_engine_tpu.parallel.distributed import (
            global_market,
        )

        if chunk_agents == "default":
            chunk_agents = DEFAULT_CHUNK_AGENTS
        elif isinstance(chunk_agents, str):
            raise ValueError(
                f"chunk_agents={chunk_agents!r}: the session entry takes "
                "an int, None (unchunked), or 'default' (the recorded "
                "DEFAULT_CHUNK_AGENTS); measured 'auto' tuning lives on "
                "parallel.ring.build_ring_tiebreak"
            )
        now_abs, conf_exact, outcome_band = self._settle_preamble(
            outcomes, now
        )
        loop = _cached_fused_tiebreak_loop(
            self._mesh, chunk_agents, precision
        )
        with active_timeline().span("settle_dispatch"):
            outcome_g = global_market(
                outcome_band, self._mesh, self._padded_total
            )
            new_state, consensus, tiebreak = loop(
                self._probs_g, self._mask_g, outcome_g, self._state,
                jnp.asarray(now_abs - self._epoch0, dtype=self._cdtype),
                steps,
            )
        self._settle_commit(new_state, steps, now_abs, conf_exact)
        live, keys = self._band_live()
        return (
            SettlementResult(
                market_keys=keys,
                consensus=_BandView(consensus, self._lo, live),
            ),
            RingTieBreakResult(
                *(_BandView(x, self._lo, live) for x in tiebreak)
            ),
        )

    def _graph_blocks(self, graph) -> tuple:
        """Device neighbour blocks for *graph* aligned to the CURRENT
        plan's market rows, cached per (graph, topology, padding).

        The cache key is the graph-side extension of the plan-reuse
        story: a probability-only refresh keeps ``market_keys`` (and so
        the alignment) identical, so the blocks — like the mask — ride
        the session across refreshes; a topology change or a different
        graph misses and re-aligns. Keyed by
        :meth:`~.analytics.graph.MarketGraph.extended_fingerprint` over
        the plan's topology digest when the plan carries one, else by
        market-key equality.
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from bayesian_consensus_engine_tpu.parallel.mesh import (
            MARKETS_AXIS,
        )

        plan = self._plan
        if plan.fingerprint is not None:
            key = (
                graph.extended_fingerprint(plan.fingerprint),
                self._padded_total,
            )
            keys_sig = None
        else:
            key = (graph.fingerprint, self._padded_total)
            keys_sig = plan.market_keys
        cached = getattr(self, "_graph_cache", None)
        if (
            cached is not None
            and cached[0] == key
            and (keys_sig is None or cached[1] == keys_sig)
        ):
            return cached[2]
        neighbor_idx, neighbor_w = graph.align(
            plan.market_keys, self._padded_total
        )
        sharding = NamedSharding(
            self._mesh, PartitionSpec(MARKETS_AXIS, None)
        )
        blocks = (
            jax.device_put(neighbor_idx, sharding),
            jax.device_put(neighbor_w, sharding),
        )
        self._graph_cache = (
            key, list(keys_sig) if keys_sig is not None else None, blocks
        )
        return blocks

    def settle_with_analytics(
        self,
        outcomes: Sequence[bool],
        steps: int = 1,
        now: Optional[float] = None,
        analytics=None,
        kernel: Optional[str] = None,
        sweep_kernel: Optional[str] = None,
    ) -> tuple:
        """Settle AND analyse the batch in ONE compiled program per chip.

        The round-12 extension of :meth:`settle_with_tiebreak`
        (ROADMAP items 4/5): the fused program
        (:func:`~.parallel.sharded.build_cycle_analytics_loop`) runs the
        N-cycle settlement loop, the chunked ring tie-break, the
        uncertainty bands, and — when *analytics* carries a
        :class:`~.analytics.graph.MarketGraph` — the damped
        correlated-market sweep, all against the one resident
        reliability block. Returns ``(SettlementResult,
        RingTieBreakResult, UncertaintyBands, propagated-or-None)``
        where every analytics field is a per-market band view over this
        process's markets.

        *analytics* is an :class:`~.analytics.bands.AnalyticsOptions`
        (``None`` → the defaults: recorded chunk sizes, 95% bands, no
        graph; ``tiebreak=False`` drops the ring stage from the program
        and returns ``None`` in its slot; ``tiebreak="sorted"`` swaps in
        the sort-based grouping kernel for CPU-heavy deployments —
        group metrics byte-equal to the ring path on
        exactly-representable weights, empty rows keep each kernel's
        own convention). Settlement semantics — state
        merge recipe, confidence
        replay, journal/export bytes — are exactly :meth:`settle`'s (the
        shared commit path), and the consensus comes out of the same
        loop scaffold: bit-equal to :meth:`settle`'s on the tier-1
        backend (pinned by tests/test_analytics.py — the analytics
        on/off byte-parity contract the serving layer's ``analytics=``
        mode rests on). Bands/tie-break/sweep are pure-additive reads of
        the PRE-update state at *now*; nothing analytics-side is ever
        written back.

        *kernel* (round 14; ``None`` defers to ``analytics.kernel``)
        routes the whole fused program: ``"xla"`` — the multi-pass
        program, the production default; ``"pallas"`` — the one-pass
        settlement kernel (``ops/pallas_settle.py``: consensus,
        tie-break, and band moments in a single HBM sweep per tile,
        outputs AND store bytes bit-identical to the XLA program,
        pinned by tests/test_pallas_settle.py); ``"auto"`` — the
        honesty-guarded shape tuner (knob ``settle_kernel``): XLA ships
        unless the kernel strictly won this shape's A/B.

        *sweep_kernel* (round 19; ``None`` defers to
        ``analytics.sweep_kernel``) routes the graph sweep, orthogonal
        to *kernel*: ``"pallas"`` runs the VMEM-resident
        belief-propagation kernel (``ops/pallas_bp.py`` — moment state
        carried in VMEM across all sweep iterations, outputs AND store
        bytes bit-identical to the XLA sweep, pinned by
        tests/test_pallas_bp.py); ``"auto"`` asks the honesty-guarded
        tuner (knob ``sweep_kernel``). Needs a graph (or blocks) — the
        loop builder refuses ``"pallas"`` with no sweep to offload.
        """
        import jax.numpy as jnp

        from bayesian_consensus_engine_tpu.analytics.bands import (
            AnalyticsOptions,
        )
        from bayesian_consensus_engine_tpu.ops.tiebreak import (
            DEFAULT_CHUNK_AGENTS,
            RingTieBreakResult,
        )
        from bayesian_consensus_engine_tpu.ops.uncertainty import (
            DEFAULT_CHUNK_SLOTS,
            UncertaintyBands,
        )
        from bayesian_consensus_engine_tpu.parallel.distributed import (
            global_market,
        )

        options = analytics if analytics is not None else AnalyticsOptions()

        def resolve(value, recorded, knob):
            if value == "default":
                return recorded
            if isinstance(value, str):
                raise ValueError(
                    f"{knob}={value!r}: the session entry takes an int, "
                    "None (unchunked), or 'default' (the recorded "
                    "default); measured 'auto' tuning lives on the "
                    "standalone builders"
                )
            return value

        chunk_agents = resolve(
            options.chunk_agents, DEFAULT_CHUNK_AGENTS, "chunk_agents"
        )
        chunk_slots = resolve(
            options.chunk_slots, DEFAULT_CHUNK_SLOTS, "chunk_slots"
        )
        tiebreak_opt = options.tiebreak
        if tiebreak_opt not in (True, False, "sorted"):
            raise ValueError(
                f"tiebreak={tiebreak_opt!r}: True (ring), False (off), "
                "or 'sorted' (the sort-based grouping kernel)"
            )
        tiebreak_kind = "sorted" if tiebreak_opt == "sorted" else "ring"
        kernel = kernel if kernel is not None else options.kernel
        sweep_kernel = (
            sweep_kernel if sweep_kernel is not None
            else options.sweep_kernel
        )
        graph = options.graph
        # Cluster posture (round 13): bands and the tie-break are
        # per-market reductions over the sources axis, so they serve a
        # banded session unchanged (pinned by tests/test_cluster.py).
        # What cannot be served yet is named, not left to fail deep in
        # alignment or collectives with a generic shape error.
        from bayesian_consensus_engine_tpu.cluster.recover import (
            ClusterModeUnsupported,
        )

        if _process_count() > 1:
            raise ClusterModeUnsupported(
                "settle_with_analytics on a multi-controller runtime is "
                "not served yet: route cluster deployments through the "
                "shared-nothing band membership (cluster.membership."
                "MeshView — each host serves its band on its local mesh, "
                "where the full analytics tier runs unchanged); the "
                "hybrid DCN×ICI analytics program is the ROADMAP "
                "follow-up this error names"
            )
        # Round 18: graph + band plans no longer refuse. On a single
        # controller the plan cache only admits the band that covers the
        # whole global axis (rows [0, M) — `process_market_rows` pins
        # lo to 0), so the fused sweep's all_gather sees every market
        # and the banded session runs the IDENTICAL program as the
        # whole-axis session (byte parity pinned by tests/test_infer.py
        # and tests/test_cluster.py). Genuine multi-host sub-bands stop
        # at the multi-controller gate above; the cross-band halo
        # machinery their hybrid program will stand on lives in
        # infer/partition.py (bit parity pinned at host level).
        inference = options.inference
        blocks = options.blocks
        if blocks is not None:
            from bayesian_consensus_engine_tpu.infer.blocks import (
                MarketBlocks,
            )

            if not isinstance(blocks, MarketBlocks):
                raise TypeError(
                    "analytics.blocks takes an infer.MarketBlocks; got "
                    f"{type(blocks).__name__}"
                )
            if graph is None:
                # Blocks alone ARE the graph: compile the constraint
                # edges. A caller composing blocks with correlation
                # edges builds the merged graph once via
                # MarketBlocks.to_graph(extra_edges=...) and passes it
                # as graph= (the projection still applies).
                graph = blocks.to_graph()
        sweep_mode = "point"
        sweep_tol = None
        sweep_steps = graph.steps if graph is not None else 0
        damping = graph.damping if graph is not None else 0.0
        if inference is not None:
            from bayesian_consensus_engine_tpu.infer.bp import (
                InferenceOptions,
            )

            if not isinstance(inference, InferenceOptions):
                raise TypeError(
                    "analytics.inference takes an infer.InferenceOptions; "
                    f"got {type(inference).__name__}"
                )
            if graph is None:
                raise ValueError(
                    "analytics.inference needs a graph to sweep over — "
                    "set graph= (correlation edges) or blocks= "
                    "(combinatorial constraints)"
                )
            damping, sweep_steps, sweep_tol = inference.resolve(graph)
            sweep_mode = "moments" if inference.moments else "point"

        now_abs, conf_exact, outcome_band = self._settle_preamble(
            outcomes, now
        )
        with active_timeline().span("analytics"):
            # The analytics tier's OWN overhead only: graph alignment/
            # upload and program resolution. The shared preamble/commit
            # stay attributed exactly as settle() leaves them, and the
            # fused kernel dispatch stays on `settle_dispatch` — an
            # analytics-on vs -off phase comparison then isolates what
            # analytics actually added.
            graph_args = (
                self._graph_blocks(graph) if sweep_steps > 0 else ()
            )
            loop = _cached_analytics_loop(
                self._mesh, chunk_agents, chunk_slots, options.precision,
                options.z, damping, sweep_steps, bool(tiebreak_opt),
                tiebreak_kind, kernel, sweep_mode, sweep_tol,
                sweep_kernel,
            )
        with active_timeline().span("settle_dispatch"):
            outcome_g = global_market(
                outcome_band, self._mesh, self._padded_total
            )
            new_state, consensus, tiebreak, bands, propagated = loop(
                self._probs_g, self._mask_g, outcome_g, self._state,
                jnp.asarray(now_abs - self._epoch0, dtype=self._cdtype),
                steps,
                *graph_args,
            )
        self._settle_commit(new_state, steps, now_abs, conf_exact)
        live, keys = self._band_live()
        if propagated is not None and blocks is not None:
            propagated = self._project_blocks(
                propagated, blocks, keys, live
            )
        if propagated is None:
            prop_out = None
        elif isinstance(propagated, PropagatedBeliefs):
            prop_out = PropagatedBeliefs(
                mean=_BandView(propagated.mean, self._lo, live),
                stderr=_BandView(propagated.stderr, self._lo, live),
                iters_run=propagated.iters_run,
                residual=propagated.residual,
            )
        else:
            prop_out = _BandView(propagated, self._lo, live)
        return (
            SettlementResult(
                market_keys=keys,
                consensus=_BandView(consensus, self._lo, live),
            ),
            (
                RingTieBreakResult(
                    *(_BandView(x, self._lo, live) for x in tiebreak)
                )
                if tiebreak is not None else None
            ),
            UncertaintyBands(
                *(_BandView(x, self._lo, live) for x in bands)
            ),
            prop_out,
        )

    def _project_blocks(self, propagated, blocks, keys, live: int):
        """Apply the combinatorial-block projection to the propagated
        analytics output (host-side, deterministic — infer/blocks.py).

        Only the ADDITIVE propagated vector is rewritten; consensus,
        bands, state, and every byte surface stay exactly as the fused
        program left them.
        """
        import jax.numpy as jnp

        lo = self._lo
        is_moments = isinstance(propagated, PropagatedBeliefs)
        mean_arr = np.asarray(
            propagated.mean if is_moments else propagated
        ).copy()
        stderr_arr = (
            np.asarray(propagated.stderr).copy() if is_moments else None
        )
        proj_mean, proj_stderr = blocks.project(
            keys,
            mean_arr[lo:lo + live],
            None if stderr_arr is None else stderr_arr[lo:lo + live],
        )
        mean_arr[lo:lo + live] = proj_mean
        if is_moments:
            stderr_arr[lo:lo + live] = proj_stderr
            return PropagatedBeliefs(
                mean=jnp.asarray(mean_arr),
                stderr=jnp.asarray(stderr_arr),
                iters_run=propagated.iters_run,
                residual=propagated.residual,
            )
        return jnp.asarray(mean_arr)

    def refresh(self, plan: SettlementPlan) -> None:
        """Adopt a probability-only twin of the session's plan in place.

        *plan* must come from :meth:`SettlementPlan.refresh` on this
        session's current plan (same topology — the slot layout, row
        mapping, and mask are shared array objects). Only the new probs
        block is uploaded; ``mask_g`` and the retained block state stay
        device-resident, and the old probs block is donated. This is the
        steady-state daily re-settlement shape for a long-lived session:
        refresh with the day's probabilities, then :meth:`settle` with
        the day's outcomes — chained-settle semantics throughout (the
        state never leaves the device between calls).
        """
        if plan is self._plan:
            return
        if (
            plan.slot_rows is not self._plan.slot_rows
            or plan.mask is not self._plan.mask
        ):
            raise ValueError(
                "session refresh needs a probability-only twin of the "
                "current plan (SettlementPlan.refresh on the same "
                "topology); this plan has its own slot layout"
            )
        with active_timeline().span("upload"):
            (self._padded_total, self._lo, self._hi,
             self._band_rows, self._band_mask, self._probs_g,
             self._mask_g) = _sharded_plan_cache(
                plan, self._mesh, self._cdtype, self._band
            )
        self._plan = plan

    def _release_standing(self) -> None:
        """The session is leaving its current block: the standing recipe
        (if any) becomes the only thing pinning it, so its bytes count
        against the store's deferral budget again (held_nbytes checks
        ``_standing_gather is self`` through a weakref, so clearing the
        attribute — or the session dying — is the release)."""
        self._standing_gather = None

    def adopt(self, plan: SettlementPlan, band=None) -> str:
        """Swap the session onto a NEW-topology *plan* without teardown.

        The topology-miss half of the resident streamed service
        (:func:`settle_stream` with ``mesh=``): where :meth:`refresh`
        handles the steady-state probability-only twin, ``adopt`` takes
        any plan bound to the same store and carries the RESIDENT block
        across the swap. Returns how the swap was served:

        * ``"refresh"`` — *plan* shares the current plan's topology
          arrays (the fingerprint-hit fast path): probs-only upload.
        * ``"relayout"`` — the resident block was carried across the
          swap. Single-controller sessions whose band spans the whole
          axis relayout IN HBM
          (:func:`~.parallel.sharded.relayout_slot_state`): rows
          STAYING in the active set move with it (zero host traffic),
          rows ENTERING upload their host values (O(entering) — fresh
          markets enter as cold defaults), and rows LEAVING stay
          covered by the standing sync recipe, reaching the host store
          lazily at the next checkpoint/sync exactly as any deferred
          band gather does. Cluster sessions (``band=`` partial bands,
          or a multi-controller runtime) stage the SAME permutation
          through process-local host buffers instead (round 13,
          retiring the PR-5 teardown+rebuild fallback): per-band
          topology drift is a process-local event, so a collective
          device relayout would force every host to synchronise its
          misses — the staged path touches only this process's band,
          dispatches no collective, and keeps the session (plan cache,
          standing recipe, store deferrals) fully resident. Either
          flavour: capacity-ladder growth of the padded extents re-pads
          the block in place, and the result is bit-equal to tearing
          the session down and rebuilding (pinned by
          tests/test_overlap.py and tests/test_cluster.py).
        * ``"rebuild:<reason>"`` — the resident state was dropped; the
          next :meth:`settle` rebuilds from host (the per-batch-session
          cost). The reason names why — the observability hook the
          ``stream.resident_fallbacks`` counter and the per-batch
          ``stats["session_adopt"]`` field surface: ``no-resident-state``
          (nothing to carry yet), ``band-change`` (the global axis was
          re-partitioned — a membership-epoch event; recovery rebuilds
          through journal replay, :mod:`~.cluster.recover`), or
          ``backdated-stamps`` (an entering row's host stamp cannot be
          re-expressed against the session epoch).
        """
        if band == self._band:
            # The hit shortcut only applies within the SAME band: a band
            # change under a shared topology still re-slices rows, so it
            # must take the miss path (which rebuilds for band mode).
            if plan is self._plan:
                return "refresh"
            if (
                plan.slot_rows is self._plan.slot_rows
                and plan.mask is self._plan.mask
            ):
                self.refresh(plan)
                return "refresh"
        # Topology miss from here on: the swap work (row-set delta, host
        # reads for entering rows, device relayout) is the ``state_adopt``
        # phase; the probs/mask upload inside the plan cache stays
        # attributed to ``upload`` (exclusive nesting).
        with active_timeline().span("state_adopt"):
            return self._adopt_miss(plan, band)

    def _adopt_miss(self, plan: SettlementPlan, band) -> str:
        from bayesian_consensus_engine_tpu.parallel.sharded import (
            MarketBlockState,
            relayout_slot_state,
        )
        from bayesian_consensus_engine_tpu.utils.timeconv import NEVER

        store = self._store
        old_state = self._state
        old_band_rows, old_band_mask = self._band_rows, self._band_mask
        old_lo, old_hi, old_total = self._lo, self._hi, self._padded_total
        band_changed = band != self._band
        self._band = band
        with active_timeline().span("upload"):
            (self._padded_total, self._lo, self._hi,
             self._band_rows, self._band_mask, self._probs_g,
             self._mask_g) = _sharded_plan_cache(
                plan, self._mesh, self._cdtype, band
            )
        self._plan = plan
        self._touched = self._band_rows[self._band_mask]
        # The session is mid-swap from here: drop the resident binding
        # FIRST, so an exception anywhere below (a sync failure, a device
        # error in the relayout) leaves a clean rebuild posture — never
        # the OLD layout's block bound to the NEW plan, which a retrying
        # caller would silently settle against the wrong rows. The old
        # block stays reachable through the standing recipe (and the
        # local reference) for the relayout/recipe resolution.
        self._release_standing()
        self._state = None
        if old_state is None:
            return "rebuild:no-resident-state"
        if band_changed:
            # A band change re-partitions the global axis — a membership
            # epoch event, not in-band drift: the row universe itself
            # moved, and state crossing bands travels through journal
            # replay (cluster/recover.py), never through a live block.
            return "rebuild:band-change"

        # Row-set delta between the outgoing and incoming layout, as flat
        # BAND-LOCAL slot-major positions (each plan maps a row to exactly
        # one slot; a whole-axis band's local positions ARE global).
        old_pos = np.flatnonzero(old_band_mask.ravel())
        old_rows = old_band_rows.ravel()[old_pos]
        new_pos = np.flatnonzero(self._band_mask.ravel())
        new_rows = self._band_rows.ravel()[new_pos]
        if old_rows.size:
            order = np.argsort(old_rows, kind="stable")
            sorted_rows = old_rows[order]
            sorted_pos = old_pos[order]
            idx = np.minimum(
                np.searchsorted(sorted_rows, new_rows), old_rows.size - 1
            )
            staying = sorted_rows[idx] == new_rows
        else:
            sorted_pos = old_pos
            idx = np.zeros(new_rows.size, dtype=np.int64)
            staying = np.zeros(new_rows.size, dtype=bool)
        entering_rows = new_rows[~staying]
        entering_pos = new_pos[~staying]

        # Entering rows may sit behind an OLDER deferred recipe (e.g. a
        # previous adopt's leaving rows re-entering): resolve before
        # reading their host values. The session's own standing recipe
        # covers only the outgoing set — disjoint from entering rows by
        # construction — so the steady drift case never syncs here.
        if entering_rows.size and store.pending_overlaps(entering_rows):
            store.sync()
        host_rel, host_conf, host_days, host_exists = store.host_rows(
            entering_rows, sync=False
        )
        live = host_days > NEVER
        rel_days = np.where(live, host_days - self._epoch0, 0.0)
        if bool((live & (rel_days <= 0)).any()):
            # A host stamp at/below the session epoch has no positive
            # relative expression (backdated writes): stay in the rebuild
            # posture; the next settle rebuilds at a fresh epoch.
            return "rebuild:backdated-stamps"

        np_cdtype = np.dtype(self._cdtype)
        whole_axis = (
            old_lo == 0 and old_hi == old_total
            and self._lo == 0 and self._hi == self._padded_total
        )
        if whole_axis and _process_count() == 1:
            # Single-controller, whole-axis band: the local position maps
            # are global, so the permutation runs IN HBM — zero host
            # traffic for staying rows.
            src = np.full(self._band_mask.size, -1, dtype=np.int64)
            src[new_pos[staying]] = sorted_pos[idx[staying]]
            self._state = relayout_slot_state(
                old_state,
                src,
                entering_pos,
                host_rel.astype(np_cdtype),
                host_conf.astype(np_cdtype),
                rel_days.astype(np_cdtype),
                host_exists.astype(bool),
                self._band_mask.shape,
                mesh=self._mesh,
            )
            return "relayout"

        # Cluster posture (multi-controller runtime, or a band covering
        # part of the global axis): stage the SAME permutation through
        # process-local host buffers. Per-band topology drift is a
        # process-local event — host A's batch can be a fingerprint hit
        # while host B's drifts — so a device-side relayout (whose gather
        # lowers to collectives on the markets axis) would require every
        # host to synchronise its misses; the staged path touches only
        # this process's band columns and dispatches nothing collective.
        # Same value sources as the device path (staying rows from the
        # live block, entering rows host-exact), so byte-parity with
        # teardown+rebuild holds by the same argument — pinned by
        # tests/test_cluster.py::TestClusterAdopt.
        from bayesian_consensus_engine_tpu.parallel.distributed import (
            global_slot_block,
            local_view,
        )

        old_local = tuple(
            local_view(x)
            for x in (
                old_state.reliability, old_state.confidence,
                old_state.updated_days, old_state.exists,
            )
        )

        def relaid(old_arr, fill, entered, dtype):
            flat = np.full(self._band_mask.size, fill, dtype=dtype)
            flat[new_pos[staying]] = old_arr.ravel()[
                sorted_pos[idx[staying]]
            ].astype(dtype, copy=False)
            if entering_pos.size:
                flat[entering_pos] = entered
            return flat.reshape(self._band_mask.shape)

        self._state = MarketBlockState(
            reliability=global_slot_block(
                relaid(old_local[0], DEFAULT_RELIABILITY,
                       host_rel.astype(np_cdtype), np_cdtype),
                self._mesh, self._padded_total,
            ),
            confidence=global_slot_block(
                relaid(old_local[1], DEFAULT_CONFIDENCE,
                       host_conf.astype(np_cdtype), np_cdtype),
                self._mesh, self._padded_total,
            ),
            updated_days=global_slot_block(
                relaid(old_local[2], 0.0,
                       rel_days.astype(np_cdtype), np_cdtype),
                self._mesh, self._padded_total,
            ),
            exists=global_slot_block(
                relaid(old_local[3], False,
                       host_exists.astype(bool), np.dtype(bool)),
                self._mesh, self._padded_total,
            ),
        )
        return "relayout"

    def sync(self) -> None:
        """Merge every deferred settlement into the host store now."""
        self._store.sync()

    def close(self) -> None:
        self.sync()
        self._release_standing()
        self._state = None

    def __enter__(self) -> "ShardedSettlementSession":
        return self

    def __exit__(self, *exc_info: object) -> None:
        self.close()


def settle_sharded(
    store,
    plan: SettlementPlan,
    outcomes: Sequence[bool],
    mesh,
    steps: int = 1,
    now: Optional[float] = None,
    dtype=None,
) -> SettlementResult:
    """Markets-sharded end-to-end settlement over a device *mesh*.

    The distributed twin of :func:`settle` for one LOGICAL store: because a
    (source, market) pair belongs to exactly one market, the slot-major
    (K, M) settlement block sharded over the markets axis is a *complete*
    partition of every row the settlement touches — so the gather/scatter
    happens at the host boundary, per process band, and the device runs the
    production sharded loop (``parallel.sharded.build_cycle_loop``) with
    zero cross-market communication. This replaces the reference's
    whole-store sweep + per-pair update (reference: market.py:200-221,
    reliability.py:185-231) sharded across a TPU mesh.

    Multi-process: each process feeds only its own band of market columns
    (``process_market_rows``) via ``global_slot_block`` — no host ever
    materialises the full block — and absorbs back exactly its band's rows
    (its shard of the store). The returned result covers THIS process's
    band of markets (single-process: all of them). ``plan``/``outcomes``
    are indexed globally on every process.

    Numerics: identical elementwise ops and per-market reduction order as
    :func:`settle`, so results and post-settle store state are bit-identical
    to the single-device path at the same dtype — on a markets-only mesh.
    A sources-sharded (2-D) mesh splits each market's slot reduction into a
    ``psum`` of per-shard partial sums, a different (deterministic) float
    association: equal to ~1 ulp, not bitwise.

    One-shot wrapper around :class:`ShardedSettlementSession` (state built,
    one settle, synced into the store before returning); chain settlements
    through a session directly to keep the block device-resident across
    calls.
    """
    session = ShardedSettlementSession(store, plan, mesh, dtype=dtype)
    result = session.settle(outcomes, steps=steps, now=now)
    session.close()
    return result


class PlanPrefetcher:
    """Build settlement plans one batch ahead on a worker thread.

    Ingest (pack → intern → block fill) is pure host-CPU work that round-3
    measurements put at ~9-13 s per 1M-market batch, while the settle it
    feeds is device work the host merely dispatches — so a stream of
    batches settled serially leaves the chip idle during every ingest and
    the host idle during every settle. This iterator overlaps them: ONE
    worker thread builds plan N+1 (and, with ``depth`` > 1, N+1+k) while
    the caller settles plan N.

        with PlanPrefetcher(store, batches, num_slots=K) as plans:
            for plan, outcomes in zip(plans, outcome_batches):
                settle(store, plan, outcomes, steps=steps)

    Equivalence: builds run sequentially on the single worker in batch
    order, so interning order — and therefore row assignment, block
    content, and settlement results — is identical to the serial loop
    (tests/test_pipeline.py pins it). The store's host tier is
    thread-safe (``TensorReliabilityStore._locked``), which is what makes
    the worker's interning safe against the caller's settle-side host
    reads.

    ``columnar=False``: *batches* yields dict payloads
    (:func:`build_settlement_plan`). ``columnar=True``: *batches* yields
    ``(market_keys, source_ids, probabilities, offsets)`` tuples
    (:func:`build_settlement_plan_columnar`). Pass ``num_slots`` pinned
    across batches — otherwise each batch's natural K compiles its own
    settle program. A build error surfaces on the ``next()`` that would
    have yielded that plan; later batches are not attempted.

    ``reuse_plans=True`` engages the DELTA-INGEST fast path: each batch's
    topology is fingerprinted (:func:`~.core.batch.topology_fingerprint`)
    and, when it matches the previous batch's, the previous plan is
    :meth:`~SettlementPlan.refresh`-ed with the new probabilities instead
    of rebuilt — skipping pack, intern, and block fill entirely. A
    changed topology (drift, reordering, fresh markets) rebuilds. Dict
    payloads are flattened once to columns
    (:func:`~.core.batch.columns_from_payloads`) and built through the
    columnar path, which is bit-identical to the dict path by contract.
    Refreshed plans settle bit-identically to rebuilt ones (pinned by
    tests/test_overlap.py).

    ``intern_mode`` (``"auto"`` default) routes every columnar build's
    pair interning through the store's epoch-persistent pair table, so
    the batches that DON'T hit the topology fingerprint — drifted
    topologies, the very case ``reuse_plans`` cannot help — intern only
    their pair-delta; ``"full"`` restores the legacy every-pair walk.
    Byte-identical plans either way.
    """

    def __init__(
        self,
        store,
        batches,
        columnar: bool = False,
        num_slots: "int | str | None" = None,
        native: Optional[bool] = None,
        depth: int = 1,
        reuse_plans: bool = False,
        intern_mode: str = "auto",
    ) -> None:
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._queue: "_queue.Queue" = _queue.Queue(maxsize=depth)
        self._cancelled = threading.Event()
        self._sentinel = object()
        last_plan: "list[Optional[SettlementPlan]]" = [None]

        def build(batch):
            if not reuse_plans:
                if columnar:
                    keys, source_ids, probabilities, offsets = batch
                    return build_settlement_plan_columnar(
                        store, keys, source_ids, probabilities, offsets,
                        num_slots=num_slots, native=native,
                        intern_mode=intern_mode,
                    )
                return build_settlement_plan(
                    store, batch, native=native, num_slots=num_slots
                )
            if columnar:
                keys, source_ids, probabilities, offsets = batch
                probabilities = np.ascontiguousarray(
                    probabilities, dtype=np.float64
                )
            else:
                # Dict payloads flatten to columns HERE, on the worker —
                # one C pass when built — so the consumer thread never
                # pays a per-signal Python walk.
                keys, source_ids, probabilities, offsets = (
                    columns_from_payloads(batch, native=native)
                )
            digest = topology_fingerprint(keys, source_ids, offsets)
            prev = last_plan[0]
            if prev is not None and prev.fingerprint == digest:
                plan = prev.refresh(probabilities)
            else:
                plan = build_settlement_plan_columnar(
                    store, keys, source_ids, probabilities, offsets,
                    num_slots=num_slots, fingerprint=digest, native=native,
                    intern_mode=intern_mode,
                )
            last_plan[0] = plan
            return plan

        # Build times feed the metrics registry, not the timeline: the
        # worker overlaps the consumer's wall clock by design, so its
        # seconds must not enter the additive phase breakdown — the
        # consumer-visible share is settle_stream's "pack" wait span.
        build_hist = metrics_registry().histogram("stream.plan_build_s")

        def work():
            # The iterator itself may raise (a generator streaming payloads
            # from disk/network): that failure must surface on next() like
            # a build failure, never collapse into a clean StopIteration.
            import time as _time

            try:
                iterator = iter(batches)
                while not self._cancelled.is_set():
                    try:
                        batch = next(iterator)
                    except StopIteration:
                        break
                    build_start = _time.perf_counter()
                    plan = build(batch)
                    build_hist.observe(_time.perf_counter() - build_start)
                    self._put(("ok", plan))
            except BaseException as exc:  # noqa: BLE001 — re-raised on next()
                self._put(("err", exc))
            finally:
                self._put((self._sentinel, None))

        self._worker = threading.Thread(
            target=work, name="bce-plan-prefetch", daemon=True
        )
        self._worker.start()

    def _put(self, item) -> None:
        # A bounded put would deadlock against a consumer that stopped
        # consuming (close() mid-stream): poll with the cancel flag.
        while True:
            try:
                self._queue.put(item, timeout=0.1)
                return
            except _queue.Full:
                if self._cancelled.is_set():
                    return

    def __iter__(self) -> "PlanPrefetcher":
        return self

    def __next__(self) -> SettlementPlan:
        kind, value = self._queue.get()
        if kind is self._sentinel:
            self._queue.put((self._sentinel, None))  # stay terminated
            raise StopIteration
        if kind == "err":
            raise value
        return value

    def close(self) -> None:
        """Stop building; pending batches are dropped, the worker joined."""
        self._cancelled.set()
        while True:  # drain so a blocked worker put() can finish
            try:
                item = self._queue.get_nowait()
            except _queue.Empty:
                break
            if item[0] is self._sentinel:
                break
        self._worker.join(timeout=60)

    def __enter__(self) -> "PlanPrefetcher":
        return self

    def __exit__(self, *exc_info: object) -> None:
        self.close()


def settle_payloads(
    store,
    payloads: Payload,
    outcomes: Sequence[bool],
    steps: int = 1,
    now: Optional[float] = None,
    db_path=None,
) -> SettlementResult:
    """One-shot pipeline: plan, settle, and optionally checkpoint to SQLite."""
    plan = build_settlement_plan(store, payloads)
    result = settle(store, plan, outcomes, steps=steps, now=now)
    if db_path is not None:
        store.flush_to_sqlite(db_path)
    return result


def settle_stream(
    store,
    batches,
    steps: int = 1,
    now: Optional[float] = None,
    db_path=None,
    checkpoint_every: int = 1,
    num_slots: "int | str | None" = "bucket",
    columnar: bool = False,
    native: Optional[bool] = None,
    stats: Optional[list] = None,
    mesh=None,
    band=None,
    dtype=None,
    lazy_checkpoints: bool = False,
    journal=None,
    reuse_plans: bool = False,
    sync_checkpoints: bool = False,
    resident_session: bool = True,
    intern_mode: str = "auto",
    trace=None,
):
    """The streamed settle-and-checkpoint service loop, fully overlapped.

    One generator wires the round-4 machinery together the way a
    long-running settlement service should run it:

    * plan N+1 builds on a prefetch thread while plan N settles
      (:class:`PlanPrefetcher`; ``num_slots`` defaults to ``"bucket"`` so
      wobbling batch widths share compiled settle programs);
    * settles chain device-resident (deferred absorb; the capacity-ladder
      state survives each batch's new interning);
    * every *checkpoint_every* batches the store checkpoints to *db_path*
      with the SQLite transaction on a background thread
      (:meth:`~.tensor_store.TensorReliabilityStore.flush_to_sqlite_async`),
      overlapping the write with the next batch's ingest + settle; a tail
      flush on exit makes the final file complete. A failed background
      write surfaces at the NEXT flush (bookkeeping rolled back — see
      FlushHandle); the final join re-raises any last-write failure.

    Sizing note: every ×2 growth of the store's capacity ladder compiles
    a fresh settle program (the flat state's shape changes). A service
    that knows its scale can pre-size —
    ``TensorReliabilityStore(capacity=expected_rows)`` — but whether
    that pays depends entirely on compile cost: pre-sizing cut a
    30-batch/1.5M-row COLD stream from 14.6 to 9.7 s (round-5 host
    measurement), yet with a warm persistent compile cache it LOSES
    (measured on-chip 2026-07-31, ``e2e_stream`` ``journal_presized``
    vs ``journal``: 0.84 vs 1.06 amortised 1M-cycles/sec) — the
    pre-sized program pays capacity-length gather/scatter from batch 1
    while the ladder's early batches run over small state and its
    recompiles are cache hits. Pre-size only when compiles are cold and
    uncached. (The ``mesh=`` path sidesteps the trade: its per-batch
    block shapes never depend on store size.)

    *batches* yields ``(payloads, outcomes)`` pairs — with
    ``columnar=True``, ``((market_keys, source_ids, probabilities,
    offsets), outcomes)``. ``now=None`` stamps wall clock per settle; a
    float is the first batch's settlement day, advancing one day per
    batch (the reference's daily re-settlement shape). Yields one
    :class:`SettlementResult` per batch, in order. Results, store state,
    and checkpoint files equal the serial build → settle → flush loop
    (pinned by tests/test_overlap.py).

    *reuse_plans* engages the topology-cached DELTA-INGEST fast path: the
    prefetcher fingerprints each batch's topology and, in the steady
    state where consecutive batches carry the same (source, market)
    universe (the reference's daily re-settlement shape), re-plans only
    the probability columns (:meth:`SettlementPlan.refresh`) — pack,
    intern, block fill, and the topology half of the host→device upload
    all drop out of the per-batch cost; the sharded path likewise keeps
    ``mask_g`` device-resident and uploads only the new probs block. Any
    topology change (drift, reordering, fresh markets) falls back to a
    full rebuild for that batch — the fast path is bit-exact with
    ``reuse_plans=False`` (results, store state, and checkpoint bytes;
    pinned by tests/test_overlap.py). Per-batch ``stats`` dicts record
    the hit/miss under ``"plan_reused"``.

    *stats*, if given, is a mutable list the service appends one dict per
    batch to: ``{"batch", "markets", "plan_wait_s", "settle_dispatch_s",
    "checkpoint_s", "plan_reused", "intern_s", "interned_pairs"}``
    (``intern_s``/``interned_pairs`` are the batch's pair-interning
    seconds and the pairs that actually walked the interner — zero on a
    plan-reuse refresh, the pair-DELTA under ``intern_mode="auto"``).
    ``plan_wait_s`` is how long the consumer waited on
    the prefetch thread (near zero once the pipeline fills; large values
    mean ingest is the bottleneck). ``settle_dispatch_s`` is the HOST
    cost of dispatching the settle — deliberately unfenced: the kernel
    runs asynchronously (fencing here would serialise away the overlap),
    so device time is NOT in it; device backpressure surfaces instead in
    ``checkpoint_s`` (the flush call drains the pending device results
    before snapshotting — unless *lazy_checkpoints*, whose flushes never
    drain, leaving backpressure invisible until the tail flush) —
    ``None`` on batches that didn't checkpoint.
    Raw floats, un-rounded. The dict for a batch is appended BEFORE its
    result is yielded — and before its checkpoint, so ``len(stats)`` is
    the SETTLED batch count even when a checkpoint failure aborts the
    stream: the failing batch has settled without yielding, and a
    restart must resume from ``batches[len(stats):]`` (re-settling it
    would double its updates — see examples/fault_tolerant_service.py).
    Under ``mesh=`` each dict also carries ``"session_adopt"``: how the
    resident session served the batch (``"start"``/``"refresh"``/
    ``"relayout"``/``"rebuild:<reason>"`` — the reason names the
    remaining fallback, see :meth:`ShardedSettlementSession.adopt`;
    ``None`` on the flat path and with *resident_session* off). The dispatch-only reading of
    ``settle_dispatch_s`` holds under ``mesh=`` too since round 7: the
    persistent session keeps the reliability block in HBM across
    batches, so nothing drains or re-uploads inside the settle window on
    a topology hit (with ``resident_session=False`` — the per-batch
    legacy shape — the old caveat returns: each batch's session build
    drains the previous batch's band gather and re-uploads host state,
    so read it as the full per-batch settle window there).

    When this thread is recording a phase timeline
    (:func:`~.obs.timeline.recording`), each stats dict additionally
    carries ``"phases"``: the batch's additive breakdown into the
    canonical :data:`~.obs.timeline.PHASES` names (exclusive seconds —
    ``checkpoint`` excludes the nested ``journal_fsync`` /
    ``interchange_export`` it wraps). Obs-disabled callers see the
    unchanged schema. The stream also feeds the process metrics registry
    (``stream.batches``, ``stream.plan_reuse_hits``/``misses``,
    ``stream.settle_dispatch_s``, ``stream.plan_build_s``) — all no-ops
    unless :func:`~.obs.metrics.set_metrics_registry` enabled one.
    With a process tracer active (:func:`~.obs.trace.set_tracer`) each
    batch additionally records a span chain — ``pack`` plus every
    canonical phase span taken inside its dispatch/checkpoint window,
    and the driver's ``durable_watermark`` — keyed by batch index
    (deterministic ids; same contract as the serving front end, and the
    same ``bce-tpu trace`` Perfetto export reads both). Under ``mesh=``
    an enabled metrics registry also gets ``hbm.bytes_in_use``/
    ``hbm.peak_bytes`` gauges sampled at the dispatch and checkpoint
    phase boundaries (zeros on backends without allocator stats).
    Tracing on vs off moves no settlement byte, like the rest of obs.

    *mesh*, if given, runs every settle sharded over the device mesh
    through ONE long-lived :class:`ShardedSettlementSession` (markets on
    the lane axis, source slots optionally split with a ``psum``
    reduction) held across batches — the round-7 resident service shape.
    On a topology-fingerprint hit (the ``reuse_plans`` steady state) a
    batch is: upload the probs block (:meth:`~ShardedSettlementSession.
    refresh`) → in-jit donated cycle loop → register the deferred
    band-gather recipe — ZERO reliability-state host traffic. On a miss
    the session is NOT torn down: :meth:`~ShardedSettlementSession.
    adopt` re-lays the resident block out for the new plan — in HBM on
    a whole-axis single-controller session, via the process-local
    host-staged permutation in the cluster posture (``band=`` partial
    bands / multi-controller runtimes, round 13) — uploading only rows
    entering the active set (rows leaving reach the host lazily
    through the standing recipe; capacity-ladder growth re-pads in
    place). ``resident_session=False`` restores the per-batch-session
    legacy shape (one session per batch, state rebuilt from host each
    time) — kept for A/B benches; the resident path no longer falls
    back to it anywhere (``stream.resident_fallbacks`` counts the
    named ``rebuild:<reason>`` cases that remain).
    Either way the session's host-merge recipe resolves at the next
    checkpoint or the first later batch that OVERLAPS its rows (batches
    of fresh markets never stall on their predecessors' device→host
    gathers; the deferral chain is bounded at 8, older links applying
    early). Results, store state, and checkpoint files are bit-identical
    to the per-batch-session stream AND to the flat stream on a
    markets-only mesh (a 2-D mesh re-associates each market's slot sum
    into psum partials: ≤1 ulp on consensus, state updates quantised
    identically — see :func:`settle_sharded`); pinned by
    tests/test_overlap.py::TestResidentSessionStream.
    ``num_slots="bucket"`` remains the default; the mesh path
    additionally pads K to the sources-axis extent, so wobbling batch
    widths still share compiled settle programs.

    *band*, multi-process only: ``(lo, global_markets)`` marks each
    batch's plan as covering ONLY this process's markets — rows
    ``[lo, lo+M_plan)`` of a ``global_markets``-wide axis — or a
    callable ``band(batch_index) -> (lo, global_markets)`` when the
    global width wobbles per batch. Band mode needs a globally-agreed
    integer *num_slots* (``"bucket"`` pads per-process maxima, which
    processes disagree on). *dtype* overrides the mesh path's compute
    dtype (:func:`~.utils.dtypes.default_float_dtype` otherwise).

    *lazy_checkpoints* is RETIRED TO BENCH-ONLY (round-5 adjudication,
    the Pallas precedent): it lost every on-chip and CPU capture —
    0.57–0.77 amortised 1M-cycles/sec vs eager's 0.9–1.2 across the four
    round-5 banked runs — because what the lag defers it also
    un-overlaps. The flag remains for the standing ``e2e_stream``
    re-adjudication leg (and the tests that pin its torn-state
    semantics); production services should leave it off. Mechanics, for
    the record: it takes the checkpoint drain off the critical path:
    periodic flushes snapshot the APPLIED host truth without resolving
    deferred device results (``resolve_pending=False``), so they never
    block on the device — mid-stream files then lag by the deferred
    chain (bounded at 8 settles) instead of being current through the
    yielding batch. The tail flush on exit always resolves, so the final
    file is identical to the eager mode's. Trade-off, measured
    (bench.py ``e2e_stream`` A/B, CPU 2026-07-31): what the lag defers
    it also UN-OVERLAPS — periodic lazy flushes write almost nothing, so
    the tail flush pays one large serial write where eager mode streamed
    those rows on the background thread between batches. On this host's
    CPU backend lazy therefore LOSES (~0.75 vs ~0.50 amortised
    1M-cycles/sec); it can only pay where the device drain (not the
    SQLite write) dominates the flush — the remote-tunnel/TPU
    hypothesis the bench leg exists to adjudicate. Default off.

    *journal*, a :class:`~.state.journal.JournalWriter` (or a path, which
    opens one), moves ROLLING durability off the SQLite floor entirely:
    every *checkpoint_every* batches the service appends a journal epoch
    (raw binary columns at disk bandwidth, fsynced; tag = the settled
    batch index) instead of a rolling SQLite flush, and the SQLite
    interchange file is written ONCE by the tail flush at exit when
    *db_path* is also given. Measured on-chip 2026-07-31 (`e2e_stream`
    leg): the rolling-SQLite drain was 11.8 s of a 21.7 s / 1M-market
    stream wall; a journal epoch writes the same rows in ~0.3-0.5 s.
    Crash recovery: :func:`~.state.journal.replay_journal` rebuilds the
    store through the last complete epoch and returns its tag — resume
    from ``batches[tag + 1:]``, passing
    ``journal=JournalWriter(path, resume=True)`` to APPEND to the same
    journal (a bare path refuses to touch an existing journal rather
    than truncate durable epochs). The journal's durable point REPLACES
    the ``len(stats)`` recipe, which assumes rolling SQLite. With
    *journal* set, ``lazy_checkpoints`` must be off (an epoch's content
    is the drained truth by contract).

    Journal epochs are ASYNC by default (*sync_checkpoints* = False):
    the epoch's content is snapshotted in-loop (the delta drain + dirty-
    row copy — the ``checkpoint`` phase, now snapshot-cheap), and the
    frame/CRC/append/fsync run on a background writer thread
    (:meth:`~.state.tensor_store.TensorReliabilityStore.
    flush_to_journal_async`) that the NEXT epoch joins — writes
    serialise, and a background failure surfaces at that join (the
    ``journal_async_wait`` phase, near zero when the write overlapped the
    intervening batches), never silently. The durability contract at a
    yield is therefore *epoch N−1 fsynced, epoch N in flight*; a crash
    between them loses at most one cadence of batches, which replay's
    torn-frame drop and ``batches[tag + 1:]`` resumption already handle.
    Every clean exit (exhaustion, break/close, a batch error) joins the
    in-flight epoch in the tail, so the journal a caller observes after
    the stream ends is identical to the synchronous mode's.
    ``sync_checkpoints=True`` is the escape hatch restoring the strict
    "yield implies fsynced" — each epoch writes and fsyncs in-loop,
    today's pre-async semantics. The flag is journal-mode only: rolling
    SQLite checkpoints were already backgrounded and keep their
    semantics either way.

    *trace*, a :class:`~.state.journal.TraceWriter` (or a path, which
    opens one), records each batch's INPUTS — the columnar payload
    columns, outcomes, the resolved settlement day, and ``steps`` — as
    the journal's replayable-workload sidecar (the capture half of the
    counterfactual replay lab, ``replay/``; the journal itself stores
    only output deltas). Requires ``columnar=True`` (the trace format IS
    the columnar payload). With ``now=None`` the wall-clock day is
    resolved host-side per batch and the SAME value drives the settle
    and the trace frame, so a replay reproduces the recorded stamps
    exactly. Pair it with ``journal=``:
    :func:`~.state.journal.extract_trace` bounds the replayable workload
    by the journal's durable tag.
    """
    import time as _time

    if checkpoint_every < 1:
        raise ValueError("checkpoint_every must be >= 1")
    if band is not None and mesh is None:
        raise ValueError("band= requires mesh=")
    if journal is not None and lazy_checkpoints:
        raise ValueError(
            "journal= epochs are drained truth by contract; "
            "lazy_checkpoints cannot combine with a journal"
        )
    if band is not None and (
        isinstance(num_slots, bool)
        or not isinstance(num_slots, (int, np.integer))
    ):
        raise ValueError(
            "band mode needs a globally-agreed integer num_slots; "
            f"{num_slots!r} derives K from per-process maxima, which "
            "processes disagree on"
        )
    # Opened only after EVERY validation above: a journal path must never
    # be touched by a call that then refuses to run (JournalWriter itself
    # refuses to truncate an existing journal — resume by passing a
    # JournalWriter(path, resume=True) instance instead of a path).
    owns_journal = False
    if journal is not None and not hasattr(journal, "append_epoch"):
        from bayesian_consensus_engine_tpu.state.journal import JournalWriter

        journal = JournalWriter(journal)
        owns_journal = True
    if trace is not None and not columnar:
        raise ValueError(
            "trace= records the columnar payload columns verbatim; "
            "pass columnar=True (the replay workload format)"
        )
    owns_trace = False
    if trace is not None and not hasattr(trace, "append_batch"):
        from bayesian_consensus_engine_tpu.state.journal import TraceWriter

        trace = TraceWriter(trace)
        owns_trace = True
    # The loop body — session lifecycle, checkpoint cadence, exit contract
    # — is the serve-layer SessionDriver (round 8): this stream and the
    # online coalescing front end drive the same object, which is what
    # makes their byte-equality structural. Lazy import: serve sits at the
    # same layer and imports this module at its top level.
    from bayesian_consensus_engine_tpu.serve.driver import SessionDriver

    outcome_queue: "deque" = _collections.deque()
    trace_queue: "deque" = _collections.deque()

    def payload_stream():
        for payloads, outcomes in batches:
            outcome_queue.append(outcomes)
            if trace is not None:
                trace_queue.append(payloads)
            yield payloads

    # Observability (obs/): phase spans land on this thread's active
    # timeline (null by default — zero overhead), per-batch phase deltas
    # ride the stats dicts when a timeline is recording, and the stream
    # counters feed the process metrics registry (null by default).
    timeline = active_timeline()
    registry = metrics_registry()
    tracer = active_tracer()
    batches_counter = registry.counter("stream.batches")
    reuse_hit_counter = registry.counter("stream.plan_reuse_hits")
    reuse_miss_counter = registry.counter("stream.plan_reuse_misses")
    dispatch_hist = registry.histogram("stream.settle_dispatch_s")
    # Cumulative consumer seconds blocked on the prefetch thread — the
    # stream's live ingest-wait number (ledger/stats surface it; ~0 in
    # the steady state now that packing is native + overlapped).
    ingest_wait_gauge = registry.gauge("stream.ingest_wait_s")
    total_plan_wait = 0.0
    # Cumulative seconds the stream's plan builds spent in the pair-
    # interning pass (plan.intern_stats, measured where the intern runs —
    # the prefetch worker). Refreshed plans never intern, so in the
    # reuse_plans steady state this stays flat; under drift it is the
    # number the delta path shrinks (the e2e_ingest drift act's
    # acceptance, reported by e2e_stream_resident next to ingest_wait).
    intern_wait_gauge = registry.gauge("stream.intern_wait_s")
    total_intern_wait = 0.0

    driver = SessionDriver(
        store,
        steps=steps,
        mesh=mesh,
        dtype=dtype,
        resident_session=resident_session,
        journal=journal,
        owns_journal=owns_journal,
        db_path=db_path,
        checkpoint_every=checkpoint_every,
        sync_checkpoints=sync_checkpoints,
        lazy_checkpoints=lazy_checkpoints,
    )
    index = -1
    try:
        with PlanPrefetcher(
            store,
            payload_stream(),
            columnar=columnar,
            num_slots=num_slots,
            native=native,
            reuse_plans=reuse_plans,
            intern_mode=intern_mode,
        ) as plans:
            plan_iter = iter(plans)
            while True:
                phase_mark = timeline.totals() if timeline.enabled else None
                wait_start = _time.perf_counter()
                try:
                    with timeline.span("pack"):
                        plan = next(plan_iter)
                except StopIteration:
                    break
                plan_wait_s = _time.perf_counter() - wait_start
                total_plan_wait += plan_wait_s
                ingest_wait_gauge.set(total_plan_wait)
                intern_stats = getattr(plan, "intern_stats", None)
                intern_s = intern_stats["intern_s"] if intern_stats else 0.0
                total_intern_wait += intern_s
                intern_wait_gauge.set(total_intern_wait)
                index += 1
                outcomes = outcome_queue.popleft()
                batch_now = None if now is None else now + index
                if trace is not None:
                    # The trace frame and the settle must stamp the SAME
                    # day: resolve wall clock once here (the settle would
                    # otherwise resolve its own inside the dispatch).
                    if batch_now is None:
                        batch_now = _now_days()
                    t_keys, t_sids, t_probs, t_offsets = (
                        trace_queue.popleft()
                    )
                    with timeline.span("replay"):
                        trace.append_batch(
                            t_keys, t_sids, t_probs, t_offsets, outcomes,
                            now_days=float(batch_now), steps=steps,
                        )
                # Captured BEFORE the settle: the delta-upload path
                # consumes (and drops) the refresh back-reference.
                plan_reused = (
                    getattr(plan, "_refreshed_from", None) is not None
                )
                batch_band = band(index) if callable(band) else band
                # The trace scope (obs/trace.py; a shared no-op when no
                # tracer is active): phase spans taken inside — the
                # dispatch's upload/settle_dispatch, the checkpoint's
                # journal/interchange — land on batch `index`'s chain,
                # the same chains the serving front end records. Closed
                # BEFORE the yield so the consumer never runs inside the
                # batch's recording window.
                with tracer.batch(index):
                    if tracer.enabled:
                        tracer.batch_event(
                            index, "pack", dur_s=plan_wait_s,
                            args={"markets": plan.num_markets,
                                  "plan_reused": plan_reused},
                        )
                    settle_start = _time.perf_counter()
                    result = driver.dispatch(
                        plan, outcomes, now=batch_now, band=batch_band
                    )
                    session_adopt = driver.last_adopt
                    settle_dispatch_s = _time.perf_counter() - settle_start
                    batches_counter.inc()
                    (reuse_hit_counter if plan_reused
                     else reuse_miss_counter).inc()
                    dispatch_hist.observe(settle_dispatch_s)
                    # Appended BEFORE the checkpoint so ``len(stats)`` is
                    # the SETTLED count even when the checkpoint raises: a
                    # failing batch has settled but never yields, and a
                    # consumer that restarted from its yielded count would
                    # re-settle it (doubling its updates). Resume with
                    # batches[len(stats):].
                    if stats is not None:
                        stats.append(
                            {
                                "batch": index,
                                "markets": plan.num_markets,
                                "plan_wait_s": plan_wait_s,
                                "settle_dispatch_s": settle_dispatch_s,
                                "checkpoint_s": None,
                                "plan_reused": plan_reused,
                                "session_adopt": session_adopt,
                                "intern_s": intern_s,
                                "interned_pairs": (
                                    intern_stats["interned_pairs"]
                                    if intern_stats else 0
                                ),
                            }
                        )
                    # Rolling durability rides the driver: journal mode
                    # appends one epoch (tag = this settled batch; async
                    # by default — the PREVIOUS epoch's completion or
                    # failure surfaces at the join inside the call),
                    # SQLite mode backgrounds the rolling flush. ``None``
                    # when this batch is off-cadence.
                    checkpoint_s = driver.checkpoint(index)
                if checkpoint_s is not None and stats is not None:
                    stats[-1]["checkpoint_s"] = checkpoint_s
                if phase_mark is not None and stats is not None:
                    # The batch's additive phase breakdown (exclusive
                    # seconds per obs/timeline.PHASES name) — present only
                    # when a timeline is recording, so the stats schema is
                    # unchanged for obs-disabled callers.
                    stats[-1]["phases"] = PhaseTimeline.delta(
                        phase_mark, timeline.totals()
                    )
                yield result
    finally:
        # Runs on EVERY exit — exhaustion, a consumer break/close
        # (GeneratorExit), or a batch error: the driver's exit contract
        # joins the in-flight write, covers every fully settled batch
        # with a tail epoch/flush (never a batch that raised mid-settle),
        # skips the tail epoch when the loop is exiting BECAUSE a journal
        # write failed, closes an owned journal, and tail-flushes SQLite.
        try:
            driver.finalize()
        finally:
            if owns_trace:
                trace.close()
