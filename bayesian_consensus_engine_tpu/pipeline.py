"""End-to-end settlement pipeline: payloads → device cycle → SQLite.

The one flow the subsystems exist for, as a single tested API:

    raw (market_id, signals) payloads
      → native ingest packer                    (core.batch.pack_markets)
      → interned (source, market) rows          (TensorReliabilityStore)
      → gather flat rows into an (K, M) block   (this module)
      → N consensus+update cycles in one jit    (parallel.sharded loop)
      → scatter the block back into flat rows   (this module)
      → host-authoritative absorb               (TensorReliabilityStore.absorb)
      → reference-format SQLite checkpoint      (flush_to_sqlite)

The reference's contract is per-(source_id, market_id) state updated after
outcomes (reference: reliability.py:36-45, market.py:200-221); here that
state lives in the store's flat HBM tensors and the cycle runs on a dense
slot-major block, so the bridge is one gather at entry and one scatter at
exit — both inside the same jit dispatch as the cycle loop itself.

Two-phase API so the (host-side) packing/interning cost is paid once per
signal topology, then any number of settlement cycles run device-only:

    plan = build_settlement_plan(store, payloads)
    result = settle(store, plan, outcomes, steps=5)
    store.flush_to_sqlite("checkpoint.db")

`settle_payloads` wraps the three steps for the one-shot case.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Mapping, Optional, Sequence

import numpy as np

from bayesian_consensus_engine_tpu.core.batch import pack_markets
from bayesian_consensus_engine_tpu.utils.config import (
    CONFIDENCE_GROWTH_RATE,
    DEFAULT_CONFIDENCE,
    DEFAULT_RELIABILITY,
)
from bayesian_consensus_engine_tpu.utils.timeconv import now_days as _now_days

Payload = Sequence[tuple[str, Sequence[Mapping[str, Any]]]]


@dataclass(frozen=True)
class SettlementPlan:
    """Static device layout for one signal topology (reusable across cycles).

    Block arrays are slot-major (K, M): markets ride the 128-wide lane
    dimension (the measured-fastest layout — see parallel.sharded). Padding
    slots carry ``row = -1`` and ``mask = False``; at kernel time row −1
    resolves to a sink row appended past the store's flat state, so the plan
    stays valid even if the store interns more pairs after it was built.

    The plan is **immutable after build** — ``build_settlement_plan`` marks
    every array read-only, because ``settle`` caches device copies of
    ``slot_rows``/``probs``/``mask`` on the plan (keyed by dtype) to skip
    the host→device re-upload on repeat settlements; a mutated host array
    would silently diverge from its cached device twin.
    """

    market_keys: list[str]        # row → market id (payload order)
    slot_rows: np.ndarray         # i32[K, M] flat store row per slot (−1 pad)
    probs: np.ndarray             # f64[K, M] per-pair mean probability
    mask: np.ndarray              # bool[K, M] slot carries a signal
    signals_per_market: np.ndarray  # i32[M] raw signal counts (diagnostics)
    binding: tuple[tuple[int, str, str], ...]  # (row, source, market) probes

    @property
    def num_markets(self) -> int:
        return len(self.market_keys)

    @property
    def num_slots(self) -> int:
        return int(self.slot_rows.shape[0])


def build_settlement_plan(
    store,
    payloads: Payload,
    native: Optional[bool] = None,
) -> SettlementPlan:
    """Pack, intern, and lay out payloads as a dense settlement block.

    One native packing pass groups/sorts/flattens the raw signals
    (duplicate signals from one source collapse to their mean, in scalar
    accumulation order), one native interning pass maps every
    (source, market) pair to its flat store row, and the ragged per-market
    pair lists become a dense slot-major block.

    Market ids must be unique within one plan: two slots mapping to the
    same flat row would race in the scatter.
    """
    payloads = list(payloads)
    keys = [market_id for market_id, _ in payloads]
    if len(set(keys)) != len(keys):
        raise ValueError("duplicate market ids in one settlement plan")

    packed = pack_markets(payloads, native=native)
    pairs = [
        (sid, keys[market_row])
        for sid, market_row in zip(packed.pair_source_ids, packed.pair_market)
    ]
    rows = store.rows_for_pairs(pairs, allocate=True)

    counts = np.diff(packed.pair_offsets)
    num_markets = len(keys)
    num_slots = int(counts.max()) if num_markets else 0
    pair_mean = _pair_means(packed)

    # Ragged pair lists → dense (M, K): slot k of market m is its k-th pair
    # (source-id-sorted within the market, the scalar engine's float order).
    slot_rows = np.full((num_markets, num_slots), -1, dtype=np.int32)
    probs = np.zeros((num_markets, num_slots), dtype=np.float64)
    mask = np.zeros((num_markets, num_slots), dtype=bool)
    market_of_pair = packed.pair_market
    slot_of_pair = (
        np.arange(len(rows), dtype=np.int64)
        - packed.pair_offsets[:-1][market_of_pair]
    )
    slot_rows[market_of_pair, slot_of_pair] = rows
    probs[market_of_pair, slot_of_pair] = pair_mean
    mask[market_of_pair, slot_of_pair] = True

    # Binding probes: a spread of (row, pair) samples (always including the
    # highest row) lets settle() verify the plan still matches the store's
    # interner — a checkpoint-restored store with identical row assignment
    # passes; an unrelated store of coincidentally sufficient size does not.
    if len(rows):
        probe_idx = {0, len(rows) - 1, int(np.argmax(rows))}
        probe_idx.update(range(0, len(rows), max(1, len(rows) // 8)))
        binding = tuple(
            (int(rows[i]), pairs[i][0], pairs[i][1]) for i in sorted(probe_idx)
        )
    else:
        binding = ()

    plan = SettlementPlan(
        market_keys=keys,
        slot_rows=np.ascontiguousarray(slot_rows.T),
        probs=np.ascontiguousarray(probs.T),
        mask=np.ascontiguousarray(mask.T),
        signals_per_market=packed.signals_per_market,
        binding=binding,
    )
    # Freeze the arrays: settle() caches device copies keyed by the plan
    # object (see SettlementPlan docstring), so host-side mutation after
    # build must fail loudly rather than desync host and device views.
    for array in (plan.slot_rows, plan.probs, plan.mask, plan.signals_per_market):
        array.setflags(write=False)
    return plan


def _pair_means(packed) -> np.ndarray:
    """Per-pair duplicate-signal means, host-side in scalar float order.

    Duplicate averaging must match the scalar engine bit-for-bit
    (left-to-right sum per pair, reference: core.py:115-116); the flat
    signal list is already in original order, so a stable per-pair
    accumulation reproduces it exactly.
    """
    num_pairs = len(packed.pair_source_ids)
    sums = np.zeros(num_pairs, dtype=np.float64)
    counts = np.zeros(num_pairs, dtype=np.int64)
    flat_pair = packed.flat_pair
    flat_probs = packed.flat_probs
    # np.add.at is an ordered sequential accumulate — scalar-sum order.
    np.add.at(sums, flat_pair, flat_probs)
    np.add.at(counts, flat_pair, 1)
    return sums / np.maximum(counts, 1)


@dataclass(frozen=True)
class SettlementResult:
    """Per-market outputs of the final cycle, payload order."""

    market_keys: list[str]
    consensus: np.ndarray  # f[M] final-cycle consensus (NaN: zero weight)

    def by_market(self) -> dict[str, float]:
        return {
            key: float(value)
            for key, value in zip(self.market_keys, self.consensus)
        }


def _settle_math(
    flat_rel, flat_conf, flat_days, flat_exists,
    slot_rows, probs, mask, outcome, now0, steps: int,
):
    """gather → N-cycle loop → scatter, traced as one jit dispatch.

    Flat state buffers are donated (the caller re-materialises from host
    state via ``device_state()`` afterwards — ``settle`` absorbs + drops the
    cache immediately). Padding slots carry row −1, which indexes the sink
    row appended at the end; sink writes are sliced off before returning.
    """
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel.sharded import (
        MarketBlockState,
        _cycle_math,
        _fast_cycle_math,
        make_loop_math,
    )

    def ext(x, fill):
        return jnp.concatenate([x, jnp.full((1,), fill, x.dtype)])

    rel = ext(flat_rel, DEFAULT_RELIABILITY)
    conf = ext(flat_conf, DEFAULT_CONFIDENCE)
    days = ext(flat_days, 0.0)
    exists = ext(flat_exists, False)

    block = MarketBlockState(
        reliability=rel[slot_rows],
        confidence=conf[slot_rows],
        updated_days=days[slot_rows],
        exists=exists[slot_rows],
    )
    cycle_fn = partial(_cycle_math, axis_name=None, slots_axis=0)
    fast_fn = partial(_fast_cycle_math, axis_name=None, slots_axis=0)
    loop_math = make_loop_math(cycle_fn, steps, fast_cycle_fn=fast_fn)
    new_block, consensus = loop_math(probs, mask, outcome, block, now0)

    # Every real (mask=True) slot maps to a distinct flat row, so the
    # scatter is a permutation write; pad slots all land on the sink row.
    new_rel = rel.at[slot_rows].set(new_block.reliability)[:-1]
    new_conf = conf.at[slot_rows].set(new_block.confidence)[:-1]
    new_days = days.at[slot_rows].set(new_block.updated_days)[:-1]
    new_exists = exists.at[slot_rows].set(new_block.exists)[:-1]
    return new_rel, new_conf, new_days, new_exists, consensus


_settle_kernel = None


def _get_settle_kernel():
    global _settle_kernel
    if _settle_kernel is None:
        import jax

        _settle_kernel = jax.jit(
            _settle_math, static_argnames=("steps",), donate_argnums=(0, 1, 2, 3)
        )
    return _settle_kernel


def settle(
    store,
    plan: SettlementPlan,
    outcomes: Sequence[bool],
    steps: int = 1,
    now: Optional[float] = None,
    dtype=None,
) -> SettlementResult:
    """Run *steps* settlement cycles for the planned markets on device.

    Each cycle: decay-on-read → weighted consensus → outcome correctness at
    p ≥ 0.5 → capped update of the undecayed state (day ``now + i``).
    The mutated state is absorbed back into *store* before returning, so
    a follow-up ``flush_to_sqlite`` checkpoints exactly what settled.

    ``now`` is absolute epoch-days (defaults to the current time); pass it
    explicitly for reproducible parity runs.
    """
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.state.tensor_store import (
        DeviceReliabilityState,
    )

    if len(outcomes) != plan.num_markets:
        raise ValueError(
            f"{len(outcomes)} outcomes for {plan.num_markets} planned markets"
        )
    if plan.mask.any() and int(plan.slot_rows.max()) >= len(store):
        # A plan built against a different (or rebuilt) store: the gather
        # would clamp onto the sink row and silently corrupt results.
        raise ValueError(
            f"plan references row {int(plan.slot_rows.max())} but the store "
            f"holds {len(store)} pairs — was the plan built for this store?"
        )
    if plan.binding:
        probe_rows = store.rows_for_pairs(
            [(source_id, market_id) for _, source_id, market_id in plan.binding],
            allocate=False,
        )
        for (row, source_id, market_id), got in zip(plan.binding, probe_rows):
            if int(got) != row:
                raise ValueError(
                    f"plan is bound to a different store: ({source_id!r}, "
                    f"{market_id!r}) does not intern to row {row} here"
                )

    # Capture pre-settle confidences: the post-settle values are replayed
    # host-side in exact scalar arithmetic (see overwrite_confidences — XLA
    # fuses the growth multiply-add into an FMA, one rounding short of the
    # scalar contract; the trajectory is data-independent, so the host can
    # reproduce it bit-exactly no matter what precision the device ran at).
    touched_rows = plan.slot_rows[plan.mask]
    conf_exact = store.host_confidences(touched_rows)

    (flat, epoch0) = store.device_state(dtype, donate=True)
    now_abs = _now_days() if now is None else now
    cdtype = flat.reliability.dtype

    # The plan is static across settle calls; keep its device copies so a
    # repeat settlement pays no host→device re-upload (measured ~1 s at
    # 100k markets through the axon tunnel). Cached on the plan itself —
    # frozen dataclass, hence object.__setattr__ — keyed by dtype.
    device_plan = getattr(plan, "_device_arrays", None)
    if device_plan is None or device_plan[0] != str(cdtype):
        device_plan = (
            str(cdtype),
            jnp.asarray(plan.slot_rows),
            jnp.asarray(plan.probs, dtype=cdtype),
            jnp.asarray(plan.mask),
        )
        object.__setattr__(plan, "_device_arrays", device_plan)
    _, slot_rows_d, probs_d, mask_d = device_plan

    rel, conf, days, exists, consensus = _get_settle_kernel()(
        flat.reliability,
        flat.confidence,
        flat.updated_days,
        flat.exists,
        slot_rows_d,
        probs_d,
        mask_d,
        jnp.asarray(np.asarray(outcomes, dtype=bool)),
        jnp.asarray(now_abs - epoch0, dtype=cdtype),
        steps,
    )
    store.absorb(
        DeviceReliabilityState(rel, conf, days, exists), epoch0
    )
    for _ in range(steps):
        conf_exact = np.minimum(
            1.0, conf_exact + (1.0 - conf_exact) * CONFIDENCE_GROWTH_RATE
        )
    store.overwrite_confidences(touched_rows, conf_exact)
    return SettlementResult(
        market_keys=plan.market_keys,
        consensus=np.asarray(consensus),
    )


def settle_payloads(
    store,
    payloads: Payload,
    outcomes: Sequence[bool],
    steps: int = 1,
    now: Optional[float] = None,
    db_path=None,
) -> SettlementResult:
    """One-shot pipeline: plan, settle, and optionally checkpoint to SQLite."""
    plan = build_settlement_plan(store, payloads)
    result = settle(store, plan, outcomes, steps=steps, now=now)
    if db_path is not None:
        store.flush_to_sqlite(db_path)
    return result
