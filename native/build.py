"""Build the native extensions in place.

Usage:  python native/build.py

Compiles every ``native/*.c`` CPython extension into
``bayesian_consensus_engine_tpu/_native/`` (``<name>*.so``):

  * ``fastpack`` — signal-ingest packer (core/batch.py falls back to the
    pure-Python packer without it). Measured gain on dict-shaped payloads
    is a modest ~1.3x (the pass is PyObject-bound either way).
  * ``internmap`` — batch id/pair interning for the host boundary
    (utils/interning.py falls back to the dict-backed IdInterner). Batch
    pair interning returns an int32 buffer ready for device upload.

The framework works fully without any of them. No third-party build deps:
the system compiler only.
"""

import pathlib
import shutil
import sys
import sysconfig
import subprocess
import tempfile

ROOT = pathlib.Path(__file__).resolve().parents[1]
NATIVE_DIR = ROOT / "native"
DEST_DIR = ROOT / "bayesian_consensus_engine_tpu" / "_native"


def build_one(source: pathlib.Path) -> pathlib.Path:
    DEST_DIR.mkdir(exist_ok=True)
    (DEST_DIR / "__init__.py").touch()

    suffix = sysconfig.get_config_var("EXT_SUFFIX")
    dest = DEST_DIR / f"{source.stem}{suffix}"
    include = sysconfig.get_path("include")
    cc = sysconfig.get_config_var("CC") or "cc"

    with tempfile.TemporaryDirectory() as tmp:
        obj = pathlib.Path(tmp) / f"{source.stem}.o"
        so = pathlib.Path(tmp) / f"{source.stem}.so"
        subprocess.run(
            [*cc.split(), "-O2", "-fPIC", f"-I{include}", "-c", str(source), "-o", str(obj)],
            check=True,
        )
        link = [*cc.split(), "-shared", str(obj), "-o", str(so)]
        if sys.platform == "darwin":
            # ld64 rejects unresolved CPython symbols without this.
            link[1:1] = ["-undefined", "dynamic_lookup"]
        subprocess.run(link, check=True)
        shutil.copy2(so, dest)
    return dest


def build() -> list[pathlib.Path]:
    return [build_one(src) for src in sorted(NATIVE_DIR.glob("*.c"))]


if __name__ == "__main__":
    paths = build()
    sys.path.insert(0, str(DEST_DIR))

    import fastpack  # smoke import

    out = fastpack.pack([("m", [{"sourceId": "b", "probability": 0.5},
                                {"sourceId": "a", "probability": 0.25}])])
    assert out[1] == ["a", "b"], out

    import internmap  # smoke import

    m = internmap.InternMap()
    assert m.intern("alpha") == 0 and m.intern("beta") == 1
    assert m.intern("alpha") == 0 and len(m) == 2
    assert m.intern_pair("s", "mkt") == 2
    assert bytes(m.intern_pairs(["s", "t"], ["mkt", "mkt"])) == (
        (2).to_bytes(4, sys.byteorder) + (3).to_bytes(4, sys.byteorder)
    )
    assert m.lookup("gone") == -1 and m.id_of(2) == ("s", "mkt")

    # Delta-interning probe/commit round trip (round 15): probe finds the
    # existing pair, the miss commits to the next row in batch order.
    import numpy as np

    codes = np.asarray([0, 1], dtype=np.int32)
    mkts = np.asarray([0, 0], dtype=np.int32)
    rows = np.empty(2, dtype=np.int32)
    hashes = np.empty(2, dtype=np.uint64)
    slots = np.empty(2, dtype=np.int64)
    cap = m.reserve_pairs(2)
    misses = m.probe_pairs_indexed(
        ["s", "u"], codes, ["mkt"], mkts, rows, hashes, slots, 0, 2
    )
    assert misses == 1 and rows[0] == 2 and rows[1] == -1
    assert m.commit_probed(
        ["s", "u"], codes, ["mkt"], mkts, rows, hashes, slots, cap
    ) == 1
    assert rows[1] == 4 and m.id_of(4) == ("u", "mkt")
    out = np.empty(2, dtype=np.int32)
    matched = internmap.delta_match_rows(
        None,
        codes, np.asarray([0, 2], dtype=np.int64),
        codes, np.asarray([0, 2], dtype=np.int64),
        None, rows, out,
    )
    assert matched == 2 and list(out) == list(rows)

    for path in paths:
        print(f"built + smoke-tested: {path}")
