"""Build the native fastpack extension in place.

Usage:  python native/build.py

Compiles native/fastpack.c into bayesian_consensus_engine_tpu/_native/
(fastpack*.so). The framework works without it — core.batch falls back to
the pure-Python packer. Measured gain on dict-shaped payloads is a modest
~1.3x (the pass is PyObject-bound either way); the extension mainly keeps
the ingest path off the GIL-heavy Python bytecode loop and is the template
for columnar native ingest if payload shape ever allows it. No third-party
build deps: the system compiler only.
"""

import pathlib
import shutil
import sys
import sysconfig
import subprocess
import tempfile

ROOT = pathlib.Path(__file__).resolve().parents[1]
SOURCE = ROOT / "native" / "fastpack.c"
DEST_DIR = ROOT / "bayesian_consensus_engine_tpu" / "_native"


def build() -> pathlib.Path:
    DEST_DIR.mkdir(exist_ok=True)
    (DEST_DIR / "__init__.py").touch()

    suffix = sysconfig.get_config_var("EXT_SUFFIX")
    dest = DEST_DIR / f"fastpack{suffix}"
    include = sysconfig.get_path("include")
    cc = sysconfig.get_config_var("CC") or "cc"

    with tempfile.TemporaryDirectory() as tmp:
        obj = pathlib.Path(tmp) / "fastpack.o"
        so = pathlib.Path(tmp) / "fastpack.so"
        subprocess.run(
            [*cc.split(), "-O2", "-fPIC", f"-I{include}", "-c", str(SOURCE), "-o", str(obj)],
            check=True,
        )
        link = [*cc.split(), "-shared", str(obj), "-o", str(so)]
        if sys.platform == "darwin":
            # ld64 rejects unresolved CPython symbols without this.
            link[1:1] = ["-undefined", "dynamic_lookup"]
        subprocess.run(link, check=True)
        shutil.copy2(so, dest)
    return dest


if __name__ == "__main__":
    path = build()
    sys.path.insert(0, str(path.parent))
    import fastpack  # smoke import

    out = fastpack.pack([("m", [{"sourceId": "b", "probability": 0.5},
                                {"sourceId": "a", "probability": 0.25}])])
    assert out[1] == ["a", "b"], out
    print(f"built + smoke-tested: {path}")
