/* internmap — native id-interning hash for bayesian_consensus_engine_tpu.
 *
 * The TPU state tensors are keyed by dense int32 rows; ids — source ids and
 * (source_id, market_id) pairs — are interned to rows at the host boundary
 * (utils/interning.py, state/tensor_store.py). At ingest scale (millions of
 * pairs per settlement batch) the pure-Python dict loop pays per-item
 * bytecode dispatch, tuple construction, and PyLong boxing; this module
 * provides batch interning in one C pass over an open-addressing FNV-1a
 * table, returning a ready-to-upload int32 buffer (wrap with
 * numpy.frombuffer, no copies).
 *
 * Design notes (why inserts are pure C):
 *   - key bytes live in one growable arena (offset-addressed, so realloc
 *     is safe); no per-key malloc.
 *   - no Python objects are created at insert time: the id for a row is
 *     materialised lazily by ids()/id_of() from the arena bytes (a pair is
 *     recognised by its embedded NUL separator — see key spaces below).
 *   - batch calls pre-size the table for the incoming run, so a cold 1M-pair
 *     batch does not pay incremental rehashes.
 *
 * Contract: row assignment is first-seen order, identical to the Python
 * IdInterner (equivalence enforced by tests/test_internmap.py). Pair keys
 * are the two UTF-8 strings joined by a NUL byte; to keep the single-key
 * and pair-key spaces disjoint, NUL is rejected in EVERY key half AND in
 * single-string keys (so intern("a\0b") cannot alias intern_pair("a","b")).
 * The reference caps ids at 256 chars and its validator rejects empty ids
 * (reference: config.py:37-38), so real ids never hit the restriction.
 *
 * API (all methods on InternMap):
 *   intern(str) -> int                      single string key
 *   intern_pair(str, str) -> int           single pair key
 *   intern_batch(seq[str]) -> bytearray            int32 rows, len*4 bytes
 *   intern_pairs(seq[str], seq[str]) -> bytearray  elementwise pair keys
 *   lookup(str) -> int        (-1 when absent; no insertion)
 *   lookup_pair(str, str) -> int
 *   lookup_pairs(seq[str], seq[str]) -> bytearray  (-1 rows when absent)
 *   __len__() -> unique keys; ids() -> list (row order; str or (str, str))
 *   sorted_rows(buffer[i32]) -> bytearray  rows reordered by key bytes
 *   flush_sqlite(path, rows, rel, conf, iso) -> int   checkpoint writer
 *
 * The last two back the SQLite checkpoint fast path
 * (state/tensor_store.flush_to_sqlite): sorting rows by raw key bytes
 * reproduces Python's (source_id, market_id) tuple sort exactly — the NUL
 * separator orders below every valid id byte and UTF-8 byte order equals
 * code-point order — and the writer binds arena bytes straight into a
 * dlopen()ed libsqlite3, so a million-row flush never materialises a
 * Python string, tuple, or number. Without libsqlite3 the writer raises
 * and the caller falls back to the sqlite3-module path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>
#ifndef _WIN32
#include <dlfcn.h>
#endif

typedef struct {
    uint64_t hash;     /* 0 means empty (FNV-1a output is remapped off 0) */
    int32_t row;
    uint32_t key_len;
    size_t key_off;    /* offset of the key bytes in the arena */
} slot_t;

typedef struct {
    size_t off;
    uint32_t len;
} rowref_t;

typedef struct {
    PyObject_HEAD
    slot_t *slots;
    size_t capacity;   /* power of two */
    size_t used;
    char *arena;       /* all key bytes, back to back */
    size_t arena_used;
    size_t arena_cap;
    rowref_t *rows;    /* row -> key bytes, for lazy id materialisation */
    size_t rows_cap;
} InternMap;

static uint64_t
fnv1a(const char *data, size_t len)
{
    uint64_t h = 1469598103934665603ULL;
    for (size_t i = 0; i < len; i++) {
        h ^= (unsigned char)data[i];
        h *= 1099511628211ULL;
    }
    return h ? h : 1ULL;  /* reserve 0 for "empty slot" */
}

static int
map_resize(InternMap *self, size_t new_capacity)
{
    slot_t *fresh = PyMem_Calloc(new_capacity, sizeof(slot_t));
    if (!fresh) {
        PyErr_NoMemory();
        return -1;
    }
    size_t mask = new_capacity - 1;
    for (size_t i = 0; i < self->capacity; i++) {
        slot_t *old = &self->slots[i];
        if (!old->hash) continue;
        size_t j = old->hash & mask;
        while (fresh[j].hash) j = (j + 1) & mask;
        fresh[j] = *old;
    }
    PyMem_Free(self->slots);
    self->slots = fresh;
    self->capacity = new_capacity;
    return 0;
}

/* Pre-size the table for an incoming batch of *n* keys, but only when the
 * map holds far fewer keys than the batch — i.e. most of the batch is
 * probably new (a cold load). A warm batch (most keys already present)
 * must NOT trigger this: treating its n as all-new would resize the table
 * 2x past need on every call, paying a full rehash and colder probes. */
static int
map_reserve_cold(InternMap *self, size_t n)
{
    if (self->used * 8 >= n) return 0;
    size_t cap = self->capacity;
    while (n * 3 >= cap * 2) cap *= 2;
    if (cap == self->capacity) return 0;
    return map_resize(self, cap);
}

/* Append the key at the already-located EMPTY slot *i*; returns the new
 * row or -1 on error. The probe that found slot *i* is the caller's job
 * (map_intern_hashed's walk, or commit_probed resuming from a recorded
 * probe-phase slot). */
static int32_t
map_insert_at(InternMap *self, size_t i, const char *key, size_t len,
              uint64_t h)
{
    if (self->used >= (size_t)INT32_MAX) {
        PyErr_SetString(PyExc_OverflowError, "more than 2^31-1 interned ids");
        return -1;
    }
    if (self->arena_used + len > self->arena_cap) {
        size_t cap = self->arena_cap * 2;
        while (self->arena_used + len > cap) cap *= 2;
        char *grown = PyMem_Realloc(self->arena, cap);
        if (!grown) {
            PyErr_NoMemory();
            return -1;
        }
        self->arena = grown;
        self->arena_cap = cap;
    }
    if (self->used >= self->rows_cap) {
        size_t cap = self->rows_cap * 2;
        rowref_t *grown = PyMem_Realloc(self->rows, cap * sizeof(rowref_t));
        if (!grown) {
            PyErr_NoMemory();
            return -1;
        }
        self->rows = grown;
        self->rows_cap = cap;
    }
    size_t off = self->arena_used;
    memcpy(self->arena + off, key, len);
    self->arena_used += len;

    int32_t row = (int32_t)self->used;
    self->rows[row].off = off;
    self->rows[row].len = (uint32_t)len;
    self->slots[i].hash = h;
    self->slots[i].row = row;
    self->slots[i].key_len = (uint32_t)len;
    self->slots[i].key_off = off;
    self->used++;
    return row;
}

/* Find or insert the key; returns the row, or -1 on error. */
static int32_t
map_intern_hashed(InternMap *self, const char *key, size_t len, uint64_t h)
{
    if (self->used * 3 >= self->capacity * 2) {
        if (map_resize(self, self->capacity * 2) < 0) return -1;
    }
    size_t mask = self->capacity - 1;
    size_t i = h & mask;
    while (self->slots[i].hash) {
        slot_t *s = &self->slots[i];
        if (s->hash == h && s->key_len == len &&
            memcmp(self->arena + s->key_off, key, len) == 0)
            return s->row;
        i = (i + 1) & mask;
    }
    return map_insert_at(self, i, key, len, h);
}

static int32_t
map_intern(InternMap *self, const char *key, size_t len)
{
    return map_intern_hashed(self, key, len, fnv1a(key, len));
}

/* Prefetch hint for the batched insert pipeline (no-op off GCC/clang). */
#if defined(__GNUC__) || defined(__clang__)
#define FF_PREFETCH(p) __builtin_prefetch((p), 0, 1)
#else
#define FF_PREFETCH(p) ((void)0)
#endif

static int32_t
map_lookup(InternMap *self, const char *key, size_t len)
{
    uint64_t h = fnv1a(key, len);
    size_t mask = self->capacity - 1;
    size_t i = h & mask;
    while (self->slots[i].hash) {
        slot_t *s = &self->slots[i];
        if (s->hash == h && s->key_len == len &&
            memcmp(self->arena + s->key_off, key, len) == 0)
            return s->row;
        i = (i + 1) & mask;
    }
    return -1;
}

/* Build the Python id object for a row from its arena bytes: a NUL byte
 * marks a pair key (singles reject NUL), so "a\0b" -> ("a", "b"). */
static PyObject *
row_to_id(InternMap *self, size_t row)
{
    const char *key = self->arena + self->rows[row].off;
    size_t len = self->rows[row].len;
    const char *sep = memchr(key, '\0', len);
    if (!sep)
        return PyUnicode_DecodeUTF8(key, (Py_ssize_t)len, NULL);
    Py_ssize_t alen = sep - key;
    PyObject *a = PyUnicode_DecodeUTF8(key, alen, NULL);
    if (!a) return NULL;
    PyObject *b = PyUnicode_DecodeUTF8(sep + 1, (Py_ssize_t)len - alen - 1, NULL);
    if (!b) {
        Py_DECREF(a);
        return NULL;
    }
    PyObject *pair = PyTuple_Pack(2, a, b);
    Py_DECREF(a);
    Py_DECREF(b);
    return pair;
}

/* ---- key building -------------------------------------------------------- */

/* UTF-8 view of a str; sets error and returns NULL on non-str. */
static const char *
utf8_of(PyObject *obj, Py_ssize_t *len)
{
    if (!PyUnicode_Check(obj)) {
        PyErr_Format(PyExc_TypeError, "expected str, got %.100s",
                     Py_TYPE(obj)->tp_name);
        return NULL;
    }
    return PyUnicode_AsUTF8AndSize(obj, len);
}

/* NUL would let a single-string key alias a NUL-joined pair key; reject it
 * in both key kinds so the two key spaces cannot collide. */
static int
reject_nul(const char *buf, Py_ssize_t len)
{
    if (memchr(buf, '\0', (size_t)len)) {
        PyErr_SetString(PyExc_ValueError, "ids must not contain NUL");
        return -1;
    }
    return 0;
}

/* Joined "a\0b" key in *scratch (grown as needed). Returns length or -1. */
static Py_ssize_t
pair_key(PyObject *a, PyObject *b, char **scratch, Py_ssize_t *scratch_cap)
{
    Py_ssize_t alen, blen;
    const char *abuf = utf8_of(a, &alen);
    if (!abuf) return -1;
    const char *bbuf = utf8_of(b, &blen);
    if (!bbuf) return -1;
    if (reject_nul(abuf, alen) < 0 || reject_nul(bbuf, blen) < 0) return -1;
    Py_ssize_t need = alen + 1 + blen;
    if (need > *scratch_cap) {
        char *grown = PyMem_Realloc(*scratch, (size_t)(need * 2));
        if (!grown) {
            PyErr_NoMemory();
            return -1;
        }
        *scratch = grown;
        *scratch_cap = need * 2;
    }
    memcpy(*scratch, abuf, (size_t)alen);
    (*scratch)[alen] = '\0';
    memcpy(*scratch + alen + 1, bbuf, (size_t)blen);
    return need;
}

/* Validated fast views of two equal-length sequences. Returns 0 or -1. */
static int
two_seqs(PyObject *args, PyObject **fast_a, PyObject **fast_b, Py_ssize_t *n)
{
    PyObject *seq_a, *seq_b;
    if (!PyArg_ParseTuple(args, "OO", &seq_a, &seq_b)) return -1;
    *fast_a = PySequence_Fast(seq_a, "expected a sequence of str");
    if (!*fast_a) return -1;
    *fast_b = PySequence_Fast(seq_b, "expected a sequence of str");
    if (!*fast_b) {
        Py_DECREF(*fast_a);
        return -1;
    }
    *n = PySequence_Fast_GET_SIZE(*fast_a);
    if (PySequence_Fast_GET_SIZE(*fast_b) != *n) {
        PyErr_SetString(PyExc_ValueError, "sequences must have equal length");
        Py_DECREF(*fast_a);
        Py_DECREF(*fast_b);
        return -1;
    }
    return 0;
}

/* ---- methods ------------------------------------------------------------- */

static PyObject *
InternMap_intern(InternMap *self, PyObject *arg)
{
    Py_ssize_t len;
    const char *buf = utf8_of(arg, &len);
    if (!buf) return NULL;
    if (reject_nul(buf, len) < 0) return NULL;
    int32_t row = map_intern(self, buf, (size_t)len);
    if (row < 0) return NULL;
    return PyLong_FromLong(row);
}

static PyObject *
InternMap_intern_pair(InternMap *self, PyObject *args)
{
    PyObject *a, *b;
    if (!PyArg_ParseTuple(args, "OO", &a, &b)) return NULL;
    char *scratch = NULL;
    Py_ssize_t cap = 0;
    Py_ssize_t len = pair_key(a, b, &scratch, &cap);
    if (len < 0) {
        PyMem_Free(scratch);
        return NULL;
    }
    int32_t row = map_intern(self, scratch, (size_t)len);
    PyMem_Free(scratch);
    if (row < 0) return NULL;
    return PyLong_FromLong(row);
}

static PyObject *
InternMap_intern_batch(InternMap *self, PyObject *arg)
{
    PyObject *fast = PySequence_Fast(arg, "expected a sequence of str");
    if (!fast) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    PyObject *out = PyByteArray_FromStringAndSize(NULL, n * 4);
    if (!out || map_reserve_cold(self, (size_t)n) < 0) {
        Py_XDECREF(out);
        Py_DECREF(fast);
        return NULL;
    }
    int32_t *rows = (int32_t *)PyByteArray_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        Py_ssize_t len;
        const char *buf = utf8_of(item, &len);
        if (!buf) goto fail;
        if (reject_nul(buf, len) < 0) goto fail;
        int32_t row = map_intern(self, buf, (size_t)len);
        if (row < 0) goto fail;
        rows[i] = row;
    }
    Py_DECREF(fast);
    return out;
fail:
    Py_DECREF(fast);
    Py_DECREF(out);
    return NULL;
}

static PyObject *
InternMap_intern_pairs(InternMap *self, PyObject *args)
{
    PyObject *fast_a, *fast_b;
    Py_ssize_t n;
    if (two_seqs(args, &fast_a, &fast_b, &n) < 0) return NULL;
    PyObject *out = PyByteArray_FromStringAndSize(NULL, n * 4);
    char *scratch = NULL;
    /* Same chunked assemble→hash→prefetch→insert pipeline as the indexed
     * path (see intern_pairs_indexed): the inserts are random-miss-bound
     * on the slots table at scale, and prefetching each key's home slot
     * while the rest of the chunk assembles hides part of the latency.
     * Error-recovery parity preserved: pairs before a failing pair still
     * intern before the error raises. */
    enum { FF_PCHUNK = 1024 };
    size_t offs[FF_PCHUNK];
    uint32_t lens[FF_PCHUNK];
    uint64_t hashes[FF_PCHUNK];
    Py_ssize_t cap = 64 * FF_PCHUNK;
    scratch = PyMem_Malloc((size_t)cap);
    if (!out || !scratch || map_reserve_cold(self, (size_t)n) < 0) {
        if (!scratch) PyErr_NoMemory();
        goto fail;
    }
    int32_t *rows = (int32_t *)PyByteArray_AS_STRING(out);
    for (Py_ssize_t start = 0; start < n; start += FF_PCHUNK) {
        Py_ssize_t m = n - start < FF_PCHUNK ? n - start : FF_PCHUNK;
        size_t kused = 0;
        int chunk_failed = 0;
        Py_ssize_t assembled = m;
        for (Py_ssize_t j = 0; j < m; j++) {
            PyObject *a = PySequence_Fast_GET_ITEM(fast_a, start + j);
            PyObject *b = PySequence_Fast_GET_ITEM(fast_b, start + j);
            Py_ssize_t alen, blen;
            const char *abuf = utf8_of(a, &alen);
            const char *bbuf = abuf ? utf8_of(b, &blen) : NULL;
            if (!abuf || !bbuf || reject_nul(abuf, alen) < 0 ||
                reject_nul(bbuf, blen) < 0) {
                chunk_failed = 1;
                assembled = j;
                break;
            }
            Py_ssize_t need = alen + 1 + blen;
            if ((Py_ssize_t)kused + need > cap) {
                cap = ((Py_ssize_t)kused + need) * 2;
                char *grown = PyMem_Realloc(scratch, (size_t)cap);
                if (!grown) {
                    PyErr_NoMemory();
                    chunk_failed = 1;
                    assembled = j;
                    break;
                }
                scratch = grown;
            }
            memcpy(scratch + kused, abuf, (size_t)alen);
            scratch[kused + (size_t)alen] = '\0';
            memcpy(scratch + kused + (size_t)alen + 1, bbuf, (size_t)blen);
            offs[j] = kused;
            lens[j] = (uint32_t)need;
            hashes[j] = fnv1a(scratch + kused, (size_t)need);
            FF_PREFETCH(&self->slots[hashes[j] & (self->capacity - 1)]);
            kused += (size_t)need;
        }
        for (Py_ssize_t j = 0; j < assembled; j++) {
            int32_t row = map_intern_hashed(
                self, scratch + offs[j], lens[j], hashes[j]);
            if (row < 0) goto fail;
            rows[start + j] = row;
        }
        if (chunk_failed) goto fail;
    }
    PyMem_Free(scratch);
    Py_DECREF(fast_a);
    Py_DECREF(fast_b);
    return out;
fail:
    PyMem_Free(scratch);
    Py_XDECREF(out);
    Py_DECREF(fast_a);
    Py_DECREF(fast_b);
    return NULL;
}

/* intern_pairs_indexed(a_table, a_codes, b_table, b_codes) -> bytearray.
 *
 * Elementwise pair interning where each half is given as (unique string
 * table, int32 code buffer) instead of one Python string per element: the
 * UTF-8 views are resolved once per TABLE entry, so a million-pair batch
 * whose ids repeat heavily (the settlement planner's shape — ~10k sources,
 * unique-but-tabled markets) skips per-element PyUnicode traffic entirely.
 * Code buffers must be contiguous int32 of equal element count; codes
 * index their table (range-checked).
 */
static PyObject *
InternMap_intern_pairs_indexed(InternMap *self, PyObject *args)
{
    PyObject *a_table_obj, *b_table_obj, *a_codes_obj, *b_codes_obj;
    if (!PyArg_ParseTuple(args, "OOOO", &a_table_obj, &a_codes_obj,
                          &b_table_obj, &b_codes_obj))
        return NULL;

    typedef struct { const char *buf; Py_ssize_t len; } strview_t;
    PyObject *fast_a = NULL, *fast_b = NULL, *out = NULL;
    strview_t *views_a = NULL, *views_b = NULL;
    char *scratch = NULL;
    Py_buffer codes_a, codes_b;
    codes_a.obj = NULL;
    codes_b.obj = NULL;

    fast_a = PySequence_Fast(a_table_obj, "expected a sequence of str");
    if (!fast_a) goto fail;
    fast_b = PySequence_Fast(b_table_obj, "expected a sequence of str");
    if (!fast_b) goto fail;
    if (PyObject_GetBuffer(a_codes_obj, &codes_a, PyBUF_CONTIG_RO) < 0)
        goto fail;
    if (PyObject_GetBuffer(b_codes_obj, &codes_b, PyBUF_CONTIG_RO) < 0)
        goto fail;
    if (codes_a.len != codes_b.len || codes_a.len % 4 != 0) {
        PyErr_SetString(PyExc_ValueError,
                        "code buffers must be equal-length int32");
        goto fail;
    }
    Py_ssize_t n = codes_a.len / 4;
    Py_ssize_t na = PySequence_Fast_GET_SIZE(fast_a);
    Py_ssize_t nb = PySequence_Fast_GET_SIZE(fast_b);

    /* UTF-8 views resolve LAZILY, on a table entry's first use (cached on
     * the str objects, which the caller's tables keep alive for the whole
     * call): an entry no code references — e.g. a zero-signal market's
     * id — is never validated, exactly like the per-pair paths it
     * replaces. buf == NULL marks "not yet resolved". */
    views_a = PyMem_Calloc((size_t)(na ? na : 1), sizeof(strview_t));
    views_b = PyMem_Calloc((size_t)(nb ? nb : 1), sizeof(strview_t));
    if (!views_a || !views_b) {
        PyErr_NoMemory();
        goto fail;
    }

    out = PyByteArray_FromStringAndSize(NULL, n * 4);
    if (!out || map_reserve_cold(self, (size_t)n) < 0) goto fail;
    /* Chunked assemble→hash→prefetch→insert pipeline. The insert is
     * DRAM/TLB-latency-bound (every probe is a random miss into a table
     * of hundreds of MB at million-pair scale); assembling a chunk of
     * keys first and prefetching each key's home slot while the rest of
     * the chunk assembles hides part of that latency (alternating A/B on
     * the 4M-pair cold-ingest fixture: ~30% off this pass, ~12-15% off
     * whole-plan columnar ingest). 1024 keys × 64 B of prefetched slot
     * lines stays well inside L2. */
    enum { FF_CHUNK = 1024 };
    size_t offs[FF_CHUNK];
    uint32_t lens[FF_CHUNK];
    uint64_t hashes[FF_CHUNK];
    Py_ssize_t scratch_cap = 64 * FF_CHUNK;
    scratch = PyMem_Malloc((size_t)scratch_cap);
    if (!scratch) {
        PyErr_NoMemory();
        goto fail;
    }
    const int32_t *ca = (const int32_t *)codes_a.buf;
    const int32_t *cb = (const int32_t *)codes_b.buf;
    int32_t *rows = (int32_t *)PyByteArray_AS_STRING(out);
    for (Py_ssize_t start = 0; start < n; start += FF_CHUNK) {
        Py_ssize_t m = n - start < FF_CHUNK ? n - start : FF_CHUNK;
        size_t kused = 0;
        /* A validation failure at pair j must still intern pairs
         * [start, start+j) first: the per-pair paths (and the Python
         * IdInterner) intern everything before the bad pair, and a caller
         * that catches the error observes that state — the chunking is an
         * implementation detail and may not change it. */
        int chunk_failed = 0;
        Py_ssize_t assembled = m;
        for (Py_ssize_t j = 0; j < m; j++) {
            Py_ssize_t i = start + j;
            int32_t ia = ca[i], ib = cb[i];
            if (ia < 0 || ia >= na || ib < 0 || ib >= nb) {
                PyErr_Format(PyExc_IndexError,
                             "pair %zd: code (%d, %d) out of table range",
                             i, ia, ib);
                chunk_failed = 1;
                assembled = j;
                break;
            }
            if (!views_a[ia].buf) {
                views_a[ia].buf = utf8_of(
                    PySequence_Fast_GET_ITEM(fast_a, ia), &views_a[ia].len);
                if (!views_a[ia].buf ||
                    reject_nul(views_a[ia].buf, views_a[ia].len) < 0) {
                    chunk_failed = 1;
                    assembled = j;
                    break;
                }
            }
            if (!views_b[ib].buf) {
                views_b[ib].buf = utf8_of(
                    PySequence_Fast_GET_ITEM(fast_b, ib), &views_b[ib].len);
                if (!views_b[ib].buf ||
                    reject_nul(views_b[ib].buf, views_b[ib].len) < 0) {
                    chunk_failed = 1;
                    assembled = j;
                    break;
                }
            }
            Py_ssize_t alen = views_a[ia].len, blen = views_b[ib].len;
            Py_ssize_t need = alen + 1 + blen;
            if ((Py_ssize_t)kused + need > scratch_cap) {
                scratch_cap = ((Py_ssize_t)kused + need) * 2;
                char *grown = PyMem_Realloc(scratch, (size_t)scratch_cap);
                if (!grown) {
                    PyErr_NoMemory();
                    chunk_failed = 1;
                    assembled = j;
                    break;
                }
                scratch = grown;
            }
            memcpy(scratch + kused, views_a[ia].buf, (size_t)alen);
            scratch[kused + (size_t)alen] = '\0';
            memcpy(scratch + kused + (size_t)alen + 1, views_b[ib].buf,
                   (size_t)blen);
            offs[j] = kused;
            lens[j] = (uint32_t)need;
            hashes[j] = fnv1a(scratch + kused, (size_t)need);
            FF_PREFETCH(&self->slots[hashes[j] & (self->capacity - 1)]);
            kused += (size_t)need;
        }
        for (Py_ssize_t j = 0; j < assembled; j++) {
            int32_t row = map_intern_hashed(
                self, scratch + offs[j], lens[j], hashes[j]);
            if (row < 0) goto fail;  /* insert error outranks a later one */
            rows[start + j] = row;
        }
        if (chunk_failed) goto fail;
    }
    PyMem_Free(scratch);
    PyMem_Free(views_a);
    PyMem_Free(views_b);
    PyBuffer_Release(&codes_a);
    PyBuffer_Release(&codes_b);
    Py_DECREF(fast_a);
    Py_DECREF(fast_b);
    return out;

fail:
    PyMem_Free(scratch);
    PyMem_Free(views_a);
    PyMem_Free(views_b);
    if (codes_a.obj) PyBuffer_Release(&codes_a);
    if (codes_b.obj) PyBuffer_Release(&codes_b);
    Py_XDECREF(fast_a);
    Py_XDECREF(fast_b);
    Py_XDECREF(out);
    return NULL;
}

/* ---- delta interning: batch probe + deterministic commit ----------------- */

typedef struct { const char *buf; Py_ssize_t len; } ff_view_t;

/* Resolve every table entry's UTF-8 view up front (GIL held, cached on
 * the str objects the caller's table keeps alive) so a probe loop can
 * run with the GIL released. NUL-rejects every entry — the probe
 * validates the whole table, unlike the lazy per-use resolution of the
 * insert paths. *max_len gets the longest entry. Returns NULL on error. */
static ff_view_t *
resolve_table_views(PyObject *fast, Py_ssize_t n, Py_ssize_t *max_len)
{
    ff_view_t *views = PyMem_Malloc((size_t)(n ? n : 1) * sizeof(ff_view_t));
    if (!views) {
        PyErr_NoMemory();
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        views[i].buf = utf8_of(PySequence_Fast_GET_ITEM(fast, i),
                               &views[i].len);
        if (!views[i].buf || reject_nul(views[i].buf, views[i].len) < 0) {
            PyMem_Free(views);
            return NULL;
        }
        if (views[i].len > *max_len) *max_len = views[i].len;
    }
    return views;
}

/* probe_pairs_indexed(a_table, a_codes, b_table, b_codes,
 *                     rows_out, hashes_out, slots_out, start, stop)
 *     -> miss count in [start, stop)
 *
 * The batch-probe half of the delta interning pass (round 15): for every
 * pair in [start, stop) — (unique table, int32 codes) halves exactly as
 * intern_pairs_indexed takes them — LOOK UP the joined key without
 * inserting, writing into caller-preallocated full-batch-length buffers
 *
 *   rows_out[i]   int32  the existing row, or -1 when absent
 *   hashes_out[i] uint64 the key's hash (valid for every probed i)
 *   slots_out[i]  int64  the first EMPTY slot on the key's probe chain —
 *                        the insertion point commit_probed resumes from
 *                        (only meaningful where rows_out[i] < 0)
 *
 * The main loop runs with the GIL RELEASED (table views are resolved up
 * front and the map is only read), so worker threads probing disjoint
 * [start, stop) ranges of one batch truly overlap — the sharded intern
 * pass in utils/interning.py. The map must not be mutated between a
 * probe and its commit (the tensor store's host lock guarantees that);
 * commit_probed re-verifies the capacity it was probed against.
 */
static PyObject *
InternMap_probe_pairs_indexed(InternMap *self, PyObject *args)
{
    PyObject *a_table_obj, *b_table_obj, *a_codes_obj, *b_codes_obj;
    PyObject *rows_obj, *hashes_obj, *slots_obj;
    Py_ssize_t start, stop;
    if (!PyArg_ParseTuple(args, "OOOOOOOnn", &a_table_obj, &a_codes_obj,
                          &b_table_obj, &b_codes_obj, &rows_obj,
                          &hashes_obj, &slots_obj, &start, &stop))
        return NULL;

    PyObject *fast_a = NULL, *fast_b = NULL;
    ff_view_t *views_a = NULL, *views_b = NULL;
    char *scratch = NULL;
    Py_buffer codes_a, codes_b, rows_v, hashes_v, slots_v;
    codes_a.obj = codes_b.obj = rows_v.obj = hashes_v.obj = slots_v.obj =
        NULL;

    fast_a = PySequence_Fast(a_table_obj, "expected a sequence of str");
    if (!fast_a) goto fail;
    fast_b = PySequence_Fast(b_table_obj, "expected a sequence of str");
    if (!fast_b) goto fail;
    if (PyObject_GetBuffer(a_codes_obj, &codes_a, PyBUF_CONTIG_RO) < 0 ||
        PyObject_GetBuffer(b_codes_obj, &codes_b, PyBUF_CONTIG_RO) < 0 ||
        PyObject_GetBuffer(rows_obj, &rows_v, PyBUF_CONTIG) < 0 ||
        PyObject_GetBuffer(hashes_obj, &hashes_v, PyBUF_CONTIG) < 0 ||
        PyObject_GetBuffer(slots_obj, &slots_v, PyBUF_CONTIG) < 0)
        goto fail;
    if (codes_a.len != codes_b.len || codes_a.len % 4 != 0 ||
        rows_v.len != codes_a.len || hashes_v.len != codes_a.len * 2 ||
        slots_v.len != codes_a.len * 2) {
        PyErr_SetString(PyExc_ValueError,
                        "buffers must be equal-count int32 codes/rows with "
                        "uint64 hashes and int64 slots");
        goto fail;
    }
    Py_ssize_t n = codes_a.len / 4;
    if (start < 0 || stop < start || stop > n) {
        PyErr_SetString(PyExc_IndexError, "probe range out of bounds");
        goto fail;
    }
    Py_ssize_t na = PySequence_Fast_GET_SIZE(fast_a);
    Py_ssize_t nb = PySequence_Fast_GET_SIZE(fast_b);
    Py_ssize_t max_a = 0, max_b = 0;
    views_a = resolve_table_views(fast_a, na, &max_a);
    if (!views_a) goto fail;
    views_b = resolve_table_views(fast_b, nb, &max_b);
    if (!views_b) goto fail;
    /* Same chunked assemble→hash→prefetch→lookup pipeline as the insert
     * paths: the lookups are random-miss-bound on the slots table, and
     * prefetching each key's home slot while the rest of the chunk
     * assembles hides part of the latency (the probe is the phase the
     * sharded pass parallelises, so its per-pair cost IS the floor). */
    enum { FF_QCHUNK = 1024 };
    Py_ssize_t max_key = max_a + 1 + max_b + 1;
    scratch = PyMem_Malloc((size_t)(FF_QCHUNK * max_key));
    if (!scratch) {
        PyErr_NoMemory();
        goto fail;
    }

    const int32_t *ca = (const int32_t *)codes_a.buf;
    const int32_t *cb = (const int32_t *)codes_b.buf;
    int32_t *rows = (int32_t *)rows_v.buf;
    uint64_t *hashes = (uint64_t *)hashes_v.buf;
    int64_t *slot_out = (int64_t *)slots_v.buf;
    Py_ssize_t misses = 0;
    Py_ssize_t bad_index = -1;
    Py_BEGIN_ALLOW_THREADS
    size_t mask = self->capacity - 1;
    size_t offs[FF_QCHUNK];
    uint32_t lens[FF_QCHUNK];
    for (Py_ssize_t cstart = start; cstart < stop && bad_index < 0;
         cstart += FF_QCHUNK) {
        Py_ssize_t m = stop - cstart < FF_QCHUNK ? stop - cstart
                                                 : FF_QCHUNK;
        size_t kused = 0;
        Py_ssize_t assembled = m;
        for (Py_ssize_t j = 0; j < m; j++) {
            Py_ssize_t i = cstart + j;
            int32_t ia = ca[i], ib = cb[i];
            if (ia < 0 || ia >= na || ib < 0 || ib >= nb) {
                bad_index = i;
                assembled = j;
                break;
            }
            size_t alen = (size_t)views_a[ia].len;
            size_t blen = (size_t)views_b[ib].len;
            size_t len = alen + 1 + blen;
            memcpy(scratch + kused, views_a[ia].buf, alen);
            scratch[kused + alen] = '\0';
            memcpy(scratch + kused + alen + 1, views_b[ib].buf, blen);
            offs[j] = kused;
            lens[j] = (uint32_t)len;
            uint64_t h = fnv1a(scratch + kused, len);
            hashes[i] = h;
            FF_PREFETCH(&self->slots[h & mask]);
            kused += len;
        }
        for (Py_ssize_t j = 0; j < assembled; j++) {
            Py_ssize_t i = cstart + j;
            const char *key = scratch + offs[j];
            size_t len = lens[j];
            uint64_t h = hashes[i];
            size_t k = h & mask;
            int32_t row = -1;
            while (self->slots[k].hash) {
                slot_t *s = &self->slots[k];
                if (s->hash == h && s->key_len == len &&
                    memcmp(self->arena + s->key_off, key, len) == 0) {
                    row = s->row;
                    break;
                }
                k = (k + 1) & mask;
            }
            rows[i] = row;
            if (row < 0) {
                slot_out[i] = (int64_t)k;  /* the first empty slot found */
                misses++;
            } else {
                slot_out[i] = -1;
            }
        }
    }
    Py_END_ALLOW_THREADS
    if (bad_index >= 0) {
        PyErr_Format(PyExc_IndexError,
                     "pair %zd: code (%d, %d) out of table range",
                     bad_index, (int)ca[bad_index], (int)cb[bad_index]);
        goto fail;
    }

    PyMem_Free(scratch);
    PyMem_Free(views_a);
    PyMem_Free(views_b);
    PyBuffer_Release(&codes_a);
    PyBuffer_Release(&codes_b);
    PyBuffer_Release(&rows_v);
    PyBuffer_Release(&hashes_v);
    PyBuffer_Release(&slots_v);
    Py_DECREF(fast_a);
    Py_DECREF(fast_b);
    return PyLong_FromSsize_t(misses);

fail:
    PyMem_Free(scratch);
    PyMem_Free(views_a);
    PyMem_Free(views_b);
    if (codes_a.obj) PyBuffer_Release(&codes_a);
    if (codes_b.obj) PyBuffer_Release(&codes_b);
    if (rows_v.obj) PyBuffer_Release(&rows_v);
    if (hashes_v.obj) PyBuffer_Release(&hashes_v);
    if (slots_v.obj) PyBuffer_Release(&slots_v);
    Py_XDECREF(fast_a);
    Py_XDECREF(fast_b);
    return NULL;
}

/* commit_probed(a_table, a_codes, b_table, b_codes, rows, hashes, slots,
 *               probed_capacity) -> newly probed-in (miss) count committed
 *
 * The deterministic serial half of the sharded intern pass: walk the
 * batch IN INDEX ORDER and intern exactly the pairs the probe phase left
 * at rows[i] < 0, writing each assigned row back into rows[i] in place.
 * Row assignment is therefore first-occurrence-in-batch order —
 * identical, key for key, to one intern_pairs_indexed pass over the
 * whole batch (existing pairs never allocate in either path), which is
 * the byte-parity contract the delta-interning path rests on.
 *
 * probed_capacity is the slots-table capacity the probe ran against:
 * while it still holds (no resize since), each insert RESUMES from the
 * recorded first-empty slot — the hash and the walk to the insertion
 * point were already paid in the (parallel) probe phase, so the commit
 * does no full-chain re-probe. Equality is still checked from the
 * resumed slot (keys inserted since the probe live at or past it), so
 * duplicate keys in one batch commit to one row. The first internal
 * resize — or a capacity mismatch at entry, i.e. the map was mutated
 * between probe and commit — falls back to standard hash-based probing
 * for the remaining keys; results are identical either way.
 */
static PyObject *
InternMap_commit_probed(InternMap *self, PyObject *args)
{
    PyObject *a_table_obj, *b_table_obj, *a_codes_obj, *b_codes_obj;
    PyObject *rows_obj, *hashes_obj, *slots_obj;
    Py_ssize_t probed_capacity;
    if (!PyArg_ParseTuple(args, "OOOOOOOn", &a_table_obj, &a_codes_obj,
                          &b_table_obj, &b_codes_obj, &rows_obj,
                          &hashes_obj, &slots_obj, &probed_capacity))
        return NULL;

    PyObject *fast_a = NULL, *fast_b = NULL;
    ff_view_t *views_a = NULL, *views_b = NULL;
    char *scratch = NULL;
    Py_ssize_t scratch_cap = 0;
    Py_buffer codes_a, codes_b, rows_v, hashes_v, slots_v;
    codes_a.obj = codes_b.obj = rows_v.obj = hashes_v.obj = slots_v.obj =
        NULL;

    fast_a = PySequence_Fast(a_table_obj, "expected a sequence of str");
    if (!fast_a) goto fail;
    fast_b = PySequence_Fast(b_table_obj, "expected a sequence of str");
    if (!fast_b) goto fail;
    if (PyObject_GetBuffer(a_codes_obj, &codes_a, PyBUF_CONTIG_RO) < 0 ||
        PyObject_GetBuffer(b_codes_obj, &codes_b, PyBUF_CONTIG_RO) < 0 ||
        PyObject_GetBuffer(rows_obj, &rows_v, PyBUF_CONTIG) < 0 ||
        PyObject_GetBuffer(hashes_obj, &hashes_v, PyBUF_CONTIG_RO) < 0 ||
        PyObject_GetBuffer(slots_obj, &slots_v, PyBUF_CONTIG_RO) < 0)
        goto fail;
    if (codes_a.len != codes_b.len || codes_a.len % 4 != 0 ||
        rows_v.len != codes_a.len || hashes_v.len != codes_a.len * 2 ||
        slots_v.len != codes_a.len * 2) {
        PyErr_SetString(PyExc_ValueError,
                        "buffers must be equal-count int32 codes/rows with "
                        "uint64 hashes and int64 slots");
        goto fail;
    }
    Py_ssize_t n = codes_a.len / 4;
    Py_ssize_t na = PySequence_Fast_GET_SIZE(fast_a);
    Py_ssize_t nb = PySequence_Fast_GET_SIZE(fast_b);

    /* Lazy views, like intern_pairs_indexed: only entries a MISS
     * references resolve (hits never re-assemble their key). */
    views_a = PyMem_Calloc((size_t)(na ? na : 1), sizeof(ff_view_t));
    views_b = PyMem_Calloc((size_t)(nb ? nb : 1), sizeof(ff_view_t));
    if (!views_a || !views_b) {
        PyErr_NoMemory();
        goto fail;
    }
    scratch_cap = 256;
    scratch = PyMem_Malloc((size_t)scratch_cap);
    if (!scratch) {
        PyErr_NoMemory();
        goto fail;
    }

    const int32_t *ca = (const int32_t *)codes_a.buf;
    const int32_t *cb = (const int32_t *)codes_b.buf;
    int32_t *rows = (int32_t *)rows_v.buf;
    const uint64_t *hashes = (const uint64_t *)hashes_v.buf;
    const int64_t *slot_in = (const int64_t *)slots_v.buf;
    int use_slots = (size_t)probed_capacity == self->capacity;
    Py_ssize_t committed = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (rows[i] >= 0) continue;
        if (use_slots && i + 24 < n && rows[i + 24] < 0) {
            /* Look-ahead prefetch of an upcoming miss's recorded slot —
             * the insert's one random access. */
            int64_t ahead = slot_in[i + 24];
            if ((size_t)ahead < self->capacity)
                FF_PREFETCH(&self->slots[ahead]);
        }
        int32_t ia = ca[i], ib = cb[i];
        if (ia < 0 || ia >= na || ib < 0 || ib >= nb) {
            PyErr_Format(PyExc_IndexError,
                         "pair %zd: code (%d, %d) out of table range",
                         i, ia, ib);
            goto fail;
        }
        if (!views_a[ia].buf) {
            views_a[ia].buf = utf8_of(
                PySequence_Fast_GET_ITEM(fast_a, ia), &views_a[ia].len);
            if (!views_a[ia].buf ||
                reject_nul(views_a[ia].buf, views_a[ia].len) < 0)
                goto fail;
        }
        if (!views_b[ib].buf) {
            views_b[ib].buf = utf8_of(
                PySequence_Fast_GET_ITEM(fast_b, ib), &views_b[ib].len);
            if (!views_b[ib].buf ||
                reject_nul(views_b[ib].buf, views_b[ib].len) < 0)
                goto fail;
        }
        Py_ssize_t alen = views_a[ia].len, blen = views_b[ib].len;
        Py_ssize_t need = alen + 1 + blen;
        if (need > scratch_cap) {
            scratch_cap = need * 2;
            char *grown = PyMem_Realloc(scratch, (size_t)scratch_cap);
            if (!grown) {
                PyErr_NoMemory();
                goto fail;
            }
            scratch = grown;
        }
        memcpy(scratch, views_a[ia].buf, (size_t)alen);
        scratch[alen] = '\0';
        memcpy(scratch + alen + 1, views_b[ib].buf, (size_t)blen);
        uint64_t h = hashes[i];
        int32_t row = -1;
        if (use_slots && self->used * 3 >= self->capacity * 2) {
            if (map_resize(self, self->capacity * 2) < 0) goto fail;
            use_slots = 0;  /* recorded slots are stale from here on */
        }
        if (use_slots) {
            size_t mask = self->capacity - 1;
            size_t j = (size_t)slot_in[i];
            if (j >= self->capacity) {
                use_slots = 0;
                row = map_intern_hashed(self, scratch, (size_t)need, h);
            } else {
                while (self->slots[j].hash) {
                    slot_t *s = &self->slots[j];
                    if (s->hash == h && s->key_len == (uint32_t)need &&
                        memcmp(self->arena + s->key_off, scratch,
                               (size_t)need) == 0) {
                        row = s->row;
                        break;
                    }
                    j = (j + 1) & mask;
                }
                if (row < 0)
                    row = map_insert_at(self, j, scratch, (size_t)need, h);
            }
        } else {
            row = map_intern_hashed(self, scratch, (size_t)need, h);
        }
        if (row < 0) goto fail;
        rows[i] = row;
        committed++;
    }

    PyMem_Free(scratch);
    PyMem_Free(views_a);
    PyMem_Free(views_b);
    PyBuffer_Release(&codes_a);
    PyBuffer_Release(&codes_b);
    PyBuffer_Release(&rows_v);
    PyBuffer_Release(&hashes_v);
    PyBuffer_Release(&slots_v);
    Py_DECREF(fast_a);
    Py_DECREF(fast_b);
    return PyLong_FromSsize_t(committed);

fail:
    PyMem_Free(scratch);
    PyMem_Free(views_a);
    PyMem_Free(views_b);
    if (codes_a.obj) PyBuffer_Release(&codes_a);
    if (codes_b.obj) PyBuffer_Release(&codes_b);
    if (rows_v.obj) PyBuffer_Release(&rows_v);
    if (hashes_v.obj) PyBuffer_Release(&hashes_v);
    if (slots_v.obj) PyBuffer_Release(&slots_v);
    Py_XDECREF(fast_a);
    Py_XDECREF(fast_b);
    return NULL;
}

/* reserve_pairs(n) -> capacity token
 *
 * Pre-size the table for an incoming batch of *n* keys (the same
 * cold-load heuristic every batch insert runs) BEFORE a probe pass, so
 * the probe's recorded slots stay valid through the commit. Returns the
 * post-reserve capacity — the token commit_probed verifies.
 */
static PyObject *
InternMap_reserve_pairs(InternMap *self, PyObject *arg)
{
    Py_ssize_t n = PyLong_AsSsize_t(arg);
    if (n == -1 && PyErr_Occurred()) return NULL;
    if (n < 0) {
        PyErr_SetString(PyExc_ValueError, "reserve size must be >= 0");
        return NULL;
    }
    if (map_reserve_cold(self, (size_t)n) < 0) return NULL;
    return PyLong_FromSize_t(self->capacity);
}

static PyObject *
InternMap_lookup(InternMap *self, PyObject *arg)
{
    Py_ssize_t len;
    const char *buf = utf8_of(arg, &len);
    if (!buf) return NULL;
    return PyLong_FromLong(map_lookup(self, buf, (size_t)len));
}

static PyObject *
InternMap_lookup_pair(InternMap *self, PyObject *args)
{
    PyObject *a, *b;
    if (!PyArg_ParseTuple(args, "OO", &a, &b)) return NULL;
    char *scratch = NULL;
    Py_ssize_t cap = 0;
    Py_ssize_t len = pair_key(a, b, &scratch, &cap);
    if (len < 0) {
        PyMem_Free(scratch);
        return NULL;
    }
    long row = map_lookup(self, scratch, (size_t)len);
    PyMem_Free(scratch);
    return PyLong_FromLong(row);
}

static PyObject *
InternMap_lookup_pairs(InternMap *self, PyObject *args)
{
    PyObject *fast_a, *fast_b;
    Py_ssize_t n;
    if (two_seqs(args, &fast_a, &fast_b, &n) < 0) return NULL;
    PyObject *out = PyByteArray_FromStringAndSize(NULL, n * 4);
    char *scratch = NULL;
    Py_ssize_t cap = 0;
    if (!out) goto fail;
    int32_t *rows = (int32_t *)PyByteArray_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *a = PySequence_Fast_GET_ITEM(fast_a, i);
        PyObject *b = PySequence_Fast_GET_ITEM(fast_b, i);
        Py_ssize_t len = pair_key(a, b, &scratch, &cap);
        if (len < 0) goto fail;
        rows[i] = map_lookup(self, scratch, (size_t)len);
    }
    PyMem_Free(scratch);
    Py_DECREF(fast_a);
    Py_DECREF(fast_b);
    return out;
fail:
    PyMem_Free(scratch);
    Py_XDECREF(out);
    Py_DECREF(fast_a);
    Py_DECREF(fast_b);
    return NULL;
}

static PyObject *
InternMap_ids(InternMap *self, PyObject *Py_UNUSED(ignored))
{
    PyObject *out = PyList_New((Py_ssize_t)self->used);
    if (!out) return NULL;
    for (size_t row = 0; row < self->used; row++) {
        PyObject *id_obj = row_to_id(self, row);
        if (!id_obj) {
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, (Py_ssize_t)row, id_obj);
    }
    return out;
}

static PyObject *
InternMap_id_of(InternMap *self, PyObject *arg)
{
    Py_ssize_t row = PyLong_AsSsize_t(arg);
    if (row == -1 && PyErr_Occurred()) return NULL;
    if (row < 0 || (size_t)row >= self->used) {
        PyErr_SetString(PyExc_IndexError, "row out of range");
        return NULL;
    }
    return row_to_id(self, (size_t)row);
}

static Py_ssize_t
InternMap_len(InternMap *self)
{
    return (Py_ssize_t)self->used;
}

/* pair_blob(lo, hi) -> bytes
 *
 * Rows [lo, hi) as (u32 src_len, src utf-8, u32 mkt_len, mkt utf-8) —
 * the durability journal's pair wire format (state/journal.py), built
 * at memcpy speed straight from the arena instead of per-row Python
 * struct.pack (measured: the Python loop cost ~seconds per million
 * rows and dominated a journal epoch). Lengths are written in host
 * byte order; the journal format is little-endian, which every
 * platform this builds on is — static-asserted at module init. */
static PyObject *
InternMap_pair_blob(InternMap *self, PyObject *args)
{
    Py_ssize_t lo, hi;
    if (!PyArg_ParseTuple(args, "nn", &lo, &hi)) return NULL;
    if (lo < 0 || hi < lo || (size_t)hi > self->used) {
        PyErr_SetString(PyExc_IndexError, "row range out of bounds");
        return NULL;
    }
    size_t total = 0;
    for (Py_ssize_t row = lo; row < hi; row++) {
        if (self->rows[row].len < 1) {
            PyErr_SetString(PyExc_ValueError, "corrupt arena row");
            return NULL;
        }
        /* two u32 prefixes + key bytes minus the NUL joiner */
        total += 8 + (size_t)self->rows[row].len - 1;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)total);
    if (!out) return NULL;
    char *dst = PyBytes_AS_STRING(out);
    for (Py_ssize_t row = lo; row < hi; row++) {
        const char *key = self->arena + self->rows[row].off;
        uint32_t len = self->rows[row].len;
        const char *nul = memchr(key, '\0', len);
        if (!nul) {
            Py_DECREF(out);
            PyErr_SetString(PyExc_ValueError, "arena key missing joiner");
            return NULL;
        }
        uint32_t src_len = (uint32_t)(nul - key);
        uint32_t mkt_len = len - src_len - 1;
        memcpy(dst, &src_len, 4); dst += 4;
        memcpy(dst, key, src_len); dst += src_len;
        memcpy(dst, &mkt_len, 4); dst += 4;
        memcpy(dst, nul + 1, mkt_len); dst += mkt_len;
    }
    return out;
}

/* pack_strings(list[str]) -> bytes: u32-length-prefixed UTF-8 values —
 * the journal's iso-blob wire format, one C pass instead of per-row
 * struct.pack. */
static PyObject *
internmap_pack_strings(PyObject *Py_UNUSED(module), PyObject *arg)
{
    if (!PyList_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "pack_strings takes a list of str");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(arg);
    size_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        Py_ssize_t len;
        /* Cached on the unicode object; the second pass reuses it. */
        if (!PyUnicode_AsUTF8AndSize(PyList_GET_ITEM(arg, i), &len))
            return NULL;
        total += 4 + (size_t)len;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)total);
    if (!out) return NULL;
    char *dst = PyBytes_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        Py_ssize_t len;
        const char *buf =
            PyUnicode_AsUTF8AndSize(PyList_GET_ITEM(arg, i), &len);
        if (!buf) {
            Py_DECREF(out);
            return NULL;
        }
        uint32_t le_len = (uint32_t)len;
        memcpy(dst, &le_len, 4); dst += 4;
        memcpy(dst, buf, (size_t)len); dst += len;
    }
    return out;
}

/* ---- key-order sort ------------------------------------------------------ */

/* memcmp over the raw arena keys == Python's (source, market) tuple sort:
 * the NUL joiner sorts below every valid id byte (ids reject NUL), and
 * UTF-8 byte order equals Unicode code-point order.
 *
 * The comparator context travels through a file-static pointer instead of
 * qsort_r (whose signature differs between glibc and BSD/macOS); the GIL
 * is held across the qsort call, so the static cannot be raced. */
static const InternMap *sort_ctx;

static int
key_bytes_cmp(const void *pa, const void *pb)
{
    const InternMap *self = sort_ctx;
    const rowref_t *ra = &self->rows[*(const int32_t *)pa];
    const rowref_t *rb = &self->rows[*(const int32_t *)pb];
    size_t min_len = ra->len < rb->len ? ra->len : rb->len;
    int cmp = memcmp(self->arena + ra->off, self->arena + rb->off, min_len);
    if (cmp) return cmp;
    return (ra->len > rb->len) - (ra->len < rb->len);
}

/* sorted_rows(buffer[i32]) -> bytearray[i32]: the given rows reordered by
 * their key bytes. Rows must be valid (0 <= row < len(self)). */
static PyObject *
InternMap_sorted_rows(InternMap *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_CONTIG_RO) < 0) return NULL;
    if (view.len % 4 != 0) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError,
                        "rows buffer length must be a multiple of 4 (int32)");
        return NULL;
    }
    Py_ssize_t n = view.len / 4;
    PyObject *out = PyByteArray_FromStringAndSize(view.buf, view.len);
    PyBuffer_Release(&view);
    if (!out) return NULL;
    int32_t *rows = (int32_t *)PyByteArray_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (rows[i] < 0 || (size_t)rows[i] >= self->used) {
            Py_DECREF(out);
            PyErr_Format(PyExc_IndexError, "row %d out of range", rows[i]);
            return NULL;
        }
    }
    sort_ctx = self;
    qsort(rows, (size_t)n, sizeof(int32_t), key_bytes_cmp);
    return out;
}

/* ---- SQLite checkpoint writer -------------------------------------------- */

/* Hand-declared slice of the stable sqlite3 C ABI, resolved from the
 * runtime library with dlopen: this image ships libsqlite3.so.0 but no
 * development header, and the checkpoint writer needs only these twelve
 * entry points. */
typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;

#define FF_SQLITE_OK 0
#define FF_SQLITE_ROW 100
#define FF_SQLITE_DONE 101
#define FF_SQLITE_OPEN_READWRITE 0x2
#define FF_SQLITE_OPEN_CREATE 0x4
#define FF_SQLITE_STATIC ((void (*)(void *))0)

static struct {
    int loaded; /* 0 = not tried, 1 = ok, -1 = unavailable */
    int (*open_v2)(const char *, sqlite3 **, int, const char *);
    int (*close)(sqlite3 *);
    int (*exec)(sqlite3 *, const char *,
                int (*)(void *, int, char **, char **), void *, char **);
    void (*free)(void *);
    int (*prepare_v2)(sqlite3 *, const char *, int, sqlite3_stmt **,
                      const char **);
    int (*bind_text)(sqlite3_stmt *, int, const char *, int,
                     void (*)(void *));
    int (*bind_double)(sqlite3_stmt *, int, double);
    int (*step)(sqlite3_stmt *);
    int (*reset)(sqlite3_stmt *);
    int (*finalize)(sqlite3_stmt *);
    int (*column_int)(sqlite3_stmt *, int);
    const char *(*errmsg)(sqlite3 *);
    int (*busy_timeout)(sqlite3 *, int);
} ff_sql;

static int
sqlite_runtime_load(void)
{
    if (ff_sql.loaded) return ff_sql.loaded;
#ifdef _WIN32
    ff_sql.loaded = -1;
    return -1;
#else
    /* RTLD_LOCAL: every entry point is dlsym-resolved, and exporting the
     * symbols process-wide could interpose on another extension's own
     * sqlite build. */
    void *lib = dlopen("libsqlite3.so.0", RTLD_NOW | RTLD_LOCAL);
    if (!lib) lib = dlopen("libsqlite3.so", RTLD_NOW | RTLD_LOCAL);
    if (!lib) lib = dlopen("libsqlite3.dylib", RTLD_NOW | RTLD_LOCAL);
    if (!lib) {
        ff_sql.loaded = -1;
        return -1;
    }
#define FF_RESOLVE(field, symbol)                                   \
    do {                                                            \
        *(void **)(&ff_sql.field) = dlsym(lib, symbol);             \
        if (!ff_sql.field) {                                        \
            ff_sql.loaded = -1;                                     \
            return -1;                                              \
        }                                                           \
    } while (0)
    FF_RESOLVE(open_v2, "sqlite3_open_v2");
    FF_RESOLVE(close, "sqlite3_close");
    FF_RESOLVE(exec, "sqlite3_exec");
    FF_RESOLVE(free, "sqlite3_free");
    FF_RESOLVE(prepare_v2, "sqlite3_prepare_v2");
    FF_RESOLVE(bind_text, "sqlite3_bind_text");
    FF_RESOLVE(bind_double, "sqlite3_bind_double");
    FF_RESOLVE(step, "sqlite3_step");
    FF_RESOLVE(reset, "sqlite3_reset");
    FF_RESOLVE(finalize, "sqlite3_finalize");
    FF_RESOLVE(column_int, "sqlite3_column_int");
    FF_RESOLVE(errmsg, "sqlite3_errmsg");
    FF_RESOLVE(busy_timeout, "sqlite3_busy_timeout");
#undef FF_RESOLVE
    ff_sql.loaded = 1;
    return 1;
#endif
}

static const char FF_SCHEMA_SQL[] =
    "CREATE TABLE IF NOT EXISTS sources ("
    " source_id   TEXT    NOT NULL,"
    " market_id   TEXT    NOT NULL,"
    " reliability REAL    NOT NULL DEFAULT 0.5,"
    " confidence  REAL    NOT NULL DEFAULT 0.5,"
    " updated_at  TEXT    NOT NULL,"
    " PRIMARY KEY (source_id, market_id) )"; /* trailing space matches the
       whitespace-normalized sqlite_store._SCHEMA_SQL text exactly (pinned
       by TestNativeFlushParity's sqlite_master comparison) */

static const char FF_UPSERT_SQL[] =
    "INSERT INTO sources"
    " (source_id, market_id, reliability, confidence, updated_at)"
    " VALUES (?, ?, ?, ?, ?)"
    " ON CONFLICT(source_id, market_id)"
    " DO UPDATE SET reliability = excluded.reliability,"
    "               confidence  = excluded.confidence,"
    "               updated_at  = excluded.updated_at";

static const char FF_INSERT_SQL[] =
    "INSERT OR REPLACE INTO sources"
    " (source_id, market_id, reliability, confidence, updated_at)"
    " VALUES (?, ?, ?, ?, ?)";

/* Set a RuntimeError from the connection's message and clean up. */
static void
sqlite_fail(sqlite3 *db, sqlite3_stmt *stmt, const char *doing)
{
    PyErr_Format(PyExc_RuntimeError, "sqlite checkpoint (%s): %s", doing,
                 db ? ff_sql.errmsg(db) : "library unavailable");
    if (stmt) ff_sql.finalize(stmt);
    if (db) {
        ff_sql.exec(db, "ROLLBACK", NULL, NULL, NULL);
        ff_sql.close(db);
    }
}

/* flush_sqlite(path, rows, rel, conf, iso) -> written row count.
 *
 * rows: contiguous int32 buffer of this map's pair-key rows, in the exact
 * order they should hit the file (pre-sort with sorted_rows for the
 * deterministic checkpoint order). rel/conf: contiguous float64 buffers
 * indexed BY ROW (full store columns). iso: list of str indexed by row.
 *
 * Matches the sqlite3-module path byte-for-byte in observable semantics:
 * same WAL journal, same schema, empty-table INSERT fast path, UPSERT
 * otherwise, one transaction. The GIL is held throughout: bindings point
 * into the arena with SQLITE_STATIC lifetimes, and a concurrent intern
 * could realloc the arena out from under them (the store is
 * single-writer by contract; holding the GIL turns that contract into a
 * guarantee here).
 */
static PyObject *
InternMap_flush_sqlite(InternMap *self, PyObject *args)
{
    const char *path;
    PyObject *rows_obj, *rel_obj, *conf_obj, *iso_obj;
    if (!PyArg_ParseTuple(args, "sOOOO", &path, &rows_obj, &rel_obj,
                          &conf_obj, &iso_obj))
        return NULL;
    if (sqlite_runtime_load() < 0) {
        PyErr_SetString(PyExc_RuntimeError,
                        "libsqlite3 runtime library not available");
        return NULL;
    }
    if (!PyList_Check(iso_obj)) {
        PyErr_SetString(PyExc_TypeError, "iso must be a list of str");
        return NULL;
    }

    Py_buffer rows_view, rel_view, conf_view;
    if (PyObject_GetBuffer(rows_obj, &rows_view, PyBUF_CONTIG_RO) < 0)
        return NULL;
    if (PyObject_GetBuffer(rel_obj, &rel_view, PyBUF_CONTIG_RO) < 0) {
        PyBuffer_Release(&rows_view);
        return NULL;
    }
    if (PyObject_GetBuffer(conf_obj, &conf_view, PyBUF_CONTIG_RO) < 0) {
        PyBuffer_Release(&rows_view);
        PyBuffer_Release(&rel_view);
        return NULL;
    }
    const int32_t *rows = (const int32_t *)rows_view.buf;
    const double *rel = (const double *)rel_view.buf;
    const double *conf = (const double *)conf_view.buf;
    Py_ssize_t n = rows_view.len / 4;
    Py_ssize_t value_rows = rel_view.len / 8;
    if (conf_view.len != rel_view.len || rows_view.len % 4 != 0) {
        PyErr_SetString(PyExc_ValueError,
                        "rows must be int32; rel/conf must be equal-length "
                        "float64 columns");
        goto fail_views;
    }
    Py_ssize_t iso_len = PyList_GET_SIZE(iso_obj);

    /* Pre-validate rows and pre-extract iso UTF-8 views while binding is
     * still cheap to abort; utf8 caches live on the str objects the iso
     * list keeps alive for the whole call. */
    typedef struct { const char *buf; Py_ssize_t len; } strview_t;
    strview_t *iso_views = PyMem_Malloc((size_t)(n ? n : 1) * sizeof(strview_t));
    if (!iso_views) {
        PyErr_NoMemory();
        goto fail_views;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t row = rows[i];
        if (row < 0 || (size_t)row >= self->used || row >= value_rows ||
            row >= iso_len) {
            PyErr_Format(PyExc_IndexError,
                         "row %d out of range of the map/columns", row);
            PyMem_Free(iso_views);
            goto fail_views;
        }
        if (!memchr(self->arena + self->rows[row].off, '\0',
                    self->rows[row].len)) {
            PyErr_Format(PyExc_ValueError,
                         "row %d is a single-string key, not a pair", row);
            PyMem_Free(iso_views);
            goto fail_views;
        }
        PyObject *iso_item = PyList_GET_ITEM(iso_obj, row);
        iso_views[i].buf = utf8_of(iso_item, &iso_views[i].len);
        if (!iso_views[i].buf) {
            PyMem_Free(iso_views);
            goto fail_views;
        }
    }

    sqlite3 *db = NULL;
    sqlite3_stmt *stmt = NULL;
    if (ff_sql.open_v2(path, &db,
                       FF_SQLITE_OPEN_READWRITE | FF_SQLITE_OPEN_CREATE,
                       NULL) != FF_SQLITE_OK) {
        sqlite_fail(db, NULL, "open");
        goto fail_iso;
    }
    /* Match the sqlite3 module's default 5 s busy wait so a concurrent
     * reader holding the lock briefly delays the flush instead of
     * failing it. */
    ff_sql.busy_timeout(db, 5000);
    /* 16 KB pages for FRESH checkpoint files (fewer B-tree nodes for long
     * text rows; measured ~13% on the bulk insert). Must precede WAL and
     * the first write; a no-op on existing files, whose page size is
     * fixed — any sqlite >= 3.12 reads either. 256 MB page cache for the
     * bulk transaction: the default ~2 MB cache thrashes on a
     * multi-million-row B-tree (measured 1.5x slower at 4M rows);
     * connection-local, not persisted in the file. */
    if (ff_sql.exec(db, "PRAGMA page_size=16384", NULL, NULL, NULL) !=
            FF_SQLITE_OK ||
        ff_sql.exec(db, "PRAGMA journal_mode=WAL", NULL, NULL, NULL) !=
            FF_SQLITE_OK ||
        ff_sql.exec(db, "PRAGMA foreign_keys=ON", NULL, NULL, NULL) !=
            FF_SQLITE_OK ||
        ff_sql.exec(db, "PRAGMA cache_size=-262144", NULL, NULL, NULL) !=
            FF_SQLITE_OK ||
        ff_sql.exec(db, FF_SCHEMA_SQL, NULL, NULL, NULL) != FF_SQLITE_OK) {
        sqlite_fail(db, NULL, "schema");
        goto fail_iso;
    }

    /* Empty table => plain INSERT (same fast path as put_rows). */
    int empty = 0;
    if (ff_sql.prepare_v2(db, "SELECT NOT EXISTS (SELECT 1 FROM sources)",
                          -1, &stmt, NULL) != FF_SQLITE_OK ||
        ff_sql.step(stmt) != FF_SQLITE_ROW) {
        sqlite_fail(db, stmt, "empty probe");
        goto fail_iso;
    }
    empty = ff_sql.column_int(stmt, 0);
    ff_sql.finalize(stmt);
    stmt = NULL;

    if (ff_sql.exec(db, "BEGIN", NULL, NULL, NULL) != FF_SQLITE_OK ||
        ff_sql.prepare_v2(db, empty ? FF_INSERT_SQL : FF_UPSERT_SQL, -1,
                          &stmt, NULL) != FF_SQLITE_OK) {
        sqlite_fail(db, stmt, "begin");
        goto fail_iso;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t row = rows[i];
        const char *key = self->arena + self->rows[row].off;
        size_t key_len = self->rows[row].len;
        const char *sep = memchr(key, '\0', key_len);
        if (ff_sql.bind_text(stmt, 1, key, (int)(sep - key),
                             FF_SQLITE_STATIC) != FF_SQLITE_OK ||
            ff_sql.bind_text(stmt, 2, sep + 1,
                             (int)(key_len - (size_t)(sep - key) - 1),
                             FF_SQLITE_STATIC) != FF_SQLITE_OK ||
            ff_sql.bind_double(stmt, 3, rel[row]) != FF_SQLITE_OK ||
            ff_sql.bind_double(stmt, 4, conf[row]) != FF_SQLITE_OK ||
            ff_sql.bind_text(stmt, 5, iso_views[i].buf,
                             (int)iso_views[i].len, FF_SQLITE_STATIC) !=
                FF_SQLITE_OK ||
            ff_sql.step(stmt) != FF_SQLITE_DONE ||
            ff_sql.reset(stmt) != FF_SQLITE_OK) {
            sqlite_fail(db, stmt, "insert");
            goto fail_iso;
        }
    }
    ff_sql.finalize(stmt);
    stmt = NULL;
    if (ff_sql.exec(db, "COMMIT", NULL, NULL, NULL) != FF_SQLITE_OK) {
        sqlite_fail(db, NULL, "commit");
        goto fail_iso;
    }
    ff_sql.close(db);
    PyMem_Free(iso_views);
    PyBuffer_Release(&rows_view);
    PyBuffer_Release(&rel_view);
    PyBuffer_Release(&conf_view);
    return PyLong_FromSsize_t(n);

fail_iso:
    PyMem_Free(iso_views);
fail_views:
    PyBuffer_Release(&rows_view);
    PyBuffer_Release(&rel_view);
    PyBuffer_Release(&conf_view);
    return NULL;
}

/* snapshot_rows(rows, rel, conf, iso) -> bytes
 *
 * A self-contained copy of everything a checkpoint flush needs for the
 * given rows, in write order. Layout (native endianness, 8-aligned):
 *
 *   [0]        int64  n
 *   [8]        int64  heap_off        (string heap's offset in the blob)
 *   [16]       double vals[2n]        (reliability, confidence) per row
 *   [16+16n]   int32  meta[6n]        (src_off, src_len, mkt_off, mkt_len,
 *                                      iso_off, iso_len), heap-relative
 *   [heap_off] string heap bytes
 *
 * The point of the copy: flush_sqlite must hold the GIL for its whole
 * write (its SQLITE_STATIC bindings alias the live arena, which a
 * concurrent intern may realloc). A snapshot owns its bytes, so
 * flush_snapshot() below can release the GIL for the entire SQLite
 * transaction and a background checkpoint thread truly overlaps with
 * ingest/settle host work (state/tensor_store.flush_to_sqlite_async).
 */
static PyObject *
InternMap_snapshot_rows(InternMap *self, PyObject *args)
{
    PyObject *rows_obj, *rel_obj, *conf_obj, *iso_obj;
    if (!PyArg_ParseTuple(args, "OOOO", &rows_obj, &rel_obj, &conf_obj,
                          &iso_obj))
        return NULL;
    if (!PyList_Check(iso_obj)) {
        PyErr_SetString(PyExc_TypeError, "iso must be a list of str");
        return NULL;
    }

    Py_buffer rows_view, rel_view, conf_view;
    if (PyObject_GetBuffer(rows_obj, &rows_view, PyBUF_CONTIG_RO) < 0)
        return NULL;
    if (PyObject_GetBuffer(rel_obj, &rel_view, PyBUF_CONTIG_RO) < 0) {
        PyBuffer_Release(&rows_view);
        return NULL;
    }
    if (PyObject_GetBuffer(conf_obj, &conf_view, PyBUF_CONTIG_RO) < 0) {
        PyBuffer_Release(&rows_view);
        PyBuffer_Release(&rel_view);
        return NULL;
    }
    const int32_t *rows = (const int32_t *)rows_view.buf;
    const double *rel = (const double *)rel_view.buf;
    const double *conf = (const double *)conf_view.buf;
    Py_ssize_t n = rows_view.len / 4;
    Py_ssize_t value_rows = rel_view.len / 8;
    PyObject *blob = NULL;
    typedef struct { const char *buf; Py_ssize_t len; } strview_t;
    strview_t *iso_views = NULL;
    if (conf_view.len != rel_view.len || rows_view.len % 4 != 0) {
        PyErr_SetString(PyExc_ValueError,
                        "rows must be int32; rel/conf must be equal-length "
                        "float64 columns");
        goto out;
    }
    Py_ssize_t iso_len = PyList_GET_SIZE(iso_obj);

    iso_views = PyMem_Malloc((size_t)(n ? n : 1) * sizeof(strview_t));
    if (!iso_views) {
        PyErr_NoMemory();
        goto out;
    }
    /* Validate + measure the heap in one pass (utf8 views cache on the
     * str objects the iso list keeps alive for this call). */
    int64_t heap_len = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t row = rows[i];
        if (row < 0 || (size_t)row >= self->used || row >= value_rows ||
            row >= iso_len) {
            PyErr_Format(PyExc_IndexError,
                         "row %d out of range of the map/columns", row);
            goto out;
        }
        if (!memchr(self->arena + self->rows[row].off, '\0',
                    self->rows[row].len)) {
            PyErr_Format(PyExc_ValueError,
                         "row %d is a single-string key, not a pair", row);
            goto out;
        }
        PyObject *iso_item = PyList_GET_ITEM(iso_obj, row);
        iso_views[i].buf = utf8_of(iso_item, &iso_views[i].len);
        if (!iso_views[i].buf) goto out;
        /* Key bytes minus the NUL separator + the iso stamp. */
        heap_len += (int64_t)self->rows[row].len - 1 + iso_views[i].len;
    }

    int64_t heap_off = 16 + 16 * (int64_t)n + 24 * (int64_t)n;
    if (heap_len > INT32_MAX) {
        PyErr_SetString(PyExc_OverflowError,
                        "snapshot heap exceeds int32 offsets");
        goto out;
    }
    blob = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)(heap_off + heap_len));
    if (!blob) goto out;
    char *base = PyBytes_AS_STRING(blob);
    ((int64_t *)base)[0] = n;
    ((int64_t *)base)[1] = heap_off;
    double *vals = (double *)(base + 16);
    int32_t *meta = (int32_t *)(base + 16 + 16 * n);
    char *heap = base + heap_off;
    int32_t cursor = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t row = rows[i];
        const char *key = self->arena + self->rows[row].off;
        size_t key_len = self->rows[row].len;
        const char *sep = memchr(key, '\0', key_len);
        int32_t src_len = (int32_t)(sep - key);
        int32_t mkt_len = (int32_t)(key_len - (size_t)src_len - 1);
        vals[2 * i] = rel[row];
        vals[2 * i + 1] = conf[row];
        meta[6 * i] = cursor;
        meta[6 * i + 1] = src_len;
        memcpy(heap + cursor, key, (size_t)src_len);
        cursor += src_len;
        meta[6 * i + 2] = cursor;
        meta[6 * i + 3] = mkt_len;
        memcpy(heap + cursor, sep + 1, (size_t)mkt_len);
        cursor += mkt_len;
        meta[6 * i + 4] = cursor;
        meta[6 * i + 5] = (int32_t)iso_views[i].len;
        memcpy(heap + cursor, iso_views[i].buf, (size_t)iso_views[i].len);
        cursor += (int32_t)iso_views[i].len;
    }

out:
    PyMem_Free(iso_views);
    PyBuffer_Release(&rows_view);
    PyBuffer_Release(&rel_view);
    PyBuffer_Release(&conf_view);
    return blob;
}

/* flush_snapshot(path, blob) -> written row count.
 *
 * The GIL-free twin of flush_sqlite over a snapshot_rows() blob: every
 * binding points into the blob (owned bytes — nothing can move under it),
 * so the whole SQLite transaction runs with the GIL RELEASED and a
 * background flush thread overlaps with foreground Python. Identical
 * observable file semantics to flush_sqlite (same pragmas, schema,
 * empty-table INSERT fast path, UPSERT otherwise, one transaction).
 */
static PyObject *
internmap_flush_snapshot(PyObject *Py_UNUSED(module), PyObject *args)
{
    const char *path;
    Py_buffer blob;
    if (!PyArg_ParseTuple(args, "sy*", &path, &blob))
        return NULL;
    if (sqlite_runtime_load() < 0) {
        PyBuffer_Release(&blob);
        PyErr_SetString(PyExc_RuntimeError,
                        "libsqlite3 runtime library not available");
        return NULL;
    }
    const char *base = (const char *)blob.buf;
    if (blob.len < 16) {
        PyBuffer_Release(&blob);
        PyErr_SetString(PyExc_ValueError, "snapshot blob truncated");
        return NULL;
    }
    int64_t n = ((const int64_t *)base)[0];
    int64_t heap_off = ((const int64_t *)base)[1];
    int64_t heap_len = (int64_t)blob.len - heap_off;
    /* Bound n BEFORE the 16 + 40*n multiply: a corrupt blob with a huge n
     * would overflow the int64 (UB) and could wrap past this check. */
    if (n < 0 || n > ((int64_t)blob.len - 16) / 40 ||
        heap_off != 16 + 40 * n || heap_off > (int64_t)blob.len) {
        PyBuffer_Release(&blob);
        PyErr_SetString(PyExc_ValueError, "malformed snapshot blob header");
        return NULL;
    }
    const double *vals = (const double *)(base + 16);
    const int32_t *meta = (const int32_t *)(base + 16 + 16 * n);
    const char *heap = base + heap_off;
    /* Bounds-check every span with the GIL still held (errors can raise
     * here; the no-GIL region below must be exception-free). */
    for (int64_t i = 0; i < n; i++) {
        const int32_t *m = meta + 6 * i;
        for (int half = 0; half < 3; half++) {
            int64_t off = m[2 * half], len = m[2 * half + 1];
            if (off < 0 || len < 0 || off + len > heap_len) {
                PyBuffer_Release(&blob);
                PyErr_SetString(PyExc_ValueError,
                                "snapshot span out of bounds");
                return NULL;
            }
        }
    }

    const char *fail_step = NULL;
    char fail_msg[256] = "";
    sqlite3 *db = NULL;
    sqlite3_stmt *stmt = NULL;

    Py_BEGIN_ALLOW_THREADS
#define FF_NOGIL_FAIL(step)                                              \
    do {                                                                 \
        fail_step = (step);                                              \
        snprintf(fail_msg, sizeof(fail_msg), "%s",                       \
                 db ? ff_sql.errmsg(db) : "library unavailable");        \
        goto nogil_done;                                                 \
    } while (0)

    if (ff_sql.open_v2(path, &db,
                       FF_SQLITE_OPEN_READWRITE | FF_SQLITE_OPEN_CREATE,
                       NULL) != FF_SQLITE_OK)
        FF_NOGIL_FAIL("open");
    ff_sql.busy_timeout(db, 5000);
    if (ff_sql.exec(db, "PRAGMA page_size=16384", NULL, NULL, NULL) !=
            FF_SQLITE_OK ||
        ff_sql.exec(db, "PRAGMA journal_mode=WAL", NULL, NULL, NULL) !=
            FF_SQLITE_OK ||
        ff_sql.exec(db, "PRAGMA foreign_keys=ON", NULL, NULL, NULL) !=
            FF_SQLITE_OK ||
        ff_sql.exec(db, "PRAGMA cache_size=-262144", NULL, NULL, NULL) !=
            FF_SQLITE_OK ||
        ff_sql.exec(db, FF_SCHEMA_SQL, NULL, NULL, NULL) != FF_SQLITE_OK)
        FF_NOGIL_FAIL("schema");

    int empty = 0;
    if (ff_sql.prepare_v2(db, "SELECT NOT EXISTS (SELECT 1 FROM sources)",
                          -1, &stmt, NULL) != FF_SQLITE_OK ||
        ff_sql.step(stmt) != FF_SQLITE_ROW)
        FF_NOGIL_FAIL("empty probe");
    empty = ff_sql.column_int(stmt, 0);
    ff_sql.finalize(stmt);
    stmt = NULL;

    if (ff_sql.exec(db, "BEGIN", NULL, NULL, NULL) != FF_SQLITE_OK ||
        ff_sql.prepare_v2(db, empty ? FF_INSERT_SQL : FF_UPSERT_SQL, -1,
                          &stmt, NULL) != FF_SQLITE_OK)
        FF_NOGIL_FAIL("begin");
    for (int64_t i = 0; i < n; i++) {
        const int32_t *m = meta + 6 * i;
        if (ff_sql.bind_text(stmt, 1, heap + m[0], m[1],
                             FF_SQLITE_STATIC) != FF_SQLITE_OK ||
            ff_sql.bind_text(stmt, 2, heap + m[2], m[3],
                             FF_SQLITE_STATIC) != FF_SQLITE_OK ||
            ff_sql.bind_double(stmt, 3, vals[2 * i]) != FF_SQLITE_OK ||
            ff_sql.bind_double(stmt, 4, vals[2 * i + 1]) != FF_SQLITE_OK ||
            ff_sql.bind_text(stmt, 5, heap + m[4], m[5],
                             FF_SQLITE_STATIC) != FF_SQLITE_OK ||
            ff_sql.step(stmt) != FF_SQLITE_DONE ||
            ff_sql.reset(stmt) != FF_SQLITE_OK)
            FF_NOGIL_FAIL("insert");
    }
    ff_sql.finalize(stmt);
    stmt = NULL;
    if (ff_sql.exec(db, "COMMIT", NULL, NULL, NULL) != FF_SQLITE_OK)
        FF_NOGIL_FAIL("commit");
    ff_sql.close(db);
    db = NULL;

nogil_done:
    if (fail_step) {
        if (stmt) ff_sql.finalize(stmt);
        if (db) {
            ff_sql.exec(db, "ROLLBACK", NULL, NULL, NULL);
            ff_sql.close(db);
        }
    }
#undef FF_NOGIL_FAIL
    Py_END_ALLOW_THREADS

    PyBuffer_Release(&blob);
    if (fail_step) {
        PyErr_Format(PyExc_RuntimeError, "sqlite checkpoint (%s): %s",
                     fail_step, fail_msg);
        return NULL;
    }
    return PyLong_FromSsize_t((Py_ssize_t)n);
}

/* ---- type ---------------------------------------------------------------- */

static PyObject *
InternMap_new(PyTypeObject *type, PyObject *Py_UNUSED(args),
              PyObject *Py_UNUSED(kwargs))
{
    InternMap *self = (InternMap *)type->tp_alloc(type, 0);
    if (!self) return NULL;
    self->capacity = 64;
    self->used = 0;
    self->slots = PyMem_Calloc(self->capacity, sizeof(slot_t));
    self->arena_cap = 1024;
    self->arena_used = 0;
    self->arena = PyMem_Malloc(self->arena_cap);
    self->rows_cap = 64;
    self->rows = PyMem_Malloc(self->rows_cap * sizeof(rowref_t));
    if (!self->slots || !self->arena || !self->rows) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return NULL;
    }
    return (PyObject *)self;
}

static void
InternMap_dealloc(InternMap *self)
{
    PyMem_Free(self->slots);
    PyMem_Free(self->arena);
    PyMem_Free(self->rows);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyMethodDef InternMap_methods[] = {
    {"intern", (PyCFunction)InternMap_intern, METH_O,
     "intern(id) -> row (assigning the next row if new)"},
    {"intern_pair", (PyCFunction)InternMap_intern_pair, METH_VARARGS,
     "intern_pair(a, b) -> row for the (a, b) pair key"},
    {"intern_batch", (PyCFunction)InternMap_intern_batch, METH_O,
     "intern_batch(seq) -> bytearray of int32 rows"},
    {"intern_pairs", (PyCFunction)InternMap_intern_pairs, METH_VARARGS,
     "intern_pairs(seq_a, seq_b) -> bytearray of int32 rows"},
    {"intern_pairs_indexed",
     (PyCFunction)InternMap_intern_pairs_indexed, METH_VARARGS,
     "intern_pairs_indexed(a_table, a_codes, b_table, b_codes) -> rows"},
    {"probe_pairs_indexed",
     (PyCFunction)InternMap_probe_pairs_indexed, METH_VARARGS,
     "probe_pairs_indexed(a_table, a_codes, b_table, b_codes, rows, "
     "hashes, slots, start, stop) -> miss count (lookup only, GIL "
     "released)"},
    {"commit_probed", (PyCFunction)InternMap_commit_probed, METH_VARARGS,
     "commit_probed(a_table, a_codes, b_table, b_codes, rows, hashes, "
     "slots, probed_capacity) -> interned miss count (in batch order)"},
    {"reserve_pairs", (PyCFunction)InternMap_reserve_pairs, METH_O,
     "reserve_pairs(n) -> capacity token (pre-size before a probe pass)"},
    {"lookup", (PyCFunction)InternMap_lookup, METH_O,
     "lookup(id) -> row or -1 (no insertion)"},
    {"lookup_pair", (PyCFunction)InternMap_lookup_pair, METH_VARARGS,
     "lookup_pair(a, b) -> row or -1 (no insertion)"},
    {"lookup_pairs", (PyCFunction)InternMap_lookup_pairs, METH_VARARGS,
     "lookup_pairs(seq_a, seq_b) -> bytearray of int32 rows (-1 when absent)"},
    {"ids", (PyCFunction)InternMap_ids, METH_NOARGS,
     "ids() -> all interned ids in row order"},
    {"id_of", (PyCFunction)InternMap_id_of, METH_O,
     "id_of(row) -> the id interned at row"},
    {"sorted_rows", (PyCFunction)InternMap_sorted_rows, METH_O,
     "sorted_rows(int32 buffer) -> bytearray of the rows in key order"},
    {"flush_sqlite", (PyCFunction)InternMap_flush_sqlite, METH_VARARGS,
     "flush_sqlite(path, rows, rel, conf, iso) -> written row count"},
    {"snapshot_rows", (PyCFunction)InternMap_snapshot_rows, METH_VARARGS,
     "snapshot_rows(rows, rel, conf, iso) -> self-contained flush blob"},
    {"pair_blob", (PyCFunction)InternMap_pair_blob, METH_VARARGS,
     "pair_blob(lo, hi) -> journal wire-format bytes for rows [lo, hi)"},
    {NULL, NULL, 0, NULL},
};

/* delta_match_rows(rank_map, pr_new, po_new, pr_old, po_old, prev_of,
 *                  rows_old, rows_out) -> matched pair count
 *
 * The per-market match pass of the epoch-persistent pair table
 * (state/tensor_store.py): market *m* of the NEW batch matches old
 * market prev_of[m] (identity when prev_of is None) iff their pair
 * counts are equal and every pair's source rank maps elementwise
 * (rank_map translates new ranks to the old batch's; None means the
 * source tables are identical and the comparison is a raw memcmp).
 * Matched markets copy their resolved rows from rows_old; every other
 * position gets -1 — the miss set the interner then walks. One
 * sequential O(P) pass at memcmp/memcpy speed, GIL released (pure
 * buffers). Offsets must be non-decreasing and in range (checked).
 *
 * Buffers: rank_map i32[U] or None, pr_new i32[P_new], po_new i64[M+1],
 * pr_old i32[P_old], po_old i64[M_old+1], prev_of i64[M] or None,
 * rows_old i32[>= P_old], rows_out i32[P_new] (writable, fully
 * overwritten).
 */
static PyObject *
internmap_delta_match_rows(PyObject *Py_UNUSED(module), PyObject *args)
{
    PyObject *rank_obj, *prev_obj;
    PyObject *prn_obj, *pon_obj, *pro_obj, *poo_obj, *rowso_obj, *out_obj;
    if (!PyArg_ParseTuple(args, "OOOOOOOO", &rank_obj, &prn_obj, &pon_obj,
                          &pro_obj, &poo_obj, &prev_obj, &rowso_obj,
                          &out_obj))
        return NULL;

    Py_buffer rank_v, prn_v, pon_v, pro_v, poo_v, prev_v, rowso_v, out_v;
    rank_v.obj = prn_v.obj = pon_v.obj = pro_v.obj = poo_v.obj =
        prev_v.obj = rowso_v.obj = out_v.obj = NULL;
#define FF_GETBUF(obj, view, flags)                                       \
    do {                                                                  \
        if (PyObject_GetBuffer(obj, &view, flags) < 0) goto fail;         \
    } while (0)
    if (rank_obj != Py_None) FF_GETBUF(rank_obj, rank_v, PyBUF_CONTIG_RO);
    FF_GETBUF(prn_obj, prn_v, PyBUF_CONTIG_RO);
    FF_GETBUF(pon_obj, pon_v, PyBUF_CONTIG_RO);
    FF_GETBUF(pro_obj, pro_v, PyBUF_CONTIG_RO);
    FF_GETBUF(poo_obj, poo_v, PyBUF_CONTIG_RO);
    if (prev_obj != Py_None) FF_GETBUF(prev_obj, prev_v, PyBUF_CONTIG_RO);
    FF_GETBUF(rowso_obj, rowso_v, PyBUF_CONTIG_RO);
    FF_GETBUF(out_obj, out_v, PyBUF_CONTIG);
#undef FF_GETBUF

    if (pon_v.len < 8 || pon_v.len % 8 != 0 || poo_v.len < 8 ||
        poo_v.len % 8 != 0 || prn_v.len % 4 != 0 || pro_v.len % 4 != 0 ||
        rowso_v.len % 4 != 0 || out_v.len != prn_v.len ||
        (rank_v.obj && rank_v.len % 4 != 0) ||
        (prev_v.obj && prev_v.len % 8 != 0)) {
        PyErr_SetString(PyExc_ValueError,
                        "delta_match_rows: malformed buffer shapes");
        goto fail;
    }
    const int32_t *rank_map = rank_v.obj ? (const int32_t *)rank_v.buf
                                         : NULL;
    Py_ssize_t n_rank = rank_v.obj ? rank_v.len / 4 : 0;
    const int32_t *pr_new = (const int32_t *)prn_v.buf;
    const int64_t *po_new = (const int64_t *)pon_v.buf;
    const int32_t *pr_old = (const int32_t *)pro_v.buf;
    const int64_t *po_old = (const int64_t *)poo_v.buf;
    const int64_t *prev_of = prev_v.obj ? (const int64_t *)prev_v.buf
                                        : NULL;
    const int32_t *rows_old = (const int32_t *)rowso_v.buf;
    int32_t *rows_out = (int32_t *)out_v.buf;
    Py_ssize_t m_new = pon_v.len / 8 - 1;
    Py_ssize_t m_old = poo_v.len / 8 - 1;
    Py_ssize_t p_new = prn_v.len / 4;
    Py_ssize_t p_old = pro_v.len / 4;
    Py_ssize_t n_rows_old = rowso_v.len / 4;
    if ((prev_v.obj && prev_v.len / 8 != m_new) ||
        (!prev_v.obj && m_new > m_old) || n_rows_old < p_old) {
        PyErr_SetString(PyExc_ValueError,
                        "delta_match_rows: table sizes do not line up");
        goto fail;
    }

    Py_ssize_t matched_pairs = 0;
    Py_ssize_t bad_market = -1;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t m = 0; m < m_new; m++) {
        int64_t lo = po_new[m], hi = po_new[m + 1];
        if (lo < 0 || hi < lo || hi > p_new) {
            bad_market = m;
            break;
        }
        int64_t c = hi - lo;
        int64_t pm = prev_of ? prev_of[m] : (int64_t)m;
        int matched = 0;
        int64_t plo = 0;
        if (pm >= 0 && pm < m_old) {
            plo = po_old[pm];
            int64_t phi = po_old[pm + 1];
            if (plo >= 0 && phi >= plo && phi <= p_old && phi - plo == c) {
                if (!rank_map) {
                    matched = memcmp(pr_new + lo, pr_old + plo,
                                     (size_t)c * 4) == 0;
                } else {
                    matched = 1;
                    for (int64_t k = 0; k < c; k++) {
                        int32_t r = pr_new[lo + k];
                        if (r < 0 || r >= n_rank ||
                            rank_map[r] != pr_old[plo + k]) {
                            matched = 0;
                            break;
                        }
                    }
                }
            }
        }
        if (matched) {
            memcpy(rows_out + lo, rows_old + plo, (size_t)c * 4);
            matched_pairs += c;
        } else {
            for (int64_t k = lo; k < hi; k++) rows_out[k] = -1;
        }
    }
    Py_END_ALLOW_THREADS
#define FF_RELEASE_ALL()                                                  \
    do {                                                                  \
        if (rank_v.obj) PyBuffer_Release(&rank_v);                        \
        PyBuffer_Release(&prn_v);                                         \
        PyBuffer_Release(&pon_v);                                         \
        PyBuffer_Release(&pro_v);                                         \
        PyBuffer_Release(&poo_v);                                         \
        if (prev_v.obj) PyBuffer_Release(&prev_v);                        \
        PyBuffer_Release(&rowso_v);                                       \
        PyBuffer_Release(&out_v);                                         \
    } while (0)
    if (bad_market >= 0) {
        PyErr_Format(PyExc_ValueError,
                     "delta_match_rows: offsets for market %zd out of "
                     "range", bad_market);
        FF_RELEASE_ALL();
        return NULL;
    }
    FF_RELEASE_ALL();
#undef FF_RELEASE_ALL
    return PyLong_FromSsize_t(matched_pairs);

fail:
    if (rank_v.obj) PyBuffer_Release(&rank_v);
    if (prn_v.obj) PyBuffer_Release(&prn_v);
    if (pon_v.obj) PyBuffer_Release(&pon_v);
    if (pro_v.obj) PyBuffer_Release(&pro_v);
    if (poo_v.obj) PyBuffer_Release(&poo_v);
    if (prev_v.obj) PyBuffer_Release(&prev_v);
    if (rowso_v.obj) PyBuffer_Release(&rowso_v);
    if (out_v.obj) PyBuffer_Release(&out_v);
    return NULL;
}

/* sqlite_writer_available() -> bool: whether flush_sqlite can run here
 * (libsqlite3 dlopen()able). Lets callers choose a fallback up front
 * instead of catching the writer's genuine I/O errors. */
static PyObject *
internmap_sqlite_writer_available(PyObject *Py_UNUSED(module),
                                  PyObject *Py_UNUSED(ignored))
{
    return PyBool_FromLong(sqlite_runtime_load() > 0);
}

static PySequenceMethods InternMap_as_sequence = {
    .sq_length = (lenfunc)InternMap_len,
};

static PyTypeObject InternMapType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "internmap.InternMap",
    .tp_basicsize = sizeof(InternMap),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "Open-addressing string/pair interning map with int32 rows",
    .tp_new = InternMap_new,
    .tp_dealloc = (destructor)InternMap_dealloc,
    .tp_methods = InternMap_methods,
    .tp_as_sequence = &InternMap_as_sequence,
};

static PyMethodDef internmap_functions[] = {
    {"sqlite_writer_available", internmap_sqlite_writer_available,
     METH_NOARGS, "whether flush_sqlite's libsqlite3 runtime is loadable"},
    {"flush_snapshot", internmap_flush_snapshot, METH_VARARGS,
     "flush_snapshot(path, blob) -> row count (GIL released during write)"},
    {"pack_strings", internmap_pack_strings, METH_O,
     "pack_strings(list[str]) -> u32-length-prefixed UTF-8 blob"},
    {"delta_match_rows", internmap_delta_match_rows, METH_VARARGS,
     "delta_match_rows(rank_map, pr_new, po_new, pr_old, po_old, "
     "prev_of, rows_old, rows_out) -> matched pair count"},
    {NULL, NULL, 0, NULL},
};

static PyModuleDef internmap_module = {
    PyModuleDef_HEAD_INIT,
    .m_name = "internmap",
    .m_doc = "Native id interning for the TPU host boundary",
    .m_size = -1,
    .m_methods = internmap_functions,
};

PyMODINIT_FUNC
PyInit_internmap(void)
{
    /* pair_blob/pack_strings write u32 lengths in host order and the
     * journal format is little-endian: refuse to load on a big-endian
     * host rather than write unreadable journals. */
    const uint32_t one = 1;
    if (*(const unsigned char *)&one != 1) {
        PyErr_SetString(PyExc_ImportError,
                        "internmap requires a little-endian host");
        return NULL;
    }
    if (PyType_Ready(&InternMapType) < 0) return NULL;
    PyObject *module = PyModule_Create(&internmap_module);
    if (!module) return NULL;
    Py_INCREF(&InternMapType);
    if (PyModule_AddObject(module, "InternMap", (PyObject *)&InternMapType) < 0) {
        Py_DECREF(&InternMapType);
        Py_DECREF(module);
        return NULL;
    }
    return module;
}
