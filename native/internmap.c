/* internmap — native id-interning hash for bayesian_consensus_engine_tpu.
 *
 * The TPU state tensors are keyed by dense int32 rows; ids — source ids and
 * (source_id, market_id) pairs — are interned to rows at the host boundary
 * (utils/interning.py, state/tensor_store.py). At ingest scale (millions of
 * pairs per settlement batch) the pure-Python dict loop pays per-item
 * bytecode dispatch, tuple construction, and PyLong boxing; this module
 * provides batch interning in one C pass over an open-addressing FNV-1a
 * table, returning a ready-to-upload int32 buffer (wrap with
 * numpy.frombuffer, no copies).
 *
 * Contract: row assignment is first-seen order, identical to the Python
 * IdInterner (equivalence enforced by tests/test_internmap.py). Pair keys
 * are the two UTF-8 strings joined by a NUL byte — NUL cannot occur inside
 * either half (validated in the wrapper; the reference caps ids at 256
 * chars and its validator rejects empty ids, reference: config.py:37-38).
 *
 * API (all methods on InternMap):
 *   intern(str) -> int                      single string key
 *   intern_pair(str, str) -> int           single pair key
 *   intern_batch(seq[str]) -> bytearray            int32 rows, len*4 bytes
 *   intern_pairs(seq[str], seq[str]) -> bytearray  elementwise pair keys
 *   lookup(str) -> int        (-1 when absent; no insertion)
 *   lookup_pair(str, str) -> int
 *   __len__() -> unique keys; ids() -> list (row order; str or (str, str))
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

typedef struct {
    uint64_t hash;     /* 0 means empty (FNV-1a output is remapped off 0) */
    int32_t row;
    uint32_t key_len;
    char *key;         /* owned copy of the key bytes */
} slot_t;

typedef struct {
    PyObject_HEAD
    slot_t *slots;
    size_t capacity;   /* power of two */
    size_t used;
    PyObject *ids;     /* list of interned id objects, row order */
} InternMap;

static uint64_t
fnv1a(const char *data, size_t len)
{
    uint64_t h = 1469598103934665603ULL;
    for (size_t i = 0; i < len; i++) {
        h ^= (unsigned char)data[i];
        h *= 1099511628211ULL;
    }
    return h ? h : 1ULL;  /* reserve 0 for "empty slot" */
}

static int
map_resize(InternMap *self, size_t new_capacity)
{
    slot_t *fresh = PyMem_Calloc(new_capacity, sizeof(slot_t));
    if (!fresh) {
        PyErr_NoMemory();
        return -1;
    }
    size_t mask = new_capacity - 1;
    for (size_t i = 0; i < self->capacity; i++) {
        slot_t *old = &self->slots[i];
        if (!old->hash) continue;
        size_t j = old->hash & mask;
        while (fresh[j].hash) j = (j + 1) & mask;
        fresh[j] = *old;
    }
    PyMem_Free(self->slots);
    self->slots = fresh;
    self->capacity = new_capacity;
    return 0;
}

/* Find or insert the key; returns the row, or -1 on error. *id_factory* is
 * called (with *factory_arg*) to build the Python object appended to ids
 * only when the key is new. */
typedef PyObject *(*id_factory_t)(void *arg);

static int32_t
map_intern(InternMap *self, const char *key, size_t len,
           id_factory_t id_factory, void *factory_arg)
{
    if (self->used * 3 >= self->capacity * 2) {
        if (map_resize(self, self->capacity * 2) < 0) return -1;
    }
    uint64_t h = fnv1a(key, len);
    size_t mask = self->capacity - 1;
    size_t i = h & mask;
    while (self->slots[i].hash) {
        slot_t *s = &self->slots[i];
        if (s->hash == h && s->key_len == len && memcmp(s->key, key, len) == 0)
            return s->row;
        i = (i + 1) & mask;
    }
    if (PyList_GET_SIZE(self->ids) >= INT32_MAX) {
        PyErr_SetString(PyExc_OverflowError, "more than 2^31-1 interned ids");
        return -1;
    }
    char *copy = PyMem_Malloc(len ? len : 1);
    if (!copy) {
        PyErr_NoMemory();
        return -1;
    }
    memcpy(copy, key, len);
    PyObject *id_obj = id_factory(factory_arg);
    if (!id_obj) {
        PyMem_Free(copy);
        return -1;
    }
    if (PyList_Append(self->ids, id_obj) < 0) {
        Py_DECREF(id_obj);
        PyMem_Free(copy);
        return -1;
    }
    Py_DECREF(id_obj);
    int32_t row = (int32_t)(PyList_GET_SIZE(self->ids) - 1);
    self->slots[i].hash = h;
    self->slots[i].row = row;
    self->slots[i].key_len = (uint32_t)len;
    self->slots[i].key = copy;
    self->used++;
    return row;
}

static int32_t
map_lookup(InternMap *self, const char *key, size_t len)
{
    uint64_t h = fnv1a(key, len);
    size_t mask = self->capacity - 1;
    size_t i = h & mask;
    while (self->slots[i].hash) {
        slot_t *s = &self->slots[i];
        if (s->hash == h && s->key_len == len && memcmp(s->key, key, len) == 0)
            return s->row;
        i = (i + 1) & mask;
    }
    return -1;
}

/* ---- key building -------------------------------------------------------- */

static PyObject *
factory_incref(void *arg)
{
    PyObject *obj = (PyObject *)arg;
    Py_INCREF(obj);
    return obj;
}

static PyObject *
factory_pair(void *arg)
{
    PyObject **pair = (PyObject **)arg;
    return PyTuple_Pack(2, pair[0], pair[1]);
}

/* UTF-8 view of a str; sets error and returns NULL on non-str. */
static const char *
utf8_of(PyObject *obj, Py_ssize_t *len)
{
    if (!PyUnicode_Check(obj)) {
        PyErr_Format(PyExc_TypeError, "expected str, got %.100s",
                     Py_TYPE(obj)->tp_name);
        return NULL;
    }
    return PyUnicode_AsUTF8AndSize(obj, len);
}

/* Joined "a\0b" key in *scratch (grown as needed). Returns length or -1. */
static Py_ssize_t
pair_key(PyObject *a, PyObject *b, char **scratch, Py_ssize_t *scratch_cap)
{
    Py_ssize_t alen, blen;
    const char *abuf = utf8_of(a, &alen);
    if (!abuf) return -1;
    const char *bbuf = utf8_of(b, &blen);
    if (!bbuf) return -1;
    if (memchr(abuf, '\0', (size_t)alen) || memchr(bbuf, '\0', (size_t)blen)) {
        PyErr_SetString(PyExc_ValueError, "ids must not contain NUL");
        return -1;
    }
    Py_ssize_t need = alen + 1 + blen;
    if (need > *scratch_cap) {
        char *grown = PyMem_Realloc(*scratch, (size_t)(need * 2));
        if (!grown) {
            PyErr_NoMemory();
            return -1;
        }
        *scratch = grown;
        *scratch_cap = need * 2;
    }
    memcpy(*scratch, abuf, (size_t)alen);
    (*scratch)[alen] = '\0';
    memcpy(*scratch + alen + 1, bbuf, (size_t)blen);
    return need;
}

/* ---- methods ------------------------------------------------------------- */

static PyObject *
InternMap_intern(InternMap *self, PyObject *arg)
{
    Py_ssize_t len;
    const char *buf = utf8_of(arg, &len);
    if (!buf) return NULL;
    int32_t row = map_intern(self, buf, (size_t)len, factory_incref, arg);
    if (row < 0) return NULL;
    return PyLong_FromLong(row);
}

static PyObject *
InternMap_intern_pair(InternMap *self, PyObject *args)
{
    PyObject *a, *b;
    if (!PyArg_ParseTuple(args, "OO", &a, &b)) return NULL;
    char *scratch = NULL;
    Py_ssize_t cap = 0;
    Py_ssize_t len = pair_key(a, b, &scratch, &cap);
    if (len < 0) {
        PyMem_Free(scratch);
        return NULL;
    }
    PyObject *pair[2] = {a, b};
    int32_t row = map_intern(self, scratch, (size_t)len, factory_pair, pair);
    PyMem_Free(scratch);
    if (row < 0) return NULL;
    return PyLong_FromLong(row);
}

static PyObject *
InternMap_intern_batch(InternMap *self, PyObject *arg)
{
    PyObject *fast = PySequence_Fast(arg, "expected a sequence of str");
    if (!fast) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    PyObject *out = PyByteArray_FromStringAndSize(NULL, n * 4);
    if (!out) {
        Py_DECREF(fast);
        return NULL;
    }
    int32_t *rows = (int32_t *)PyByteArray_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        Py_ssize_t len;
        const char *buf = utf8_of(item, &len);
        if (!buf) goto fail;
        int32_t row = map_intern(self, buf, (size_t)len, factory_incref, item);
        if (row < 0) goto fail;
        rows[i] = row;
    }
    Py_DECREF(fast);
    return out;
fail:
    Py_DECREF(fast);
    Py_DECREF(out);
    return NULL;
}

static PyObject *
InternMap_intern_pairs(InternMap *self, PyObject *args)
{
    PyObject *seq_a, *seq_b;
    if (!PyArg_ParseTuple(args, "OO", &seq_a, &seq_b)) return NULL;
    PyObject *fast_a = PySequence_Fast(seq_a, "expected a sequence of str");
    if (!fast_a) return NULL;
    PyObject *fast_b = PySequence_Fast(seq_b, "expected a sequence of str");
    if (!fast_b) {
        Py_DECREF(fast_a);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast_a);
    if (PySequence_Fast_GET_SIZE(fast_b) != n) {
        PyErr_SetString(PyExc_ValueError, "sequences must have equal length");
        Py_DECREF(fast_a);
        Py_DECREF(fast_b);
        return NULL;
    }
    PyObject *out = PyByteArray_FromStringAndSize(NULL, n * 4);
    char *scratch = NULL;
    Py_ssize_t cap = 0;
    if (!out) goto fail;
    int32_t *rows = (int32_t *)PyByteArray_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *a = PySequence_Fast_GET_ITEM(fast_a, i);
        PyObject *b = PySequence_Fast_GET_ITEM(fast_b, i);
        Py_ssize_t len = pair_key(a, b, &scratch, &cap);
        if (len < 0) goto fail;
        PyObject *pair[2] = {a, b};
        int32_t row = map_intern(self, scratch, (size_t)len, factory_pair, pair);
        if (row < 0) goto fail;
        rows[i] = row;
    }
    PyMem_Free(scratch);
    Py_DECREF(fast_a);
    Py_DECREF(fast_b);
    return out;
fail:
    PyMem_Free(scratch);
    Py_XDECREF(out);
    Py_DECREF(fast_a);
    Py_DECREF(fast_b);
    return NULL;
}

static PyObject *
InternMap_lookup(InternMap *self, PyObject *arg)
{
    Py_ssize_t len;
    const char *buf = utf8_of(arg, &len);
    if (!buf) return NULL;
    return PyLong_FromLong(map_lookup(self, buf, (size_t)len));
}

static PyObject *
InternMap_lookup_pair(InternMap *self, PyObject *args)
{
    PyObject *a, *b;
    if (!PyArg_ParseTuple(args, "OO", &a, &b)) return NULL;
    char *scratch = NULL;
    Py_ssize_t cap = 0;
    Py_ssize_t len = pair_key(a, b, &scratch, &cap);
    if (len < 0) {
        PyMem_Free(scratch);
        return NULL;
    }
    long row = map_lookup(self, scratch, (size_t)len);
    PyMem_Free(scratch);
    return PyLong_FromLong(row);
}

static PyObject *
InternMap_ids(InternMap *self, PyObject *Py_UNUSED(ignored))
{
    return PyList_GetSlice(self->ids, 0, PyList_GET_SIZE(self->ids));
}

static PyObject *
InternMap_id_of(InternMap *self, PyObject *arg)
{
    Py_ssize_t row = PyLong_AsSsize_t(arg);
    if (row == -1 && PyErr_Occurred()) return NULL;
    if (row < 0 || row >= PyList_GET_SIZE(self->ids)) {
        PyErr_SetString(PyExc_IndexError, "row out of range");
        return NULL;
    }
    PyObject *obj = PyList_GET_ITEM(self->ids, row);
    Py_INCREF(obj);
    return obj;
}

static Py_ssize_t
InternMap_len(InternMap *self)
{
    return PyList_GET_SIZE(self->ids);
}

/* ---- type ---------------------------------------------------------------- */

static PyObject *
InternMap_new(PyTypeObject *type, PyObject *Py_UNUSED(args),
              PyObject *Py_UNUSED(kwargs))
{
    InternMap *self = (InternMap *)type->tp_alloc(type, 0);
    if (!self) return NULL;
    self->capacity = 64;
    self->used = 0;
    self->slots = PyMem_Calloc(self->capacity, sizeof(slot_t));
    self->ids = PyList_New(0);
    if (!self->slots || !self->ids) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return NULL;
    }
    return (PyObject *)self;
}

static void
InternMap_dealloc(InternMap *self)
{
    if (self->slots) {
        for (size_t i = 0; i < self->capacity; i++)
            if (self->slots[i].hash) PyMem_Free(self->slots[i].key);
        PyMem_Free(self->slots);
    }
    Py_XDECREF(self->ids);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyMethodDef InternMap_methods[] = {
    {"intern", (PyCFunction)InternMap_intern, METH_O,
     "intern(id) -> row (assigning the next row if new)"},
    {"intern_pair", (PyCFunction)InternMap_intern_pair, METH_VARARGS,
     "intern_pair(a, b) -> row for the (a, b) pair key"},
    {"intern_batch", (PyCFunction)InternMap_intern_batch, METH_O,
     "intern_batch(seq) -> bytearray of int32 rows"},
    {"intern_pairs", (PyCFunction)InternMap_intern_pairs, METH_VARARGS,
     "intern_pairs(seq_a, seq_b) -> bytearray of int32 rows"},
    {"lookup", (PyCFunction)InternMap_lookup, METH_O,
     "lookup(id) -> row or -1 (no insertion)"},
    {"lookup_pair", (PyCFunction)InternMap_lookup_pair, METH_VARARGS,
     "lookup_pair(a, b) -> row or -1 (no insertion)"},
    {"ids", (PyCFunction)InternMap_ids, METH_NOARGS,
     "ids() -> all interned ids in row order"},
    {"id_of", (PyCFunction)InternMap_id_of, METH_O,
     "id_of(row) -> the id interned at row"},
    {NULL, NULL, 0, NULL},
};

static PySequenceMethods InternMap_as_sequence = {
    .sq_length = (lenfunc)InternMap_len,
};

static PyTypeObject InternMapType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "internmap.InternMap",
    .tp_basicsize = sizeof(InternMap),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "Open-addressing string/pair interning map with int32 rows",
    .tp_new = InternMap_new,
    .tp_dealloc = (destructor)InternMap_dealloc,
    .tp_methods = InternMap_methods,
    .tp_as_sequence = &InternMap_as_sequence,
};

static PyModuleDef internmap_module = {
    PyModuleDef_HEAD_INIT,
    .m_name = "internmap",
    .m_doc = "Native id interning for the TPU host boundary",
    .m_size = -1,
};

PyMODINIT_FUNC
PyInit_internmap(void)
{
    if (PyType_Ready(&InternMapType) < 0) return NULL;
    PyObject *module = PyModule_Create(&internmap_module);
    if (!module) return NULL;
    Py_INCREF(&InternMapType);
    if (PyModule_AddObject(module, "InternMap", (PyObject *)&InternMapType) < 0) {
        Py_DECREF(&InternMapType);
        Py_DECREF(module);
        return NULL;
    }
    return module;
}
