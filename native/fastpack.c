/* fastpack — native signal-ingest packer for bayesian_consensus_engine_tpu.
 *
 * The TPU compute path consumes dense arrays; turning ragged JSON-shaped
 * signal payloads into those arrays is the framework's host-side hot loop
 * (core/batch.py:pack_markets). This module implements that inner loop in C
 * against the CPython API: one pass per market grouping signals by source,
 * sorted-source slot assignment, and flat (signal → pair-slot) emission.
 *
 * Contract: byte-for-byte the same outputs as the pure-Python packer (the
 * fallback when this extension is not built); equivalence is enforced by
 * tests/test_fastpack.py. The reliability lookup stays in Python — it is a
 * user-supplied callable per (source, market) pair, O(pairs) not O(signals).
 *
 * Returns, for a list of (market_id, signals) tuples:
 *   pair_market        list[int]   market row per (market, source) pair
 *   pair_source_ids    list[str]   source id per pair (sorted within market)
 *   flat_probs         list[float] raw probabilities in input order
 *   flat_pair          list[int]   pair slot per raw signal
 *   signals_per_market list[int]
 *   pair_offsets       list[int]   pair range per market (len M+1)
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static int append_long(PyObject *list, long value) {
    PyObject *obj = PyLong_FromLong(value);
    if (!obj) return -1;
    int rc = PyList_Append(list, obj);
    Py_DECREF(obj);
    return rc;
}

static PyObject *
fastpack_pack(PyObject *Py_UNUSED(self), PyObject *args)
{
    PyObject *markets;
    if (!PyArg_ParseTuple(args, "O", &markets))
        return NULL;
    PyObject *markets_fast = PySequence_Fast(markets, "markets must be a sequence");
    if (!markets_fast) return NULL;

    PyObject *pair_market = PyList_New(0);
    PyObject *pair_source_ids = PyList_New(0);
    PyObject *flat_probs = PyList_New(0);
    PyObject *flat_pair = PyList_New(0);
    PyObject *signals_per_market = PyList_New(0);
    PyObject *pair_offsets = PyList_New(0);
    PyObject *by_source = NULL, *slot_of = NULL, *ordered = NULL;
    PyObject *key_source = NULL, *key_prob = NULL;

    if (!pair_market || !pair_source_ids || !flat_probs || !flat_pair ||
        !signals_per_market || !pair_offsets)
        goto fail;

    key_source = PyUnicode_InternFromString("sourceId");
    key_prob = PyUnicode_InternFromString("probability");
    if (!key_source || !key_prob) goto fail;

    if (append_long(pair_offsets, 0) < 0) goto fail;

    Py_ssize_t num_markets = PySequence_Fast_GET_SIZE(markets_fast);
    for (Py_ssize_t m = 0; m < num_markets; m++) {
        PyObject *entry = PySequence_Fast_GET_ITEM(markets_fast, m);  /* borrowed */
        if (!PyTuple_Check(entry) && !PyList_Check(entry)) {
            PyErr_SetString(PyExc_TypeError, "each market must be (id, signals)");
            goto fail;
        }
        PyObject *signals = PySequence_GetItem(entry, 1);  /* new ref */
        if (!signals) goto fail;
        PyObject *signals_fast = PySequence_Fast(signals, "signals must be a sequence");
        Py_DECREF(signals);
        if (!signals_fast) goto fail;

        Py_ssize_t num_signals = PySequence_Fast_GET_SIZE(signals_fast);
        if (append_long(signals_per_market, (long)num_signals) < 0) {
            Py_DECREF(signals_fast); goto fail;
        }

        /* Group: source id → first-seen order (value unused; dict preserves
         * insertion order, we only need the key set). */
        by_source = PyDict_New();
        if (!by_source) { Py_DECREF(signals_fast); goto fail; }
        for (Py_ssize_t s = 0; s < num_signals; s++) {
            PyObject *signal = PySequence_Fast_GET_ITEM(signals_fast, s);
            PyObject *sid = PyObject_GetItem(signal, key_source);  /* new */
            if (!sid) { Py_DECREF(signals_fast); goto fail; }
            if (PyDict_SetItem(by_source, sid, Py_None) < 0) {
                Py_DECREF(sid); Py_DECREF(signals_fast); goto fail;
            }
            Py_DECREF(sid);
        }

        /* Sorted unique source ids → slot assignment. */
        ordered = PyDict_Keys(by_source);
        if (!ordered || PyList_Sort(ordered) < 0) { Py_DECREF(signals_fast); goto fail; }

        Py_ssize_t base = PyList_GET_SIZE(pair_source_ids);
        slot_of = PyDict_New();
        if (!slot_of) { Py_DECREF(signals_fast); goto fail; }
        Py_ssize_t num_unique = PyList_GET_SIZE(ordered);
        for (Py_ssize_t u = 0; u < num_unique; u++) {
            PyObject *sid = PyList_GET_ITEM(ordered, u);  /* borrowed */
            PyObject *slot = PyLong_FromSsize_t(base + u);
            if (!slot || PyDict_SetItem(slot_of, sid, slot) < 0) {
                Py_XDECREF(slot); Py_DECREF(signals_fast); goto fail;
            }
            Py_DECREF(slot);
            if (append_long(pair_market, (long)m) < 0 ||
                PyList_Append(pair_source_ids, sid) < 0) {
                Py_DECREF(signals_fast); goto fail;
            }
        }

        /* Flat emission in original signal order (preserves the scalar
         * engine's duplicate-averaging float order). */
        for (Py_ssize_t s = 0; s < num_signals; s++) {
            PyObject *signal = PySequence_Fast_GET_ITEM(signals_fast, s);
            PyObject *sid = PyObject_GetItem(signal, key_source);
            if (!sid) { Py_DECREF(signals_fast); goto fail; }
            PyObject *slot = PyDict_GetItem(slot_of, sid);  /* borrowed */
            Py_DECREF(sid);
            if (!slot) {
                PyErr_SetString(PyExc_RuntimeError, "slot lookup failed");
                Py_DECREF(signals_fast); goto fail;
            }
            PyObject *prob = PyObject_GetItem(signal, key_prob);
            if (!prob) { Py_DECREF(signals_fast); goto fail; }
            if (PyList_Append(flat_probs, prob) < 0 ||
                PyList_Append(flat_pair, slot) < 0) {
                Py_DECREF(prob); Py_DECREF(signals_fast); goto fail;
            }
            Py_DECREF(prob);
        }

        if (append_long(pair_offsets, (long)PyList_GET_SIZE(pair_source_ids)) < 0) {
            Py_DECREF(signals_fast); goto fail;
        }
        Py_DECREF(signals_fast);
        Py_CLEAR(by_source);
        Py_CLEAR(ordered);
        Py_CLEAR(slot_of);
    }

    Py_DECREF(markets_fast);
    Py_XDECREF(key_source);
    Py_XDECREF(key_prob);
    return Py_BuildValue(
        "(NNNNNN)",
        pair_market, pair_source_ids, flat_probs, flat_pair,
        signals_per_market, pair_offsets);

fail:
    Py_XDECREF(markets_fast);
    Py_XDECREF(pair_market);
    Py_XDECREF(pair_source_ids);
    Py_XDECREF(flat_probs);
    Py_XDECREF(flat_pair);
    Py_XDECREF(signals_per_market);
    Py_XDECREF(pair_offsets);
    Py_XDECREF(by_source);
    Py_XDECREF(ordered);
    Py_XDECREF(slot_of);
    Py_XDECREF(key_source);
    Py_XDECREF(key_prob);
    return NULL;
}

static PyMethodDef fastpack_methods[] = {
    {"pack", fastpack_pack, METH_VARARGS,
     "pack(markets) -> (pair_market, pair_source_ids, flat_probs, flat_pair, "
     "signals_per_market, pair_offsets)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fastpack_module = {
    PyModuleDef_HEAD_INIT,
    "fastpack",
    "Native signal-ingest packer (C twin of core.batch.pack_markets grouping).",
    -1,
    fastpack_methods,
};

PyMODINIT_FUNC
PyInit_fastpack(void)
{
    return PyModule_Create(&fastpack_module);
}
