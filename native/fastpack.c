/* fastpack — native signal-ingest packer for bayesian_consensus_engine_tpu.
 *
 * The TPU compute path consumes dense arrays; turning ragged JSON-shaped
 * signal payloads into those arrays is the framework's host-side hot loop
 * (core/batch.py:pack_markets). This module implements that inner loop in C
 * against the CPython API: one pass per market grouping signals by source,
 * sorted-source slot assignment, and flat (signal → pair-slot) emission.
 *
 * Contract: byte-for-byte the same outputs as the pure-Python packer (the
 * fallback when this extension is not built); equivalence is enforced by
 * tests/test_fastpack.py. The reliability lookup stays in Python — it is a
 * user-supplied callable per (source, market) pair, O(pairs) not O(signals).
 *
 * pack(markets) returns, for a list of (market_id, signals) tuples:
 *   pair_market        list[int]   market row per (market, source) pair
 *   pair_source_ids    list[str]   source id per pair (sorted within market)
 *   flat_probs         list[float] raw probabilities in input order
 *   flat_pair          list[int]   pair slot per raw signal
 *   signals_per_market list[int]
 *   pair_offsets       list[int]   pair range per market (len M+1)
 *
 * COLUMNAR FAST PATH (the ingest-floor work). The object-path pack()
 * above is PyObject-bound by construction; the functions below operate
 * on flat columns instead, emitting directly into caller-preallocated
 * buffers (buffer protocol — numpy arrays pass zero-copy), so the only
 * per-signal cost is integer/double arithmetic:
 *
 *   group_columns(codes, rank_of_code, offsets, probs,
 *                 out_signal_pair, out_pair_market, out_pair_rank,
 *                 out_pair_offsets, out_sums, out_counts) -> num_pairs
 *       The grouping pass of core.batch.group_columns: per market,
 *       dedupe signals by source rank, sort the unique ranks (= the
 *       scalar engine's source-id code-point order), assign pair slots,
 *       and accumulate per-pair probability sums IN SIGNAL ORDER (the
 *       float-summation contract np.add.at keeps on the numpy twin).
 *   pair_accumulate(pair_idx, probs, out_sums)
 *       Ordered per-pair probability sum (the probability-only refresh
 *       twin's inner loop; out_sums must arrive zeroed).
 *   columns_from_payloads(payloads) -> (keys, sids, probs_buf, offs_buf)
 *       Dict payloads → flat columns in one C pass (the
 *       core.batch.columns_from_payloads layout; buffers wrap as numpy
 *       float64/int64 with no copy).
 *   join_codes(codes, table) -> bytes
 *       Concatenated UTF-8 of table[code] per signal — the
 *       topology_fingerprint joined-id bytes for the zero-copy coded
 *       intake, without materialising a Python string per signal.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static int append_long(PyObject *list, long value) {
    PyObject *obj = PyLong_FromLong(value);
    if (!obj) return -1;
    int rc = PyList_Append(list, obj);
    Py_DECREF(obj);
    return rc;
}

static PyObject *
fastpack_pack(PyObject *Py_UNUSED(self), PyObject *args)
{
    PyObject *markets;
    if (!PyArg_ParseTuple(args, "O", &markets))
        return NULL;
    PyObject *markets_fast = PySequence_Fast(markets, "markets must be a sequence");
    if (!markets_fast) return NULL;

    PyObject *pair_market = PyList_New(0);
    PyObject *pair_source_ids = PyList_New(0);
    PyObject *flat_probs = PyList_New(0);
    PyObject *flat_pair = PyList_New(0);
    PyObject *signals_per_market = PyList_New(0);
    PyObject *pair_offsets = PyList_New(0);
    PyObject *by_source = NULL, *slot_of = NULL, *ordered = NULL;
    PyObject *key_source = NULL, *key_prob = NULL;

    if (!pair_market || !pair_source_ids || !flat_probs || !flat_pair ||
        !signals_per_market || !pair_offsets)
        goto fail;

    key_source = PyUnicode_InternFromString("sourceId");
    key_prob = PyUnicode_InternFromString("probability");
    if (!key_source || !key_prob) goto fail;

    if (append_long(pair_offsets, 0) < 0) goto fail;

    Py_ssize_t num_markets = PySequence_Fast_GET_SIZE(markets_fast);
    for (Py_ssize_t m = 0; m < num_markets; m++) {
        PyObject *entry = PySequence_Fast_GET_ITEM(markets_fast, m);  /* borrowed */
        if (!PyTuple_Check(entry) && !PyList_Check(entry)) {
            PyErr_SetString(PyExc_TypeError, "each market must be (id, signals)");
            goto fail;
        }
        PyObject *signals = PySequence_GetItem(entry, 1);  /* new ref */
        if (!signals) goto fail;
        PyObject *signals_fast = PySequence_Fast(signals, "signals must be a sequence");
        Py_DECREF(signals);
        if (!signals_fast) goto fail;

        Py_ssize_t num_signals = PySequence_Fast_GET_SIZE(signals_fast);
        if (append_long(signals_per_market, (long)num_signals) < 0) {
            Py_DECREF(signals_fast); goto fail;
        }

        /* Group: source id → first-seen order (value unused; dict preserves
         * insertion order, we only need the key set). */
        by_source = PyDict_New();
        if (!by_source) { Py_DECREF(signals_fast); goto fail; }
        for (Py_ssize_t s = 0; s < num_signals; s++) {
            PyObject *signal = PySequence_Fast_GET_ITEM(signals_fast, s);
            PyObject *sid = PyObject_GetItem(signal, key_source);  /* new */
            if (!sid) { Py_DECREF(signals_fast); goto fail; }
            if (PyDict_SetItem(by_source, sid, Py_None) < 0) {
                Py_DECREF(sid); Py_DECREF(signals_fast); goto fail;
            }
            Py_DECREF(sid);
        }

        /* Sorted unique source ids → slot assignment. */
        ordered = PyDict_Keys(by_source);
        if (!ordered || PyList_Sort(ordered) < 0) { Py_DECREF(signals_fast); goto fail; }

        Py_ssize_t base = PyList_GET_SIZE(pair_source_ids);
        slot_of = PyDict_New();
        if (!slot_of) { Py_DECREF(signals_fast); goto fail; }
        Py_ssize_t num_unique = PyList_GET_SIZE(ordered);
        for (Py_ssize_t u = 0; u < num_unique; u++) {
            PyObject *sid = PyList_GET_ITEM(ordered, u);  /* borrowed */
            PyObject *slot = PyLong_FromSsize_t(base + u);
            if (!slot || PyDict_SetItem(slot_of, sid, slot) < 0) {
                Py_XDECREF(slot); Py_DECREF(signals_fast); goto fail;
            }
            Py_DECREF(slot);
            if (append_long(pair_market, (long)m) < 0 ||
                PyList_Append(pair_source_ids, sid) < 0) {
                Py_DECREF(signals_fast); goto fail;
            }
        }

        /* Flat emission in original signal order (preserves the scalar
         * engine's duplicate-averaging float order). */
        for (Py_ssize_t s = 0; s < num_signals; s++) {
            PyObject *signal = PySequence_Fast_GET_ITEM(signals_fast, s);
            PyObject *sid = PyObject_GetItem(signal, key_source);
            if (!sid) { Py_DECREF(signals_fast); goto fail; }
            PyObject *slot = PyDict_GetItem(slot_of, sid);  /* borrowed */
            Py_DECREF(sid);
            if (!slot) {
                PyErr_SetString(PyExc_RuntimeError, "slot lookup failed");
                Py_DECREF(signals_fast); goto fail;
            }
            PyObject *prob = PyObject_GetItem(signal, key_prob);
            if (!prob) { Py_DECREF(signals_fast); goto fail; }
            if (PyList_Append(flat_probs, prob) < 0 ||
                PyList_Append(flat_pair, slot) < 0) {
                Py_DECREF(prob); Py_DECREF(signals_fast); goto fail;
            }
            Py_DECREF(prob);
        }

        if (append_long(pair_offsets, (long)PyList_GET_SIZE(pair_source_ids)) < 0) {
            Py_DECREF(signals_fast); goto fail;
        }
        Py_DECREF(signals_fast);
        Py_CLEAR(by_source);
        Py_CLEAR(ordered);
        Py_CLEAR(slot_of);
    }

    Py_DECREF(markets_fast);
    Py_XDECREF(key_source);
    Py_XDECREF(key_prob);
    return Py_BuildValue(
        "(NNNNNN)",
        pair_market, pair_source_ids, flat_probs, flat_pair,
        signals_per_market, pair_offsets);

fail:
    Py_XDECREF(markets_fast);
    Py_XDECREF(pair_market);
    Py_XDECREF(pair_source_ids);
    Py_XDECREF(flat_probs);
    Py_XDECREF(flat_pair);
    Py_XDECREF(signals_per_market);
    Py_XDECREF(pair_offsets);
    Py_XDECREF(by_source);
    Py_XDECREF(ordered);
    Py_XDECREF(slot_of);
    Py_XDECREF(key_source);
    Py_XDECREF(key_prob);
    return NULL;
}

/* ---- columnar fast path -------------------------------------------------- */

static int
int32_cmp(const void *pa, const void *pb)
{
    int32_t a = *(const int32_t *)pa, b = *(const int32_t *)pb;
    return (a > b) - (a < b);
}

/* Contiguous buffer helper: fills *view, validating element width.
 * writable=1 requests a writable buffer. Returns element count or -1. */
static Py_ssize_t
get_elems(PyObject *obj, Py_buffer *view, int itemsize, int writable,
          const char *name)
{
    int flags = writable ? (PyBUF_CONTIG) : (PyBUF_CONTIG_RO);
    if (PyObject_GetBuffer(obj, view, flags) < 0) return -1;
    if (view->len % itemsize != 0) {
        PyErr_Format(PyExc_ValueError,
                     "%s: buffer length %zd is not a multiple of %d",
                     name, view->len, itemsize);
        PyBuffer_Release(view);
        view->obj = NULL;
        return -1;
    }
    return view->len / itemsize;
}

/* group_columns: the whole-column grouping pass. Inputs are per-signal
 * int32 source codes, the code → sorted-rank permutation (int32[U]),
 * CSR int64 offsets (M+1), and float64 probabilities. Outputs land in
 * preallocated buffers: signal→pair (int64[N]), pair market / rank
 * (int32, capacity >= P), pair offsets (int64[M+1]), per-pair sums
 * (float64) and counts (int64). Returns the pair count P.
 *
 * Equivalence notes (pinned by tests/test_fastpack.py):
 *  - pairs emit market-major with ranks ascending within each market —
 *    exactly np.unique's sorted (market * stride + rank) key order;
 *  - per-pair sums accumulate in original signal order — np.add.at's
 *    sequential-accumulate semantics, so duplicate averaging keeps the
 *    scalar engine's left-to-right float order bit-for-bit. */
static PyObject *
fastpack_group_columns(PyObject *Py_UNUSED(self), PyObject *args)
{
    PyObject *codes_o, *rank_o, *offs_o, *probs_o;
    PyObject *sp_o, *pm_o, *pr_o, *po_o, *sums_o, *cnt_o;
    if (!PyArg_ParseTuple(args, "OOOOOOOOOO", &codes_o, &rank_o, &offs_o,
                          &probs_o, &sp_o, &pm_o, &pr_o, &po_o, &sums_o,
                          &cnt_o))
        return NULL;

    Py_buffer codes = {0}, rank = {0}, offs = {0}, probs = {0};
    Py_buffer sp = {0}, pm = {0}, pr = {0}, po = {0}, sums = {0}, cnt = {0};
    int64_t *stamp = NULL, *slot = NULL;
    int32_t *market_ranks = NULL;
    PyObject *result = NULL;

    Py_ssize_t n = get_elems(codes_o, &codes, 4, 0, "codes");
    Py_ssize_t u = n < 0 ? -1 : get_elems(rank_o, &rank, 4, 0, "rank_of_code");
    Py_ssize_t mo = u < 0 ? -1 : get_elems(offs_o, &offs, 8, 0, "offsets");
    Py_ssize_t np_ = mo < 0 ? -1 : get_elems(probs_o, &probs, 8, 0, "probs");
    Py_ssize_t nsp = np_ < 0 ? -1 : get_elems(sp_o, &sp, 8, 1, "out_signal_pair");
    Py_ssize_t npm = nsp < 0 ? -1 : get_elems(pm_o, &pm, 4, 1, "out_pair_market");
    Py_ssize_t npr = npm < 0 ? -1 : get_elems(pr_o, &pr, 4, 1, "out_pair_rank");
    Py_ssize_t npo = npr < 0 ? -1 : get_elems(po_o, &po, 8, 1, "out_pair_offsets");
    Py_ssize_t nsums = npo < 0 ? -1 : get_elems(sums_o, &sums, 8, 1, "out_sums");
    Py_ssize_t ncnt = nsums < 0 ? -1 : get_elems(cnt_o, &cnt, 8, 1, "out_counts");
    if (ncnt < 0) goto done;

    Py_ssize_t m = mo - 1;
    if (m < 0 || np_ != n || nsp != n || npo != mo) {
        PyErr_SetString(PyExc_ValueError,
                        "group_columns: inconsistent buffer shapes");
        goto done;
    }
    Py_ssize_t cap = npm;
    if (npr < cap) cap = npr;
    if (nsums < cap) cap = nsums;
    if (ncnt < cap) cap = ncnt;

    const int32_t *codes_p = (const int32_t *)codes.buf;
    const int32_t *rank_p = (const int32_t *)rank.buf;
    const int64_t *offs_p = (const int64_t *)offs.buf;
    const double *probs_p = (const double *)probs.buf;
    int64_t *sp_p = (int64_t *)sp.buf;
    int32_t *pm_p = (int32_t *)pm.buf;
    int32_t *pr_p = (int32_t *)pr.buf;
    int64_t *po_p = (int64_t *)po.buf;
    double *sums_p = (double *)sums.buf;
    int64_t *cnt_p = (int64_t *)cnt.buf;

    int64_t max_width = 0;
    for (Py_ssize_t i = 0; i < m; i++) {
        int64_t w = offs_p[i + 1] - offs_p[i];
        if (offs_p[i] < 0 || w < 0 || offs_p[i + 1] > n) {
            PyErr_SetString(PyExc_ValueError,
                            "group_columns: offsets out of range");
            goto done;
        }
        if (w > max_width) max_width = w;
    }
    if (m > 0 && offs_p[0] != 0) {
        PyErr_SetString(PyExc_ValueError,
                        "group_columns: offsets must start at 0");
        goto done;
    }
    /* The terminal offset must cover every signal: a short CSR would
     * silently drop the tail AND leave out_signal_pair's tail
     * uninitialized (the numpy twin errors on the same input). */
    if ((m > 0 ? offs_p[m] : 0) != n) {
        PyErr_Format(PyExc_ValueError,
                     "group_columns: offsets cover %lld signals but "
                     "codes/probs carry %zd",
                     (long long)(m > 0 ? offs_p[m] : 0), n);
        goto done;
    }

    stamp = PyMem_Malloc((size_t)(u ? u : 1) * 8);
    slot = PyMem_Malloc((size_t)(u ? u : 1) * 8);
    market_ranks = PyMem_Malloc((size_t)(max_width ? max_width : 1) * 4);
    if (!stamp || !slot || !market_ranks) {
        PyErr_NoMemory();
        goto done;
    }
    memset(stamp, 0xFF, (size_t)(u ? u : 1) * 8); /* int64 -1 fill */

    int64_t pair_base = 0;
    po_p[0] = 0;
    for (Py_ssize_t mk = 0; mk < m; mk++) {
        int64_t lo = offs_p[mk], hi = offs_p[mk + 1];
        int64_t uniq = 0;
        for (int64_t s = lo; s < hi; s++) {
            int32_t c = codes_p[s];
            if (c < 0 || c >= u) {
                PyErr_Format(PyExc_IndexError,
                             "signal %lld: code %d out of table range",
                             (long long)s, c);
                goto done;
            }
            int32_t r = rank_p[c];
            if (r < 0 || r >= u) {
                PyErr_Format(PyExc_IndexError,
                             "code %d: rank %d out of range", c, r);
                goto done;
            }
            if (stamp[r] != (int64_t)mk) {
                stamp[r] = (int64_t)mk;
                market_ranks[uniq++] = r;
            }
        }
        qsort(market_ranks, (size_t)uniq, sizeof(int32_t), int32_cmp);
        if (pair_base + uniq > cap) {
            PyErr_SetString(PyExc_ValueError,
                            "group_columns: pair output buffers too small");
            goto done;
        }
        for (int64_t j = 0; j < uniq; j++) {
            int32_t r = market_ranks[j];
            int64_t p = pair_base + j;
            slot[r] = p;
            pm_p[p] = (int32_t)mk;
            pr_p[p] = r;
            sums_p[p] = 0.0;
            cnt_p[p] = 0;
        }
        for (int64_t s = lo; s < hi; s++) {
            int64_t p = slot[rank_p[codes_p[s]]];
            sp_p[s] = p;
            sums_p[p] += probs_p[s];
            cnt_p[p] += 1;
        }
        pair_base += uniq;
        po_p[mk + 1] = pair_base;
    }
    result = PyLong_FromLongLong((long long)pair_base);

done:
    PyMem_Free(stamp);
    PyMem_Free(slot);
    PyMem_Free(market_ranks);
    if (codes.obj) PyBuffer_Release(&codes);
    if (rank.obj) PyBuffer_Release(&rank);
    if (offs.obj) PyBuffer_Release(&offs);
    if (probs.obj) PyBuffer_Release(&probs);
    if (sp.obj) PyBuffer_Release(&sp);
    if (pm.obj) PyBuffer_Release(&pm);
    if (pr.obj) PyBuffer_Release(&pr);
    if (po.obj) PyBuffer_Release(&po);
    if (sums.obj) PyBuffer_Release(&sums);
    if (cnt.obj) PyBuffer_Release(&cnt);
    return result;
}

/* pair_accumulate(pair_idx, probs, out_sums): out_sums[idx[i]] += probs[i]
 * in signal order (np.add.at's sequential accumulate — the refresh twin's
 * float-summation contract). pair_idx may be int32 or int64; out_sums
 * must arrive zeroed by the caller. */
static PyObject *
fastpack_pair_accumulate(PyObject *Py_UNUSED(self), PyObject *args)
{
    PyObject *idx_o, *probs_o, *sums_o;
    if (!PyArg_ParseTuple(args, "OOO", &idx_o, &probs_o, &sums_o))
        return NULL;
    Py_buffer idx = {0}, probs = {0}, sums = {0};
    PyObject *result = NULL;

    if (PyObject_GetBuffer(idx_o, &idx, PyBUF_CONTIG_RO) < 0) goto done;
    Py_ssize_t n = get_elems(probs_o, &probs, 8, 0, "probs");
    Py_ssize_t p_cap = n < 0 ? -1 : get_elems(sums_o, &sums, 8, 1, "out_sums");
    if (p_cap < 0) goto done;

    const double *probs_p = (const double *)probs.buf;
    double *sums_p = (double *)sums.buf;
    if (idx.len == n * 8) {
        const int64_t *idx_p = (const int64_t *)idx.buf;
        for (Py_ssize_t i = 0; i < n; i++) {
            int64_t p = idx_p[i];
            if (p < 0 || p >= p_cap) {
                PyErr_Format(PyExc_IndexError,
                             "pair index %lld out of range", (long long)p);
                goto done;
            }
            sums_p[p] += probs_p[i];
        }
    } else if (idx.len == n * 4) {
        const int32_t *idx_p = (const int32_t *)idx.buf;
        for (Py_ssize_t i = 0; i < n; i++) {
            int32_t p = idx_p[i];
            if (p < 0 || p >= p_cap) {
                PyErr_Format(PyExc_IndexError,
                             "pair index %d out of range", p);
                goto done;
            }
            sums_p[p] += probs_p[i];
        }
    } else {
        PyErr_SetString(PyExc_ValueError,
                        "pair_idx must be int32 or int64, one per prob");
        goto done;
    }
    result = Py_None;
    Py_INCREF(result);

done:
    if (idx.obj) PyBuffer_Release(&idx);
    if (probs.obj) PyBuffer_Release(&probs);
    if (sums.obj) PyBuffer_Release(&sums);
    return result;
}

/* columns_from_payloads(payloads) ->
 *     (market_keys, source_ids, probs_bytearray, offsets_bytearray)
 * One C pass flattening dict payloads to the columnar layout: probs are
 * float64 host bytes, offsets int64 (M+1) — both wrap as numpy arrays
 * with no copy. Identical values to the pure-Python loop (probability
 * conversion goes through the same __float__ protocol numpy uses). */
static PyObject *
fastpack_columns_from_payloads(PyObject *Py_UNUSED(self), PyObject *arg)
{
    PyObject *markets_fast = PySequence_Fast(
        arg, "payloads must be a sequence");
    if (!markets_fast) return NULL;
    Py_ssize_t m = PySequence_Fast_GET_SIZE(markets_fast);

    PyObject *keys = PyList_New(0);
    PyObject *sids = PyList_New(0);
    PyObject *offs_ba = PyByteArray_FromStringAndSize(NULL, (m + 1) * 8);
    PyObject *key_source = PyUnicode_InternFromString("sourceId");
    PyObject *key_prob = PyUnicode_InternFromString("probability");
    double *probs_buf = NULL;
    size_t probs_used = 0, probs_cap = 1024;
    probs_buf = PyMem_Malloc(probs_cap * sizeof(double));
    if (!keys || !sids || !offs_ba || !key_source || !key_prob ||
        !probs_buf) {
        if (!probs_buf) PyErr_NoMemory();
        goto fail;
    }
    int64_t *offs = (int64_t *)PyByteArray_AS_STRING(offs_ba);
    offs[0] = 0;

    for (Py_ssize_t i = 0; i < m; i++) {
        PyObject *entry = PySequence_Fast_GET_ITEM(markets_fast, i);
        if (!PyTuple_Check(entry) && !PyList_Check(entry)) {
            PyErr_SetString(PyExc_TypeError,
                            "each payload must be (market_id, signals)");
            goto fail;
        }
        PyObject *market_id = PySequence_GetItem(entry, 0);
        if (!market_id) goto fail;
        int rc = PyList_Append(keys, market_id);
        Py_DECREF(market_id);
        if (rc < 0) goto fail;
        PyObject *signals = PySequence_GetItem(entry, 1);
        if (!signals) goto fail;
        PyObject *signals_fast = PySequence_Fast(
            signals, "signals must be a sequence");
        Py_DECREF(signals);
        if (!signals_fast) goto fail;
        Py_ssize_t ns = PySequence_Fast_GET_SIZE(signals_fast);
        if (probs_used + (size_t)ns > probs_cap) {
            size_t cap = probs_cap * 2;
            while (probs_used + (size_t)ns > cap) cap *= 2;
            double *grown = PyMem_Realloc(probs_buf, cap * sizeof(double));
            if (!grown) {
                PyErr_NoMemory();
                Py_DECREF(signals_fast);
                goto fail;
            }
            probs_buf = grown;
            probs_cap = cap;
        }
        for (Py_ssize_t s = 0; s < ns; s++) {
            PyObject *signal = PySequence_Fast_GET_ITEM(signals_fast, s);
            PyObject *sid = PyObject_GetItem(signal, key_source);
            if (!sid) { Py_DECREF(signals_fast); goto fail; }
            rc = PyList_Append(sids, sid);
            Py_DECREF(sid);
            if (rc < 0) { Py_DECREF(signals_fast); goto fail; }
            PyObject *prob = PyObject_GetItem(signal, key_prob);
            if (!prob) { Py_DECREF(signals_fast); goto fail; }
            double value = PyFloat_AsDouble(prob);
            Py_DECREF(prob);
            if (value == -1.0 && PyErr_Occurred()) {
                Py_DECREF(signals_fast);
                goto fail;
            }
            probs_buf[probs_used++] = value;
        }
        Py_DECREF(signals_fast);
        offs[i + 1] = (int64_t)probs_used;
    }

    PyObject *probs_ba = PyByteArray_FromStringAndSize(
        (const char *)probs_buf, (Py_ssize_t)(probs_used * sizeof(double)));
    PyMem_Free(probs_buf);
    probs_buf = NULL;
    if (!probs_ba) goto fail;
    Py_DECREF(markets_fast);
    Py_DECREF(key_source);
    Py_DECREF(key_prob);
    return Py_BuildValue("(NNNN)", keys, sids, probs_ba, offs_ba);

fail:
    PyMem_Free(probs_buf);
    Py_XDECREF(markets_fast);
    Py_XDECREF(keys);
    Py_XDECREF(sids);
    Py_XDECREF(offs_ba);
    Py_XDECREF(key_source);
    Py_XDECREF(key_prob);
    return NULL;
}

/* join_codes(codes, table) -> bytes: UTF-8 of table[code] per signal,
 * concatenated — equal to "".join(table[c] for c in codes).encode()
 * (UTF-8 of a concatenation is the concatenation of UTF-8), the joined
 * half of topology_fingerprint's source column for the coded intake. */
static PyObject *
fastpack_join_codes(PyObject *Py_UNUSED(self), PyObject *args)
{
    PyObject *codes_o, *table_o;
    if (!PyArg_ParseTuple(args, "OO", &codes_o, &table_o)) return NULL;
    PyObject *table = PySequence_Fast(table_o, "table must be a sequence");
    if (!table) return NULL;
    Py_ssize_t u = PySequence_Fast_GET_SIZE(table);

    Py_buffer codes = {0};
    typedef struct { const char *buf; Py_ssize_t len; } strview_t;
    strview_t *views = NULL;
    PyObject *out = NULL;

    Py_ssize_t n = get_elems(codes_o, &codes, 4, 0, "codes");
    if (n < 0) goto done;
    views = PyMem_Calloc((size_t)(u ? u : 1), sizeof(strview_t));
    if (!views) {
        PyErr_NoMemory();
        goto done;
    }
    const int32_t *codes_p = (const int32_t *)codes.buf;
    size_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t c = codes_p[i];
        if (c < 0 || c >= u) {
            PyErr_Format(PyExc_IndexError,
                         "signal %zd: code %d out of table range", i, c);
            goto done;
        }
        if (!views[c].buf) {
            PyObject *item = PySequence_Fast_GET_ITEM(table, c);
            if (!PyUnicode_Check(item)) {
                PyErr_SetString(PyExc_TypeError, "table entries must be str");
                goto done;
            }
            views[c].buf = PyUnicode_AsUTF8AndSize(item, &views[c].len);
            if (!views[c].buf) goto done;
        }
        total += (size_t)views[c].len;
    }
    out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)total);
    if (!out) goto done;
    char *dst = PyBytes_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        strview_t *v = &views[codes_p[i]];
        memcpy(dst, v->buf, (size_t)v->len);
        dst += v->len;
    }

done:
    PyMem_Free(views);
    if (codes.obj) PyBuffer_Release(&codes);
    Py_DECREF(table);
    return out;
}

static PyMethodDef fastpack_methods[] = {
    {"pack", fastpack_pack, METH_VARARGS,
     "pack(markets) -> (pair_market, pair_source_ids, flat_probs, flat_pair, "
     "signals_per_market, pair_offsets)"},
    {"group_columns", fastpack_group_columns, METH_VARARGS,
     "group_columns(codes, rank_of_code, offsets, probs, out_signal_pair, "
     "out_pair_market, out_pair_rank, out_pair_offsets, out_sums, "
     "out_counts) -> num_pairs"},
    {"pair_accumulate", fastpack_pair_accumulate, METH_VARARGS,
     "pair_accumulate(pair_idx, probs, out_sums): ordered per-pair sum"},
    {"columns_from_payloads", fastpack_columns_from_payloads, METH_O,
     "columns_from_payloads(payloads) -> (market_keys, source_ids, "
     "probs_bytearray, offsets_bytearray)"},
    {"join_codes", fastpack_join_codes, METH_VARARGS,
     "join_codes(codes, table) -> concatenated UTF-8 bytes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fastpack_module = {
    PyModuleDef_HEAD_INIT,
    "fastpack",
    "Native signal-ingest packer (C twin of core.batch.pack_markets grouping).",
    -1,
    fastpack_methods,
};

PyMODINIT_FUNC
PyInit_fastpack(void)
{
    return PyModule_Create(&fastpack_module);
}
