"""Perf experiments round 2: bandwidth roofline + reduced-state cycle variants.

Run: python scripts/perf_experiments2.py
"""

from __future__ import annotations

import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp

from bench import build_workload

M, K, STEPS = 1_048_576, 16, 100


def time_loop(fn, *args, trials=3):
    out = fn(*args)
    float(jax.tree_util.tree_leaves(out)[-1].reshape(-1)[0])
    best = float("inf")
    for _ in range(trials):
        start = time.perf_counter()
        out = fn(*args)
        float(jax.tree_util.tree_leaves(out)[-1].reshape(-1)[0])
        best = min(best, time.perf_counter() - start)
    return best


def report(name, secs, nbytes):
    per = secs / STEPS
    print(
        f"{name:24s}: {STEPS / secs:10.1f} cycles/sec  ({per * 1e3:.3f} ms/cycle, "
        f"{nbytes / per / 1e9:.0f} GB/s effective)"
    )


def main():
    dtype = jnp.float32
    probs, mask, outcome, _ = build_workload(jax.random.PRNGKey(0), M, K, dtype)
    probs, mask = probs.T, mask.T
    mib = 1024 * 1024

    # --- roofline probe: stream 3 (K, M) f32 arrays read+write in a loop ----
    def stream_fn(a, b, c):
        def body(i, carry):
            a, b, c = carry
            return (a * 1.000001 + 1e-9, b * 0.999999 + 1e-9, c + a * 1e-9)

        return jax.lax.fori_loop(0, STEPS, body, (a, b, c))

    stream = jax.jit(stream_fn)
    arrs = [jnp.full((K, M), 0.5 + i * 0.1, dtype) for i in range(3)]
    secs = time_loop(stream, *arrs)
    report("stream 3rw", secs, 6 * 64 * mib)

    # --- full 4-buffer cycle (current) --------------------------------------
    from bayesian_consensus_engine_tpu.parallel import (
        MarketBlockState,
        build_cycle_loop,
        init_block_state,
    )

    loop = build_cycle_loop(mesh=None, slot_major=True, donate=False)
    state = MarketBlockState(*(x.T for x in init_block_state(M, K, dtype=dtype)))
    secs = time_loop(
        lambda: loop(probs, mask, outcome, state, jnp.asarray(1.0, dtype), STEPS)
    )
    report("xla 4-buf (current)", secs, (64 * 4 + 16 + 64 + 16 * 2 + 64 * 3) * mib)

    # --- 3-buffer variant: exists dropped from the carried state ------------
    # exists is monotone (exists | mask each step) and only gates reads for
    # slots whose stored values are still the cold-start defaults — with state
    # initialised at the defaults and decay gated on upd > 0, the masked reads
    # are identical without it.
    from bayesian_consensus_engine_tpu.utils.config import (
        BASE_LEARNING_RATE,
        CONFIDENCE_GROWTH_RATE,
        DECAY_HALF_LIFE_DAYS,
        DECAY_MINIMUM,
        MAX_UPDATE_STEP,
    )

    def cycle3(probs, mask, outcome, rel, conf, upd, now):
        elapsed = jnp.maximum(now - upd, 0.0)
        factor = jnp.exp2(-elapsed / DECAY_HALF_LIFE_DAYS)
        decayed = jnp.clip(
            DECAY_MINIMUM + (rel - DECAY_MINIMUM) * factor, DECAY_MINIMUM, 1.0
        )
        read_rel = jnp.where(upd > 0, decayed, rel)
        w = jnp.where(mask, read_rel, 0.0)
        total_weight = jnp.sum(w, axis=0)
        weighted_prob = jnp.sum(jnp.where(mask, probs, 0.0) * w, axis=0)
        # (confidence output dropped from this probe: unused → XLA DCE'd it,
        # so it was never part of the measured traffic anyway)
        has_weight = total_weight != 0
        safe_total = jnp.where(has_weight, total_weight, 1.0)
        consensus = jnp.where(has_weight, weighted_prob / safe_total, jnp.nan)
        correct = (probs >= 0.5) == outcome[None, :]
        direction = jnp.where(correct, 1.0, -1.0)
        delta = jnp.clip(
            BASE_LEARNING_RATE * direction, -MAX_UPDATE_STEP, MAX_UPDATE_STEP
        )
        new_rel = jnp.where(mask, jnp.clip(rel + delta, 0.0, 1.0), rel)
        new_conf = jnp.where(
            mask, jnp.minimum(1.0, conf + (1.0 - conf) * CONFIDENCE_GROWTH_RATE), conf
        )
        new_upd = jnp.where(mask, now, upd)
        return new_rel, new_conf, new_upd, consensus

    def loop3_fn(probs, mask, outcome, rel, conf, upd, now0):
        def body(i, carry):
            rel, conf, upd, _ = carry
            return cycle3(probs, mask, outcome, rel, conf, upd, now0 + i)

        init = jnp.zeros(M, probs.dtype)
        return jax.lax.fori_loop(0, STEPS, body, (rel, conf, upd, init))

    loop3 = jax.jit(loop3_fn)
    rel = jnp.full((K, M), 0.5, dtype)
    conf = jnp.full((K, M), 0.25, dtype)
    upd = jnp.zeros((K, M), dtype)
    secs = time_loop(
        lambda: loop3(probs, mask, outcome, rel, conf, upd, jnp.asarray(1.0, dtype))
    )
    report("xla 3-buf no-exists", secs, (64 * 3 + 16 + 64 + 64 * 3) * mib)

    # --- 3-buf + precomputed masked probs (probs pre-zeroed where ~mask) ----
    probs_masked = jnp.where(mask, probs, 0.0)

    def cycle3b(probs_m, mask, outcome, rel, conf, upd, now):
        elapsed = jnp.maximum(now - upd, 0.0)
        factor = jnp.exp2(-elapsed / DECAY_HALF_LIFE_DAYS)
        decayed = jnp.clip(
            DECAY_MINIMUM + (rel - DECAY_MINIMUM) * factor, DECAY_MINIMUM, 1.0
        )
        read_rel = jnp.where(upd > 0, decayed, rel)
        w = jnp.where(mask, read_rel, 0.0)
        total_weight = jnp.sum(w, axis=0)
        weighted_prob = jnp.sum(probs_m * w, axis=0)
        # (confidence output dropped: unused → DCE'd, never measured)
        has_weight = total_weight != 0
        safe_total = jnp.where(has_weight, total_weight, 1.0)
        consensus = jnp.where(has_weight, weighted_prob / safe_total, jnp.nan)
        correct = (probs_m >= 0.5) == outcome[None, :]
        direction = jnp.where(correct, 1.0, -1.0)
        delta = jnp.clip(
            BASE_LEARNING_RATE * direction, -MAX_UPDATE_STEP, MAX_UPDATE_STEP
        )
        new_rel = jnp.where(mask, jnp.clip(rel + delta, 0.0, 1.0), rel)
        new_conf = jnp.where(
            mask, jnp.minimum(1.0, conf + (1.0 - conf) * CONFIDENCE_GROWTH_RATE), conf
        )
        new_upd = jnp.where(mask, now, upd)
        return new_rel, new_conf, new_upd, consensus

    def loop3b_fn(probs_m, mask, outcome, rel, conf, upd, now0):
        def body(i, carry):
            rel, conf, upd, _ = carry
            return cycle3b(probs_m, mask, outcome, rel, conf, upd, now0 + i)

        init = jnp.zeros(M, probs_m.dtype)
        return jax.lax.fori_loop(0, STEPS, body, (rel, conf, upd, init))

    loop3b = jax.jit(loop3b_fn)
    secs = time_loop(
        lambda: loop3b(
            probs_masked, mask, outcome, rel, conf, upd, jnp.asarray(1.0, dtype)
        )
    )
    report("xla 3-buf premask", secs, (64 * 3 + 16 + 64 + 64 * 3) * mib)


if __name__ == "__main__":
    main()
