"""Characterize the compact loop's ~1.1 ms/step floor at 1M x 16 (VERDICT r2).

Differential attribution, all in ONE process so the axon tunnel's run-to-run
bandwidth swing cancels:

  1. stream probe            — the live bandwidth denominator
  2. baseline                — compact loop per-step at (1M, 16)
  3. markets scaling         — M in {125k..4M}: throughput-bound would scale
                               linearly, a fixed floor would not
  4. slots scaling           — K in {1, 4, 16}: 16x fewer bytes at K=1
  5. steps scaling           — per-step time must be step-count independent
  6. fori unroll             — lax.fori_loop(unroll=k): if the floor is
                               per-iteration sequencing overhead (scalar-core
                               loop bookkeeping / kernel launch latency),
                               unrolling amortises it; if bandwidth, it won't
  7. counter-only body       — the mid-loop body after DCE is just the
                               masked counter bump (consensus+decay feed only
                               the discarded mid-loop consensus); measuring
                               it standalone bounds the compute

Run on the real TPU:  python scripts/perf_floor.py
"""

import json
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel.compact import (
    _compact_cycle_math,
    init_compact_state,
)
from bayesian_consensus_engine_tpu.ops.decay import decayed_reliability_at
from bayesian_consensus_engine_tpu.parallel.compact import decode_reliability


def fence(x):
    return float(jax.numpy.ravel(x)[0])


def build_workload(m, k, seed=0):
    kp, km, ko = jax.random.split(jax.random.PRNGKey(seed), 3)
    probs = jax.random.uniform(kp, (k, m), dtype=jnp.float32)
    mask = jax.random.uniform(km, (k, m)) < 0.9
    outcome = jax.random.uniform(ko, (m,)) < 0.5
    return probs, mask, outcome


def compact_loop_unrolled(steps: int, unroll: int):
    """The production compact loop body with a configurable fori unroll."""

    def loop_math(probs, mask, outcome, state, now0):
        read0 = decayed_reliability_at(
            decode_reliability(state.rel_steps),
            state.updated_days,
            now0 + 0,
            state.conf_steps > 0,
        )
        rs, cs, consensus0 = _compact_cycle_math(
            probs, mask, outcome, state.rel_steps, state.conf_steps, read0,
            None, 0,
        )

        def fast_step(carry, now_i, prev_now):
            rs, cs = carry
            read_rel = decayed_reliability_at(
                decode_reliability(rs),
                jnp.broadcast_to(prev_now, rs.shape),
                now_i,
                jnp.asarray(True),
            )
            rs, cs, consensus = _compact_cycle_math(
                probs, mask, outcome, rs, cs, read_rel, None, 0
            )
            return (rs, cs), consensus

        if steps == 1:
            return (rs, cs), consensus0

        def body(i, carry):
            new_carry, _ = fast_step(carry, now0 + i, now0 + (i - 1))
            return new_carry

        carry = jax.lax.fori_loop(1, steps - 1, body, (rs, cs), unroll=unroll)
        return fast_step(carry, now0 + (steps - 1), now0 + (steps - 2))

    return jax.jit(loop_math, donate_argnums=(3,))


def counter_only_loop(steps: int, unroll: int = 1):
    """Only the masked counter bump per step (the DCE'd-loop lower bound)."""

    def loop_math(correct, mask, rs, cs):
        def body(_i, carry):
            rs, cs = carry
            bump = jnp.where(correct, jnp.int8(1), jnp.int8(-1))
            new_rs = jnp.clip(rs + bump, -5, 5).astype(jnp.int8)
            new_cs = jnp.where(cs < 255, cs + jnp.uint8(1), cs)
            return jnp.where(mask, new_rs, rs), jnp.where(mask, new_cs, cs)

        return jax.lax.fori_loop(0, steps, body, (rs, cs), unroll=unroll)

    return jax.jit(loop_math, donate_argnums=(2, 3))


def time_loop(call, make_args, steps, trials=3):
    out = call(*make_args())
    fence(out[0][0] if isinstance(out[0], tuple) else out[0])
    best = float("inf")
    for _ in range(trials):
        args = make_args()
        t0 = time.perf_counter()
        out = call(*args)
        fence(out[0][0] if isinstance(out[0], tuple) else out[0])
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def stream_probe(steps=100):
    k, m = 16, 1_000_448

    def loop(a, b):
        return jax.lax.fori_loop(
            0, steps, lambda i, c: (c[1] + 1.0, c[0] * 0.5), (a, b)
        )

    sl = jax.jit(loop, donate_argnums=(0, 1))

    def fresh():
        a = jnp.ones((k, m), jnp.float32)
        b = jnp.ones((k, m), jnp.float32)
        fence(a)
        return a, b

    best = time_loop(sl, fresh, steps)
    return 4 * k * m * 4 / best / 1e9


def main():
    results = {"backend": jax.default_backend(),
               "device": str(jax.devices()[0])}
    results["stream_probe_gbs"] = round(stream_probe(), 1)

    def run_shape(m, k, steps=100, unroll=1, seed=0):
        probs, mask, outcome = build_workload(m, k, seed)
        loop = compact_loop_unrolled(steps, unroll)

        def make():
            state = init_compact_state(m, k)
            fence(state.updated_days)
            return (probs, mask, outcome, state, jnp.float32(1.0))

        return time_loop(loop, make, steps) * 1e3  # ms/step

    # 2-4. shape scaling
    results["markets_scaling_ms_per_step_k16"] = {
        str(m): round(run_shape(m, 16), 4)
        for m in (125_000, 250_000, 500_000, 1_000_448, 2_000_896)
    }
    results["slots_scaling_ms_per_step_1m"] = {
        str(k): round(run_shape(1_000_448, k), 4) for k in (1, 4, 16)
    }
    # 5. steps scaling
    results["steps_scaling_ms_per_step_1m_k16"] = {
        str(s): round(run_shape(1_000_448, 16, steps=s), 4)
        for s in (25, 100, 400)
    }
    # 6. unroll
    results["unroll_ms_per_step_1m_k16"] = {
        str(u): round(run_shape(1_000_448, 16, unroll=u), 4)
        for u in (1, 2, 4, 8)
    }
    # 7. counter-only lower bound
    probs, mask, outcome = build_workload(1_000_448, 16)
    correct = (probs >= 0.5) == outcome[None, :]
    for u in (1, 4):
        loop = counter_only_loop(100, u)

        def make():
            state = init_compact_state(1_000_448, 16)
            fence(state.updated_days)
            return (correct, mask, state.rel_steps, state.conf_steps)

        results[f"counter_only_ms_per_step_unroll{u}"] = round(
            time_loop(loop, make, 100) * 1e3, 4
        )

    print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
