"""Perf experiments on the real TPU: XLA cycle loop vs fused Pallas loop.

Run:  python scripts/perf_experiments.py [--markets 1000000] [--steps 100]

Compares, at the bench workload size (slot-major (K, M) float32):
  * xla    — parallel.sharded.build_cycle_loop (the current bench path)
  * pallas — ops.pallas_cycle fused kernel inside a lax.fori_loop

Each timing fences with a scalar value fetch (block_until_ready does not
force remote execution through the axon tunnel — see bench.py notes).
"""

from __future__ import annotations

import argparse
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.ops.pallas_cycle import (
    SlotMajorState,
    build_pallas_cycle,
)
from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle_loop,
    init_block_state,
)
from bench import build_workload


def time_loop(fn, *args, trials=3):
    out = fn(*args)
    _fence(out)
    best = float("inf")
    for _ in range(trials):
        start = time.perf_counter()
        out = fn(*args)
        _fence(out)
        best = min(best, time.perf_counter() - start)
    return best, out


def _fence(out):
    leaves = jax.tree_util.tree_leaves(out)
    float(leaves[-1].reshape(-1)[0])


def main():
    ap = argparse.ArgumentParser()
    # Defaults must satisfy the pallas constraint markets % tile == 0 with
    # tile a multiple of 128 (1_000_000 is not; 2^20 is the clean shape).
    ap.add_argument("--markets", type=int, default=1_048_576)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--tile", type=int, default=4096)
    args = ap.parse_args()

    M, K, steps = args.markets, args.slots, args.steps
    dtype = jnp.float32
    probs, mask, outcome, _ = build_workload(jax.random.PRNGKey(0), M, K, dtype)
    probs_t, mask_t = probs.T, mask.T  # (K, M)

    # --- XLA loop (current bench path) --------------------------------------
    loop = build_cycle_loop(mesh=None, slot_major=True, donate=False)
    state = MarketBlockState(*(x.T for x in init_block_state(M, K, dtype=dtype)))
    secs, _ = time_loop(
        lambda: loop(probs_t, mask_t, outcome, state, jnp.asarray(1.0, dtype), steps)
    )
    print(f"xla    : {steps / secs:10.1f} cycles/sec  ({secs / steps * 1e3:.3f} ms/cycle)")

    # --- Pallas fused loop ---------------------------------------------------
    cycle = build_pallas_cycle(M, K, tile_markets=args.tile)

    def pallas_loop_fn(probs, mask, outcome, state, now0, steps):
        def body(i, carry):
            st, _ = carry
            st, consensus, _, _ = cycle(probs, mask, outcome, st, now0 + i)
            return st, consensus

        init = jnp.zeros((1, probs.shape[1]), probs.dtype)
        return jax.lax.fori_loop(0, steps, body, (state, init))

    pallas_loop = jax.jit(pallas_loop_fn, static_argnums=(5,))

    f32 = lambda x: jnp.asarray(x, jnp.float32)
    pstate = SlotMajorState(
        reliability=jnp.full((K, M), 0.5, jnp.float32),
        confidence=jnp.full((K, M), 0.25, jnp.float32),
        updated_days=jnp.zeros((K, M), jnp.float32),
        exists=jnp.zeros((K, M), jnp.float32),
    )
    pm, pk, po = f32(probs_t), f32(mask_t), f32(outcome)[None, :]
    secs, _ = time_loop(
        lambda: pallas_loop(pm, pk, po, pstate, jnp.float32(1.0), steps)
    )
    print(f"pallas : {steps / secs:10.1f} cycles/sec  ({secs / steps * 1e3:.3f} ms/cycle)")


if __name__ == "__main__":
    main()
