"""Measure the reference implementation's CPU throughput for the driver metric.

The reference publishes no benchmarks (BASELINE.md), so the yardstick is its
own hot path measured here: per-market consensus via
``MarketStore.compute_all_consensus`` with decayed per-(source, market)
SQLite reads, plus one ``update_reliability`` per (source, market) pair —
one full "consensus + reliability-update cycle" over the batch
(reference: market.py:200-221, reliability.py:185-231).

Usage:  python scripts/measure_reference_baseline.py [markets] [sources_per_market] [trials]

Methodology (pinned — the shared host carries external load that can
inflate any single pass up to ~4x, so one-shot numbers are not
defensible):

  * ``trials`` (default 5) independent timed passes run in THIS process,
    each on a freshly built store (the build is outside the timer);
  * the headline is the FASTEST pass (min elapsed = max throughput):
    external load only ever slows the reference down, so min-of-N is the
    reference-favouring bound — the conservative denominator for any
    "× faster" claim we publish;
  * 1-minute load average is sampled before and after and recorded next
    to the number; a run whose load swamps ``nproc`` should be re-taken;
  * per-trial throughputs are printed so the spread (contention) is
    visible in the record.

Prints one JSON line with all trials + the headline markets/sec and the
extrapolated cycles/sec at 1M markets — the constant baked into bench.py
(re-run this script to refresh it; update BASELINE.md's table with the
JSON).
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, "/root/reference/src")

from bayesian_engine.market import MarketId, MarketStore  # noqa: E402
from bayesian_engine.reliability import SQLiteReliabilityStore  # noqa: E402


def one_trial(num_markets: int = 500, sources_per_market: int = 16) -> dict:
    rng = random.Random(0)
    store = MarketStore()
    rel = SQLiteReliabilityStore(":memory:")
    universe = [f"src-{i:05d}" for i in range(10_000)]

    for m in range(num_markets):
        mid = MarketId(f"market-{m:07d}")
        for sid in rng.sample(universe, sources_per_market):
            store.add_signal(mid, {"sourceId": sid, "probability": rng.random()})

    # Warm the reliability table so reads hit real rows (worst case for the
    # reference: every read pays decay + SQLite).
    for market in store.list_markets():
        for signal in market.signals:
            rel.update_reliability(signal["sourceId"], str(market.id), True)

    start = time.perf_counter()
    results = store.compute_all_consensus(rel)
    for market in store.list_markets():
        outcome = rng.random() < 0.5
        for signal in market.signals:
            rel.update_reliability(
                signal["sourceId"],
                str(market.id),
                (signal["probability"] >= 0.5) == outcome,
            )
    elapsed = time.perf_counter() - start
    del store, rel

    assert len(results) == num_markets
    markets_per_sec = num_markets / elapsed
    return {
        "elapsed_s": elapsed,
        "markets_per_sec": markets_per_sec,
    }


def measure(
    num_markets: int = 500, sources_per_market: int = 16, trials: int = 5
) -> dict:
    """Min-of-N load-controlled measurement (see module docstring)."""
    load_before = os.getloadavg()[0]
    runs = [
        one_trial(num_markets, sources_per_market) for _ in range(trials)
    ]
    load_after = os.getloadavg()[0]
    best = max(r["markets_per_sec"] for r in runs)
    worst = min(r["markets_per_sec"] for r in runs)
    return {
        "markets": num_markets,
        "sources_per_market": sources_per_market,
        "trials": trials,
        "trial_markets_per_sec": [
            round(r["markets_per_sec"], 2) for r in runs
        ],
        "markets_per_sec": best,  # min-of-N elapsed: reference-favouring
        "spread_worst_over_best": round(worst / best, 3),
        "load_1m_before": load_before,
        "load_1m_after": load_after,
        "nproc": os.cpu_count(),
        "cycles_per_sec_at_1M": best / 1_000_000,
    }


if __name__ == "__main__":
    markets = int(sys.argv[1]) if len(sys.argv) > 1 else 500
    spm = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    trials = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    print(json.dumps(measure(markets, spm, trials)))
