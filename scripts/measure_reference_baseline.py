"""Measure the reference implementation's CPU throughput for the driver metric.

The reference publishes no benchmarks (BASELINE.md), so the yardstick is its
own hot path measured here: per-market consensus via
``MarketStore.compute_all_consensus`` with decayed per-(source, market)
SQLite reads, plus one ``update_reliability`` per (source, market) pair —
one full "consensus + reliability-update cycle" over the batch
(reference: market.py:200-221, reliability.py:185-231).

Usage:  python scripts/measure_reference_baseline.py [markets] [sources_per_market]

Prints markets/sec and the extrapolated cycles/sec at 1M markets — the
constant baked into bench.py (re-run this script to refresh it).
"""

import random
import sys
import time

sys.path.insert(0, "/root/reference/src")

from bayesian_engine.market import MarketId, MarketStore  # noqa: E402
from bayesian_engine.reliability import SQLiteReliabilityStore  # noqa: E402


def measure(num_markets: int = 500, sources_per_market: int = 16) -> dict:
    rng = random.Random(0)
    store = MarketStore()
    rel = SQLiteReliabilityStore(":memory:")
    universe = [f"src-{i:05d}" for i in range(10_000)]

    for m in range(num_markets):
        mid = MarketId(f"market-{m:07d}")
        for sid in rng.sample(universe, sources_per_market):
            store.add_signal(mid, {"sourceId": sid, "probability": rng.random()})

    # Warm the reliability table so reads hit real rows (worst case for the
    # reference: every read pays decay + SQLite).
    for market in store.list_markets():
        for signal in market.signals:
            rel.update_reliability(signal["sourceId"], str(market.id), True)

    start = time.perf_counter()
    results = store.compute_all_consensus(rel)
    for market in store.list_markets():
        outcome = rng.random() < 0.5
        for signal in market.signals:
            rel.update_reliability(
                signal["sourceId"],
                str(market.id),
                (signal["probability"] >= 0.5) == outcome,
            )
    elapsed = time.perf_counter() - start

    assert len(results) == num_markets
    markets_per_sec = num_markets / elapsed
    return {
        "markets": num_markets,
        "sources_per_market": sources_per_market,
        "elapsed_s": elapsed,
        "markets_per_sec": markets_per_sec,
        "cycles_per_sec_at_1M": markets_per_sec / 1_000_000,
    }


if __name__ == "__main__":
    markets = int(sys.argv[1]) if len(sys.argv) > 1 else 500
    spm = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    out = measure(markets, spm)
    for key, value in out.items():
        print(f"{key}: {value}")
