"""Journal durability at scale: a REAL process death mid-stream, then
replay + resume — the crash-recovery contract under production-ish load.

The in-suite tests (tests/test_journal.py) pin the journal contracts at
small shapes; this soak proves them at the 1M-row scale the service
actually runs. A CHILD process streams 8 columnar batches x 40k markets
(~1.28M store rows) with journal-only durability
(`settle_stream(journal=)`, epoch every 2 batches) and dies with
``os._exit`` — no GeneratorExit, no finally blocks, no tail epoch —
right after batch 4 yields. The parent then replays the journal: the
durable watermark must be batch 3 (the last cadence epoch; batch 4
settled in the dead process but was never durable), resume re-settles
batch 4 exactly once along with 5..7, and the recovered store must
equal a never-killed straight-through run RECORD FOR RECORD, including
row assignment. Exits 0 on success; prints sizes/timings for the round
notes (2026-07-31 on this host: 935k rows durable at death, 65 MB
journal, ~2 s replay, byte-equal at 1.28M records).

Run from the repo root:  python scripts/journal_scale_soak.py
"""

import os
import pathlib
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from bayesian_consensus_engine_tpu.pipeline import settle_stream  # noqa: E402
from bayesian_consensus_engine_tpu.state.journal import (  # noqa: E402
    JournalWriter,
    replay_journal,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (  # noqa: E402
    TensorReliabilityStore,
)

BATCHES = 8
PER_BATCH = 40_000
UNIVERSE = 30_000
DIE_AFTER = 4        # child os._exit()s right after this batch yields
CHECKPOINT_EVERY = 2
DURABLE_TAG = 3      # last cadence epoch before the death point
KILL_RC = 137
START_DAY = 21_500.0


def build_batches():
    rng = np.random.default_rng(97)
    batch_data = []
    for b in range(BATCHES):
        counts = rng.poisson(3, PER_BATCH) + 1
        total = int(counts.sum())
        keys = [f"b{b}-m{m}" for m in range(PER_BATCH)]
        sids = [f"src-{v}" for v in rng.integers(0, UNIVERSE, total)]
        probs = rng.random(total)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        outcomes = (rng.random(PER_BATCH) < 0.5).tolist()
        batch_data.append(((keys, sids, probs, offsets), outcomes))
    return batch_data


def child_main(jrnl: str) -> None:
    """Stream with journal-only durability; die hard mid-run."""
    store = TensorReliabilityStore(capacity=2_000_000)
    for i, _result in enumerate(settle_stream(
        store, build_batches(), steps=3, now=START_DAY, journal=jrnl,
        checkpoint_every=CHECKPOINT_EVERY, columnar=True,
    )):
        if i == DIE_AFTER:
            os._exit(KILL_RC)  # the real thing: no finally, no tail epoch


def fingerprint(store):
    """Records AND row assignment — the replay contract's full surface."""
    store.sync()
    return store.list_sources(), store._pairs.ids()


def main() -> None:
    with tempfile.TemporaryDirectory() as tmp:
        jrnl = os.path.join(tmp, "scale.jrnl")

        start = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env={**os.environ, "_SOAK_CHILD_JRNL": jrnl},
            cwd=str(pathlib.Path(__file__).resolve().parents[1]),
        )
        child_s = time.perf_counter() - start
        assert proc.returncode == KILL_RC, (
            f"child exited {proc.returncode}, expected the kill"
        )
        size_mb = os.path.getsize(jrnl) / 1e6

        start = time.perf_counter()
        replayed, tag = replay_journal(jrnl)
        replay_s = time.perf_counter() - start
        assert tag == DURABLE_TAG, (
            f"durable watermark {tag}, expected {DURABLE_TAG}: batch "
            f"{DIE_AFTER} settled in the dead process but must NOT be "
            "durable (no tail epoch ran)"
        )
        print(
            f"child killed after batch {DIE_AFTER} ({child_s:.1f}s): "
            f"{len(replayed):,} rows durable through batch {tag}, "
            f"journal {size_mb:.0f} MB, replay {replay_s:.1f}s"
        )

        # Resume re-settles batch 4 (lost with the process) exactly once.
        batch_data = build_batches()
        with JournalWriter(jrnl, resume=True) as journal:
            for _result in settle_stream(
                replayed, batch_data[tag + 1:], steps=3,
                now=START_DAY + tag + 1, journal=journal,
                checkpoint_every=CHECKPOINT_EVERY, columnar=True,
            ):
                pass

        straight = TensorReliabilityStore(capacity=2_000_000)
        for _result in settle_stream(
            straight, batch_data, steps=3, now=START_DAY, columnar=True,
        ):
            pass

        mine, theirs = fingerprint(replayed), fingerprint(straight)
        assert mine[1] == theirs[1], "row assignment diverged in replay"
        assert mine[0] == theirs[0], "resumed state != straight-through"
        print(
            f"post-kill resume == straight-through: {len(mine[0]):,} "
            "records byte-equal, row assignment identical"
        )


if __name__ == "__main__":
    child_jrnl = os.environ.get("_SOAK_CHILD_JRNL")
    if child_jrnl:
        child_main(child_jrnl)
    else:
        main()
