"""Journal durability at scale: a REAL process death mid-stream, then
replay + resume — the crash-recovery contract under production-ish load.

The in-suite tests (tests/test_journal.py) pin the journal contracts at
small shapes; this soak proves them at the 1M-row scale the service
actually runs. A CHILD process streams 8 columnar batches x 40k markets
(~1.28M store rows) with journal-only durability
(`settle_stream(journal=)`, epoch every 2 batches) and dies with
``os._exit`` — no GeneratorExit, no finally blocks, no tail epoch —
right after batch 4 yields. The parent then replays the journal.

Watermark under the ASYNC-epoch contract (round 6, the default): a
yield means *the previous cadence's epoch is fsynced and the current
one is in flight* — so at the death point the durable tag is batch 1
at worst (epoch 1 was joined before epoch 3 started) and batch 3 when
the in-flight write won its race with ``os._exit`` (a torn epoch-3
frame is dropped by replay, exactly the contract). Resume re-settles
``batches[tag + 1:]`` and the recovered store must equal a never-killed
straight-through run RECORD FOR RECORD, including row assignment —
whichever tag the crash left. Run with ``--sync`` to pin the strict
pre-round-6 contract instead (tag must be exactly 3). Exits 0 on
success.

Captures route through the obs run ledger (``--ledger PATH``) so soak
numbers carry the same loadavg/min-of-N attribution as bench legs —
render with ``bce-tpu stats PATH`` (ROADMAP obs follow-up).

Run from the repo root:  python scripts/journal_scale_soak.py \
    [--ledger soak.jsonl] [--sync]
"""

import argparse
import os
import pathlib
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from bayesian_consensus_engine_tpu.obs.ledger import RunLedger  # noqa: E402
from bayesian_consensus_engine_tpu.pipeline import settle_stream  # noqa: E402
from bayesian_consensus_engine_tpu.state.journal import (  # noqa: E402
    JournalWriter,
    replay_journal,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (  # noqa: E402
    TensorReliabilityStore,
)

BATCHES = 8
PER_BATCH = 40_000
UNIVERSE = 30_000
DIE_AFTER = 4        # child os._exit()s right after this batch yields
CHECKPOINT_EVERY = 2
SYNC_DURABLE_TAG = 3   # sync mode: the last cadence epoch, exactly
ASYNC_DURABLE_TAGS = (1, 3)  # async: epoch 3 in flight — either outcome
KILL_RC = 137
START_DAY = 21_500.0


def build_batches():
    rng = np.random.default_rng(97)
    batch_data = []
    for b in range(BATCHES):
        counts = rng.poisson(3, PER_BATCH) + 1
        total = int(counts.sum())
        keys = [f"b{b}-m{m}" for m in range(PER_BATCH)]
        sids = [f"src-{v}" for v in rng.integers(0, UNIVERSE, total)]
        probs = rng.random(total)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        outcomes = (rng.random(PER_BATCH) < 0.5).tolist()
        batch_data.append(((keys, sids, probs, offsets), outcomes))
    return batch_data


def child_main(jrnl: str, sync: bool) -> None:
    """Stream with journal-only durability; die hard mid-run."""
    store = TensorReliabilityStore(capacity=2_000_000)
    for i, _result in enumerate(settle_stream(
        store, build_batches(), steps=3, now=START_DAY, journal=jrnl,
        checkpoint_every=CHECKPOINT_EVERY, columnar=True,
        sync_checkpoints=sync,
    )):
        if i == DIE_AFTER:
            os._exit(KILL_RC)  # the real thing: no finally, no tail epoch


def fingerprint(store):
    """Records AND row assignment — the replay contract's full surface."""
    store.sync()
    return store.list_sources(), store._pairs.ids()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--ledger",
        help="append obs run-ledger records here (render: bce-tpu stats)",
    )
    parser.add_argument(
        "--sync", action="store_true",
        help="sync_checkpoints=True: pin the strict yield-implies-fsynced "
             "contract (durable tag must be exactly the last cadence)",
    )
    args = parser.parse_args()
    ledger = RunLedger(args.ledger, backend="cpu") if args.ledger else None

    def record(leg, value=None, unit=None, extras=None):
        if ledger is not None:
            ledger.record(f"soak.journal_scale.{leg}", value=value,
                          unit=unit, extras=extras)

    with tempfile.TemporaryDirectory() as tmp:
        jrnl = os.path.join(tmp, "scale.jrnl")

        start = time.perf_counter()
        env = {**os.environ, "_SOAK_CHILD_JRNL": jrnl}
        if args.sync:
            env["_SOAK_CHILD_SYNC"] = "1"
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            cwd=str(pathlib.Path(__file__).resolve().parents[1]),
        )
        child_s = time.perf_counter() - start
        assert proc.returncode == KILL_RC, (
            f"child exited {proc.returncode}, expected the kill"
        )
        size_mb = os.path.getsize(jrnl) / 1e6

        start = time.perf_counter()
        replayed, tag = replay_journal(jrnl)
        replay_s = time.perf_counter() - start
        if args.sync:
            assert tag == SYNC_DURABLE_TAG, (
                f"durable watermark {tag}, expected {SYNC_DURABLE_TAG}: "
                f"batch {DIE_AFTER} settled in the dead process but must "
                "NOT be durable (no tail epoch ran)"
            )
        else:
            # Async contract: epoch 1 was joined before epoch 3 started
            # (durable floor); epoch 3 was in flight at the yield — either
            # it won the race with os._exit or its torn frame was dropped.
            assert tag in ASYNC_DURABLE_TAGS, (
                f"durable watermark {tag}, expected one of "
                f"{ASYNC_DURABLE_TAGS}: yield implies epoch N-1 fsynced, "
                "epoch N in flight"
            )
        print(
            f"child killed after batch {DIE_AFTER} ({child_s:.1f}s): "
            f"{len(replayed):,} rows durable through batch {tag}, "
            f"journal {size_mb:.0f} MB, replay {replay_s:.1f}s"
        )
        record("child_wall_s", value=round(child_s, 3), unit="s",
               extras={"mode": "sync" if args.sync else "async",
                       "die_after_batch": DIE_AFTER})
        record("replay_s", value=round(replay_s, 3), unit="s",
               extras={"rows_durable": len(replayed),
                       "journal_mb": round(size_mb, 1),
                       "durable_tag": tag})

        # Resume re-settles the batches lost with the process exactly once.
        batch_data = build_batches()
        start = time.perf_counter()
        with JournalWriter(jrnl, resume=True) as journal:
            for _result in settle_stream(
                replayed, batch_data[tag + 1:], steps=3,
                now=START_DAY + tag + 1, journal=journal,
                checkpoint_every=CHECKPOINT_EVERY, columnar=True,
                sync_checkpoints=args.sync,
            ):
                pass
        resume_s = time.perf_counter() - start

        straight = TensorReliabilityStore(capacity=2_000_000)
        for _result in settle_stream(
            straight, batch_data, steps=3, now=START_DAY, columnar=True,
        ):
            pass

        mine, theirs = fingerprint(replayed), fingerprint(straight)
        assert mine[1] == theirs[1], "row assignment diverged in replay"
        assert mine[0] == theirs[0], "resumed state != straight-through"
        print(
            f"post-kill resume == straight-through: {len(mine[0]):,} "
            "records byte-equal, row assignment identical"
        )
        record("resume_s", value=round(resume_s, 3), unit="s",
               extras={"records_equal": len(mine[0]),
                       "resumed_from_batch": tag + 1})
    if ledger is not None:
        ledger.close()
    return 0


if __name__ == "__main__":
    child_jrnl = os.environ.get("_SOAK_CHILD_JRNL")
    if child_jrnl:
        child_main(child_jrnl, sync=bool(os.environ.get("_SOAK_CHILD_SYNC")))
    else:
        sys.exit(main())
