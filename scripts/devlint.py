"""Stdlib approximation of the CI lint gates for this offline environment.

CI runs real ``ruff check .`` and ``mypy`` (see .github/workflows/ci.yml);
neither tool is installed in the baked TPU image, so this script covers the
highest-signal subset of the gated rules with ``ast`` + ``symtable`` only:

  F401  imports never referenced — module level AND function scope
  F541  f-string without any placeholders
  F811  redefinition of an imported name by a later import
  F821  undefined name (referenced, bound in no enclosing scope, not a
        builtin; skipped for files with wildcard imports)
  F841  local assigned and never used (simple ``x = ...`` targets only,
        matching ruff: loop variables and unpacking are not flagged)
  E711  ``== None`` / ``!= None`` comparisons
  E712  ``== True`` / ``== False`` comparisons
  E722  bare ``except:``

``# noqa`` on the offending line suppresses, as with ruff.

Usage: ``python scripts/devlint.py [paths...]`` (defaults to the package,
tests, and repo-root scripts). Exits 1 on findings.
"""

from __future__ import annotations

import ast
import builtins
import pathlib
import symtable
import sys

_BUILTIN_NAMES = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__annotations__",
    "__path__", "__cached__", "__class__",
}

DEFAULT_PATHS = [
    "bayesian_consensus_engine_tpu",
    "tests",
    "scripts",
    "examples",
    "native",
    "bench.py",
    "__graft_entry__.py",
]


def _names_loaded(tree: ast.AST) -> set[str]:
    loaded: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            loaded.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                loaded.add(root.id)
        elif isinstance(node, (ast.AnnAssign, ast.arg)):
            # Quoted annotations ('decimal.Decimal') reference names too —
            # ruff resolves them; parse the string as an expression.
            loaded |= _annotation_names(node.annotation)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            loaded |= _annotation_names(node.returns)
    return loaded


def _annotation_names(annotation) -> set[str]:
    if not (
        isinstance(annotation, ast.Constant)
        and isinstance(annotation.value, str)
    ):
        return set()
    try:
        parsed = ast.parse(annotation.value, mode="eval")
    except SyntaxError:
        return set()
    return _names_loaded(parsed)


def _function_scope_unused_imports(
    tree: ast.AST, path: pathlib.Path
) -> list[str]:
    """F401 inside function bodies (ruff flags these; module pass misses
    them — the exact class the round-2 advisor caught in a test)."""
    problems: list[str] = []

    def visit(node: ast.AST, owner) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, child)
                continue
            if owner is not None and isinstance(
                child, (ast.Import, ast.ImportFrom)
            ):
                if not (
                    isinstance(child, ast.ImportFrom)
                    and child.module == "__future__"
                ):
                    loaded = _names_loaded(owner)
                    for alias in child.names:
                        if alias.name == "*":
                            continue
                        name = (alias.asname or alias.name).split(".")[0]
                        if name not in loaded and not (
                            alias.asname is None and "." in alias.name
                        ):
                            problems.append(
                                f"{path}:{child.lineno}: F401 {name!r} "
                                f"imported but unused (in {owner.name})"
                            )
            visit(child, owner)

    visit(tree, None)
    return problems


def _undefined_names(
    src: str, tree: ast.AST, path: pathlib.Path
) -> list[str]:
    """F821: names referenced but bound in no enclosing scope.

    ``symtable`` resolves scoping (locals, closures, globals, class
    bodies, comprehensions); a GLOBAL_IMPLICIT reference with no module
    binding and no builtin is a NameError waiting to run. Files with
    wildcard imports are skipped (bindings unknowable statically).
    """
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and any(
            alias.name == "*" for alias in node.names
        ):
            return []
    try:
        table = symtable.symtable(src, str(path), "exec")
    except SyntaxError:
        return []

    module_bound = {
        s.get_name()
        for s in table.get_symbols()
        if s.is_assigned() or s.is_imported() or s.is_namespace()
    }
    # `global x` inside a function binds x at module scope at runtime.
    declared_global: set[str] = set()

    def collect_globals(t) -> None:
        for s in t.get_symbols():
            if s.is_declared_global() and s.is_assigned():
                declared_global.add(s.get_name())
        for child in t.get_children():
            collect_globals(child)

    collect_globals(table)
    module_bound |= declared_global

    undefined: set[str] = set()

    def walk(t) -> None:
        for s in t.get_symbols():
            name = s.get_name()
            if not s.is_referenced() or name in _BUILTIN_NAMES:
                continue
            if (
                s.is_assigned() or s.is_imported() or s.is_parameter()
                or s.is_free() or s.is_namespace()
            ):
                continue
            if name not in module_bound:
                undefined.add(name)
        for child in t.get_children():
            walk(child)

    walk(table)
    if not undefined:
        return []
    # Attach line numbers from the first Load of each name.
    first_load: dict[str, int] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in undefined
        ):
            first_load.setdefault(node.id, node.lineno)
    return [
        f"{path}:{first_load.get(name, 1)}: F821 undefined name {name!r}"
        for name in sorted(undefined)
    ]


def check_file(path: pathlib.Path) -> list[str]:
    src = path.read_text()
    try:
        tree = ast.parse(src)
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: E999 syntax error: {exc.msg}"]
    lines = src.splitlines()

    def noqa(lineno: int) -> bool:
        return 0 < lineno <= len(lines) and "# noqa" in lines[lineno - 1]

    problems: list[str] = []
    problems += _function_scope_unused_imports(tree, path)
    problems += _undefined_names(src, tree, path)
    loaded = _names_loaded(tree)
    # format_spec of f"{x:,}" is itself a JoinedStr; exclude those from F541.
    format_specs = {
        id(node.format_spec)
        for node in ast.walk(tree)
        if isinstance(node, ast.FormattedValue) and node.format_spec is not None
    }
    exported = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__" for t in node.targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            exported |= {
                c.value for c in node.value.elts if isinstance(c, ast.Constant)
            }

    # F401 / F811 over module-level imports.
    seen_imports: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.ImportFrom) and node.module == "__future__":
                continue
            for alias in node.names:
                name = (alias.asname or alias.name).split(".")[0]
                if alias.name == "*":
                    continue
                if name in seen_imports:
                    problems.append(
                        f"{path}:{node.lineno}: F811 redefinition of "
                        f"{name!r} (first import line {seen_imports[name]})"
                    )
                seen_imports[name] = node.lineno
                if (
                    name not in loaded
                    and name not in exported
                    and (alias.name or "") not in exported
                    and not (alias.asname is None and "." in alias.name)
                ):
                    problems.append(
                        f"{path}:{node.lineno}: F401 {name!r} imported but unused"
                    )

    for node in ast.walk(tree):
        if isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Eq, ast.NotEq)) and isinstance(
                    comp, ast.Constant
                ):
                    if comp.value is None:
                        problems.append(
                            f"{path}:{node.lineno}: E711 comparison to None "
                            "(use `is`/`is not`)"
                        )
                    elif comp.value is True or comp.value is False:
                        problems.append(
                            f"{path}:{node.lineno}: E712 comparison to "
                            f"{comp.value} (use `is` or truthiness)"
                        )
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append(f"{path}:{node.lineno}: E722 bare except")
        elif (
            isinstance(node, ast.JoinedStr)
            and id(node) not in format_specs
            and not any(isinstance(v, ast.FormattedValue) for v in node.values)
        ):
            problems.append(
                f"{path}:{node.lineno}: F541 f-string without placeholders"
            )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Own scope only: nested defs report themselves. A name used by
            # a nested def still counts as used (closures), so collect uses
            # from the full subtree but assignments from this scope alone.
            assigned: dict[str, int] = {}
            used: set[str] = set()
            stack = list(ast.iter_child_nodes(node))
            while stack:
                inner = stack.pop()
                if (
                    isinstance(inner, ast.Assign)
                    and len(inner.targets) == 1
                    and isinstance(inner.targets[0], ast.Name)
                ):
                    assigned.setdefault(inner.targets[0].id, inner.lineno)
                if not isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    stack.extend(ast.iter_child_nodes(inner))
            for inner in ast.walk(node):
                if isinstance(inner, ast.Name) and not isinstance(
                    inner.ctx, ast.Store
                ):
                    used.add(inner.id)
            for name, lineno in assigned.items():
                if name not in used and not name.startswith("_"):
                    problems.append(
                        f"{path}:{lineno}: F841 local {name!r} assigned but "
                        f"never used (in {node.name})"
                    )
    return [
        msg for msg in problems if not noqa(int(msg.split(":", 2)[1] or 0))
    ]


def main(argv: list[str]) -> int:
    root = pathlib.Path(__file__).resolve().parents[1]
    targets = argv or DEFAULT_PATHS
    files: list[pathlib.Path] = []
    for t in targets:
        p = root / t
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    problems: list[str] = []
    for f in files:
        problems.extend(dict.fromkeys(check_file(f)))  # dedupe nested-walk repeats
    for line in problems:
        print(line)
    print(f"devlint: {len(files)} files, {len(problems)} findings")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
