"""Thin shim over the graftlint engine (the repo's one lint gate).

Historically this file carried its own pyflakes-lite implementation; those
rules now live in ``bayesian_consensus_engine_tpu/lint/rules_pyflakes.py``
alongside the JAX/determinism/layering rules, so there is exactly one
engine behind CI, ``bench.py``, and ``python -m
bayesian_consensus_engine_tpu.lint``. This shim keeps the old entry points
stable:

  ``check_file(path) -> list[str]``  findings as ``path:line: CODE msg``
  ``main(argv) -> int``              lint paths (default: the repo gate
                                     set), print findings, exit 1 on any

Rule catalog (F401/F541/F811/F821/F841/E711/E712/E722 plus JX1xx/DT2xx/
LY3xx/SH4xx/PL5xx/AS6xx): docs/static-analysis.md. The round-16 LY303
extension rides through here too: ``obs`` modules are held stdlib-only
and the obs READ surface (``obs.export``/``obs.fleet``/``obs.health``)
is import-confined to ``serve``/``cli`` — write-only obs, gated in CI by
this shim like every other rule. ``# noqa`` / ``# noqa: ID`` suppress.

``main`` runs the engine's whole-program tier too: ``run()`` builds a
ProjectContext over the full gate set, so the cross-module rules (JX110
traced-helper-boundary, the AS6xx async-safety family) gate through
this shim exactly like the per-file families. ``check_file`` stays a
single-file probe — project rules see a one-file project there, which
is what the per-rule fixtures want.

Round 20: ``main`` additionally schema-validates every ``*.bank.json``
in the tree (``utils.autotune.validate_bank``) — a shipped autotune
bank is adjudicated evidence, and a hand-edited one that no longer
parses would otherwise fail SILENTLY at load time (an invalid bank is
ignored whole by design). Bank errors gate like lint errors.
"""

from __future__ import annotations

import pathlib
import sys

_ROOT = pathlib.Path(__file__).resolve().parents[1]
if str(_ROOT) not in sys.path:
    sys.path.insert(0, str(_ROOT))

from bayesian_consensus_engine_tpu.lint import engine as _engine

DEFAULT_PATHS = list(_engine.config.DEFAULT_PATHS)


def check_file(path) -> list[str]:
    """Lint one file; returns rendered ``path:line: CODE message`` strings."""
    return [f.render() for f in _engine.check_file(path, root=_ROOT)]


def check_banks(root: pathlib.Path = _ROOT) -> list[str]:
    """Schema-validate every checked-in ``*.bank.json`` under *root*.

    Returns ``path: message`` strings — empty when every bank parses
    and validates. The runtime loader ignores an invalid bank WHOLE
    (falling back to live measurement), so this is the only gate that
    makes a hand-edited bank fail loudly instead of silently
    de-adjudicating every entry it carried.
    """
    import json

    from bayesian_consensus_engine_tpu.utils.autotune import validate_bank

    errors: list[str] = []
    for path in sorted(root.rglob("*.bank.json")):
        if any(part.startswith(".") for part in path.parts):
            continue  # .git, editor litter
        rel = path.relative_to(root)
        try:
            payload = json.loads(path.read_text())
        except ValueError as exc:
            errors.append(f"{rel}: not valid JSON ({exc})")
            continue
        errors.extend(
            f"{rel}: {problem}" for problem in validate_bank(payload)
        )
    return errors


def main(argv: list[str]) -> int:
    # ``--cache [PATH]`` forwards the engine's lint-cache sidecar (round
    # 18): a warm run of an unchanged tree replays cached findings
    # without re-parsing. Everything else is a path.
    cache = None
    paths: list[str] = []
    args = list(argv)
    while args:
        arg = args.pop(0)
        if arg == "--cache":
            cache = args.pop(0) if args else str(
                _ROOT / ".lint_cache.json"
            )
        elif arg.startswith("--cache="):
            cache = arg.split("=", 1)[1]
        else:
            paths.append(arg)
    n_files, findings = _engine.run(paths or None, root=_ROOT, cache=cache)
    for f in findings:
        print(f.render())
    bank_errors = check_banks()
    for err in bank_errors:
        print(f"BANK {err}")
    print(
        f"devlint: {n_files} files, {len(findings)} findings, "
        f"{len(bank_errors)} bank error(s)"
    )
    # Same severity gating as engine.main: warnings report, errors gate
    # — and an invalid shipped bank gates like an error.
    return 1 if bank_errors or any(
        f.severity == "error" for f in findings
    ) else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
