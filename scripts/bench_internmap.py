"""Micro-bench: native internmap vs dict-backed IdInterner at 1M pairs.

Measures the ingest-boundary cost the C extension exists to kill: interning
1M (source_id, market_id) pairs into int32 rows (the allocating cold pass +
a warm re-intern pass), and the non-allocating batch lookup.

Each backend runs in its own subprocess: a 1M-entry interner is hundreds of
MB of GC-tracked objects, and whichever backend runs second would otherwise
pay generational-GC traversals of the first one's heap.

Usage: python scripts/bench_internmap.py [N]
"""

from __future__ import annotations

import json
import subprocess
import sys

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000

WORKER = r"""
import json, random, sys, time

sys.path.insert(0, ".")
from bayesian_consensus_engine_tpu.utils.interning import (
    IdInterner, NativePairInterner,
)

backend, n = sys.argv[1], int(sys.argv[2])
rng = random.Random(7)
sources = [f"source-{rng.randrange(10_000):05d}" for _ in range(n)]
markets = [f"market-{rng.randrange(100_000):06d}" for _ in range(n)]

interner = IdInterner() if backend == "pure" else NativePairInterner()

out = {}
for label, fn in [
    ("cold", lambda: interner.intern_arrays(sources, markets)),
    ("warm", lambda: interner.intern_arrays(sources, markets)),
    ("lookup", lambda: interner.lookup_arrays(sources, markets)),
]:
    start = time.perf_counter()
    rows = fn()
    out[label] = time.perf_counter() - start
out["rows_head"] = [int(x) for x in rows[:16]]
out["unique"] = len(interner)
print(json.dumps(out))
"""


def run_backend(backend: str) -> dict:
    proc = subprocess.run(
        [sys.executable, "-c", WORKER, backend, str(N)],
        capture_output=True, text=True, check=True, cwd=".",
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    pure = run_backend("pure")
    native = run_backend("native")
    assert pure["rows_head"] == native["rows_head"], "row parity violated"
    assert pure["unique"] == native["unique"]

    print(f"interning {N:,} pairs ({pure['unique']:,} unique):")
    print(f"  {'':<8s} {'IdInterner':>12s} {'native':>12s} {'speedup':>9s}")
    for label in ("cold", "warm", "lookup"):
        p, n_ = pure[label], native[label]
        print(
            f"  {label:<8s} {p * 1e3:10.1f} ms {n_ * 1e3:10.1f} ms "
            f"{p / n_:8.2f}x"
        )


if __name__ == "__main__":
    main()
