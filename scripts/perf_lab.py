"""Perf lab — the consolidated on-chip measurement fixtures.

One entry point for the experiments whose adjudications back the numbers
in docs/tpu-architecture.md (consolidating the retired round-2/3 one-offs
``perf_floor.py``, ``perf_floor2.py``, ``perf_experiments{,2,3}.py``):

    python scripts/perf_lab.py rtt        # dispatch RTT, fixed/marginal fit,
                                          # chained-dispatch pipelining
    python scripts/perf_lab.py stream     # delivered HBM bandwidth probe
    python scripts/perf_lab.py scaling    # compact loop vs markets/slots/steps
    python scripts/perf_lab.py ab         # XLA cycle loop vs fused Pallas
    python scripts/perf_lab.py large-k    # the 10k-source regime
    python scripts/perf_lab.py all

Each subcommand prints one JSON line. Run on the real TPU; every timing
fences with a scalar value fetch (``block_until_ready`` does not force
remote execution through the axon tunnel) and reuses bench.py's workload
builders so lab numbers and driver numbers stay apples-to-apples.

Retired variants whose conclusions are already recorded (and whose
fixtures this file deliberately does NOT carry): the fori-unroll and
counter-only-body attribution runs (perf_floor.py — verdict: the
"1.1 ms/step floor" was dispatch RTT ÷ steps, kernel marginal
0.078 ms/step) and the reduced-state cycle ladder (perf_experiments2.py —
verdict: the int8 counter encoding won and became parallel/compact.py).
See docs/tpu-architecture.md "dispatch, adjudicated".
"""

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import bench  # noqa: E402 — repo-root import after path setup


def _slot_major_workload(markets, slots):
    """Slot-major (K, M) inputs from bench's OWN workload builder — the
    module's apples-to-apples promise is literal: same generator, same
    key, same occupancy as the driver's headline path."""
    import jax
    import jax.numpy as jnp

    probs, mask, outcome, _src = bench.build_workload(
        jax.random.PRNGKey(0), markets, slots, jnp.float32
    )
    probs, mask = probs.T, mask.T
    bench._fence(probs)
    return probs, mask, outcome


def _compact_rate(markets, slots, steps, trials=3, workload=None):
    """Compact-loop cycles/sec at (markets, slots), *steps* in one jit.

    Pass *workload* (a ``_slot_major_workload`` result) to reuse one
    device-resident input set across calls at the same shape."""
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import (
        build_compact_cycle_loop,
        init_compact_state,
    )

    probs, mask, outcome = workload or _slot_major_workload(markets, slots)
    loop = build_compact_cycle_loop(mesh=None, donate=True)

    def fresh():
        state = init_compact_state(markets, slots)
        bench._fence(state.updated_days)
        return state

    day = jnp.asarray(1.0, jnp.float32)
    return bench.timed_best_of(
        lambda s: loop(probs, mask, outcome, s, day, steps),
        fresh,
        steps,
        trials=trials,
    )


def cmd_rtt(args):
    """Fixed-vs-marginal dispatch decomposition + chained pipelining.

    (perf_floor2.py's question): every dispatch through the tunnel pays a
    fixed RTT; D chained dispatches with ONE fence pipeline to ~one RTT,
    so a long-running service sees the marginal kernel rate.
    """
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import (
        build_compact_cycle_loop,
        init_compact_state,
    )

    rtt_ms = bench.bench_dispatch_rtt()

    markets, slots = 1_000_448, bench.SLOTS_PER_MARKET
    workload = _slot_major_workload(markets, slots)  # one build, all legs
    big_steps, small_steps = 1600, 400
    big = _compact_rate(markets, slots, big_steps, workload=workload)
    small = _compact_rate(markets, slots, small_steps, workload=workload)
    t_big, t_small = big_steps / big, small_steps / small
    marginal_s = (t_big - t_small) / (big_steps - small_steps)
    fit = (
        {
            "fixed_dispatch_ms": round(
                (t_small - small_steps * marginal_s) * 1e3, 1
            ),
            "marginal_ms_per_step": round(marginal_s * 1e3, 4),
            "sustained_cycles_per_sec": round(1.0 / marginal_s, 1),
        }
        if marginal_s > 0
        else "degenerate (tunnel variance swamped the kernel term)"
    )

    # Chained dispatches, one fence: D loop calls threading donated state.
    # Best-of-3: this host's external load bursts can land inside a lone
    # timed window and fake a "dispatches do not pipeline" verdict.
    probs, mask, outcome = workload
    loop = build_compact_cycle_loop(mesh=None, donate=True)
    day = jnp.asarray(1.0, jnp.float32)
    chain_depth, chain_steps = 8, 100
    state = init_compact_state(markets, slots)
    bench._fence(state.updated_days)
    state, consensus = loop(probs, mask, outcome, state, day, chain_steps)
    bench._fence(consensus)  # warm
    chained_s = float("inf")
    for _ in range(3):
        start = time.perf_counter()
        for _ in range(chain_depth):
            state, consensus = loop(
                probs, mask, outcome, state, day, chain_steps
            )
        bench._fence(consensus)
        chained_s = min(chained_s, time.perf_counter() - start)

    return {
        "dispatch_rtt_ms": round(rtt_ms, 2),
        "compact_fit_1m16": fit,
        "chained_dispatches": {
            "depth": chain_depth,
            "steps_per_dispatch": chain_steps,
            "total_s": round(chained_s, 3),
            "ms_per_dispatch": round(chained_s / chain_depth * 1e3, 1),
            "note": (
                "one fence at the end: if dispatches pipeline, "
                "ms_per_dispatch ~ steps x marginal, not + RTT each"
            ),
        },
    }


def cmd_stream(args):
    return {"stream_probe_gbs": round(bench.bench_stream_probe(), 1)}


def cmd_scaling(args):
    """Compact-loop scaling (perf_floor.py's attribution grids).

    Bandwidth-bound behaviour: per-step ms scales ~linearly with markets
    and with slots, and is independent of the in-jit step count.
    """
    steps = 100
    markets_grid = {}
    for markets in (125_056, 500_224, 1_000_448, 2_000_896):
        rate = _compact_rate(markets, 16, steps)
        markets_grid[str(markets)] = round(1e3 / rate, 3)
    slots_grid = {}
    for slots in (1, 4, 16):
        rate = _compact_rate(1_000_448, slots, steps)
        slots_grid[str(slots)] = round(1e3 / rate, 3)
    steps_grid = {}
    for in_jit_steps in (100, 400, 1600):
        rate = _compact_rate(1_000_448, 16, in_jit_steps, trials=2)
        steps_grid[str(in_jit_steps)] = round(1e3 / rate, 3)
    return {
        "per_step_ms_vs_markets@K16": markets_grid,
        "per_step_ms_vs_slots@1M": slots_grid,
        "per_step_ms_vs_in_jit_steps@1Mx16": steps_grid,
    }


def cmd_ab(args):
    """XLA-fused cycle loop vs the hand-fused Pallas kernel at 1M x 16.

    Delegates to the bench's adjudication leg (same-process interleaved
    XLA/Pallas passes, autotuned tile, large-K attempt, verdict)."""
    try:
        return bench.bench_pallas_ab()
    except Exception as exc:  # noqa: BLE001 — Pallas needs the TPU backend
        out = {"pallas_ab": f"failed: {type(exc).__name__}: {exc}"}
        try:
            # The XLA half is backend-agnostic: keep reporting it so a
            # degraded host still records the comparison anchor.
            out["xla_cycles_per_sec"] = round(bench.bench_headline(), 1)
        except Exception as xla_exc:  # noqa: BLE001
            out["xla_cycles_per_sec"] = f"failed: {xla_exc}"
        return out


def cmd_large_k(args):
    return bench.bench_large_k()


COMMANDS = {
    "rtt": cmd_rtt,
    "stream": cmd_stream,
    "scaling": cmd_scaling,
    "ab": cmd_ab,
    "large-k": cmd_large_k,
}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("command", choices=[*COMMANDS, "all"])
    parser.add_argument(
        "--no-lint", action="store_true",
        help="skip the pre-run graftlint gate (docs/static-analysis.md)",
    )
    parser.add_argument(
        "--ledger",
        help=(
            "append one obs run-ledger record per experiment (JSONL, "
            "loadavg attribution) — render with `bce-tpu stats`"
        ),
    )
    args = parser.parse_args(argv)
    # Same contract as bench.py: lab numbers from a lint-dirty tree are
    # not comparable to the adjudicated baselines. The gate rides
    # bench's lint cache sidecar, so between-experiment re-checks of an
    # unchanged tree are a stat pass, not a 150-file re-parse.
    bench.lint_gate(args.no_lint)
    ledger = None
    if args.ledger:
        from bayesian_consensus_engine_tpu.obs.ledger import RunLedger

        ledger = RunLedger(args.ledger)

    def run_one(name, fn):
        # Record IMMEDIATELY after each experiment (same discipline as
        # bench --ledger): the record's host snapshot then reflects the
        # load during/around that experiment, not the end of the run.
        result = fn(args)
        if ledger is not None:
            ledger.record(f"perf_lab.{name}", extras={"result": result})
        return result

    try:
        if args.command == "all":
            out = {name: run_one(name, fn) for name, fn in COMMANDS.items()}
        else:
            out = run_one(args.command, COMMANDS[args.command])
    finally:
        if ledger is not None:
            ledger.close()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
