"""Real-kill cluster soak: failure as steady state, goodput as the verdict.

Runs a shared-nothing banded cluster (cluster/membership.py: N worker
PROCESSES, each settling its deterministic band of the global markets
axis on its own local mesh, journaling every batch), then — mid-stream —
``os.kill``\\ s one live worker with SIGKILL and proves the round-13
recovery story end to end:

  1. the supervisor observes the death and publishes the DEGRADED
     membership view (epoch+1 over the survivors — every survivor would
     derive the same view; the supervisor here is a failure *detector*,
     never a layout coordinator);
  2. the surviving worker picks the epoch bump up between batches,
     replays the dead band's journal INTO its live store
     (``cluster.recover.adopt_journal`` — reading state/journal.py's
     frame walk), and its resident session carries the orphan rows onto
     the device block through the round-13 adopt relayout — the stream
     RESUMES on the degraded view without a process restart, a session
     teardown, or a single ``rebuild`` fallback;
  3. the headline is **recovered ``goodput_within_slo``** (obs/slo.py):
     every offered request counts in the denominator — the crash-eaten
     batches the dead worker offered but never made durable re-drive on
     the survivor and land as SLO *violations*, exactly the PR-7
     accounting under which a recovery that loses traffic cannot look
     healthy;
  4. the byte coda: at adoption the survivor's live store must be
     bit-equal (store digest AND SQLite export bytes) to
     ``replay_cluster_journals`` over the surviving journals, and at
     exit the survivor's OWN journal must replay to its final store —
     the adopted band rides its next epochs, so the dead journal is
     needed once and never again;
  5. the LIVE health surface (round 16): every worker serves the
     telemetry plane (``obs/export.py`` — ``/metrics``, ``/snapshot``,
     ``/healthz`` on an ephemeral port published to the shared dir) and
     feeds a burn-rate monitor (``obs/health.py``) with its per-request
     outcomes; the supervisor POLLS ``/healthz`` through the kill window
     and scrapes ``/snapshot`` for the deterministic fleet merge
     (``obs/fleet.py`` — the degraded epoch shows the dead host as an
     explicit ``hosts_absent`` entry, and two fold orders must produce
     identical bytes). The leg JSON records the health TRANSITION
     timeline — healthy → burning/degraded → healthy — so recovery is
     observable while it happens, not just post-hoc.

Run from the repo root::

    python scripts/kill_soak.py [--markets 64] [--batches 12]
                                [--kill-after 3] [--interval 0.15]
                                [--slo 0.5] [--json] [--ledger soak.jsonl]

Exit code 0 iff every assertion holds; the final stdout line is one JSON
object (the ``e2e_kill_soak`` bench leg parses it).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SOAK_SEED = 20260803


def _membership_path(shared: str) -> str:
    return os.path.join(shared, "membership.json")


def _telemetry_path(shared: str, host: int) -> str:
    return os.path.join(shared, f"telemetry_{host}.json")


def _write_telemetry_port(shared: str, host: int, port: int) -> None:
    tmp = _telemetry_path(shared, host) + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"host": host, "port": port}, f, sort_keys=True)
    os.replace(tmp, _telemetry_path(shared, host))


def _write_membership(shared: str, view, kill_ts=None) -> None:
    payload = {
        "epoch": view.epoch,
        "hosts": list(view.hosts),
        "devices_per_host": view.devices_per_host,
        "fingerprint": view.fingerprint.hex(),
        "kill_ts": kill_ts,
    }
    tmp = _membership_path(shared) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, sort_keys=True)
    os.replace(tmp, _membership_path(shared))


def _read_membership(shared: str) -> dict:
    with open(_membership_path(shared)) as f:
        return json.load(f)


def _global_batch(args, index: int):
    """Deterministic global batch *index*: every host regenerates the
    SAME columnar (keys, sids, probs, offsets, outcomes) from the seed
    alone, then slices its band — which is what lets a survivor re-drive
    a dead band's remaining batches bit-for-bit. Topology drifts every
    two batches (``index // 2`` rotates the source assignment), so the
    steady phase exercises both the fingerprint-hit refresh and the
    topology-miss adopt relayout."""
    import numpy as np

    markets = args.markets
    drift = index // 2
    # Topology (signal counts + source assignment) is a function of the
    # DRIFT PERIOD; values (probs, outcomes) of the batch. Consecutive
    # batches inside a period are fingerprint hits (probs-only refresh),
    # period boundaries are topology misses (adopt relayout) — the
    # steady phase exercises both resident moves.
    rng_topo = np.random.default_rng((SOAK_SEED, 1, drift))
    rng_vals = np.random.default_rng((SOAK_SEED, 2, index))
    counts = rng_topo.integers(1, 4, markets)
    keys = [f"m{g}" for g in range(markets)]
    sids = []
    for g in range(markets):
        for j in range(counts[g]):
            sids.append(f"s{(g * 3 + j * 7 + drift) % args.sources}")
    probs = rng_vals.random(int(counts.sum()))
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    outcomes = (rng_vals.random(markets) < 0.5).tolist()
    return keys, sids, probs, offsets, outcomes


def _band_slice(args, batch, rows):
    """One band's columnar slice of a global batch (rows = global ids)."""
    import numpy as np

    keys, sids, probs, offsets, outcomes = batch
    rows = list(rows)
    out_keys, out_sids, out_probs, out_counts, out_outcomes = [], [], [], [], []
    for g in rows:
        lo, hi = int(offsets[g]), int(offsets[g + 1])
        out_keys.append(keys[g])
        out_sids.extend(sids[lo:hi])
        out_probs.append(probs[lo:hi])
        out_counts.append(hi - lo)
        out_outcomes.append(outcomes[g])
    merged_probs = (
        np.concatenate(out_probs) if out_probs else np.empty(0, np.float64)
    )
    merged_offsets = np.concatenate(
        [[0], np.cumsum(out_counts)]
    ).astype(np.int64)
    return out_keys, out_sids, merged_probs, merged_offsets, out_outcomes


# --------------------------------------------------------------------------
# worker
# --------------------------------------------------------------------------


def run_worker(args) -> int:
    flag = f"--xla_force_host_platform_device_count={args.devices_per_host}"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", args.devices_per_host)
    except AttributeError:
        pass  # old JAX: the XLA_FLAGS fallback above covers it

    import numpy as np

    from bayesian_consensus_engine_tpu.cluster.membership import MeshView
    from bayesian_consensus_engine_tpu.cluster.recover import (
        adopt_journal,
        replay_cluster_journals,
        store_digest,
    )
    from bayesian_consensus_engine_tpu.obs.export import TelemetryServer
    from bayesian_consensus_engine_tpu.obs.health import (
        BurnWindow,
        HealthMonitor,
    )
    from bayesian_consensus_engine_tpu.obs.metrics import (
        MetricsRegistry,
        set_metrics_registry,
    )
    from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
    from bayesian_consensus_engine_tpu.serve.driver import (
        PlanCache,
        SessionDriver,
    )
    from bayesian_consensus_engine_tpu.state.journal import JournalWriter
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    me = args.worker
    shared = args.dir
    view = MeshView(
        epoch=0,
        hosts=tuple(range(args.hosts)),
        devices_per_host=args.devices_per_host,
    )
    mesh = make_mesh()  # shared-nothing: this host's LOCAL mesh
    registry = MetricsRegistry()
    set_metrics_registry(registry)

    # The live health surface: a burn-rate monitor fed with this band's
    # per-request outcomes, exported over the stdlib telemetry server.
    # Windows are sized to the band (one batch's worth of outcomes fast,
    # four slow) so a crash-eaten batch re-driven as violations fires
    # the fast window immediately and the next met-only batch clears it.
    band_rows = max(1, len(list(view0_rows(args, me))))
    monitor = HealthMonitor(
        objective_goodput=0.9,
        windows=(
            BurnWindow(
                fast=max(8, band_rows),
                slow=4 * max(8, band_rows),
                threshold=2.0,
            ),
        ),
    )
    server = TelemetryServer(
        registry=registry, health=monitor, host_id=me, epoch=view.epoch,
    ).start()
    _write_telemetry_port(shared, me, server.port)

    store = TensorReliabilityStore()
    journal = JournalWriter(os.path.join(shared, f"band{me}.jrnl"))
    # Strict durability (sync epochs, every batch): a yielded batch IS
    # fsynced, which is both the honest SLO endpoint (submit→durable)
    # and what makes the adoption-time byte coda exact.
    driver = SessionDriver(
        store, steps=args.steps, mesh=mesh, journal=journal,
        owns_journal=True, checkpoint_every=1, sync_checkpoints=True,
    )
    cache = PlanCache(store, num_slots=args.num_slots)
    progress = open(os.path.join(shared, f"progress_{me}.jsonl"), "w")

    def log(kind: str, **payload) -> None:
        payload.update(kind=kind, ts=time.time())
        progress.write(json.dumps(payload, sort_keys=True) + "\n")
        progress.flush()

    def fallbacks() -> int:
        counters = registry.export().get("counters", {})
        return int(counters.get("stream.resident_fallbacks", 0))

    last_verdict = [None]

    def log_health(force: bool = False) -> None:
        """Append a ``health`` progress line on every VERDICT CHANGE (or
        forced) — the worker-side transition record the supervisor folds
        into the leg JSON's health timeline (the HTTP polls prove the
        endpoint is live; these lines make the sequence deterministic)."""
        v = monitor.verdict()
        if force or v["verdict"] != last_verdict[0]:
            last_verdict[0] = v["verdict"]
            log(
                "health", verdict=v["verdict"], burning=v["burning"],
                degraded=v["degraded"], epoch=view.epoch,
            )

    own_next = 0
    orphans: list = []  # [host, next_index] bands adopted from the dead
    adoption_report = None
    result: dict = {"ok": False, "host": me}
    dispatch_index = 0
    now0 = 20_950.0
    drain_deadline = None
    #: (band, index) → the DEAD worker's offer wall-ts for batches it
    #: offered but never made durable: their re-drive latency is measured
    #: from the ORIGINAL offer, so crash-eaten traffic lands as SLO
    #: violations in the live monitor exactly like it does in the
    #: supervisor's post-hoc accounting.
    victim_offers: dict = {}
    degraded_pending = False

    try:
        log_health(force=True)  # the timeline's starting "healthy"
        while True:
            # Membership poll — the coordinator-free agreement point:
            # the view file names the epoch and survivors; this worker
            # derives everything else (who died, which bands are orphan)
            # from the view alone.
            member = _read_membership(shared)
            if member["epoch"] > view.epoch:
                survivors = member["hosts"]
                dead = [h for h in view.hosts if h not in survivors]
                view = view.degraded(survivors)
                if me not in view.hosts:
                    break  # not our story: this worker was voted dead
                # Snapshot epoch tagging rides recovery: every survivor
                # re-tags its telemetry identity with the degraded
                # epoch, so a fleet fold of the scraped snapshots names
                # the membership it observed.
                server.set_epoch(view.epoch)
                for host in dead:
                    # Exactly ONE survivor owns each orphan band — a pure
                    # function of (dead host, degraded view), so every
                    # survivor derives the same owner with no
                    # coordination. A band adopted twice would put its
                    # pairs in two journals: the split-brain state
                    # replay_cluster_journals exists to refuse.
                    owner = view.hosts[host % view.num_hosts]
                    if owner != me:
                        log("orphan_assigned", dead_host=host,
                            owner=owner)
                        continue
                    dead_path = os.path.join(shared, f"band{host}.jrnl")
                    # This survivor now carries the recovery: degraded
                    # on the health surface until the orphan band flows
                    # again. The dead worker's offered-but-undurable
                    # batches keep their ORIGINAL offer timestamps so
                    # their re-drive burns budget honestly.
                    monitor.set_degraded(
                        f"adopting band {host} (hosts absent: {dead})"
                    )
                    degraded_pending = True
                    log_health()
                    for line in _read_lines(
                        os.path.join(shared, f"progress_{host}.jsonl")
                    ):
                        if line["kind"] != "offered":
                            continue
                        for band, index in line["parts"]:
                            key = (band, index)
                            victim_offers[key] = min(
                                victim_offers.get(key, line["ts"]),
                                line["ts"],
                            )
                    adopt_start = time.perf_counter()
                    tag, rows_adopted = adopt_journal(store, dead_path)
                    adopt_s = time.perf_counter() - adopt_start
                    resume_at = 0 if tag is None else tag + 1
                    orphans.append([host, resume_at])
                    # Byte coda at the adoption point: the live store
                    # (own band synced through the last durable epoch +
                    # the adopted band) must be bit-equal to the merged
                    # replay of the surviving journals — the degraded-
                    # mesh byte contract, live.
                    merged = replay_cluster_journals(
                        [os.path.join(shared, f"band{me}.jrnl"), dead_path]
                    )
                    live_digest = store_digest(store)
                    byte_equal_store = live_digest == store_digest(
                        merged.store
                    )
                    live_db = os.path.join(shared, f"coda_live_{me}.db")
                    replay_db = os.path.join(shared, f"coda_replay_{me}.db")
                    store.flush_to_sqlite(live_db)
                    merged.store.flush_to_sqlite(replay_db)
                    with open(live_db, "rb") as fa, open(replay_db, "rb") as fb:
                        byte_equal_sqlite = fa.read() == fb.read()
                    adoption_report = {
                        "dead_host": host,
                        "journal_tag": tag,
                        "resume_at": resume_at,
                        "rows_adopted": rows_adopted,
                        "adopt_s": adopt_s,
                        "byte_equal_store": byte_equal_store,
                        "byte_equal_sqlite": byte_equal_sqlite,
                    }
                    log("adopt", epoch=view.epoch, **adoption_report)

            own_done = own_next >= args.batches
            live_orphans = [o for o in orphans if o[1] < args.batches]
            if own_done and not live_orphans:
                if orphans or args.hosts == 1:
                    break
                # Own batches are done but no epoch bump arrived yet: a
                # surviving worker drains briefly in case it is about to
                # inherit a band, then exits clean.
                if drain_deadline is None:
                    drain_deadline = time.time() + args.drain_wait
                if time.time() >= drain_deadline:
                    break
                time.sleep(0.05)
                continue

            parts = []
            if not own_done:
                parts.append((me, own_next))
                own_next += 1
            for entry in live_orphans:
                parts.append((entry[0], entry[1]))
                entry[1] += 1

            columns = [
                _band_slice(
                    args, _global_batch(args, index),
                    view0_rows(args, host),
                )
                for host, index in parts
            ]
            keys = sum((c[0] for c in columns), [])
            sids = sum((c[1] for c in columns), [])
            probs = np.concatenate([c[2] for c in columns])
            offsets = np.concatenate(
                [[0]] + [np.diff(c[3]) for c in columns]
            )
            offsets = np.cumsum(offsets).astype(np.int64)
            outcomes = sum((c[4] for c in columns), [])

            offer_wall = time.time()
            log("offered", parts=parts, requests=len(keys))
            time.sleep(args.interval)
            plan = cache.plan_for(keys, sids, probs, offsets)
            driver.dispatch(plan, outcomes, now=now0 + dispatch_index)
            driver.checkpoint(dispatch_index)
            log(
                "durable", parts=parts, adopt=driver.last_adopt,
                fallbacks=fallbacks(), batch=dispatch_index,
            )
            # Feed the burn-rate monitor the batch's per-request
            # outcomes against the offer→durable objective — re-driven
            # crash-eaten parts measure from the DEAD worker's offer, so
            # recovery burns budget live exactly as the supervisor's
            # post-hoc goodput counts it.
            durable_wall = time.time()
            for i, (band, index) in enumerate(parts):
                offer_ts = victim_offers.get((band, index), offer_wall)
                outcome = (
                    "met" if durable_wall - offer_ts <= args.slo
                    else "violated"
                )
                for _ in range(len(columns[i][0])):
                    monitor.record(outcome)
            if degraded_pending and any(band != me for band, _ in parts):
                # The orphan band just flowed through a durable batch on
                # this host: the membership impairment is carried.
                monitor.clear_degraded()
                degraded_pending = False
            log_health()
            dispatch_index += 1

        log_health(force=True)  # the timeline's closing verdict
        result.update(
            ok=True,
            batches_settled=dispatch_index,
            fallbacks=fallbacks(),
            adoption=adoption_report,
            final_store_digest=None,
            final_rows=len(store),
        )
    finally:
        driver.finalize()
        try:
            from bayesian_consensus_engine_tpu.cluster.recover import (
                store_digest as _digest,
            )

            result["final_store_digest"] = _digest(store)
        except Exception:
            pass
        with open(os.path.join(shared, f"result_{me}.json"), "w") as f:
            json.dump(result, f, sort_keys=True)
        server.close()
        progress.close()
    return 0


def view0_rows(args, host: int):
    """Epoch-0 band rows of *host* — the ownership the workload is keyed
    by (orphan bands keep their original rows; only the SERVING host
    changes across epochs)."""
    from bayesian_consensus_engine_tpu.cluster.membership import MeshView

    view = MeshView(
        epoch=0,
        hosts=tuple(range(args.hosts)),
        devices_per_host=args.devices_per_host,
    )
    return view.owned_markets(host, args.markets)


# --------------------------------------------------------------------------
# supervisor
# --------------------------------------------------------------------------


def _read_lines(path: str) -> list:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail of a killed worker
    return out


class _HealthPoller:
    """Supervisor-side live poller: ``/healthz`` + ``/snapshot`` of every
    worker on a background thread through the whole soak — the proof the
    health surface answers WHILE the kill and recovery are happening
    (the worker-side progress lines make the transition sequence
    deterministic; these polls make it observable over the wire)."""

    def __init__(self, shared: str, hosts) -> None:
        import threading

        self._shared = shared
        self._hosts = list(hosts)
        self._ports: dict = {}
        self._stop = threading.Event()
        self.polls: list = []          # {ts, host, verdict, ok}
        self.snapshots: dict = {}      # host → latest parsed /snapshot
        self._thread = threading.Thread(
            target=self._loop, name="soak-health-poller", daemon=True
        )

    def start(self) -> "_HealthPoller":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _port(self, host: int):
        port = self._ports.get(host)
        if port is not None:
            return port
        path = _telemetry_path(self._shared, host)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                port = int(json.load(f)["port"])
        except (ValueError, KeyError, OSError):
            return None
        self._ports[host] = port
        return port

    def _loop(self) -> None:
        from bayesian_consensus_engine_tpu.obs.export import scrape_endpoint

        while not self._stop.is_set():
            for host in self._hosts:
                port = self._port(host)
                if port is None:
                    continue
                base = f"http://127.0.0.1:{port}"
                try:
                    # 503 /healthz = burning/degraded; scrape_endpoint
                    # parses the body either way — the body IS the answer.
                    _status, payload = scrape_endpoint(
                        base + "/healthz", timeout=0.5
                    )
                    self.polls.append(
                        {
                            "ts": time.time(), "host": host,
                            "verdict": payload.get("verdict"), "ok": True,
                        }
                    )
                    _status, snapshot = scrape_endpoint(
                        base + "/snapshot", timeout=0.5
                    )
                    self.snapshots[host] = snapshot
                except Exception:
                    # Dead worker (the kill), not-yet-bound port, slow
                    # scrape: absence of an answer is itself data.
                    self.polls.append(
                        {
                            "ts": time.time(), "host": host,
                            "verdict": None, "ok": False,
                        }
                    )
            self._stop.wait(0.05)


def run_supervisor(args) -> int:
    from bayesian_consensus_engine_tpu.cluster.membership import MeshView
    from bayesian_consensus_engine_tpu.cluster.recover import (
        replay_cluster_journals,
        store_digest,
    )
    from bayesian_consensus_engine_tpu.obs.fleet import (
        fleet_to_json,
        merge_fleet,
        snapshot_from_wire,
    )
    from bayesian_consensus_engine_tpu.obs.ledger import RunLedger
    from bayesian_consensus_engine_tpu.obs.slo import (
        SloTracker,
        goodput_from_counts,
    )

    wall_start = time.perf_counter()
    shared = args.dir or tempfile.mkdtemp(prefix="bce_kill_soak_")
    os.makedirs(shared, exist_ok=True)
    view = MeshView(
        epoch=0,
        hosts=tuple(range(args.hosts)),
        devices_per_host=args.devices_per_host,
    )
    _write_membership(shared, view)
    victim = args.hosts - 1
    survivor_hosts = [h for h in view.hosts if h != victim]

    script = os.path.abspath(__file__)
    base_cmd = [
        sys.executable, script,
        "--dir", shared,
        "--hosts", str(args.hosts),
        "--markets", str(args.markets),
        "--batches", str(args.batches),
        "--sources", str(args.sources),
        "--steps", str(args.steps),
        "--num-slots", str(args.num_slots),
        "--interval", str(args.interval),
        "--devices-per-host", str(args.devices_per_host),
        "--drain-wait", str(args.drain_wait),
    ]
    procs = {}
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    for host in view.hosts:
        procs[host] = subprocess.Popen(
            base_cmd + ["--worker", str(host)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
    poller = _HealthPoller(shared, view.hosts).start()

    def durable_lines(host):
        return [
            line
            for line in _read_lines(
                os.path.join(shared, f"progress_{host}.jsonl")
            )
            if line["kind"] == "durable"
        ]

    # Phase 1: steady stream until the victim has made kill_after
    # batches durable — then the REAL kill, mid-stream, no warning.
    deadline = time.time() + args.phase_timeout
    while len(durable_lines(victim)) < args.kill_after:
        if time.time() > deadline:
            for p in procs.values():
                p.kill()
            raise RuntimeError(
                f"victim never reached {args.kill_after} durable batches "
                f"within {args.phase_timeout}s"
            )
        if procs[victim].poll() is not None:
            raise RuntimeError("victim exited before the kill")
        time.sleep(0.03)

    kill_ts = time.time()
    os.kill(procs[victim].pid, signal.SIGKILL)
    procs[victim].wait(timeout=30)

    # Phase 2: the failure detector publishes the degraded view; the
    # survivors do the rest (replay, adopt, resume) on their own.
    degraded = view.degraded(survivor_hosts)
    _write_membership(shared, degraded, kill_ts=kill_ts)

    deadline = time.time() + args.phase_timeout
    for host in survivor_hosts:
        remaining = max(5.0, deadline - time.time())
        try:
            out, _ = procs[host].communicate(timeout=remaining)
        except subprocess.TimeoutExpired:
            procs[host].kill()
            out, _ = procs[host].communicate()
            raise RuntimeError(
                f"survivor {host} did not finish within {remaining:.0f}s:"
                f"\n{out[-4000:]}"
            )
        if procs[host].returncode != 0:
            raise RuntimeError(
                f"survivor {host} failed rc={procs[host].returncode}:"
                f"\n{out[-4000:]}"
            )
    poller.stop()

    # -- adjudication ------------------------------------------------------
    # The orphan band's OWNER (the one survivor that adopted it — the
    # worker-side deterministic assignment) is the story's protagonist;
    # other survivors just keep streaming their own bands.
    survivor_results = {}
    for host in survivor_hosts:
        with open(os.path.join(shared, f"result_{host}.json")) as f:
            survivor_results[host] = json.load(f)
        assert survivor_results[host]["ok"], survivor_results[host]
    adopters = [
        h for h, res in survivor_results.items()
        if res["adoption"] is not None
    ]
    assert len(adopters) == 1, (
        f"exactly one survivor must adopt the dead band; got {adopters}"
    )
    survivor = adopters[0]
    survivor_result = survivor_results[survivor]
    adoption = survivor_result["adoption"]

    # Offer/durable bookkeeping per (band, batch): offered at the FIRST
    # offer anywhere (the dead worker's offers count — crash-eaten
    # traffic stays in the denominator), durable at the first journal-
    # fsynced settle covering the part.
    offered: dict = {}
    durable: dict = {}
    requests_per_band: dict = {}
    for host in view.hosts:
        for line in _read_lines(
            os.path.join(shared, f"progress_{host}.jsonl")
        ):
            if line["kind"] not in ("offered", "durable"):
                continue
            for band, index in line["parts"]:
                key = (band, index)
                rows = len(list(view0_rows(args, band)))
                requests_per_band[band] = rows
                if line["kind"] == "offered":
                    offered[key] = min(
                        offered.get(key, line["ts"]), line["ts"]
                    )
                else:
                    durable[key] = min(
                        durable.get(key, line["ts"]), line["ts"]
                    )

    # Durable authority for the victim's final pre-kill batch: the
    # journal, not the progress log. A SIGKILL can land between the
    # epoch fsync and the worker's own durable line — the batch IS
    # durable (the survivor resumes after it, trusting the journal tag),
    # so count it durable no later than the kill instant rather than
    # failing the soak on a lost log write.
    dead_tag = adoption["journal_tag"]
    if dead_tag is not None:
        for index in range(dead_tag + 1):
            durable.setdefault((victim, index), kill_ts)

    tracker = SloTracker(args.slo)
    for key, offer_ts in sorted(offered.items()):
        n = requests_per_band[key[0]]
        if key not in durable:
            for _ in range(n):
                tracker.record("failed")
            continue
        latency = durable[key] - offer_ts
        for _ in range(n):
            tracker.record_latency(latency)
    snapshot = tracker.snapshot()
    goodput = goodput_from_counts(snapshot["counts"])

    # Recovery latency: kill → the first durable batch that covers an
    # orphan (dead-band) part on the survivor.
    orphan_durable = [
        ts for (band, _idx), ts in durable.items()
        if band == victim and ts > kill_ts
    ]
    assert orphan_durable, "no dead-band batch ever re-settled"
    recovery_s = min(orphan_durable) - kill_ts

    # Steady-state residency: zero fallbacks before the kill on every
    # worker, zero overall on the survivor (adoption itself rides the
    # relayout, not a rebuild).
    pre_kill_fallbacks = max(
        (
            line["fallbacks"]
            for host in view.hosts
            for line in durable_lines(host)
            if line["ts"] <= kill_ts
        ),
        default=0,
    )
    survivor_fallbacks = max(
        res["fallbacks"] for res in survivor_results.values()
    )
    adopt_modes = sorted(
        {line["adopt"] for line in durable_lines(survivor)}
    )

    # Final self-containment: the survivor's OWN journal now carries the
    # adopted band — it alone must replay to the survivor's final store.
    survivor_journal = os.path.join(shared, f"band{survivor}.jrnl")
    final_replay = replay_cluster_journals([survivor_journal])
    journal_self_contained = (
        store_digest(final_replay.store)
        == survivor_result["final_store_digest"]
    )

    # -- live health surface adjudication ----------------------------------
    # The transition timeline comes from the workers' own health lines
    # (deterministic: verdict changes, logged at the batch cadence); the
    # HTTP polls prove the /healthz endpoint answered over the wire
    # while the kill window was open. The recovery story must read
    # healthy → burning/degraded → healthy ON THE ADOPTING SURVIVOR.
    health_timeline = sorted(
        (
            {
                "ts": line["ts"], "host": host,
                "verdict": line["verdict"], "burning": line["burning"],
                "degraded": line["degraded"], "epoch": line["epoch"],
            }
            for host in view.hosts
            for line in _read_lines(
                os.path.join(shared, f"progress_{host}.jsonl")
            )
            if line["kind"] == "health"
        ),
        key=lambda e: (e["ts"], e["host"]),
    )
    survivor_verdicts = [
        e for e in health_timeline if e["host"] == survivor
    ]
    health_transitions_ok = bool(
        survivor_verdicts
        and survivor_verdicts[0]["verdict"] == "healthy"
        and any(
            e["verdict"] != "healthy" and e["ts"] >= kill_ts
            for e in survivor_verdicts
        )
        and survivor_verdicts[-1]["verdict"] == "healthy"
    )
    polls_ok = [p for p in poller.polls if p["ok"]]
    healthz_poll_ok = len(polls_ok) > 0
    # Condensed over-the-wire verdict sequence per host (transitions
    # only) — the liveness record beside the deterministic timeline.
    poll_transitions: list = []
    last_polled: dict = {}
    for p in poller.polls:
        if not p["ok"]:
            continue
        if last_polled.get(p["host"]) != p["verdict"]:
            last_polled[p["host"]] = p["verdict"]
            poll_transitions.append(
                {"ts": p["ts"], "host": p["host"], "verdict": p["verdict"]}
            )

    # Fleet merge over the scraped survivor snapshots: the degraded
    # membership shows the dead host as an EXPLICIT hosts_absent entry
    # (never silently missing series), and the fold must be
    # order-independent — two observers, identical bytes.
    fleet_snaps = [
        snapshot_from_wire(poller.snapshots[host])
        for host in survivor_hosts
        if host in poller.snapshots
    ]
    fleet_summary = None
    fleet_ok = False
    if fleet_snaps:
        fleet_view = merge_fleet(fleet_snaps, expected_hosts=view.hosts)
        fleet_ok = bool(
            fleet_view["hosts_absent"] == [victim]
            and fleet_view["epoch"] == degraded.epoch
            and fleet_to_json(fleet_view) == fleet_to_json(
                merge_fleet(
                    list(reversed(fleet_snaps)),
                    expected_hosts=view.hosts,
                )
            )
        )
        fleet_summary = {
            "epoch": fleet_view["epoch"],
            "hosts": fleet_view["hosts"],
            "hosts_absent": fleet_view["hosts_absent"],
            "host_epochs": fleet_view["host_epochs"],
            "deterministic": fleet_ok,
        }

    wall_s = time.perf_counter() - wall_start
    every_batch_durable = all(
        (band, index) in durable
        for band in view.hosts
        for index in range(args.batches)
    )
    payload = {
        "ok": bool(
            adoption["byte_equal_store"]
            and adoption["byte_equal_sqlite"]
            and journal_self_contained
            and every_batch_durable
            and pre_kill_fallbacks == 0
            and survivor_fallbacks == 0
            and health_transitions_ok
            and healthz_poll_ok
            and fleet_ok
        ),
        "hosts": args.hosts,
        "killed_host": victim,
        "kill_after_durable": args.kill_after,
        "batches_per_band": args.batches,
        "requests_offered": snapshot["offered"],
        "goodput_within_slo": goodput,
        "slo": snapshot,
        "recovery_s": recovery_s,
        "adopt_s": adoption["adopt_s"],
        "rows_adopted": adoption["rows_adopted"],
        "dead_journal_tag": adoption["journal_tag"],
        "resident_fallbacks_steady": pre_kill_fallbacks,
        "resident_fallbacks_survivor": survivor_fallbacks,
        "survivor_adopt_modes": adopt_modes,
        "byte_equal_store": adoption["byte_equal_store"],
        "byte_equal_sqlite": adoption["byte_equal_sqlite"],
        "survivor_journal_self_contained": journal_self_contained,
        "every_batch_durable": every_batch_durable,
        "health_timeline": health_timeline,
        "health_transitions_ok": health_transitions_ok,
        "healthz_polls": len(poller.polls),
        "healthz_poll_ok": healthz_poll_ok,
        "healthz_poll_transitions": poll_transitions,
        "fleet": fleet_summary,
        "wall_s": wall_s,
    }

    if args.ledger:
        ledger = RunLedger(args.ledger, backend="cpu")
        ledger.record(
            "soak.kill.recovery", value=round(recovery_s, 4), unit="s",
            extras={
                "slo": snapshot,
                "recovery_s": recovery_s,
                "goodput_within_slo": goodput,
                "resident_fallbacks": survivor_fallbacks,
            },
        )
        ledger.close()

    if not args.json:
        print(
            f"kill soak: {args.hosts} hosts, killed host {victim} after "
            f"{args.kill_after} durable batches"
        )
        print(
            f"  recovered goodput_within_slo = {goodput:.3f} over "
            f"{snapshot['offered']} offered requests "
            f"(counts {snapshot['counts']})"
        )
        print(
            f"  recovery_s = {recovery_s:.3f}  adopt_s = "
            f"{adoption['adopt_s']:.3f}  rows_adopted = "
            f"{adoption['rows_adopted']}"
        )
        print(
            f"  byte coda: store={adoption['byte_equal_store']} "
            f"sqlite={adoption['byte_equal_sqlite']} "
            f"self_contained={journal_self_contained} "
            f"fallbacks={survivor_fallbacks}"
        )
        transitions = " -> ".join(
            e["verdict"] for e in health_timeline if e["host"] == survivor
        )
        print(
            f"  health: {transitions or '(none)'} "
            f"(transitions_ok={health_transitions_ok}, "
            f"{len(poller.polls)} /healthz polls, "
            f"fleet absent={fleet_summary['hosts_absent'] if fleet_summary else '?'})"
        )
    print(json.dumps(payload, sort_keys=True))
    return 0 if payload["ok"] else 1


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker", type=int, default=None,
                        help="internal: run as worker RANK")
    parser.add_argument("--dir", default=None,
                        help="shared soak directory (default: mkdtemp)")
    parser.add_argument("--hosts", type=int, default=2)
    parser.add_argument("--markets", type=int, default=64,
                        help="GLOBAL market count (bands split it)")
    parser.add_argument("--batches", type=int, default=12,
                        help="batches per band")
    parser.add_argument("--kill-after", type=int, default=3,
                        help="victim durable batches before the SIGKILL")
    parser.add_argument("--sources", type=int, default=40)
    parser.add_argument("--steps", type=int, default=1)
    parser.add_argument("--num-slots", type=int, default=8)
    parser.add_argument("--interval", type=float, default=0.15,
                        help="per-batch arrival pacing, seconds")
    parser.add_argument("--slo", type=float, default=0.5,
                        help="submit→durable objective, seconds")
    parser.add_argument("--devices-per-host", type=int, default=2)
    parser.add_argument("--drain-wait", type=float, default=8.0)
    parser.add_argument("--phase-timeout", type=float, default=240.0)
    parser.add_argument("--json", action="store_true",
                        help="suppress the prose; emit only the JSON line")
    parser.add_argument("--ledger",
                        help="append obs run-ledger records here "
                             "(render: bce-tpu stats)")
    args = parser.parse_args()
    if args.hosts < 2:
        parser.error("--hosts must be >= 2 (someone has to die)")
    if args.worker is not None:
        return run_worker(args)
    return run_supervisor(args)


if __name__ == "__main__":
    sys.exit(main())
