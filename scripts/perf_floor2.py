"""Floor follow-up: isolate the ~100 ms fixed per-dispatch cost.

perf_floor.py showed TOTAL wall time for the compact loop is ~constant
(~102-132 ms) for steps in {25, 100, 400} at 1M x 16 — i.e. the "1.1 ms/step
floor" is fixed dispatch+fence overhead divided by the step count, and the
marginal kernel cost is ~0.08 ms/step. This script pins:

  1. tunnel RTT            — a 1-element add, fenced: pure dispatch+fetch
  2. steps=1 loop total    — fixed cost including our operand set
  3. single-dispatch curve — steps in {100, 400, 1600}: linear fit gives
                             (fixed, per-step) directly
  4. chained dispatches    — D back-to-back loop calls threading the donated
                             state, ONE fence at the end: if the tunnel
                             queues asynchronously, D dispatches pay ~one
                             RTT, and the production amortised rate is the
                             kernel rate
"""

import json
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel import (
    build_compact_cycle_loop,
    init_compact_state,
)


def fence(x):
    return float(jnp.ravel(x)[0])


M, K = 1_000_448, 16


def workload():
    kp, km, ko = jax.random.split(jax.random.PRNGKey(0), 3)
    probs = jax.random.uniform(kp, (K, M), dtype=jnp.float32)
    mask = jax.random.uniform(km, (K, M)) < 0.9
    outcome = jax.random.uniform(ko, (M,)) < 0.5
    return probs, mask, outcome


def main():
    results = {}

    # 1. pure tunnel RTT
    tiny = jax.jit(lambda x: x + 1.0)
    a = jnp.zeros((8,), jnp.float32)
    fence(tiny(a))
    best = min(
        (lambda t0: (fence(tiny(a)), time.perf_counter() - t0)[1])(
            time.perf_counter()
        )
        for _ in range(5)
    )
    results["tiny_dispatch_rtt_ms"] = round(best * 1e3, 2)

    probs, mask, outcome = workload()
    loop = build_compact_cycle_loop(mesh=None, donate=True)

    def fresh():
        state = init_compact_state(M, K)
        fence(state.updated_days)
        return state

    # 2-3. single-dispatch totals
    totals = {}
    for steps in (1, 100, 400, 1600):
        _s, c = loop(probs, mask, outcome, fresh(), jnp.float32(1.0), steps)
        fence(c)
        best = float("inf")
        for _ in range(3):
            st = fresh()
            t0 = time.perf_counter()
            _s, c = loop(probs, mask, outcome, st, jnp.float32(1.0), steps)
            fence(c)
            best = min(best, time.perf_counter() - t0)
        totals[str(steps)] = round(best * 1e3, 2)
    results["single_dispatch_total_ms"] = totals
    # linear fit on (400, 1600)
    per_step = (totals["1600"] - totals["400"]) / (1600 - 400)
    results["marginal_kernel_ms_per_step"] = round(per_step, 4)
    results["implied_fixed_overhead_ms"] = round(
        totals["400"] - 400 * per_step, 2
    )

    # 4. chained dispatches, one fence
    for dispatches, steps in ((10, 100), (4, 400)):
        state = fresh()
        _s, c = loop(probs, mask, outcome, state, jnp.float32(1.0), steps)
        fence(c)  # warm
        best = float("inf")
        for _ in range(3):
            state = fresh()
            t0 = time.perf_counter()
            for d in range(dispatches):
                state, c = loop(
                    probs, mask, outcome, state,
                    jnp.float32(1.0 + d * steps), steps,
                )
            fence(c)
            best = min(best, time.perf_counter() - t0)
        key = f"chained_{dispatches}x{steps}_ms_per_step"
        results[key] = round(best / (dispatches * steps) * 1e3, 4)

    results["implied_cycles_per_sec_at_1600"] = round(
        1600 / (totals["1600"] / 1e3), 1
    )
    print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
