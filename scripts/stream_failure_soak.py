"""Failure-injection soak for the streamed settlement service at scale.

Streams ≥1M (source, market) rows through ``settle_stream`` with rolling
background checkpoints, then injects a REAL mid-stream checkpoint failure
— an exclusive SQLite lock held by a second connection, the
operationally-common stand-in for disk-full/locked-volume (the rollback
path is failure-agnostic; tests/test_overlap.py pins the injected-
exception variant) — and proves the service contract at scale:

  1. the failure surfaces at the next flush join (``database is locked``
     after the native writer's busy timeout);
  2. the flush bookkeeping rolled back (failed rows re-marked dirty);
  3. NO settled batch is lost: after the lock clears, one caller retry
     flush produces a checkpoint holding exactly the store's live rows.

Run from the repo root:

    python scripts/stream_failure_soak.py [--markets 60000] [--batches 10]
                                          [--fail-at 5] [--mesh] [--steps 1]
                                          [--ledger soak.jsonl]

CPU by default (the contract is host-side); ``--tpu`` leaves the default
backend alone. ``--mesh`` streams sharded — through the round-7 RESIDENT
session by default, so the soak's failure/rollback/retry contract covers
the persistent-session service shape; ``--per-batch-session`` restores
the legacy one-session-per-batch stream for A/B. ``--ledger`` routes the captures through the obs run
ledger (loadavg/min-of-N attribution, same as bench legs — render with
``bce-tpu stats``; ROADMAP obs follow-up). Exit code 0 iff every
assertion holds.
"""

from __future__ import annotations

import argparse
import os
import sqlite3
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--markets", type=int, default=60_000)
    parser.add_argument("--batches", type=int, default=10)
    parser.add_argument("--fail-at", type=int, default=5,
                        help="batch index whose checkpoint hits the lock")
    parser.add_argument("--steps", type=int, default=1)
    parser.add_argument("--checkpoint-every", type=int, default=2)
    parser.add_argument("--sources", type=int, default=3_000,
                        help="source-id universe (rows ≈ markets × ~2.1)")
    parser.add_argument("--mesh", action="store_true",
                        help="stream sharded over an 8-device CPU mesh")
    parser.add_argument("--per-batch-session", action="store_true",
                        help="with --mesh: the legacy one-session-per-"
                             "batch shape (default: the round-7 resident "
                             "session held across batches)")
    parser.add_argument("--tpu", action="store_true",
                        help="keep the default backend (else force CPU)")
    parser.add_argument("--ledger",
                        help="append obs run-ledger records here "
                             "(render: bce-tpu stats)")
    args = parser.parse_args()

    if not args.tpu:
        # Old JAX has no jax_num_cpu_devices option; the XLA flag only
        # works if set before jax's first import in this process.
        flag = "--xla_force_host_platform_device_count=8"
        if flag not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag
            ).strip()

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except AttributeError:
            pass  # old JAX: the XLA_FLAGS fallback above covers it

    import numpy as np

    from bayesian_consensus_engine_tpu.obs.ledger import RunLedger
    from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
    from bayesian_consensus_engine_tpu.pipeline import settle_stream
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    ledger = (
        RunLedger(args.ledger, backend=None if args.tpu else "cpu")
        if args.ledger else None
    )

    def record(leg, value=None, unit=None, extras=None):
        if ledger is not None:
            ledger.record(f"soak.stream_failure.{leg}", value=value,
                          unit=unit, extras=extras)

    rng = np.random.default_rng(11)
    lock_holder: dict = {}

    def day_batch(day: int):
        """Columnar (keys, source_ids, probs, offsets) + outcomes."""
        counts = rng.poisson(1.2, args.markets) + 1
        total = int(counts.sum())
        keys = [f"d{day}-m{m}" for m in range(args.markets)]
        sids = [f"s{v}" for v in rng.integers(0, args.sources, total)]
        probs = rng.random(total)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        outcomes = (rng.random(args.markets) < 0.5).tolist()
        return (keys, sids, probs, offsets), outcomes

    tmp = tempfile.mkdtemp()
    db = os.path.join(tmp, "soak.db")
    store = TensorReliabilityStore()

    def batches():
        for day in range(args.batches):
            yield day_batch(day)
            if day == args.fail_at:
                # The NEXT flush's background write must hit a held lock:
                # take it now, from a second connection, like an external
                # process pinning the file. This generator runs on the
                # PlanPrefetcher's worker thread; the main thread releases
                # the lock later, so the connection must be thread-free.
                # timeout=300: a rolling background checkpoint may hold the
                # write lock right now — the injector must WAIT for it, not
                # die with its own spurious "database is locked".
                conn = sqlite3.connect(
                    db, check_same_thread=False, timeout=300
                )
                conn.execute("PRAGMA locking_mode=EXCLUSIVE")
                conn.execute("BEGIN EXCLUSIVE")
                lock_holder["conn"] = conn
                print(f"  [inject] exclusive lock taken after batch {day}")

    mesh = make_mesh() if args.mesh else None
    stats: list = []
    settled = 0
    start = time.perf_counter()
    failure = None
    try:
        for _result in settle_stream(
            store, batches(), steps=args.steps, now=21_500.0, db_path=db,
            checkpoint_every=args.checkpoint_every, columnar=True,
            stats=stats, mesh=mesh,
            resident_session=not args.per_batch_session,
        ):
            settled += 1
            print(f"  batch {settled - 1} settled "
                  f"({len(store):,} store rows)")
    except Exception as exc:  # the injected failure
        failure = exc
    elapsed = time.perf_counter() - start

    assert failure is not None, "injected lock never failed a checkpoint"
    assert "locked" in str(failure), failure
    print(f"failure surfaced after {settled} settled batches in "
          f"{elapsed:.1f}s: {type(failure).__name__}: {failure}")
    record("stream_to_failure_s", value=round(elapsed, 3), unit="s",
           extras={"settled_batches": settled,
                   "failure": f"{type(failure).__name__}: {failure}",
                   "mesh": bool(args.mesh),
                   "resident_session": bool(
                       args.mesh and not args.per_batch_session
                   )})

    used = len(store)
    dirty = int(store._dirty[:used].sum())
    assert dirty > 0, "rollback did not re-mark failed rows dirty"
    print(f"rollback OK: {dirty:,} rows re-marked dirty of {used:,}")

    lock_holder["conn"].rollback()
    lock_holder["conn"].close()
    store.sync()
    t0 = time.perf_counter()
    store.flush_to_sqlite(db)
    retry_s = time.perf_counter() - t0
    print(f"retry flush re-covered in {retry_s:.1f}s")
    record("retry_flush_s", value=round(retry_s, 3), unit="s",
           extras={"rows_dirty_at_retry": dirty, "store_rows": used})

    live = store.list_sources()
    with sqlite3.connect(db) as conn:
        rows = conn.execute(
            "SELECT source_id, market_id, reliability, confidence"
            " FROM sources ORDER BY source_id, market_id"
        ).fetchall()
    assert len(rows) == len(live), (len(rows), len(live))
    for rec, row in zip(live, rows):
        assert (rec.source_id, rec.market_id) == (row[0], row[1])
        assert rec.reliability == row[2] and rec.confidence == row[3]
    assert len(rows) >= 1_000_000, (
        f"soak must cover ≥1M rows, got {len(rows):,} — raise --markets"
    )
    print(f"checkpoint complete: {len(rows):,} rows byte-equal to the "
          f"store's live records; no settled batch lost")
    record("checkpoint_rows", value=float(len(rows)), unit="rows",
           extras={"byte_equal": True})
    if ledger is not None:
        ledger.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
