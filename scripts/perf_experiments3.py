"""Perf experiments round 3: Pallas-on-hardware + the large-K (10k-source) regime.

Answers two VERDICT questions:
  1. Does the hand-fused Pallas cycle beat the XLA-fused loop at 1M x 16 on a
     real v5e chip? (round 1 never compiled it on hardware)
  2. What does the K=10k regime (BASELINE config #5's source scale) run at on
     one chip — slot-major XLA loop vs the chunked ring cycle?

Run: python scripts/perf_experiments3.py
"""

from __future__ import annotations

import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp

from bench import build_workload

STEPS = 100


def time_loop(fn, *args, trials=3):
    out = fn(*args)
    float(jax.tree_util.tree_leaves(out)[-1].reshape(-1)[0])
    best = float("inf")
    for _ in range(trials):
        start = time.perf_counter()
        out = fn(*args)
        float(jax.tree_util.tree_leaves(out)[-1].reshape(-1)[0])
        best = min(best, time.perf_counter() - start)
    return best


def report(name, secs, nbytes, steps=STEPS):
    per = secs / steps
    print(
        f"{name:28s}: {steps / secs:10.1f} cycles/sec  ({per * 1e3:.3f} ms/cycle, "
        f"{nbytes / per / 1e9:.0f} GB/s effective)",
        flush=True,
    )


def pallas_1m16():
    """Pallas fused cycle vs XLA loop at 1M x 16, both as in-jit loops."""
    from bayesian_consensus_engine_tpu.ops.pallas_cycle import (
        SlotMajorState,
        build_pallas_cycle,
    )
    from bayesian_consensus_engine_tpu.parallel import (
        MarketBlockState,
        build_cycle_loop,
        init_block_state,
    )

    M, K = 1_048_576, 16
    dtype = jnp.float32
    probs, mask, outcome, _ = build_workload(jax.random.PRNGKey(0), M, K, dtype)
    probs, mask = probs.T, mask.T
    mib = 1024 * 1024
    # Per cycle: read probs+mask+4 state (6*64 MiB) + outcome (4 MiB) +
    # write 4 state + 3 outputs (~4*64 + 12 MiB) -> ~652 MiB.
    cycle_bytes = (64 * 6 + 4 + 64 * 4 + 12) * mib

    # XLA loop (the current bench path).
    loop = build_cycle_loop(mesh=None, slot_major=True, donate=False)
    state = MarketBlockState(*(x.T for x in init_block_state(M, K, dtype=dtype)))
    secs = time_loop(
        lambda: loop(probs, mask, outcome, state, jnp.asarray(1.0, dtype), STEPS)
    )
    report("xla loop 1Mx16", secs, cycle_bytes)

    # Pallas fused cycle, wrapped in an in-jit fori_loop for the same
    # dispatch-amortised shape.
    for tile in (256, 512, 1024, 2048):
        call = build_pallas_cycle(M, K, tile_markets=tile)
        f32 = lambda x: jnp.asarray(x, jnp.float32)
        pprobs, pmask = f32(probs), f32(mask)
        poutcome = f32(outcome)[None, :]
        pstate = SlotMajorState(
            jnp.full((K, M), 0.5, jnp.float32),
            jnp.full((K, M), 0.25, jnp.float32),
            jnp.zeros((K, M), jnp.float32),
            jnp.zeros((K, M), jnp.float32),
        )

        def ploop_fn(probs, mask, outcome, state):
            def body(i, carry):
                state, _ = carry
                state, consensus, _, _ = call(
                    probs, mask, outcome, state, 1.0 + i
                )
                return state, consensus

            init = jnp.zeros((1, M), jnp.float32)
            return jax.lax.fori_loop(0, STEPS, body, (state, init))

        ploop = jax.jit(ploop_fn)
        try:
            secs = time_loop(lambda: ploop(pprobs, pmask, poutcome, pstate))
        except Exception as e:  # noqa: BLE001
            print(f"pallas tile={tile}: FAILED {type(e).__name__}: {e}")
            continue
        report(f"pallas loop 1Mx16 t={tile}", secs, cycle_bytes)


def large_k():
    """K=10k regime on one chip: XLA loop vs ring cycle (1-device mesh)."""
    from bayesian_consensus_engine_tpu.parallel import (
        MarketBlockState,
        build_cycle_loop,
        init_block_state,
    )
    from bayesian_consensus_engine_tpu.parallel.ring import build_ring_cycle
    from jax.sharding import Mesh
    import numpy as np

    M, K = 16_384, 10_000
    steps = 20
    dtype = jnp.float32
    probs, mask, outcome, _ = build_workload(jax.random.PRNGKey(1), M, K, dtype)
    # Per cycle bytes: 6 block reads + 4 block writes + small per-market IO.
    blk = M * K * 4
    cycle_bytes = 10 * blk

    # Slot-major XLA loop (K on sublanes, M on lanes).
    loop = build_cycle_loop(mesh=None, slot_major=True, donate=False)
    state = MarketBlockState(*(x.T for x in init_block_state(M, K, dtype=dtype)))
    tp, tm = probs.T, mask.T
    secs = time_loop(
        lambda: loop(tp, tm, outcome, state, jnp.asarray(1.0, dtype), steps)
    )
    report("xla loop 16kx10k slotmaj", secs, cycle_bytes, steps)

    # Market-major XLA loop (K on lanes — at K=10k the reduction axis is wide).
    loop_mm = build_cycle_loop(mesh=None, slot_major=False, donate=False)
    state = init_block_state(M, K, dtype=dtype)
    secs = time_loop(
        lambda: loop_mm(probs, mask, outcome, state, jnp.asarray(1.0, dtype), steps)
    )
    report("xla loop 16kx10k mktmaj", secs, cycle_bytes, steps)

    # Ring cycle (single-device mesh; chunked local reduction).
    devs = np.array(jax.devices()[:1]).reshape(1, 1)
    mesh = Mesh(devs, ("markets", "sources"))
    for chunk in (512, 2048, None):
        ring = build_ring_cycle(mesh, chunk_slots=chunk, donate=False)
        state = init_block_state(M, K, dtype=dtype)
        try:
            secs = time_loop(
                lambda: ring(probs, mask, outcome, state, jnp.asarray(1.0, dtype)),
            )
        except Exception as e:  # noqa: BLE001
            print(f"ring chunk={chunk}: FAILED {type(e).__name__}: {e}")
            continue
        # Single dispatch per cycle here (no loop wrapper) — report per call.
        report(f"ring 16kx10k chunk={chunk}", secs, cycle_bytes, 1)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "pallas"):
        pallas_1m16()
    if which in ("all", "largek"):
        large_k()
