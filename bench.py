"""Driver benchmark: consensus + reliability-update cycles/sec at 1M × 10k.

One cycle = the full pipeline over a 1M-market batch with signals from a
10k-source universe (16 source slots per market): read-time decay →
reliability-weighted consensus → outcome correctness → capped reliability/
confidence update — the batched equivalent of the reference's
``compute_all_consensus`` + per-pair ``update_reliability`` sweep
(reference: market.py:200-221, reliability.py:185-231).

Measurement notes (all learned the hard way on this host):
  * the timed loop runs INSIDE one jit (``build_cycle_loop`` → lax.fori_loop)
    — per-dispatch overhead through the axon TPU tunnel is ~4 ms, 3× the
    actual 1M×16 cycle compute, so chained host dispatches measure the tunnel
  * state is slot-major (K, M): markets on the 128-lane minor dim (~25%
    faster than (M, K) with K=16)
  * the markets axis is padded to a lane multiple (1M → 1,000,448 = 7816·128,
    mask=0 pads): the ragged tail tile otherwise costs ~20% of throughput
  * on the axon tunnel ``block_until_ready`` does NOT force remote execution
    — every timing fence is a scalar value fetch

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "cycles/sec", "vs_baseline": N}

``vs_baseline`` is against the reference implementation measured on this
host's CPU (scripts/measure_reference_baseline.py): 1983.8 markets/sec at
16 sources/market → 0.0019838 1M-cycles/sec. Re-run that script to refresh.
"""

import json
import time

# Measured 2026-07-29 via scripts/measure_reference_baseline.py (1000 markets,
# 16 sources/market, in-memory SQLite, warm reliability table).
REFERENCE_BASELINE_CYCLES_PER_SEC = 0.0019838

NUM_MARKETS = 1_000_000
SLOTS_PER_MARKET = 16
SOURCE_UNIVERSE = 10_000
TIMED_STEPS = 100


def build_workload(key, num_markets, slots, dtype):
    import jax
    import jax.numpy as jnp

    k_probs, k_mask, k_outcome, k_src = jax.random.split(key, 4)
    probs = jax.random.uniform(k_probs, (num_markets, slots), dtype=dtype)
    # ~90% slot occupancy: not every source signals every market.
    mask = jax.random.uniform(k_mask, (num_markets, slots)) < 0.9
    outcome = jax.random.uniform(k_outcome, (num_markets,)) < 0.5
    # Slot → source-universe assignment (pair identity; carried for realism,
    # not consumed by the cycle math, which is per-(market, slot)).
    src_idx = jax.random.randint(
        k_src, (num_markets, slots), 0, SOURCE_UNIVERSE, dtype=jnp.int32
    )
    return probs, mask, outcome, src_idx


def run(num_markets=NUM_MARKETS, slots=SLOTS_PER_MARKET, timed_steps=TIMED_STEPS):
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import (
        MarketBlockState,
        build_cycle_loop,
        init_block_state,
        make_mesh,
        pad_markets,
    )
    from bayesian_consensus_engine_tpu.parallel.mesh import (
        MARKETS_AXIS,
        SOURCES_AXIS,
    )

    devices = jax.devices()
    # All devices on the markets axis: the reductions stay device-local and
    # the cycle needs zero communication (mesh.py default policy).
    mesh = make_mesh() if len(devices) > 1 else None
    dtype = jnp.float32

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        block_sharding = NamedSharding(mesh, P(SOURCES_AXIS, MARKETS_AXIS))
        market_sharding = NamedSharding(mesh, P(MARKETS_AXIS))
    else:
        block_sharding = market_sharding = None

    probs, mask, outcome, _src_idx = build_workload(
        jax.random.PRNGKey(0), num_markets, slots, dtype
    )
    # Slot-major layout: (K, M), markets on lanes — padded to a lane multiple
    # (pads carry mask=0: zero weight, NaN consensus, cold state).
    probs, mask = probs.T, mask.T
    lane_multiple = 128 * (mesh.shape[MARKETS_AXIS] if mesh is not None else 1)
    probs, mask, outcome, _, padded_total = pad_markets(
        probs, mask, outcome, state=None, multiple=lane_multiple
    )
    if mesh is not None:
        probs = jax.device_put(probs, block_sharding)
        mask = jax.device_put(mask, block_sharding)
        outcome = jax.device_put(outcome, market_sharding)

    def fresh_state():
        """Slot-major state, pre-sharded, fully materialised (fenced)."""
        state = MarketBlockState(
            *(x.T for x in init_block_state(padded_total, slots, dtype=dtype))
        )
        if mesh is not None:
            state = MarketBlockState(
                *(jax.device_put(x, block_sharding) for x in state)
            )
        float(state.reliability[0, 0])  # fence: construction outside the timer
        return state

    loop = build_cycle_loop(mesh, slot_major=True, donate=True)

    # Warmup: compile + one full run (fenced by a value fetch — see notes).
    state, consensus = loop(
        probs, mask, outcome, fresh_state(), jnp.asarray(1.0, dtype), timed_steps
    )
    float(consensus[0])

    best = float("inf")
    for _trial in range(3):
        state_in = fresh_state()
        start = time.perf_counter()
        state, consensus = loop(
            probs, mask, outcome, state_in, jnp.asarray(10.0, dtype), timed_steps
        )
        float(consensus[0])  # fences the whole in-jit loop
        best = min(best, (time.perf_counter() - start) / timed_steps)

    cycles_per_sec = 1.0 / best
    return {
        "metric": (
            f"consensus+reliability-update cycles/sec at "
            f"{num_markets / 1_000_000:g}M markets x {SOURCE_UNIVERSE // 1000}k sources"
        ),
        "value": round(cycles_per_sec, 4),
        "unit": "cycles/sec",
        "vs_baseline": round(cycles_per_sec / REFERENCE_BASELINE_CYCLES_PER_SEC, 1),
    }


if __name__ == "__main__":
    print(json.dumps(run()))
