"""Driver benchmark: consensus + reliability-update cycles/sec at 1M × 10k.

One cycle = the full pipeline over a 1M-market batch with signals from a
10k-source universe (16 source slots per market): read-time decay →
reliability-weighted consensus → outcome correctness → capped reliability/
confidence update — the batched equivalent of the reference's
``compute_all_consensus`` + per-pair ``update_reliability`` sweep
(reference: market.py:200-221, reliability.py:185-231).

State stays resident in HBM across cycles (buffer donation); on multi-device
hosts the blocks shard over a (markets, sources) mesh via shard_map.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "cycles/sec", "vs_baseline": N}

``vs_baseline`` is against the reference implementation measured on this
host's CPU (scripts/measure_reference_baseline.py): 1983.8 markets/sec at
16 sources/market → 0.0019838 1M-cycles/sec. Re-run that script to refresh.
"""

import json
import time

# Measured 2026-07-29 via scripts/measure_reference_baseline.py (1000 markets,
# 16 sources/market, in-memory SQLite, warm reliability table).
REFERENCE_BASELINE_CYCLES_PER_SEC = 0.0019838

NUM_MARKETS = 1_000_000
SLOTS_PER_MARKET = 16
SOURCE_UNIVERSE = 10_000
TIMED_STEPS = 30


def build_workload(key, num_markets, slots, dtype):
    import jax
    import jax.numpy as jnp

    k_probs, k_mask, k_outcome, k_src = jax.random.split(key, 4)
    probs = jax.random.uniform(k_probs, (num_markets, slots), dtype=dtype)
    # ~90% slot occupancy: not every source signals every market.
    mask = jax.random.uniform(k_mask, (num_markets, slots)) < 0.9
    outcome = jax.random.uniform(k_outcome, (num_markets,)) < 0.5
    # Slot → source-universe assignment (pair identity; carried for realism,
    # not consumed by the cycle math, which is per-(market, slot)).
    src_idx = jax.random.randint(
        k_src, (num_markets, slots), 0, SOURCE_UNIVERSE, dtype=jnp.int32
    )
    return probs, mask, outcome, src_idx


def run(num_markets=NUM_MARKETS, slots=SLOTS_PER_MARKET, timed_steps=TIMED_STEPS):
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import (
        MarketBlockState,
        build_cycle,
        init_block_state,
        make_mesh,
        shard_block,
        shard_market,
    )

    devices = jax.devices()
    mesh = make_mesh() if len(devices) > 1 else None
    dtype = jnp.float32

    probs, mask, outcome, _src_idx = build_workload(
        jax.random.PRNGKey(0), num_markets, slots, dtype
    )
    state = init_block_state(num_markets, slots, dtype=dtype)

    if mesh is not None:
        probs, mask = shard_block(probs, mesh), shard_block(mask, mesh)
        outcome = shard_market(outcome, mesh)
        state = MarketBlockState(*(shard_block(x, mesh) for x in state))

    cycle = build_cycle(mesh, donate=True)

    # Warmup: compile + first executions. NOTE: on the axon TPU tunnel,
    # block_until_ready does NOT force remote execution — only a value fetch
    # does — so every timing fence below is a scalar fetch.
    result = cycle(probs, mask, outcome, state, jnp.asarray(1.0, dtype))
    result = cycle(probs, mask, outcome, result.state, jnp.asarray(2.0, dtype))
    float(result.consensus[0])

    start = time.perf_counter()
    for step in range(timed_steps):
        result = cycle(
            probs, mask, outcome, result.state, jnp.asarray(3.0 + step, dtype)
        )
    float(result.consensus[0])  # fences the whole chain
    elapsed = time.perf_counter() - start

    cycles_per_sec = timed_steps / elapsed
    return {
        "metric": (
            f"consensus+reliability-update cycles/sec at "
            f"{num_markets / 1_000_000:g}M markets x {SOURCE_UNIVERSE // 1000}k sources"
        ),
        "value": round(cycles_per_sec, 4),
        "unit": "cycles/sec",
        "vs_baseline": round(cycles_per_sec / REFERENCE_BASELINE_CYCLES_PER_SEC, 1),
    }


if __name__ == "__main__":
    print(json.dumps(run()))
