"""Driver benchmark: consensus + reliability-update cycles/sec.

Headline workload: a 1M-market batch with 16 source slots per market drawn
from a 10k-source universe — the slot-packed representation of the sparse
(markets × sources) signal matrix (not every source signals every market;
~90% slot occupancy). One cycle = read-time decay → reliability-weighted
consensus → outcome correctness → capped reliability/confidence update —
the batched equivalent of the reference's ``compute_all_consensus`` +
per-pair ``update_reliability`` sweep (reference: market.py:200-221,
reliability.py:185-231).

The JSON line also carries the **large-K regime** (BASELINE config #5's
source scale, 16k markets × 10k slots), the **north-star band** (125,056
markets × 10k slots — the exact per-chip slice of BASELINE.json's 1M×10k
dense metric on a v5e-8, ~13.8 GB HBM working set), the hand-fused Pallas
kernel's number at 1M×16 (XLA fusion wins — kept for the record), the
full ingest→settle→flush pipeline at 1M markets, and the
**stable-topology stream** (``e2e_stream_stable_topology``): the daily
re-settlement steady state A/B'd with ``settle_stream(reuse_plans=)``
off vs on — the delta-ingest fast path that fingerprints each batch's
topology and refreshes only the probability columns on a hit. Its
per-batch ``stats`` dicts add ``plan_reused`` to the stream's
``{"batch", "markets", "plan_wait_s", "settle_dispatch_s",
"checkpoint_s"}`` keys, and the leg reports the summed
``plan_reuse_hits``/``plan_reuse_misses`` plus the off/on
``reuse_speedup``.

Harness (round 4): the round-3 driver bench died with rc=1 because a
single hung ``jax.devices()`` during TPU-tunnel bring-up took the whole
process with it. This file is now an ORCHESTRATOR: a pure-Python parent
(no jax import on the orchestration path) runs every leg as a subprocess
(``python bench.py --leg NAME``) with a hard wall-clock timeout and a
process-group kill, so no single hang or crash can sink the run:

  * backend bring-up is a tiny-jit PROBE subprocess, retried with
    exponential backoff while ``BCE_BENCH_PROBE_BUDGET_S`` (default 600 s)
    lasts — a transient tunnel outage is ridden out, a permanent one is
    reported, never hung on;
  * if the TPU never comes up (or every device headline leg fails), the
    headline legs re-run on the CPU backend at reduced step counts —
    clearly marked ``degraded`` — so the driver always records a real
    measured number against the (CPU) reference baseline;
  * every leg result lands in the final JSON as it completes: a late leg
    timing out costs that leg only (``"failed: timeout..."`` in extras),
    never the already-measured ones;
  * exit code is 0 whenever ANY headline leg produced a number; the JSON
    line is printed even when none did (with ``value: 0.0`` and the
    ``degraded`` reasons).

Environment knobs: ``BCE_BENCH_BUDGET_S`` global wall-clock budget
(default 4800 s; priority-ordered legs — the headline is secured first);
``BCE_BENCH_PROBE_BUDGET_S`` bring-up retry budget; ``BCE_JAX_CACHE``
persistent-compile-cache dir (DEFAULT ON at ``<repo>/.jax_cache`` —
``off``/``0`` disables; the bench compiles ~12 loop programs and on a
loaded host each costs tens of seconds of XLA time, the largest share of
a healthy run's wall clock); ``--fast`` shrinks every shape for harness
self-tests (tests/test_bench_harness.py).

Measurement notes (all learned the hard way on this host):
  * every timed loop runs INSIDE one jit (``lax.fori_loop``) — per-dispatch
    overhead through the axon TPU tunnel is ~4 ms at 1M×16 and grows with
    operand sizes (~70 ms at 16k×10k), so chained host dispatches measure
    the tunnel, not the kernel
  * state is slot-major (K, M): markets on the 128-lane minor dim (~25%
    faster than (M, K) with K=16)
  * the markets axis is padded to a lane multiple (1M → 1,000,448 = 7816·128,
    mask=0 pads): the ragged tail tile otherwise costs ~20% of throughput
  * on the axon tunnel ``block_until_ready`` does NOT force remote execution
    — every timing fence is a scalar value fetch
  * the tunnel's delivered HBM bandwidth VARIES RUN TO RUN (measured
    ~140-410 GB/s across sessions); every run emits a live stream probe
    (``stream_probe_gbs``) so cycle numbers can be normalised across
    rounds — ``extras.normalised_vs_probe`` carries the division already
    done

Output contract (round 6 — headline durability, VERDICT r5 #4): stdout
carries the full JSON record line, then a COMPACT headline line LAST —
``{"metric": ..., "vs_baseline": ..., "value": N, "unit": "cycles/sec"}``
with value/unit as its final bytes, so a front-truncated tail capture
still contains the round's headline. ``--out FILE`` additionally writes
the full record atomically (tmp + rename) — the self-contained artifact
no tail capture can lose.

Observability (round 6, obs/): every ``--leg`` subprocess runs under a
phase timeline (obs/timeline.py) and reports ``wall_s`` + ``phases`` — an
additive breakdown of the leg wall clock into the canonical phase names
(pack/upload/settle_dispatch/fetch/journal_fsync/checkpoint/
interchange_export, remainder as ``untracked``) — surfaced per leg under
``extras.harness.phase_breakdown``. ``--ledger FILE`` appends one
obs/ledger.py JSONL record per leg (and per repeat inside min-of-N legs)
carrying loadavg + repeat index; render with ``bce-tpu stats``. The
``obs_overhead`` leg A/Bs the streamed service with obs off vs on (the
"within 1%" contract).

``vs_baseline`` is against the reference implementation measured on this
host's CPU (scripts/measure_reference_baseline.py): 2743.4 markets/sec at
16 sources/market → 0.0027434 1M-cycles/sec. Re-run that script to refresh
(host CPU contention moves it; the recorded value is the FASTEST measured,
so vs_baseline is conservative).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

# Measured via scripts/measure_reference_baseline.py (in-memory SQLite, warm
# reliability table; min-of-N methodology + full trial record in BASELINE.md).
# History on this host: 0.0019838 (2026-07-29, busy CPU), 0.0027102
# (2026-07-30, 1000 markets, single pass), 0.0024822 / 0.0023932 (2026-07-31,
# 2000 markets, min-of-5 / min-of-8, load 0.5-0.8 on nproc=1), 0.0027434
# (2026-07-31 quiet host, 500 markets, min-of-5, load 0.1-0.2). The FASTEST
# ever observed is recorded — reference-favouring, so vs_baseline is a lower
# bound on the true ratio.
REFERENCE_BASELINE_CYCLES_PER_SEC = 0.0027434

NUM_MARKETS = 1_000_000
SLOTS_PER_MARKET = 16
SOURCE_UNIVERSE = 10_000
# Step count amortises the axon tunnel's ~96 ms dispatch+fence round trip
# (measured: a jitted 8-element add costs 95.7 ms end-to-end; see
# scripts/perf_lab.py rtt + docs/tpu-architecture.md). At 100 steps the
# dispatch dominated (~1 ms/step of pure RTT — round 2's misattributed
# "1.1 ms/step floor"); at 1600 it is ~6% of the total. The marginal
# kernel rate is reported separately in extras via a two-point fit.
TIMED_STEPS = 1600
FIT_STEPS = 400  # second point for the fixed-vs-marginal decomposition

LARGE_K_MARKETS = 16_384
LARGE_K_SLOTS = 10_000
LARGE_K_STEPS = 50

# The v5e-8 per-chip band of the 1M×10k dense north star: 1M markets pad
# to 1,000,448 lanes (7816·128), an (8,1) markets-mesh gives each chip
# exactly 125,056 markets × 10k slots. Working set ≈ 13.8 GB (f32 probs
# 5 GB + bool mask 1.25 GB + compact state 7.5 GB) — fits one 16 GB chip
# ONLY via the counter-compact state; the f32 block state (~20 GB) does
# not, which is the capacity argument for the compact encoding.
NORTH_STAR_MARKETS = 125_056
NORTH_STAR_SLOTS = 10_000
NORTH_STAR_STEPS = 40
NORTH_STAR_FIT_STEPS = 10

# Steps for the degraded CPU-fallback headline legs: enough to amortise
# per-dispatch overhead on the in-process CPU backend, small enough to
# finish within the leg budget on a loaded host.
CPU_FALLBACK_STEPS = 96

# The run ledger (obs/ledger.py) this process appends measurement records
# to — None unless --ledger was given. Leg functions with internal repeats
# (e2e_overlap) record per-repeat through _ledger_record; the --leg entry
# point records one summary per leg.
_LEDGER = None


def _ledger_record(leg, **kwargs):
    """Append one run-ledger record; silently a no-op without --ledger."""
    if _LEDGER is not None:
        _LEDGER.record(leg, **kwargs)


def _loadavg_1m():
    """1-minute loadavg via the ledger's one host-snapshot implementation."""
    from bayesian_consensus_engine_tpu.obs.ledger import host_snapshot

    return host_snapshot()["loadavg_1m"]


def _min_of_trials(leg_name, variant_names, run_variant, trials):
    """BASELINE.md's min-of-N + loadavg protocol for a leg's A/B variants.

    *trials* alternating rounds over *variant_names* (order preserved
    within a round, so a host-load burst never lands wholly on one
    variant), each repeat's wall seconds + pre-run 1-minute loadavg
    recorded to the run ledger as ``<leg>.<variant>`` (the
    ``min_of_repeats`` band source ``bce-tpu stats`` renders).
    ``run_variant(name)`` returns the variant's result dict (must carry
    ``wall_s``). Returns ``{name: best_dict}`` where each best (min-wall)
    dict additionally carries ``wall_s_band`` ([min, max] over repeats)
    and ``repeats`` — rounds quote the band, not a lucky single
    (VERDICT r5 #6: ``journal_presized`` flipped 0.82↔1.45 between
    same-day single captures).
    """
    best: dict = {}
    walls: dict = {name: [] for name in variant_names}
    for rep in range(trials):
        for name in variant_names:
            load = _loadavg_1m()
            out = run_variant(name)
            walls[name].append(out["wall_s"])
            _ledger_record(
                f"{leg_name}.{name}", value=out["wall_s"], unit="s",
                repeat=rep,
                extras={
                    "loadavg_1m_before": load,
                    "amortised_1m_cycles_per_sec": out.get(
                        "amortised_1m_cycles_per_sec"
                    ),
                    # Consumer seconds blocked on ingest (legs that track
                    # it) — the `bce-tpu stats` ingest_wait column.
                    "ingest_wait_s": out.get("ingest_wait_s"),
                    # Pair-interning seconds (legs that track it) — the
                    # `bce-tpu stats` intern column (round 15).
                    "intern_s": out.get("intern_s"),
                    "signals_per_sec": out.get("signals_per_sec"),
                    # Device allocator high-water mark (legs that sample
                    # it) — the `bce-tpu stats` peak_mem column.
                    "hbm_peak_bytes": out.get("hbm_peak_bytes"),
                    # Per-settle bytes-read proxy (args + temps of the
                    # AOT executable that ran) — the `bce-tpu stats`
                    # hbm_read column (round 14 one-pass legs).
                    "hbm_read_bytes": out.get("hbm_read_bytes"),
                    # Counterfactual-sweep throughput (the round-18
                    # replay leg) — the `bce-tpu stats` replay column.
                    "replay_batches_per_s": out.get("replay_batches_per_s"),
                },
            )
            if name not in best or out["wall_s"] < best[name]["wall_s"]:
                best[name] = out
    for name, out in best.items():
        out["wall_s_band"] = [min(walls[name]), max(walls[name])]
        out["repeats"] = trials
    return best


def _setup_compile_cache() -> None:
    """Persistent XLA compile cache for leg processes — ON by default.

    ``BCE_JAX_CACHE`` overrides the location; ``off``/``0``/``none``
    disables. Round 3 kept this opt-in because executable serialization
    through the tunneled TPU plugin was unverified; the round-3 outage
    showed the opposite risk is worse — every bench run recompiling ~12
    loop programs stretches the window in which a tunnel degradation can
    kill the run. jax treats cache failures as warnings, so an
    uncooperative backend degrades to the old behaviour, not an error.
    """
    val = os.environ.get("BCE_JAX_CACHE", "")
    if val.lower() in ("0", "off", "none", "disable", "disabled"):
        return
    cache_dir = val or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
    )
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:  # noqa: BLE001 — cache is an optimisation only
        pass


def build_workload(key, num_markets, slots, dtype):
    import jax
    import jax.numpy as jnp

    k_probs, k_mask, k_outcome, k_src = jax.random.split(key, 4)
    probs = jax.random.uniform(k_probs, (num_markets, slots), dtype=dtype)
    # ~90% slot occupancy: not every source signals every market.
    mask = jax.random.uniform(k_mask, (num_markets, slots)) < 0.9
    outcome = jax.random.uniform(k_outcome, (num_markets,)) < 0.5
    # Slot → source-universe assignment (pair identity; carried for realism,
    # not consumed by the cycle math, which is per-(market, slot)).
    src_idx = jax.random.randint(
        k_src, (num_markets, slots), 0, SOURCE_UNIVERSE, dtype=jnp.int32
    )
    return probs, mask, outcome, src_idx


def _fence(x):
    """Force remote execution (scalar value fetch — see module notes)."""
    return float(x.reshape(-1)[0])


def _hbm_read_capture(mem):
    """The per-settle bytes-read floor off one AOT ``memory_analysis()``.

    args + temps: every argument byte is read at least once and every
    temp byte is written then read — ONE definition shared by every
    one-pass capture site (e2e_onepass, e2e_ring_memory, e2e_analytics)
    so the legs' hbm_read columns can never diverge.
    """
    return {
        "arg_bytes": int(mem.argument_size_in_bytes),
        "compiled_temp_bytes": int(mem.temp_size_in_bytes),
        "hbm_read_bytes": int(
            mem.argument_size_in_bytes + mem.temp_size_in_bytes
        ),
    }


def _onepass_ratio_fields(multi_read, one_read, markets, tile):
    """The shared one-pass acceptance fields (ratio, the ≤0.5 bar, grid
    arithmetic) — one definition for every leg recording the capture,
    so a threshold change can never leave the legs disagreeing."""
    ratio = one_read / max(multi_read, 1)
    return {
        "multi_pass_read_bytes": multi_read,
        "one_pass_read_bytes": one_read,
        "read_ratio": round(ratio, 3),
        "single_pass_halves_reads": bool(ratio <= 0.5),
        "tile_markets": tile,
        "grid_tiles": markets // tile,
    }


def _infeasible(exc):
    """The bench-wide 'compile failure is data' rendering."""
    return f"infeasible: {type(exc).__name__}: {str(exc)[:200]}"


def timed_best_of(loop_call, make_state, steps, trials=3):
    """Warm once (compile + full run), then best-of-N per-step seconds → rate.

    Every run gets a fresh donated state and is fenced by a scalar value
    fetch (``block_until_ready`` does not force execution through the axon
    tunnel — see module notes). ``loop_call`` returns (state, consensus).

    The output state is explicitly dropped before the next trial's fresh
    state allocates: holding it across ``make_state()`` doubles the state
    footprint, which at the north-star band (7.5 GB compact state on a
    16 GB chip with 6.25 GB of inputs resident) is the difference between
    running and RESOURCE_EXHAUSTED.
    """
    out_state, consensus = loop_call(make_state())
    _fence(consensus)
    del out_state, consensus
    best = float("inf")
    for _ in range(trials):
        state_in = make_state()
        start = time.perf_counter()
        out_state, consensus = loop_call(state_in)
        _fence(consensus)
        best = min(best, (time.perf_counter() - start) / steps)
        del out_state, consensus
    return 1.0 / best


def headline_inputs(num_markets, slots):
    """Shared headline workload setup: mesh, padded slot-major inputs.

    ONE place decides mesh selection, lane padding, and shardings for every
    bench that claims the headline shape (bench_headline, bench_compact) —
    they must stay apples-to-apples. Returns
    ``(mesh, probs, mask, outcome, padded_total, block_sharding)``.
    """
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import make_mesh, pad_markets
    from bayesian_consensus_engine_tpu.parallel.mesh import (
        MARKETS_AXIS,
        SOURCES_AXIS,
    )

    # All devices on the markets axis: the reductions stay device-local and
    # the cycle needs zero communication (mesh.py default policy).
    mesh = make_mesh() if len(jax.devices()) > 1 else None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        block_sharding = NamedSharding(mesh, P(SOURCES_AXIS, MARKETS_AXIS))
        market_sharding = NamedSharding(mesh, P(MARKETS_AXIS))
    else:
        block_sharding = market_sharding = None

    probs, mask, outcome, _src_idx = build_workload(
        jax.random.PRNGKey(0), num_markets, slots, jnp.float32
    )
    # Slot-major layout: (K, M), markets on lanes — padded to a lane multiple
    # (pads carry mask=0: zero weight, NaN consensus, cold state).
    probs, mask = probs.T, mask.T
    lane_multiple = 128 * (mesh.shape[MARKETS_AXIS] if mesh is not None else 1)
    probs, mask, outcome, _, padded_total = pad_markets(
        probs, mask, outcome, state=None, multiple=lane_multiple
    )
    if mesh is not None:
        probs = jax.device_put(probs, block_sharding)
        mask = jax.device_put(mask, block_sharding)
        outcome = jax.device_put(outcome, market_sharding)
    return mesh, probs, mask, outcome, padded_total, block_sharding


def bench_headline(num_markets=NUM_MARKETS, slots=SLOTS_PER_MARKET,
                   timed_steps=TIMED_STEPS):
    """The 1M-market slot-packed cycle loop (driver metric)."""
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import (
        MarketBlockState,
        build_cycle_loop,
        init_block_state,
    )

    mesh, probs, mask, outcome, padded_total, block_sharding = headline_inputs(
        num_markets, slots
    )
    dtype = jnp.float32

    def fresh_state():
        """Slot-major state, pre-sharded, fully materialised (fenced)."""
        state = MarketBlockState(
            *(x.T for x in init_block_state(padded_total, slots, dtype=dtype))
        )
        if mesh is not None:
            state = MarketBlockState(
                *(jax.device_put(x, block_sharding) for x in state)
            )
        _fence(state.reliability)  # construction outside the timer
        return state

    loop = build_cycle_loop(mesh, slot_major=True, donate=True)
    return timed_best_of(
        lambda s: loop(probs, mask, outcome, s, jnp.asarray(1.0, dtype), timed_steps),
        fresh_state,
        timed_steps,
    )


def bench_large_k(markets=LARGE_K_MARKETS, slots=LARGE_K_SLOTS,
                  steps=LARGE_K_STEPS):
    """The 10k-source regime on one chip: flat, ring, and compact loops.

    Returns a dict of cycles/sec per loop. The compact state at this shape
    is ~0.9 GB vs ~2 GB of f32 — the counter encoding is also a capacity
    lever for the long-sources regime.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.parallel import (
        MarketBlockState,
        build_compact_cycle_loop,
        build_cycle_loop,
        init_block_state,
        init_compact_state,
    )
    from bayesian_consensus_engine_tpu.parallel.ring import build_ring_cycle_loop

    dtype = jnp.float32
    probs, mask, outcome, _ = build_workload(
        jax.random.PRNGKey(1), markets, slots, dtype
    )

    # Flat slot-major loop (K on sublanes, M on lanes).
    tp, tm = probs.T, mask.T
    flat = build_cycle_loop(mesh=None, slot_major=True, donate=True)

    def flat_state():
        state = MarketBlockState(
            *(x.T for x in init_block_state(markets, slots, dtype=dtype))
        )
        _fence(state.reliability)
        return state

    flat_cps = timed_best_of(
        lambda s: flat(tp, tm, outcome, s, jnp.asarray(1.0, dtype), steps),
        flat_state,
        steps,
    )

    # Ring (sources-parallel) loop on a 1-device mesh; full-width local pass
    # is fastest when the shard fits (chunking is for when it does not).
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources"))
    ring = build_ring_cycle_loop(mesh, chunk_slots=None, donate=True)

    def ring_state():
        state = init_block_state(markets, slots, dtype=dtype)
        _fence(state.reliability)
        return state

    ring_cps = timed_best_of(
        lambda s: ring(probs, mask, outcome, s, jnp.asarray(1.0, dtype), steps),
        ring_state,
        steps,
    )

    compact = build_compact_cycle_loop(mesh=None, donate=True)

    def compact_state():
        state = init_compact_state(markets, slots)
        _fence(state.updated_days)
        return state

    compact_cps = timed_best_of(
        lambda s: compact(tp, tm, outcome, s, jnp.asarray(1.0, dtype), steps),
        compact_state,
        steps,
    )
    # u16 fixed-point signals: at this K the f32 probability block is the
    # loop's largest per-step read — the encoding's home regime (explicit
    # reduced-precision contract; parallel/compact.py::encode_probs_u16).
    try:
        from bayesian_consensus_engine_tpu.parallel import encode_probs_u16

        tp_u16 = encode_probs_u16(tp)
        _fence(tp_u16)
        compact_u16_cps = round(timed_best_of(
            lambda s: compact(
                tp_u16, tm, outcome, s, jnp.asarray(1.0, dtype), steps
            ),
            compact_state,
            steps,
        ), 1)
    except Exception as exc:  # noqa: BLE001 — variant must not sink the leg
        compact_u16_cps = f"failed: {type(exc).__name__}: {exc}"
    return {
        "workload": f"{markets} markets x {slots} slots",
        "flat_loop_cycles_per_sec": round(flat_cps, 1),
        "ring_loop_cycles_per_sec": round(ring_cps, 1),
        "compact_loop_cycles_per_sec": round(compact_cps, 1),
        "compact_u16_probs_cycles_per_sec": compact_u16_cps,
    }


def _gen_chunked(key, slots, markets_n, dtype, chunks=8):
    """Slot-axis-chunked on-device RNG for the north-star bands.

    Threefry at the full band (1.25e9 draws) materialises multi-GB uint32
    transients and OOMs the chip ON ITS OWN (measured 2026-07-31: uint16
    bits at (10k, 62528) fits, (10k, 125056) does not, with 14 GiB free),
    so draw per-chunk, fence each so its transients die, and pay one
    2x-output concatenate instead."""
    import jax
    import jax.numpy as jnp

    parts = []
    for i, k in enumerate(jax.random.split(key, chunks)):
        lo = slots * i // chunks
        hi = slots * (i + 1) // chunks
        if dtype == jnp.float32:
            p = jax.random.uniform(k, (hi - lo, markets_n), dtype=jnp.float32)
        else:
            p = jax.random.bits(k, (hi - lo, markets_n), dtype)
        _fence(p)
        parts.append(p)
    out = jnp.concatenate(parts, axis=0)
    _fence(out)
    return out


def _gen_mask_outcome(k_mask, k_outcome, slots, markets_n):
    """Band mask/outcome from 16-bit draws: threshold 58982/65536 ≈
    0.90002 occupancy — the headline's ~90% without a 5 GB f32 uniform
    transient ever existing."""
    import jax
    import jax.numpy as jnp

    bits = _gen_chunked(k_mask, slots, markets_n, jnp.uint16)
    mask_n = bits < 58982
    _fence(mask_n)
    del bits
    outcome_n = jax.random.bits(k_outcome, (markets_n,), jnp.uint16) < 32768
    _fence(outcome_n)
    return mask_n, outcome_n


def _band_working_set_gb(slots, markets_n, probs_bytes, days_bytes=4):
    """Resident HBM of one band: compact state (i8 + u8 + days per slot —
    CompactBlockState's fields) + probs at ``probs_bytes`` + bool mask +
    bool outcome. ONE formula for both north-star legs."""
    state_bytes = (1 + 1 + days_bytes) * slots * markets_n
    input_bytes = (probs_bytes + 1) * slots * markets_n + markets_n
    return round((state_bytes + input_bytes) / 1e9, 1)


def _band_fit(loop, probs, mask, outcome, markets_n, slots, steps, fit_steps,
              days_dtype=None):
    """Two-point (steps, fit_steps) fit of the compact loop at one band.

    Returns ``(out_dict, marginal_s)`` — the end-to-end rate plus the
    dispatch-free marginal seconds/step (0.0 when the fit is degenerate).
    Both north-star legs go through HERE so their numbers stay
    methodologically identical (same fencing, same fresh-state
    discipline, same fit)."""
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import init_compact_state

    if days_dtype is None:
        days_dtype = jnp.float32
    day = jnp.asarray(1.0, jnp.float32)

    def fresh_state():
        state = init_compact_state(markets_n, slots, days_dtype=days_dtype)
        _fence(state.updated_days)
        return state

    cps_big = timed_best_of(
        lambda s: loop(probs, mask, outcome, s, day, steps),
        fresh_state,
        steps,
    )
    cps_small = timed_best_of(
        lambda s: loop(probs, mask, outcome, s, day, fit_steps),
        fresh_state,
        fit_steps,
    )
    out = {"end_to_end_cycles_per_sec": round(cps_big, 2)}
    t_big, t_small = steps / cps_big, fit_steps / cps_small
    marginal_s = (t_big - t_small) / (steps - fit_steps)
    if marginal_s <= 0:
        out["fit"] = (
            f"degenerate (t_{fit_steps}={t_small * 1e3:.1f}ms, "
            f"t_{steps}={t_big * 1e3:.1f}ms)"
        )
        return out, 0.0
    out["marginal_ms_per_step"] = round(marginal_s * 1e3, 2)
    out["band_sustained_cycles_per_sec"] = round(1.0 / marginal_s, 1)
    return out, marginal_s


def bench_north_star_band(markets=NORTH_STAR_MARKETS, slots=NORTH_STAR_SLOTS,
                          steps=NORTH_STAR_STEPS,
                          fit_steps=NORTH_STAR_FIT_STEPS):
    """BASELINE.json's metric shape, measured: the per-chip band of 1M×10k.

    Dense 1M markets × 10k sources needs ~112 GB of f32 — it exists only
    sharded. On a v5e-8 markets-only mesh each chip owns a 125,056×10k
    band and the cycle moves zero cross-device bytes (the one psum
    compiles to singleton replica groups — checked in HLO on the 8-device
    virtual mesh), so ONE measured band step IS the projected global step.
    This leg runs that band through the counter-compact loop and reports
    the marginal ms/step via a two-point fit, replacing the projection
    table's extrapolated row (docs/tpu-architecture.md) with a measured
    anchor.

    Capacity, measured on the axon chip 2026-07-31: ~15 GiB of plain
    buffers allocate, but each multi-GB RNG generation pass leaves ~1 GiB
    of unreclaimed scratch, so the f32-probs band (13.75 GB resident)
    does NOT reliably fit — the final 5 GB state block dies with
    RESOURCE_EXHAUSTED. The band that fits with headroom is the u16
    fixed-point probability block (11.25 GB resident), generated directly
    as 16-bit draws (`jax.random.bits` — uniform on the u16 lattice,
    which IS `encode_probs_u16`'s codomain, parallel/compact.py:90-113)
    so no 5 GB f32 transient ever exists. That u16 band is this leg's
    number; the f32 numeric contract is anchored at the v5e-16 half band
    by the separate ``north_star_f32`` leg (an OOM poisons every later
    allocation in its process, so the two run in separate subprocesses).
    Inputs are generated ON DEVICE in slot-major layout (a multi-GB host
    transfer through the tunnel would blow the time budget).
    """
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import build_compact_cycle_loop

    loop = build_compact_cycle_loop(mesh=None, donate=True)
    k_probs, k_mask, k_outcome = jax.random.split(jax.random.PRNGKey(2), 3)

    result = {
        "workload": (
            f"{markets} markets x {slots} slots (dense; the per-chip band "
            f"of 1M x 10k on a v5e-8 markets-only mesh)"
        ),
    }

    mask, outcome = _gen_mask_outcome(k_mask, k_outcome, slots, markets)
    # The u16 fit IS this leg's measurement — no blanket except here: a
    # failure must fail the leg (harness status, degraded accounting,
    # circuit breaker), not report ok with a failure string inside.
    probs_u16 = _gen_chunked(k_probs, slots, markets, jnp.uint16)
    u16_result, u16_marginal = _band_fit(
        loop, probs_u16, mask, outcome, markets, slots, steps, fit_steps
    )
    del probs_u16
    u16_result["contract"] = (
        "u16 fixed-point signals (quantization <= 7.6e-6 — "
        "parallel/compact.py::encode_probs_u16); bitwise equal to the "
        "f32 loop on the decoded inputs"
    )
    u16_result["hbm_working_set_gb"] = _band_working_set_gb(
        slots, markets, probs_bytes=2
    )
    result["u16_probs"] = u16_result
    if u16_marginal > 0:
        result["projected_v5e8_1m_x_10k_u16_cycles_per_sec"] = round(
            1.0 / u16_marginal, 1
        )
        result["projection_basis"] = (
            "u16-probs band (reduced-precision contract — NOT an f32 "
            "number): 8 chips each run this band in lockstep with zero "
            "cross-device bytes (singleton psum groups on a markets-only "
            "mesh), so the global 1M x 10k sustained rate equals the "
            "measured band rate"
        )
    return result


def bench_north_star_f32(markets=NORTH_STAR_MARKETS // 2,
                         slots=NORTH_STAR_SLOTS, steps=NORTH_STAR_STEPS,
                         fit_steps=NORTH_STAR_FIT_STEPS):
    """The f32-probs anchor for the north-star band: the HALF band.

    The full f32 band (125,056 markets: 13.75 GB resident) does not fit
    the axon chip — measured three ways on 2026-07-31, each dying in
    RESOURCE_EXHAUSTED at the final 5 GB state block (~15 GiB of plain
    buffers allocate, but multi-GB RNG passes leave ~1 GiB of scratch
    residue each, and an OOM poisons every later allocation in the same
    process — which is why this is its OWN leg, not a fallback inside
    ``north_star_band``). So the f32 loop is anchored at 62,528 markets —
    exactly the v5e-16 per-chip slice — and the published projection is
    the v5e-16 lockstep rate: 16 chips each running this measured band,
    global rate = the band rate (1/marginal). The u16 band
    (``north_star_band``) is the encoding that actually fits a v5e-8;
    this leg pins the f32 numeric-contract rate the projection table
    quotes alongside it.
    """
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import build_compact_cycle_loop

    loop = build_compact_cycle_loop(mesh=None, donate=True)
    k_probs, k_mask, k_outcome = jax.random.split(jax.random.PRNGKey(2), 3)

    mask, outcome = _gen_mask_outcome(k_mask, k_outcome, slots, markets)
    probs = _gen_chunked(k_probs, slots, markets, jnp.float32)
    fit_result, marginal_s = _band_fit(
        loop, probs, mask, outcome, markets, slots, steps, fit_steps
    )
    result = {
        "workload": (
            f"{markets} markets x {slots} slots, f32 probs (the v5e-16 "
            f"per-chip slice of 1M x 10k; the v5e-8 slice at f32 exceeds "
            f"this chip's usable HBM — see leg docstring)"
        ),
        **fit_result,
        "hbm_working_set_gb": _band_working_set_gb(
            slots, markets, probs_bytes=4
        ),
    }
    if marginal_s > 0:
        result["projected_v5e16_1m_x_10k_f32_cycles_per_sec"] = round(
            1.0 / marginal_s, 1
        )
        result["projection_basis"] = (
            "a v5e-16 markets-only mesh runs 16 of these half-bands in "
            "lockstep with zero cross-device bytes, so the global 1M x "
            "10k f32 rate equals the measured band rate; a v5e-8 full "
            "band does not fit at f32 with f32 day stamps — see "
            "f32_probs_u16_days_full_band for the encoding that makes "
            "the f32-signal v5e-8 band fit"
        )

    # --- Full f32-signal band via u16 day stamps (11.25 GB resident —
    # init_compact_state(days_dtype=uint16), bit-identical on integral
    # days): the capacity rung that makes the f32-SIGNAL north star fit
    # a v5e-8 chip. Attempted LAST: the half band above is already
    # banked, so an OOM here costs only this entry. ---
    full_markets = markets * 2
    try:
        del probs, mask, outcome  # free ALL half-band buffers first
        mask, outcome = _gen_mask_outcome(
            k_mask, k_outcome, slots, full_markets
        )
        probs = _gen_chunked(k_probs, slots, full_markets, jnp.float32)
        u16d_result, u16d_marginal = _band_fit(
            loop, probs, mask, outcome, full_markets, slots, steps,
            fit_steps, days_dtype=jnp.uint16,
        )
        u16d_result["workload"] = (
            f"{full_markets} markets x {slots} slots, f32 probs + u16 day "
            f"stamps (the v5e-8 per-chip slice)"
        )
        u16d_result["contract"] = (
            "f32 signals (full numeric contract), u16 day stamps — "
            "bit-identical to f32 days on the integral [0, 65535] day "
            "domain (tests/test_compact.py::TestU16Days)"
        )
        u16d_result["hbm_working_set_gb"] = _band_working_set_gb(
            slots, full_markets, probs_bytes=4, days_bytes=2
        )
        if u16d_marginal > 0:
            u16d_result["projected_v5e8_1m_x_10k_f32_cycles_per_sec"] = (
                round(1.0 / u16d_marginal, 1)
            )
        result["f32_probs_u16_days_full_band"] = u16d_result
    except Exception as exc:  # noqa: BLE001 — must not sink the half band
        result["f32_probs_u16_days_full_band"] = (
            f"failed: {type(exc).__name__}: {exc}"
        )
    return result


def _pallas_rate(num_markets, slots, timed_steps, tile):
    """Best-of-N cycles/sec for the fused Pallas cycle at one (M, K, tile).

    ``tile="auto"`` resolves through the shape tuner (M padded to the
    2048 multiple every candidate divides)."""
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.ops.pallas_cycle import (
        SlotMajorState,
        _tuned_tile,
        build_pallas_cycle,
    )

    pad_unit = 2048 if tile == "auto" else tile
    padded = -(-num_markets // pad_unit) * pad_unit
    if tile == "auto":
        tile = _tuned_tile(padded, slots)
    probs, mask, outcome, _ = build_workload(
        jax.random.PRNGKey(0), num_markets, slots, jnp.float32
    )
    pad = padded - num_markets
    probs = jnp.pad(probs.T, ((0, 0), (0, pad)))
    mask = jnp.pad(mask.T, ((0, 0), (0, pad))).astype(jnp.float32)
    outcome = jnp.pad(outcome, (0, pad)).astype(jnp.float32)[None, :]

    call = build_pallas_cycle(padded, slots, tile_markets=tile)

    def loop_fn(probs, mask, outcome, state):
        def body(i, carry):
            state, _ = carry
            state, consensus, _, _ = call(probs, mask, outcome, state, 1.0 + i)
            return state, consensus

        init = jnp.zeros((1, padded), jnp.float32)
        return jax.lax.fori_loop(0, timed_steps, body, (state, init))

    loop = jax.jit(loop_fn)

    def fresh_state():
        state = SlotMajorState(
            jnp.full((slots, padded), 0.5, jnp.float32),
            jnp.full((slots, padded), 0.25, jnp.float32),
            jnp.zeros((slots, padded), jnp.float32),
            jnp.zeros((slots, padded), jnp.float32),
        )
        _fence(state.reliability)
        return state

    return timed_best_of(
        lambda s: loop(probs, mask, outcome, s), fresh_state, timed_steps
    )


def _onepass_rate(num_markets, slots, timed_steps):
    """Best-of-N cycles/sec for the ONE-PASS settlement kernel at (M, K).

    One kernel launch runs the whole N-step cycle loop PLUS the tie-break
    fold and band moments (the kernel always computes all three — it does
    strictly more work per sweep than the plain-cycle arms, so a tie here
    is a win). Interpret mode off-TPU, real Mosaic on TPU.
    """
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.ops.cycle_math import (
        MarketBlockState,
    )
    from bayesian_consensus_engine_tpu.ops.pallas_settle import (
        build_onepass_settle,
        resolve_tile_markets,
    )

    padded = -(-num_markets // 128) * 128
    tile = resolve_tile_markets(padded, slots)
    probs, mask, outcome, _ = build_workload(
        jax.random.PRNGKey(0), num_markets, slots, jnp.float32
    )
    pad = padded - num_markets
    probs = jnp.pad(probs.T, ((0, 0), (0, pad)))
    mask = jnp.pad(mask.T, ((0, 0), (0, pad)))
    outcome = jnp.pad(outcome, (0, pad))

    onepass = build_onepass_settle(
        padded, slots, timed_steps, tile_markets=tile,
        interpret=jax.default_backend() != "tpu",
    )
    loop = jax.jit(lambda p, ma, o, s: onepass(p, ma, o, s, 1.0))

    def fresh_state():
        state = MarketBlockState(
            jnp.full((slots, padded), 0.5, jnp.float32),
            jnp.full((slots, padded), 0.25, jnp.float32),
            jnp.zeros((slots, padded), jnp.float32),
            jnp.zeros((slots, padded), bool),
        )
        _fence(state.reliability)
        return state

    def loop_call(state):
        new_state, consensus, _tb, _bands = loop(probs, mask, outcome, state)
        return new_state, consensus

    return timed_best_of(loop_call, fresh_state, timed_steps)


def _sharded_onepass_capture(markets, slots, steps, mesh_shape,
                             chunk_agents, chunk_slots, reps=2):
    """The round-20 sources-sharded A/B: partials route vs fused XLA.

    Builds BOTH routes on the SAME 2-D mesh (markets × sources), AOT
    compiles each, captures the per-settle bytes-read floor off the
    executables that run (the `_hbm_read_capture` definition every
    one-pass leg shares), and times best-of-N. ``per_shard_read_bytes``
    divides the program total by the device count — each shard's kernel
    streams only its local (K_local, M_local) block.

    The recorded ``read_ratio`` compares what ONE device streams per
    settle: the sharded one-pass route's per-shard read vs the
    single-device multi-pass program at the same global shape and
    chunking — the deployment question this route exists to answer (the
    dense shapes that RESOURCE_EXHAUSTED on one device must not forfeit
    the one-pass diet by sharding). ``program_read_ratio`` records the
    whole-program sharded one-pass vs sharded multi-pass comparison
    alongside (≈1 when the per-shard block fits one kernel tile —
    ``grid_tiles`` says which regime the ratio came from, exactly like
    the 1-D capture). Raises when the host has fewer devices than the
    mesh needs or a route fails to compile — callers record that as
    the infeasibility datum, never a crash.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.ops.pallas_settle import (
        resolve_tile_markets,
    )
    from bayesian_consensus_engine_tpu.parallel.sharded import (
        build_cycle_analytics_loop,
        init_block_state,
    )

    n_devices = mesh_shape[0] * mesh_shape[1]
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"mesh {mesh_shape} needs {n_devices} devices, "
            f"have {len(jax.devices())}"
        )
    mesh = Mesh(
        np.array(jax.devices()[:n_devices]).reshape(mesh_shape),
        ("markets", "sources"),
    )
    m_loc = markets // mesh_shape[0]
    k_loc = slots // mesh_shape[1]
    rng = np.random.default_rng(20)
    probs = jnp.asarray(rng.random((slots, markets)), jnp.float32)
    mask = jnp.asarray(rng.random((slots, markets)) < 0.9)
    outcome = jnp.asarray(rng.random(markets) < 0.5)
    state0 = jax.tree.map(
        lambda x: x.T, init_block_state(markets, slots)
    )
    now0 = jnp.asarray(400.0, jnp.float32)
    ca, cs = min(chunk_agents, k_loc), min(chunk_slots, k_loc)

    out = {
        "workload": (
            f"{markets} markets x {slots} slots, {steps} steps, "
            f"mesh {mesh_shape[0]}x{mesh_shape[1]}"
        ),
        "mesh_shape": list(mesh_shape),
        "per_shard_shape": [k_loc, m_loc],
    }
    for name, kwargs in (
        ("multi_pass", {}), ("one_pass", {"kernel": "pallas"})
    ):
        loop = build_cycle_analytics_loop(
            mesh, chunk_agents=ca, chunk_slots=cs, donate=False, **kwargs
        )
        exe = jax.jit(
            lambda p, ma, o, s, n, _loop=loop: _loop(p, ma, o, s, n, steps)
        ).lower(probs, mask, outcome, state0, now0).compile()
        read = _hbm_read_capture(exe.memory_analysis())
        _fence(exe(probs, mask, outcome, state0, now0)[1])  # warm
        best = float("inf")
        for _ in range(reps):
            start = time.perf_counter()
            _fence(exe(probs, mask, outcome, state0, now0)[1])
            best = min(best, time.perf_counter() - start)
        out[name] = {
            "wall_s": round(best, 4),
            "markets_per_sec": round(markets / best, 1),
            **read,
            "per_shard_read_bytes": read["hbm_read_bytes"] // n_devices,
        }
    # The single-device reference at the same global shape and chunking
    # — matched ring folds, so the ratio isolates what sharding buys.
    mesh1 = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources")
    )
    ref_loop = build_cycle_analytics_loop(
        mesh1, chunk_agents=ca, chunk_slots=cs, donate=False
    )
    ref = jax.jit(
        lambda p, ma, o, s, n: ref_loop(p, ma, o, s, n, steps)
    ).lower(probs, mask, outcome, state0, now0).compile()
    out["unsharded_multi_pass"] = _hbm_read_capture(ref.memory_analysis())
    tile = resolve_tile_markets(m_loc, k_loc)
    out.update(_onepass_ratio_fields(
        out["unsharded_multi_pass"]["hbm_read_bytes"],
        out["one_pass"]["per_shard_read_bytes"], m_loc, tile,
    ))
    out["program_read_ratio"] = round(
        out["one_pass"]["hbm_read_bytes"]
        / max(out["multi_pass"]["hbm_read_bytes"], 1), 3
    )
    return out


#: The BP bracket arm's shape (round 19). The markets cap keeps the
#: kernel's resident state set (3 VMEM windows x 2 moment vectors x 4
#: bytes/market ≈ 24 B/market) safely inside the 16 MB VMEM budget —
#: the uncapped 1M-market regime is the kernel's recorded infeasibility,
#: not this arm's workload.
BP_SWEEP_MARKETS = 262_144
BP_SWEEP_DEPTH = 24


def _bp_rate(markets, degree, max_steps, kind, reps=3):
    """Best-of-N full-depth moment sweeps/sec for ONE sweep route.

    ``kind="xla"`` is the ``while_loop`` sweep
    (:func:`~.ops.propagate.bp_sweep_math`), ``"pallas"`` the
    VMEM-resident BP kernel (``ops/pallas_bp.py``) — the same dense
    ``degree``-regular (M, D) workload AOT-compiled either way, so the
    bracket times exactly the route swap. Interpret mode off-TPU, real
    Mosaic on TPU.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bayesian_consensus_engine_tpu.ops.pallas_bp import build_bp_sweep
    from bayesian_consensus_engine_tpu.ops.propagate import bp_sweep_math

    rng = np.random.default_rng(19)
    means = jnp.asarray(rng.random(markets), jnp.float32)
    variances = jnp.asarray(
        rng.uniform(1e-4, 0.05, markets), jnp.float32
    )
    idx = jnp.asarray(
        rng.integers(0, markets, (markets, degree)), jnp.int32
    )
    w = jnp.asarray(rng.uniform(0.5, 1.5, (markets, degree)), jnp.float32)
    if kind == "pallas":
        fn = build_bp_sweep(
            markets, degree, max_steps, damping=0.5,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        def fn(v, s, i, wt):
            return bp_sweep_math(
                v, s, i, wt, damping=0.5, max_steps=max_steps
            )
    exe = jax.jit(fn).lower(means, variances, idx, w).compile()
    _fence(exe(means, variances, idx, w)[0])  # warm off the clock
    best = float("inf")
    for _ in range(reps):
        start = time.perf_counter()
        _fence(exe(means, variances, idx, w)[0])
        best = min(best, time.perf_counter() - start)
    return round(max_steps / best, 1)


def _bp_autotune_decision(markets, slots):
    """Race the fused program's sweep routes through the honesty-guarded
    tuner (knob ``sweep_kernel``) and return the recorded verdict.

    The race itself is :func:`~.parallel.sharded._tuned_sweep_kernel` —
    the two candidate programs differ only in the sweep stage — so the
    leg's JSON carries the same adjudication record the ``"auto"``
    route would act on (choice, default, beat_default).
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.parallel.sharded import (
        _tuned_sweep_kernel,
    )
    from bayesian_consensus_engine_tpu.utils.autotune import default_tuner

    mesh = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources")
    )
    _tuned_sweep_kernel(
        mesh, slots, markets, 1, 8, BP_SWEEP_DEPTH, "moments", None,
        0.5, None, None, 6, 1.959964,
    )
    return default_tuner().decision(
        "sweep_kernel",
        (slots, markets, 1, 8, BP_SWEEP_DEPTH, "moments", None, 1, 1),
    )


def bench_pallas_ab(num_markets=NUM_MARKETS, slots=SLOTS_PER_MARKET,
                    timed_steps=TIMED_STEPS, large_k_attempt=True,
                    sharded_markets=2048, sharded_slots=512):
    """Adjudicate the Pallas kernel vs the XLA loop, interleaved in ONE
    process — the only A/B this host makes meaningful (tunnel bandwidth
    swings up to ~3x between processes).

    Measures, in order: XLA production loop at 1M×16, Pallas at the
    shipped tile (2048), Pallas at the AUTOTUNED tile (``BCE_AUTOTUNE=1``
    forced for this leg; the chosen tile is reported), XLA again (the
    bracket bounds drift — compare Pallas to the BEST XLA pass), and —
    round 14 — the THIRD bracket arm: the one-pass settlement kernel
    (``ops/pallas_settle.py``, cycles + tie-break + bands in one sweep)
    at the same shape, so one leg re-adjudicates BOTH Pallas artifacts
    on real hardware. The 16k×10k regime is then attempted with a
    lane-minimal tile; the expected VMEM infeasibility (a (10k, 128) f32
    block alone is 5.1 MB; the kernel holds ~10) is recorded as data,
    not a crash. The returned ``verdict``/``onepass_verdict`` are the
    win-or-retire decision inputs (VERDICT r4 #6; ISSUE 12).

    Round 19 adds the FOURTH bracket arm: the correlated-market sweep,
    XLA ``while_loop`` vs the VMEM-resident BP kernel at the
    VMEM-bounded dense shape (``_bp_rate``), infeasible-as-data like
    the other kernel arms, plus the honesty-guarded tuner's recorded
    ``sweep_kernel`` adjudication (``bp_autotune_decision``) for the
    fused route at the same shape.

    Round 20 adds the FIFTH bracket arm: the sources-sharded one-pass
    route (partials kernel + cross-device merge) vs the fused XLA
    program on a (2, 4) mesh (``_sharded_onepass_capture``) —
    infeasible-as-data on hosts short of 8 devices, same posture as
    the BP bracket.
    """
    from bayesian_consensus_engine_tpu.ops.pallas_cycle import _tuned_tile

    prior_autotune = os.environ.get("BCE_AUTOTUNE")
    os.environ["BCE_AUTOTUNE"] = "1"
    try:
        out = {}
        out["xla_cycles_per_sec"] = bench_headline(
            num_markets, slots, timed_steps
        )
        out["pallas_tile2048_cycles_per_sec"] = _pallas_rate(
            num_markets, slots, timed_steps, 2048
        )
        # The tuner is asked at the SAME 2048-padded M that _pallas_rate's
        # "auto" branch uses, so the reported tile, the tuner's cache key,
        # and the measured workload all agree (and match the tile-2048
        # pass's M — apples to apples).
        padded = -(-num_markets // 2048) * 2048
        auto_tile = _tuned_tile(padded, slots)
        out["autotuned_tile"] = auto_tile
        # The tuner's honesty-guard verdict (utils/autotune.py): which of
        # tuned-vs-default won on the same A/B clock. A tuned value only
        # ever ships when beat_default is true — the leg records the
        # verdict so the round's JSON carries the adjudication, not just
        # the chosen tile.
        from bayesian_consensus_engine_tpu.utils.autotune import (
            default_tuner,
        )

        out["autotune_decision"] = default_tuner().decision(
            "pallas_tile", (padded, slots)
        )
        out["pallas_auto_cycles_per_sec"] = (
            out["pallas_tile2048_cycles_per_sec"]
            if auto_tile == 2048
            else _pallas_rate(num_markets, slots, timed_steps, "auto")
        )
        out["xla_recheck_cycles_per_sec"] = bench_headline(
            num_markets, slots, timed_steps
        )
        # The one-pass settlement kernel does the cycle loop PLUS the
        # tie-break and band moments per sweep; a compile failure on
        # this backend is the recorded datum, never a crash.
        try:
            out["onepass_settle_cycles_per_sec"] = _onepass_rate(
                num_markets, slots, timed_steps
            )
        except Exception as exc:
            out["onepass_settle"] = (
                f"infeasible: {type(exc).__name__}: {str(exc)[:200]}"
            )

        # Round 19: the BP sweep bracket at the VMEM-bounded dense
        # shape. Either route failing to compile is the recorded
        # datum; the tuner's fused-route verdict rides along.
        bp_m = min(num_markets, BP_SWEEP_MARKETS)
        try:
            out["bp_xla_sweeps_per_sec"] = _bp_rate(
                bp_m, 8, BP_SWEEP_DEPTH, "xla"
            )
            out["bp_pallas_sweeps_per_sec"] = _bp_rate(
                bp_m, 8, BP_SWEEP_DEPTH, "pallas"
            )
        except Exception as exc:
            out["bp_sweep"] = _infeasible(exc)
        out["bp_autotune_decision"] = _bp_autotune_decision(bp_m, slots)

        # Round 20: the sources-sharded bracket arm. The partials
        # route and the fused program race on the SAME (2, 4) mesh;
        # a host short of 8 devices records the shortage as data.
        try:
            out["sharded_onepass"] = _sharded_onepass_capture(
                sharded_markets, sharded_slots, 2, (2, 4), 1024, 1024,
            )
        except Exception as exc:
            out["sharded_onepass"] = _infeasible(exc)

        if large_k_attempt:
            try:
                out["pallas_16k10k_cycles_per_sec"] = _pallas_rate(
                    LARGE_K_MARKETS, LARGE_K_SLOTS,
                    max(2, timed_steps // 100), 128,
                )
            except Exception as exc:  # VMEM overflow is the expected datum
                out["pallas_16k10k"] = (
                    f"infeasible: {type(exc).__name__}: {str(exc)[:200]}"
                )
    finally:
        # Autotune is documented default-off; in-process callers
        # (perf_lab ab) must not leave it enabled behind the user's back.
        if prior_autotune is None:
            os.environ.pop("BCE_AUTOTUNE", None)
        else:
            os.environ["BCE_AUTOTUNE"] = prior_autotune

    xla_best = max(out["xla_cycles_per_sec"], out["xla_recheck_cycles_per_sec"])
    pallas_best = max(
        out["pallas_tile2048_cycles_per_sec"], out["pallas_auto_cycles_per_sec"]
    )
    out["verdict"] = (
        f"pallas_wins_1m16 ({pallas_best:.1f} vs {xla_best:.1f})"
        if pallas_best > xla_best
        else f"xla_wins_1m16 ({xla_best:.1f} vs {pallas_best:.1f})"
    )
    onepass = out.get("onepass_settle_cycles_per_sec")
    if onepass is not None:
        # The one-pass arm computes MORE per cycle (tie-break + bands
        # ride the sweep), so it is adjudicated against the plain-cycle
        # XLA best as a lower bound: a win here is decisive, a loss is
        # re-judged by the apples-to-apples e2e_onepass leg.
        out["onepass_verdict"] = (
            f"onepass_wins_1m16 ({onepass:.1f} vs {xla_best:.1f})"
            if onepass > xla_best
            else f"xla_wins_onepass_1m16 ({xla_best:.1f} vs {onepass:.1f})"
        )
    bp_pallas = out.get("bp_pallas_sweeps_per_sec")
    if bp_pallas is not None:
        # Same-workload, same-clock: the sweep bracket is its own
        # apples-to-apples pair, no cross-arm lower-bounding needed.
        bp_xla = out["bp_xla_sweeps_per_sec"]
        out["bp_verdict"] = (
            f"bp_kernel_wins ({bp_pallas:.1f} vs {bp_xla:.1f})"
            if bp_pallas > bp_xla
            else f"xla_wins_bp ({bp_xla:.1f} vs {bp_pallas:.1f})"
        )
    sharded = out.get("sharded_onepass")
    if isinstance(sharded, dict):
        # Same-mesh, same-clock pair — its own apples-to-apples verdict.
        sharded_one = sharded["one_pass"]["wall_s"]
        sharded_multi = sharded["multi_pass"]["wall_s"]
        out["sharded_verdict"] = (
            f"sharded_onepass_wins ({sharded_one:.4f}s vs "
            f"{sharded_multi:.4f}s)"
            if sharded_one < sharded_multi
            else f"xla_wins_sharded ({sharded_multi:.4f}s vs "
                 f"{sharded_one:.4f}s)"
        )
    return out


def bench_e2e_stream(markets=NUM_MARKETS, batches=6, mean_slots=4, steps=20,
                     checkpoint_every=2, trials=2):
    """The streamed settlement SERVICE at scale: amortised rate with every
    overlap engaged.

    ``settle_stream`` over *batches* columnar batches covering *markets*
    markets total — prefetch builds plan N+1 during settle N, checkpoints
    write on the background thread every *checkpoint_every* batches, the
    tail flush drains everything — timed end to end (data pre-generated
    outside the timer; a real service receives its feed). This is the
    steady-state number the two-batch ``e2e_overlap`` A/B understates,
    and the leg breakdown (summed per-batch stats) shows where wall-clock
    hides: ``ingest_wait_s`` is consumer time blocked on the prefetch
    thread (ingest-bound when large), ``checkpoint_s`` is flush-call time
    (drains device + snapshots; the SQLite write itself is backgrounded).
    ``amortised_1m_cycles_per_sec`` is market-cycles/sec ÷ 1M — directly
    comparable to the headline device-only rate (VERDICT r4 #5).
    """
    import gc
    import tempfile as _tf

    import numpy as np

    from bayesian_consensus_engine_tpu.pipeline import settle_stream
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    per_batch = markets // batches
    rng = np.random.default_rng(13)
    batch_data = []
    for b in range(batches):
        counts = rng.poisson(mean_slots - 1, per_batch) + 1
        total = int(counts.sum())
        keys = [f"b{b}-m{m}" for m in range(per_batch)]
        sids = [f"src-{v}" for v in rng.integers(0, SOURCE_UNIVERSE, total)]
        probs = rng.random(total)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        outcomes = (rng.random(per_batch) < 0.5).tolist()
        batch_data.append(((keys, sids, probs, offsets), outcomes))
    gc.freeze()

    market_cycles = per_batch * batches * steps

    def run(lazy, journal=False, presize=0):
        stats: list = []
        # presize=0 clamps to the store's own minimum — identical to the
        # historical no-arg default, keeping eager/lazy/journal ladders
        # comparable across rounds.
        store = TensorReliabilityStore(capacity=presize)
        extra = {}
        with _tf.TemporaryDirectory() as tmp:
            db = os.path.join(tmp, "stream.db")
            jrnl = os.path.join(tmp, "stream.jrnl") if journal else None
            start = time.perf_counter()
            # Journal mode measures the long-running SERVICE shape:
            # rolling durability rides the fsynced journal alone, and the
            # SQLite interchange file is an on-demand EXPORT, not part of
            # the per-batch loop — timed separately below. (A journal
            # stream that also tail-flushes SQLite pays the same total
            # SQLite bytes as eager mode but without eager's overlap, so
            # the complete-run comparison is eager's to win; the journal
            # changes the durability rate, which is what a service runs
            # at between exports.)
            for _result in settle_stream(
                store, batch_data, steps=steps, now=21_900.0,
                db_path=None if journal else db,
                checkpoint_every=checkpoint_every, columnar=True,
                stats=stats, lazy_checkpoints=lazy, journal=jrnl,
            ):
                pass
            store.sync()
            wall = time.perf_counter() - start
            if journal:
                export_start = time.perf_counter()
                store.flush_to_sqlite(db)
                extra["interchange_export_s"] = round(
                    time.perf_counter() - export_start, 2
                )

        def sum_of(key):
            return round(
                sum(s[key] for s in stats if s[key] is not None), 2
            )

        return len(store), {
            "wall_s": round(wall, 2),
            "amortised_1m_cycles_per_sec": round(
                market_cycles / wall / 1e6, 4
            ),
            "ingest_wait_s": sum_of("plan_wait_s"),
            "settle_dispatch_s": sum_of("settle_dispatch_s"),
            "checkpoint_s": sum_of("checkpoint_s"),
            **extra,
        }

    # Same-process A/B/C: eager rolling-SQLite (interchange file current
    # through the yielding batch) vs lazy checkpoints (applied-truth
    # snapshots) vs the durability JOURNAL service shape (rolling
    # fsynced binary epochs, interchange as a separate export —
    # state/journal.py, VERDICT r4 #5's lever). LAZY RUNS FIRST in each
    # round and the first round pays all compilation/warmup; compare
    # journal to eager (both warm, same compiled shapes).
    # journal_presized compiles its OWN capacity shape inside its first
    # timed wall when the persistent cache is cold — min-of-N absorbs
    # that from round 2 on (docs/round5-notes.md carries both regimes).
    # The pre-size value is the docs/round5-notes.md "pre-sized store"
    # recipe; kept as a SEPARATE variant so eager/journal stay
    # comparable across rounds. Variants alternate per round and each
    # repeat lands in the run ledger (BASELINE.md min-of-N + loadavg
    # protocol, VERDICT r5 #6) — quote wall_s_band, not singles.
    rows_seen = {}
    variants = {
        "lazy_checkpoints": dict(lazy=True),
        "eager": dict(lazy=False),
        "journal": dict(lazy=False, journal=True),
        "journal_presized": dict(
            lazy=False, journal=True,
            presize=int(markets * mean_slots * 1.1),
        ),
    }

    def run_variant(name):
        rows, out = run(**variants[name])
        rows_seen[name] = rows
        return out

    best = _min_of_trials("e2e_stream", list(variants), run_variant, trials)
    return {
        "workload": (
            f"{batches} batches x {per_batch} markets x {steps} cycles, "
            f"checkpoint every {checkpoint_every}, min of {trials} "
            "alternating trials"
        ),
        "store_rows": rows_seen["lazy_checkpoints"],
        "eager": best["eager"],
        "lazy_checkpoints": best["lazy_checkpoints"],
        "journal": best["journal"],
        "journal_presized": best["journal_presized"],
    }


def bench_e2e_stream_stable_topology(markets=NUM_MARKETS, batches=6,
                                     mean_slots=4, steps=20,
                                     checkpoint_every=2, trials=2):
    """The streamed service in its STEADY STATE: one persistent
    (source, market) universe re-settled every batch with fresh
    probabilities/outcomes — the reference's daily re-settlement shape
    (market.py:200-221) — A/B'd with plan reuse off vs on.

    With ``reuse_plans=False`` every batch pays the full ingest (pack,
    intern, block fill, topology upload) for a topology that has not
    changed; with ``True`` the prefetcher fingerprints each batch and
    refreshes the previous plan's probability columns instead
    (``SettlementPlan.refresh`` — the delta-ingest fast path, bit-exact
    with the rebuild path by tests/test_overlap.py). Both runs stream
    through the same eager rolling-SQLite checkpoint loop, so the delta
    is the ingest cost alone. ``amortised_1m_cycles_per_sec`` is
    comparable to ``e2e_stream``'s (same formula); ``plan_reuse_hits``/
    ``plan_reuse_misses`` come straight from the per-batch ``stats``.
    ``reuse_speedup`` is the wall-clock ratio (off/on) — the number that
    adjudicates whether topology caching pays on this host.
    """
    import gc
    import tempfile as _tf

    import numpy as np

    from bayesian_consensus_engine_tpu.pipeline import settle_stream
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    per_batch = markets // batches
    rng = np.random.default_rng(17)
    # ONE topology for the whole stream (the persistent universe)...
    counts = rng.poisson(mean_slots - 1, per_batch) + 1
    total = int(counts.sum())
    keys = [f"m-{m}" for m in range(per_batch)]
    sids = [f"src-{v}" for v in rng.integers(0, SOURCE_UNIVERSE, total)]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    # ...and fresh probabilities/outcomes per batch (the only delta).
    batch_data = [
        (
            (keys, sids, rng.random(total), offsets),
            (rng.random(per_batch) < 0.5).tolist(),
        )
        for _ in range(batches)
    ]
    gc.freeze()
    try:
        market_cycles = per_batch * batches * steps

        def run(reuse):
            stats: list = []
            store = TensorReliabilityStore()
            with _tf.TemporaryDirectory() as tmp:
                db = os.path.join(tmp, "stable.db")
                start = time.perf_counter()
                for _result in settle_stream(
                    store, batch_data, steps=steps, now=21_900.0,
                    db_path=db, checkpoint_every=checkpoint_every,
                    columnar=True, stats=stats, reuse_plans=reuse,
                ):
                    pass
                store.sync()
                wall = time.perf_counter() - start

            def sum_of(key):
                return round(
                    sum(s[key] for s in stats if s[key] is not None), 2
                )

            hits = sum(bool(s["plan_reused"]) for s in stats)
            return wall, {
                "wall_s": round(wall, 2),
                "amortised_1m_cycles_per_sec": round(
                    market_cycles / wall / 1e6, 4
                ),
                "ingest_wait_s": sum_of("plan_wait_s"),
                "settle_dispatch_s": sum_of("settle_dispatch_s"),
                "checkpoint_s": sum_of("checkpoint_s"),
                "plan_reuse_hits": hits,
                "plan_reuse_misses": len(stats) - hits,
            }

        # Warm the trace/compile caches with one batch (same shapes both
        # runs settle) so neither timed run pays compilation — whichever
        # run went first would otherwise eat the whole warmup and the
        # speedup ratio would measure compile attribution, not ingest.
        warm_store = TensorReliabilityStore()
        for _result in settle_stream(
            warm_store, batch_data[:1], steps=steps, now=21_900.0,
            columnar=True,
        ):
            pass
        warm_store.sync()
        best = _min_of_trials(
            "e2e_stream_stable_topology", ["no_reuse", "reuse"],
            lambda name: run(reuse=name == "reuse")[1], trials,
        )
    finally:
        gc.unfreeze()
    return {
        "workload": (
            f"{batches} batches x {per_batch} markets x {steps} cycles, "
            f"STABLE topology, checkpoint every {checkpoint_every}, "
            f"min of {trials} alternating trials"
        ),
        "no_reuse": best["no_reuse"],
        "reuse": best["reuse"],
        "reuse_speedup": round(
            best["no_reuse"]["wall_s"] / max(best["reuse"]["wall_s"], 1e-9),
            3,
        ),
    }


def _two_act_stream_workload(markets, batches, mean_slots, steps,
                             resettle_fraction, seed):
    """Two-act steady+drift columnar workload shared by the
    ``e2e_stream_delta`` and ``e2e_stream_resident`` legs: act 1
    re-settles ONE persistent (source, market) universe per batch (the
    steady-state service shape), act 2 re-settles only
    ``resettle_fraction`` of the markets (a prefix slice of the same
    topology — the daily partial re-settlement). One definition so the
    two legs provably benchmark the same shape; the rng draw ORDER is
    part of the contract (seed 29 reproduces the pre-round-7 delta-leg
    workload byte-for-byte). Returns ``(act1, act2, per_batch,
    sub_markets, half, market_cycles)``.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    per_batch = markets // batches
    counts = rng.poisson(mean_slots - 1, per_batch) + 1
    total = int(counts.sum())
    keys = [f"m-{m}" for m in range(per_batch)]
    sids = [f"src-{v}" for v in rng.integers(0, SOURCE_UNIVERSE, total)]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    half = max(1, batches // 2)
    act1 = [
        (
            (keys, sids, rng.random(total), offsets),
            (rng.random(per_batch) < 0.5).tolist(),
        )
        for _ in range(half)
    ]
    sub_markets = max(1, int(per_batch * resettle_fraction))
    sub_total = int(offsets[sub_markets])
    act2 = [
        (
            (keys[:sub_markets], sids[:sub_total], rng.random(sub_total),
             offsets[: sub_markets + 1]),
            (rng.random(sub_markets) < 0.5).tolist(),
        )
        for _ in range(batches - half)
    ]
    market_cycles = (
        per_batch * half + sub_markets * (batches - half)
    ) * steps
    return act1, act2, per_batch, sub_markets, half, market_cycles


def bench_e2e_stream_delta(markets=NUM_MARKETS, batches=6, mean_slots=4,
                           steps=20, checkpoint_every=2,
                           resettle_fraction=0.1, trials=2):
    """Sync-full vs async-delta DURABILITY on the stable-topology stream
    — the round-6 tentpole's A/B (VERDICT r5 gap (a): ``checkpoint_s``
    7.5-9.5 s and ``interchange_export_s`` 15-18 s of a 16.8 s wall).

    Both variants stream the same workload with journal-mode durability:
    a persistent (source, market) universe re-settled per batch
    (``reuse_plans=True``, the steady-state service shape), then a
    second act re-settling only ``resettle_fraction`` of the markets
    (the daily partial re-settlement that makes interchange exports
    delta-shaped). ``sync_full`` writes+fsyncs each epoch in-loop
    (``sync_checkpoints=True`` — the pre-round-6 semantics);
    ``async_delta`` snapshots in-loop and backgrounds the write
    (``sync_checkpoints=False``, the new default), so its serial
    ``checkpoint_s`` is the snapshot+delta-drain alone and the fsync
    shows up (if at all) as the ``journal_async_wait`` join phase.

    Interchange: each variant exports the SQLite file after act 1 (a
    FULL baseline write) and re-exports to the SAME path after act 2 —
    O(rows dirtied since), the incremental fast path with the
    content-fingerprint fallback. ``interchange_delta_rows`` ≪
    ``store_rows`` is the O(dirty) proof; ``checkpoint_serial_speedup``
    (sync/async in-loop checkpoint seconds) is the headline of the A/B
    — strictly > 1 when the background write actually overlapped.
    """
    import gc
    import tempfile as _tf

    from bayesian_consensus_engine_tpu.obs.timeline import (
        PhaseTimeline,
        recording,
    )
    from bayesian_consensus_engine_tpu.pipeline import settle_stream
    from bayesian_consensus_engine_tpu.state.journal import JournalWriter
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    # ONE persistent topology (act 1: the full universe) and a
    # partial-universe act 2 (a prefix slice of the same topology): only
    # those rows dirty between the two exports.
    (act1, act2, per_batch, sub_markets, half,
     market_cycles) = _two_act_stream_workload(
        markets, batches, mean_slots, steps, resettle_fraction, seed=29
    )
    gc.freeze()
    try:
        def run(sync_full):
            stats: list = []
            store = TensorReliabilityStore()
            timeline = PhaseTimeline()
            with _tf.TemporaryDirectory() as tmp:
                db = os.path.join(tmp, "delta.db")
                journal = JournalWriter(os.path.join(tmp, "delta.jrnl"))
                start = time.perf_counter()
                with recording(timeline):
                    for _result in settle_stream(
                        store, act1, steps=steps, now=21_900.0,
                        journal=journal, checkpoint_every=checkpoint_every,
                        columnar=True, stats=stats, reuse_plans=True,
                        sync_checkpoints=sync_full,
                    ):
                        pass
                    t0 = time.perf_counter()
                    full_rows = store.flush_to_sqlite(db)
                    full_s = time.perf_counter() - t0
                    for _result in settle_stream(
                        store, act2, steps=steps, now=21_900.0 + half,
                        journal=journal, checkpoint_every=checkpoint_every,
                        columnar=True, stats=stats, reuse_plans=True,
                        sync_checkpoints=sync_full,
                    ):
                        pass
                    store.sync()
                    t0 = time.perf_counter()
                    delta_rows = store.flush_to_sqlite(db)
                    delta_s = time.perf_counter() - t0
                wall = time.perf_counter() - start
                journal.close()

            checkpoint_s = sum(
                s["checkpoint_s"] for s in stats
                if s["checkpoint_s"] is not None
            )
            phases = {
                k: round(v, 6) for k, v in timeline.totals().items()
            }
            return len(store), checkpoint_s, {
                "wall_s": round(wall, 2),
                "amortised_1m_cycles_per_sec": round(
                    market_cycles / wall / 1e6, 4
                ),
                "checkpoint_s": round(checkpoint_s, 4),
                "journal_fsync_s": phases.get("journal_fsync", 0.0),
                "journal_async_wait_s": phases.get(
                    "journal_async_wait", 0.0
                ),
                "interchange_full_s": round(full_s, 3),
                "interchange_full_rows": full_rows,
                "interchange_delta_s": round(delta_s, 3),
                "interchange_delta_rows": delta_rows,
                "phases": phases,
            }

        # Warm both acts' compiled shapes so neither timed variant pays
        # compilation (whichever ran first would otherwise eat act 2's
        # sub-topology compile and skew the checkpoint A/B).
        warm_store = TensorReliabilityStore()
        for _result in settle_stream(
            warm_store, act1[:1] + act2[:1], steps=steps, now=21_900.0,
            columnar=True,
        ):
            pass
        warm_store.sync()
        rows_seen = {}
        cps = {}

        def run_variant(name):
            rows, cp, out = run(sync_full=name == "sync_full")
            rows_seen[name] = rows
            cps.setdefault(name, []).append(cp)
            return out

        best = _min_of_trials(
            "e2e_stream_delta", ["sync_full", "async_delta"], run_variant,
            trials,
        )
        sync_cp = min(cps["sync_full"])
        async_cp = min(cps["async_delta"])
    finally:
        gc.unfreeze()
    return {
        "workload": (
            f"{half} batches x {per_batch} markets + {batches - half} "
            f"batches x {sub_markets} markets, {steps} cycles, STABLE "
            f"topology, journal epoch every {checkpoint_every}, min of "
            f"{trials} alternating trials"
        ),
        "store_rows": rows_seen["sync_full"],
        "sync_full": best["sync_full"],
        "async_delta": best["async_delta"],
        "checkpoint_serial_speedup": (
            round(sync_cp / async_cp, 3) if async_cp > 0 else None
        ),
    }


def bench_e2e_stream_resident(markets=NUM_MARKETS, batches=6, mean_slots=4,
                              steps=20, checkpoint_every=2,
                              resettle_fraction=0.1, trials=2):
    """Per-batch-session vs PERSISTENT-session sharded streaming — the
    round-7 tentpole's A/B (VERDICT r5 item 1: the ~600× resident-vs-
    service gap traced to ``settle_stream(mesh=)`` building a fresh
    ``ShardedSettlementSession`` per batch, draining the previous batch's
    band gather and re-uploading host state every time).

    Two acts on one journal-mode stream (``reuse_plans=True``, the
    steady-state service shape): act 1 re-settles a persistent
    (source, market) universe per batch (topology HITS — the persistent
    session serves them with a probs-only ``refresh``), act 2 re-settles
    only ``resettle_fraction`` of the markets (one topology MISS
    exercising ``ShardedSettlementSession.adopt``'s resident relayout,
    then hits on the sub-universe). ``per_batch`` streams with
    ``resident_session=False`` (the legacy one-session-per-batch shape);
    ``resident`` holds ONE session across all batches. Variants alternate
    per round with per-repeat ledger records (BASELINE.md min-of-N +
    loadavg protocol).

    The scaling evidence (CPU virtual mesh included): per-batch host
    dispatch cost must scale with rows CHANGED, not store size —
    ``dispatch_s_per_batch_act1`` (full universe) vs ``…_act2`` (the
    small sub-universe re-settled against the full store) quantifies it,
    and the ``state_adopt``/``upload`` phases show the drift act's
    traffic being O(delta). ``resident_speedup`` (min-wall per_batch /
    min-wall resident) is the headline; ``session_adopts`` counts the
    misses the resident session absorbed without teardown. Byte-parity
    of the two shapes is pinned by
    tests/test_overlap.py::TestResidentSessionStream.
    """
    import gc
    import tempfile as _tf

    from bayesian_consensus_engine_tpu.obs.timeline import (
        PhaseTimeline,
        recording,
    )
    from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
    from bayesian_consensus_engine_tpu.pipeline import settle_stream
    from bayesian_consensus_engine_tpu.state.journal import JournalWriter
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    (act1, act2, per_batch_markets, sub_markets, half,
     market_cycles) = _two_act_stream_workload(
        markets, batches, mean_slots, steps, resettle_fraction, seed=31
    )
    gc.freeze()
    try:
        mesh = make_mesh()

        def run(resident):
            stats: list = []
            store = TensorReliabilityStore()
            timeline = PhaseTimeline()
            with _tf.TemporaryDirectory() as tmp:
                journal = JournalWriter(os.path.join(tmp, "resident.jrnl"))
                start = time.perf_counter()
                with recording(timeline):
                    for _result in settle_stream(
                        store, act1 + act2, steps=steps, now=21_900.0,
                        journal=journal, checkpoint_every=checkpoint_every,
                        columnar=True, stats=stats, reuse_plans=True,
                        mesh=mesh, resident_session=resident,
                    ):
                        pass
                    store.sync()
                wall = time.perf_counter() - start
                journal.close()

            def act_dispatch(lo, hi):
                window = stats[lo:hi]
                if not window:
                    return None
                return round(
                    sum(s["settle_dispatch_s"] for s in window)
                    / len(window), 6,
                )

            phases = {k: round(v, 6) for k, v in timeline.totals().items()}
            ingest_wait = sum(s["plan_wait_s"] for s in stats)
            # Steady state excludes batch 0: the pipeline-fill build has
            # nothing to overlap yet (same convention as the per-act
            # dispatch windows below). The ISSUE-8 acceptance band is
            # the STEADY fraction ≈ 0 — every later batch's pack
            # (topology-miss rebuilds included) rides the prefetch
            # thread behind the previous batch's settle.
            ingest_wait_steady = sum(s["plan_wait_s"] for s in stats[1:])
            return {
                "wall_s": round(wall, 2),
                "amortised_1m_cycles_per_sec": round(
                    market_cycles / wall / 1e6, 4
                ),
                "ingest_wait_s": round(ingest_wait, 4),
                "ingest_wait_frac": round(ingest_wait / max(wall, 1e-9), 4),
                "ingest_wait_s_steady": round(ingest_wait_steady, 5),
                "ingest_wait_frac_steady": round(
                    ingest_wait_steady / max(wall, 1e-9), 5
                ),
                # Pair-interning seconds inside those plan builds (zero
                # on plan-reuse refreshes; the pair-DELTA walk on
                # topology misses — the round-15 acceptance next to
                # ingest_wait). Same batch-0 steady convention.
                "intern_s": round(
                    sum(s.get("intern_s", 0.0) for s in stats), 5
                ),
                "intern_s_steady": round(
                    sum(s.get("intern_s", 0.0) for s in stats[1:]), 5
                ),
                "interned_pairs": sum(
                    s.get("interned_pairs", 0) for s in stats
                ),
                # Steady-state windows exclude each act's first batch
                # (act 1's compiles+session start; act 2's adopt).
                "dispatch_s_per_batch_act1": act_dispatch(1, half),
                "dispatch_s_per_batch_act2": act_dispatch(half + 1, batches),
                "adopt_s": phases.get("state_adopt", 0.0),
                "session_adopts": sum(
                    s["session_adopt"] == "relayout"
                    or (s["session_adopt"] or "").startswith("rebuild")
                    for s in stats
                ),
                "session_modes": [s["session_adopt"] for s in stats],
                "plan_reuse_hits": sum(
                    bool(s["plan_reused"]) for s in stats
                ),
                "phases": phases,
            }

        # Warm both acts' compiled shapes (one batch each) so neither
        # timed variant pays compilation.
        warm_store = TensorReliabilityStore()
        for _result in settle_stream(
            warm_store, act1[:1] + act2[:1], steps=steps, now=21_900.0,
            columnar=True, mesh=mesh, reuse_plans=True,
        ):
            pass
        warm_store.sync()
        best = _min_of_trials(
            "e2e_stream_resident", ["per_batch", "resident"],
            lambda name: run(resident=name == "resident"), trials,
        )
    finally:
        gc.unfreeze()
    return {
        "workload": (
            f"{half} batches x {per_batch_markets} markets + "
            f"{batches - half} batches x {sub_markets} markets, {steps} "
            f"cycles, journal epoch every {checkpoint_every}, sharded "
            f"mesh {tuple(mesh.devices.shape)}, min of {trials} "
            "alternating trials"
        ),
        "per_batch": best["per_batch"],
        "resident": best["resident"],
        "resident_speedup": round(
            best["per_batch"]["wall_s"]
            / max(best["resident"]["wall_s"], 1e-9), 3,
        ),
    }


def bench_e2e_serve(markets=2000, source_universe=500, requests=3000,
                    hot_fraction=0.1, hot_share=0.8, concurrency=32,
                    max_batch=128, max_delay_ms=2.0, steps=5,
                    checkpoint_every=4, slo_ms=50.0, trials=2):
    """Latency under load for the round-8 serving front end — the leg
    that makes p50/p99 a measured band next to throughput (ROADMAP
    item 1: "latency under load becomes a first-class measured number").

    One request = one market's signal update + outcome report through
    :class:`~.serve.coalesce.ConsensusService` (journal-mode durability,
    resident sharded session, per-request latency histograms). Each
    market keeps a FIXED source set across requests, so steady window
    composition re-creates stable topologies and the plan cache serves
    refreshes; market choice is hot-skewed (*hot_share* of requests land
    on the *hot_fraction* hottest markets — the coalescer's duplicate-
    market windowing is exactly what hot keys stress). Three acts, run
    as min-of-N alternating variants (BASELINE.md protocol):

    * ``closed_loop`` — *concurrency* clients, each awaiting its result
      before submitting the next: self-pacing, measures the sustainable
      service rate (``throughput_rps``) and its latency floor.
    * ``open_loop`` — Poisson arrivals at ~70% of the closed-loop rate
      (the measured rate from this round's closed-loop run): arrival
      jitter and coalescing delay included, the production regime.
    * ``overload`` — the whole request load offered as ONE burst into a
      small bounded queue (``reject`` policy): the act that proves
      admission control keeps p99 bounded — rejections absorb the excess
      by construction (no rate calibration to go stale), pending never
      exceeds the bound, and the p99 of ADMITTED requests stays a
      latency, not a queue length.

    Per-request distributions (the ``serve.latency_total_s`` histogram)
    ride to the run ledger as ``latency_hist`` extras, which is what the
    ``bce-tpu stats`` p50/p99 columns render.

    Round 9: every act declares a latency SLO (*slo_ms*, submit →
    durable) and reports ``goodput_within_slo`` — met / (met + violated
    + shed + rejected), refused traffic counting AGAINST — so the
    overload act's headline is goodput-under-objective, not raw p99
    alone (a bounded queue that rejects half its offered load has a fine
    p99 and a terrible goodput; both now show). The per-act SLO counts
    ride to the run ledger as ``extras.slo`` and render as the ``bce-tpu
    stats`` ``goodput`` column; live ``hbm.*`` gauge samples (device
    memory at the dispatch/checkpoint phase boundaries — zeros on CPU
    backends) ride along in the act dicts.
    """
    import asyncio
    import gc
    import tempfile as _tf

    from bayesian_consensus_engine_tpu import obs
    from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
    from bayesian_consensus_engine_tpu.serve import (
        AdmissionConfig,
        ConsensusService,
        Overloaded,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )
    import numpy as np

    rng = np.random.default_rng(41)
    hot_markets = max(1, int(markets * hot_fraction))
    source_lists = [
        [f"src-{v}" for v in rng.integers(0, source_universe, n)]
        for n in rng.integers(1, 4, markets)
    ]

    def request_stream(n, seed):
        """Deterministic (market, signals, outcome) request sequence."""
        req_rng = np.random.default_rng(seed)
        hot = req_rng.random(n) < hot_share
        market_ids = np.where(
            hot,
            req_rng.integers(0, hot_markets, n),
            req_rng.integers(0, markets, n),
        )
        for i in range(n):
            market = int(market_ids[i])
            sources = source_lists[market]
            probs = req_rng.random(len(sources))
            yield (
                f"m-{market}",
                list(zip(sources, probs)),
                bool(req_rng.random() < 0.5),
            )

    mesh = make_mesh()
    closed_rate = [None]  # measured by closed_loop; paces the open acts

    # Warm the compiled settle shapes before timing: hot-skewed windows
    # wobble in composition, so the bucketed K ladder compiles a handful
    # of programs — the warm pass eats them off the clock (the same
    # request prefix every timed act replays).
    warm_store = TensorReliabilityStore()

    async def _warm():
        service = ConsensusService(
            warm_store, steps=steps, now=21_900.0, mesh=mesh,
            max_batch=max_batch, max_delay_s=max_delay_ms / 1e3,
        )
        async with service:
            for req in request_stream(min(requests, 4 * max_batch), 97):
                service.submit(*req)
            await service.drain()

    asyncio.run(_warm())
    warm_store.sync()

    def run(name):
        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        gc.freeze()
        try:
            store = TensorReliabilityStore()
            with _tf.TemporaryDirectory() as tmp:
                admission = (
                    AdmissionConfig(
                        max_pending=max(64, max_batch), policy="reject",
                        retry_after_s=max_delay_ms / 1e3,
                    )
                    if name == "overload"
                    else AdmissionConfig(max_pending=1 << 20)
                )
                service = ConsensusService(
                    store, steps=steps, now=21_900.0, mesh=mesh,
                    journal=os.path.join(tmp, "serve.jrnl"),
                    checkpoint_every=checkpoint_every,
                    max_batch=max_batch, max_delay_s=max_delay_ms / 1e3,
                    admission=admission,
                    slo=obs.LatencyObjective(slo_ms / 1e3),
                )
                counts = {"served": 0, "rejected": 0, "failed": 0,
                          "max_pending": 0}

                async def closed_loop():
                    stream = request_stream(requests, seed=97)
                    lock = asyncio.Lock()

                    async def client():
                        while True:
                            async with lock:
                                try:
                                    req = next(stream)
                                except StopIteration:
                                    return
                            try:
                                await service.submit(*req)
                                counts["served"] += 1
                            except Overloaded:
                                counts["rejected"] += 1

                    await asyncio.gather(
                        *(client() for _ in range(concurrency))
                    )

                async def open_loop(rate):
                    """Poisson arrivals at *rate*; ``None`` = one burst
                    (every request offered back to back without yielding
                    to the loop — in-flight completions cannot free
                    admission slots mid-burst, so overload is guaranteed
                    by construction, not by rate calibration)."""
                    loop = asyncio.get_running_loop()
                    if rate is not None:
                        arrivals = np.cumsum(
                            np.random.default_rng(83).exponential(
                                1.0 / rate, requests
                            )
                        )
                    futures = []
                    t0 = loop.time()
                    for i, req in enumerate(request_stream(requests, 97)):
                        if rate is not None:
                            delay = t0 + arrivals[i] - loop.time()
                            if delay > 0:
                                await asyncio.sleep(delay)
                        try:
                            futures.append(service.submit(*req))
                        except Overloaded:
                            counts["rejected"] += 1
                        counts["max_pending"] = max(
                            counts["max_pending"], service.pending_requests
                        )
                    await service.drain()
                    for future in futures:
                        if future.exception() is None:
                            counts["served"] += 1
                        else:
                            counts["failed"] += 1

                async def act():
                    async with service:
                        if name == "closed_loop":
                            await closed_loop()
                        elif name == "open_loop":
                            rate = (closed_rate[0] or 200.0) * 0.7
                            await open_loop(rate)
                        else:
                            # The burst shape: overload must actually
                            # overload on every host, or the act proves
                            # nothing (a rate calibrated off the closed
                            # loop understates open-loop capacity — full
                            # windows serve several times faster).
                            await open_loop(None)
                        await service.drain()

                start = time.perf_counter()
                asyncio.run(act())
                wall = time.perf_counter() - start
                store.sync()

            total = registry.histogram("serve.latency_total_s")
            snapshot = total.snapshot()
            summary = total.summary((0.5, 0.99))
            dispatch = registry.histogram(
                "serve.latency_dispatch_s"
            ).summary((0.5, 0.99))
            counters = registry.export()["counters"]
            gauges = registry.export()["gauges"]
            slo_snap = service.goodput()
            throughput = counts["served"] / wall if wall > 0 else 0.0
            if name == "closed_loop":
                closed_rate[0] = throughput
            out = {
                "wall_s": round(wall, 3),
                "requests_offered": requests,
                "served": counts["served"],
                "rejected": counts["rejected"],
                "shed": counters.get("serve.shed", 0),
                "failed": counts["failed"],
                "batches": counters.get("serve.batches", 0),
                "mean_batch_fill": round(
                    counts["served"] / max(counters.get("serve.batches", 1),
                                           1), 2,
                ),
                "throughput_rps": round(throughput, 1),
                "p50_ms": _q_ms(summary["p50"]),
                "p99_ms": _q_ms(summary["p99"]),
                "dispatch_p50_ms": _q_ms(dispatch["p50"]),
                "dispatch_p99_ms": _q_ms(dispatch["p99"]),
                "max_pending_seen": counts["max_pending"],
                # Dispatch-worker seconds blocked on plan builds (the
                # pack thread overlaps staging with device compute; ≈ 0
                # steady-state — the ISSUE-8 served-path acceptance).
                "ingest_wait_s": round(service.ingest_wait_s, 4),
                "ingest_wait_frac": round(
                    service.ingest_wait_s / max(wall, 1e-9), 4
                ),
                # The slice of that wait inside the pair-interning pass
                # (cannot overlap onto the pack thread — interning order
                # IS row assignment; the epoch-persistent pair table is
                # what keeps it ≈ 0 under drift, round 15).
                "intern_s": round(service.intern_wait_s, 5),
                # Goodput-under-objective: the resilience headline the
                # overload act exists for (refused requests count
                # against — raw p99 alone cannot see them).
                "goodput_within_slo": (
                    None if slo_snap["goodput_within_slo"] is None
                    else round(slo_snap["goodput_within_slo"], 4)
                ),
                "slo": {
                    "objective_ms": slo_ms,
                    "counts": slo_snap["counts"],
                    "window_goodput": (
                        slo_snap["window"]["goodput_within_slo"]
                    ),
                },
                # Live device memory at the phase boundaries (zeros on
                # backends without allocator stats).
                "hbm_bytes_in_use": gauges.get("hbm.bytes_in_use"),
                "hbm_peak_bytes": gauges.get("hbm.peak_bytes"),
            }
            # Per-request distribution + SLO accounting to the ledger:
            # the stats table's p50/p99/goodput columns merge these
            # across repeats.
            _ledger_record(
                f"e2e_serve.{name}.latency",
                value=summary["p99"], unit="s",
                extras={
                    "latency_hist": {
                        "bounds": snapshot["bounds"],
                        "counts": snapshot["counts"],
                    },
                    "slo": {
                        "objective_s": slo_ms / 1e3,
                        "counts": slo_snap["counts"],
                    },
                },
            )
            return out
        finally:
            gc.unfreeze()
            obs.set_metrics_registry(previous)

    best = _min_of_trials(
        "e2e_serve", ["closed_loop", "open_loop", "overload"], run, trials,
    )
    overload = best["overload"]
    return {
        "workload": (
            f"{requests} requests x {markets} markets ({hot_markets} hot, "
            f"{hot_share:.0%} of traffic), fixed per-market source sets, "
            f"max_batch={max_batch}, max_delay={max_delay_ms}ms, journal "
            f"epoch every {checkpoint_every} batches, SLO {slo_ms}ms "
            f"(submit->durable), min of {trials} alternating trials"
        ),
        "closed_loop": best["closed_loop"],
        "open_loop": best["open_loop"],
        "overload": overload,
        # The bounded-overload claim as data: the queue never outgrew the
        # admission bound and rejections (not latency) absorbed the rest.
        "overload_bounded": bool(
            overload["rejected"] > 0
            and overload["max_pending_seen"] <= max(64, max_batch)
        ),
    }


def _q_ms(quantile_s):
    """Histogram quantile (seconds) → milliseconds for bench output."""
    return None if quantile_s is None else round(quantile_s * 1e3, 3)


def bench_e2e_netserve(markets=600, source_universe=150, requests=1600,
                       concurrency=12, acceptors=4, max_batch=64,
                       max_delay_ms=2.0, steps=3, premium_slo_ms=120.0,
                       besteffort_slo_ms=120.0, besteffort_budget=24,
                       trials=2):
    """The round-17 front-door leg: mixed-class overload over the REAL
    socket transport (net/), where premium-class goodput HOLDS while the
    best-effort class sheds — the Ironwood "goodput under objective"
    framing applied at the request tier, now with tenants.

    One request = one market's update through
    :class:`~.net.client.ConsensusClient` (blocking, one TCP connection
    per load thread) → :class:`~.net.server.ConsensusServer` (N asyncio
    acceptors) → the ONE coalescing :class:`ConsensusService` with two
    :class:`~.serve.admission.QosClass` tenants: ``premium``
    (*premium_slo_ms*, a budget sized to the offered load, reject
    policy) and ``besteffort`` (same objective, a deliberately small
    *besteffort_budget*, ``shed_oldest`` → the variance-aware policy).
    Two acts, min-of-N alternating (BASELINE.md protocol):

    * ``closed_loop`` — *concurrency* premium clients, each awaiting its
      result before the next submit: the sustainable wire-path service
      rate and the premium class's BASELINE goodput band.
    * ``overload_mixed`` — the same premium closed-loop load running
      WHILE a best-effort client offers its whole request share as one
      pipelined burst into the small best-effort budget: overload by
      construction. Acceptance: premium ``goodput_within_slo`` holds at
      its closed-loop baseline (``premium_holds`` compares the acts)
      while best-effort sheds (``besteffort_sheds``: shed+rejected > 0).

    Per-act per-class accounting (``service.qos_snapshot()``) rides to
    the run ledger as ``extras.qos`` — the ``bce-tpu stats`` per-class
    goodput/slo columns, merged across repeats and diffed by
    ``--against`` — next to the usual ``latency_hist``/``slo`` extras.
    """
    import asyncio
    import gc
    import tempfile as _tf

    from bayesian_consensus_engine_tpu import obs
    from bayesian_consensus_engine_tpu.net import (
        ConsensusClient,
        ConsensusServer,
    )
    from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
    from bayesian_consensus_engine_tpu.serve import (
        ConsensusService,
        Overloaded,
        QosClass,
        ShedError,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )
    import numpy as np

    rng = np.random.default_rng(53)
    source_lists = [
        [f"src-{v}" for v in rng.integers(0, source_universe, n)]
        for n in rng.integers(1, 4, markets)
    ]

    def request_stream(n, seed, prefix="m"):
        req_rng = np.random.default_rng(seed)
        market_ids = req_rng.integers(0, markets, n)
        for i in range(n):
            market = int(market_ids[i])
            sources = source_lists[market]
            probs = req_rng.random(len(sources))
            yield (
                f"{prefix}-{market}",
                list(zip(sources, probs)),
                bool(req_rng.random() < 0.5),
            )

    mesh = make_mesh()

    # Warm the compiled settle shapes off the clock (same discipline as
    # e2e_serve): the bucketed K ladder compiles a handful of programs.
    warm_store = TensorReliabilityStore()

    async def _warm():
        service = ConsensusService(
            warm_store, steps=steps, now=21_900.0, mesh=mesh,
            max_batch=max_batch, max_delay_s=max_delay_ms / 1e3,
        )
        async with service:
            for req in request_stream(min(requests, 4 * max_batch), 11):
                service.submit(*req)
            await service.drain()

    asyncio.run(_warm())
    warm_store.sync()

    premium_requests = requests // 2
    besteffort_requests = requests - premium_requests

    def run(name):
        registry = obs.MetricsRegistry()
        previous = obs.set_metrics_registry(registry)
        gc.freeze()
        try:
            store = TensorReliabilityStore()
            with _tf.TemporaryDirectory() as tmp:
                qos = [
                    QosClass(
                        "premium", premium_slo_ms / 1e3,
                        max(concurrency * 4, 64),
                    ),
                    QosClass(
                        "besteffort", besteffort_slo_ms / 1e3,
                        besteffort_budget, policy="shed_oldest",
                    ),
                ]
                service = ConsensusService(
                    store, steps=steps, now=21_900.0, mesh=mesh,
                    journal=os.path.join(tmp, "netserve.jrnl"),
                    checkpoint_every=4,
                    max_batch=max_batch, max_delay_s=max_delay_ms / 1e3,
                    qos=qos,
                    slo=obs.LatencyObjective(premium_slo_ms / 1e3),
                )
                counts = {"served": 0, "refused": 0}

                def premium_worker(worker_requests, port):
                    served = refused = 0
                    with ConsensusClient(port=port) as client:
                        for req in worker_requests:
                            try:
                                client.submit(*req, qos_class="premium")
                                served += 1
                            except (Overloaded, ShedError):
                                refused += 1
                    return served, refused

                def besteffort_burst(burst, port):
                    # The whole best-effort share pipelined back to back
                    # on one connection: the coalescer sees a burst the
                    # small budget cannot hold — shedding is guaranteed
                    # by construction, not by rate calibration.
                    with ConsensusClient(port=port) as client:
                        results = client.submit_pipelined(
                            burst, qos_class="besteffort"
                        )
                    served = sum(
                        1 for r in results
                        if not isinstance(r, BaseException)
                    )
                    return served, len(results) - served

                async def act():
                    import concurrent.futures as _cf

                    server = await ConsensusServer(
                        service, acceptors=acceptors
                    ).start()
                    loop = asyncio.get_running_loop()
                    port = server.port
                    # One thread per load client: the default executor
                    # caps at cpu+4 workers, which on a small host would
                    # QUEUE the best-effort burst behind the premium
                    # clients and quietly remove the overlap the
                    # overload act exists to create.
                    pool = _cf.ThreadPoolExecutor(
                        max_workers=concurrency + 1,
                        thread_name_prefix="bce-netserve-load",
                    )
                    try:
                        premium = list(
                            request_stream(premium_requests, 19)
                        )
                        shards = [
                            premium[i::concurrency]
                            for i in range(concurrency)
                        ]
                        jobs = []
                        if name == "overload_mixed":
                            # The burst launches FIRST so the small
                            # best-effort budget is already overflowing
                            # while premium traffic runs.
                            burst = list(request_stream(
                                besteffort_requests, 23, prefix="be",
                            ))
                            jobs.append(loop.run_in_executor(
                                pool, besteffort_burst, burst, port
                            ))
                        jobs.extend(
                            loop.run_in_executor(
                                pool, premium_worker, shard, port
                            )
                            for shard in shards if shard
                        )
                        for served, refused in await asyncio.gather(*jobs):
                            counts["served"] += served
                            counts["refused"] += refused
                        await service.drain()
                    finally:
                        pool.shutdown(wait=True)
                        await server.close()
                        await service.close()

                start = time.perf_counter()
                asyncio.run(act())
                wall = time.perf_counter() - start
                store.sync()

            qos_snap = service.qos_snapshot()
            slo_snap = service.goodput()
            total = registry.histogram("serve.latency_total_s")
            snapshot = total.snapshot()
            summary = total.summary((0.5, 0.99))
            counters = registry.export()["counters"]

            def class_out(cls):
                record = qos_snap[cls]
                return {
                    "offered": record["offered"],
                    "counts": record["counts"],
                    "goodput_within_slo": (
                        None if record["goodput_within_slo"] is None
                        else round(record["goodput_within_slo"], 4)
                    ),
                }

            out = {
                "wall_s": round(wall, 3),
                "served": counts["served"],
                "refused": counts["refused"],
                "throughput_rps": round(
                    counts["served"] / wall if wall > 0 else 0.0, 1
                ),
                "batches": counters.get("serve.batches", 0),
                "connections": counters.get("net.connections", 0),
                "wire_errors": counters.get("net.wire_errors", 0),
                "p50_ms": _q_ms(summary["p50"]),
                "p99_ms": _q_ms(summary["p99"]),
                "premium": class_out("premium"),
                "besteffort": class_out("besteffort"),
                "ingest_wait_s": round(service.ingest_wait_s, 4),
                "intern_s": round(service.intern_wait_s, 5),
            }
            # Per-class accounting to the ledger: the stats table's
            # qos follow-up line merges these across repeats.
            _ledger_record(
                f"e2e_netserve.{name}.latency",
                value=summary["p99"], unit="s",
                extras={
                    "latency_hist": {
                        "bounds": snapshot["bounds"],
                        "counts": snapshot["counts"],
                    },
                    "slo": {
                        "objective_s": premium_slo_ms / 1e3,
                        "counts": slo_snap["counts"],
                    },
                    "qos": {
                        cls: {
                            "slo_s": qos_snap[cls]["slo_s"],
                            "counts": qos_snap[cls]["counts"],
                        }
                        for cls in qos_snap
                    },
                },
            )
            return out
        finally:
            gc.unfreeze()
            obs.set_metrics_registry(previous)

    best = _min_of_trials(
        "e2e_netserve", ["closed_loop", "overload_mixed"], run, trials,
    )
    closed, overload = best["closed_loop"], best["overload_mixed"]
    closed_premium = closed["premium"]["goodput_within_slo"] or 0.0
    overload_premium = overload["premium"]["goodput_within_slo"] or 0.0
    besteffort_counts = overload["besteffort"]["counts"]
    besteffort_refused = (
        besteffort_counts.get("shed", 0)
        + besteffort_counts.get("rejected", 0)
    )
    return {
        "workload": (
            f"{requests} requests x {markets} markets over the net/ "
            f"socket transport ({concurrency} premium closed-loop "
            f"clients, best-effort burst of {besteffort_requests} into a "
            f"{besteffort_budget}-deep shed_oldest budget), "
            f"max_batch={max_batch}, premium SLO {premium_slo_ms}ms, "
            f"min of {trials} alternating trials"
        ),
        "closed_loop": closed,
        "overload_mixed": overload,
        # The acceptance pair: the premium tenant's goodput under mixed
        # overload holds at (a small tolerance under) its closed-loop
        # baseline, while the best-effort tenant absorbed the overload
        # as explicit policy.
        "premium_goodput_closed": closed_premium,
        "premium_goodput_overload": overload_premium,
        "premium_holds": bool(
            overload_premium >= closed_premium - 0.05
        ),
        "besteffort_refused": besteffort_refused,
        "besteffort_sheds": bool(besteffort_refused > 0),
    }


def bench_obs_overhead(markets=60_000, batches=3, mean_slots=4, steps=10,
                       trials=3):
    """The obs contract's A/B/C: the streamed service with observability
    DISABLED vs fully ENABLED (phase timeline recording + live metrics
    registry + per-batch ``phases`` stats) vs ENABLED + TRACED
    (request-scoped tracer recording every batch's span chain).

    obs promises provably-zero disabled overhead and ≤1% enabled overhead
    on the e2e stream; this leg measures the second claim (the first is a
    structural property — null-object singletons — pinned by
    tests/test_obs.py). Round 9 extends the same contract to tracing:
    ``trace_overhead_ratio`` is traced/obs-on wall, and
    ``trace_within_1pct`` asserts tracing adds ≤1% over obs-only on the
    stream leg. All runs stream the same pre-generated columnar batches
    through the eager rolling-SQLite loop, alternating min-of-N
    (*trials*) after one shared warmup, so compile attribution and load
    bursts fall evenly — on this externally-loaded 1-core host a single
    short run swings several-fold (the min converges; the mean lies).
    ``overhead_ratio`` is on/off wall (min-of-N each); ``phases`` is the
    enabled run's timeline total — the named decomposition of the stream
    wall the 600×-gap work navigates by.
    """
    import gc
    import tempfile as _tf

    import numpy as np

    from bayesian_consensus_engine_tpu.obs import (
        MetricsRegistry,
        PhaseTimeline,
        Tracer,
        recording,
        set_metrics_registry,
        set_tracer,
    )
    from bayesian_consensus_engine_tpu.pipeline import settle_stream
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    per_batch = markets // batches
    rng = np.random.default_rng(23)
    batch_data = []
    for b in range(batches):
        counts = rng.poisson(mean_slots - 1, per_batch) + 1
        total = int(counts.sum())
        keys = [f"b{b}-m{m}" for m in range(per_batch)]
        sids = [f"src-{v}" for v in rng.integers(0, SOURCE_UNIVERSE, total)]
        probs = rng.random(total)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        outcomes = (rng.random(per_batch) < 0.5).tolist()
        batch_data.append(((keys, sids, probs, offsets), outcomes))
    gc.freeze()
    try:

        def run(enabled, traced=False):
            store = TensorReliabilityStore()
            stats: list = []
            timeline = PhaseTimeline() if enabled else None
            previous = (
                set_metrics_registry(MetricsRegistry()) if enabled else None
            )
            tracer = Tracer() if traced else None
            previous_tracer = set_tracer(tracer) if traced else None
            try:
                with _tf.TemporaryDirectory() as tmp:
                    db = os.path.join(tmp, "obs.db")
                    start = time.perf_counter()
                    with recording(timeline):
                        for _result in settle_stream(
                            store, batch_data, steps=steps, now=21_900.0,
                            db_path=db, checkpoint_every=1, columnar=True,
                            stats=stats,
                        ):
                            pass
                        store.sync()
                    wall = time.perf_counter() - start
            finally:
                if traced:
                    set_tracer(previous_tracer)
                if enabled:
                    set_metrics_registry(previous)
            phases = timeline.totals() if timeline is not None else {}
            events = len(tracer.events()) if tracer is not None else 0
            return wall, phases, stats, events

        run(enabled=False)  # shared warmup: compiles land on nobody's clock
        wall_off = wall_on = wall_traced = float("inf")
        phases_on = {}
        trace_events = 0
        for _trial in range(trials):
            off, _p, _s, _e = run(enabled=False)
            wall_off = min(wall_off, off)
            on, phases, stats, _e = run(enabled=True)
            if on < wall_on:
                wall_on, phases_on = on, phases
            traced, _p, _s, events = run(enabled=True, traced=True)
            if traced < wall_traced:
                wall_traced, trace_events = traced, events
        assert all("phases" in s for s in stats)
        return {
            "workload": (
                f"{batches} batches x {per_batch} markets x {steps} "
                f"cycles, eager checkpoints, min of {trials} "
                f"alternating trials"
            ),
            "obs_off_wall_s": round(wall_off, 3),
            "obs_on_wall_s": round(wall_on, 3),
            "overhead_ratio": round(wall_on / wall_off, 4),
            "within_1pct": wall_on / wall_off <= 1.01,
            # The tracing leg of the same contract: batch span chains
            # recorded for every batch, ≤1% over obs-only.
            "obs_trace_wall_s": round(wall_traced, 3),
            "trace_overhead_ratio": round(wall_traced / wall_on, 4),
            "trace_within_1pct": wall_traced / wall_on <= 1.01,
            "trace_events": trace_events,
            "phases": {k: round(v, 4) for k, v in phases_on.items()},
        }
    finally:
        gc.unfreeze()


def bench_dispatch_rtt(trials=5):
    """Pure tunnel dispatch+fence round trip: a jitted 8-element add.

    This is the fixed cost every dispatch pays through the axon tunnel —
    the denominator correction for every other number here (measured
    ~96 ms on this host, i.e. 1600 amortising steps put it at ~6%).
    """
    import jax
    import jax.numpy as jnp

    tiny = jax.jit(lambda x: x + 1.0)
    a = jnp.zeros((8,), jnp.float32)
    _fence(tiny(a))
    best = float("inf")
    for _ in range(trials):
        start = time.perf_counter()
        _fence(tiny(a))
        best = min(best, time.perf_counter() - start)
    return best * 1e3


def bench_stream_probe(steps=400):
    """Live streaming roofline: read+write two f32 blocks per step (GB/s).

    The axon tunnel's delivered bandwidth varies run to run (measured
    anywhere from ~140 to ~410 GB/s on this host); this number is the
    denominator that makes cycle throughput comparable across rounds.
    """
    import jax
    import jax.numpy as jnp

    k, m = SLOTS_PER_MARKET, 1_000_448

    def loop(a, b):
        return jax.lax.fori_loop(
            0, steps, lambda i, c: (c[1] + 1.0, c[0] * 0.5), (a, b)
        )

    sl = jax.jit(loop, donate_argnums=(0, 1))

    def fresh():
        a = jnp.ones((k, m), jnp.float32)
        b = jnp.ones((k, m), jnp.float32)
        _fence(a)
        return a, b

    out = sl(*fresh())
    _fence(out[0])
    best = float("inf")
    for _ in range(3):
        a, b = fresh()
        start = time.perf_counter()
        out = sl(a, b)
        _fence(out[0])
        best = min(best, (time.perf_counter() - start) / steps)
    return 4 * k * m * 4 / best / 1e9  # 2 tensors read + 2 written per step


def bench_compact(num_markets=NUM_MARKETS, slots=SLOTS_PER_MARKET,
                  timed_steps=TIMED_STEPS):
    """The counter-compact loop (parallel/compact.py) at the headline shape.

    Inputs come from the same ``headline_inputs`` as bench_headline, so the
    compact-vs-headline numbers stay apples-to-apples by construction.
    """
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import (
        build_compact_cycle_loop,
        init_compact_state,
    )

    mesh, probs, mask, outcome, padded_total, block_sharding = headline_inputs(
        num_markets, slots
    )
    loop = build_compact_cycle_loop(mesh, donate=True)

    def fresh_state():
        state = init_compact_state(padded_total, slots)
        if mesh is not None:
            state = jax.tree.map(
                lambda x: jax.device_put(x, block_sharding), state
            )
        _fence(state.updated_days)
        return state

    return timed_best_of(
        lambda s: loop(probs, mask, outcome, s, jnp.asarray(1.0, jnp.float32),
                       timed_steps),
        fresh_state,
        timed_steps,
    )


def bench_tiebreak_stress(markets=2048, agents=10_000, reps=3):
    """BASELINE config #4: deterministic tie-break at 10k agents per market.

    Runs BOTH at-scale groupings on the chip — the ring/pairwise path
    (parallel/ring.py, O(A²) compares that XLA fuses) and the sort-based
    path (ops/tiebreak.py, O(A log A) but bottlenecked by XLA's TPU sort)
    — and reports markets-resolved/sec plus the compiled memory footprint
    of the ring call (its origin buffer is the documented at-scale risk;
    single-chip ring_size=1 keeps it one block — shard markets too when
    multi-device, tests/test_ring.py::test_markets_axis_sharded_too).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.ops.tiebreak import build_batched_tiebreak
    from bayesian_consensus_engine_tpu.parallel.ring import build_ring_tiebreak

    rng = np.random.default_rng(11)
    grid = np.round(np.linspace(0.05, 0.95, 37), 6)
    args = (
        jnp.asarray(rng.choice(grid, (markets, agents)), jnp.float32),
        jnp.asarray(rng.uniform(0.1, 2.0, (markets, agents)), jnp.float32),
        jnp.asarray(rng.uniform(0, 1, (markets, agents)), jnp.float32),
        jnp.asarray(rng.uniform(0, 1, (markets, agents)), jnp.float32),
        jnp.asarray(rng.random((markets, agents)) < 0.9),
    )

    def best_of(fn):
        out = fn(*args)
        _fence(out.prediction)
        best = float("inf")
        for _ in range(reps):
            start = time.perf_counter()
            out = fn(*args)
            _fence(out.prediction)
            best = min(best, time.perf_counter() - start)
        return markets / best

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources"))
    # AOT-compile once and time the executable itself — .lower().compile()
    # does not seed the jit call cache, so timing `ring` would recompile.
    compiled = build_ring_tiebreak(mesh).lower(*args).compile()
    mem = compiled.memory_analysis()
    ring_rate = best_of(lambda *a: compiled(*a))
    sorted_rate = best_of(build_batched_tiebreak())

    return {
        "workload": f"{markets} markets x {agents} agents",
        "ring_markets_per_sec": round(ring_rate, 1),
        "sorted_markets_per_sec": round(sorted_rate, 1),
        "ring_compiled_temp_mb": round(mem.temp_size_in_bytes / 1e6, 1),
        "ring_compiled_args_mb": round(mem.argument_size_in_bytes / 1e6, 1),
    }


def bench_e2e_ring_memory(markets=2048, agents=10_000, chunk_agents=1024,
                          fused_slots=512, reps=3, trials=2):
    """ISSUE-9 acceptance leg: the chunked ring tie-break memory diet.

    Three captures on one shape:

    1. **Static footprint** — both tie-break programs AOT-lowered and
       ``memory_analysis()``-read: ``compiled_temp_bytes`` /
       ``arg_bytes`` for the unchunked (pre-round-11) accumulation vs the
       chunked one. The acceptance bar lives here: chunked temps ≤ ~100 MB
       at 2048×10k on TPU (where XLA fuses the per-chunk compare; a CPU
       backend materialises the compare mask per chunk, so CPU temp
       numbers are larger but show the same chunked/unchunked ratio).
    2. **Throughput A/B** — markets/sec for both variants under the
       min-of-N + loadavg protocol (`_min_of_trials`, alternating rounds);
       acceptance is equal-or-better for the chunked path with NO losing
       trial (``no_losing_trial`` folds every round's ratio). The live
       ``hbm.*`` view rides along at LEG level: ``hbm_peak_bytes`` is the
       allocator's process-lifetime high-water mark (monotone, so it
       cannot attribute per variant once both programs have run —
       per-program attribution is the AOT capture's job) and feeds the
       ``bce-tpu stats`` peak_mem column through a leg-level ledger
       record; absent on backends without allocator stats (CPU).
    3. **Fused co-resident program** — ``memory_analysis()`` of
       ``build_cycle_tiebreak_loop`` (cycle + tie-break, ONE program per
       chip) at (markets × fused_slots), next to the sum of the two
       separate programs it replaces, plus a live
       ``ShardedSettlementSession.settle_with_tiebreak`` dispatch
       timing at a small plan — the payoff the diet was named the
       prerequisite for.

    ``chunk_agents`` is the recorded default; with ``BCE_AUTOTUNE=1`` the
    chunked variant resolves through the shape tuner instead and the
    recorded verdict is reported as ``autotune_decision`` (honesty guard:
    the tuned value shipped only if it beat this default on the same
    clock).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.parallel.ring import (
        build_ring_tiebreak,
    )
    from bayesian_consensus_engine_tpu.utils.autotune import default_tuner
    from bayesian_consensus_engine_tpu.utils.profiling import (
        device_memory_stats,
    )

    rng = np.random.default_rng(11)
    grid = np.round(np.linspace(0.05, 0.95, 37), 6)
    args = (
        jnp.asarray(rng.choice(grid, (markets, agents)), jnp.float32),
        jnp.asarray(rng.uniform(0.1, 2.0, (markets, agents)), jnp.float32),
        jnp.asarray(rng.uniform(0, 1, (markets, agents)), jnp.float32),
        jnp.asarray(rng.uniform(0, 1, (markets, agents)), jnp.float32),
        jnp.asarray(rng.random((markets, agents)) < 0.9),
    )
    mesh = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources")
    )

    tuner = default_tuner()
    chunk_arg = "auto" if tuner.enabled else chunk_agents
    builders = {
        "unchunked": build_ring_tiebreak(mesh, chunk_agents=None),
        "chunked": build_ring_tiebreak(mesh, chunk_agents=chunk_arg),
    }
    # AOT: lower+compile once per variant and time the executables —
    # memory_analysis is read off the same compiled object that runs.
    compiled = {}
    memory = {}
    for name, builder in builders.items():
        exe = builder.lower(*args).compile()
        mem = exe.memory_analysis()
        compiled[name] = exe
        memory[name] = {
            "compiled_temp_bytes": int(mem.temp_size_in_bytes),
            "arg_bytes": int(mem.argument_size_in_bytes),
        }

    def run_variant(name):
        exe = compiled[name]
        best = float("inf")
        for _ in range(reps):
            start = time.perf_counter()
            out = exe(*args)
            _fence(out.prediction)
            best = min(best, time.perf_counter() - start)
        return {
            "wall_s": round(best, 4),
            "markets_per_sec": round(markets / best, 1),
            **memory[name],
        }

    # Warm both executables off the clock (first dispatch pays transfer
    # layout work even AOT-compiled).
    for exe in compiled.values():
        _fence(exe(*args).prediction)
    best = _min_of_trials(
        "e2e_ring_memory", ["unchunked", "chunked"], run_variant, trials
    )
    # Runtime memory is a LEG-level number by necessity:
    # peak_bytes_in_use is the allocator's process-lifetime high-water
    # mark (monotone — it never attributes to one variant once both have
    # run), so the gauge records the A/B's overall footprint — dominated
    # by the unchunked program — and per-PROGRAM attribution stays with
    # the AOT memory_analysis numbers above. None/0 on backends without
    # allocator stats (CPU).
    hbm_peak = device_memory_stats()["peak_bytes_in_use"] or None
    ratios = [
        best["unchunked"]["wall_s"] / max(best["chunked"]["wall_s"], 1e-9)
    ]
    # No-losing-trial fold over the recorded bands: the chunked band's
    # WORST repeat must not lose to the unchunked band's BEST (the same
    # reading the overlap leg's decision rule uses).
    chunk_worst = best["chunked"]["wall_s_band"][1]
    unchunk_best = best["unchunked"]["wall_s_band"][0]
    no_losing_trial = chunk_worst <= unchunk_best * 1.05

    # Fused co-resident program: static footprint of cycle+tie-break in
    # ONE program vs the two programs it replaces, plus a live session
    # dispatch at a small plan (compile cost bounded for the leg budget).
    from bayesian_consensus_engine_tpu.parallel.sharded import (
        build_cycle_tiebreak_loop,
        build_cycle_loop,
        init_block_state,
    )

    k = min(fused_slots, agents)
    probs_km = jnp.asarray(rng.random((k, markets)), jnp.float32)
    mask_km = jnp.asarray(rng.random((k, markets)) < 0.9)
    outcome_m = jnp.asarray(rng.random(markets) < 0.5)
    state0 = jax.tree.map(
        lambda x: x.T, init_block_state(markets, k)
    )
    now0 = jnp.asarray(400.0, jnp.float32)

    fused_loop = build_cycle_tiebreak_loop(
        mesh, chunk_agents=min(chunk_agents, k), donate=False
    )
    fused_mem = jax.jit(
        lambda p, m, o, s, n: fused_loop(p, m, o, s, n, 1)
    ).lower(
        probs_km, mask_km, outcome_m, state0, now0
    ).compile().memory_analysis()
    plain_loop = build_cycle_loop(mesh, donate=False)
    plain_mem = jax.jit(
        lambda p, m, o, s, n: plain_loop(p, m, o, s, n, 1)
    ).lower(
        probs_km, mask_km, outcome_m, state0, now0
    ).compile().memory_analysis()
    tb_small = build_ring_tiebreak(
        mesh, chunk_agents=min(chunk_agents, k)
    ).lower(
        probs_km.T, probs_km.T, probs_km.T, probs_km.T, mask_km.T
    ).compile().memory_analysis()

    # Live fused-session dispatch: one ShardedSettlementSession serving
    # settle AND tie-break from its resident block in one program — the
    # co-residency walkthrough, timed steady-state at a small plan.
    from bayesian_consensus_engine_tpu.pipeline import (
        ShardedSettlementSession,
        build_settlement_plan,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    sess_markets = min(markets, 256)
    payloads = [
        (
            f"market-{m}",
            [
                {"sourceId": f"src-{s}", "probability": float(grid[(m + s) % len(grid)])}
                for s in range(8)
            ],
        )
        for m in range(sess_markets)
    ]
    store = TensorReliabilityStore()
    plan = build_settlement_plan(store, payloads, num_slots=8)
    outcomes = list(rng.random(sess_markets) < 0.5)
    with ShardedSettlementSession(store, plan, mesh) as session:
        session.settle_with_tiebreak(  # warm: state build + compile
            outcomes, now=21_900.0, chunk_agents=min(chunk_agents, 8)
        )
        fused_dispatch = float("inf")
        for i in range(reps):
            start = time.perf_counter()
            _res, tb = session.settle_with_tiebreak(
                outcomes, now=21_901.0 + i,
                chunk_agents=min(chunk_agents, 8),
            )
            _fence(np.asarray(tb.prediction))
            fused_dispatch = min(
                fused_dispatch, time.perf_counter() - start
            )

    # One-pass settlement (round 14): the co-resident shape with bands
    # riding too — the multi-pass fused XLA analytics program vs the
    # one-pass kernel, read off AOT memory_analysis of the programs that
    # would run. hbm_read_bytes = args + temps (every argument byte read
    # at least once, every temp byte written then read) — the per-settle
    # bytes-read floor; the acceptance is one-pass ≤ ~0.5× multi-pass
    # once the kernel grid actually tiles the markets axis.
    from bayesian_consensus_engine_tpu.ops.pallas_settle import (
        resolve_tile_markets,
    )
    from bayesian_consensus_engine_tpu.parallel.sharded import (
        build_cycle_analytics_loop,
    )

    def analytics_read_bytes(kernel_kind):
        loop = build_cycle_analytics_loop(
            mesh, chunk_agents=min(chunk_agents, k),
            chunk_slots=min(256, k), donate=False, kernel=kernel_kind,
        )
        mem = jax.jit(
            lambda p, m_, o, s, n: loop(p, m_, o, s, n, 1)
        ).lower(
            probs_km, mask_km, outcome_m, state0, now0
        ).compile().memory_analysis()
        return _hbm_read_capture(mem)["hbm_read_bytes"]

    # A Mosaic compile failure of the one-pass kernel on this backend is
    # the recorded datum, never the leg's death (the e2e_onepass /
    # pallas_ab discipline) — and the leg's ledger record is written
    # either way.
    onepass_tile = resolve_tile_markets(markets, k)
    shape_label = f"{markets} markets x {k} slots, 1 step"
    try:
        multi_read = analytics_read_bytes("xla")
        one_read = analytics_read_bytes("pallas")
        onepass_capture = {
            "shape": shape_label,
            **_onepass_ratio_fields(
                multi_read, one_read, markets, onepass_tile
            ),
        }
    except Exception as exc:
        one_read = None
        onepass_capture = {"shape": shape_label, "error": _infeasible(exc)}
    _ledger_record(
        "e2e_ring_memory", value=best["chunked"]["wall_s"], unit="s",
        extras={"hbm_peak_bytes": hbm_peak, "hbm_read_bytes": one_read},
    )

    result = {
        "workload": f"{markets} markets x {agents} agents",
        "unchunked": best["unchunked"],
        "chunked": best["chunked"],
        "chunked_speedup": round(ratios[0], 3),
        "no_losing_trial": bool(no_losing_trial),
        "hbm_peak_bytes": hbm_peak,
        "chunk_agents": "auto" if tuner.enabled else chunk_agents,
        "temp_ratio": round(
            memory["unchunked"]["compiled_temp_bytes"]
            / max(memory["chunked"]["compiled_temp_bytes"], 1), 2
        ),
        "onepass": onepass_capture,
        "fused_coresident": {
            "shape": f"{markets} markets x {k} slots, 1 step",
            "session_shape": f"{sess_markets} markets x 8 slots",
            "session_fused_dispatch_s": round(fused_dispatch, 4),
            "fused_temp_bytes": int(fused_mem.temp_size_in_bytes),
            "separate_cycle_temp_bytes": int(plain_mem.temp_size_in_bytes),
            "separate_tiebreak_temp_bytes": int(tb_small.temp_size_in_bytes),
            "separate_arg_bytes": int(
                plain_mem.argument_size_in_bytes
                + tb_small.argument_size_in_bytes
            ),
            "fused_arg_bytes": int(fused_mem.argument_size_in_bytes),
        },
    }
    decision = tuner.decision(
        "ring_chunk_agents", (markets, agents, 1, 1)
    )
    if decision is not None:
        result["autotune_decision"] = decision
    return result


def bench_e2e_analytics(markets=1024, slots=512, chunk_slots=256,
                        graph_degree=4, steps=2, reps=3, trials=2):
    """ISSUE-10 acceptance leg: the device-resident analytics tier.

    Three variants over ONE slot-major (K, M) workload, min-of-N +
    loadavg (`_min_of_trials`, alternating rounds), ``hbm_peak_bytes``
    recorded per variant (the allocator high-water mark AFTER that
    variant's runs — monotone, so later variants inherit earlier peaks;
    per-program attribution is the AOT capture's job):

    1. **bands_only** — the standalone band program
       (analytics/bands.py) dispatched against the resident state: the
       two-program shape, where every dispatch re-sends the whole
       probs/mask/state argument list.
    2. **fused_resident** — ``build_cycle_analytics_loop``: N cycles +
       chunked tie-break + bands in ONE program per chip against the
       same block.
    3. **fused_graph** — the fused program plus the correlated-market
       sweep over a random ``graph_degree``-regular dependency graph.

    The acceptance number is the CO-RESIDENCY argument ratio, read off
    AOT ``memory_analysis()`` of the same compiled objects that run:
    ``fused_arg_bytes`` vs ``separate_arg_bytes`` (= the plain cycle
    loop's args + the bands program's args — what "run a separate bands
    program after settle" actually re-sends). Fused, the block rides
    once and the bands' marginal argument cost is one outcomes vector —
    the leg records ``coresident_arg_ratio`` (≤ ~0.55 ⇒
    ``fused_halves_args``) in the leg JSON, the PR 9 co-residency
    argument applied to the analytics tier.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.analytics.bands import (
        build_band_program,
    )
    from bayesian_consensus_engine_tpu.parallel.sharded import (
        build_cycle_analytics_loop,
        build_cycle_loop,
        init_block_state,
    )
    from bayesian_consensus_engine_tpu.utils.profiling import (
        device_memory_stats,
    )

    rng = np.random.default_rng(12)
    k, m = slots, markets
    probs = jnp.asarray(rng.random((k, m)), jnp.float32)
    mask = jnp.asarray(rng.random((k, m)) < 0.9)
    outcome = jnp.asarray(rng.random(m) < 0.5)
    state = jax.tree.map(lambda x: x.T, init_block_state(m, k))
    now0 = jnp.asarray(400.0, jnp.float32)
    mesh = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources")
    )
    # A random degree-regular dependency graph over the batch's markets.
    nb_idx = jnp.asarray(
        rng.integers(0, m, (m, graph_degree)), jnp.int32
    )
    nb_w = jnp.asarray(
        rng.uniform(0.5, 1.5, (m, graph_degree)), jnp.float32
    )

    chunk = min(chunk_slots, k)
    bands_prog = build_band_program(mesh, chunk_slots=chunk)
    fused = build_cycle_analytics_loop(
        mesh, chunk_slots=chunk, chunk_agents=min(1024, k), donate=False
    )
    fused_graph = build_cycle_analytics_loop(
        mesh, chunk_slots=chunk, chunk_agents=min(1024, k), donate=False,
        sweep_steps=2,
    )
    plain_loop = build_cycle_loop(mesh, donate=False)

    # AOT: compile once per program, run the same executables.
    bands_exe = bands_prog.lower(probs, mask, state, now0).compile()
    fused_exe = jax.jit(
        lambda p, ma, o, s, n: fused(p, ma, o, s, n, steps)
    ).lower(probs, mask, outcome, state, now0).compile()
    graph_exe = jax.jit(
        lambda p, ma, o, s, n, gi, gw: fused_graph(
            p, ma, o, s, n, steps, gi, gw
        )
    ).lower(probs, mask, outcome, state, now0, nb_idx, nb_w).compile()
    plain_mem = jax.jit(
        lambda p, ma, o, s, n: plain_loop(p, ma, o, s, n, steps)
    ).lower(probs, mask, outcome, state, now0).compile().memory_analysis()
    bands_mem = bands_exe.memory_analysis()
    fused_mem = fused_exe.memory_analysis()
    graph_mem = graph_exe.memory_analysis()

    def timed(run_once):
        best = float("inf")
        for _ in range(reps):
            start = time.perf_counter()
            run_once()
            best = min(best, time.perf_counter() - start)
        # Variant-scoped allocator view: a monotone high-water mark, so
        # ordering matters (documented in the leg docstring).
        hbm = device_memory_stats()["peak_bytes_in_use"] or None
        return best, hbm

    runners = {
        "bands_only": lambda: _fence(
            bands_exe(probs, mask, state, now0).mean
        ),
        "fused_resident": lambda: _fence(
            fused_exe(probs, mask, outcome, state, now0)[3].mean
        ),
        "fused_graph": lambda: _fence(
            graph_exe(probs, mask, outcome, state, now0, nb_idx, nb_w)[4]
        ),
    }
    memory = {
        "bands_only": bands_mem,
        "fused_resident": fused_mem,
        "fused_graph": graph_mem,
    }

    def run_variant(name):
        wall, hbm = timed(runners[name])
        mem = memory[name]
        return {
            "wall_s": round(wall, 4),
            "markets_per_sec": round(m / wall, 1),
            "compiled_temp_bytes": int(mem.temp_size_in_bytes),
            "arg_bytes": int(mem.argument_size_in_bytes),
            "hbm_peak_bytes": hbm,
        }

    for run_once in runners.values():  # warm off the clock
        run_once()
    best = _min_of_trials(
        "e2e_analytics",
        ["bands_only", "fused_resident", "fused_graph"],
        run_variant,
        trials,
    )

    # Live fused-session act: one ShardedSettlementSession serving
    # settle + tie-break + bands + sweep from its resident block — the
    # `analytics` phase span (alignment/wrapping overhead, exclusive of
    # settle_dispatch) lands in the leg's phase breakdown here.
    from bayesian_consensus_engine_tpu.analytics import (
        AnalyticsOptions,
        MarketGraph,
    )
    from bayesian_consensus_engine_tpu.pipeline import (
        ShardedSettlementSession,
        build_settlement_plan,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    sess_markets = min(m, 256)
    payloads = [
        (
            f"market-{i}",
            [
                {"sourceId": f"src-{s}", "probability": float(rng.random())}
                for s in range(8)
            ],
        )
        for i in range(sess_markets)
    ]
    graph_live = MarketGraph.from_edges(
        [
            (f"market-{i}", f"market-{(i + 1) % sess_markets}", 1.0)
            for i in range(0, sess_markets, 2)
        ]
    )
    options = AnalyticsOptions(graph=graph_live, chunk_slots=8)
    store = TensorReliabilityStore()
    plan = build_settlement_plan(store, payloads, num_slots=8)
    sess_outcomes = list(rng.random(sess_markets) < 0.5)
    with ShardedSettlementSession(store, plan, mesh) as session:
        session.settle_with_analytics(  # warm: state build + compile
            sess_outcomes, now=21_900.0, analytics=options
        )
        session_dispatch = float("inf")
        for i in range(reps):
            start = time.perf_counter()
            _res, _tb, bands_live, _prop = session.settle_with_analytics(
                sess_outcomes, now=21_901.0 + i, analytics=options
            )
            _fence(np.asarray(bands_live.mean))
            session_dispatch = min(
                session_dispatch, time.perf_counter() - start
            )

    plain_args = int(plain_mem.argument_size_in_bytes)
    bands_args = int(bands_mem.argument_size_in_bytes)
    separate_args = plain_args + bands_args
    fused_args = int(fused_mem.argument_size_in_bytes)
    # The acceptance reading: what does DISPATCHING BANDS cost in
    # argument bytes? Separate: the standalone program re-sends the
    # probs/mask/state blocks (bands_args — XLA already drops the
    # state fields bands never read). Fused: the settle was sending its
    # argument list anyway, so bands' marginal cost is fused − plain —
    # zero blocks (the block rides once). ≤ half is the bar; measured,
    # the marginal is ~0.
    bands_marginal = fused_args - plain_args
    ratio = bands_marginal / max(bands_args, 1)
    # One-pass settlement (round 14): the same fused workload through
    # the one-pass kernel, AOT-captured — hbm_read_bytes = args + temps
    # (the per-settle bytes-read floor), vs the multi-pass fused program
    # above. The ≤ ~0.5× acceptance engages once the kernel grid
    # actually tiles the markets axis (grid_tiles > 1).
    from bayesian_consensus_engine_tpu.ops.pallas_settle import (
        resolve_tile_markets,
    )

    multi_read = _hbm_read_capture(fused_mem)["hbm_read_bytes"]
    onepass_tile = resolve_tile_markets(m, k)
    # Same discipline as the ring leg: a Mosaic compile failure of the
    # one-pass kernel is data, never the leg's death.
    try:
        onepass_fused = build_cycle_analytics_loop(
            mesh, chunk_slots=chunk, chunk_agents=min(1024, k),
            donate=False, kernel="pallas",
        )
        onepass_mem = jax.jit(
            lambda p, ma, o, s, n: onepass_fused(p, ma, o, s, n, steps)
        ).lower(
            probs, mask, outcome, state, now0
        ).compile().memory_analysis()
        one_read = _hbm_read_capture(onepass_mem)["hbm_read_bytes"]
        onepass_capture = _onepass_ratio_fields(
            multi_read, one_read, m, onepass_tile
        )
    except Exception as exc:
        one_read = None
        onepass_capture = {
            "multi_pass_read_bytes": multi_read,
            "error": _infeasible(exc),
        }
    hbm_peak = device_memory_stats()["peak_bytes_in_use"] or None
    _ledger_record(
        "e2e_analytics", value=best["fused_resident"]["wall_s"], unit="s",
        extras={"hbm_peak_bytes": hbm_peak, "hbm_read_bytes": one_read},
    )
    return {
        "workload": f"{m} markets x {k} slots, {steps} steps",
        "chunk_slots": chunk,
        "graph_degree": graph_degree,
        "bands_only": best["bands_only"],
        "fused_resident": best["fused_resident"],
        "fused_graph": best["fused_graph"],
        # The co-residency argument, both readings: the whole-pipeline
        # ratio (fused program vs settle + separate bands programs) and
        # the bands-dispatch marginal the acceptance bar is about.
        "separate_arg_bytes": separate_args,
        "fused_arg_bytes": fused_args,
        "coresident_arg_ratio": round(fused_args / max(separate_args, 1), 3),
        "bands_separate_arg_bytes": bands_args,
        "bands_marginal_arg_bytes": bands_marginal,
        "bands_dispatch_arg_ratio": round(ratio, 3),
        "fused_halves_band_args": bool(ratio <= 0.5),
        "sweep_marginal_arg_bytes": int(
            graph_mem.argument_size_in_bytes
            - fused_mem.argument_size_in_bytes
        ),
        "session_shape": f"{sess_markets} markets x 8 slots",
        "session_fused_dispatch_s": round(session_dispatch, 4),
        "hbm_peak_bytes": hbm_peak,
        "onepass": onepass_capture,
    }


def bench_e2e_onepass(markets=NUM_MARKETS, slots=SLOTS_PER_MARKET,
                      steps=4, chunk_agents=1024, chunk_slots=1024,
                      reps=3, trials=2, sharded_markets=2048,
                      sharded_slots=512):
    """ISSUE-12 acceptance leg: one-pass settlement at the 1M-market
    projection shape.

    The fused analytics program (cycles + chunked ring tie-break +
    uncertainty bands, ONE program per chip since rounds 11-12) still
    streams the resident (slots × markets) state from HBM 2-3 times per
    settle — one pass per reduce family. ``ops/pallas_settle.py`` folds
    all three into a single VMEM sweep per (K, TILE_M) tile. This leg
    A/Bs the two routes on identical operands:

    1. **Throughput** — settles/sec for ``multi_pass`` (the XLA fused
       program, the production default) vs ``one_pass`` (the kernel;
       interpret mode off-TPU, real Mosaic on TPU), min-of-N + loadavg
       (`_min_of_trials`). Outputs are bit-identical by the
       tests/test_pallas_settle.py oracle, so this is a pure
       wall-clock adjudication — the ``settle_kernel`` autotune knob's
       honesty guard makes the same comparison per shape at runtime.
    2. **HBM bytes-read per settle** — ``hbm_read_bytes`` = argument +
       temp bytes off AOT ``memory_analysis()`` of the SAME compiled
       executables that run (every argument byte is read at least once,
       every temp byte written then read — the program's bytes-read
       floor). Acceptance: ``read_ratio`` ≤ ~0.5 once the kernel grid
       actually tiles the markets axis (``grid_tiles`` > 1 — at one
       tile the interpret-mode program degenerates to the XLA program
       and the ratio is ~1 by construction). Feeds the ``bce-tpu
       stats`` hbm_read column via the per-repeat ledger records.

    Round 20 adds the ``sharded_sources`` arm: the SAME A/B on a 2-D
    (2, 4) markets × sources mesh at the (``sharded_markets`` ×
    ``sharded_slots``) co-resident shape — the partials kernel +
    cross-device merge vs the fused XLA program, with the per-shard
    read capture and ``read_ratio`` recorded next to the 1-D capture.
    A host short of 8 devices (or a Mosaic/shard_map failure) records
    the infeasibility as data, never a crash.

    The markets default is the 1M-market north-star projection (lane
    padding applied); ``--fast`` shrinks to a self-test shape.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.ops.pallas_settle import (
        resolve_tile_markets,
    )
    from bayesian_consensus_engine_tpu.parallel.sharded import (
        build_cycle_analytics_loop,
        init_block_state,
    )
    from bayesian_consensus_engine_tpu.utils.profiling import (
        device_memory_stats,
    )

    m = -(-markets // 128) * 128  # lane padding, the production shape
    k = slots
    rng = np.random.default_rng(14)
    probs = jnp.asarray(rng.random((k, m)), jnp.float32)
    mask = jnp.asarray(rng.random((k, m)) < 0.9)
    outcome = jnp.asarray(rng.random(m) < 0.5)
    state0 = jax.tree.map(lambda x: x.T, init_block_state(m, k))
    now0 = jnp.asarray(400.0, jnp.float32)
    mesh = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources")
    )

    ca, cs = min(chunk_agents, k), min(chunk_slots, k)
    loops = {
        "multi_pass": build_cycle_analytics_loop(
            mesh, chunk_agents=ca, chunk_slots=cs, donate=False
        ),
        "one_pass": build_cycle_analytics_loop(
            mesh, chunk_agents=ca, chunk_slots=cs, donate=False,
            kernel="pallas",
        ),
    }
    # AOT: lower+compile once per route and run the same executables —
    # hbm_read_bytes is read off the programs that produce the timings.
    # A Mosaic compile failure on this backend loses the one_pass arm
    # only (recorded as the infeasibility datum), never the leg.
    compiled = {}
    reads = {}
    infeasible = {}
    for name, loop in loops.items():
        try:
            exe = jax.jit(
                lambda p, ma, o, s, n, _loop=loop: _loop(
                    p, ma, o, s, n, steps
                )
            ).lower(probs, mask, outcome, state0, now0).compile()
        except Exception as exc:
            if name == "multi_pass":
                raise  # the production default failing IS a leg failure
            infeasible[name] = _infeasible(exc)
            continue
        compiled[name] = exe
        reads[name] = _hbm_read_capture(exe.memory_analysis())

    def run_variant(name):
        exe = compiled[name]
        best_wall = float("inf")
        for _ in range(reps):
            start = time.perf_counter()
            out = exe(probs, mask, outcome, state0, now0)
            _fence(out[1])  # consensus — scalar fetch forces execution
            best_wall = min(best_wall, time.perf_counter() - start)
        return {
            "wall_s": round(best_wall, 4),
            "markets_per_sec": round(m / best_wall, 1),
            **reads[name],
        }

    for exe in compiled.values():  # warm off the clock
        _fence(exe(probs, mask, outcome, state0, now0)[1])
    best = _min_of_trials(
        "e2e_onepass", list(compiled), run_variant, trials
    )

    tile = resolve_tile_markets(m, k)
    hbm_peak = device_memory_stats()["peak_bytes_in_use"] or None
    result = {
        "workload": f"{m} markets x {k} slots, {steps} steps",
        "multi_pass": best["multi_pass"],
        "tile_markets": tile,
        "grid_tiles": m // tile,
        "onepass_tiled": bool(m // tile > 1),
        "chunk_agents": ca,
        "chunk_slots": cs,
        "hbm_peak_bytes": hbm_peak,
    }
    if "one_pass" not in best:
        result["one_pass"] = infeasible["one_pass"]
        _ledger_record(
            "e2e_onepass", value=best["multi_pass"]["wall_s"], unit="s",
            extras={"hbm_peak_bytes": hbm_peak},
        )
        return result
    _ledger_record(
        "e2e_onepass", value=best["one_pass"]["wall_s"], unit="s",
        extras={
            "hbm_read_bytes": best["one_pass"]["hbm_read_bytes"],
            "hbm_peak_bytes": hbm_peak,
        },
    )
    result.update({
        "one_pass": best["one_pass"],
        "onepass_speedup": round(
            best["multi_pass"]["wall_s"]
            / max(best["one_pass"]["wall_s"], 1e-9), 3
        ),
        **_onepass_ratio_fields(
            best["multi_pass"]["hbm_read_bytes"],
            best["one_pass"]["hbm_read_bytes"], m, tile,
        ),
    })
    # Round 20: the sources-sharded arm — the same A/B at a 2-D mesh.
    try:
        result["sharded_sources"] = _sharded_onepass_capture(
            sharded_markets, sharded_slots, max(steps, 1), (2, 4),
            chunk_agents, chunk_slots, reps=min(reps, 2),
        )
    except Exception as exc:
        result["sharded_sources"] = _infeasible(exc)
    else:
        sharded = result["sharded_sources"]
        _ledger_record(
            "e2e_onepass_sharded",
            value=sharded["one_pass"]["wall_s"], unit="s",
            extras={
                "hbm_read_bytes": sharded["one_pass"]["hbm_read_bytes"],
            },
        )
    return result


def _e2e_payloads(markets, mean_slots, seed=7):
    """The e2e legs' shared synthetic payload shape (dict payloads)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    counts = rng.poisson(mean_slots - 1, markets) + 1
    src = rng.integers(0, SOURCE_UNIVERSE, counts.sum())
    prob = rng.random(counts.sum())
    offsets = np.concatenate([[0], np.cumsum(counts)])
    payloads = [
        (
            f"market-{m}",
            [
                {"sourceId": f"src-{src[i]}", "probability": float(prob[i])}
                for i in range(offsets[m], offsets[m + 1])
            ],
        )
        for m in range(markets)
    ]
    outcomes = rng.random(markets) < 0.5
    return payloads, outcomes, counts, src, prob, offsets


def bench_e2e_overlap(markets=NUM_MARKETS, mean_slots=4, steps=20, trials=2):
    """Serial vs overlapped two-batch settlement service at headline scale.

    The same work, twice: batch A then batch B, each ingest → settle →
    checkpoint to one DB file. The serial flow runs the legs back to back
    (round-3 shape: chip idle during ingest/flush, host idle during
    settle). The overlapped flow uses the round-4 machinery — a
    PlanPrefetcher builds B's plan while A settles, and A's checkpoint
    writes on a background thread (GIL-released native writer) while B
    ingests/settles. Identical results by construction (pinned by
    tests/test_overlap.py); the measured delta is pure wall-clock.

    Adjudication discipline (round 6, VERDICT r5 #2 — the feature's sign
    flipped between captures: 1.172× in round 5's session, 0.907× in
    BANKED run4): *trials* alternating repeats per flow, each repeat's
    wall time AND 1-minute loadavg recorded (to the ``repeats`` list and,
    with ``--ledger``, to the run ledger), min-of-N for the headline
    ``speedup``, the per-trial ratio band alongside (*trials* defaults to
    2 — the pre-round-6 run count, so the leg stays inside its subprocess
    timeout at 1M shapes; raise it when the budget allows). The DECISION
    RULE
    (``decision_rule`` in the output, quoted by docs/round5-notes.md):
    overlap **wins** iff min-of-N speedup ≥ 1.05 AND no paired trial ran
    slower than 1.0×; **loses** iff min-of-N speedup ≤ 0.95; anything
    between is a **wash** (quote the band, ship nothing on it).
    """
    import gc
    import tempfile as _tf

    from bayesian_consensus_engine_tpu.pipeline import (
        PlanPrefetcher,
        build_settlement_plan,
        settle,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    half = markets // 2
    payloads, outcomes, counts, _, _, _ = _e2e_payloads(markets, mean_slots)
    pay_a, pay_b = payloads[:half], payloads[half:]
    out_a, out_b = outcomes[:half], outcomes[half:]
    # One pinned slot height: both flows compile ONE settle program.
    num_slots = int(counts.max())

    def run_serial(db):
        store = TensorReliabilityStore()
        start = time.perf_counter()
        plan_a = build_settlement_plan(store, pay_a, num_slots=num_slots)
        settle(store, plan_a, out_a, steps=steps).fence()
        store.flush_to_sqlite(db)
        plan_b = build_settlement_plan(store, pay_b, num_slots=num_slots)
        settle(store, plan_b, out_b, steps=steps).fence()
        store.flush_to_sqlite(db)
        return time.perf_counter() - start

    def run_overlapped(db):
        store = TensorReliabilityStore()
        start = time.perf_counter()
        with PlanPrefetcher(
            store, [pay_a, pay_b], num_slots=num_slots
        ) as plans:
            settle(store, next(plans), out_a, steps=steps).fence()
            # A's checkpoint writes in the background while B's plan (built
            # during A's settle) settles in the foreground.
            handle = store.flush_to_sqlite_async(db)
            settle(store, next(plans), out_b, steps=steps).fence()
            handle.result()
            store.flush_to_sqlite(db)
        return time.perf_counter() - start

    gc.freeze()
    try:
        with _tf.TemporaryDirectory() as tmp:
            # Warm by running the serial flow once, untimed, at the REAL
            # shapes (jit caches per exact store/block size, so a small
            # warm-up shape would leave both of serial's first-compiles on
            # its clock while the overlapped flow reused the cache; the
            # capacity-ladder export keeps the overlapped flow's dispatch
            # shapes on the same rungs). Flows then run ALTERNATING,
            # min-of-N each — this box's external load bursts can swing a
            # host-bound pass several-fold, and alternation keeps a burst
            # from landing wholly on one flow.
            run_serial(os.path.join(tmp, "warm.db"))
            repeats = []
            t_serial = t_overlap = float("inf")
            ratios = []
            for trial in range(trials):
                for flow, runner in (
                    ("serial", run_serial), ("overlapped", run_overlapped)
                ):
                    load = _loadavg_1m()
                    seconds = runner(
                        os.path.join(tmp, f"{flow[0]}{trial}.db")
                    )
                    repeats.append(
                        {
                            "trial": trial,
                            "flow": flow,
                            "s": round(seconds, 3),
                            "loadavg_1m": load,
                        }
                    )
                    _ledger_record(
                        f"e2e_overlap.{flow}", value=round(seconds, 3),
                        unit="s", repeat=trial,
                        extras={"loadavg_1m_before": load},
                    )
                    if flow == "serial":
                        trial_serial = seconds
                        t_serial = min(t_serial, seconds)
                    else:
                        ratios.append(trial_serial / seconds)
                        t_overlap = min(t_overlap, seconds)
        speedup = t_serial / t_overlap
        if speedup >= 1.05 and min(ratios) >= 1.0:
            decision = "wins"
        elif speedup <= 0.95:
            decision = "loses"
        else:
            decision = "wash"
        return {
            "workload": (
                f"2 batches x {half} markets, {steps} cycles each, "
                f"checkpoint per batch, min of {trials} alternating trials"
            ),
            "serial_s": round(t_serial, 2),
            "overlapped_s": round(t_overlap, 2),
            "saved_s": round(t_serial - t_overlap, 2),
            "speedup": round(speedup, 3),
            "speedup_band": [
                round(min(ratios), 3), round(max(ratios), 3)
            ],
            "repeats": repeats,
            "decision": decision,
            "decision_rule": (
                "wins iff min-of-N speedup >= 1.05 and every paired "
                "trial >= 1.0x; loses iff min-of-N <= 0.95; else wash "
                "(quote the band)"
            ),
        }
    finally:
        gc.unfreeze()


def bench_e2e(markets=NUM_MARKETS, mean_slots=4, steps=20,
              resettle_markets=10_000):
    """The whole pipeline at headline scale, ingest and flush included.

    payloads → native packer → interned rows → device block → N-cycle loop
    → absorb → SQLite flush: the full settlement flow a production caller
    runs, not just the device kernel, at 1M markets. A second, small
    settlement (*resettle_markets*) then checkpoints INCREMENTALLY to the
    same file — flush cost must scale with touched rows, not store size
    (reference UPSERT semantics, reliability.py:221-231). Returns a dict
    with the amortised rate and the per-leg breakdown in seconds.
    """
    import numpy as np

    from bayesian_consensus_engine_tpu.pipeline import (
        build_settlement_plan,
        settle,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    payloads, outcomes, counts, src, prob, offsets = _e2e_payloads(
        markets, mean_slots
    )

    # The 1M-dict payload fixture is long-lived caller data: without
    # gc.freeze() every generational collection re-scans its ~9M containers,
    # tripling every host-side pass below (measured 14 s -> 4 s for one
    # ingest). Freezing long-lived state is the standard CPython service
    # pattern; the framework's own cost is what remains. Paired with the
    # unfreeze below so callers get normal GC back.
    import gc

    gc.freeze()
    try:
        # Warm the packers off the clock: the first call pays C extension
        # import/registration (and numpy's ufunc setup), which is not a
        # steady-state ingest cost — the same honesty rule the autotune
        # guard follows. Both the object and columnar paths are warmed so
        # neither timed region below reports a cold-module artefact.
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan_columnar,
        )

        warm_payloads = payloads[: min(64, markets)]
        build_settlement_plan(TensorReliabilityStore(), warm_payloads)
        warm_signals = int(counts[: len(warm_payloads)].sum())
        build_settlement_plan_columnar(
            TensorReliabilityStore(),
            [market_id for market_id, _ in warm_payloads],
            [f"src-{s}" for s in src[:warm_signals].tolist()],
            prob[:warm_signals],
            offsets[: len(warm_payloads) + 1].astype(np.int64),
        )

        # Host-CPU legs run min-of-2: this box carries unrelated load whose
        # bursts can inflate a pure-host pass several-fold (device legs are
        # unaffected — they wait on the chip, not the host).
        store = TensorReliabilityStore()
        start = time.perf_counter()
        plan = build_settlement_plan(store, payloads)
        t_ingest = time.perf_counter() - start
        start = time.perf_counter()
        build_settlement_plan(TensorReliabilityStore(), payloads)
        t_ingest = min(t_ingest, time.perf_counter() - start)

        # Columnar twin: callers holding signals as flat columns skip the
        # per-dict Python walk entirely (vectorised grouping + one C interning
        # pass). Measured on its own store so interner state is comparable.
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan_columnar,
        )

        source_ids = [f"src-{s}" for s in src.tolist()]
        market_keys = [market_id for market_id, _signals in payloads]
        t_ingest_columnar = float("inf")
        for _ in range(2):
            start = time.perf_counter()
            build_settlement_plan_columnar(
                TensorReliabilityStore(), market_keys, source_ids, prob,
                offsets.astype(np.int64),
            )
            t_ingest_columnar = min(
                t_ingest_columnar, time.perf_counter() - start
            )

        settle(store, plan, outcomes, steps=steps)  # compile + warm
        store.epoch_origin()  # sync the warm-up's deferred state off the clock
        start = time.perf_counter()
        result = settle(store, plan, outcomes, steps=steps)
        result.fence()  # completion, without the full result-vector fetch
        t_settle = time.perf_counter() - start
        # Result delivery (the consensus vector's device→host transfer) is
        # a separate leg: through this host's tunnel it can dwarf the
        # kernel, and a settle-and-checkpoint service never pays it.
        start = time.perf_counter()
        _ = result.consensus
        t_consensus_fetch = time.perf_counter() - start
        # The settle deferred its host merge; time the sync explicitly so the
        # breakdown stays honest (epoch_origin is the cheapest forcing read).
        start = time.perf_counter()
        store.epoch_origin()
        t_sync = time.perf_counter() - start

        with tempfile.TemporaryDirectory() as tmp:
            db = os.path.join(tmp, "settled.db")
            start = time.perf_counter()
            rows = store.flush_to_sqlite(os.path.join(tmp, "probe.db"))
            t_flush = time.perf_counter() - start
            start = time.perf_counter()
            store.flush_to_sqlite(db)  # min-of-2; db is the kept target
            t_flush = min(t_flush, time.perf_counter() - start)

            # Incremental checkpoint: settle a small slice, flush the delta
            # (the flush syncs the deferred state first — all-in cost shown).
            sub_plan = build_settlement_plan(store, payloads[:resettle_markets])
            settle(store, sub_plan, outcomes[:resettle_markets], steps=1)
            start = time.perf_counter()
            dirty_rows = store.flush_to_sqlite(db)
            t_flush_incr = time.perf_counter() - start

            # Steady state: chained settles stay device-resident (deferred
            # absorb — no per-settle re-upload or host merge). The first settle
            # below re-primes the device after the flush's sync; the second is
            # the sustained per-batch cost a long-running service pays.
            settle(store, plan, outcomes, steps=steps)
            start = time.perf_counter()
            chained = settle(store, plan, outcomes, steps=steps)
            chained.fence()
            t_settle_chained = time.perf_counter() - start

        # Amortised total stays conservative: result delivery included.
        total = t_ingest + t_settle + t_consensus_fetch + t_sync + t_flush
        return {
            "cycles_per_sec_amortised": round(steps / total, 1),
            "workload": (
                f"{markets} markets, {int(counts.sum())} signals, "
                f"{rows} pairs, {steps} cycles"
            ),
            "ingest_s": round(t_ingest, 3),
            "ingest_columnar_s": round(t_ingest_columnar, 3),
            "settle_s": round(t_settle, 3),
            "consensus_fetch_s": round(t_consensus_fetch, 3),
            "host_sync_s": round(t_sync, 3),
            "settle_chained_s": round(t_settle_chained, 3),
            "steady_state_cycles_per_sec": round(steps / t_settle_chained, 1),
            "flush_s": round(t_flush, 3),
            "incremental_flush": {
                "resettled_markets": resettle_markets,
                "rows_written": dirty_rows,
                "flush_s": round(t_flush_incr, 3),
            },
        }
    finally:
        # Pairing is local: any caller of bench_e2e gets normal GC back.
        gc.unfreeze()


def bench_e2e_ingest(markets=NUM_MARKETS, mean_slots=4, trials=3):
    """Packer A/B/C at headline scale — the ingest-floor adjudication.

    One full columnar plan build (grouping + duplicate averaging + pair
    interning + dense block fill) of ~``markets × mean_slots`` signals
    onto a FRESH store, three ways:

    * ``python`` — every native fast path forced down to its pure-Python
      twin (``BCE_NO_NATIVE`` store + ``native=False`` builders): the
      floor the C paths are measured against, and the CI-lane twin that
      must stay correct.
    * ``native_columnar`` — string source-id columns through the C
      grouping pass (``fastpack.group_columns``) + C interning.
    * ``zero_copy`` — the :class:`~.core.batch.SourceCodes` coded intake
      (codes tabled OFF the clock): no per-signal Python object exists
      anywhere on the timed path.

    Min-of-N alternating trials with per-repeat loadavg to the run
    ledger (BASELINE.md protocol); the durable headline is
    ``signals_per_sec`` (native_columnar, min wall). The ISSUE-8
    acceptance bar — < 1 s per 4M signals — is ``sub_second_4m``:
    min wall scaled to 4M signals, band quoted alongside.

    Act 3 (round 15) re-packs the same universe with drifting source
    sets — ``stable`` / ``drift1`` (1 %) / ``drift25`` (25 %) — through
    the epoch-persistent pair table, reporting ``intern_s`` +
    ``delta_pairs`` per variant (min-of-N like the packers), the
    ``intern_mode="full"`` floor beside each, an in-act
    ``delta_parity`` row-assignment coda, and the ROADMAP-4 acceptance
    fields ``sub_100ms_drift_4m`` / ``sub_half_s_cold_4m`` (intern
    seconds scaled to the 4M-signal reference shape).
    """
    import gc

    import numpy as np

    from bayesian_consensus_engine_tpu.core.batch import encode_source_ids
    from bayesian_consensus_engine_tpu.pipeline import (
        build_settlement_plan_columnar,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    rng = np.random.default_rng(23)
    counts = rng.poisson(mean_slots - 1, markets) + 1
    signals = int(counts.sum())
    keys = [f"market-{m}" for m in range(markets)]
    sids = [f"src-{v}" for v in rng.integers(0, SOURCE_UNIVERSE, signals)]
    probs = rng.random(signals)
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    # The zero-copy caller's tabled encoding happens once, off the clock
    # (ids in a steady feed repeat; re-encoding only new ids is the
    # caller's amortisation).
    coded = encode_source_ids(sids)

    # Warm every packer variant off the clock: the first call pays C
    # extension import/registration, which is not a steady-state ingest
    # cost (the honesty rule the autotune guard follows).
    warm_markets = min(64, markets)
    warm_signals = int(counts[:warm_markets].sum())
    for native in (None, False):
        build_settlement_plan_columnar(
            TensorReliabilityStore(), keys[:warm_markets],
            sids[:warm_signals], probs[:warm_signals],
            offsets[: warm_markets + 1], native=native,
        )

    gc.freeze()
    try:
        def run(name):
            # Save/RESTORE the forced-fallback knob (never pop): a run
            # launched under BCE_NO_NATIVE=1 must keep its setting for
            # the rest of the process, or later auto-detected paths
            # would go half-native behind the operator's back.
            prior_no_native = os.environ.get("BCE_NO_NATIVE")
            if name == "python":
                os.environ["BCE_NO_NATIVE"] = "1"
            try:
                # Store constructed INSIDE the env guard: the python
                # variant's store interns through the dict-backed
                # IdInterner, so the whole pure-Python ingest stack is
                # what the clock sees.
                store = TensorReliabilityStore()
                source_column = coded if name == "zero_copy" else sids
                native = False if name == "python" else None
                start = time.perf_counter()
                # intern_mode="full": this act A/Bs the PACKERS across
                # rounds — the ISSUE-8 baseline must not silently absorb
                # the delta path's staging digest (act 3 measures that).
                build_settlement_plan_columnar(
                    store, keys, source_column, probs, offsets,
                    native=native, intern_mode="full",
                )
                wall = time.perf_counter() - start
            finally:
                if name == "python":
                    if prior_no_native is None:
                        os.environ.pop("BCE_NO_NATIVE", None)
                    else:
                        os.environ["BCE_NO_NATIVE"] = prior_no_native
            return {
                "wall_s": round(wall, 4),
                "signals_per_sec": round(signals / wall, 1),
            }

        best = _min_of_trials(
            "e2e_ingest", ["python", "native_columnar", "zero_copy"],
            run, trials,
        )

        # --- Act 3 (round 15): drifting-topology packs over the
        # epoch-persistent pair table. Each variant re-packs the SAME
        # market universe with some fraction of markets' source sets
        # redrawn (the drifting-stream shape the ROADMAP-4 floor is
        # about): `stable` = identical pair set (pair-fingerprint O(1)
        # tier), `drift1`/`drift25` = 1% / 25% of markets redrawn. The
        # store+table are warmed with the base batch OFF the clock; the
        # timed region is one full plan build, and `intern_s` /
        # `delta_pairs` come off the bound plan's intern_stats — the
        # number the delta path is supposed to crush vs `intern_full_s`
        # (the same drifted batch through intern_mode="full").
        drift_rng = np.random.default_rng(29)

        def drift_batch(frac):
            if frac == 0.0:
                return sids, probs.copy()
            drifted = drift_rng.random(markets) < frac
            pick = np.repeat(drifted, counts)
            new_sids = list(sids)
            redraw = drift_rng.integers(
                0, SOURCE_UNIVERSE, int(pick.sum())
            )
            for pos, val in zip(np.flatnonzero(pick).tolist(),
                                redraw.tolist()):
                new_sids[pos] = f"src-{val}"
            return new_sids, drift_rng.random(signals)

        drift_variants = {
            "stable": drift_batch(0.0),
            "drift1": drift_batch(0.01),
            "drift25": drift_batch(0.25),
        }

        def run_drift(name):
            from bayesian_consensus_engine_tpu.pipeline import (
                stage_settlement_plan_columnar,
            )

            store = TensorReliabilityStore()
            base_plan = build_settlement_plan_columnar(
                store, keys, sids, probs, offsets, intern_mode="auto",
            )
            d_sids, d_probs = drift_variants[name]
            start = time.perf_counter()
            plan = stage_settlement_plan_columnar(
                keys, d_sids, d_probs, offsets, intern_mode="auto",
            ).bind(store)
            wall = time.perf_counter() - start
            stats = plan.intern_stats
            # The same drifted batch through the legacy every-pair walk,
            # on an identically warmed store — the floor the delta path
            # is measured against (and a cheap in-act parity coda: both
            # routes must assign identical rows).
            full_store = TensorReliabilityStore()
            build_settlement_plan_columnar(
                full_store, keys, sids, probs, offsets,
                intern_mode="full",
            )
            full_start = time.perf_counter()
            full_plan = stage_settlement_plan_columnar(
                keys, d_sids, d_probs, offsets, intern_mode="full",
            ).bind(full_store)
            full_wall = time.perf_counter() - full_start
            return {
                "wall_s": round(wall, 4),
                "intern_s": round(stats["intern_s"], 5),
                "delta_pairs": int(stats["interned_pairs"]),
                "matched_pairs": int(stats["matched_pairs"]),
                "fingerprint_hit": bool(stats["fingerprint_hit"]),
                # The fresh-store base pack's intern — the COLD floor.
                "intern_cold_s": round(
                    base_plan.intern_stats["intern_s"], 5
                ),
                "wall_full_s": round(full_wall, 4),
                "intern_full_s": round(
                    full_plan.intern_stats["intern_s"], 5
                ),
                "delta_parity": bool(
                    np.array_equal(plan.slot_rows, full_plan.slot_rows)
                ),
            }

        drift_best = _min_of_trials(
            "e2e_ingest_drift", ["stable", "drift1", "drift25"],
            run_drift, trials,
        )
    finally:
        gc.unfreeze()
    native_best = best["native_columnar"]
    scale_4m = 4_000_000 / max(signals, 1)
    drift_scaled = {
        name: round(out["intern_s"] * scale_4m, 4)
        for name, out in drift_best.items()
    }
    return {
        "workload": (
            f"{markets} markets x ~{mean_slots} signals ({signals} signals, "
            f"{SOURCE_UNIVERSE}-source universe), full columnar plan build "
            f"onto a fresh store, min of {trials} alternating trials"
        ),
        "signals": signals,
        "python": best["python"],
        "native_columnar": native_best,
        "zero_copy": best["zero_copy"],
        "signals_per_sec": native_best["signals_per_sec"],
        "native_speedup": round(
            best["python"]["wall_s"] / max(native_best["wall_s"], 1e-9), 2
        ),
        "wall_s_per_4m_signals": round(native_best["wall_s"] * scale_4m, 3),
        "wall_s_per_4m_band": [
            round(b * scale_4m, 3) for b in native_best["wall_s_band"]
        ],
        "sub_second_4m": bool(native_best["wall_s"] * scale_4m < 1.0),
        # Act 3 — the round-15 drifting-topology acceptance: a drifted
        # pack interns only its pair-delta against the epoch-persistent
        # table. `intern_s_per_4m` scales each variant's intern seconds
        # to the 4M-signal reference shape; the bar is the 1%-drift
        # intern under 100 ms (with the cold/full floor quoted beside
        # it), and `delta_parity` pins delta == full row assignment on
        # this very workload.
        "drift": {
            name: drift_best[name] for name in drift_best
        },
        "drift_intern_s_per_4m": drift_scaled,
        "cold_intern_s_per_4m": round(
            drift_best["drift1"]["intern_cold_s"] * scale_4m, 4
        ),
        "sub_100ms_drift_4m": bool(drift_scaled["drift1"] < 0.1),
        "sub_half_s_cold_4m": bool(
            drift_best["drift1"]["intern_cold_s"] * scale_4m < 0.5
        ),
    }


def bench_dryrun_multichip(n_devices=8, markets=LARGE_K_MARKETS,
                           slots=LARGE_K_SLOTS, steps=3):
    """Scaled virtual-mesh execution (VERDICT r5 #3): the sharded
    north-star band at 8 devices × 16k markets × 10k slots — the
    ``large_k`` anchor shape, not the old 16×8 toy — with the REAL psum
    epilogue (sources axis split: non-singleton replica groups) and the
    ring tie-break leg, parity-checked against the single-device loop.

    Runs on self-provisioned virtual CPU devices by design (this leg is
    program-property evidence for the projection table, not a TPU rate):
    ``step_ms`` is the virtual-mesh per-step wall — the dispatch/
    partitioning cost datum docs/tpu-architecture.md cites next to the
    measured single-chip anchors. The leg subprocess pins its own
    backend, so it runs identically on healthy and tunnel-dead rounds.
    """
    from __graft_entry__ import dryrun_north_star_band

    return dryrun_north_star_band(
        n_devices=n_devices, markets=markets, slots=slots, steps=steps
    )


def leg_probe():
    """Backend bring-up canary: device list + one tiny jit round trip."""
    import jax
    import jax.numpy as jnp

    start = time.perf_counter()
    devices = jax.devices()
    _fence(jax.jit(lambda x: x + 1.0)(jnp.zeros((8,), jnp.float32)))
    return {
        "platform": devices[0].platform,
        "devices": len(devices),
        "bringup_s": round(time.perf_counter() - start, 1),
    }


# ---------------------------------------------------------------------------
# Harness: leg registry, subprocess runner, probe backoff, composition.
# ---------------------------------------------------------------------------

# name -> (callable, production kwargs, --fast kwargs, timeout seconds).
# Order below is NOT priority order; see DEVICE_LEG_ORDER.
_FAST_SHAPE = dict(num_markets=4096, slots=8)
def bench_e2e_kill_soak(markets=64, batches=12, kill_after=3,
                        interval=0.15, slo_s=0.3, hosts=2, steps=1,
                        sources=40, num_slots=8):
    """Round-13 failure-as-steady-state leg: a REAL worker kill mid-stream.

    Drives ``scripts/kill_soak.py`` — a shared-nothing banded cluster of
    *hosts* worker processes (cluster/membership.py views, per-band
    journals, resident sessions on local meshes), one of which is
    ``os.kill``-ed with SIGKILL after *kill_after* durable batches. The
    headline is the PR-7 metric the ROADMAP demands for this leg:
    **recovered ``goodput_within_slo``** — every offered request in the
    denominator, the crash-eaten batches re-driven by the survivor
    landing as SLO violations — next to ``recovery_s`` (kill → first
    re-settled dead-band batch). Acceptance rides in the leg JSON:
    ``resident_fallbacks_steady``/``_survivor`` must be 0 (the stream
    never fell back to teardown+rebuild, before OR during recovery) and
    the three byte-coda fields must be True (adoption-time store +
    SQLite bytes equal the merged journal replay; the survivor's own
    journal ends self-contained).

    Round 16 adds the LIVE health surface to the acceptance: the leg
    JSON carries the ``/healthz`` transition timeline (the survivor must
    read healthy → burning/degraded → healthy across the kill window,
    with the endpoint answering over the wire while recovery runs) and
    the deterministic fleet merge of the scraped snapshots (the dead
    host as an explicit ``hosts_absent`` entry, fold order-independent).
    """
    import subprocess as _subprocess

    script = os.path.join(os.path.dirname(_SELF), "scripts", "kill_soak.py")
    cmd = [
        sys.executable, script, "--json",
        "--hosts", str(hosts),
        "--markets", str(markets),
        "--batches", str(batches),
        "--kill-after", str(kill_after),
        "--interval", str(interval),
        "--slo", str(slo_s),
        "--steps", str(steps),
        "--sources", str(sources),
        "--num-slots", str(num_slots),
    ]
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # workers pin CPU themselves
    start = time.perf_counter()
    proc = _subprocess.run(
        cmd, capture_output=True, text=True, env=env,
    )
    wall_s = time.perf_counter() - start
    if proc.returncode != 0:
        raise RuntimeError(
            f"kill soak failed rc={proc.returncode}:\n"
            f"{(proc.stdout or '')[-2000:]}\n{(proc.stderr or '')[-2000:]}"
        )
    soak = json.loads(proc.stdout.strip().splitlines()[-1])
    result = {
        "wall_s": wall_s,
        "goodput_within_slo": soak["goodput_within_slo"],
        "recovery_s": soak["recovery_s"],
        "adopt_s": soak["adopt_s"],
        "rows_adopted": soak["rows_adopted"],
        "requests_offered": soak["requests_offered"],
        "slo": soak["slo"],
        "resident_fallbacks_steady": soak["resident_fallbacks_steady"],
        "resident_fallbacks_survivor": soak["resident_fallbacks_survivor"],
        "survivor_adopt_modes": soak["survivor_adopt_modes"],
        "byte_equal_store": soak["byte_equal_store"],
        "byte_equal_sqlite": soak["byte_equal_sqlite"],
        "survivor_journal_self_contained": soak[
            "survivor_journal_self_contained"
        ],
        "every_batch_durable": soak["every_batch_durable"],
        "health_timeline": soak["health_timeline"],
        "health_transitions_ok": soak["health_transitions_ok"],
        "healthz_polls": soak["healthz_polls"],
        "healthz_poll_ok": soak["healthz_poll_ok"],
        "fleet": soak["fleet"],
        "killed_host": soak["killed_host"],
        "hosts": hosts,
        "batches_per_band": batches,
        "soak_ok": soak["ok"],
    }
    # One ledger record carrying the recovery story: value = recovery_s,
    # extras.slo feeds the stats goodput column, extras.recovery_s the
    # round-13 recovery column/trailer.
    _ledger_record(
        "e2e_kill_soak", value=round(soak["recovery_s"], 4), unit="s",
        extras={
            "loadavg_1m_before": _loadavg_1m(),
            "slo": soak["slo"],
            "recovery_s": soak["recovery_s"],
            "goodput_within_slo": soak["goodput_within_slo"],
            "resident_fallbacks": soak["resident_fallbacks_survivor"],
        },
    )
    print(
        f"e2e_kill_soak: recovered goodput_within_slo="
        f"{soak['goodput_within_slo']:.3f} over "
        f"{soak['requests_offered']} offered, recovery_s="
        f"{soak['recovery_s']:.3f}, fallbacks="
        f"{soak['resident_fallbacks_survivor']}"
    )
    return result


def bench_e2e_replay_sweep(markets=2000, batches=6, mean_slots=4, steps=2,
                           sweep_configs=16, trials=2):
    """Round-18 counterfactual-replay leg: the K-lane sweep vs K
    sequential replays, over one recorded workload.

    Records a live ``settle_stream`` run with its trace sidecar
    (``trace=`` — the INPUT columns per admitted batch), then re-drives
    the trace through the replay lab two ways on identical inputs:

    1. **sweep** — ONE :func:`~.replay.replay_sweep` over *sweep_configs*
       lanes (the recorded config + altered decay half-life / capped
       step / learning rate / band z points): plans stage+bind once,
       every batch is one vmapped device dispatch for all lanes.
    2. **sequential** — *sweep_configs* :func:`~.replay.replay_single`
       calls, each paying its own store, interning pass, and 1-wide
       program: the host cost the sweep amortises, K times over.

    Acceptance (ISSUE 17): at 16 configs the sweep is **≥6×** faster
    than sequential (``sweep_speedup``); the rebuild sweep's lane-0
    store digest equals the live run's (``byte_equal_store``); and two
    sweeps over the same trace produce identical ``result_digest``
    (``run_twice_identical``). The timed number feeds the ``bce-tpu
    stats`` replay column via ``extras.replay_batches_per_s`` (recorded
    batches re-driven per second across all lanes).
    """
    import gc
    import tempfile as _tf

    import numpy as np

    from bayesian_consensus_engine_tpu.cluster.recover import store_digest
    from bayesian_consensus_engine_tpu.pipeline import settle_stream
    from bayesian_consensus_engine_tpu.replay import (
        RECORDED_CONFIG,
        ReplayConfig,
        load_trace,
        replay_single,
        replay_sweep,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    per_batch = markets // batches
    rng = np.random.default_rng(18)
    batch_data = []
    for b in range(batches):
        counts = rng.poisson(mean_slots - 1, per_batch) + 1
        total = int(counts.sum())
        # Half the keys recur across batches (re-settlement rows), half
        # are fresh — the interning mix a live service actually feeds.
        keys = [
            f"m{m}" if m % 2 == 0 else f"b{b}-m{m}" for m in range(per_batch)
        ]
        sids = [f"src-{v}" for v in rng.integers(0, SOURCE_UNIVERSE, total)]
        probs = rng.random(total)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        outcomes = (rng.random(per_batch) < 0.5).tolist()
        batch_data.append(((keys, sids, probs, offsets), outcomes))
    gc.freeze()

    # Lane 0 is the recorded config; the rest walk a deterministic grid
    # over the four swept knobs (no RNG — the sweep result is pinned to
    # be a pure function of (trace, config set)).
    configs = [RECORDED_CONFIG] + [
        ReplayConfig(
            half_life_days=15.0 + 5.0 * (i % 4),
            base_learning_rate=0.04 + 0.02 * (i % 3),
            max_update_step=0.06 + 0.03 * (i % 3),
            band_z=1.25 + 0.25 * (i % 4),
        )
        for i in range(sweep_configs - 1)
    ]

    with _tf.TemporaryDirectory() as tmp:
        jrnl = os.path.join(tmp, "live.jrnl")
        live_store = TensorReliabilityStore()
        for _result in settle_stream(
            live_store, batch_data, steps=steps, now=21_900.0,
            journal=jrnl, trace=jrnl + ".trace", columnar=True,
        ):
            pass
        live_digest = store_digest(live_store)
        trace = load_trace(jrnl)

        # Warm both arms off the clock (compiles land in the program
        # caches; the trials time steady-state host+dispatch work).
        warm = replay_sweep(trace, configs, rebuild=False)
        replay_single(trace, RECORDED_CONFIG)  # 1-lane program
        replay_single(trace, configs[1])       # 2-lane program

        def run_variant(name):
            start = time.perf_counter()
            if name == "sweep":
                result = replay_sweep(trace, configs, rebuild=False)
                settled = result.lanes[0].markets_settled
            else:
                settled = None
                for config in configs:
                    lane = replay_single(trace, config)
                    if config is RECORDED_CONFIG:
                        settled = lane.markets_settled
            wall = time.perf_counter() - start
            out = {
                "wall_s": round(wall, 4),
                "configs": len(configs),
                "lane0_markets_settled": settled,
            }
            if name == "sweep":
                out["replay_batches_per_s"] = round(len(trace) / wall, 2)
            return out

        best = _min_of_trials(
            "e2e_replay_sweep", ["sequential", "sweep"], run_variant,
            trials,
        )

        # Acceptance codas, off the clock: the lane-0 byte contract
        # (rebuild sweep == live run) and run-twice determinism.
        full = replay_sweep(
            trace, configs,
            journal=os.path.join(tmp, "replay.jrnl"),
            db_path=os.path.join(tmp, "replay.db"),
        )
        again = replay_sweep(trace, configs, rebuild=False)

    speedup = round(
        best["sequential"]["wall_s"] / max(best["sweep"]["wall_s"], 1e-9), 2
    )
    batches_per_s = best["sweep"]["replay_batches_per_s"]
    result = {
        "workload": (
            f"{markets} markets x {batches} batches, {steps} steps, "
            f"{len(configs)} configs"
        ),
        "sweep": best["sweep"],
        "sequential": best["sequential"],
        "wall_s": best["sweep"]["wall_s"],
        "sweep_speedup": speedup,
        "speedup_ok": bool(speedup >= 6.0) if sweep_configs >= 16 else None,
        "replay_batches_per_s": batches_per_s,
        "byte_equal_store": bool(full.digest == live_digest),
        "run_twice_identical": bool(
            warm.result_digest == again.result_digest
        ),
        "lane0_brier_mean": round(full.lanes[0].brier_mean, 6),
    }
    _ledger_record(
        "e2e_replay_sweep", value=best["sweep"]["wall_s"], unit="s",
        extras={
            "loadavg_1m_before": _loadavg_1m(),
            "replay_batches_per_s": batches_per_s,
            "sweep_speedup": speedup,
        },
    )
    print(
        f"e2e_replay_sweep: {len(configs)} configs x {len(batch_data)} "
        f"batches — sweep {best['sweep']['wall_s']}s vs sequential "
        f"{best['sequential']['wall_s']}s ({speedup}x), "
        f"byte_equal_store={result['byte_equal_store']}"
    )
    return result


def bench_e2e_infer(markets=1024, slots=32, sparse_degree=2, dense_degree=8,
                    max_steps=24, tol=1e-5, steps=2, reps=3, trials=2):
    """Round-18 inference leg: fixed-depth vs adaptive moment sweeps.

    Four variants of the SAME fused settle+analytics program
    (``build_cycle_analytics_loop`` with ``sweep_mode="moments"``) over
    one slot-major workload, AOT-compiled, min-of-N + loadavg:

    * **fixed_sparse / fixed_dense** — the static ``max_steps``-deep
      sweep (``tol=None``): every dispatch pays the full depth.
    * **adaptive_sparse / adaptive_dense** — the deterministic
      early-exit (``tol``): the sweep stops once the all-reduced
      ``max |Δmean|`` residual drops to the tolerance.
    * **xla_sweep / pallas_sweep** — the round-19 kernel arm: the SAME
      dense fixed-depth moment sweep as a standalone AOT stage, XLA
      ``while_loop`` vs the VMEM-resident BP kernel
      (``ops/pallas_bp.py``). Both executables ride the same
      min-of-N clock AND the same ``_hbm_read_capture`` — the
      ``sweep_read_capture`` ratio is args+temps of the kernel program
      over the XLA program (the per-sweep gather temps the kernel keeps
      in VMEM), the ``bce-tpu stats`` hbm_read column for this leg.

    The two graph shapes are the point of the comparison: *sparse*
    pairs each market with one partner (tiny components — the damped
    mix equilibrates in a couple of sweeps), *dense* is a random
    ``dense_degree``-regular graph (one giant component — the residual
    decays at the graph's mixing rate). Acceptance (ISSUE 18): the
    adaptive sparse sweep settles in FEWER iterations than both the
    static bound (``adaptive_saves_sweeps``) and the dense graph
    (``sparse_fewer_sweeps``), at matching outputs
    (``adaptive_matches_fixed`` — the fixed sweep just keeps iterating
    past convergence). ``extras.bp_iters`` (the adaptive sparse trip
    count) feeds the ``bce-tpu stats`` iters column.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.parallel.sharded import (
        build_cycle_analytics_loop,
        init_block_state,
    )

    rng = np.random.default_rng(18)
    k, m = slots, markets
    probs = jnp.asarray(rng.random((k, m)), jnp.float32)
    mask = jnp.asarray(rng.random((k, m)) < 0.9)
    outcome = jnp.asarray(rng.random(m) < 0.5)
    state = jax.tree.map(lambda x: x.T, init_block_state(m, k))
    now0 = jnp.asarray(400.0, jnp.float32)
    mesh = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources")
    )
    # Sparse: each market paired with its xor-partner, the rest of the
    # degree padded out (-1). Dense: a random degree-regular graph.
    sparse_idx = np.full((m, sparse_degree), -1, np.int32)
    sparse_idx[:, 0] = np.arange(m, dtype=np.int32) ^ 1
    sparse_idx = jnp.asarray(sparse_idx)
    sparse_w = jnp.asarray(
        rng.uniform(0.5, 1.5, (m, sparse_degree)), jnp.float32
    )
    dense_idx = jnp.asarray(
        rng.integers(0, m, (m, dense_degree)), jnp.int32
    )
    dense_w = jnp.asarray(
        rng.uniform(0.5, 1.5, (m, dense_degree)), jnp.float32
    )

    # Four (program, blocks) pairs: the depth policy (fixed/adaptive)
    # changes the compiled loop, and the neighbour-block width (the
    # graph degree) is part of the compiled shape.
    exes = {}
    for shape, (gi, gw) in (
        ("sparse", (sparse_idx, sparse_w)),
        ("dense", (dense_idx, dense_w)),
    ):
        for policy, sweep_tol in (("fixed", None), ("adaptive", tol)):
            loop = build_cycle_analytics_loop(
                mesh, donate=False, sweep_steps=max_steps,
                sweep_mode="moments", sweep_tol=sweep_tol,
            )
            exe = jax.jit(
                lambda p, ma, o, s, n, gi_, gw_, loop=loop: loop(
                    p, ma, o, s, n, steps, gi_, gw_
                )
            ).lower(probs, mask, outcome, state, now0, gi, gw).compile()
            exes[f"{policy}_{shape}"] = (exe, gi, gw)

    # Round 19: the kernel arm — the dense fixed-depth sweep stage
    # alone, both routes AOT-compiled so the read capture and the clock
    # run off the SAME executables.
    from bayesian_consensus_engine_tpu.ops.pallas_bp import (
        build_bp_sweep,
        resolve_tile_sweep,
    )
    from bayesian_consensus_engine_tpu.ops.propagate import bp_sweep_math

    sweep_means = jnp.asarray(rng.random(m), jnp.float32)
    sweep_vars = jnp.asarray(
        rng.uniform(1e-4, 0.05, m), jnp.float32
    )
    sweep_tile = resolve_tile_sweep(m, dense_degree, True)
    bp = build_bp_sweep(
        m, dense_degree, max_steps, damping=0.5, moments=True,
        interpret=jax.default_backend() != "tpu",
    )
    sweep_exes = {
        "xla_sweep": jax.jit(
            lambda v, s, gi, gw: bp_sweep_math(
                v, s, gi, gw, damping=0.5, max_steps=max_steps
            )
        ).lower(sweep_means, sweep_vars, dense_idx, dense_w).compile(),
        "pallas_sweep": jax.jit(bp).lower(
            sweep_means, sweep_vars, dense_idx, dense_w
        ).compile(),
    }
    sweep_reads = {
        name: _hbm_read_capture(exe.memory_analysis())["hbm_read_bytes"]
        for name, exe in sweep_exes.items()
    }

    def dispatch(name):
        exe, gi, gw = exes[name]
        out = exe(probs, mask, outcome, state, now0, gi, gw)
        prop = out[4]
        _fence(prop.mean)
        return prop

    def run_variant(name):
        if name in sweep_exes:
            exe = sweep_exes[name]
            best = float("inf")
            for _ in range(reps):
                start = time.perf_counter()
                out = exe(sweep_means, sweep_vars, dense_idx, dense_w)
                _fence(out[0])
                best = min(best, time.perf_counter() - start)
            return {
                "wall_s": round(best, 4),
                "sweeps_per_sec": round(max_steps / best, 1),
                "hbm_read_bytes": sweep_reads[name],
            }
        best = float("inf")
        for _ in range(reps):
            start = time.perf_counter()
            prop = dispatch(name)
            best = min(best, time.perf_counter() - start)
        return {
            "wall_s": round(best, 4),
            "markets_per_sec": round(m / best, 1),
            "iters_run": int(prop.iters_run),
            "residual": float(prop.residual),
        }

    variants = [
        "fixed_sparse", "adaptive_sparse", "fixed_dense", "adaptive_dense",
        "xla_sweep", "pallas_sweep",
    ]
    for name in variants:  # warm off the clock
        if name in sweep_exes:
            _fence(
                sweep_exes[name](
                    sweep_means, sweep_vars, dense_idx, dense_w
                )[0]
            )
        else:
            dispatch(name)
    best = _min_of_trials("e2e_infer", variants, run_variant, trials)

    # Acceptance codas, off the clock: adaptive == fixed at convergence
    # (the fixed sweep just keeps iterating), and the trip counts.
    fixed_prop = dispatch("fixed_sparse")
    adaptive_prop = dispatch("adaptive_sparse")
    matches = bool(
        np.allclose(
            np.asarray(fixed_prop.mean), np.asarray(adaptive_prop.mean),
            rtol=0, atol=10 * tol, equal_nan=True,
        )
    )
    iters_sparse = best["adaptive_sparse"]["iters_run"]
    iters_dense = best["adaptive_dense"]["iters_run"]
    # The kernel arm's read story, off the same executables that raced:
    # the shared one-pass capture fields plus this leg's own bar (the
    # kernel must cut the sweep stage's bytes-read floor to ≤ 0.6 of
    # the XLA program's at the dense shape — ISSUE 19).
    sweep_fields = _onepass_ratio_fields(
        sweep_reads["xla_sweep"], sweep_reads["pallas_sweep"],
        m, sweep_tile,
    )
    result = {
        "workload": (
            f"{m} markets x {k} slots, sweep depth {max_steps}, "
            f"tol {tol:g}"
        ),
        **{name: best[name] for name in variants},
        "wall_s": best["adaptive_sparse"]["wall_s"],
        "bp_iters": iters_sparse,
        "adaptive_saves_sweeps": bool(iters_sparse < max_steps),
        "sparse_fewer_sweeps": bool(iters_sparse < iters_dense),
        "adaptive_matches_fixed": matches,
        "sweep_read_capture": {
            **sweep_fields,
            "sweep_read_leq_0p6": bool(
                sweep_fields["read_ratio"] <= 0.6
            ),
        },
    }
    _ledger_record(
        "e2e_infer", value=best["adaptive_sparse"]["wall_s"], unit="s",
        extras={
            "loadavg_1m_before": _loadavg_1m(),
            "bp_iters": iters_sparse,
            "bp_iters_dense": iters_dense,
            # The kernel sweep's bytes-read floor — the stats table's
            # hbm_read column for this leg, diffed by --against.
            "hbm_read_bytes": sweep_reads["pallas_sweep"],
        },
    )
    print(
        f"e2e_infer: sparse settles in {iters_sparse}/{max_steps} sweeps "
        f"(dense {iters_dense}), adaptive {best['adaptive_sparse']['wall_s']}s "
        f"vs fixed {best['fixed_sparse']['wall_s']}s, "
        f"matches_fixed={matches}; kernel sweep read_ratio "
        f"{sweep_fields['read_ratio']} "
        f"({best['pallas_sweep']['wall_s']}s vs "
        f"{best['xla_sweep']['wall_s']}s)"
    )
    return result


LEGS = {
    "probe": (leg_probe, {}, {}, 240),
    "headline_f32": (
        bench_headline, {}, dict(**_FAST_SHAPE, timed_steps=16), 900,
    ),
    "compact": (
        bench_compact, {}, dict(**_FAST_SHAPE, timed_steps=16), 700,
    ),
    "compact_fit": (
        bench_compact, dict(timed_steps=FIT_STEPS),
        dict(**_FAST_SHAPE, timed_steps=4), 500,
    ),
    "dispatch_rtt": (bench_dispatch_rtt, {}, {}, 240),
    "stream_probe": (bench_stream_probe, {}, dict(steps=8), 400),
    "north_star_band": (
        bench_north_star_band, {},
        dict(markets=2048, slots=64, steps=8, fit_steps=2), 1200,
    ),
    "north_star_f32": (
        bench_north_star_f32, {},
        dict(markets=1024, slots=64, steps=8, fit_steps=2), 1200,
    ),
    "large_k": (
        bench_large_k, {}, dict(markets=512, slots=64, steps=4), 1200,
    ),
    "e2e_pipeline": (
        bench_e2e, {}, dict(markets=2000, resettle_markets=200), 1500,
    ),
    "e2e_ingest": (
        bench_e2e_ingest, {}, dict(markets=20_000, trials=2), 900,
    ),
    "e2e_overlap": (
        bench_e2e_overlap, {}, dict(markets=2000, steps=3, trials=2), 900,
    ),
    "e2e_stream": (
        bench_e2e_stream, {},
        dict(markets=6000, batches=3, steps=3, trials=1), 2000,
    ),
    "e2e_stream_stable_topology": (
        bench_e2e_stream_stable_topology, {},
        dict(markets=3000, batches=3, steps=2, trials=1), 2000,
    ),
    "e2e_stream_delta": (
        bench_e2e_stream_delta, {},
        dict(markets=3000, batches=4, steps=2, trials=1), 2000,
    ),
    "e2e_stream_resident": (
        bench_e2e_stream_resident, {},
        dict(markets=3000, batches=4, steps=2, trials=1), 2000,
    ),
    "dryrun_multichip": (
        bench_dryrun_multichip, {},
        dict(markets=1024, slots=64, steps=2), 1500,
    ),
    "e2e_serve": (
        bench_e2e_serve, {},
        dict(markets=200, source_universe=60, requests=160, concurrency=8,
             max_batch=32, steps=2, trials=1), 2000,
    ),
    "e2e_netserve": (
        bench_e2e_netserve, {},
        dict(markets=120, source_universe=40, requests=240, concurrency=4,
             max_batch=16, steps=2, besteffort_budget=8,
             premium_slo_ms=1000.0, besteffort_slo_ms=1000.0, trials=1),
        2000,
    ),
    "obs_overhead": (
        bench_obs_overhead, {},
        dict(markets=2000, batches=2, steps=2, trials=6), 900,
    ),
    "tiebreak_10k_agents": (
        bench_tiebreak_stress, {}, dict(markets=64, agents=128, reps=1), 900,
    ),
    "e2e_ring_memory": (
        bench_e2e_ring_memory, {},
        dict(markets=64, agents=256, chunk_agents=64, fused_slots=32,
             reps=1, trials=1), 1200,
    ),
    "e2e_analytics": (
        bench_e2e_analytics, {},
        dict(markets=128, slots=64, chunk_slots=16, graph_degree=2,
             steps=2, reps=1, trials=1), 1200,
    ),
    "e2e_onepass": (
        bench_e2e_onepass, {},
        dict(markets=256, slots=32, steps=2, chunk_agents=16,
             chunk_slots=16, reps=1, trials=1, sharded_markets=256,
             sharded_slots=32), 2000,
    ),
    "e2e_kill_soak": (
        bench_e2e_kill_soak, {},
        dict(markets=32, batches=8, kill_after=2, interval=0.08,
             slo_s=0.25), 600,
    ),
    "e2e_replay_sweep": (
        bench_e2e_replay_sweep, {},
        dict(markets=240, batches=3, steps=2, sweep_configs=4, trials=1),
        1200,
    ),
    "e2e_infer": (
        bench_e2e_infer, {},
        dict(markets=128, slots=8, max_steps=12, reps=1, trials=1),
        1200,
    ),
    "pallas_ab": (
        bench_pallas_ab, {},
        dict(num_markets=1024, slots=8, timed_steps=8,
             large_k_attempt=False, sharded_markets=256,
             sharded_slots=32), 1500,
    ),
    "headline_f32_cpu": (
        bench_headline, dict(timed_steps=CPU_FALLBACK_STEPS),
        dict(**_FAST_SHAPE, timed_steps=16), 1200,
    ),
    "compact_cpu": (
        bench_compact, dict(timed_steps=CPU_FALLBACK_STEPS),
        dict(**_FAST_SHAPE, timed_steps=16), 1200,
    ),
    # The streamed-service rate is mostly host work (ingest, checkpoint,
    # orchestration), so even the CPU fallback's number is informative —
    # a degraded round still records the amortised service rate.
    "e2e_stream_cpu": (
        bench_e2e_stream, {},
        dict(markets=6000, batches=3, steps=3), 2000,
    ),
    # Harness self-test hooks (tests/test_bench_harness.py); never scheduled.
    "selftest": (lambda: {"hello": 1}, {}, {}, 60),
    "selftest_hang": (lambda: time.sleep(3600), {}, {}, 60),
    "selftest_crash": (lambda: os._exit(3), {}, {}, 60),
}

# Priority order: the headline legs are secured before anything else so a
# mid-run outage costs side measurements, never the driver metric.
DEVICE_LEG_ORDER = [
    "headline_f32",
    "compact",
    "compact_fit",
    "dispatch_rtt",
    "stream_probe",
    "north_star_band",
    "north_star_f32",
    "large_k",
    "e2e_pipeline",
    "e2e_ingest",
    "e2e_overlap",
    "e2e_stream",
    "e2e_stream_stable_topology",
    "e2e_stream_delta",
    "e2e_stream_resident",
    "e2e_serve",
    "e2e_netserve",
    "obs_overhead",
    "tiebreak_10k_agents",
    "e2e_ring_memory",
    "e2e_analytics",
    "e2e_onepass",
    "e2e_kill_soak",
    "e2e_replay_sweep",
    "e2e_infer",
    "pallas_ab",
    "dryrun_multichip",
]
CPU_FALLBACK_ORDER = ["headline_f32_cpu", "compact_cpu", "e2e_stream_cpu"]

_SELF = os.path.abspath(__file__)


def run_leg_inprocess(name, fast=False, cpu=False):
    """Execute one leg in THIS process (the ``--leg`` entry point)."""
    if cpu:
        import jax

        # Env-var overrides don't work on this host (sitecustomize imports
        # jax with JAX_PLATFORMS=axon pinned); config.update before first
        # backend use is the one effective switch.
        jax.config.update("jax_platforms", "cpu")
    _setup_compile_cache()
    fn, kwargs, fast_kwargs, _ = LEGS[name]
    return fn(**(fast_kwargs if fast else kwargs))


def run_leg_subprocess(name, timeout=None, fast=False, cpu=False,
                       ledger=None):
    """Run one leg as a killable subprocess; never raises, never hangs.

    Returns ``{"ok": True, "value": ..., "wall_s": ..., "phases": {...}}``
    or ``{"ok": False, "error": ...}``. The child gets its own session so
    a hard kill takes its whole process group (jax runtimes spawn threads;
    a hung tunnel read ignores SIGTERM). *ledger* forwards an obs run-
    ledger path so the child appends its own measurement records.
    """
    spec = LEGS.get(name)
    if spec is None:
        return {"ok": False, "error": f"unknown leg {name!r}"}
    _, _, _, default_timeout = spec
    timeout = timeout if timeout is not None else default_timeout
    if fast:
        timeout = min(timeout, 300)
    fd, out_path = tempfile.mkstemp(prefix=f"bce_leg_{name}_", suffix=".json")
    os.close(fd)
    env = None
    if name == "dryrun_multichip":
        # The virtual-mesh leg needs its 8 CPU devices provisioned BEFORE
        # the child's first jax import (this jax has no
        # jax_num_cpu_devices config; the XLA flag is read at backend
        # bring-up, so it must ride the child's environment).
        flag = "--xla_force_host_platform_device_count=8"
        env = dict(os.environ)
        if flag not in env.get("XLA_FLAGS", ""):
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()
    cmd = [sys.executable, _SELF, "--leg", name, "--out", out_path]
    if fast:
        cmd.append("--fast")
    if cpu:
        cmd.append("--cpu")
    if ledger:
        cmd += ["--ledger", str(ledger)]
    try:
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
            start_new_session=True,
            text=True,
            env=env,
        )
        try:
            _, stderr = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.communicate()
            return {"ok": False, "error": f"timeout after {timeout}s (killed)"}
        try:
            with open(out_path) as fh:
                payload = json.load(fh)
        except (OSError, ValueError):
            tail = " | ".join((stderr or "").strip().splitlines()[-3:])[-400:]
            return {
                "ok": False,
                "error": f"leg process died rc={proc.returncode}: {tail}",
            }
        return payload
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass


def probe_with_backoff(run_leg, budget_s, fast=False, sleeper=time.sleep):
    """Retry backend bring-up until it works or *budget_s* is spent.

    Returns ``(probe_result_or_None, attempts, last_error_or_None)``.
    """
    start = time.monotonic()
    backoff = 5 if fast else 15
    attempts = 0
    last_err = "not attempted"
    while True:
        attempts += 1
        res = run_leg("probe", fast=fast)
        if res.get("ok"):
            return res["value"], attempts, None
        last_err = res.get("error", "unknown")
        if time.monotonic() - start + backoff > budget_s:
            return None, attempts, last_err
        sleeper(backoff)
        backoff = min(backoff * 2, 120)


def _num(results, name, key=None):
    """The leg's numeric value, or None (failed / absent / non-numeric)."""
    res = results.get(name)
    if not res or not res.get("ok"):
        return None
    value = res["value"]
    if key is not None:
        value = value.get(key) if isinstance(value, dict) else None
    return value if isinstance(value, (int, float)) else None


def _show(results, name, round_to=None):
    """The leg's value for the JSON, or its failure as a string."""
    res = results.get(name)
    if res is None:
        return "failed: not run"
    if not res.get("ok"):
        return f"failed: {res.get('error', 'unknown')}"
    value = res["value"]
    if round_to is not None and isinstance(value, float):
        return round(value, round_to)
    return value


def compose(results, degraded, probe_info, elapsed_s, fast=False,
            forced_cpu=False):
    """Fold leg results into the driver JSON line. Pure; unit-tested.

    Returns ``(payload, exit_code)``; exit code 0 iff any headline leg
    produced a number. *fast* suppresses the derived numbers whose formulas
    assume the production step counts/shapes (the fit decomposition and
    slot throughput) — a ``--fast`` self-test must never fabricate them.
    *forced_cpu* marks every number as CPU-backend (``--cpu``) so a forced
    run can never masquerade as a TPU record.
    """
    degraded = list(degraded)
    if forced_cpu:
        degraded.append(
            "--cpu: every leg ran on the CPU backend — not a TPU number"
        )
    f32_fast = _num(results, "headline_f32")
    compact = _num(results, "compact")

    headline = headline_source = None
    if compact is not None and (f32_fast is None or compact > f32_fast):
        headline, headline_source = compact, "compact_int8_loop"
        headline_contract = (
            "int8 counter encoding: consensus equal to the scalar contract "
            "within 1e-6 (f32 resolution), state exactly recoverable"
        )
    elif f32_fast is not None:
        headline, headline_source = f32_fast, "f32_fast_loop"
        headline_contract = "bit-exact vs chained single f32 cycles"
    else:
        # Device headline never landed: fall back to the CPU legs.
        cpu_f32 = _num(results, "headline_f32_cpu")
        cpu_compact = _num(results, "compact_cpu")
        if cpu_compact is not None and (
            cpu_f32 is None or cpu_compact > cpu_f32
        ):
            headline, headline_source = cpu_compact, "compact_int8_loop_cpu_fallback"
        elif cpu_f32 is not None:
            headline, headline_source = cpu_f32, "f32_fast_loop_cpu_fallback"
        if headline is not None:
            headline_contract = (
                "CPU-backend fallback at reduced steps — NOT a TPU number; "
                "see degraded"
            )
        else:
            headline_contract = "no headline leg succeeded — see degraded"
            degraded.append("no headline leg succeeded")

    # Two-point decomposition: total(steps) = fixed_dispatch + steps·marginal.
    # The sustained (dispatch-free) kernel rate is the number a long-running
    # settlement service sees — chained dispatches pipeline to ~one RTT.
    # The formula is only valid for the production step counts.
    compact_small = _num(results, "compact_fit")
    if fast:
        compact_fit = "n/a (--fast shapes)"
    elif compact is not None and compact_small is not None:
        t_big = TIMED_STEPS / compact
        t_small = FIT_STEPS / compact_small
        marginal_s = (t_big - t_small) / (TIMED_STEPS - FIT_STEPS)
        if marginal_s <= 0:
            # Tunnel variance between the two runs swamped the kernel term;
            # publish the degeneracy, not a negative rate.
            compact_fit = (
                f"fit degenerate (t_{FIT_STEPS}={t_small * 1e3:.1f}ms, "
                f"t_{TIMED_STEPS}={t_big * 1e3:.1f}ms)"
            )
        else:
            compact_fit = {
                "fixed_dispatch_ms": round(
                    (t_small - FIT_STEPS * marginal_s) * 1e3, 1
                ),
                "marginal_ms_per_step": round(marginal_s * 1e3, 4),
                "sustained_cycles_per_sec": round(1.0 / marginal_s, 1),
            }
    elif compact_small is None and "compact_fit" in results:
        compact_fit = _show(results, "compact_fit")
    else:
        compact_fit = "failed: needs both compact legs"

    stream_gbs = _num(results, "stream_probe")
    if headline is not None and stream_gbs:
        normalised = {
            "headline_cycles_per_gbs": round(headline / stream_gbs, 3),
            "headline_at_819_gbs": round(headline * 819.0 / stream_gbs, 1),
            "note": (
                "819 GB/s = v5e per-chip HBM datasheet; the tunnel's "
                "delivered bandwidth varies run to run, so the per-GB/s "
                "quotient is the cross-round comparable"
            ),
        }
    else:
        normalised = "failed: needs headline + stream probe"

    band = results.get("north_star_band")
    band_value = _show(results, "north_star_band")
    baseline_shape = {
        "metric": (
            "consensus+reliability-update cycles/sec at 1M markets x "
            "10k sources (dense; BASELINE.json shape)"
        ),
        # The full band dict lives once, under extras.north_star_band.
        "measured_per_chip_band": (
            "see extras.north_star_band"
            if isinstance(band_value, dict)
            else band_value
        ),
    }
    if band and band.get("ok") and isinstance(band["value"], dict):
        projected = band["value"].get(
            "projected_v5e8_1m_x_10k_u16_cycles_per_sec"
        )
        if projected is not None:
            # Labelled u16: the v5e-8 projection rests on the
            # reduced-precision band (the f32 band does not fit a 16 GB
            # chip); the f32 anchor is extras.north_star_f32.
            baseline_shape["projected_v5e8_u16_cycles_per_sec"] = projected

    # Slot throughput multiplies by the PRODUCTION shapes — skip under
    # --fast, where the legs ran tiny ones.
    slot_updates = {}
    if not fast:
        if headline is not None:
            slot_updates["headline_gslots_per_sec"] = round(
                headline * NUM_MARKETS * SLOTS_PER_MARKET / 1e9, 2
            )
        large_flat = _num(results, "large_k", "flat_loop_cycles_per_sec")
        if large_flat is not None:
            slot_updates["large_k_gslots_per_sec"] = round(
                large_flat * LARGE_K_MARKETS * LARGE_K_SLOTS / 1e9, 2
            )

    harness = {
        "probe": probe_info if probe_info is not None else "failed",
        "elapsed_s": round(elapsed_s, 1),
        "legs": {
            name: ("ok" if res.get("ok") else res.get("error", "unknown"))
            for name, res in results.items()
        },
        # Per-leg phase decomposition (obs/timeline.py): named spans +
        # "untracked" remainder summing to the leg's wall_s — how the
        # 600× resident-vs-store gap is navigated across rounds. Only
        # legs that ran in a phase-recording subprocess carry one.
        "phase_breakdown": {
            name: {"wall_s": res["wall_s"], "phases": res["phases"]}
            for name, res in results.items()
            if res.get("ok") and "phases" in res
        },
    }

    extras = {
        "stream_probe_gbs": _show(results, "stream_probe", round_to=1),
        "dispatch_rtt_ms": _show(results, "dispatch_rtt", round_to=2),
        "compact_dispatch_fit": compact_fit,
        "headline_source": headline_source,
        "headline_numeric_contract": headline_contract,
        "f32_fast_loop_cycles_per_sec": _show(
            results, "headline_f32", round_to=1
        ),
        "compact_state_cycles_per_sec": _show(results, "compact", round_to=1),
        "normalised_vs_probe": normalised,
        "baseline_shape": baseline_shape,
        "north_star_band": band_value,
        "north_star_f32": _show(results, "north_star_f32"),
        "large_k": _show(results, "large_k"),
        "pallas_ab": _show(results, "pallas_ab"),
        "e2e_pipeline": _show(results, "e2e_pipeline"),
        "e2e_ingest": _show(results, "e2e_ingest"),
        "e2e_overlap": _show(results, "e2e_overlap"),
        "e2e_stream": _show(results, "e2e_stream"),
        "e2e_stream_stable_topology": _show(
            results, "e2e_stream_stable_topology"
        ),
        "e2e_stream_delta": _show(results, "e2e_stream_delta"),
        "e2e_stream_resident": _show(results, "e2e_stream_resident"),
        "e2e_serve": _show(results, "e2e_serve"),
        "e2e_netserve": _show(results, "e2e_netserve"),
        "dryrun_multichip": _show(results, "dryrun_multichip"),
        "obs_overhead": _show(results, "obs_overhead"),
        # Fallback-only leg: absent (not "failed") on healthy runs.
        **(
            {"e2e_stream_cpu": _show(results, "e2e_stream_cpu")}
            if "e2e_stream_cpu" in results
            else {}
        ),
        "tiebreak_10k_agents": _show(results, "tiebreak_10k_agents"),
        "e2e_ring_memory": _show(results, "e2e_ring_memory"),
        "e2e_analytics": _show(results, "e2e_analytics"),
        "e2e_onepass": _show(results, "e2e_onepass"),
        "e2e_kill_soak": _show(results, "e2e_kill_soak"),
        "e2e_replay_sweep": _show(results, "e2e_replay_sweep"),
        "e2e_infer": _show(results, "e2e_infer"),
        "per_slot_throughput": slot_updates,
        "harness": harness,
        "notes": (
            "every dispatch through the axon tunnel pays ~dispatch_rtt_ms "
            "of fixed round-trip cost; headline numbers amortise it over "
            f"{TIMED_STEPS} in-jit steps and compact_dispatch_fit reports "
            "the dispatch-free sustained kernel rate. stream_probe_gbs "
            "is the live bandwidth denominator (tunnel-varying); "
            "normalised_vs_probe divides it out. The headline loop drops "
            "the updated_days carry (21 B/slot/step, bit-exact); "
            "compact_state carries int8 counters (9 B/slot/step, "
            "f32-tolerance-equivalent). north_star_band is the measured "
            "per-chip slice of the BASELINE 1M x 10k dense shape"
        ),
    }
    if degraded:
        extras["degraded"] = degraded

    if forced_cpu:
        cpu_note = " [CPU backend forced via --cpu — not a TPU number]"
    elif "_cpu_fallback" in (headline_source or ""):
        cpu_note = " [CPU-backend fallback — TPU unavailable; see extras.degraded]"
    else:
        cpu_note = ""
    payload = {
        "metric": (
            f"consensus+reliability-update cycles/sec at "
            f"{NUM_MARKETS / 1_000_000:g}M markets x {SLOTS_PER_MARKET} "
            f"signal slots ({SOURCE_UNIVERSE // 1000}k-source universe)"
            f"{cpu_note}"
        ),
        "value": round(headline, 4) if headline is not None else 0.0,
        "unit": "cycles/sec",
        "vs_baseline": (
            round(headline / REFERENCE_BASELINE_CYCLES_PER_SEC, 1)
            if headline is not None
            else 0.0
        ),
        "extras": extras,
    }
    return payload, 0 if headline is not None else 1


def orchestrate(run_leg=run_leg_subprocess, fast=False, cpu=False,
                sleeper=time.sleep):
    """Probe → device legs (priority order) → CPU fallback if needed.

    *cpu* forces every leg onto the CPU backend (harness self-tests on
    hosts where the tunnel is down). Returns ``(payload, exit_code)``.
    """
    start = time.monotonic()
    budget_s = float(
        os.environ.get("BCE_BENCH_BUDGET_S", "300" if fast else "4800")
    )
    probe_budget_s = float(
        os.environ.get("BCE_BENCH_PROBE_BUDGET_S", "30" if fast else "600")
    )
    deadline = start + budget_s
    results = {}
    degraded = []

    probe_info, attempts, probe_err = probe_with_backoff(
        lambda name, fast=False: run_leg(name, fast=fast, cpu=cpu),
        probe_budget_s,
        fast=fast,
        sleeper=sleeper,
    )
    device_ok = probe_info is not None

    def spent():
        return time.monotonic() - start

    def run_or_skip(name, cpu_leg):
        remaining = deadline - time.monotonic()
        if remaining < 60:
            results[name] = {
                "ok": False,
                "error": f"skipped: global budget ({budget_s:g}s) exhausted",
            }
            return
        _, _, _, leg_timeout = LEGS[name]
        results[name] = run_leg(
            name,
            timeout=min(leg_timeout, max(60, int(remaining))),
            fast=fast,
            cpu=cpu_leg,
        )

    if device_ok:
        if attempts > 1:
            degraded.append(
                f"backend bring-up needed {attempts} probe attempts"
            )
        consecutive_timeouts = 0
        tripped = False
        for name in DEVICE_LEG_ORDER:
            if consecutive_timeouts >= 2:
                # Circuit breaker: the probe passed but the tunnel is sick
                # enough that legs hang to their full timeouts — burning
                # every remaining leg's budget would cost over an hour and
                # measure nothing. Remaining device legs are skipped (the
                # CPU headline fallback below still runs if no device
                # headline landed).
                tripped = True
                results[name] = {
                    "ok": False,
                    "error": (
                        "skipped: device legs circuit-broken after 2 "
                        "consecutive timeouts"
                    ),
                }
                continue
            run_or_skip(name, cpu_leg=cpu)
            res = results.get(name)
            # Match the harness's own kill message exactly: a fast CRASH
            # whose stderr tail merely mentions "timeout" burned no budget
            # and must not trip the breaker.
            if res and not res.get("ok") and res.get("error", "").startswith(
                "timeout after"
            ):
                consecutive_timeouts += 1
            elif res and res.get("ok"):
                consecutive_timeouts = 0
        if tripped:
            degraded.append(
                "device legs circuit-broken after consecutive timeouts "
                "(tunnel degraded mid-run)"
            )
    else:
        degraded.append(
            f"tpu backend unavailable after {attempts} probe attempts over "
            f"{round(spent())}s ({probe_err}); headline legs re-run on the "
            f"CPU backend at {CPU_FALLBACK_STEPS} steps"
        )
    if not device_ok or (
        _num(results, "headline_f32") is None
        and _num(results, "compact") is None
    ):
        if device_ok:
            degraded.append(
                "device headline legs failed; CPU-backend fallback appended"
            )
        for name in CPU_FALLBACK_ORDER:
            run_or_skip(name, cpu_leg=True)

    return compose(
        results, degraded, probe_info, spent(), fast=fast, forced_cpu=cpu
    )


def lint_gate(skip: bool) -> None:
    """Refuse to measure a lint-dirty tree.

    A tree that violates the hot-path/determinism contracts (graftlint,
    docs/static-analysis.md) produces numbers that are not comparable to
    the banked baselines — a stray host sync IS a benchmark result change.
    ``--no-lint`` is the escape hatch for deliberately-dirty experiments.
    """
    if skip:
        return
    import pathlib

    from bayesian_consensus_engine_tpu import lint

    # The sidecar makes the warm gate a stat pass: per-file findings are
    # keyed on mtime+size, whole-program findings on the gate-set digest
    # (docs/static-analysis.md "Caching") — an unchanged tree re-lints
    # in milliseconds instead of re-parsing ~150 files per measurement.
    cache = pathlib.Path(__file__).resolve().parent / ".graftlint-cache.json"
    n_files, findings = lint.run(cache=cache)
    errors = [f for f in findings if f.severity == "error"]
    # stderr, not stdout: bench's stdout contract is one JSON line.
    for f in findings:
        print(f.render(), file=sys.stderr)
    if errors:
        print(
            f"bench: tree is lint-dirty ({len(errors)} findings above); "
            "fix them or rerun with --no-lint",
            file=sys.stderr,
        )
        sys.exit(1)


def headline_line(payload):
    """The compact durable headline: final bytes carry value + unit.

    VERDICT r5 #4: BENCH_r05.json lost the round's headline because the
    single (huge) JSON line scrolled off the front of the driver's tail
    capture. This line is printed LAST and is small — any tail capture
    that holds its end holds the number; key order is fixed so
    ``"value"``/``"unit"`` are literally the closing bytes.
    """
    return json.dumps(
        {
            "headline": True,
            "metric": payload["metric"],
            "vs_baseline": payload["vs_baseline"],
            "value": payload["value"],
            "unit": payload["unit"],
        }
    )


def _atomic_write(path, text):
    """Write *text* to *path* via tmp + rename: the file is never torn."""
    tmp_path = f"{path}.tmp.{os.getpid()}"
    with open(tmp_path, "w") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp_path, path)


def _run_leg_with_obs(args):
    """The ``--leg`` entry: run under a phase timeline, report the
    breakdown, append the run-ledger record. Returns the child payload."""
    from bayesian_consensus_engine_tpu.obs.ledger import RunLedger
    from bayesian_consensus_engine_tpu.obs.timeline import (
        PhaseTimeline,
        recording,
    )

    global _LEDGER
    if args.ledger:
        backend = "cpu" if args.cpu else os.environ.get("JAX_PLATFORMS")
        _LEDGER = RunLedger(args.ledger, backend=backend)
    timeline = PhaseTimeline()
    start = time.perf_counter()
    try:
        with recording(timeline):
            value = run_leg_inprocess(args.leg, fast=args.fast, cpu=args.cpu)
    except Exception as exc:  # noqa: BLE001 — reported to the parent
        _ledger_record(
            args.leg, extras={"error": f"{type(exc).__name__}: {exc}"}
        )
        if _LEDGER is not None:
            _LEDGER.close()
        return {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
    wall = time.perf_counter() - start
    # The additive leg breakdown: named exclusive spans plus the
    # untracked remainder, summing to wall_s by construction.
    phases = {k: round(v, 6) for k, v in timeline.totals().items()}
    untracked = wall - sum(phases.values())
    if untracked > 0:
        phases["untracked"] = round(untracked, 6)
    if _LEDGER is not None:
        extras = {"wall_s": round(wall, 3)}
        if isinstance(value, dict):
            # Kernel-bearing legs (pallas_ab) carry the honesty-guarded
            # tuner adjudications — recorded so `bce-tpu stats` renders
            # the bank-vs-race provenance and `--against` can flag a
            # verdict flip (round 20).
            extras.update({
                key: val for key, val in value.items()
                if str(key).endswith("autotune_decision")
            })
        _ledger_record(
            args.leg,
            value=value if isinstance(value, (int, float)) else None,
            phases=phases,
            extras=extras,
        )
        _LEDGER.close()
    return {
        "ok": True,
        "value": value,
        "wall_s": round(wall, 3),
        "phases": phases,
    }


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--leg", help="run one leg in-process (internal)")
    parser.add_argument(
        "--out",
        help=(
            "atomically write the full JSON record here (per-leg result "
            "for --leg; the driver record otherwise)"
        ),
    )
    parser.add_argument(
        "--ledger",
        help=(
            "append per-leg obs run-ledger records (JSONL; loadavg + "
            "repeat index) here — render with `bce-tpu stats`"
        ),
    )
    parser.add_argument(
        "--fast", action="store_true",
        help="tiny shapes + short budgets (harness self-test)",
    )
    parser.add_argument(
        "--cpu", action="store_true",
        help="force the CPU backend for every leg",
    )
    parser.add_argument(
        "--no-lint", action="store_true",
        help="skip the pre-run graftlint gate (docs/static-analysis.md)",
    )
    args = parser.parse_args(argv)

    if args.leg:
        payload = _run_leg_with_obs(args)
        out = json.dumps(payload)
        if args.out:
            # Atomic like the driver record: the parent must never read a
            # torn per-leg file from a child that died mid-write.
            _atomic_write(args.out, out)
        else:
            print(out)
        return 0

    # Gate the orchestrated run only — each --leg subprocess is spawned by
    # an orchestrator that already passed (or explicitly skipped) the gate.
    lint_gate(args.no_lint)
    if args.ledger:
        import functools

        run_leg = functools.partial(run_leg_subprocess, ledger=args.ledger)
    else:
        run_leg = run_leg_subprocess
    payload, rc = orchestrate(run_leg=run_leg, fast=args.fast, cpu=args.cpu)
    full = json.dumps(payload)
    if args.out:
        _atomic_write(args.out, full + "\n")
    print(full)
    # LAST line, always: the compact headline no tail capture can lose.
    print(headline_line(payload))
    return rc


if __name__ == "__main__":
    sys.exit(main())
