"""Driver benchmark: consensus + reliability-update cycles/sec.

Headline workload: a 1M-market batch with 16 source slots per market drawn
from a 10k-source universe — the slot-packed representation of the sparse
(markets × sources) signal matrix (not every source signals every market;
~90% slot occupancy). One cycle = read-time decay → reliability-weighted
consensus → outcome correctness → capped reliability/confidence update —
the batched equivalent of the reference's ``compute_all_consensus`` +
per-pair ``update_reliability`` sweep (reference: market.py:200-221,
reliability.py:185-231).

The JSON line also carries the **large-K regime** (BASELINE config #5's
source scale): 16k markets × 10k slots ≈ 655 MB per f32 block, the densest
single-chip configuration, run through both the flat slot-major loop and
the ring (sources-parallel) loop, plus the hand-fused Pallas kernel's
number at 1M×16 (XLA fusion wins — kept for the record).

Measurement notes (all learned the hard way on this host):
  * every timed loop runs INSIDE one jit (``lax.fori_loop``) — per-dispatch
    overhead through the axon TPU tunnel is ~4 ms at 1M×16 and grows with
    operand sizes (~70 ms at 16k×10k), so chained host dispatches measure
    the tunnel, not the kernel
  * state is slot-major (K, M): markets on the 128-lane minor dim (~25%
    faster than (M, K) with K=16)
  * the markets axis is padded to a lane multiple (1M → 1,000,448 = 7816·128,
    mask=0 pads): the ragged tail tile otherwise costs ~20% of throughput
  * on the axon tunnel ``block_until_ready`` does NOT force remote execution
    — every timing fence is a scalar value fetch
  * the tunnel's delivered HBM bandwidth VARIES RUN TO RUN (measured
    ~140-410 GB/s across sessions); every run emits a live stream probe
    (``stream_probe_gbs``) so cycle numbers can be normalised across
    rounds — when the chip delivers ~400 GB/s the cycle is
    bandwidth-bound and byte counts are destiny (bf16 moves 2× the
    elements at the same GB/s), at degraded bandwidth other floors appear

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "cycles/sec", "vs_baseline": N,
     "extras": {...}}

``vs_baseline`` is against the reference implementation measured on this
host's CPU (scripts/measure_reference_baseline.py): 2710.2 markets/sec at
16 sources/market → 0.0027102 1M-cycles/sec. Re-run that script to refresh
(host CPU contention moves it; the recorded value is the FASTEST measured,
so vs_baseline is conservative).
"""

import json
import os
import time


def _enable_compile_cache() -> None:
    """Opt-in persistent XLA compile cache (set BCE_JAX_CACHE=<dir>).

    The bench compiles ~12 distinct loop programs; on a loaded host each
    costs tens of seconds of host-CPU XLA time, a large share of wall
    clock. A persistent cache lets every run after the first reuse them —
    but executable serialization through the tunneled TPU plugin is
    unverified here, so the cache stays OFF unless explicitly requested.
    """
    cache_dir = os.environ.get("BCE_JAX_CACHE")
    if not cache_dir:
        return
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:  # noqa: BLE001 — cache is an optimisation only
        pass


_enable_compile_cache()

# Measured 2026-07-30 via scripts/measure_reference_baseline.py (1000 markets,
# 16 sources/market, in-memory SQLite, warm reliability table). 2026-07-29
# measured 0.0019838 on a busier CPU; the faster (reference-favouring)
# number is recorded.
REFERENCE_BASELINE_CYCLES_PER_SEC = 0.0027102

NUM_MARKETS = 1_000_000
SLOTS_PER_MARKET = 16
SOURCE_UNIVERSE = 10_000
# Step count amortises the axon tunnel's ~96 ms dispatch+fence round trip
# (measured: a jitted 8-element add costs 95.7 ms end-to-end; see
# scripts/perf_floor2.py + docs/tpu-architecture.md). At 100 steps the
# dispatch dominated (~1 ms/step of pure RTT — round 2's misattributed
# "1.1 ms/step floor"); at 1600 it is ~6% of the total. The marginal
# kernel rate is reported separately in extras via a two-point fit.
TIMED_STEPS = 1600
FIT_STEPS = 400  # second point for the fixed-vs-marginal decomposition

LARGE_K_MARKETS = 16_384
LARGE_K_SLOTS = 10_000
LARGE_K_STEPS = 50


def build_workload(key, num_markets, slots, dtype):
    import jax
    import jax.numpy as jnp

    k_probs, k_mask, k_outcome, k_src = jax.random.split(key, 4)
    probs = jax.random.uniform(k_probs, (num_markets, slots), dtype=dtype)
    # ~90% slot occupancy: not every source signals every market.
    mask = jax.random.uniform(k_mask, (num_markets, slots)) < 0.9
    outcome = jax.random.uniform(k_outcome, (num_markets,)) < 0.5
    # Slot → source-universe assignment (pair identity; carried for realism,
    # not consumed by the cycle math, which is per-(market, slot)).
    src_idx = jax.random.randint(
        k_src, (num_markets, slots), 0, SOURCE_UNIVERSE, dtype=jnp.int32
    )
    return probs, mask, outcome, src_idx


def _fence(x):
    """Force remote execution (scalar value fetch — see module notes)."""
    return float(x.reshape(-1)[0])


def timed_best_of(loop_call, make_state, steps, trials=3):
    """Warm once (compile + full run), then best-of-N per-step seconds → rate.

    Every run gets a fresh donated state and is fenced by a scalar value
    fetch (``block_until_ready`` does not force execution through the axon
    tunnel — see module notes). ``loop_call`` returns (state, consensus).
    """
    _, consensus = loop_call(make_state())
    _fence(consensus)
    best = float("inf")
    for _ in range(trials):
        state_in = make_state()
        start = time.perf_counter()
        _, consensus = loop_call(state_in)
        _fence(consensus)
        best = min(best, (time.perf_counter() - start) / steps)
    return 1.0 / best


def headline_inputs(num_markets, slots):
    """Shared headline workload setup: mesh, padded slot-major inputs.

    ONE place decides mesh selection, lane padding, and shardings for every
    bench that claims the headline shape (bench_headline, bench_compact) —
    they must stay apples-to-apples. Returns
    ``(mesh, probs, mask, outcome, padded_total, block_sharding)``.
    """
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import make_mesh, pad_markets
    from bayesian_consensus_engine_tpu.parallel.mesh import (
        MARKETS_AXIS,
        SOURCES_AXIS,
    )

    # All devices on the markets axis: the reductions stay device-local and
    # the cycle needs zero communication (mesh.py default policy).
    mesh = make_mesh() if len(jax.devices()) > 1 else None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        block_sharding = NamedSharding(mesh, P(SOURCES_AXIS, MARKETS_AXIS))
        market_sharding = NamedSharding(mesh, P(MARKETS_AXIS))
    else:
        block_sharding = market_sharding = None

    probs, mask, outcome, _src_idx = build_workload(
        jax.random.PRNGKey(0), num_markets, slots, jnp.float32
    )
    # Slot-major layout: (K, M), markets on lanes — padded to a lane multiple
    # (pads carry mask=0: zero weight, NaN consensus, cold state).
    probs, mask = probs.T, mask.T
    lane_multiple = 128 * (mesh.shape[MARKETS_AXIS] if mesh is not None else 1)
    probs, mask, outcome, _, padded_total = pad_markets(
        probs, mask, outcome, state=None, multiple=lane_multiple
    )
    if mesh is not None:
        probs = jax.device_put(probs, block_sharding)
        mask = jax.device_put(mask, block_sharding)
        outcome = jax.device_put(outcome, market_sharding)
    return mesh, probs, mask, outcome, padded_total, block_sharding


def bench_headline(num_markets=NUM_MARKETS, slots=SLOTS_PER_MARKET,
                   timed_steps=TIMED_STEPS):
    """The 1M-market slot-packed cycle loop (driver metric)."""
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import (
        MarketBlockState,
        build_cycle_loop,
        init_block_state,
    )

    mesh, probs, mask, outcome, padded_total, block_sharding = headline_inputs(
        num_markets, slots
    )
    dtype = jnp.float32

    def fresh_state():
        """Slot-major state, pre-sharded, fully materialised (fenced)."""
        state = MarketBlockState(
            *(x.T for x in init_block_state(padded_total, slots, dtype=dtype))
        )
        if mesh is not None:
            state = MarketBlockState(
                *(jax.device_put(x, block_sharding) for x in state)
            )
        _fence(state.reliability)  # construction outside the timer
        return state

    loop = build_cycle_loop(mesh, slot_major=True, donate=True)
    return timed_best_of(
        lambda s: loop(probs, mask, outcome, s, jnp.asarray(1.0, dtype), timed_steps),
        fresh_state,
        timed_steps,
    )


def bench_large_k(markets=LARGE_K_MARKETS, slots=LARGE_K_SLOTS,
                  steps=LARGE_K_STEPS):
    """The 10k-source regime on one chip: flat, ring, and compact loops.

    Returns ``(flat_cps, ring_cps, compact_cps)``. The compact state at
    this shape is ~0.9 GB vs ~2 GB of f32 — the counter encoding is also
    a capacity lever for the long-sources regime.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.parallel import (
        MarketBlockState,
        build_compact_cycle_loop,
        build_cycle_loop,
        init_block_state,
        init_compact_state,
    )
    from bayesian_consensus_engine_tpu.parallel.ring import build_ring_cycle_loop

    dtype = jnp.float32
    probs, mask, outcome, _ = build_workload(
        jax.random.PRNGKey(1), markets, slots, dtype
    )

    # Flat slot-major loop (K on sublanes, M on lanes).
    tp, tm = probs.T, mask.T
    flat = build_cycle_loop(mesh=None, slot_major=True, donate=True)

    def flat_state():
        state = MarketBlockState(
            *(x.T for x in init_block_state(markets, slots, dtype=dtype))
        )
        _fence(state.reliability)
        return state

    flat_cps = timed_best_of(
        lambda s: flat(tp, tm, outcome, s, jnp.asarray(1.0, dtype), steps),
        flat_state,
        steps,
    )

    # Ring (sources-parallel) loop on a 1-device mesh; full-width local pass
    # is fastest when the shard fits (chunking is for when it does not).
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources"))
    ring = build_ring_cycle_loop(mesh, chunk_slots=None, donate=True)

    def ring_state():
        state = init_block_state(markets, slots, dtype=dtype)
        _fence(state.reliability)
        return state

    ring_cps = timed_best_of(
        lambda s: ring(probs, mask, outcome, s, jnp.asarray(1.0, dtype), steps),
        ring_state,
        steps,
    )

    compact = build_compact_cycle_loop(mesh=None, donate=True)

    def compact_state():
        state = init_compact_state(markets, slots)
        _fence(state.updated_days)
        return state

    compact_cps = timed_best_of(
        lambda s: compact(tp, tm, outcome, s, jnp.asarray(1.0, dtype), steps),
        compact_state,
        steps,
    )
    return flat_cps, ring_cps, compact_cps


def bench_pallas(num_markets=NUM_MARKETS, slots=SLOTS_PER_MARKET,
                 timed_steps=TIMED_STEPS, tile=2048):
    """The hand-fused Pallas cycle at 1M×16 (hardware evidence; XLA wins)."""
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.ops.pallas_cycle import (
        SlotMajorState,
        build_pallas_cycle,
    )

    padded = -(-num_markets // tile) * tile
    probs, mask, outcome, _ = build_workload(
        jax.random.PRNGKey(0), num_markets, slots, jnp.float32
    )
    pad = padded - num_markets
    probs = jnp.pad(probs.T, ((0, 0), (0, pad)))
    mask = jnp.pad(mask.T, ((0, 0), (0, pad))).astype(jnp.float32)
    outcome = jnp.pad(outcome, (0, pad)).astype(jnp.float32)[None, :]

    call = build_pallas_cycle(padded, slots, tile_markets=tile)

    def loop_fn(probs, mask, outcome, state):
        def body(i, carry):
            state, _ = carry
            state, consensus, _, _ = call(probs, mask, outcome, state, 1.0 + i)
            return state, consensus

        init = jnp.zeros((1, padded), jnp.float32)
        return jax.lax.fori_loop(0, timed_steps, body, (state, init))

    loop = jax.jit(loop_fn)

    def fresh_state():
        state = SlotMajorState(
            jnp.full((slots, padded), 0.5, jnp.float32),
            jnp.full((slots, padded), 0.25, jnp.float32),
            jnp.zeros((slots, padded), jnp.float32),
            jnp.zeros((slots, padded), jnp.float32),
        )
        _fence(state.reliability)
        return state

    return timed_best_of(
        lambda s: loop(probs, mask, outcome, s), fresh_state, timed_steps
    )


def bench_dispatch_rtt(trials=5):
    """Pure tunnel dispatch+fence round trip: a jitted 8-element add.

    This is the fixed cost every dispatch pays through the axon tunnel —
    the denominator correction for every other number here (measured
    ~96 ms on this host, i.e. 1600 amortising steps put it at ~6%).
    """
    import jax
    import jax.numpy as jnp

    tiny = jax.jit(lambda x: x + 1.0)
    a = jnp.zeros((8,), jnp.float32)
    _fence(tiny(a))
    best = float("inf")
    for _ in range(trials):
        start = time.perf_counter()
        _fence(tiny(a))
        best = min(best, time.perf_counter() - start)
    return best * 1e3


def bench_stream_probe(steps=400):
    """Live streaming roofline: read+write two f32 blocks per step (GB/s).

    The axon tunnel's delivered bandwidth varies run to run (measured
    anywhere from ~140 to ~410 GB/s on this host); this number is the
    denominator that makes cycle throughput comparable across rounds.
    """
    import jax
    import jax.numpy as jnp

    k, m = SLOTS_PER_MARKET, 1_000_448

    def loop(a, b):
        return jax.lax.fori_loop(
            0, steps, lambda i, c: (c[1] + 1.0, c[0] * 0.5), (a, b)
        )

    sl = jax.jit(loop, donate_argnums=(0, 1))

    def fresh():
        a = jnp.ones((k, m), jnp.float32)
        b = jnp.ones((k, m), jnp.float32)
        _fence(a)
        return a, b

    out = sl(*fresh())
    _fence(out[0])
    best = float("inf")
    for _ in range(3):
        a, b = fresh()
        start = time.perf_counter()
        out = sl(a, b)
        _fence(out[0])
        best = min(best, (time.perf_counter() - start) / steps)
    return 4 * k * m * 4 / best / 1e9  # 2 tensors read + 2 written per step


def bench_compact(num_markets=NUM_MARKETS, slots=SLOTS_PER_MARKET,
                  timed_steps=TIMED_STEPS):
    """The counter-compact loop (parallel/compact.py) at the headline shape.

    Inputs come from the same ``headline_inputs`` as bench_headline, so the
    compact-vs-headline numbers stay apples-to-apples by construction.
    """
    import jax
    import jax.numpy as jnp

    from bayesian_consensus_engine_tpu.parallel import (
        build_compact_cycle_loop,
        init_compact_state,
    )

    mesh, probs, mask, outcome, padded_total, block_sharding = headline_inputs(
        num_markets, slots
    )
    loop = build_compact_cycle_loop(mesh, donate=True)

    def fresh_state():
        state = init_compact_state(padded_total, slots)
        if mesh is not None:
            state = jax.tree.map(
                lambda x: jax.device_put(x, block_sharding), state
            )
        _fence(state.updated_days)
        return state

    return timed_best_of(
        lambda s: loop(probs, mask, outcome, s, jnp.asarray(1.0, jnp.float32),
                       timed_steps),
        fresh_state,
        timed_steps,
    )


def bench_tiebreak_stress(markets=2048, agents=10_000, reps=3):
    """BASELINE config #4: deterministic tie-break at 10k agents per market.

    Runs BOTH at-scale groupings on the chip — the ring/pairwise path
    (parallel/ring.py, O(A²) compares that XLA fuses) and the sort-based
    path (ops/tiebreak.py, O(A log A) but bottlenecked by XLA's TPU sort)
    — and reports markets-resolved/sec plus the compiled memory footprint
    of the ring call (its origin buffer is the documented at-scale risk;
    single-chip ring_size=1 keeps it one block — shard markets too when
    multi-device, tests/test_ring.py::test_markets_axis_sharded_too).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bayesian_consensus_engine_tpu.ops.tiebreak import build_batched_tiebreak
    from bayesian_consensus_engine_tpu.parallel.ring import build_ring_tiebreak

    rng = np.random.default_rng(11)
    grid = np.round(np.linspace(0.05, 0.95, 37), 6)
    args = (
        jnp.asarray(rng.choice(grid, (markets, agents)), jnp.float32),
        jnp.asarray(rng.uniform(0.1, 2.0, (markets, agents)), jnp.float32),
        jnp.asarray(rng.uniform(0, 1, (markets, agents)), jnp.float32),
        jnp.asarray(rng.uniform(0, 1, (markets, agents)), jnp.float32),
        jnp.asarray(rng.random((markets, agents)) < 0.9),
    )

    def best_of(fn):
        out = fn(*args)
        _fence(out.prediction)
        best = float("inf")
        for _ in range(reps):
            start = time.perf_counter()
            out = fn(*args)
            _fence(out.prediction)
            best = min(best, time.perf_counter() - start)
        return markets / best

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("markets", "sources"))
    # AOT-compile once and time the executable itself — .lower().compile()
    # does not seed the jit call cache, so timing `ring` would recompile.
    compiled = build_ring_tiebreak(mesh).lower(*args).compile()
    mem = compiled.memory_analysis()
    ring_rate = best_of(lambda *a: compiled(*a))
    sorted_rate = best_of(build_batched_tiebreak())

    return {
        "workload": f"{markets} markets x {agents} agents",
        "ring_markets_per_sec": round(ring_rate, 1),
        "sorted_markets_per_sec": round(sorted_rate, 1),
        "ring_compiled_temp_mb": round(mem.temp_size_in_bytes / 1e6, 1),
        "ring_compiled_args_mb": round(mem.argument_size_in_bytes / 1e6, 1),
    }


def bench_e2e(markets=NUM_MARKETS, mean_slots=4, steps=20,
              resettle_markets=10_000):
    """The whole pipeline at headline scale, ingest and flush included.

    payloads → native packer → interned rows → device block → N-cycle loop
    → absorb → SQLite flush: the full settlement flow a production caller
    runs, not just the device kernel, at 1M markets. A second, small
    settlement (*resettle_markets*) then checkpoints INCREMENTALLY to the
    same file — flush cost must scale with touched rows, not store size
    (reference UPSERT semantics, reliability.py:221-231). Returns
    (cycles_per_sec_amortised, breakdown dict in seconds).
    """
    import os
    import tempfile

    import numpy as np

    from bayesian_consensus_engine_tpu.pipeline import (
        build_settlement_plan,
        settle,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    rng = np.random.default_rng(7)
    counts = rng.poisson(mean_slots - 1, markets) + 1
    src = rng.integers(0, SOURCE_UNIVERSE, counts.sum())
    prob = rng.random(counts.sum())
    offsets = np.concatenate([[0], np.cumsum(counts)])
    payloads = [
        (
            f"market-{m}",
            [
                {"sourceId": f"src-{src[i]}", "probability": float(prob[i])}
                for i in range(offsets[m], offsets[m + 1])
            ],
        )
        for m in range(markets)
    ]
    outcomes = rng.random(markets) < 0.5

    # The 1M-dict payload fixture is long-lived caller data: without
    # gc.freeze() every generational collection re-scans its ~9M containers,
    # tripling every host-side pass below (measured 14 s -> 4 s for one
    # ingest). Freezing long-lived state is the standard CPython service
    # pattern; the framework's own cost is what remains. Paired with the
    # unfreeze below so callers get normal GC back.
    import gc

    gc.freeze()
    try:
        # Host-CPU legs run min-of-2: this box carries unrelated load whose
        # bursts can inflate a pure-host pass several-fold (device legs are
        # unaffected — they wait on the chip, not the host).
        store = TensorReliabilityStore()
        start = time.perf_counter()
        plan = build_settlement_plan(store, payloads)
        t_ingest = time.perf_counter() - start
        start = time.perf_counter()
        build_settlement_plan(TensorReliabilityStore(), payloads)
        t_ingest = min(t_ingest, time.perf_counter() - start)

        # Columnar twin: callers holding signals as flat columns skip the
        # per-dict Python walk entirely (vectorised grouping + one C interning
        # pass). Measured on its own store so interner state is comparable.
        from bayesian_consensus_engine_tpu.pipeline import (
            build_settlement_plan_columnar,
        )

        source_ids = [f"src-{s}" for s in src.tolist()]
        market_keys = [market_id for market_id, _signals in payloads]
        t_ingest_columnar = float("inf")
        for _ in range(2):
            start = time.perf_counter()
            build_settlement_plan_columnar(
                TensorReliabilityStore(), market_keys, source_ids, prob,
                offsets.astype(np.int64),
            )
            t_ingest_columnar = min(
                t_ingest_columnar, time.perf_counter() - start
            )

        settle(store, plan, outcomes, steps=steps)  # compile + warm
        store.epoch_origin()  # sync the warm-up's deferred state off the clock
        start = time.perf_counter()
        result = settle(store, plan, outcomes, steps=steps)
        result.fence()  # completion, without the full result-vector fetch
        t_settle = time.perf_counter() - start
        # Result delivery (the consensus vector's device→host transfer) is
        # a separate leg: through this host's tunnel it can dwarf the
        # kernel, and a settle-and-checkpoint service never pays it.
        start = time.perf_counter()
        _ = result.consensus
        t_consensus_fetch = time.perf_counter() - start
        # The settle deferred its host merge; time the sync explicitly so the
        # breakdown stays honest (epoch_origin is the cheapest forcing read).
        start = time.perf_counter()
        store.epoch_origin()
        t_sync = time.perf_counter() - start

        with tempfile.TemporaryDirectory() as tmp:
            db = os.path.join(tmp, "settled.db")
            start = time.perf_counter()
            rows = store.flush_to_sqlite(os.path.join(tmp, "probe.db"))
            t_flush = time.perf_counter() - start
            start = time.perf_counter()
            store.flush_to_sqlite(db)  # min-of-2; db is the kept target
            t_flush = min(t_flush, time.perf_counter() - start)

            # Incremental checkpoint: settle a small slice, flush the delta
            # (the flush syncs the deferred state first — all-in cost shown).
            sub_plan = build_settlement_plan(store, payloads[:resettle_markets])
            settle(store, sub_plan, outcomes[:resettle_markets], steps=1)
            start = time.perf_counter()
            dirty_rows = store.flush_to_sqlite(db)
            t_flush_incr = time.perf_counter() - start

            # Steady state: chained settles stay device-resident (deferred
            # absorb — no per-settle re-upload or host merge). The first settle
            # below re-primes the device after the flush's sync; the second is
            # the sustained per-batch cost a long-running service pays.
            settle(store, plan, outcomes, steps=steps)
            start = time.perf_counter()
            chained = settle(store, plan, outcomes, steps=steps)
            chained.fence()
            t_settle_chained = time.perf_counter() - start

        # Amortised total stays conservative: result delivery included.
        total = t_ingest + t_settle + t_consensus_fetch + t_sync + t_flush
        return steps / total, {
            "workload": (
                f"{markets} markets, {int(counts.sum())} signals, "
                f"{rows} pairs, {steps} cycles"
            ),
            "ingest_s": round(t_ingest, 3),
            "ingest_columnar_s": round(t_ingest_columnar, 3),
            "settle_s": round(t_settle, 3),
            "consensus_fetch_s": round(t_consensus_fetch, 3),
            "host_sync_s": round(t_sync, 3),
            "settle_chained_s": round(t_settle_chained, 3),
            "steady_state_cycles_per_sec": round(steps / t_settle_chained, 1),
            "flush_s": round(t_flush, 3),
            "incremental_flush": {
                "resettled_markets": resettle_markets,
                "rows_written": dirty_rows,
                "flush_s": round(t_flush_incr, 3),
            },
        }
    finally:
        # Pairing is local: any caller of bench_e2e gets normal GC back.
        gc.unfreeze()


def run():
    f32_fast = bench_headline()
    # Side measurements must never sink the bench (or the headline metric):
    # report a failure string instead.
    try:
        dispatch_rtt = round(bench_dispatch_rtt(), 2)
    except Exception as exc:  # noqa: BLE001
        dispatch_rtt = f"failed: {type(exc).__name__}"
    try:
        stream_gbs = round(bench_stream_probe(), 1)
    except Exception as exc:  # noqa: BLE001
        stream_gbs = f"failed: {type(exc).__name__}"
    try:
        compact = bench_compact()
    except Exception as exc:  # noqa: BLE001
        compact = f"failed: {type(exc).__name__}"
    # Two-point decomposition: total(steps) = fixed_dispatch + steps·marginal.
    # The sustained (dispatch-free) kernel rate is the number a long-running
    # settlement service sees — chained dispatches pipeline to ~one RTT
    # (measured, scripts/perf_floor2.py).
    try:
        compact_small = bench_compact(timed_steps=FIT_STEPS)
        t_big = TIMED_STEPS / compact
        t_small = FIT_STEPS / compact_small
        marginal_s = (t_big - t_small) / (TIMED_STEPS - FIT_STEPS)
        if marginal_s <= 0:
            # Tunnel variance between the two runs swamped the kernel term;
            # publish the degeneracy, not a negative rate.
            compact_fit = (
                f"fit degenerate (t_{FIT_STEPS}={t_small * 1e3:.1f}ms, "
                f"t_{TIMED_STEPS}={t_big * 1e3:.1f}ms)"
            )
        else:
            compact_fit = {
                "fixed_dispatch_ms": round(
                    (t_small - FIT_STEPS * marginal_s) * 1e3, 1
                ),
                "marginal_ms_per_step": round(marginal_s * 1e3, 4),
                "sustained_cycles_per_sec": round(1.0 / marginal_s, 1),
            }
    except Exception as exc:  # noqa: BLE001
        compact_fit = f"failed: {type(exc).__name__}"
    # The metric is the cycle, not one implementation of it: report the
    # fastest valid path (compact int8 counters vs bit-exact f32 fast
    # loop), with both numbers and the winner recorded in extras.
    if isinstance(compact, float) and compact > f32_fast:
        headline, headline_source = compact, "compact_int8_loop"
        headline_contract = (
            "int8 counter encoding: consensus equal to the scalar contract "
            "within 1e-6 (f32 resolution), state exactly recoverable"
        )
    else:
        headline, headline_source = f32_fast, "f32_fast_loop"
        headline_contract = "bit-exact vs chained single f32 cycles"
    try:
        large_flat, large_ring, large_compact = bench_large_k()
    except Exception as exc:  # noqa: BLE001
        large_flat = large_ring = large_compact = f"failed: {type(exc).__name__}"
    try:
        pallas = round(bench_pallas(), 1)
    except Exception as exc:  # noqa: BLE001
        pallas = f"failed: {type(exc).__name__}"
    try:
        e2e_cps, e2e_parts = bench_e2e()
        e2e = {"cycles_per_sec_amortised": round(e2e_cps, 1), **e2e_parts}
    except Exception as exc:  # noqa: BLE001
        e2e = f"failed: {type(exc).__name__}"
    try:
        tiebreak = bench_tiebreak_stress()
    except Exception as exc:  # noqa: BLE001
        tiebreak = f"failed: {type(exc).__name__}"

    slot_updates = {
        "headline_gslots_per_sec": round(
            headline * NUM_MARKETS * SLOTS_PER_MARKET / 1e9, 2
        ),
    }
    if isinstance(large_flat, float):
        slot_updates["large_k_gslots_per_sec"] = round(
            large_flat * LARGE_K_MARKETS * LARGE_K_SLOTS / 1e9, 2
        )
    return {
        "metric": (
            f"consensus+reliability-update cycles/sec at "
            f"{NUM_MARKETS / 1_000_000:g}M markets x {SLOTS_PER_MARKET} "
            f"signal slots ({SOURCE_UNIVERSE // 1000}k-source universe)"
        ),
        "value": round(headline, 4),
        "unit": "cycles/sec",
        "vs_baseline": round(headline / REFERENCE_BASELINE_CYCLES_PER_SEC, 1),
        "extras": {
            "stream_probe_gbs": stream_gbs,
            "dispatch_rtt_ms": dispatch_rtt,
            "compact_dispatch_fit": compact_fit,
            "headline_source": headline_source,
            "headline_numeric_contract": headline_contract,
            "f32_fast_loop_cycles_per_sec": round(f32_fast, 1),
            "compact_state_cycles_per_sec": (
                round(compact, 1) if isinstance(compact, float) else compact
            ),
            "large_k": {
                "workload": f"{LARGE_K_MARKETS} markets x {LARGE_K_SLOTS} slots",
                "flat_loop_cycles_per_sec": (
                    round(large_flat, 1)
                    if isinstance(large_flat, float) else large_flat
                ),
                "ring_loop_cycles_per_sec": (
                    round(large_ring, 1)
                    if isinstance(large_ring, float) else large_ring
                ),
                "compact_loop_cycles_per_sec": (
                    round(large_compact, 1)
                    if isinstance(large_compact, float) else large_compact
                ),
            },
            "pallas_1m16_cycles_per_sec": pallas,
            "e2e_pipeline": e2e,
            "tiebreak_10k_agents": tiebreak,
            "per_slot_throughput": slot_updates,
            "notes": (
                "every dispatch through the axon tunnel pays ~dispatch_rtt_ms "
                "of fixed round-trip cost (round 2's '1.1 ms/step floor' was "
                "this RTT divided by 100 steps — resolved, see "
                "docs/tpu-architecture.md); headline numbers amortise it over "
                f"{TIMED_STEPS} in-jit steps and compact_dispatch_fit reports "
                "the dispatch-free sustained kernel rate. stream_probe_gbs "
                "is the live bandwidth denominator (tunnel-varying). The "
                "headline loop drops the updated_days carry (21 B/slot/step, "
                "bit-exact); compact_state carries int8 counters "
                "(9 B/slot/step, f32-tolerance-equivalent). XLA fusion beats "
                "the hand-fused Pallas kernel at 1M x 16"
            ),
        },
    }


if __name__ == "__main__":
    print(json.dumps(run()))
