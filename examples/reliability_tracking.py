"""Reliability lifecycle: outcomes move scores, decay erodes them, consensus
weights shift accordingly.

Run from the repo root:  python examples/reliability_tracking.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from bayesian_consensus_engine_tpu.core import compute_consensus
from bayesian_consensus_engine_tpu.state import SQLiteReliabilityStore

with SQLiteReliabilityStore(":memory:") as store:
    # A source that keeps being right, one that keeps being wrong.
    for _ in range(4):
        store.update_reliability("oracle", "btc-2026", outcome_correct=True)
        store.update_reliability("contrarian", "btc-2026", outcome_correct=False)

    for record in store.list_sources():
        print(
            f"{record.source_id:12s} reliability={record.reliability:.2f} "
            f"confidence={record.confidence:.3f}"
        )

    reliability = {
        r.source_id: {"reliability": r.reliability, "confidence": r.confidence}
        for r in store.list_sources()
    }
    result = compute_consensus(
        [
            {"sourceId": "oracle", "probability": 0.9},
            {"sourceId": "contrarian", "probability": 0.1},
        ],
        reliability,
    )
    print(f"\nWeighted consensus: {result['consensus']:.3f}  (oracle dominates)")

    # Dry-run: preview the next update without writing.
    preview = store.update_reliability("oracle", "btc-2026", True, dry_run=True)
    print(f"Dry-run preview:    {preview.reliability:.2f} (nothing persisted)")
