"""Traced serving: span chains, a flight recorder, and goodput under SLO.

The round-9 observability layer end to end, in three acts:

* **Act 1 — serve under an objective**: drive ``ConsensusService`` with
  a declared latency SLO and a live ``Tracer``. Every request carries a
  deterministic trace id (its submit sequence number) and records the
  chain enqueue → window_join → flush → settled → durable; every
  micro-batch records its canonical phase spans (pack / upload /
  settle_dispatch / checkpoint / journal) on the batch chain.
* **Act 2 — artifacts**: dump the span log as JSONL, convert it to
  Chrome trace-event JSON (the same conversion ``bce-tpu trace RUN.jsonl
  --out trace.json`` runs), and write the flight-recorder snapshot the
  service took at close — the postmortem a crash would have left.
  Load the ``.chrome.json`` at https://ui.perfetto.dev.
* **Act 3 — goodput accounting**: an overload burst against a bounded
  queue. Rejected requests count AGAINST ``goodput_within_slo`` (met /
  offered) — the overload story a raw p99 cannot tell, since refused
  traffic never enters a latency histogram.

Run from the repo root:  python examples/traced_serving.py
"""

import asyncio
import json
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from bayesian_consensus_engine_tpu import obs
from bayesian_consensus_engine_tpu.serve import (
    AdmissionConfig,
    ConsensusService,
    Overloaded,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

MARKETS = 16
ROUNDS = 3
NOW = 21_900.0  # fixed settlement day: reproducible demo output
SLO_S = 60.0  # generous on a cold CPU run; tighten on a warm service

rng = np.random.default_rng(11)
SOURCES = [
    [f"src-{v}" for v in rng.integers(0, 30, n)]
    for n in rng.integers(1, 4, MARKETS)
]


def requests_for_round():
    for market in range(MARKETS):
        probs = rng.random(len(SOURCES[market]))
        yield (
            f"m-{market}",
            list(zip(SOURCES[market], probs)),
            bool(rng.random() < 0.5),
        )


async def main(tmp):
    registry = obs.MetricsRegistry()
    previous_registry = obs.set_metrics_registry(registry)
    tracer = obs.Tracer(flight_capacity=128)
    previous_tracer = obs.set_tracer(tracer)

    store = TensorReliabilityStore()
    service = ConsensusService(
        store,
        steps=2,
        now=NOW,
        journal=tmp / "traced.jrnl",
        checkpoint_every=2,
        max_batch=MARKETS,
        max_delay_s=0.002,
        admission=AdmissionConfig(max_pending=2 * MARKETS, policy="reject"),
        slo=obs.LatencyObjective(SLO_S),
        record_batches=True,
    )

    print(f"act 1 — serve {ROUNDS} rounds x {MARKETS} markets under a "
          f"{SLO_S:.0f}s SLO, traced")
    rejected = 0
    async with service:
        for _round in range(ROUNDS):
            for market_id, signals, outcome in requests_for_round():
                service.submit(market_id, signals, outcome)
            await service.drain()

        # Act 3's traffic: a burst far past the admission bound.
        print("act 3 — overload burst against the bounded queue")
        for i in range(6 * MARKETS):
            try:
                service.submit(f"burst-{i}", [("src-0", 0.5)], True)
            except Overloaded:
                rejected += 1
        await service.drain()

    print(f"  batches coalesced: {len(service.batch_log)}, "
          f"rejected under overload: {rejected}")

    print("act 2 — artifacts")
    span_log = tmp / "traced.jsonl"
    n_events = tracer.write_jsonl(span_log)
    chrome = obs.to_chrome_trace(obs.load_trace_jsonl(span_log))
    chrome_path = tmp / "traced.chrome.json"
    chrome_path.write_text(json.dumps(chrome, sort_keys=True))
    flight_path = tmp / "traced.flight.json"
    flight_path.write_text(
        json.dumps(service.flight_dump, sort_keys=True)
    )
    print(f"  span log: {n_events} events -> {span_log.name}")
    print(f"  perfetto: {len(chrome['traceEvents'])} trace events -> "
          f"{chrome_path.name} (load at ui.perfetto.dev)")
    print(f"  flight recorder ({service.flight_dump['reason']}): "
          + ", ".join(
              f"{name}:{len(events)}"
              for name, events in sorted(
                  service.flight_dump["components"].items()
              )
          ))

    # One request's chain, read back from the artifact.
    request_zero = [
        e for e in tracer.events()
        if e["scope"] == "request" and e["key"] == 0
    ]
    print("  request 0 chain: "
          + " -> ".join(e["name"] for e in request_zero))

    snap = service.goodput()
    counts = snap["counts"]
    print("goodput under SLO (met / offered; refused traffic counts "
          "against):")
    print(f"  met={counts['met']} violated={counts['violated']} "
          f"shed={counts['shed']} rejected={counts['rejected']}")
    print(f"  goodput_within_slo = {snap['goodput_within_slo']:.3f} "
          f"(window: {snap['window']['goodput_within_slo']:.3f} over "
          f"last {snap['window']['n']})")
    gauge = registry.export()["gauges"]["serve.goodput_within_slo"]
    print(f"  serve.goodput_within_slo gauge = {gauge:.3f}")

    obs.set_tracer(previous_tracer)
    obs.set_metrics_registry(previous_registry)


if __name__ == "__main__":
    with tempfile.TemporaryDirectory() as tmp:
        asyncio.run(main(pathlib.Path(tmp)))
