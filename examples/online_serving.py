"""Online serving: per-market requests coalesced over the resident session.

The round-8 front end end to end, in three acts:

* **Act 1 — steady traffic**: clients submit one market's signal update +
  outcome at a time; ``ConsensusService`` coalesces them into
  topology-stable micro-batches (duplicate markets roll to the next
  window), the plan cache serves repeat topologies with probs-only
  refreshes, and every request's future resolves to its market's
  consensus. Durability is the stream's: a journal epoch every few
  batches, tail epoch fsynced at close — the journal always ends JOINED.
* **Act 2 — latency accounting**: the per-request spans
  (enqueue → coalesce → dispatch → durable) land in the metrics registry
  as log-spaced histograms; ``Histogram.summary()`` quotes the p50/p99 a
  load report needs.
* **Act 3 — overload as policy**: a burst far past the admission bound —
  the bounded queue rejects the excess with a retry-after hint while the
  admitted requests keep a bounded p99. Shed-oldest is one config knob
  away.

The served path is byte-exact with ``settle_stream`` over the same
coalesced batch list (tests/test_serve.py) — this demo replays its own
batch log through the stream at the end to prove it on the spot.

Run from the repo root:  python examples/online_serving.py
"""

import asyncio
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from bayesian_consensus_engine_tpu import obs
from bayesian_consensus_engine_tpu.pipeline import settle_stream
from bayesian_consensus_engine_tpu.serve import (
    AdmissionConfig,
    ConsensusService,
    Overloaded,
)
from bayesian_consensus_engine_tpu.state.journal import replay_journal
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

MARKETS = 24
ROUNDS = 3
NOW = 21_900.0  # fixed settlement day: reproducible demo output

rng = np.random.default_rng(7)
SOURCES = [
    [f"src-{v}" for v in rng.integers(0, 40, n)]
    for n in rng.integers(1, 4, MARKETS)
]


def requests_for_round(round_index):
    """One steady round: every market updates, fixed source sets."""
    for market in range(MARKETS):
        probs = rng.random(len(SOURCES[market]))
        yield (
            f"m-{market}",
            list(zip(SOURCES[market], probs)),
            bool(rng.random() < 0.5),
        )


async def main(tmp):
    registry = obs.MetricsRegistry()
    previous = obs.set_metrics_registry(registry)
    store = TensorReliabilityStore()
    journal_path = tmp / "serving.jrnl"
    service = ConsensusService(
        store,
        steps=2,
        now=NOW,
        journal=journal_path,
        checkpoint_every=2,
        max_batch=MARKETS,
        max_delay_s=0.002,
        admission=AdmissionConfig(max_pending=2 * MARKETS, policy="reject"),
        record_batches=True,
    )

    print(f"act 1 — steady traffic: {ROUNDS} rounds x {MARKETS} markets")
    futures = []
    async with service:
        for round_index in range(ROUNDS):
            for market_id, signals, outcome in requests_for_round(
                round_index
            ):
                futures.append(
                    service.submit(market_id, signals, outcome)
                )
            # Settle the round before the next (the daily cadence) —
            # and keep pending far from the admission bound.
            await service.drain()

        print("act 3 — overload burst against a bounded queue")
        rejected = 0
        admitted = []
        for i in range(6 * MARKETS):
            try:
                admitted.append(
                    service.submit(
                        f"burst-{i}", [("src-0", 0.5)], True
                    )
                )
            except Overloaded as exc:
                rejected += 1
                retry_after = exc.retry_after_s
        await service.drain()
        print(
            f"  admitted {len(admitted)}, rejected {rejected} "
            f"(retry after {retry_after * 1e3:.0f} ms), "
            f"pending never exceeded {2 * MARKETS}"
        )

    first = futures[0].result()
    print(
        f"  first request: {first.market_id} -> consensus "
        f"{first.consensus:.4f} (batch {first.batch_index})"
    )
    print(f"  batches coalesced: {len(service.batch_log)}")

    print("act 2 — per-request latency (p50/p99 from the histograms)")
    for span in ("coalesce", "dispatch", "durable", "total"):
        summary = registry.histogram(
            f"serve.latency_{span}_s"
        ).summary((0.5, 0.99))
        print(
            f"  {span:>8}: n={summary['count']:<4} "
            f"p50={summary['p50'] * 1e3:7.2f} ms  "
            f"p99={summary['p99'] * 1e3:7.2f} ms"
        )

    # The close() above drained and fsynced the tail epoch: the journal
    # replays to exactly the served state.
    replayed, tag = replay_journal(journal_path)
    replayed.sync()
    store.sync()
    assert replayed.list_sources() == store.list_sources()
    print(f"journal ends JOINED at epoch tag {tag} — replay == served state")

    # Byte-exactness coda: the same coalesced batches through the stream.
    twin = TensorReliabilityStore()
    for _result in settle_stream(
        twin, service.batch_log, steps=2, now=NOW,
        columnar=True, reuse_plans=True,
    ):
        pass
    twin.sync()
    assert twin.list_sources() == store.list_sources()
    print("settle_stream over the batch log == served state: byte-exact")
    obs.set_metrics_registry(previous)


if __name__ == "__main__":
    with tempfile.TemporaryDirectory() as tmp:
        asyncio.run(main(pathlib.Path(tmp)))
