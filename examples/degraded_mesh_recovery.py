"""Host loss as a steady-state event: kill → replay → resume → same bytes.

The round-13 recovery walkthrough (cluster/), in one process: two
"hosts" of a shared-nothing banded cluster (cluster.membership.MeshView
— each owns a deterministic band of the global markets axis, settles it
through a resident session on its own mesh, and journals every batch),
then host B dies mid-stream with offered-but-undurable work in flight.
The survivor:

  1. derives the DEGRADED view (epoch 1 over {A}) — a pure function of
     the surviving host set, no coordinator anywhere;
  2. replays B's journal INTO its live store between batches
     (``cluster.recover.adopt_journal``): B's rows appear host-exact,
     durable through B's last fsynced epoch — the crash-eaten tail is
     exactly what re-drives;
  3. keeps streaming: the next batch covers BOTH bands, and the resident
     session carries the merge through the adopt RELAYOUT (B's rows
     enter the device block as host uploads, A's rows never leave HBM —
     ``stream.resident_fallbacks`` stays 0);
  4. proves the byte contract: the live merged store is bit-identical
     (store digest AND SQLite export bytes) to an offline
     ``replay_cluster_journals`` over the two journals, and after one
     more epoch the survivor's OWN journal replays to the full store —
     the dead journal is needed once, then never again.

``scripts/kill_soak.py`` runs the same story with real worker processes,
a real SIGKILL, and recovered ``goodput_within_slo`` as the headline;
tests/test_cluster.py pins every contract used here.

Run from the repo root:  python examples/degraded_mesh_recovery.py
"""

import os
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG
    ).strip()

import numpy as np  # noqa: E402

from bayesian_consensus_engine_tpu.cluster import (  # noqa: E402
    MeshView,
    adopt_journal,
    replay_cluster_journals,
    store_digest,
)
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh  # noqa: E402
from bayesian_consensus_engine_tpu.serve.driver import (  # noqa: E402
    PlanCache,
    SessionDriver,
)
from bayesian_consensus_engine_tpu.state.journal import (  # noqa: E402
    JournalWriter,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (  # noqa: E402
    TensorReliabilityStore,
)

MARKETS = 32          # global axis; the epoch-0 view bands it 16/16
BATCHES = 6           # per band
B_DIES_AFTER = 3      # B's durable batches when it "dies"
NOW0 = 21_800.0
SEED = 31


def global_batch(index):
    """Deterministic global batch: both hosts derive the SAME columns
    from the seed, then slice their band — the property that lets the
    survivor re-drive the dead band bit-for-bit."""
    rng = np.random.default_rng((SEED, index))
    drift = index // 2  # topology drifts every two batches
    counts = np.random.default_rng((SEED, 1, drift)).integers(1, 4, MARKETS)
    keys = [f"m{g}" for g in range(MARKETS)]
    sids = [
        f"s{(g * 3 + j * 7 + drift) % 20}"
        for g in range(MARKETS)
        for j in range(counts[g])
    ]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    probs = rng.random(int(counts.sum()))
    outcomes = (rng.random(MARKETS) < 0.5).tolist()
    return keys, sids, probs, offsets, outcomes


def band_slice(batch, rows):
    keys, sids, probs, offsets, outcomes = batch
    out_k, out_s, out_p, out_c, out_o = [], [], [], [], []
    for g in rows:
        lo, hi = int(offsets[g]), int(offsets[g + 1])
        out_k.append(keys[g])
        out_s.extend(sids[lo:hi])
        out_p.append(probs[lo:hi])
        out_c.append(hi - lo)
        out_o.append(outcomes[g])
    return (
        out_k, out_s,
        np.concatenate(out_p),
        np.concatenate([[0], np.cumsum(out_c)]).astype(np.int64),
        out_o,
    )


class BandHost:
    """One shared-nothing host: store + journal + resident driver."""

    def __init__(self, host_id, view, shared):
        self.id = host_id
        self.rows = list(view.owned_markets(host_id, MARKETS))
        self.store = TensorReliabilityStore()
        self.journal_path = os.path.join(shared, f"band{host_id}.jrnl")
        self.driver = SessionDriver(
            self.store, steps=1, mesh=make_mesh(),
            journal=JournalWriter(self.journal_path), owns_journal=True,
            checkpoint_every=1, sync_checkpoints=True,
        )
        self.cache = PlanCache(self.store, num_slots=8)
        self.index = 0

    def settle(self, parts):
        """One batch over [(band_rows, batch_index), ...] merged."""
        columns = [band_slice(global_batch(i), rows) for rows, i in parts]
        keys = sum((c[0] for c in columns), [])
        sids = sum((c[1] for c in columns), [])
        probs = np.concatenate([c[2] for c in columns])
        offsets = np.cumsum(
            np.concatenate([[0]] + [np.diff(c[3]) for c in columns])
        ).astype(np.int64)
        outcomes = sum((c[4] for c in columns), [])
        plan = self.cache.plan_for(keys, sids, probs, offsets)
        self.driver.dispatch(plan, outcomes, now=NOW0 + self.index)
        self.driver.checkpoint(self.index)
        self.index += 1
        return self.driver.last_adopt


def main():
    shared = tempfile.mkdtemp(prefix="bce_degraded_")
    view0 = MeshView(epoch=0, hosts=(0, 1), devices_per_host=4)
    print(f"epoch 0: hosts {view0.hosts}, bands "
          f"{[view0.band(h, MARKETS) for h in view0.hosts]}")

    # --- Act 1: the steady cluster — both bands stream and journal.
    a = BandHost(0, view0, shared)
    b = BandHost(1, view0, shared)
    for i in range(B_DIES_AFTER):
        a.settle([(a.rows, i)])
        b.settle([(b.rows, i)])
    print(f"steady phase: {B_DIES_AFTER} durable batches per band "
          f"(adopt modes stay refresh/relayout — resident)")

    # --- Act 2: host B dies. Its journal survives (durable storage);
    # everything after its last fsynced epoch is crash-eaten.
    del b  # the process is gone; only band1.jrnl remains
    print(f"host 1 died after batch {B_DIES_AFTER - 1} "
          f"(journal durable through tag {B_DIES_AFTER - 1})")

    # --- Act 3: the survivor derives the degraded view and adopts.
    view1 = view0.degraded([0])
    print(f"epoch 1: hosts {view1.hosts} — survivor owns "
          f"{len(list(view1.owned_markets(0, MARKETS)))}/{MARKETS} markets")
    dead_rows = list(view0.owned_markets(1, MARKETS))
    tag, rows_adopted = adopt_journal(
        a.store, os.path.join(shared, "band1.jrnl")
    )
    print(f"adopted band 1: {rows_adopted} rows, durable tag {tag} → "
          f"re-driving batches {tag + 1}..{BATCHES - 1}")

    # The byte coda, live at the adoption point: the merged store equals
    # the offline replay of the two journals — digest and SQLite bytes.
    merged = replay_cluster_journals(
        [os.path.join(shared, "band0.jrnl"),
         os.path.join(shared, "band1.jrnl")]
    )
    assert store_digest(a.store) == store_digest(merged.store)
    a.store.flush_to_sqlite(os.path.join(shared, "live.db"))
    merged.store.flush_to_sqlite(os.path.join(shared, "replay.db"))
    with open(os.path.join(shared, "live.db"), "rb") as fa, \
            open(os.path.join(shared, "replay.db"), "rb") as fb:
        assert fa.read() == fb.read()
    print("byte coda: live merged store == replay_cluster_journals "
          "(store digest AND SQLite bytes)")

    # --- Act 4: the stream resumes on the degraded view — merged
    # batches, the resident session relaying B's rows in as entering
    # uploads. No rebuild, no teardown.
    adopts = []
    b_next = tag + 1
    for i in range(B_DIES_AFTER, BATCHES):
        parts = [(a.rows, i)]
        if b_next < BATCHES:
            parts.append((dead_rows, b_next))
            b_next += 1
        adopts.append(a.settle(parts))
    while b_next < BATCHES:
        adopts.append(a.settle([(dead_rows, b_next)]))
        b_next += 1
    assert not any(m is not None and m.startswith("rebuild")
                   for m in adopts), adopts
    print(f"resumed through batch {BATCHES - 1} of both bands; adopt "
          f"modes {adopts} — the merge itself rode the relayout")

    # --- Coda: the survivor's own journal is now self-contained — the
    # adopted band rode its post-adoption epochs, so IT ALONE replays to
    # the full final store. The dead journal was needed exactly once.
    a.driver.finalize()
    solo = replay_cluster_journals([os.path.join(shared, "band0.jrnl")])
    assert store_digest(solo.store) == store_digest(a.store)
    print("survivor journal self-contained: replay(band0.jrnl) == final "
          "store — the dead journal is history")
    print("OK")


if __name__ == "__main__":
    main()
