"""The ring tie-break memory diet + the fused co-resident program.

Round 11's two halves, runnable at laptop shapes:

1. The CHUNKED tie-break (`chunk_agents=`) collapses the compile-time
   temp footprint by ~agents/chunk while staying BIT-IDENTICAL to the
   unchunked accumulation — printed straight off the AOT
   ``memory_analysis()`` of the same compiled objects that run.
2. ``ShardedSettlementSession.settle_with_tiebreak`` settles a batch AND
   tie-breaks every market's conflicting predictions in ONE compiled
   program per chip, against the one resident reliability block — no
   second program evicting the first.

Run from the repo root:  python examples/coresident_tiebreak.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import jax
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.parallel.ring import build_ring_tiebreak
from bayesian_consensus_engine_tpu.pipeline import (
    ShardedSettlementSession,
    build_settlement_plan,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

# ---------------------------------------------------------------------------
# Act 1 — the memory diet, before/after, off the real compiled programs.
# ---------------------------------------------------------------------------
MARKETS, AGENTS, CHUNK = 128, 1024, 64
mesh = make_mesh((1, 1), devices=jax.devices()[:1])

rng = np.random.default_rng(7)
grid = np.round(np.linspace(0.05, 0.95, 19), 6)
blocks = (
    jnp.asarray(rng.choice(grid, (MARKETS, AGENTS)), jnp.float32),   # pred
    jnp.asarray(rng.uniform(0.1, 2.0, (MARKETS, AGENTS)), jnp.float32),
    jnp.asarray(rng.uniform(0, 1, (MARKETS, AGENTS)), jnp.float32),  # conf
    jnp.asarray(rng.uniform(0, 1, (MARKETS, AGENTS)), jnp.float32),  # rel
    jnp.asarray(rng.random((MARKETS, AGENTS)) < 0.9),                # valid
)

print(f"tie-break at {MARKETS} markets x {AGENTS} agents, one device")
results = {}
for label, chunk in (("unchunked", None), (f"chunk={CHUNK}", CHUNK)):
    tiebreak = build_ring_tiebreak(mesh, chunk_agents=chunk)
    mem = tiebreak.lower(*blocks).compile().memory_analysis()
    results[label] = tiebreak(*blocks)
    print(
        f"  {label:>10}: compile temps {mem.temp_size_in_bytes / 1e6:8.1f} MB"
        f"   args {mem.argument_size_in_bytes / 1e6:5.1f} MB"
    )

# Bit-identical outputs — the knob moves memory, never a result byte.
for name, got, want in zip(
    results["unchunked"]._fields, results[f"chunk={CHUNK}"],
    results["unchunked"],
):
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
print("  outputs bit-identical across the chunk knob\n")

# ---------------------------------------------------------------------------
# Act 2 — one program per chip: settle + tie-break on the resident block.
# ---------------------------------------------------------------------------
N_MARKETS, N_SOURCES = 24, 6
payloads = [
    (
        f"market-{m}",
        [
            {
                "sourceId": f"src-{s}",
                "probability": float(rng.choice(grid)),
            }
            for s in range(N_SOURCES)
        ],
    )
    for m in range(N_MARKETS)
]
outcomes = list(rng.random(N_MARKETS) < 0.5)

store = TensorReliabilityStore()
plan = build_settlement_plan(store, payloads, num_slots=8)
service_mesh = make_mesh()

labels = {0: "unanimous", 1: "weight_density", 2: "prediction_value_smallest"}
with ShardedSettlementSession(store, plan, service_mesh) as session:
    result, tiebreak = session.settle_with_tiebreak(
        outcomes, steps=2, now=21_900.0, chunk_agents=4
    )
    print(
        f"fused session dispatch: {N_MARKETS} markets settled AND "
        "tie-broken in one compiled program"
    )
    for row in range(4):
        print(
            f"  {result.market_keys[row]}: consensus "
            f"{float(np.asarray(result.consensus)[row]):.4f}  "
            f"tie-break winner {float(np.asarray(tiebreak.prediction)[row]):.3f} "
            f"({labels[int(np.asarray(tiebreak.resolved_by)[row])]}, "
            f"{int(np.asarray(tiebreak.num_groups)[row])} groups)"
        )
print(
    "\nThe tie-break weighs each signalling slot at its decayed READ "
    "reliability\n(the same weight the consensus reduction used) — one "
    "resident block, one program,\nno teardown between settlement and "
    "diagnostics. bench.py --leg e2e_ring_memory\ncarries the at-scale "
    "before/after capture."
)
