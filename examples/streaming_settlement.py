"""Streamed batches, fully overlapped: settle_stream in one loop.

Where examples/settlement_service.py chains ONE signal topology,
this is the other production shape: a stream of DISTINCT daily batches
(new markets, new pairs every day). ``settle_stream`` overlaps all three
legs — batch N+1's plan builds on a prefetch thread while batch N
settles, and each checkpoint's SQLite transaction writes on a background
thread (GIL released in the native writer) while the next batch ingests.
Results, store state, and the checkpoint file are exactly what the
serial build → settle → flush loop produces (tests/test_overlap.py).

Day 2 of the demo hands the SAME loop a device mesh (``mesh=``): every
batch then settles sharded — markets on the lane axis across all
devices, the merge gather deferred so it overlaps the next batch's plan
build. On a markets-only mesh the results and checkpoints are
bit-identical to the flat stream.

Act 3 is the STEADY STATE: one persistent (source, market) universe
re-settled every batch with fresh probabilities — the daily
re-settlement shape. ``reuse_plans=True`` fingerprints each batch's
topology and, on a match, refreshes the previous plan's probability
columns instead of re-packing (the delta-ingest fast path; bit-exact
with the rebuild path), reporting the hit in each ``stats`` dict.

Run from the repo root:  python examples/streaming_settlement.py
"""

import os
import pathlib
import sqlite3
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

# Older JAX has no jax_num_cpu_devices option; the XLA flag (read at
# first backend use, so set before import) is the portable spelling.
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # old JAX: the XLA_FLAGS fallback above applies
    pass

from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh  # noqa: E402
from bayesian_consensus_engine_tpu.pipeline import settle_stream  # noqa: E402
from bayesian_consensus_engine_tpu.state.tensor_store import (  # noqa: E402
    TensorReliabilityStore,
)

BATCHES = 4
MARKETS_PER_BATCH = 2_000
MEAN_SIGNALS = 3
START_DAY = 20_800.0

rng = np.random.default_rng(23)


def day_batch(day: int):
    """One day's (payloads, outcomes): fresh markets, shared source pool."""
    counts = rng.poisson(MEAN_SIGNALS - 1, MARKETS_PER_BATCH) + 1
    payloads = []
    for m, count in enumerate(counts):
        signals = [
            {
                "sourceId": f"src-{rng.integers(0, 500)}",
                "probability": round(float(rng.random()), 6),
            }
            for _ in range(count)
        ]
        payloads.append((f"day{day}-market-{m}", signals))
    outcomes = (rng.random(MARKETS_PER_BATCH) < 0.5).tolist()
    return payloads, outcomes


def main() -> None:
    batches = [day_batch(day) for day in range(BATCHES)]
    store = TensorReliabilityStore()
    with tempfile.TemporaryDirectory() as tmp:
        db = os.path.join(tmp, "checkpoints.db")
        start = time.perf_counter()
        for day, result in enumerate(
            settle_stream(
                store,
                batches,
                steps=1,
                now=START_DAY,       # day N settles at START_DAY + N
                db_path=db,          # checkpoint every batch, written in
                checkpoint_every=1,  # the background during batch N+1
            )
        ):
            # The consensus vector fetches lazily — read a couple of values.
            sample = result.by_market()
            first_key = result.market_keys[0]
            print(
                f"day {day}: settled {len(result.market_keys)} markets; "
                f"{first_key} -> {sample[first_key]:.4f}"
            )
        elapsed = time.perf_counter() - start
        store.sync()
        rows = sqlite3.connect(db).execute(
            "SELECT COUNT(*) FROM sources"
        ).fetchone()[0]
        print(
            f"\n{BATCHES} batches in {elapsed:.2f}s; final checkpoint holds "
            f"{rows} (source, market) rows — equal to the store's "
            f"{len(store.list_sources())} live records"
        )
        assert rows == len(store.list_sources())

    # The same service loop, sharded over a device mesh: markets ride the
    # lane axis across all 8 (virtual) devices; results are bit-identical
    # to the flat stream above on this markets-only mesh.
    mesh_store = TensorReliabilityStore()
    start = time.perf_counter()
    mesh_results = list(
        settle_stream(
            mesh_store, batches, steps=1, now=START_DAY, mesh=make_mesh()
        )
    )
    elapsed = time.perf_counter() - start
    mesh_store.sync()
    assert mesh_store.list_sources() == store.list_sources()
    print(
        f"sharded over {len(jax.devices())} devices: {len(mesh_results)} "
        f"batches in {elapsed:.2f}s; store state identical to the flat run"
    )

    # Act 3 — the steady state: ONE topology (day 0's universe), fresh
    # probabilities each batch. reuse_plans=True skips pack/intern/pad on
    # every batch after the first; results are bit-exact either way.
    base_payloads, _ = batches[0]
    stable_batches = []
    for _day in range(3):
        payloads = [
            (
                market_id,
                [
                    {
                        "sourceId": s["sourceId"],
                        "probability": round(float(rng.random()), 6),
                    }
                    for s in signals
                ],
            )
            for market_id, signals in base_payloads
        ]
        outcomes = (rng.random(len(base_payloads)) < 0.5).tolist()
        stable_batches.append((payloads, outcomes))

    stats: list = []
    reuse_store = TensorReliabilityStore()
    start = time.perf_counter()
    for _result in settle_stream(
        reuse_store, stable_batches, steps=1, now=START_DAY + BATCHES,
        stats=stats, reuse_plans=True,
    ):
        pass
    elapsed = time.perf_counter() - start
    reuse_store.sync()
    hits = sum(bool(s["plan_reused"]) for s in stats)
    print(
        f"stable topology with reuse_plans=True: {len(stats)} batches in "
        f"{elapsed:.2f}s, {hits} plan-reuse hits "
        f"({len(stats) - hits} rebuild)"
    )
    assert hits == len(stats) - 1  # only batch 0 built a plan from scratch


if __name__ == "__main__":
    main()
