"""Deterministic tie-break between conflicting agent predictions.

Run from the repo root:  python examples/tie_breaking.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from bayesian_consensus_engine_tpu.models import AgentSignal, DeterministicTieBreaker

agents = [
    AgentSignal("fast-model", prediction=0.8, confidence=0.7, weight=1.0, reliability_score=0.6),
    AgentSignal("slow-model", prediction=0.8, confidence=0.9, weight=1.0, reliability_score=0.7),
    AgentSignal("heuristic", prediction=0.3, confidence=0.5, weight=0.5, reliability_score=0.4),
]

winner, diagnostics = DeterministicTieBreaker().resolve(agents)

print(f"Winning prediction: {winner}")
print(f"Resolved by:        {diagnostics.tie_resolved_by}")
print(f"Confidence var:     {diagnostics.confidence_variance}")
for prediction, metrics in diagnostics.groups.items():
    print(f"  group {prediction}: {metrics}")
