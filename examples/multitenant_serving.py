"""Multi-tenant serving: premium + best-effort classes over the real
socket front door.

The round-17 request tier end to end, in three acts:

* **Act 1 — tiered overload over the wire**: a ``ConsensusService``
  declares two :class:`QosClass` tenants — *premium* (a budget sized to
  its load, reject policy) and *besteffort* (a deliberately tiny budget,
  ``shed_oldest``) — behind a :class:`ConsensusServer` on a real TCP
  socket. A best-effort burst overflows its budget while premium
  closed-loop traffic runs: premium ``goodput_within_slo`` holds, the
  best-effort class absorbs the overload as explicit policy (sheds +
  rejections, each an explicit error frame on the wire).
* **Act 2 — variance-aware shed ranking**: with per-market band
  standard errors seeded from the analytics tier's vocabulary, the shed
  policy drops WIDE-band markets first — the pending update the
  posterior will miss least — ties oldest-first; the shed order is a
  pure function of (class, stderr ranking, arrival order).
* **Act 3 — the byte-exactness coda**: the same admitted-request trace
  submitted in-process and served over the wire yields identical
  journal epoch payloads (wall_ts masked) and identical SQLite bytes —
  the transport adds reach, never semantics (pinned for flat AND
  sharded sessions by tests/test_net.py).

Run from the repo root:  python examples/multitenant_serving.py
"""

import asyncio
import pathlib
import struct
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from bayesian_consensus_engine_tpu.net import ConsensusClient, ConsensusServer
from bayesian_consensus_engine_tpu.serve import (
    ConsensusService,
    QosClass,
    ShedError,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_900.0
MARKETS = 16

rng = np.random.default_rng(5)
SOURCES = [
    [f"src-{v}" for v in rng.integers(0, 30, n)]
    for n in rng.integers(1, 4, MARKETS)
]


def trace(n, seed, prefix="m"):
    req_rng = np.random.default_rng(seed)
    ids = req_rng.integers(0, MARKETS, n)
    out = []
    for i in range(n):
        market = int(ids[i])
        probs = req_rng.random(len(SOURCES[market]))
        out.append((
            f"{prefix}-{market}",
            list(zip(SOURCES[market], probs)),
            bool(req_rng.random() < 0.5),
        ))
    return out


def act_1_tiered_overload():
    print("=== Act 1: premium holds while best-effort sheds (real socket)")
    store = TensorReliabilityStore()

    async def main():
        service = ConsensusService(
            store, steps=1, now=NOW, max_batch=8, max_delay_s=0.002,
            qos=[
                QosClass("premium", slo_s=5.0, max_pending=128),
                QosClass("besteffort", slo_s=5.0, max_pending=4,
                         policy="shed_oldest"),
            ],
        )
        server = await ConsensusServer(service).start()
        loop = asyncio.get_running_loop()

        def burst_client():
            # The whole best-effort share pipelined on one connection:
            # a burst the 4-deep budget cannot hold.
            with ConsensusClient(port=server.port) as client:
                return client.submit_pipelined(
                    trace(48, seed=21, prefix="be"),
                    qos_class="besteffort",
                )

        def premium_client():
            with ConsensusClient(port=server.port) as client:
                served = 0
                for req in trace(32, seed=13):
                    client.submit(*req, qos_class="premium")
                    served += 1
                return served

        burst_future = loop.run_in_executor(None, burst_client)
        premium_served = await loop.run_in_executor(None, premium_client)
        burst_results = await burst_future
        await service.drain()
        await server.close()
        await service.close()
        return service, premium_served, burst_results

    service, premium_served, burst_results = asyncio.run(main())
    refused = sum(1 for r in burst_results if isinstance(r, BaseException))
    snap = service.qos_snapshot()
    for name in ("premium", "besteffort"):
        record = snap[name]
        goodput = record["goodput_within_slo"]
        print(
            f"  {name:<11} offered={record['offered']:>3} "
            f"met={record['counts']['met']:>3} "
            f"shed={record['counts']['shed']:>3} "
            f"rejected={record['counts']['rejected']:>3} "
            f"goodput={goodput:.2f}" if goodput is not None else name
        )
    assert premium_served == 32, "premium class must never be refused here"
    assert refused > 0, "the burst must overflow the best-effort budget"
    assert snap["premium"]["counts"]["rejected"] == 0
    print(f"  best-effort refusals (explicit error frames): {refused}")


def act_2_variance_aware_shedding():
    print("=== Act 2: wide-band markets shed first (deterministic order)")
    store = TensorReliabilityStore()
    victims = []

    async def main():
        service = ConsensusService(
            store, steps=1, now=NOW, max_batch=64, max_delay_s=None,
            qos=[QosClass("be", slo_s=3600.0, max_pending=3,
                          policy="shed_oldest")],
        )
        # The analytics tier's per-market stderr, seeded explicitly
        # (a live analytics= service maintains this map per batch).
        service.seed_band_stderr(
            {"contested": 0.38, "leaning": 0.17, "settled": 0.03}
        )
        pending = {
            market: service.submit(market, [("s", 0.6)], True)
            for market in ("settled", "contested", "leaning")
        }
        for i in range(3):
            pending[f"fresh-{i}"] = service.submit(
                f"fresh-{i}", [("s", 0.6)], True
            )
            for market, future in list(pending.items()):
                if future.done() and isinstance(
                    future.exception(), ShedError
                ):
                    victims.append(market)
                    del pending[market]
        await service.drain()
        await service.close()

    asyncio.run(main())
    print(f"  shed order under overflow: {victims}")
    assert victims == ["contested", "leaning", "settled"], victims
    print("  widest band first, narrowest last — arrival order only ties")


def _journal_epochs_sans_clock(path):
    blob = path.read_bytes()
    assert blob[:8] == b"BCEJRNL1"
    hdr = struct.Struct("<QQQQQdQ")
    off = 8
    epochs = []
    while off < len(blob):
        (epoch_index, used_after, pair_len, dirty, iso_len,
         _wall_ts, tag) = hdr.unpack_from(blob, off)
        payload_len = pair_len + 33 * dirty + iso_len
        start = off + hdr.size
        epochs.append((
            (epoch_index, used_after, pair_len, dirty, iso_len, tag),
            blob[start:start + payload_len],
        ))
        off = start + payload_len + 4
    return epochs


def act_3_byte_exactness(tmp):
    print("=== Act 3: wire-served state == in-process state (the coda)")
    tmp = pathlib.Path(tmp)
    # Rounds of 8 DISTINCT markets: every window seals by size, so the
    # batch sequence is a pure function of the submission order on both
    # transports (a duplicate market would roll to the next window and
    # wait on the flush timer — fine live, but this coda wants size-
    # sealed windows only).
    req_rng = np.random.default_rng(31)
    requests = []
    for rnd in range(5):
        for m in range(8):
            probs = req_rng.random(len(SOURCES[m]))
            requests.append((
                f"m-{m}",
                list(zip(SOURCES[m], probs)),
                bool(req_rng.random() < 0.5),
            ))

    def service_for(store, name):
        return ConsensusService(
            store, steps=2, now=NOW, checkpoint_every=2,
            journal=tmp / f"{name}.jrnl", db_path=tmp / f"{name}.db",
            max_batch=8, max_delay_s=None,
        )

    async def in_process():
        store = TensorReliabilityStore()
        service = service_for(store, "local")
        async with service:
            futures = [service.submit(*req) for req in requests]
            await service.drain()
        store.sync()
        return [f.result().consensus for f in futures]

    async def over_wire():
        store = TensorReliabilityStore()
        service = service_for(store, "wire")
        server = await ConsensusServer(service).start()
        loop = asyncio.get_running_loop()

        def drive():
            with ConsensusClient(port=server.port) as client:
                return client.submit_pipelined(
                    requests, return_exceptions=False
                )

        results = await loop.run_in_executor(None, drive)
        await service.drain()
        await server.close()
        await service.close()
        store.sync()
        return [r.consensus for r in results]

    local = asyncio.run(in_process())
    wire = asyncio.run(over_wire())
    assert local == wire, "per-request consensus differs across transports"
    local_epochs = _journal_epochs_sans_clock(tmp / "local.jrnl")
    wire_epochs = _journal_epochs_sans_clock(tmp / "wire.jrnl")
    assert local_epochs == wire_epochs, "journal epochs differ"
    local_db = (tmp / "local.db").read_bytes()
    wire_db = (tmp / "wire.db").read_bytes()
    assert local_db == wire_db, "SQLite interchange bytes differ"
    print(
        f"  {len(requests)} requests, {len(local_epochs)} journal epochs: "
        "results, epoch payloads (sans wall_ts), and SQLite bytes all "
        "IDENTICAL across transports"
    )


if __name__ == "__main__":
    act_1_tiered_overload()
    act_2_variance_aware_shedding()
    with tempfile.TemporaryDirectory() as tmp:
        act_3_byte_exactness(tmp)
    print("done.")
