"""A settlement service's steady-state loop, end to end.

The production shape the pipeline module is built for: signals arrive as
flat COLUMNS (no per-signal dicts), the plan is built once per topology,
every settlement chains device-resident (deferred absorb — no re-upload,
no per-settle host merge), checkpoints are INCREMENTAL (dirty rows only),
and the consensus vector is fetched only when somebody actually reads it
(``SettlementResult`` materialises lazily; ``fence()`` is the cheap
completion barrier).

Run from the repo root:  python examples/settlement_service.py
"""

import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

from bayesian_consensus_engine_tpu.pipeline import (  # noqa: E402
    build_settlement_plan_columnar,
    settle,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (  # noqa: E402
    TensorReliabilityStore,
)

MARKETS = 2_000
MEAN_SIGNALS = 3
DAYS = 5

rng = np.random.default_rng(11)
counts = rng.poisson(MEAN_SIGNALS - 1, MARKETS) + 1
offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
num_signals = int(offsets[-1])

# Columnar wire format: one source-id string + one probability per signal,
# markets back to back (CSR offsets). No dicts are ever built.
market_keys = [f"market-{m}" for m in range(MARKETS)]
source_ids = [f"src-{s}" for s in rng.integers(0, 400, num_signals)]
probabilities = rng.random(num_signals)

store = TensorReliabilityStore()
plan = build_settlement_plan_columnar(
    store, market_keys, source_ids, probabilities, offsets
)
print(f"plan: {plan.num_markets} markets, {int(plan.mask.sum())} pairs, "
      f"{plan.num_slots} slots")

def day_plan(day: int):
    """Day 0 settles everything; later days a rotating tenth of markets."""
    if day == 0:
        return plan, slice(None)
    lo = (day - 1) * (MARKETS // 10) % MARKETS
    live = slice(lo, lo + MARKETS // 10)
    sub = build_settlement_plan_columnar(
        store,
        market_keys[live],
        source_ids[offsets[live.start]: offsets[live.stop]],
        probabilities[offsets[live.start]: offsets[live.stop]],
        offsets[live.start: live.stop + 1] - offsets[live.start],
    )
    return sub, live


with tempfile.TemporaryDirectory() as tmp:
    db = pathlib.Path(tmp) / "reliability.db"
    base_day = 20_700.0

    for day in range(DAYS):
        todays_plan, live = day_plan(day)
        outcomes = rng.random(todays_plan.num_markets) < 0.5
        result = settle(
            store, todays_plan, outcomes, steps=1, now=base_day + day
        )
        result.fence()  # settled on device; nothing fetched yet

        # Nightly checkpoint: first write is full, every later one writes
        # only the rows this day's settlement actually changed.
        rows_written = store.flush_to_sqlite(db)
        kind = "full" if day == 0 else "incremental"
        print(f"day {day}: settled {todays_plan.num_markets} markets, "
              f"checkpoint {kind} wrote {rows_written} rows")

    # Somebody finally asks for numbers — THIS is where the consensus
    # vector crosses device->host.
    by_market = result.by_market()
    sample = dict(list(by_market.items())[:3])
    print(f"final-day consensus (3 of {len(by_market)}): {sample}")

    # The checkpoint file is reference-format SQLite: any tool (or the
    # reference CLI) can read it straight off disk.
    resumed = TensorReliabilityStore.from_sqlite(db)
    assert resumed.list_sources() == store.list_sources()
    print(f"checkpoint verified: {len(resumed.list_sources())} records "
          "round-trip bit-exactly")
