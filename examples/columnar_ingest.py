"""Columnar ingest end to end: payloads → columns → served stream.

The round-10 ingest story in three acts:

* **Act 1 — the packer A/B/C**: the same batch of dict payloads is
  flattened to flat columns (one C pass) and planned three ways — the
  numpy grouping twin (``native=False``; the FULL pure-Python stack,
  interner included, is the ``BCE_NO_NATIVE=1`` lane the ``e2e_ingest``
  bench leg times), the native columnar grouping
  (``fastpack.group_columns``), and the zero-copy coded intake
  (``SourceCodes``: int32 codes + id table, no per-signal Python object
  on the timed path). All three produce byte-identical plans and
  topology fingerprints; the native paths are several times faster.
* **Act 2 — streamed, overlapped**: ``settle_stream`` over columnar
  batches — plan N+1 packs on the prefetch thread while plan N settles,
  so the per-batch ``plan_wait_s`` collapses to ~microseconds after the
  pipeline fills (the ``stream.ingest_wait_s`` gauge).
* **Act 3 — served, overlapped**: the same discipline in the online
  front end — ``ConsensusService`` stages each micro-batch's plan on a
  pack thread while the previous batch holds the device (interning
  stays on the dispatch worker, so durability bytes stay deterministic)
  — ``service.ingest_wait_s`` ends ≈ 0 relative to wall.

Run from the repo root:  python examples/columnar_ingest.py
"""

import asyncio
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from bayesian_consensus_engine_tpu import obs
from bayesian_consensus_engine_tpu.core.batch import (
    columns_from_payloads,
    encode_source_ids,
    topology_fingerprint,
)
from bayesian_consensus_engine_tpu.pipeline import (
    build_settlement_plan_columnar,
    settle_stream,
)
from bayesian_consensus_engine_tpu.serve import ConsensusService
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_900.0  # fixed settlement day: reproducible demo output
rng = np.random.default_rng(5)


def make_payloads(markets, mean_signals=4, tag="m"):
    counts = rng.poisson(mean_signals - 1, markets) + 1
    return [
        (
            f"{tag}-{m}",
            [
                {
                    "sourceId": f"src-{rng.integers(0, 500)}",
                    "probability": float(rng.random()),
                }
                for _ in range(counts[m])
            ],
        )
        for m in range(markets)
    ]


# --- Act 1: one batch, three intakes, identical bytes -----------------------

payloads = make_payloads(40_000)
keys, sids, probs, offsets = columns_from_payloads(payloads)  # one C pass
coded = encode_source_ids(sids)  # the zero-copy caller pays this ONCE

print(f"act 1 — {len(sids)} signals over {len(keys)} markets")
plans = {}
for name, source_column, native in (
    ("python-twin", sids, False),
    ("native-columnar", sids, None),
    ("zero-copy", coded, None),
):
    store = TensorReliabilityStore()
    start = time.perf_counter()
    plans[name] = build_settlement_plan_columnar(
        store, keys, source_column, probs, offsets,
        fingerprint=True, native=native,
    )
    wall = time.perf_counter() - start
    print(f"  {name:>16}: {wall * 1e3:7.1f} ms "
          f"({len(sids) / wall / 1e6:.2f}M signals/s)")

reference = plans["python-twin"]
for name, plan in plans.items():
    assert plan.fingerprint == reference.fingerprint
    assert plan.probs.tobytes() == reference.probs.tobytes()
    assert plan.slot_rows.tobytes() == reference.slot_rows.tobytes()
print("  all three plans byte-identical, fingerprints equal:",
      reference.fingerprint.hex())
assert topology_fingerprint(keys, coded, offsets) == reference.fingerprint

# --- Act 2: streamed with the prefetch thread doing the packing -------------

batches = []
for _ in range(5):
    # Same topology every batch (the steady-state service shape): the
    # prefetcher's fingerprint hit re-plans only the probabilities.
    batch_probs = rng.random(len(sids))
    outcomes = (rng.random(len(keys)) < 0.5).tolist()
    batches.append(((keys, sids, batch_probs, offsets), outcomes))

registry = obs.MetricsRegistry()
previous = obs.set_metrics_registry(registry)
stats = []
store = TensorReliabilityStore()
for _result in settle_stream(
    store, batches, steps=3, now=NOW, columnar=True, stats=stats,
    reuse_plans=True,
):
    pass
store.sync()
waits = [round(s["plan_wait_s"] * 1e3, 3) for s in stats]
print("\nact 2 — per-batch plan_wait_s (ms):", waits)
print(f"  batch 0 fills the pipeline; steady state waits "
      f"{max(waits[1:]):.3f} ms or less "
      f"(stream.ingest_wait_s gauge: "
      f"{registry.gauge('stream.ingest_wait_s').value * 1e3:.2f} ms total)")

# --- Act 3: served, with the pack thread ahead of the device ----------------


async def serve():
    service = ConsensusService(
        TensorReliabilityStore(), steps=3, now=NOW,
        max_batch=64, max_delay_s=0.002,
    )
    markets = [f"live-{m}" for m in range(64)]
    sources = {m: [f"src-{rng.integers(0, 200)}" for _ in range(3)]
               for m in markets}
    async with service:
        start = time.perf_counter()
        for _round in range(6):
            futures = [
                service.submit(
                    m,
                    [(sid, float(rng.random())) for sid in sources[m]],
                    bool(rng.random() < 0.5),
                )
                for m in markets
            ]
            await asyncio.gather(*futures)
        wall = time.perf_counter() - start
        print(f"\nact 3 — served {6 * len(markets)} requests in "
              f"{wall:.2f} s; dispatch-worker ingest wait "
              f"{service.ingest_wait_s * 1e3:.2f} ms "
              f"({service.ingest_wait_s / wall:.2%} of wall)")


asyncio.run(serve())
obs.set_metrics_registry(previous)
print("\ningest is no longer the wall: packing rides the pack/prefetch "
      "threads, the chip never waits for it in the steady state")
