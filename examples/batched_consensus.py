"""Batched TPU path: consensus for many markets in one device pass.

Run from the repo root:  python examples/batched_consensus.py
"""

import pathlib
import random
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from bayesian_consensus_engine_tpu.core.batch import compute_batch_consensus

rng = random.Random(0)
markets = [
    (
        f"crypto:asset-{m}",
        [
            {"sourceId": f"model-{s}", "probability": round(rng.random(), 3)}
            for s in range(rng.randint(2, 6))
        ],
    )
    for m in range(8)
]

results = compute_batch_consensus(markets)

for market_id, doc in results.items():
    print(
        f"{market_id:18s} consensus={doc['consensus']:.4f} "
        f"sources={doc['diagnostics']['uniqueSources']}"
    )
